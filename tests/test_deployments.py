"""Deployment-layer consistency checks.

There is no helm binary in the test environment, so these tests do the part
of `helm lint` that matters for drift: every `.Values.*` path referenced by a
template exists in values.yaml, the CRDs parse and match the API-layer types,
device-class names and driver names match the code's constants, and the
Dockerfile/pyproject entry points reference real modules.
"""

from __future__ import annotations

import os
import re
import pytest

import yaml

import tpu_dra.version as version
from tpu_dra.computedomain import CHANNEL_DEVICE_CLASS, DAEMON_DEVICE_CLASS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHART = os.path.join(REPO, "deployments", "helm", "tpu-dra-driver")
TEMPLATES = os.path.join(CHART, "templates")


def read(path: str) -> str:
    with open(path) as f:
        return f.read()


def template_files():
    return sorted(
        os.path.join(TEMPLATES, f)
        for f in os.listdir(TEMPLATES)
        if f.endswith((".yaml", ".tpl"))
    )


# --- values.yaml <-> template drift ----------------------------------------


def values_paths(d, prefix=""):
    out = set()
    if isinstance(d, dict):
        for k, v in d.items():
            p = f"{prefix}.{k}" if prefix else k
            out.add(p)
            out.update(values_paths(v, p))
    return out


def test_all_referenced_values_exist():
    defined = values_paths(yaml.safe_load(read(os.path.join(CHART, "values.yaml"))))
    refs = set()
    for path in template_files():
        refs.update(re.findall(r"\.Values\.([A-Za-z0-9_.]+)", read(path)))
    missing = {r for r in refs if r not in defined}
    assert not missing, f"templates reference undefined values: {sorted(missing)}"


def test_braces_balanced():
    for path in template_files():
        text = read(path)
        assert text.count("{{") == text.count("}}"), f"unbalanced braces in {path}"


# --- CRDs ------------------------------------------------------------------


def load_crds():
    crd_dir = os.path.join(CHART, "crds")
    return {
        doc["spec"]["names"]["kind"]: doc
        for f in os.listdir(crd_dir)
        for doc in [yaml.safe_load(read(os.path.join(crd_dir, f)))]
    }


def test_crds_parse_and_match_api_group():
    crds = load_crds()
    assert set(crds) == {"ComputeDomain", "ComputeDomainClique"}
    for kind, crd in crds.items():
        assert crd["spec"]["group"] == version.API_GROUP
        versions = [v["name"] for v in crd["spec"]["versions"]]
        assert version.API_VERSION in versions
        plural = crd["spec"]["names"]["plural"]
        assert crd["metadata"]["name"] == f"{plural}.{version.API_GROUP}"


def test_computedomain_crd_schema_covers_api_fields():
    from tpu_dra.api.computedomain import ComputeDomainSpec, ComputeDomainStatus

    crd = load_crds()["ComputeDomain"]
    schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
    spec_props = schema["properties"]["spec"]["properties"]
    assert set(ComputeDomainSpec.FIELDS) <= set(spec_props)
    status_props = schema["properties"]["status"]["properties"]
    assert set(ComputeDomainStatus.FIELDS) <= set(status_props)
    # status must be a subresource so the controller's status updates work
    assert crd["spec"]["versions"][0]["subresources"] == {"status": {}}


def test_clique_crd_schema_covers_api_fields():
    from tpu_dra.api.computedomain import ComputeDomainDaemonInfo

    crd = load_crds()["ComputeDomainClique"]
    schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
    daemon_props = schema["properties"]["daemons"]["items"]["properties"]
    assert set(ComputeDomainDaemonInfo.FIELDS) <= set(daemon_props)


# --- device classes / driver names -----------------------------------------


def test_deviceclasses_match_code_constants():
    text = read(os.path.join(TEMPLATES, "deviceclasses.yaml"))
    for name in (
        version.DRIVER_NAME,
        "tpu-subslice.google.com",
        "vfio-tpu.google.com",
        DAEMON_DEVICE_CLASS,
        CHANNEL_DEVICE_CLASS,
    ):
        assert f"name: {name}" in text, f"DeviceClass {name} missing"
    # CEL selectors must reference the real driver names
    assert f"device.driver == '{version.DRIVER_NAME}'" in text
    assert f"device.driver == '{version.CD_DRIVER_NAME}'" in text
    # extended-resource bridging on v1 only
    assert "extendedResourceName: google.com/tpu" in text


def test_device_type_attributes_match_allocatable():
    from tpu_dra.plugin import allocatable as alloc

    text = read(os.path.join(TEMPLATES, "deviceclasses.yaml"))
    assert f".type == '{alloc.TPU_DEVICE_TYPE}'" in text
    assert f".type == '{alloc.VFIO_DEVICE_TYPE}'" in text
    # both subslice types are covered by the startsWith selector
    assert alloc.SUBSLICE_STATIC_DEVICE_TYPE.startswith("subslice")
    assert alloc.SUBSLICE_DYNAMIC_DEVICE_TYPE.startswith("subslice")
    assert ".type.startsWith('subslice')" in text


def test_kubeletplugin_runs_real_modules():
    text = read(os.path.join(TEMPLATES, "kubeletplugin.yaml"))
    for mod in ("tpu_dra.plugin.main", "tpu_dra.computedomain.cdplugin.main"):
        assert mod in text
        __import__(mod)  # must be importable


def test_controller_and_webhook_run_real_modules():
    for fname, mod in (
        ("controller.yaml", "tpu_dra.computedomain.controller.main"),
        ("webhook.yaml", "tpu_dra.webhook.main"),
    ):
        assert mod in read(os.path.join(TEMPLATES, fname))
        __import__(mod)


def test_webhook_path_matches_server():
    text = read(os.path.join(TEMPLATES, "webhook.yaml"))
    assert "path: /validate-resource-claim-parameters" in text


# --- RBAC ------------------------------------------------------------------


def test_rbac_covers_crds_and_resourceslices():
    text = read(os.path.join(TEMPLATES, "rbac.yaml"))
    assert f'apiGroups: ["{version.API_GROUP}"]' in text
    assert '"computedomains"' in text
    assert '"resourceslices"' in text
    assert '"resourceclaimtemplates"' in text


def test_vap_restricts_kubeletplugin_sa():
    text = read(os.path.join(TEMPLATES, "validatingadmissionpolicy.yaml"))
    assert "resourceslices" in text
    assert "kubeletplugin" in text
    assert "userNodeName == variables.objectNodeName" in text


# --- packaging -------------------------------------------------------------


def test_pyproject_entry_points_import():
    # tomllib is stdlib only on 3.11+; skip the pyproject cross-check on
    # 3.10 instead of killing the whole module's collection.
    tomllib = pytest.importorskip("tomllib")
    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        proj = tomllib.load(f)
    for target in proj["project"]["scripts"].values():
        mod, func = target.split(":")
        m = __import__(mod, fromlist=[func])
        assert callable(getattr(m, func))


def test_daemonset_render_matches_image_binaries():
    # The controller-rendered per-CD DaemonSet execs a console script that
    # must exist in the image (i.e. be declared in pyproject scripts), and
    # must run under the chart's cd-daemon ServiceAccount.
    tomllib = pytest.importorskip("tomllib")
    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        scripts = set(tomllib.load(f)["project"]["scripts"])

    from tpu_dra.computedomain.controller.daemonset import DaemonSetManager

    mgr = DaemonSetManager(
        None, "tpu-dra-driver", "img:1", service_account="cd-daemon-sa"
    )
    cd = {
        "metadata": {"uid": "u" * 36, "name": "cd", "namespace": "ns"},
        "spec": {"numNodes": 2},
    }
    ds = mgr.render(cd) if hasattr(mgr, "render") else mgr._render(cd)
    pod = ds["spec"]["template"]["spec"]
    assert pod["serviceAccountName"] == "cd-daemon-sa"
    for ctr in pod["containers"]:
        assert ctr["command"][0] in scripts, ctr["command"]
        probe = ctr.get("readinessProbe", {}).get("exec", {}).get("command")
        if probe:
            assert probe[0] in scripts
    # the chart passes the SA name to the controller
    text = read(os.path.join(TEMPLATES, "controller.yaml"))
    assert "DAEMON_SERVICE_ACCOUNT" in text
    rbac = read(os.path.join(TEMPLATES, "rbac.yaml"))
    assert "-cd-daemon" in rbac


def test_dockerfile_consistency():
    text = read(os.path.join(REPO, "deployments", "container", "Dockerfile"))
    from tpu_dra.tpulib.native import NATIVE_LIB_ENV

    assert NATIVE_LIB_ENV in text
    assert "make -C native" in text
    assert os.path.exists(os.path.join(REPO, "native", "Makefile"))


def test_kubeletplugin_mounts_host_sysfs():
    """Driver-root resolution (root.go:29-87 analog): the tpus container
    must see the host's sysfs under /host-sys and point the plugin at it,
    or vfio driver rebind and linux-backend PCI enumeration fail
    in-container."""
    text = read(os.path.join(TEMPLATES, "kubeletplugin.yaml"))
    # BOTH node agents run the linux tpulib backend by default, so both
    # containers need the prefix env + mount.
    assert text.count("TPU_DRA_SYSFS_ROOT") == 2
    assert text.count("mountPath: /host-sys") == 2
    assert "path: /sys" in text
