"""MoE (expert-parallel Mixtral) and pipeline-parallel workload tests.

Runs on the virtual 8-device CPU mesh from conftest.py — the same way the
driver's dryrun validates multi-chip sharding without hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dra.workloads.models.llama import TINY_LLAMA, Llama
from tpu_dra.workloads.models.mixtral import (
    TINY_MIXTRAL,
    Mixtral,
    MixtralConfig,
    MixtralMoE,
)
from tpu_dra.workloads.parallel.mesh import MeshConfig, build_mesh
from tpu_dra.workloads.parallel.pipeline import (
    partition_stages,
    pipeline_apply,
    pipelined_llama_forward,
)
from tpu_dra.workloads.train import Trainer


# --- MoE routing + expert compute -------------------------------------------


def test_mixtral_forward_shapes_finite():
    model = Mixtral(TINY_MIXTRAL)
    params = model.init_params(jax.random.PRNGKey(0), batch=2, seq=16)
    tokens = jnp.ones((2, 16), dtype=jnp.int32)
    logits, aux = model.apply_with_aux(params, tokens)
    assert logits.shape == (2, 16, TINY_MIXTRAL.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))
    # Load-balance aux loss is positive and O(router_aux_weight).
    assert 0.0 < float(aux) < 1.0


def test_single_expert_moe_equals_dense_swiglu():
    """1 expert + top-1 routing must reduce exactly to a SwiGLU MLP with
    that expert's weights (gate weight renormalizes to 1)."""
    config = MixtralConfig(
        dim=32, ffn_dim=64, n_experts=1, top_k=1, capacity_factor=1.0,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    layer = MixtralMoE(config)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32), jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), x)["params"]
    got = layer.apply({"params": params}, x)

    wg = params["experts_w_gate"][0]
    wu = params["experts_w_up"][0]
    wd = params["experts_w_down"][0]
    want = (jax.nn.silu(x @ wg) * (x @ wu)) @ wd
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_moe_capacity_headroom_is_a_noop():
    """When capacity already covers every slot, raising it further must
    not change the output (routing is deterministic, nothing dropped)."""
    base = dict(
        dim=16, ffn_dim=16, n_experts=4, top_k=2,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    layer = MixtralMoE(MixtralConfig(capacity_factor=4.0, **base))
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 8, 16), jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), x)["params"]
    out1 = layer.apply({"params": params}, x)
    assert bool(jnp.all(jnp.isfinite(out1)))
    out2 = MixtralMoE(MixtralConfig(capacity_factor=8.0, **base)).apply(
        {"params": params}, x
    )
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-6)


def test_moe_capacity_drops_tokens():
    """With capacity 1 slot per expert, most slots drop: output becomes
    sparse (some tokens pass zero through the MoE branch) but stays
    finite and differs from the undropped result."""
    base = dict(
        dim=16, ffn_dim=16, n_experts=2, top_k=1,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    tight = MixtralConfig(capacity_factor=0.125, **base)
    loose = MixtralConfig(capacity_factor=4.0, **base)
    x = jax.random.normal(jax.random.PRNGKey(3), (1, 16, 16), jnp.float32)
    params = MixtralMoE(loose).init(jax.random.PRNGKey(0), x)["params"]
    out_tight = MixtralMoE(tight).apply({"params": params}, x)
    out_loose = MixtralMoE(loose).apply({"params": params}, x)
    assert bool(jnp.all(jnp.isfinite(out_tight)))
    assert float(jnp.max(jnp.abs(out_tight - out_loose))) > 1e-6
    # Dropped token rows are exactly zero (pass through residual).
    row_norms = jnp.sum(jnp.abs(out_tight[0]), axis=-1)
    assert int(jnp.sum(row_norms == 0.0)) > 0


def test_mixtral_ep_sharded_matches_single_device():
    """Expert-parallel execution is a layout change, not a numerics
    change: ep=4 sharded forward must match the unsharded forward."""
    model = Mixtral(TINY_MIXTRAL)
    params = model.init_params(jax.random.PRNGKey(0), batch=2, seq=16)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, TINY_MIXTRAL.vocab_size,
        dtype=jnp.int32,
    )
    ref = model.apply({"params": params}, tokens)

    mesh = build_mesh(MeshConfig(ep=4, tp=2))
    from tpu_dra.workloads.parallel.mesh import param_shardings

    sharded = jax.device_put(params, param_shardings(mesh, params))
    with mesh:
        got = jax.jit(lambda p, t: model.apply({"params": p}, t))(
            sharded, tokens
        )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), atol=2e-2, rtol=2e-2
    )


def test_mixtral_trainer_ep_step():
    trainer = Trainer(TINY_MIXTRAL, mesh_config=MeshConfig(dp=2, ep=2, tp=2))
    state = trainer.init_state(batch=4, seq=16)
    step = trainer.make_train_step()
    tokens = jnp.ones((4, 16), dtype=jnp.int32)
    state, loss = step(state, tokens)
    assert bool(jnp.isfinite(loss))
    assert int(state["step"]) == 1
    # Aux loss contributes: loss exceeds pure CE lower bound of 0.
    assert float(loss) > 0.0


# --- pipeline parallelism ---------------------------------------------------


def test_partition_stages_shapes():
    params = {"w": jnp.zeros((4, 3, 5))}
    staged = partition_stages(params, 2)
    assert staged["w"].shape == (2, 2, 3, 5)
    with pytest.raises(ValueError):
        partition_stages({"w": jnp.zeros((3, 2))}, 2)


def test_pipeline_apply_matches_sequential():
    """pp=4 microbatched relay == sequential fold over the stages."""
    mesh = build_mesh(MeshConfig(pp=4, tp=2))
    n_stages, d = 4, 16
    ws = jax.random.normal(
        jax.random.PRNGKey(0), (n_stages, d, d), jnp.float32
    ) / jnp.sqrt(d)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, d), jnp.float32)

    def stage_fn(w, x):
        return jnp.tanh(x @ w[0])  # w: [1, d, d] local stage slice

    staged = ws.reshape(n_stages, 1, d, d)
    got = pipeline_apply(
        stage_fn, staged, x, mesh=mesh, n_microbatches=4
    )

    want = x
    for i in range(n_stages):
        want = jnp.tanh(want @ ws[i])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pipelined_llama_forward_matches_unpipelined():
    mesh = build_mesh(MeshConfig(pp=2, dp=2, tp=2))
    model = Llama(TINY_LLAMA)
    params = model.init_params(jax.random.PRNGKey(0), batch=4, seq=16)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 16), 0, TINY_LLAMA.vocab_size,
        dtype=jnp.int32,
    )
    ref = model.apply({"params": params}, tokens)
    got = jax.jit(
        lambda p, t: pipelined_llama_forward(
            TINY_LLAMA, p, t, mesh=mesh, n_microbatches=2
        )
    )(params, tokens)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), atol=1e-4, rtol=1e-4
    )


def test_pipeline_gradients_flow_to_every_stage():
    mesh = build_mesh(MeshConfig(pp=2, dp=2, tp=2))
    model = Llama(TINY_LLAMA)
    params = model.init_params(jax.random.PRNGKey(0), batch=4, seq=8)
    tokens = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (4, 1))

    def loss(p):
        logits = pipelined_llama_forward(
            TINY_LLAMA, p, tokens, mesh=mesh, n_microbatches=2
        )
        return jnp.mean(logits**2)

    grads = jax.jit(jax.grad(loss))(params)
    # Every scanned layer (both pipeline stages) receives gradient.
    g = grads["layers"]["block"]["attention"]["wq"]["kernel"]
    per_layer = jnp.sum(jnp.abs(g), axis=(1, 2))
    assert per_layer.shape[0] == TINY_LLAMA.n_layers
    assert bool(jnp.all(per_layer > 0))


def test_pipeline_rejects_bad_microbatching():
    mesh = build_mesh(MeshConfig(pp=2, dp=2, tp=2))
    with pytest.raises(ValueError):
        pipeline_apply(
            lambda w, x: x,
            jnp.zeros((2, 1)),
            jnp.zeros((5, 3)),
            mesh=mesh,
            n_microbatches=2,
        )
