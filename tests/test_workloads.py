"""Workload-layer tests on the virtual 8-device CPU mesh: model forward/
grads, attention parity, ring attention vs reference, sharded train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_dra.workloads.bootstrap import read_slice_env
from tpu_dra.workloads.models.llama import (
    TINY_LLAMA,
    Llama,
    LlamaConfig,
    num_params,
)
from tpu_dra.workloads.ops.attention import attention, reference_attention
from tpu_dra.workloads.parallel.context import set_global_mesh
from tpu_dra.workloads.parallel.mesh import (
    MeshConfig,
    build_mesh,
    param_spec,
)
from tpu_dra.workloads.parallel.ring_attention import ring_attention
from tpu_dra.workloads.smoke import matmul_smoke, pmap_psum_smoke
from tpu_dra.workloads.train import Trainer, TrainConfig, loss_fn


@pytest.fixture(autouse=True)
def clear_mesh():
    set_global_mesh(None)
    yield
    set_global_mesh(None)


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8  # conftest sets the XLA flag


# --- attention --------------------------------------------------------------


def test_reference_attention_causal():
    b, s, h, hd = 2, 16, 4, 8
    rng = jax.random.PRNGKey(0)
    q, k, v = jax.random.normal(rng, (3, b, s, h, hd), dtype=jnp.float32)
    out = reference_attention(q, k, v, causal=True)
    assert out.shape == (b, s, h, hd)
    # First position attends only to itself: out[0] == v[0].
    np.testing.assert_allclose(out[:, 0], v[:, 0], rtol=1e-5)


def test_gqa_matches_repeated_kv():
    b, s, h, kvh, hd = 1, 8, 4, 2, 8
    rng = jax.random.PRNGKey(1)
    q = jax.random.normal(rng, (b, s, h, hd))
    k = jax.random.normal(jax.random.PRNGKey(2), (b, s, kvh, hd))
    v = jax.random.normal(jax.random.PRNGKey(3), (b, s, kvh, hd))
    out = reference_attention(q, k, v, causal=True)
    k_full = jnp.repeat(k, 2, axis=2)
    v_full = jnp.repeat(v, 2, axis=2)
    # repeat_kv uses grouped order [kv0, kv0, kv1, kv1]; jnp.repeat matches.
    out_full = reference_attention(q, k_full, v_full, causal=True)
    np.testing.assert_allclose(out, out_full, rtol=1e-5)


def test_attention_dispatcher_fallback_on_cpu():
    b, s, h, hd = 1, 8, 2, 4
    q = k = v = jnp.ones((b, s, h, hd))
    out = attention(q, k, v, impl="auto")  # cpu -> xla path
    assert out.shape == q.shape


def test_ring_attention_matches_reference():
    mesh = build_mesh(MeshConfig(dp=1, fsdp=1, sp=8, tp=1))
    set_global_mesh(mesh)
    b, s, h, hd = 2, 32, 4, 8  # s=32 -> 4 tokens per device
    rng = jax.random.PRNGKey(7)
    q = jax.random.normal(rng, (b, s, h, hd), dtype=jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(8), (b, s, h, hd), dtype=jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(9), (b, s, h, hd), dtype=jnp.float32)
    want = reference_attention(q, k, v, causal=True)
    got = ring_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


def test_ring_attention_gqa():
    mesh = build_mesh(MeshConfig(dp=1, fsdp=1, sp=8, tp=1))
    set_global_mesh(mesh)
    b, s, h, kvh, hd = 1, 16, 4, 2, 8
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, hd))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, kvh, hd))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, kvh, hd))
    want = reference_attention(q, k, v, causal=True)
    got = ring_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


def test_ring_attention_falls_back_without_mesh():
    q = k = v = jnp.ones((1, 8, 2, 4))
    out = ring_attention(q, k, v)
    assert out.shape == q.shape


# --- model ------------------------------------------------------------------


def test_llama_forward_shapes_and_grads():
    model = Llama(TINY_LLAMA)
    params = model.init_params(jax.random.PRNGKey(0), batch=2, seq=8)
    tokens = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]] * 2, dtype=jnp.int32)
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 8, TINY_LLAMA.vocab_size)
    assert logits.dtype == jnp.float32
    loss, grads = jax.value_and_grad(lambda p: loss_fn(model, p, tokens))(params)
    assert jnp.isfinite(loss)
    flat = jax.tree_util.tree_leaves(grads)
    assert all(jnp.all(jnp.isfinite(g)) for g in flat)
    assert any(jnp.any(g != 0) for g in flat)


def test_llama_scan_and_loop_agree():
    cfg_scan = TINY_LLAMA
    cfg_loop = LlamaConfig(**{**TINY_LLAMA.__dict__, "scan_layers": False})
    tokens = jnp.arange(8, dtype=jnp.int32)[None]
    m1, m2 = Llama(cfg_scan), Llama(cfg_loop)
    p1 = m1.init_params(jax.random.PRNGKey(0), seq=8)
    # Map scanned params [layer, ...] into per-layer dicts for the loop model.
    p2 = m2.init_params(jax.random.PRNGKey(0), seq=8)

    def copy_layer(i):
        src = p1["layers"]["block"]
        return jax.tree_util.tree_map(lambda x: x[i], src)

    p2 = dict(p2)
    for i in range(cfg_loop.n_layers):
        p2[f"layer_{i}"] = copy_layer(i)
    p2["embed"] = p1["embed"]
    p2["final_norm"] = p1["final_norm"]
    p2["lm_head"] = p1["lm_head"]
    out1 = m1.apply({"params": p1}, tokens)
    out2 = m2.apply({"params": p2}, tokens)
    # bf16 intermediates: scan vs unrolled fuse differently; only rounding-
    # level divergence is acceptable.
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), rtol=3e-2, atol=3e-2)


def test_num_params_llama3_8b():
    assert 7.9e9 < num_params(LlamaConfig()) < 8.2e9


# --- sharding rules ---------------------------------------------------------


def test_param_spec_rules():
    assert param_spec("layers/block/attention/wq/kernel") == P("fsdp", "tp")
    assert param_spec("layers/block/attention/wo/kernel") == P("tp", "fsdp")
    assert param_spec("layers/block/mlp/w_gate/kernel") == P("fsdp", "tp")
    assert param_spec("embed/embedding") == P("tp", "fsdp")
    assert param_spec("final_norm/scale") == P()
    assert param_spec("lm_head/kernel") == P("fsdp", "tp")

    class FakeArr:
        ndim = 3

    # Scanned params get a leading layer axis.
    assert param_spec("layers/block/attention/wq/kernel", FakeArr()) == P(
        None, "fsdp", "tp"
    )


# --- end-to-end sharded training -------------------------------------------


def test_trainer_full_sharded_step():
    """The dryrun_multichip path: tiny llama, 8-device mesh with dp/fsdp/
    sp/tp all non-trivial, one real train step."""
    cfg = LlamaConfig(**{**TINY_LLAMA.__dict__, "attention_impl": "ring"})
    trainer = Trainer(
        cfg,
        mesh_config=MeshConfig(dp=1, fsdp=2, sp=2, tp=2),
        train_config=TrainConfig(learning_rate=1e-3),
    )
    state = trainer.init_state(batch=4, seq=16)
    # Params actually sharded: wq kernel split over fsdp and tp.
    wq = state["params"]["layers"]["block"]["attention"]["wq"]["kernel"]
    assert wq.sharding.spec == P(None, "fsdp", "tp")
    step = trainer.make_train_step()
    tokens = jnp.tile(jnp.arange(16, dtype=jnp.int32)[None], (4, 1))
    state2, loss1 = step(state, tokens)
    state3, loss2 = step(state2, tokens)
    assert jnp.isfinite(loss1) and jnp.isfinite(loss2)
    assert float(loss2) < float(loss1)  # it learns the repeated batch
    assert int(state3["step"]) == 2


def test_smoke_workloads():
    from tpu_dra.workloads.smoke import decode_smoke

    r = pmap_psum_smoke()
    assert r["ok"] and r["devices"] == 8
    m = matmul_smoke(256)
    assert m["ok"]
    d = decode_smoke(max_new_tokens=4)
    assert d["ok"], d


def test_bootstrap_env_parsing():
    env = {
        "TPU_WORKER_ID": "3",
        "JAX_NUM_PROCESSES": "4",
        "JAX_COORDINATOR_ADDRESS": "compute-domain-daemon-0:8476",
        "TPU_ACCELERATOR_TYPE": "v5p-16",
        "MEGASCALE_NUM_SLICES": "2",
        "MEGASCALE_SLICE_ID": "1",
    }
    se = read_slice_env(env)
    assert se.worker_id == 3 and se.num_processes == 4
    assert se.multi_host
    assert se.num_slices == 2 and se.slice_id == 1
    assert read_slice_env({}).multi_host is False


def test_ulysses_attention_matches_reference():
    from tpu_dra.workloads.parallel.ulysses import ulysses_attention

    mesh = build_mesh(MeshConfig(dp=1, fsdp=1, sp=8, tp=1))
    set_global_mesh(mesh)
    b, s, h, hd = 2, 32, 8, 8  # 8 heads over sp=8 -> 1 head per device
    q = jax.random.normal(jax.random.PRNGKey(3), (b, s, h, hd))
    k = jax.random.normal(jax.random.PRNGKey(4), (b, s, h, hd))
    v = jax.random.normal(jax.random.PRNGKey(5), (b, s, h, hd))
    want = reference_attention(q, k, v, causal=True)
    got = ulysses_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


def test_ulysses_attention_gqa_and_errors():
    from tpu_dra.workloads.parallel.ulysses import ulysses_attention

    mesh = build_mesh(MeshConfig(dp=1, fsdp=1, sp=8, tp=1))
    set_global_mesh(mesh)
    b, s, h, kvh, hd = 1, 16, 8, 2, 8
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, hd))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, kvh, hd))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, kvh, hd))
    want = reference_attention(q, k, v, causal=True)
    got = ulysses_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)
    # heads not divisible by sp -> loud error, not silent aliasing
    import pytest as _pytest

    bad_q = jax.random.normal(jax.random.PRNGKey(6), (1, 16, 6, 8))
    with _pytest.raises(ValueError, match="divisible"):
        ulysses_attention(bad_q, bad_q, bad_q)


def test_ulysses_attention_falls_back_without_mesh():
    from tpu_dra.workloads.parallel.ulysses import ulysses_attention

    set_global_mesh(None)
    q = k = v = jnp.ones((1, 8, 2, 4))
    out = ulysses_attention(q, k, v)
    assert out.shape == q.shape


def test_llama_ulysses_impl_trains():
    import dataclasses as _dc

    from tpu_dra.workloads.models.llama import TINY_LLAMA, LlamaConfig
    from tpu_dra.workloads.train import TrainConfig, Trainer

    config = LlamaConfig(
        **{**_dc.asdict(TINY_LLAMA), "attention_impl": "ulysses"}
    )
    trainer = Trainer(
        config,
        mesh_config=MeshConfig(dp=1, fsdp=1, sp=2, tp=2),
        train_config=TrainConfig(),
        devices=jax.devices()[:4],
    )
    state = trainer.init_state(batch=2, seq=16)
    step = trainer.make_train_step()
    tokens = jnp.tile(jnp.arange(16, dtype=jnp.int32)[None], (2, 1))
    state, loss = step(state, tokens)
    assert jnp.isfinite(loss)


def test_ulysses_gqa_unrepeated_exchange():
    """When kv heads divide the sp axis, K/V ride the all_to_all
    un-repeated (n_rep x less collective volume) and still match."""
    from tpu_dra.workloads.parallel.ulysses import ulysses_attention

    mesh = build_mesh(MeshConfig(dp=1, fsdp=2, sp=4, tp=1))
    set_global_mesh(mesh)
    b, s, h, kvh, hd = 2, 16, 8, 4, 8  # kvh=4 % sp=4 == 0
    q = jax.random.normal(jax.random.PRNGKey(10), (b, s, h, hd))
    k = jax.random.normal(jax.random.PRNGKey(11), (b, s, kvh, hd))
    v = jax.random.normal(jax.random.PRNGKey(12), (b, s, kvh, hd))
    want = reference_attention(q, k, v, causal=True)
    got = ulysses_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


def test_fused_ce_matches_unfused_loss_and_grads():
    """ops/loss.py streamed LM-head loss == the materialized-logits loss,
    for values AND gradients, including a chunk size that does not divide
    the sequence (tail chunk zero-padded + masked) and the masked final
    position."""
    import dataclasses

    # fp32 end-to-end: the comparison is about the chunked algorithm, not
    # bf16 rounding (chunk-ordered sums flip the last bf16 bit on a few
    # near-zero grad elements).
    base = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
    )
    cfg = dataclasses.replace(base, fused_ce=True, ce_chunk=5)
    fused = Llama(cfg)
    plain = Llama(base)
    params = plain.init_params(jax.random.PRNGKey(2), batch=2, seq=12)
    tokens = jax.random.randint(
        jax.random.PRNGKey(3), (2, 12), 0, TINY_LLAMA.vocab_size
    ).astype(jnp.int32)

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: loss_fn(plain, p, tokens)
    )(params)
    fused_loss, fused_grads = jax.value_and_grad(
        lambda p: loss_fn(fused, p, tokens)
    )(params)

    np.testing.assert_allclose(
        float(fused_loss), float(ref_loss), rtol=2e-5
    )
    flat_ref = jax.tree_util.tree_leaves_with_path(ref_grads)
    flat_fused = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_leaves_with_path(fused_grads)
    )
    for k, g_ref in flat_ref:
        g = flat_fused[jax.tree_util.keystr(k)]
        np.testing.assert_allclose(
            np.asarray(g, np.float32),
            np.asarray(g_ref, np.float32),
            rtol=1e-4, atol=1e-6,
            err_msg=jax.tree_util.keystr(k),
        )


@pytest.mark.parametrize("kv", ["none", "int8"], ids=["bf16kv", "int8kv"])
@pytest.mark.parametrize("scan", [True, False], ids=["stacked", "unrolled"])
def test_decode_matches_full_forward(scan, kv):
    """generate.py's hand-rolled KV-cache decode must replay the training
    forward: teacher-forced decode logits == full causal forward logits,
    both for a whole-prompt prefill chunk and for one-token steps — in
    the full {stacked, unrolled} x {bf16, int8-KV} matrix. The bf16
    cache matches exactly (fp32 tolerance); the int8 cache is
    tolerance-pinned (per-(token, head) rounding only) and must keep
    >= 99% argmax agreement — the serving-quality bar."""
    import dataclasses

    from tpu_dra.workloads.generate import (
        forward_chunk,
        greedy_generate,
        init_cache,
    )

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32,
        scan_layers=scan,
    )
    model = Llama(cfg)
    params = model.init_params(jax.random.PRNGKey(7), batch=2, seq=10)
    tokens = jax.random.randint(
        jax.random.PRNGKey(8), (2, 10), 0, cfg.vocab_size
    ).astype(jnp.int32)
    full = model.apply({"params": params}, tokens)  # [2, 10, vocab]

    def check(got, want):
        if kv == "none":
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
            )
            return
        got, want = np.asarray(got), np.asarray(want)
        rel = np.linalg.norm(got - want) / (np.linalg.norm(want) + 1e-9)
        assert rel < 0.02, f"int8-KV logits drifted {rel:.4f}"
        agree = np.mean(np.argmax(got, -1) == np.argmax(want, -1))
        assert agree >= 0.99, f"int8-KV argmax agreement {agree:.3f}"

    # Prefill chunk == full forward.
    cache, prefill_logits = forward_chunk(
        cfg, params, init_cache(cfg, 2, 16, stacked=scan, kv_quant=kv),
        tokens,
    )
    check(prefill_logits, full)
    assert int(cache.pos) == 10
    assert bool(cache.tail_is_zero())

    # Two-chunk prefill (pos>0 AND s>1): the stacked layout's score
    # overwrite + value correction at a nonzero offset, the subtlest
    # configuration of the split contraction.
    cache_mc = init_cache(cfg, 2, 16, stacked=scan, kv_quant=kv)
    cache_mc, lg_a = forward_chunk(cfg, params, cache_mc, tokens[:, :6])
    cache_mc, lg_b = forward_chunk(cfg, params, cache_mc, tokens[:, 6:])
    check(
        jnp.concatenate([lg_a, lg_b], axis=1),
        full,
    )

    # Teacher-forced single-token steps == full forward, position by
    # position (the fused decode-attention path, offsets, and the
    # length-aware mask all in play).
    cache2 = init_cache(cfg, 2, 16, stacked=scan, kv_quant=kv)
    step_logits = []
    for t in range(10):
        cache2, lg = forward_chunk(cfg, params, cache2, tokens[:, t:t + 1])
        step_logits.append(np.asarray(lg[:, 0]))
    check(np.stack(step_logits, axis=1), full)

    # greedy_generate: right shape, prompt preserved, jit-clean, and
    # consistent with stepwise argmax.
    out = jax.jit(
        lambda p, t: greedy_generate(cfg, p, t, max_new_tokens=4,
                                     kv_quant=kv)
    )(params, tokens)
    assert out.shape == (2, 14)
    assert jnp.array_equal(out[:, :10], tokens)
    assert jnp.array_equal(
        out[:, 10], jnp.argmax(full[:, -1], axis=-1).astype(tokens.dtype)
    )


def test_decode_attention_op_matches_reference():
    """ops/attention.py decode_attention: the chunked length-aware XLA
    path == the naive fp32 oracle == reference_attention on the live
    prefix — bf16/int8 caches, chunk-unaligned lengths, and the
    stacked-layout extra-kv (stale streamed cache) form."""
    from tpu_dra.workloads.ops.attention import (
        decode_attention,
        reference_decode_attention,
    )
    from tpu_dra.workloads.quantize import dequantize_kv, quantize_kv

    b, S, h, kvh, hd = 2, 24, 8, 2, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, hd), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, S, kvh, hd))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, S, kvh, hd))
    kq, ksc = quantize_kv(k)
    vq, vsc = quantize_kv(v)
    for length in (1, 5, 16, 24):
        L = jnp.int32(length)
        ref = reference_decode_attention(q, k, v, L)
        # Oracle == the generic reference attention on the live prefix.
        want = reference_attention(
            q[:, None], k[:, :length], v[:, :length], causal=True
        )[:, 0]
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(want), rtol=2e-5, atol=2e-5
        )
        got = decode_attention(q, k, v, L, impl="xla", block_k=8)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )
        # extra-kv: cache live to length-1 plus the newest token exact —
        # in BOTH the chunked path and the oracle itself (the stacked
        # layout's decode step under decode_impl="reference").
        for impl in ("xla", "reference"):
            got2 = decode_attention(
                q, k, v, L, extra_k=k[:, length - 1],
                extra_v=v[:, length - 1], impl=impl, block_k=8,
            )
            np.testing.assert_allclose(
                np.asarray(got2), np.asarray(ref), rtol=2e-5, atol=2e-5,
                err_msg=f"extra-kv {impl}",
            )
        # int8: both impls against the dequantized-cache oracle.
        refq = reference_decode_attention(
            q, dequantize_kv(kq, ksc), dequantize_kv(vq, vsc), L
        )
        gotq = decode_attention(
            q, kq, vq, L, k_scale=ksc, v_scale=vsc, impl="xla", block_k=8
        )
        np.testing.assert_allclose(
            np.asarray(gotq), np.asarray(refq), rtol=1e-4, atol=1e-4
        )
    # Block-size selection: largest divisor <= block_k (a halving-only
    # search would collapse 48 -> 3 instead of 24), and correctness at
    # an awkward (prime) cache length that forces block 1.
    from tpu_dra.workloads.ops.attention import _decode_block_k

    assert _decode_block_k(48, 32) == 24
    assert _decode_block_k(384, 256) == 192
    assert _decode_block_k(13, 256) == 13
    assert _decode_block_k(17, 8) == 1
    kp = jax.random.normal(jax.random.PRNGKey(5), (b, 17, kvh, hd))
    vp = jax.random.normal(jax.random.PRNGKey(6), (b, 17, kvh, hd))
    np.testing.assert_allclose(
        np.asarray(decode_attention(q, kp, vp, jnp.int32(9), impl="xla")),
        np.asarray(reference_decode_attention(q, kp, vp, jnp.int32(9))),
        rtol=2e-5, atol=2e-5,
    )

    # Loud errors, not silent garbage.
    with pytest.raises(ValueError, match="multiple"):
        decode_attention(q[:, :3], k, v, jnp.int32(4))
    with pytest.raises(ValueError, match="together"):
        decode_attention(q, kq, vq, jnp.int32(4), k_scale=ksc)
    with pytest.raises(ValueError, match="impl"):
        decode_attention(q, k, v, jnp.int32(4), impl="nope")


def test_topk_exact_two_stage():
    """generate.topk_exact: the two-stage segmented top-k must be
    bit-identical to lax.top_k (values AND indices, including the
    descending order and low-index tie-breaks) at the bench vocab shape,
    and fall back cleanly at shapes the split doesn't cover."""
    from tpu_dra.workloads.generate import _TOPK_CHUNK, topk_exact

    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8 * _TOPK_CHUNK))
    for k in (1, 40, 64):
        v1, i1 = topk_exact(x, k)
        v2, i2 = jax.lax.top_k(x, k)
        assert jnp.array_equal(v1, v2) and jnp.array_equal(i1, i2)
    # Ties across segments resolve to the lower index, like lax.top_k.
    t = jnp.zeros((1, 2 * _TOPK_CHUNK))
    v1, i1 = topk_exact(t, 3)
    v2, i2 = jax.lax.top_k(t, 3)
    assert jnp.array_equal(i1, i2)
    # Non-dividing / small vocab: direct lax.top_k path.
    xs = jax.random.normal(jax.random.PRNGKey(2), (2, 100))
    v1, i1 = topk_exact(xs, 5)
    v2, i2 = jax.lax.top_k(xs, 5)
    assert jnp.array_equal(v1, v2) and jnp.array_equal(i1, i2)


def test_fused_sampler_parity():
    """ISSUE 2 satellite: the sampler fused into the decode scan must be
    TOKEN-IDENTICAL to the per-token unfused loop for a fixed key (same
    fold_in schedule, same top-k candidate draw) — across temperatures,
    top_k settings, and the int8-KV cache."""
    import dataclasses

    from tpu_dra.workloads.generate import (
        sample_generate,
        sample_generate_unfused,
    )

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
    )
    model = Llama(cfg)
    params = model.init_params(jax.random.PRNGKey(0), batch=2, seq=6)
    prompt = jnp.tile(jnp.arange(6, dtype=jnp.int32)[None], (2, 1))
    rng = jax.random.PRNGKey(42)
    for kwargs in (
        {"temperature": 0.8, "top_k": 8},
        {"temperature": 1.3, "top_k": 3},
        {"temperature": 1.0, "top_k": 0},
        {"temperature": 0.8, "top_k": 8, "kv_quant": "int8"},
    ):
        fused = sample_generate(
            cfg, params, prompt, max_new_tokens=6, rng=rng, **kwargs
        )
        unfused = sample_generate_unfused(
            cfg, params, prompt, max_new_tokens=6, rng=rng, **kwargs
        )
        assert jnp.array_equal(fused, unfused), kwargs


def test_sample_generate_modes():
    """Sampling shares the greedy cache machinery: top_k=1 and
    temperature=0 are exactly greedy; near-zero temperature converges to
    greedy; full sampling stays in-vocab and preserves the prompt."""
    import dataclasses

    from tpu_dra.workloads.generate import greedy_generate, sample_generate

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
    )
    model = Llama(cfg)
    params = model.init_params(jax.random.PRNGKey(0), batch=2, seq=6)
    prompt = jnp.tile(jnp.arange(6, dtype=jnp.int32)[None], (2, 1))
    rng = jax.random.PRNGKey(42)

    greedy = greedy_generate(cfg, params, prompt, max_new_tokens=6)
    for kwargs in ({"top_k": 1}, {"temperature": 0.0}):
        out = sample_generate(
            cfg, params, prompt, max_new_tokens=6, rng=rng, **kwargs
        )
        assert jnp.array_equal(out, greedy), kwargs
    # Tiny temperature: distribution collapses onto the argmax.
    cold = sample_generate(
        cfg, params, prompt, max_new_tokens=6, rng=rng, temperature=1e-4
    )
    assert jnp.array_equal(cold, greedy)
    # Full sampling under jit: in-vocab ids, prompt preserved.
    hot = jax.jit(
        lambda p, t, r: sample_generate(
            cfg, p, t, max_new_tokens=6, rng=r, temperature=1.0, top_k=8
        )
    )(params, prompt, rng)
    assert hot.shape == (2, 12)
    assert jnp.array_equal(hot[:, :6], prompt)
    assert bool(jnp.all((hot >= 0) & (hot < cfg.vocab_size)))


@pytest.mark.parametrize("scan", [True, False], ids=["stacked", "unrolled"])
def test_int8_weight_only_decode(scan):
    """workloads/quantize.py: per-output-channel int8 weight-only
    quantization. Unit bound: dequantization error <= scale/2 per
    element. E2E: the SAME decode code runs the quantized tree (both
    param layouts) and its teacher-forced logits stay close to full
    precision — quantized serving must not fork the forward."""
    import dataclasses

    from tpu_dra.workloads.generate import (
        forward_chunk,
        greedy_generate,
        init_cache,
    )
    from tpu_dra.workloads.quantize import (
        dequantize_weight,
        quantize_params,
        quantize_weight,
    )

    # Unit: error bound + int8 range, including a zero column (scale
    # guard) — per-channel scale means each output column is bounded by
    # ITS OWN absmax/254.
    w = jax.random.normal(jax.random.PRNGKey(0), (32, 16), jnp.float32)
    w = w.at[:, 3].set(0.0)
    q = quantize_weight(w)
    assert q["kernel_q"].dtype == jnp.int8
    assert int(jnp.max(jnp.abs(q["kernel_q"]))) <= 127
    err = jnp.abs(dequantize_weight(q) - w)
    assert bool(jnp.all(err <= q["scale"] / 2 + 1e-7))

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32,
        scan_layers=scan,
    )
    model = Llama(cfg)
    params = model.init_params(jax.random.PRNGKey(7), batch=2, seq=10)
    qparams = quantize_params(params)
    # Norm scales and embeddings must be untouched; kernels replaced.
    assert "embedding" in qparams["embed"]
    assert "kernel_q" in qparams["lm_head"]

    tokens = jax.random.randint(
        jax.random.PRNGKey(8), (2, 10), 0, cfg.vocab_size
    ).astype(jnp.int32)
    _, fp = forward_chunk(
        cfg, params, init_cache(cfg, 2, 16, stacked=scan), tokens
    )
    _, q8 = forward_chunk(
        cfg, qparams, init_cache(cfg, 2, 16, stacked=scan), tokens
    )
    # Quality: relative error of the logit tensor stays small (weight
    # rounding only; activations stay fp32 here).
    rel = float(
        jnp.linalg.norm(q8 - fp) / (jnp.linalg.norm(fp) + 1e-9)
    )
    assert rel < 0.05, f"int8 logits drifted {rel:.3f} from fp"

    # Generation over the quantized tree is jit-clean end to end.
    out = jax.jit(
        lambda p, t: greedy_generate(cfg, p, t, max_new_tokens=4)
    )(qparams, tokens)
    assert out.shape == (2, 14)
    assert jnp.array_equal(out[:, :10], tokens)


def test_int8_pallas_kernel_matches_xla(monkeypatch):
    """ops/int8mm.py kernel in interpreter mode == the XLA dequant
    matmul, at kernel-tileable shapes (the bench model's projections)."""
    from tpu_dra.workloads.ops import int8mm
    from tpu_dra.workloads.quantize import quantize_weight

    monkeypatch.setattr(int8mm, "_INTERPRET", True)
    # Shapes must TILE (multiples of _BM/_BN/_BK) or the dispatcher
    # falls back to XLA and the kernel is never exercised.
    x = jax.random.normal(jax.random.PRNGKey(0), (128, 1024), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (1024, 1024), jnp.float32)
    q = quantize_weight(w)
    assert (
        x.shape[0] % int8mm._BM == 0
        and w.shape[1] % int8mm._BN == 0
        and x.shape[1] % int8mm._BK == 0
    ), "test shapes no longer tile the kernel blocks"
    got = int8mm.int8_matmul(x, q["kernel_q"], q["scale"])
    want = int8mm._xla_int8_matmul(x, q["kernel_q"], q["scale"])
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )
    # Leading batch dims reshape through the same kernel.
    x3 = x.reshape(2, 64, 1024)
    got3 = int8mm.int8_matmul(x3, q["kernel_q"], q["scale"])
    assert got3.shape == (2, 64, 1024)
    np.testing.assert_allclose(
        np.asarray(got3.reshape(128, 1024)), np.asarray(want),
        rtol=1e-5, atol=1e-5,
    )
    # Non-tileable shapes fall back to XLA (no crash, same math).
    xs = jax.random.normal(jax.random.PRNGKey(2), (3, 1024), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(int8mm.int8_matmul(xs, q["kernel_q"], q["scale"])),
        np.asarray(int8mm._xla_int8_matmul(xs, q["kernel_q"], q["scale"])),
        rtol=1e-5,
    )


def test_param_spec_quantized_kernels_inherit_sharding():
    """int8 trees: kernel_q inherits the plain kernel's spec; the
    per-channel scale replicates (falls through the rules)."""
    assert param_spec("layers/block/attention/wq/kernel_q") == P("fsdp", "tp")
    assert param_spec("layer_3/mlp/w_down/kernel_q") == param_spec(
        "layer_3/mlp/w_down/kernel"
    )
    assert param_spec("layers/block/attention/wq/scale") == P()


def test_decode_cache_zero_tail_and_check():
    """ADVICE r4: the stacked-layout zero-tail invariant gets a
    re-establishing utility (speculative-decode rewind) and a checkable
    assertion instead of a docstring-only contract."""
    from tpu_dra.workloads.generate import DecodeCache, init_cache
    from tpu_dra.workloads.models.llama import TINY_LLAMA

    cache = init_cache(TINY_LLAMA, batch=2, max_seq=8, stacked=True)
    assert bool(cache.tail_is_zero())
    # A rewind without zeroing breaks the invariant...
    dirty = DecodeCache(
        k=cache.k + 1.0, v=cache.v + 1.0, pos=jnp.int32(4)
    )
    assert not bool(dirty.tail_is_zero())
    # ...and zero_tail repairs exactly the tail, preserving [0, pos).
    repaired = dirty.zero_tail()
    assert bool(repaired.tail_is_zero())
    np.testing.assert_array_equal(
        np.asarray(repaired.k[:, :, :4]), np.asarray(dirty.k[:, :, :4])
    )
    assert np.all(np.asarray(repaired.k[:, :, 4:]) == 0)
    # Unrolled (tuple) layout takes the same path.
    tcache = init_cache(TINY_LLAMA, batch=2, max_seq=8, stacked=False)
    tdirty = DecodeCache(
        k=tuple(a + 1.0 for a in tcache.k),
        v=tuple(a + 1.0 for a in tcache.v),
        pos=jnp.int32(3),
    )
    assert not bool(tdirty.tail_is_zero())
    assert bool(tdirty.zero_tail().tail_is_zero())
    # int8 caches carry the invariant on the SCALE arrays too: a dirty
    # scale tail alone must be detected and repaired.
    qcache = init_cache(
        TINY_LLAMA, batch=2, max_seq=8, stacked=True, kv_quant="int8"
    )
    assert qcache.quantized and bool(qcache.tail_is_zero())
    qdirty = DecodeCache(
        k=qcache.k, v=qcache.v, pos=jnp.int32(4),
        k_scale=qcache.k_scale + 1.0, v_scale=qcache.v_scale,
    )
    assert not bool(qdirty.tail_is_zero())
    qfixed = qdirty.zero_tail()
    assert bool(qfixed.tail_is_zero())
    np.testing.assert_array_equal(
        np.asarray(qfixed.k_scale[:, :, :4]),
        np.asarray(qdirty.k_scale[:, :, :4]),
    )


@pytest.mark.parametrize("kv", ["none", "int8"], ids=["bf16kv", "int8kv"])
@pytest.mark.parametrize("scan", [True, False], ids=["stacked", "unrolled"])
def test_zero_tail_length_mask_interaction(scan, kv):
    """ISSUE 2 satellite: the length-aware decode masking must compose
    with the speculative-rewind contract, in both directions:

    1. a POISONED tail (garbage at positions >= pos, the state after a
       speculative rejection rewind) must not leak into an s=1 decode
       step — decode attention's length bound never admits those slots;
    2. ``zero_tail()`` after a rewind re-establishes the full invariant,
       so subsequent PREFILL chunks (which do rely on zero tails in the
       stacked split contraction) also match a never-rewound cache."""
    import dataclasses

    from tpu_dra.workloads.generate import (
        DecodeCache,
        forward_chunk,
        init_cache,
    )

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32,
        scan_layers=scan,
    )
    model = Llama(cfg)
    params = model.init_params(jax.random.PRNGKey(3), batch=2, seq=8)
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (2, 8), 0, cfg.vocab_size
    ).astype(jnp.int32)

    clean = init_cache(cfg, 2, 12, stacked=scan, kv_quant=kv)
    clean, _ = forward_chunk(cfg, params, clean, tokens[:, :5])

    def poison(a):
        # Garbage ONLY in the dead tail [pos, max_seq); dtype-preserving.
        tail = (jnp.arange(a.shape[2 if scan else 1]) >= 5).reshape(
            [1] * (2 if scan else 1) + [-1] + [1] * (a.ndim - (3 if scan else 2))
        )
        return a + (7 * tail).astype(a.dtype)

    fields = {"k": clean.k, "v": clean.v}
    if kv == "int8":
        fields.update(k_scale=clean.k_scale, v_scale=clean.v_scale)
    dirty = DecodeCache(
        pos=clean.pos,
        **{
            n: poison(a) if scan else tuple(poison(x) for x in a)
            for n, a in fields.items()
        },
    )
    assert not bool(dirty.tail_is_zero())

    # (1) An s=1 decode step over the poisoned cache == the clean step:
    # the length mask bounds every read at pos.
    _, lg_clean = forward_chunk(cfg, params, clean, tokens[:, 5:6])
    _, lg_dirty = forward_chunk(cfg, params, dirty, tokens[:, 5:6])
    np.testing.assert_allclose(
        np.asarray(lg_dirty), np.asarray(lg_clean), rtol=1e-5, atol=1e-5
    )

    # (2) zero_tail repairs the cache for the prefill-chunk path too.
    repaired = dirty.zero_tail()
    assert bool(repaired.tail_is_zero())
    _, lg_rep = forward_chunk(cfg, params, repaired, tokens[:, 5:8])
    _, lg_ref = forward_chunk(cfg, params, clean, tokens[:, 5:8])
    np.testing.assert_allclose(
        np.asarray(lg_rep), np.asarray(lg_ref), rtol=1e-5, atol=1e-5
    )


def test_quantize_rejects_unexpected_kernel_nodes():
    """ADVICE r4: a kernel with sibling keys or an unexpected rank must
    fail loudly, not silently stay bf16."""
    from tpu_dra.workloads.quantize import quantize_params

    good = {"wq": {"kernel": jnp.ones((4, 4), jnp.float32)}}
    q = quantize_params(good)
    assert q["wq"]["kernel_q"].dtype == jnp.int8
    with pytest.raises(ValueError, match="unquantizable"):
        quantize_params({"wq": {
            "kernel": jnp.ones((4, 4)), "bias": jnp.ones((4,))
        }})
    with pytest.raises(ValueError, match="unquantizable"):
        quantize_params({"wq": {"kernel": jnp.ones((4,))}})


# --- fused decode MLP block (ISSUE 8) ----------------------------------------


def _mlp_tree(seed, d, f, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    return (
        jax.random.normal(ks[0], (d,), dtype),  # norm scale
        {
            "w_gate": {"kernel": jax.random.normal(ks[1], (d, f), dtype)},
            "w_up": {"kernel": jax.random.normal(ks[2], (d, f), dtype)},
            "w_down": {"kernel": jax.random.normal(ks[3], (f, d), dtype)},
        },
    )


def test_decode_mlp_xla_matches_reference():
    from tpu_dra.workloads.ops import decode_mlp as DM

    scale, mlp = _mlp_tree(0, d=64, f=128)
    x = jax.random.normal(jax.random.PRNGKey(9), (3, 64), jnp.float32)
    ref = DM.decode_mlp(x, scale, mlp, 1e-5, impl="reference")
    xla = DM.decode_mlp(x, scale, mlp, 1e-5, impl="xla")
    assert float(jnp.max(jnp.abs(xla - ref))) < 1e-4


def test_decode_mlp_pallas_interpret_matches_reference(monkeypatch):
    from tpu_dra.workloads.ops import attention as A
    from tpu_dra.workloads.ops import decode_mlp as DM

    monkeypatch.setattr(A, "_INTERPRET", True)
    scale, mlp = _mlp_tree(1, d=256, f=512)
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 256), jnp.float32)
    ref = DM.decode_mlp(x, scale, mlp, 1e-5, impl="reference")
    for bf in (128, 512):
        got = DM.decode_mlp(
            x, scale, mlp, 1e-5, impl="pallas", block_f=bf
        )
        rel = float(
            jnp.max(jnp.abs(got - ref)) / jnp.max(jnp.abs(ref))
        )
        assert rel < 1e-5, f"block_f={bf}: rel err {rel}"
    # auto under interpret (stand-in for TPU) picks pallas for aligned
    # shapes...
    DM._LAST_DECODE_MLP_IMPL = None
    DM.decode_mlp(x, scale, mlp, 1e-5)
    assert DM._LAST_DECODE_MLP_IMPL == "pallas"
    # ...and falls back to xla for unaligned or int8 trees.
    scale2, mlp2 = _mlp_tree(2, d=64, f=96)
    x2 = jax.random.normal(jax.random.PRNGKey(3), (2, 64), jnp.float32)
    DM._LAST_DECODE_MLP_IMPL = None
    DM.decode_mlp(x2, scale2, mlp2, 1e-5)
    assert DM._LAST_DECODE_MLP_IMPL == "xla"
    from tpu_dra.workloads.quantize import quantize_params

    qmlp = quantize_params(mlp)
    DM._LAST_DECODE_MLP_IMPL = None
    out_q = DM.decode_mlp(x, scale, qmlp, 1e-5)
    assert DM._LAST_DECODE_MLP_IMPL == "xla"
    assert out_q.shape == x.shape
    with pytest.raises(ValueError, match="plain 2D kernels"):
        DM.decode_mlp(x, scale, qmlp, 1e-5, impl="pallas")


def test_decode_step_dispatches_fused_mlp():
    """greedy_generate's s=1 steps must route the norm+MLP chain through
    ops/decode_mlp.py (a silent fall-through to the inline chain would
    void the fusion-inventory claim)."""
    import dataclasses

    from tpu_dra.workloads.generate import greedy_generate
    from tpu_dra.workloads.ops import decode_mlp as DM

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
    )
    params = Llama(cfg).init_params(jax.random.PRNGKey(7), batch=2, seq=8)
    prompt = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (2, 1))
    DM._LAST_DECODE_MLP_IMPL = None
    greedy_generate(cfg, params, prompt, 4)
    assert DM._LAST_DECODE_MLP_IMPL in ("xla", "pallas")


def test_generate_weight_quant_knob_matches_external_quantization():
    """weight_quant="int8" on greedy_generate == quantizing the tree
    yourself and passing it in — the knob is sugar, not a third path."""
    import dataclasses

    from tpu_dra.workloads.generate import greedy_generate
    from tpu_dra.workloads.quantize import quantize_params

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
    )
    params = Llama(cfg).init_params(jax.random.PRNGKey(7), batch=2, seq=8)
    prompt = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (2, 1))
    via_knob = greedy_generate(cfg, params, prompt, 6, weight_quant="int8")
    external = greedy_generate(cfg, quantize_params(params), prompt, 6)
    assert np.array_equal(np.asarray(via_knob), np.asarray(external))
    with pytest.raises(ValueError, match="unknown weight_quant"):
        greedy_generate(cfg, params, prompt, 2, weight_quant="fp4")


def test_step_breakdown_schema_and_consistency():
    """The decode_step_breakdown contract bench.py records: every
    component key present, positive, fractions normalized by step_ms."""
    import dataclasses

    from tpu_dra.workloads.decodebench import measure_step_breakdown

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
    )
    params = Llama(cfg).init_params(jax.random.PRNGKey(7), batch=2, seq=8)
    bd = measure_step_breakdown(cfg, params, batch=2, ctx_len=20, reps=2)
    for key in (
        "step_ms", "sampled_step_ms", "sampling_ms", "attention_ms",
        "qkv_ms", "attn_out_ms", "mlp_ms", "embed_norm_ms", "logits_ms",
        "residual_ms", "sampled_overhead_ms",
    ):
        assert key in bd, key
        if key.endswith("_ms") and key not in (
            "residual_ms", "sampled_overhead_ms"
        ):
            assert bd[key] > 0, (key, bd[key])
    assert bd["ctx_len"] == 20 and bd["batch"] == 2
    assert abs(
        bd["attention_frac"] - bd["attention_ms"] / bd["step_ms"]
    ) < 0.01


# --- decode mesh (ISSUE 8) ---------------------------------------------------


def test_decode_mesh_shape_ladder_and_clamp():
    from tpu_dra.workloads.parallel import mesh as meshlib

    assert meshlib.decode_mesh_shape(1) == (1, 1)
    assert meshlib.decode_mesh_shape(2) == (1, 2)
    assert meshlib.decode_mesh_shape(4) == (2, 2)
    assert meshlib.decode_mesh_shape(8) == (2, 4)
    # TINY_LLAMA has 2 kv heads: the model axis clamps to 2 at 8
    # devices and the remainder folds into batch.
    assert meshlib.decode_mesh_shape(8, TINY_LLAMA) == (4, 2)
    assert meshlib.decode_mesh_shape(2, TINY_LLAMA) == (1, 2)
    assert meshlib.decode_mesh_shape(1, TINY_LLAMA) == (1, 1)


def test_decode_param_spec_rules():
    from tpu_dra.workloads.parallel import mesh as meshlib

    assert meshlib.decode_param_spec("layer_0/attention/wq/kernel") == P(
        None, "model"
    )
    assert meshlib.decode_param_spec("layer_0/mlp/w_gate/kernel") == P(
        None, "model"
    )
    assert meshlib.decode_param_spec("lm_head/kernel") == P(None, "model")
    # int8 weight-only: kernel_q takes the kernel's spec, its scale
    # replicates.
    assert meshlib.decode_param_spec(
        "layer_0/mlp/w_up/kernel_q"
    ) == P(None, "model")
    assert meshlib.decode_param_spec("layer_0/mlp/w_up/scale") == P()
    # Contraction-splitting layouts stay replicated (the exactness
    # contract): wo, w_down, embed, norms.
    assert meshlib.decode_param_spec("layer_0/attention/wo/kernel") == P()
    assert meshlib.decode_param_spec("layer_0/mlp/w_down/kernel") == P()
    assert meshlib.decode_param_spec("embed/embedding") == P()
    assert meshlib.decode_param_spec("final_norm/scale") == P()


def test_sharded_greedy_decode_token_identical():
    """The shardbench contract as a tier-1 pin: greedy_generate over
    decode-sharded params on the (1, 2) mesh == the unsharded run,
    token for token."""
    import dataclasses

    from tpu_dra.workloads.generate import greedy_generate
    from tpu_dra.workloads.parallel import mesh as meshlib

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
    )
    params = Llama(cfg).init_params(jax.random.PRNGKey(7), batch=2, seq=8)
    prompt = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (2, 1))
    fn = jax.jit(lambda p, t: greedy_generate(cfg, p, t, max_new_tokens=8))
    base = np.asarray(fn(params, prompt))
    mesh = meshlib.build_decode_mesh(cfg, jax.devices()[:2])
    assert dict(mesh.shape) == {"batch": 1, "model": 2}
    sharded = np.asarray(fn(meshlib.shard_decode_params(mesh, params), prompt))
    assert np.array_equal(base, sharded)


def test_decode_mesh_clamp_steps_through_odd_ladders():
    """A non-power-of-2 ladder value must not collapse to a batch-only
    mesh when a smaller model axis fits: 12 devices with 8 kv heads
    lands on (3, 4), not (12, 1)."""
    import dataclasses

    from tpu_dra.workloads.parallel import mesh as meshlib

    cfg = dataclasses.replace(
        TINY_LLAMA, n_kv_heads=8, n_heads=8, ffn_dim=128, vocab_size=256
    )
    assert meshlib.decode_mesh_shape(12, cfg) == (3, 4)
    assert meshlib.decode_mesh_shape(6, cfg) == (3, 2)


def test_sharded_safe_config_forces_xla_on_multi_device_mesh():
    """pallas custom calls have no SPMD partitioning rule: under a
    multi-device mesh every pallas-capable decode op must take its XLA
    path; a (1, 1) mesh keeps the config untouched."""
    from tpu_dra.workloads.parallel import mesh as meshlib

    mesh1 = meshlib.build_decode_mesh(TINY_LLAMA, jax.devices()[:1])
    assert meshlib.sharded_safe_config(TINY_LLAMA, mesh1) is TINY_LLAMA
    mesh2 = meshlib.build_decode_mesh(TINY_LLAMA, jax.devices()[:2])
    safe = meshlib.sharded_safe_config(TINY_LLAMA, mesh2)
    assert safe.decode_impl == "xla"
    assert safe.decode_mlp_impl == "xla"
    assert safe.paged_decode_impl == "xla"


def test_decode_mlp_block_picker_is_lane_aligned():
    """The ffn block width must be a multiple of 128 lanes AND divide
    ffn — a plain largest-divisor search returns 344 for LLaMA-7B's
    ffn 11008, which mosaic rejects; the right answer under a 512
    target is 256. No viable width -> None (dispatch keeps xla)."""
    from tpu_dra.workloads.ops.decode_mlp import (
        _mlp_pallas_ok,
        _pick_block_f,
    )

    assert _pick_block_f(11008, 4096, 2, 512) == 256
    assert _pick_block_f(8192, 2048, 2, 512) == 512
    assert _pick_block_f(512, 256, 4, 128) == 128
    # Budget cap can exclude every aligned width.
    assert _pick_block_f(11008, 4096, 2, 512) is not None
    assert _pick_block_f(128, 10_000_000, 4, 512) is None
    blocked = {
        "w_gate": {"kernel": jnp.zeros((128, 11008), jnp.float32)},
        "w_up": {"kernel": jnp.zeros((128, 11008), jnp.float32)},
        "w_down": {"kernel": jnp.zeros((11008, 128), jnp.float32)},
    }
    from tpu_dra.workloads.ops import attention as A

    orig = A._INTERPRET
    A._INTERPRET = True
    try:
        x = jnp.zeros((2, 128), jnp.float32)
        assert _mlp_pallas_ok(x, blocked, 512)  # 256 fits
    finally:
        A._INTERPRET = orig
