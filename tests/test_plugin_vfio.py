"""vfio-pci passthrough tests against a fabricated sysfs tree
(vfio-device.go:176-298 analog behavior)."""

import os

import pytest

from tpu_dra.infra import featuregates as fg
from tpu_dra.plugin.cdi import CDIHandler
from tpu_dra.plugin.checkpoint import CheckpointManager
from tpu_dra.plugin.device_state import DeviceState
from tpu_dra.plugin.vfio import VfioError, VfioPciManager
from tpu_dra.tpulib.stub import StubTpuLib

from tests.helpers import make_claim


def fabricate_vfio_sysfs(root, addresses, host_driver="google-tpu"):
    """sysfs with driver bind/unbind plumbing good enough for rebind flow."""
    sys = root / "sys"
    devs = sys / "bus" / "pci" / "devices"
    drivers = sys / "bus" / "pci" / "drivers"
    for drv in (host_driver, "vfio-pci"):
        (drivers / drv).mkdir(parents=True, exist_ok=True)

    class FakeBus:
        """drivers_probe that honors driver_override like the kernel."""

    for i, addr in enumerate(addresses):
        d = devs / addr
        d.mkdir(parents=True)
        (d / "driver_override").write_text("")
        grp = sys / "kernel" / "iommu_groups" / str(40 + i)
        grp.mkdir(parents=True)
        os.symlink(grp, d / "iommu_group")
        os.symlink(drivers / host_driver, d / "driver")
    return str(sys)


class KernelishVfioManager(VfioPciManager):
    """VfioPciManager with a write() that emulates the kernel's response to
    unbind/drivers_probe writes on the fabricated tree."""

    def _write(self, path, value):
        if path.endswith("/driver/unbind"):
            dev = os.path.join(self.sysfs_root, "bus", "pci", "devices", value)
            os.remove(os.path.join(dev, "driver"))
            return
        if path.endswith("driver_override"):
            with open(path, "w") as f:
                f.write(value)
            return
        if path.endswith("drivers_probe"):
            dev = os.path.join(self.sysfs_root, "bus", "pci", "devices", value)
            with open(os.path.join(dev, "driver_override")) as f:
                target = f.read().strip() or "google-tpu"
            link = os.path.join(dev, "driver")
            if os.path.islink(link):
                os.remove(link)
            os.symlink(
                os.path.join(self.sysfs_root, "bus", "pci", "drivers", target), link
            )
            return
        raise AssertionError(f"unexpected sysfs write: {path}")


@pytest.fixture
def vfio_env(tmp_path):
    g = fg.FeatureGates()
    g.set("PassthroughSupport", True)
    fg.reset_for_tests(g)
    lib = StubTpuLib(
        config={"generation": "v5e", "hostname": "node-0"},
        state_dir=str(tmp_path / "tpustate"),
    )
    addresses = [c.pci_bus_id for c in lib.chips()]
    sysfs = fabricate_vfio_sysfs(tmp_path, addresses)
    # drivers_probe file must exist for the manager to choose that path
    open(os.path.join(sysfs, "bus", "pci", "drivers_probe"), "w").close()
    mgr = KernelishVfioManager(sysfs_root=sysfs)
    state = DeviceState(
        tpulib=lib,
        cdi=CDIHandler(cdi_root=str(tmp_path / "cdi")),
        checkpoints=CheckpointManager(str(tmp_path / "ckpt")),
        vfio_manager=mgr,
        node_name="node-0",
    )
    return state, mgr


def test_passthrough_devices_advertised(vfio_env):
    state, _ = vfio_env
    assert "tpu-0-passthrough" in state.allocatable
    assert "tpu-0" in state.allocatable


def test_vfio_prepare_rebinds_and_removes_siblings(vfio_env):
    state, mgr = vfio_env
    claim = make_claim(["tpu-0-passthrough"])
    devices = state.prepare(claim)
    assert devices[0].device_name == "tpu-0-passthrough"
    chip = state.tpulib.chips()[0]
    assert mgr.current_driver(chip.pci_bus_id) == "vfio-pci"
    # The chip's sibling full-chip device left the inventory.
    assert "tpu-0" not in state.allocatable
    assert "tpu-1" in state.allocatable
    # CDI edits expose /dev/vfio nodes.
    spec = state.cdi.read_claim_spec(claim["metadata"]["uid"])
    nodes = [n["path"] for n in spec["devices"][0]["containerEdits"]["deviceNodes"]]
    assert "/dev/vfio/vfio" in nodes
    assert any(n.startswith("/dev/vfio/4") for n in nodes)

    # Unprepare restores the host driver and re-advertises siblings.
    state.unprepare(claim["metadata"]["uid"])
    assert mgr.current_driver(chip.pci_bus_id) == "google-tpu"
    assert "tpu-0" in state.allocatable


def test_vfio_rebind_is_idempotent(vfio_env):
    state, mgr = vfio_env
    chip = state.tpulib.chips()[1]
    mgr.configure(chip)
    mgr.configure(chip)  # second call noop
    assert mgr.current_driver(chip.pci_bus_id) == "vfio-pci"
    mgr.unconfigure(chip)
    mgr.unconfigure(chip)  # noop
    assert mgr.current_driver(chip.pci_bus_id) == "google-tpu"


def test_vfio_requires_iommu_group(vfio_env, tmp_path):
    state, mgr = vfio_env
    chip = state.tpulib.chips()[2]
    os.remove(
        os.path.join(mgr.sysfs_root, "bus", "pci", "devices", chip.pci_bus_id,
                     "iommu_group")
    )
    with pytest.raises(VfioError, match="IOMMU"):
        mgr.configure(chip)
