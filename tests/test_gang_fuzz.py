"""Gang-scheduling interleaving fuzzer, as a test (ISSUE 19).

``hack/fuzz_gang.py`` is the real artifact (``python hack/fuzz_gang.py``
runs the 200-seed acceptance bar); this suite pins its contract so a
refactor cannot quietly hollow it out: a fast batch proves every
``gang.*`` crash point is reachable and every outcome class occurs,
determinism makes any violation a one-command repro, and the full
200-seed run rides the slow lane next to the chaos soak.
"""

import sys
from pathlib import Path

import pytest

from tpu_dra.infra import crashpoint

REPO = Path(__file__).resolve().parent.parent
if str(REPO / "hack") not in sys.path:
    sys.path.insert(0, str(REPO / "hack"))

import fuzz_gang  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_crashpoints():
    crashpoint.reset_for_tests()
    yield
    crashpoint.reset_for_tests()


def test_gang_points_tuple_matches_registry():
    """The fuzzer's coverage bar is pinned to the registry: a newly
    registered gang.* crash point that the fuzzer does not know about
    fails HERE, not silently in main()'s fired-count check."""
    registered = sorted(
        p for p in crashpoint.CRASH_POINTS if p.startswith("gang.")
    )
    assert sorted(fuzz_gang.GANG_POINTS) == registered


def test_fuzz_batch_covers_every_crash_point_and_outcome():
    """A 40-seed batch (seconds, not minutes) already reaches every
    gang crash window and every outcome class, with zero invariant
    violations — the tier-1 guarantee that the protocol's dangerous
    interleavings stay covered on every run."""
    agg = {}
    for seed in range(40):
        stats = fuzz_gang.run_seed(seed, steps=14)
        for k, v in stats.items():
            agg[k] = agg.get(k, 0) + v
    for point in fuzz_gang.GANG_POINTS:
        assert crashpoint.fire_count(point) > 0, (
            f"{point} never fired across 40 seeds — the fuzzer lost "
            f"its reach into the commit windows"
        )
    for key in ("gangs_committed", "gangs_unschedulable",
                "crashes_fired", "teardowns", "recoveries",
                "singles_allocated", "deletes", "nodes_lost"):
        assert agg.get(key), f"outcome class {key} never occurred"


def test_fuzz_seed_is_deterministic():
    """Same seed, same history, same stats — the property that turns a
    red run's seed number into a repro command."""
    a = fuzz_gang.run_seed(7, steps=14)
    crashpoint.reset_for_tests()
    b = fuzz_gang.run_seed(7, steps=14)
    assert a == b


def test_fuzz_main_single_seed_repro_mode():
    """--seeds 1 --seed0 N (the repro invocation printed on failure)
    runs clean and skips the whole-run coverage bar."""
    assert fuzz_gang.main(["--seeds", "1", "--seed0", "3"]) == 0


@pytest.mark.slow
def test_fuzz_full_acceptance_bar():
    """The ISSUE-19 acceptance run: >= 200 seeded interleavings, every
    gang crash point fired, zero violations (main() exits non-zero on
    any gap)."""
    assert fuzz_gang.main(["--seeds", "200"]) == 0
