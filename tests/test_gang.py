"""Gang two-phase commit protocol unit tests (ISSUE 19).

The interleaving fuzzer (test_gang_fuzz) and the crash matrix prove
convergence under randomized and per-window death; this suite pins the
protocol's *contract* case by case so a regression names the exact rule
it broke: the commit phase table, rollback-vs-roll-forward recovery,
journaled teardown, ``allocate_gang``'s exact in-memory rollback, the
heterogeneous corridor packing order, WAL parsing edge cases, and the
kubelet plugin's refusal to prepare a claim mid-protocol.
"""

import json

import pytest

from tpu_dra.infra.crashpoint import SimulatedCrash, arm
from tpu_dra.infra.metrics import Metrics
from tpu_dra.k8sclient import (
    DEVICE_CLASSES,
    RESOURCE_CLAIMS,
    RESOURCE_SLICES,
    FakeCluster,
    ResourceClient,
)
from tpu_dra.scheduler import fleet
from tpu_dra.scheduler.allocator import Allocator, Unschedulable
from tpu_dra.scheduler.gang import (
    GANG_ANNOTATION,
    PHASE_COMMITTED,
    PHASE_ROLLING_BACK,
    GangCommitError,
    commit_gang,
    gang_owned,
    gang_state,
    recover_gangs,
    teardown_gang,
    wal_age,
    wal_stale,
)

NS = "default"


def make_cluster(nodes=3, gens=None):
    """classes + ``nodes`` published slices; gens[i] picks each node's
    generation (default all v5e)."""
    cluster = FakeCluster()
    classes = ResourceClient(cluster, DEVICE_CLASSES)
    for c in fleet.CLASSES:
        classes.create(json.loads(json.dumps(c)))
    slices = ResourceClient(cluster, RESOURCE_SLICES)
    for i in range(nodes):
        gen = (gens or {}).get(i, "v5e")
        slices.create(fleet.make_node_slice(i, gen=gen))
    return cluster


def clients(cluster):
    return (
        ResourceClient(cluster, RESOURCE_CLAIMS),
        ResourceClient(cluster, RESOURCE_SLICES),
    )


def make_gang(cluster, size=2, shape="2x2x1", gen=None, name="g0", i0=0):
    claims, _ = clients(cluster)
    members = fleet.make_gang_claims(
        name, i0, size, shape, gen=gen, namespace=NS
    )
    return [claims.create(c) for c in members]


def snapshot_allocator(cluster):
    claims, slices = clients(cluster)
    return Allocator(
        fleet.CLASSES, allocated_claims=claims.list(),
        slices=slices.list(),
    )


def allocated_members(cluster, gang="g0"):
    claims, _ = clients(cluster)
    return [
        c for c in claims.list()
        if (c["metadata"].get("labels") or {}).get(
            "gang.tpu.google.com/name"
        ) == gang and (c.get("status") or {}).get("allocation")
    ]


def wal_members(cluster):
    claims, _ = clients(cluster)
    return [c for c in claims.list() if gang_state(c) is not None]


# --- commit ------------------------------------------------------------------


def test_commit_all_members_distinct_pools_no_wal_residue():
    cluster = make_cluster(nodes=3)
    claims, _ = clients(cluster)
    members = make_gang(cluster, size=2)
    alloc = snapshot_allocator(cluster)
    results = alloc.allocate_gang(members)
    metrics = Metrics()
    stored = commit_gang(
        claims, "g0", members, results, identity="test", metrics=metrics
    )
    assert len(stored) == 2
    pools = set()
    for c in stored:
        res = c["status"]["allocation"]["devices"]["results"]
        pools.update(r["pool"] for r in res)
        assert gang_state(c) is None
    # One full 2x2x1 per member forces one node each: distinct pools.
    assert len(pools) == 2
    assert wal_members(cluster) == []
    assert metrics.get_counter(
        "gang_allocations_total", labels={"result": "committed"}
    ) == 1
    assert metrics.get_counter("gang_partial_rollbacks_total") == 0


def test_commit_member_vanishing_midway_rolls_back_and_raises():
    """A member deleted between solve and commit: commit_gang rolls the
    already-committed members back on the apiserver before raising —
    never a partial gang, and the rollback is counted."""
    cluster = make_cluster(nodes=3)
    claims, _ = clients(cluster)
    members = make_gang(cluster, size=2)
    alloc = snapshot_allocator(cluster)
    results = alloc.allocate_gang(members)
    claims.delete(members[1]["metadata"]["name"], NS)
    metrics = Metrics()
    with pytest.raises(GangCommitError):
        commit_gang(
            claims, "g0", members, results,
            identity="test", metrics=metrics,
        )
    assert allocated_members(cluster) == []
    assert wal_members(cluster) == []
    assert metrics.get_counter(
        "gang_allocations_total", labels={"result": "rolled_back"}
    ) == 1


# --- the crash phase table ---------------------------------------------------

# point -> (allocations expected after recovery, rollback expected):
# everything before the finalize fence rolls BACK (all-or-nothing
# forbids keeping the half-committed members); a crash after every
# member committed rolls FORWARD (the gang is whole — recovery only
# drops the remaining WAL annotations).
COMMIT_PHASES = [
    ("gang.commit.between_intents", 0, True),
    ("gang.commit.after_intent_persisted", 0, True),
    ("gang.commit.between_members", 0, True),
    ("gang.commit.before_finalize", 2, False),
]


@pytest.mark.parametrize("point,allocs_after,rolled_back", COMMIT_PHASES)
def test_commit_crash_recovery_phase_table(point, allocs_after, rolled_back):
    cluster = make_cluster(nodes=3)
    claims, _ = clients(cluster)
    members = make_gang(cluster, size=2)
    alloc = snapshot_allocator(cluster)
    results = alloc.allocate_gang(members)
    with arm(point) as a:
        with pytest.raises(SimulatedCrash):
            commit_gang(claims, "g0", members, results, identity="test")
    assert a.fired
    assert wal_members(cluster), "crash left no WAL to recover from"

    metrics = Metrics()
    assert recover_gangs(claims, identity="restart", metrics=metrics) == 1
    assert len(allocated_members(cluster)) == allocs_after
    assert wal_members(cluster) == []
    expected_rollbacks = 1 if rolled_back and point in (
        "gang.commit.between_members",
    ) else 0
    # partial_rollbacks counts only recoveries that CLEARED an
    # allocation; intent-only crashes had nothing to clear.
    assert metrics.get_counter(
        "gang_partial_rollbacks_total"
    ) == expected_rollbacks

    # The retry after a rollback converges; after a roll-forward the
    # gang is already whole and a fresh solve sees no pending members.
    if rolled_back:
        alloc2 = snapshot_allocator(cluster)
        fresh = [claims.try_get(c["metadata"]["name"], NS)
                 for c in members]
        results2 = alloc2.allocate_gang(fresh)
        commit_gang(claims, "g0", fresh, results2, identity="retry")
    assert len(allocated_members(cluster)) == 2
    assert wal_members(cluster) == []


# --- teardown ----------------------------------------------------------------


def test_teardown_clears_all_members_and_is_idempotent():
    cluster = make_cluster(nodes=3)
    claims, _ = clients(cluster)
    members = make_gang(cluster, size=2)
    results = snapshot_allocator(cluster).allocate_gang(members)
    commit_gang(claims, "g0", members, results, identity="test")
    live = [claims.try_get(c["metadata"]["name"], NS) for c in members]
    assert teardown_gang(
        claims, live, reason="node loss", identity="test"
    ) == 2
    assert allocated_members(cluster) == []
    assert wal_members(cluster) == []
    live = [claims.try_get(c["metadata"]["name"], NS) for c in members]
    assert teardown_gang(
        claims, live, reason="again", identity="test"
    ) == 0


def test_teardown_crash_after_intent_completes_on_recovery():
    cluster = make_cluster(nodes=3)
    claims, _ = clients(cluster)
    members = make_gang(cluster, size=2)
    results = snapshot_allocator(cluster).allocate_gang(members)
    commit_gang(claims, "g0", members, results, identity="test")
    live = [claims.try_get(c["metadata"]["name"], NS) for c in members]
    with arm("gang.teardown.after_intent") as a:
        with pytest.raises(SimulatedCrash):
            teardown_gang(claims, live, reason="loss", identity="test")
    assert a.fired
    # The rolling_back intent is durable; members still hold chips.
    assert len(wal_members(cluster)) == 2
    assert recover_gangs(claims, identity="restart") == 1
    assert allocated_members(cluster) == []
    assert wal_members(cluster) == []


def test_recovery_rolling_back_anywhere_beats_committed_everywhere():
    """The precedence rule: one surviving rolling_back intent forces
    teardown even when every member looks committed+allocated — the
    teardown writer knew something (node loss) the allocations don't
    show."""
    cluster = make_cluster(nodes=3)
    claims, _ = clients(cluster)
    members = make_gang(cluster, size=2)
    results = snapshot_allocator(cluster).allocate_gang(members)
    commit_gang(claims, "g0", members, results, identity="test")
    keys = [f"{NS}/{c['metadata']['name']}" for c in members]
    first = claims.try_get(members[0]["metadata"]["name"], NS)
    first["metadata"].setdefault("annotations", {})[GANG_ANNOTATION] = (
        json.dumps({
            "phase": PHASE_ROLLING_BACK, "gang": "g0",
            "members": keys, "t": 0,
        })
    )
    claims.update(first)
    second = claims.try_get(members[1]["metadata"]["name"], NS)
    second["metadata"].setdefault("annotations", {})[GANG_ANNOTATION] = (
        json.dumps({
            "phase": PHASE_COMMITTED, "gang": "g0",
            "members": keys, "t": 0,
        })
    )
    claims.update(second)
    assert recover_gangs(claims, identity="restart") == 1
    assert allocated_members(cluster) == []
    assert wal_members(cluster) == []


def test_recovery_resolves_corrupt_wal_as_teardown():
    """A garbled WAL annotation must read as rolling_back (the
    conservative outcome) and resolve to a clean teardown — never
    crash recovery, never read as 'no protocol in flight'."""
    cluster = make_cluster(nodes=3)
    claims, _ = clients(cluster)
    members = make_gang(cluster, size=2)
    results = snapshot_allocator(cluster).allocate_gang(members)
    commit_gang(claims, "g0", members, results, identity="test")
    c = claims.try_get(members[0]["metadata"]["name"], NS)
    c["metadata"].setdefault("annotations", {})[GANG_ANNOTATION] = (
        "{not json"
    )
    claims.update(c)
    st = gang_state(claims.try_get(members[0]["metadata"]["name"], NS))
    assert st["phase"] == PHASE_ROLLING_BACK and st["corrupt"]
    assert recover_gangs(claims, identity="restart") == 1
    assert allocated_members(cluster) == []
    assert wal_members(cluster) == []


# --- allocate_gang in-memory exactness ---------------------------------------


def test_allocate_gang_rollback_leaves_ledger_exactly_as_found():
    """An infeasible late member rolls back every prior member's takes:
    in_use is byte-identical and a full-fleet singleton replay still
    succeeds (the ledger holds no phantom consumption)."""
    cluster = make_cluster(nodes=2)
    members = make_gang(cluster, size=3)  # 3 full nodes wanted, 2 exist
    alloc = snapshot_allocator(cluster)
    before = set(alloc.in_use)
    with pytest.raises(Unschedulable) as ei:
        alloc.allocate_gang(members)
    assert "gang member" in str(ei.value)
    assert set(alloc.in_use) == before
    # Both full-node placements must still be takeable on the SAME
    # allocator instance: any leaked counter would fail one of them.
    singles = [
        fleet.make_claim(100 + i, "2x2x1", namespace=NS)
        for i in range(2)
    ]
    for s in singles:
        alloc.allocate(s)


def test_gang_counter_exclusivity_within_one_solve():
    """Two members can never land on overlapping placements: on a
    one-node fleet a two-member full-node gang must be infeasible (the
    first member's takes are visible to the second's solve)."""
    cluster = make_cluster(nodes=1)
    members = make_gang(cluster, size=2)
    with pytest.raises(Unschedulable):
        snapshot_allocator(cluster).allocate_gang(members)


# --- heterogeneous corridor order --------------------------------------------


def test_singles_spill_to_small_generation_pools_first():
    """Corridor packing order: an untouched v5p node (the only pool
    advertising 4x2x1 corridors) is visited AFTER untouched v5e pools,
    so generation-agnostic singles never splinter it — regardless of
    catalog (name) order, where node-0 comes first."""
    cluster = make_cluster(nodes=3, gens={0: "v5p"})
    claims, _ = clients(cluster)
    alloc = snapshot_allocator(cluster)
    for i in range(4):  # 2 v5e nodes hold 4x 2x1x1 exactly
        res = alloc.allocate(
            fleet.make_claim(200 + i, "2x1x1", namespace=NS)
        )
        pools = {
            r["pool"] for r in res.allocation["devices"]["results"]
        }
        assert pools.issubset(
            {fleet.node_name(1), fleet.node_name(2)}
        ), f"single #{i} touched the v5p corridor node: {pools}"
    # Only once the small pools are exhausted does v5p admit a single.
    res = alloc.allocate(fleet.make_claim(299, "2x1x1", namespace=NS))
    assert {
        r["pool"] for r in res.allocation["devices"]["results"]
    } == {fleet.node_name(0)}


def test_gang_of_corridor_shapes_survives_single_pressure():
    """End to end: singles arrive first under the packed order, then a
    2-member 4x2x1 v5p gang still seats — the corridor sort left both
    v5p nodes whole."""
    cluster = make_cluster(nodes=4, gens={0: "v5p", 2: "v5p"})
    claims, _ = clients(cluster)
    alloc = snapshot_allocator(cluster)
    for i in range(4):
        alloc.allocate(fleet.make_claim(300 + i, "2x1x1", namespace=NS))
    members = make_gang(
        cluster, size=2, shape="4x2x1", gen="v5p", name="cg", i0=400
    )
    results = alloc.allocate_gang(members)
    pools = set()
    for res in results:
        pools.update(
            r["pool"] for r in res.allocation["devices"]["results"]
        )
    assert pools == {fleet.node_name(0), fleet.node_name(2)}


# --- WAL parsing edges -------------------------------------------------------


def test_wal_age_and_staleness_edges():
    c = {"metadata": {"name": "x", "namespace": NS, "annotations": {
        GANG_ANNOTATION: json.dumps({"phase": "committing", "t": 100.0})
    }}}
    assert wal_age(c, now=130.0) == 30.0
    assert wal_stale(c, now=130.0, stale_seconds=30.0)
    assert not wal_stale(c, now=120.0, stale_seconds=30.0)
    assert gang_owned(c, now=120.0)
    assert not gang_owned(c, now=200.0)
    # A stampless WAL reads as infinitely old: never protocol-owned,
    # always eligible for recovery.
    c["metadata"]["annotations"][GANG_ANNOTATION] = json.dumps(
        {"phase": "committing"}
    )
    assert wal_age(c, now=0.0) == float("inf")
    assert wal_stale(c) and not gang_owned(c)
    del c["metadata"]["annotations"][GANG_ANNOTATION]
    assert wal_age(c) is None and not gang_owned(c)


# --- kubelet fence -----------------------------------------------------------


def test_plugin_refuses_to_prepare_mid_protocol_claim(tmp_path):
    """The plugin-side fence: a claim still carrying the gang WAL may
    be rolled back any moment — prepare must refuse (retryably), and
    succeed once the annotation is gone."""
    from tests.test_plugin_device_state import make_state
    from tpu_dra.plugin.device_state import PrepareError
    from tests.helpers import make_claim as make_plugin_claim

    state, _ = make_state(tmp_path)
    claim = make_plugin_claim()
    claim["metadata"]["annotations"] = {
        GANG_ANNOTATION: json.dumps(
            {"phase": "committed", "gang": "g0", "t": 0}
        )
    }
    with pytest.raises(PrepareError, match="gang"):
        state.prepare(claim)
    del claim["metadata"]["annotations"][GANG_ANNOTATION]
    devs = state.prepare(claim)
    assert [d.device_name for d in devs] == ["tpu-0"]
