"""Operator doctor CLI: cross-checks of the plugin's own stores."""

import json
import os

from tpu_dra.plugin.cdi import CDIHandler
from tpu_dra.plugin.checkpoint import CheckpointManager
from tpu_dra.plugin.device_state import DeviceState
from tpu_dra.plugin.multiplexd import MultiplexDaemon
from tpu_dra.tools.doctor import collect, main, render
from tpu_dra.tpulib.stub import StubTpuLib


def make_state(tmp_path):
    lib = StubTpuLib(
        config={"generation": "v5e", "hostname": "node-0"},
        state_dir=str(tmp_path / "tpu"),
    )
    return DeviceState(
        tpulib=lib,
        cdi=CDIHandler(cdi_root=str(tmp_path / "cdi")),
        checkpoints=CheckpointManager(str(tmp_path / "data")),
        node_name="node-0",
    ), lib


def claim(uid, device="tpu-0"):
    return {
        "metadata": {"name": f"c-{uid[:4]}", "namespace": "ns", "uid": uid},
        "status": {"allocation": {"devices": {"results": [{
            "request": "r", "driver": "tpu.google.com",
            "pool": "node-0", "device": device,
        }], "config": []}}},
    }


def run_collect(tmp_path, lib):
    return collect(
        str(tmp_path / "data"), str(tmp_path / "cdi"),
        str(tmp_path / "mux"), tpulib=lib,
    )


def test_healthy_node_reports_clean(tmp_path):
    state, lib = make_state(tmp_path)
    state.prepare(claim("aaaa-1111"))
    report = run_collect(tmp_path, lib)
    assert report["warnings"] == []
    assert "aaaa-1111" in report["checkpoint"]["claims"]
    assert report["checkpoint"]["claims"]["aaaa-1111"]["state"] == (
        "PrepareCompleted"
    )
    assert report["cdi"]["claim_specs"] == ["aaaa-1111"]
    assert any(c["healthy"] for c in report["tpulib"]["chips"])
    out = render(report)
    assert "healthy: no warnings" in out


def test_crashed_prepare_and_orphan_spec_warn(tmp_path):
    state, lib = make_state(tmp_path)
    state.prepare(claim("aaaa-1111"))
    # Orphan CDI spec: an unprepare that died after checkpoint removal.
    from tpu_dra.plugin.prepared import PreparedDevices

    state.cdi.create_claim_spec_file("dead-beef", PreparedDevices())
    # Crashed prepare: WAL entry stuck in PrepareStarted.
    from tpu_dra.plugin.checkpoint import (
        CLAIM_STATE_PREPARE_STARTED,
        PreparedClaim,
    )

    def mutate(cp):
        cp.prepared_claims["bbbb-2222"] = PreparedClaim(
            checkpoint_state=CLAIM_STATE_PREPARE_STARTED,
            name="stuck", namespace="ns",
        )

    state.checkpoints.update(mutate)
    report = run_collect(tmp_path, lib)
    warns = "\n".join(report["warnings"])
    assert "PrepareStarted" in warns and "bbbb-2222" in warns
    assert "dead-beef" in warns and "no checkpoint entry" in warns


def test_live_arbiter_probed_and_exit_codes(tmp_path, monkeypatch, capsys):
    state, lib = make_state(tmp_path)
    state.prepare(claim("aaaa-1111"))
    mux = tmp_path / "mux" / "aaaa-1111"
    daemon = MultiplexDaemon(str(mux), ["chip-a"]).start()
    try:
        monkeypatch.setenv("TPU_DRA_BACKEND", "stub")
        import yaml

        (tmp_path / "stub.yaml").write_text(
            yaml.safe_dump({"generation": "v5e", "hostname": "node-0",
                            "state_dir": str(tmp_path / "tpu")})
        )
        monkeypatch.setenv(
            "TPU_DRA_STUB_CONFIG", str(tmp_path / "stub.yaml")
        )
        rc = main([
            "--plugin-data-dir", str(tmp_path / "data"),
            "--cdi-root", str(tmp_path / "cdi"),
            "--multiplex-socket-root", str(tmp_path / "mux"),
            "--json",
        ])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["arbiters"]["aaaa-1111"]["waiting"] == 0
        assert out["arbiters"]["aaaa-1111"]["revocations"] == 0
    finally:
        daemon.stop()


def test_unhealthy_chip_warns(tmp_path):
    from tpu_dra.tpulib.types import ChipHealthEvent

    state, lib = make_state(tmp_path)
    lib.inject_health_event(ChipHealthEvent(
        chip_uuid=lib.chips()[0].uuid, healthy=False, reason="doctor-test",
    ))
    report = run_collect(tmp_path, lib)
    assert any("UNHEALTHY" in w for w in report["warnings"])
    assert "WARN" in render(report)


def test_orphan_spec_with_empty_checkpoint_still_warns(tmp_path):
    """The crashed-unprepare scenario: checkpoint exists but is empty,
    a claim spec lingers — that exact combination must WARN."""
    state, lib = make_state(tmp_path)
    c = claim("aaaa-1111")
    state.prepare(c)
    # Simulate the crash window: checkpoint entry removed, spec left.
    spec_path = state.cdi.spec_path("aaaa-1111")
    assert os.path.exists(spec_path)
    state.checkpoints.update(lambda cp: cp.prepared_claims.clear())
    report = run_collect(tmp_path, lib)
    warns = "\n".join(report["warnings"])
    assert "aaaa-1111" in warns and "no checkpoint entry" in warns


def test_missing_cdi_root_is_noted_not_created(tmp_path):
    state, lib = make_state(tmp_path)
    bogus = tmp_path / "no-such-cdi"
    report = collect(
        str(tmp_path / "data"), str(bogus), str(tmp_path / "mux"),
        tpulib=lib,
    )
    assert not bogus.exists()  # a diagnostic must not mutate the node
    assert any("does not exist" in n for n in report.get("notes", []))
