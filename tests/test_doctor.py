"""Operator doctor CLI: cross-checks of the plugin's own stores."""

import json
import os

from tpu_dra.plugin.cdi import CDIHandler
from tpu_dra.plugin.checkpoint import CheckpointManager
from tpu_dra.plugin.device_state import DeviceState
from tpu_dra.plugin.multiplexd import MultiplexDaemon
from tpu_dra.tools.doctor import collect, main, render
from tpu_dra.tpulib.stub import StubTpuLib


def make_state(tmp_path):
    lib = StubTpuLib(
        config={"generation": "v5e", "hostname": "node-0"},
        state_dir=str(tmp_path / "tpu"),
    )
    return DeviceState(
        tpulib=lib,
        cdi=CDIHandler(cdi_root=str(tmp_path / "cdi")),
        checkpoints=CheckpointManager(str(tmp_path / "data")),
        node_name="node-0",
    ), lib


def claim(uid, device="tpu-0"):
    return {
        "metadata": {"name": f"c-{uid[:4]}", "namespace": "ns", "uid": uid},
        "status": {"allocation": {"devices": {"results": [{
            "request": "r", "driver": "tpu.google.com",
            "pool": "node-0", "device": device,
        }], "config": []}}},
    }


def run_collect(tmp_path, lib):
    return collect(
        str(tmp_path / "data"), str(tmp_path / "cdi"),
        str(tmp_path / "mux"), tpulib=lib,
    )


def test_healthy_node_reports_clean(tmp_path):
    state, lib = make_state(tmp_path)
    state.prepare(claim("aaaa-1111"))
    report = run_collect(tmp_path, lib)
    assert report["warnings"] == []
    assert "aaaa-1111" in report["checkpoint"]["claims"]
    assert report["checkpoint"]["claims"]["aaaa-1111"]["state"] == (
        "PrepareCompleted"
    )
    assert report["cdi"]["claim_specs"] == ["aaaa-1111"]
    assert any(c["healthy"] for c in report["tpulib"]["chips"])
    out = render(report)
    assert "healthy: no warnings" in out


def test_crashed_prepare_and_orphan_spec_warn(tmp_path):
    state, lib = make_state(tmp_path)
    state.prepare(claim("aaaa-1111"))
    # Orphan CDI spec: an unprepare that died after checkpoint removal.
    from tpu_dra.plugin.prepared import PreparedDevices

    state.cdi.create_claim_spec_file("dead-beef", PreparedDevices())
    # Crashed prepare: WAL entry stuck in PrepareStarted.
    from tpu_dra.plugin.checkpoint import (
        CLAIM_STATE_PREPARE_STARTED,
        PreparedClaim,
    )

    def mutate(cp):
        cp.prepared_claims["bbbb-2222"] = PreparedClaim(
            checkpoint_state=CLAIM_STATE_PREPARE_STARTED,
            name="stuck", namespace="ns",
        )

    state.checkpoints.update(mutate)
    report = run_collect(tmp_path, lib)
    warns = "\n".join(report["warnings"])
    assert "PrepareStarted" in warns and "bbbb-2222" in warns
    assert "dead-beef" in warns and "no checkpoint entry" in warns


def test_live_arbiter_probed_and_exit_codes(tmp_path, monkeypatch, capsys):
    state, lib = make_state(tmp_path)
    state.prepare(claim("aaaa-1111"))
    mux = tmp_path / "mux" / "aaaa-1111"
    daemon = MultiplexDaemon(str(mux), ["chip-a"]).start()
    try:
        monkeypatch.setenv("TPU_DRA_BACKEND", "stub")
        import yaml

        (tmp_path / "stub.yaml").write_text(
            yaml.safe_dump({"generation": "v5e", "hostname": "node-0",
                            "state_dir": str(tmp_path / "tpu")})
        )
        monkeypatch.setenv(
            "TPU_DRA_STUB_CONFIG", str(tmp_path / "stub.yaml")
        )
        rc = main([
            "--plugin-data-dir", str(tmp_path / "data"),
            "--cdi-root", str(tmp_path / "cdi"),
            "--multiplex-socket-root", str(tmp_path / "mux"),
            "--json",
        ])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["arbiters"]["aaaa-1111"]["waiting"] == 0
        assert out["arbiters"]["aaaa-1111"]["revocations"] == 0
    finally:
        daemon.stop()


def test_unhealthy_chip_warns(tmp_path):
    from tpu_dra.tpulib.types import ChipHealthEvent

    state, lib = make_state(tmp_path)
    lib.inject_health_event(ChipHealthEvent(
        chip_uuid=lib.chips()[0].uuid, healthy=False, reason="doctor-test",
    ))
    report = run_collect(tmp_path, lib)
    assert any("UNHEALTHY" in w for w in report["warnings"])
    assert "WARN" in render(report)


def test_orphan_spec_with_empty_checkpoint_still_warns(tmp_path):
    """The crashed-unprepare scenario: checkpoint exists but is empty,
    a claim spec lingers — that exact combination must WARN."""
    state, lib = make_state(tmp_path)
    c = claim("aaaa-1111")
    state.prepare(c)
    # Simulate the crash window: checkpoint entry removed, spec left.
    spec_path = state.cdi.spec_path("aaaa-1111")
    assert os.path.exists(spec_path)
    state.checkpoints.update(lambda cp: cp.prepared_claims.clear())
    report = run_collect(tmp_path, lib)
    warns = "\n".join(report["warnings"])
    assert "aaaa-1111" in warns and "no checkpoint entry" in warns


def test_corrupt_checkpoint_warns_readonly_with_bak_verdict(tmp_path):
    """A CRC-failing checkpoint WARNs with the recovery verdict — and the
    doctor must NOT heal/quarantine it (read-only diagnostic)."""
    state, lib = make_state(tmp_path)
    state.prepare(claim("aaaa-1111"))
    ckpt = tmp_path / "data" / "checkpoint.json"
    raw = ckpt.read_text()
    ckpt.write_text(raw.replace("PrepareCompleted", "PrepareCorrupted"))
    mutated = ckpt.read_text()
    report = run_collect(tmp_path, lib)
    warns = "\n".join(report["warnings"])
    assert "CORRUPT" in warns
    assert "recover from it at next boot" in warns  # .bak is readable
    assert report["checkpoint"]["corrupt"]
    # Read-only: the corrupt file is untouched, nothing quarantined.
    assert ckpt.read_text() == mutated
    assert not [
        n for n in os.listdir(tmp_path / "data") if ".corrupt-" in n
    ]
    # No false orphan-spec accusations off an unreadable claim table.
    assert "no checkpoint entry" not in warns
    assert "CORRUPT" in render(report)


def test_corrupt_checkpoint_and_bak_warns_device_scan_verdict(tmp_path):
    state, lib = make_state(tmp_path)
    state.prepare(claim("aaaa-1111"))
    (tmp_path / "data" / "checkpoint.json").write_text("{torn")
    (tmp_path / "data" / "checkpoint.json.bak").write_text("")
    report = run_collect(tmp_path, lib)
    warns = "\n".join(report["warnings"])
    assert "ALSO unreadable" in warns and "device scan" in warns


def test_leftover_tmp_and_quarantine_files_warn(tmp_path):
    state, lib = make_state(tmp_path)
    state.prepare(claim("aaaa-1111"))
    (tmp_path / "data" / "checkpoint.json.tmp").write_text("{half a wri")
    (tmp_path / "data" / "checkpoint.json.corrupt-1700000000000").write_text(
        "{was corrupt}"
    )
    report = run_collect(tmp_path, lib)
    warns = "\n".join(report["warnings"])
    assert "leftover checkpoint temp file" in warns
    assert "NEVER rename it over checkpoint.json" in warns
    assert "quarantined corrupt checkpoint" in warns
    assert report["checkpoint"]["residue"]["tmp"] == [
        "checkpoint.json.tmp"
    ]
    assert report["checkpoint"]["residue"]["quarantined"] == [
        "checkpoint.json.corrupt-1700000000000"
    ]
    out = render(report)
    assert "interrupted write" in out and "(quarantined)" in out
    # Exit code 1 (probe-friendly) comes from the warnings as usual.


def test_missing_cdi_root_is_noted_not_created(tmp_path):
    state, lib = make_state(tmp_path)
    bogus = tmp_path / "no-such-cdi"
    report = collect(
        str(tmp_path / "data"), str(bogus), str(tmp_path / "mux"),
        tpulib=lib,
    )
    assert not bogus.exists()  # a diagnostic must not mutate the node
    assert any("does not exist" in n for n in report.get("notes", []))


def test_metrics_probe_surfaces_failing_informer(tmp_path):
    """The round-3 incident class, visible in doctor output: a component
    whose informer cannot reach the apiserver accumulates sync-failure
    counters on its /metrics; doctor scrapes the endpoint and WARNs."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer
    from tpu_dra.k8sclient import Informer
    from tpu_dra.k8sclient.resources import COMPUTE_DOMAINS
    from tpu_dra.k8sclient.rest import KubeClient

    metrics = Metrics()
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    # Port 1 is never listening: every initial-sync attempt fails and
    # increments informer_sync_failures_total (the counter that was
    # silent in round 3 while four daemons died).
    kc = KubeClient(server="http://127.0.0.1:1", qps=1000, burst=1000)
    kc.MAX_CONN_RETRIES = 0
    inf = Informer(kc, COMPUTE_DOMAINS, metrics=metrics)
    inf.resync_backoff = 0.02
    # Keep the reconnect cadence fast for the climb-delta window below:
    # reconnects now back off exponentially (ISSUE 5), and the capped
    # delay is what keeps the counter climbing at a steady rate.
    inf.resync_backoff_max = 0.05
    inf.start()
    try:
        import time

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if "informer_sync_failures_total" in metrics.render():
                break
            time.sleep(0.05)
        endpoint = f"127.0.0.1:{srv.port}"
        _s, lib = make_state(tmp_path)
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        assert any(
            "informer_sync_failures_total" in w for w in report["warnings"]
        ), report["warnings"]
        out = render(report)
        assert "informer_sync_failures_total" in out

        # Second sample mode: the counter is still climbing (the informer
        # keeps retrying), so the climb-delta WARN fires too.
        report2 = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint], metrics_interval=0.3,
        )
        assert any("CLIMBED" in w for w in report2["warnings"]), (
            report2["warnings"]
        )
    finally:
        inf.stop()
        srv.stop()


def test_metrics_probe_quiet_on_stable_counters(tmp_path):
    """Old nonzero counters from a survived blip: single-sample mode
    warns (operator should look), but interval mode stays quiet when
    nothing is climbing — and an unreachable endpoint warns."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.inc("informer_sync_failures_total",
                labels={"informer": "computedomains"})
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        assert any("failing to sync" in w for w in report["warnings"])
        report2 = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint], metrics_interval=0.1,
        )
        assert report2["warnings"] == [], report2["warnings"]

        report3 = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=["127.0.0.1:1"],
        )
        assert any("did not answer" in w for w in report3["warnings"])
    finally:
        srv.stop()


def test_metrics_probe_surfaces_degraded_mode(tmp_path):
    """ISSUE 5: a driver riding out apiserver weather exports
    api_degraded=1 and an open per-verb circuit gauge; doctor names the
    degraded state, the tripped verb, and what keeps serving."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.set_gauge("api_degraded", 1)
    metrics.set_gauge("api_circuit_state", 2, labels={"verb": "get"})
    metrics.set_gauge("api_circuit_state", 0, labels={"verb": "create"})
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "DEGRADED mode" in warns
        assert "circuit for 'get' is open" in warns
        assert "'create'" not in warns  # closed circuits stay quiet
        deg = report["metrics"][endpoint]["degraded"]
        assert deg["api_degraded"] is True
        assert deg["circuits"] == {"get": "open", "create": "closed"}
        out = render(report)
        assert "DEGRADED mode (apiserver circuit open)" in out
        assert "circuit[get] = open" in out
        assert "circuit[create]" not in out
    finally:
        srv.stop()


def test_metrics_probe_sees_cd_plugin_prefix(tmp_path):
    """The CD plugin's registry renders as tpu_dra_cd_* — the weather
    gauges are matched by suffix, so its degraded state is not silently
    invisible to the doctor."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics(prefix="tpu_dra_cd")
    metrics.set_gauge("api_degraded", 1)
    metrics.set_gauge("api_circuit_state", 2, labels={"verb": "list"})
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "DEGRADED mode" in warns
        assert "circuit for 'list' is open" in warns
        deg = report["metrics"][endpoint]["degraded"]
        assert deg["api_degraded"] is True
        assert deg["circuits"] == {"list": "open"}
    finally:
        srv.stop()


def test_metrics_probe_quiet_when_circuits_closed(tmp_path):
    """A healthy driver (api_degraded=0, all circuits closed) adds no
    degraded warnings — the gauges merely being exported is normal."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.set_gauge("api_degraded", 0)
    for verb in ("get", "list", "create"):
        metrics.set_gauge("api_circuit_state", 0, labels={"verb": verb})
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[f"127.0.0.1:{srv.port}"],
        )
        assert report["warnings"] == [], report["warnings"]
        deg = report["metrics"][f"127.0.0.1:{srv.port}"]["degraded"]
        assert deg["api_degraded"] is False
    finally:
        srv.stop()


def test_metrics_probe_surfaces_scheduler_fleet_health(tmp_path):
    """ISSUE 6: a scheduler whose grid is badly fragmented, or whose
    slice index could not parse every published ResourceSlice, shows
    up in doctor output with remediation hints."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.set_gauge("scheduler_frag_score", 0.4)
    metrics.set_gauge("scheduler_index_slices_seen", 12)
    metrics.set_gauge("scheduler_index_slices_indexed", 10)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "fragmentation score is 0.4" in warns
        assert "stranded" in warns
        assert "12 ResourceSlice(s) seen but only 10 indexed" in warns
        sched = report["metrics"][endpoint]["scheduler"]
        assert sched == {
            "frag_score": 0.4, "slices_seen": 12, "slices_indexed": 10,
        }
        out = render(report)
        assert "scheduler: frag_score=0.4 index=10/12 slices" in out
    finally:
        srv.stop()


def test_metrics_probe_quiet_on_healthy_scheduler(tmp_path):
    """A tidy grid (frag below threshold) with a fully-indexed fleet
    reports the section without warning; non-scheduler endpoints get
    no scheduler section at all."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.set_gauge("scheduler_frag_score", 0.1)
    metrics.set_gauge("scheduler_index_slices_seen", 8)
    metrics.set_gauge("scheduler_index_slices_indexed", 8)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    plugin_metrics = Metrics()
    plugin_metrics.set_gauge("api_degraded", 0)
    srv2 = MetricsServer(plugin_metrics, port=0, address="127.0.0.1")
    srv2.start()
    try:
        _s, lib = make_state(tmp_path)
        sched_ep = f"127.0.0.1:{srv.port}"
        plugin_ep = f"127.0.0.1:{srv2.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[sched_ep, plugin_ep],
        )
        assert report["warnings"] == [], report["warnings"]
        assert report["metrics"][sched_ep]["scheduler"] == {
            "frag_score": 0.1, "slices_seen": 8, "slices_indexed": 8,
        }
        assert "scheduler" not in report["metrics"][plugin_ep]
    finally:
        srv.stop()
        srv2.stop()


def test_metrics_probe_surfaces_engine_backpressure_and_exhaustion(
    tmp_path,
):
    """ISSUE 7: a serving engine stalled past the threshold (the chip
    lease is held elsewhere and not coming back) or whose page
    allocator hit free-list exhaustion shows up in doctor output with
    remediation hints — suffix-matched like the other gauges."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics(prefix="tpu_dra_workload")  # prefix must not matter
    metrics.set_gauge("engine_admission_stalled", 7.5)
    metrics.set_gauge("engine_pages_free", 0)
    metrics.inc("engine_page_exhausted_total", 3)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "STALLED for 7.5s" in warns
        assert "arbiter" in warns
        assert "free-list" in warns and "exhaustion 3 time(s)" in warns
        assert "int8 KV" in warns
        eng = report["metrics"][endpoint]["engine"]
        assert eng == {
            "admission_stalled_s": 7.5,
            "pages_free": 0,
            "page_exhausted": 3,
        }
        out = render(report)
        assert "engine: stalled=7.5s pages_free=0 exhausted=3" in out
    finally:
        srv.stop()


def test_metrics_probe_quiet_on_healthy_engine(tmp_path):
    """A momentary stall below the threshold and a page pool with
    headroom report the engine section without warnings; non-engine
    endpoints get no engine section."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.set_gauge("engine_admission_stalled", 0.2)
    metrics.set_gauge("engine_pages_free", 17)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    plain = Metrics()
    plain.set_gauge("api_degraded", 0)
    srv2 = MetricsServer(plain, port=0, address="127.0.0.1")
    srv2.start()
    try:
        _s, lib = make_state(tmp_path)
        eng_ep = f"127.0.0.1:{srv.port}"
        plain_ep = f"127.0.0.1:{srv2.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[eng_ep, plain_ep],
        )
        assert report["warnings"] == [], report["warnings"]
        assert report["metrics"][eng_ep]["engine"] == {
            "admission_stalled_s": 0.2, "pages_free": 17,
        }
        assert "engine" not in report["metrics"][plain_ep]
    finally:
        srv.stop()
        srv2.stop()


# --- decode-roofline trend gate (ISSUE 8) ------------------------------------


def _bench_artifact(tmp_path, n, x, wrapped=True, key="decode_x_above_bf16_floor"):
    payload = {key: x, "decode_tok_s": 9000.0}
    data = {"n": n, "parsed": payload} if wrapped else payload
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(data))


def test_bench_trend_regression_warns(tmp_path):
    state, lib = make_state(tmp_path)
    _bench_artifact(tmp_path, 5, 3.16)
    _bench_artifact(tmp_path, 6, 3.60)  # +14% — past the 10% gate
    report = collect(
        str(tmp_path / "data"), str(tmp_path / "cdi"),
        str(tmp_path / "mux"), tpulib=lib, bench_dir=str(tmp_path),
    )
    warns = [w for w in report["warnings"] if "roofline REGRESSED" in w]
    assert warns, report["warnings"]
    assert "decode_step_breakdown" in warns[0]  # remediation hint
    assert report["bench_trend"]["latest"]["x"] == 3.6
    assert "BENCH_r06" in render(report)


def test_bench_trend_improvement_and_small_wobble_quiet(tmp_path):
    state, lib = make_state(tmp_path)
    _bench_artifact(tmp_path, 5, 3.16)
    _bench_artifact(tmp_path, 6, 1.42)  # the goal trend: improvement
    report = collect(
        str(tmp_path / "data"), str(tmp_path / "cdi"),
        str(tmp_path / "mux"), tpulib=lib, bench_dir=str(tmp_path),
    )
    assert not any("roofline" in w for w in report["warnings"])
    _bench_artifact(tmp_path, 7, 1.48)  # +4% wobble: under the gate
    report = collect(
        str(tmp_path / "data"), str(tmp_path / "cdi"),
        str(tmp_path / "mux"), tpulib=lib, bench_dir=str(tmp_path),
    )
    assert not any("roofline" in w for w in report["warnings"])
    # The trend compares the two NEWEST artifacts, not first-vs-last.
    assert report["bench_trend"]["previous"]["x"] == 1.42


def test_bench_trend_suffix_matched_and_tolerant(tmp_path):
    """Artifacts predating the key (or unparseable) are skipped, the key
    is suffix-matched like the scheduler gauges, and < 2 carriers means
    no verdict (and no crash)."""
    state, lib = make_state(tmp_path)
    (tmp_path / "BENCH_r01.json").write_text("{not json")
    _bench_artifact(tmp_path, 5, 0, key="decode_tok_s_only")  # no carrier
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"parsed": {"decode_tok_s": 1.0}})
    )
    _bench_artifact(tmp_path, 3, 3.16, wrapped=False)  # unwrapped form
    report = collect(
        str(tmp_path / "data"), str(tmp_path / "cdi"),
        str(tmp_path / "mux"), tpulib=lib, bench_dir=str(tmp_path),
    )
    assert not any("roofline" in w for w in report["warnings"])
    assert "latest" not in report["bench_trend"]
    # A second carrier under a renamed-but-suffixed key still engages.
    (tmp_path / "BENCH_r04.json").write_text(
        json.dumps({"parsed": {"serving_x_above_bf16_floor": 4.0}})
    )
    report = collect(
        str(tmp_path / "data"), str(tmp_path / "cdi"),
        str(tmp_path / "mux"), tpulib=lib, bench_dir=str(tmp_path),
    )
    assert any("roofline REGRESSED" in w for w in report["warnings"])


def test_bench_trend_absent_without_bench_dir(tmp_path):
    state, lib = make_state(tmp_path)
    report = run_collect(tmp_path, lib)
    assert "bench_trend" not in report


def test_bench_trend_reads_nested_roofline_key(tmp_path):
    """BENCH_r05 and earlier carry the ratio only inside the
    decode_roofline dict — the suffix match must search one nested
    level or the gate is disarmed for the first real comparison."""
    state, lib = make_state(tmp_path)
    (tmp_path / "BENCH_r05.json").write_text(json.dumps(
        {"parsed": {"decode_roofline": {"x_above_bf16_floor": 3.16}}}
    ))
    _bench_artifact(tmp_path, 6, 3.60)  # new top-level form, +14%
    report = collect(
        str(tmp_path / "data"), str(tmp_path / "cdi"),
        str(tmp_path / "mux"), tpulib=lib, bench_dir=str(tmp_path),
    )
    assert any("roofline REGRESSED" in w for w in report["warnings"])
    assert report["bench_trend"]["previous"]["x"] == 3.16


def test_render_still_prints_notes(tmp_path):
    """Regression pin: inserting the bench-trend render line must not
    swallow the notes section (missing CDI root / no checkpoint)."""
    state, lib = make_state(tmp_path)
    report = collect(
        str(tmp_path / "data"), str(tmp_path / "missing-cdi"),
        str(tmp_path / "mux"), tpulib=lib, bench_dir=str(tmp_path),
    )
    out = render(report)
    assert "note:" in out and "missing-cdi" in out


def test_bench_trend_skips_non_object_artifact(tmp_path):
    """Valid JSON that is not an object (truncated/mis-redirected bench
    output) is skipped like any other unparseable artifact — one bad
    file must not cost the whole diagnostic run."""
    state, lib = make_state(tmp_path)
    (tmp_path / "BENCH_r01.json").write_text("[1, 2, 3]")
    (tmp_path / "BENCH_r02.json").write_text('"half a redirect"')
    _bench_artifact(tmp_path, 5, 3.16)
    _bench_artifact(tmp_path, 6, 3.60)
    report = collect(
        str(tmp_path / "data"), str(tmp_path / "cdi"),
        str(tmp_path / "mux"), tpulib=lib, bench_dir=str(tmp_path),
    )
    assert any("roofline REGRESSED" in w for w in report["warnings"])


def test_bench_trend_sorts_rounds_numerically(tmp_path):
    """BENCH_r100 must compare against BENCH_r99, not sort between r10
    and r11 — lexicographic order would freeze the gate at three-digit
    rounds."""
    state, lib = make_state(tmp_path)
    _bench_artifact(tmp_path, 98, 2.0)
    _bench_artifact(tmp_path, 99, 2.0)
    _bench_artifact(tmp_path, 100, 2.5)  # +25% in the true newest
    report = collect(
        str(tmp_path / "data"), str(tmp_path / "cdi"),
        str(tmp_path / "mux"), tpulib=lib, bench_dir=str(tmp_path),
    )
    assert any("roofline REGRESSED" in w for w in report["warnings"])
    assert report["bench_trend"]["latest"]["path"] == "BENCH_r100.json"
    assert report["bench_trend"]["previous"]["path"] == "BENCH_r99.json"


def test_metrics_probe_warns_on_growing_workqueue_depth(tmp_path):
    """ISSUE 10: a deep reconcile queue that is STILL GROWING across
    the probe interval means the reconciler is falling behind — WARN
    with the slow-callback-vs-event-storm remediation split; per-shard
    series are matched individually."""
    import threading

    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.set_gauge("workqueue_depth", 150, labels={"shard": "3"})
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    bump = threading.Timer(
        0.1,
        lambda: metrics.set_gauge(
            "workqueue_depth", 180, labels={"shard": "3"}
        ),
    )
    bump.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint], metrics_interval=0.4,
        )
        warns = "\n".join(report["warnings"])
        assert "still GROWING" in warns
        assert "workqueue_work_duration_seconds" in warns
        assert 'shard="3"' in warns
        out = render(report)
        assert "workqueue: depth[3]=180+30" in out
    finally:
        bump.cancel()
        srv.stop()


def test_metrics_probe_quiet_on_draining_or_shallow_workqueue(tmp_path):
    """Deep but DRAINING (depth falling across the interval) and
    shallow queues stay quiet; a single-sample deep queue gets the
    re-probe hint instead of the growth verdict."""
    import threading

    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.set_gauge("workqueue_depth", 150)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    drain = threading.Timer(
        0.1, lambda: metrics.set_gauge("workqueue_depth", 90)
    )
    drain.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint], metrics_interval=0.4,
        )
        assert report["warnings"] == [], report["warnings"]
        # Single sample, deep: flagged with the re-probe hint.
        metrics.set_gauge("workqueue_depth", 150)
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "deep reconcile backlog" in warns
        assert "--metrics-interval" in warns
    finally:
        drain.cancel()
        srv.stop()


# --- serving-fabric checks (ISSUE 11) ---------------------------------------


def test_metrics_probe_warns_on_sustained_tenant_starvation(tmp_path):
    """A tenant whose WFQ virtual-time lag is past the threshold AND
    still growing across the probe interval is being starved — WARN
    with the weight/affinity/inflight-cap remediation hints; per-tenant
    series matched individually."""
    import threading

    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.set_gauge(
        "fabric_tenant_vtime_lag", 2000, labels={"tenant": "silver"}
    )
    metrics.set_gauge(
        "fabric_tenant_vtime_lag", 12, labels={"tenant": "gold"}
    )
    metrics.set_gauge("fabric_replicas", 4)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    bump = threading.Timer(
        0.1,
        lambda: metrics.set_gauge(
            "fabric_tenant_vtime_lag", 2600, labels={"tenant": "silver"}
        ),
    )
    bump.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint], metrics_interval=0.4,
        )
        warns = "\n".join(report["warnings"])
        assert "STARVED" in warns
        assert 'tenant="silver"' in warns
        assert 'tenant="gold"' not in warns
        assert "weight" in warns and "affinity" in warns
        out = render(report)
        assert "fabric: replicas=4" in out
        assert "lag[silver]=2600+600" in out
    finally:
        bump.cancel()
        srv.stop()


def test_metrics_probe_fabric_quiet_and_single_sample_reprobe(tmp_path):
    """A large lag that is DRAINING stays quiet; a single sample past
    the threshold asks for the re-probe instead of the starvation
    verdict; healthy lags report without warning."""
    import threading

    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.set_gauge(
        "fabric_tenant_vtime_lag", 2000, labels={"tenant": "bulk"}
    )
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    drain = threading.Timer(
        0.1,
        lambda: metrics.set_gauge(
            "fabric_tenant_vtime_lag", 900, labels={"tenant": "bulk"}
        ),
    )
    drain.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint], metrics_interval=0.4,
        )
        assert report["warnings"] == [], report["warnings"]
        metrics.set_gauge(
            "fabric_tenant_vtime_lag", 2000, labels={"tenant": "bulk"}
        )
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "--metrics-interval" in warns and "WFQ lag" in warns
    finally:
        drain.cancel()
        srv.stop()


def test_metrics_probe_warns_on_autoscaler_flapping(tmp_path):
    """fabric_autoscaler_flaps_total > 0 (scale up+down desired within
    one cooldown window) WARNs with the widen-the-hysteresis
    remediation; with two samples only a CLIMBING counter warns (an old
    flap already acted on stays quiet)."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.inc("fabric_autoscaler_flaps_total", 2)
    metrics.set_gauge("fabric_replicas", 3)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "FLAPPING" in warns
        assert "cooldown_seconds" in warns
        assert "fabric: replicas=3 flaps=2" in render(report)
        # Two samples, not climbing: the historical flap stays quiet.
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint], metrics_interval=0.2,
        )
        assert report["warnings"] == [], report["warnings"]
    finally:
        srv.stop()


# --- elastic-repacker checks (ISSUE 12) --------------------------------------


def _repacker_metrics(frag=0.4, leader=1.0, active=0, oldest=0.0,
                      migrations=5):
    from tpu_dra.infra.metrics import Metrics

    metrics = Metrics()
    metrics.set_gauge("repacker_frag_score", frag)
    metrics.set_gauge("repacker_leader", leader)
    metrics.set_gauge("repacker_active_migrations", active)
    metrics.set_gauge("repacker_oldest_migration_seconds", oldest)
    metrics.inc("repacker_migrations_total", migrations)
    return metrics


def test_metrics_probe_warns_on_frag_high_and_repacker_not_leading(
    tmp_path,
):
    """Fragmentation past the threshold while the repacker does not
    hold the Lease: stranded capacity has no one acting on it — WARN
    with the leader-election remediation, plus the repacker render
    line."""
    from tpu_dra.infra.metrics import MetricsServer

    metrics = _repacker_metrics(frag=0.4, leader=0.0)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "NOT LEADING" in warns
        assert "Lease" in warns
        out = render(report)
        assert "repacker: leader=0 active=0 migrations=5 frag=0.4" in out
    finally:
        srv.stop()


def test_metrics_probe_warns_on_frag_high_and_repacker_idle(tmp_path):
    """Leading but idle under high fragmentation (and, with two
    samples, migrations_total flat): likely misconfigured — WARN with
    the threshold/budget remediation. A repacker actively migrating
    (or one whose counter is climbing) stays quiet."""
    from tpu_dra.infra.metrics import MetricsServer

    metrics = _repacker_metrics(frag=0.4, leader=1.0, active=0)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "IDLE" in warns
        assert "frag_threshold" in warns
        # Mid-burst (active migrations): quiet despite the high score.
        metrics.set_gauge("repacker_active_migrations", 2)
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        assert report["warnings"] == [], report["warnings"]
        # Two samples with the counter CLIMBING: also quiet (the
        # repacker is making progress between the samples).
        import threading

        metrics.set_gauge("repacker_active_migrations", 0)
        bump = threading.Timer(
            0.1, lambda: metrics.inc("repacker_migrations_total")
        )
        bump.start()
        try:
            report = collect(
                str(tmp_path / "data"), str(tmp_path / "cdi"),
                str(tmp_path / "mux"), tpulib=lib,
                metrics_endpoints=[endpoint], metrics_interval=0.4,
            )
            assert report["warnings"] == [], report["warnings"]
        finally:
            bump.cancel()
    finally:
        srv.stop()


def test_metrics_probe_warns_on_stuck_migration(tmp_path):
    """A migration in flight past the budget window is holding a
    drained tenant in limbo — WARN with the drain/unschedulable/WAL
    remediation split. A fast in-flight migration stays quiet, as does
    a low-frag healthy repacker."""
    from tpu_dra.infra.metrics import MetricsServer

    metrics = _repacker_metrics(frag=0.01, oldest=120.0, active=1)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "past the disruption-budget window" in warns
        assert "repack.tpu.google.com/state" in warns
        assert "oldest=120s" in render(report)
        # Healthy: fast migration, low frag.
        metrics.set_gauge("repacker_oldest_migration_seconds", 2.0)
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        assert report["warnings"] == [], report["warnings"]
    finally:
        srv.stop()


def test_metrics_probe_warns_on_fleetmon_target_down(tmp_path):
    """A fleet monitor reporting a dead scrape target means the SLO
    engine is judging burn rates over a partial view — WARN with the
    endpoint/--target remediation, 'fleetmon:' render line."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.set_gauge("fleetmon_scrape_interval_seconds", 15.0)
    metrics.set_gauge(
        "fleetmon_target_up", 0.0, labels={"target": "plugin"}
    )
    metrics.set_gauge(
        "fleetmon_target_up", 1.0, labels={"target": "scheduler"}
    )
    metrics.set_gauge(
        "fleetmon_scrape_age_seconds", 2.0,
        labels={"target": "scheduler"},
    )
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[f"127.0.0.1:{srv.port}"],
        )
        warns = "\n".join(report["warnings"])
        assert "'plugin' is DOWN" in warns
        assert "PARTIAL view" in warns
        assert "scheduler" not in warns  # the healthy target is quiet
        out = render(report)
        assert "fleetmon: up=1/2" in out
        assert "down[plugin]" in out
    finally:
        srv.stop()


def test_metrics_probe_warns_on_fleetmon_staleness(tmp_path):
    """A target that answers up=1 but whose last successful scrape is
    older than 3 intervals is STALE — the burn rates are running on
    old samples. Fresh targets stay quiet."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.set_gauge("fleetmon_scrape_interval_seconds", 15.0)
    metrics.set_gauge(
        "fleetmon_target_up", 1.0, labels={"target": "router"}
    )
    metrics.set_gauge(
        "fleetmon_scrape_age_seconds", 100.0,
        labels={"target": "router"},
    )
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "'router' is STALE" in warns
        assert "old samples" in warns
        assert "stale[router]=100s" in render(report)
        # Fresh again: quiet.
        metrics.set_gauge(
            "fleetmon_scrape_age_seconds", 3.0,
            labels={"target": "router"},
        )
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        assert report["warnings"] == [], report["warnings"]
    finally:
        srv.stop()


# --- disaggregated serving (ISSUE 17) ---------------------------------------


def _disagg_gauges(metrics, n_p=2, n_d=1, backlog=0.0, p_tok=0.0,
                   d_tok=0.0):
    metrics.set_gauge(
        "fabric_phase_replicas", n_p, labels={"phase": "prefill"}
    )
    metrics.set_gauge(
        "fabric_phase_replicas", n_d, labels={"phase": "decode"}
    )
    metrics.set_gauge("fabric_migration_backlog", backlog)
    metrics.set_gauge("fabric_queued_prefill_tokens", p_tok)
    metrics.set_gauge("fabric_queued_decode_tokens", d_tok)


def test_metrics_probe_warns_on_growing_migration_backlog(tmp_path):
    """A migration waiting room climbing across the probe interval
    means the decode pool is grafting slower than prefill exports —
    WARN with the scale-decode-up remediation; a DRAINING backlog of
    the same size stays quiet; the 'disagg:' render line carries the
    pools/backlog/migrations summary."""
    import threading

    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    _disagg_gauges(metrics, backlog=4.0)
    metrics.inc(
        "fabric_kv_migrations_total", 9, labels={"outcome": "shipped"}
    )
    metrics.inc(
        "fabric_kv_migrations_total", 2, labels={"outcome": "fallback"}
    )
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    bump = threading.Timer(
        0.1, lambda: metrics.set_gauge("fabric_migration_backlog", 9.0)
    )
    bump.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint], metrics_interval=0.4,
        )
        warns = "\n".join(report["warnings"])
        assert "KV-migration backlog GROWING" in warns
        assert "scale the decode pool up" in warns
        assert "docs/serving.md" in warns
        out = render(report)
        assert "disagg: pools=decode:1,prefill:2" in out
        assert "backlog=9+5" in out
        assert "migrations=fallback:2,shipped:9" in out
        # Draining: same level, shrinking — quiet.
        metrics.set_gauge("fabric_migration_backlog", 9.0)
        drain = threading.Timer(
            0.1,
            lambda: metrics.set_gauge("fabric_migration_backlog", 3.0),
        )
        drain.start()
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint], metrics_interval=0.4,
        )
        drain.cancel()
        assert report["warnings"] == [], report["warnings"]
    finally:
        bump.cancel()
        srv.stop()


def test_metrics_probe_disagg_single_sample_asks_reprobe(tmp_path):
    """One sample with a nonzero waiting room cannot tell growth from
    drain — the doctor asks for --metrics-interval instead of guessing."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    _disagg_gauges(metrics, backlog=6.0)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "fabric_migration_backlog = 6" in warns
        assert "--metrics-interval" in warns
    finally:
        srv.stop()


def test_metrics_probe_warns_on_phase_pool_imbalance(tmp_path):
    """Per-replica backlog of one phase dwarfing the idle other pool
    WARNs in BOTH directions with the move-replicas/autoscaler hints;
    balanced or sub-floor loads stay quiet."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    _disagg_gauges(metrics, n_p=1, n_d=1, p_tok=9000.0, d_tok=10.0)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "phase-pool IMBALANCE" in warns
        assert "prefill backlog" in warns and "TTFT" in warns
        assert "prefill-ward" in warns
        assert "queued=p:9000/d:10" in render(report)
        # The other direction: decode drowning, prefill idle.
        _disagg_gauges(metrics, n_p=1, n_d=1, p_tok=10.0, d_tok=9000.0)
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "decode backlog" in warns and "ITL" in warns
        assert "decode-ward" in warns
        # Balanced load, both pools busy: quiet.
        _disagg_gauges(metrics, n_p=1, n_d=1, p_tok=4000.0, d_tok=3000.0)
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        assert report["warnings"] == [], report["warnings"]
        # Sub-floor imbalance (tiny absolute backlog): quiet.
        _disagg_gauges(metrics, n_p=1, n_d=1, p_tok=400.0, d_tok=1.0)
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        assert report["warnings"] == [], report["warnings"]
    finally:
        srv.stop()


def test_metrics_probe_colocated_fleet_has_no_disagg_section(tmp_path):
    """A colocated fleet (no phase-role replicas, empty waiting room)
    gets no 'disagg:' section at all — the section's absence IS the
    'not disaggregated' signal."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.set_gauge("fabric_replicas", 3)
    metrics.set_gauge(
        "fabric_phase_replicas", 0, labels={"phase": "prefill"}
    )
    metrics.set_gauge(
        "fabric_phase_replicas", 0, labels={"phase": "decode"}
    )
    metrics.set_gauge("fabric_migration_backlog", 0.0)
    metrics.set_gauge("fabric_queued_prefill_tokens", 50.0)
    metrics.set_gauge("fabric_queued_decode_tokens", 70.0)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        assert report["warnings"] == [], report["warnings"]
        assert "disagg" not in report["metrics"][endpoint]
        assert "disagg:" not in render(report)
    finally:
        srv.stop()


# --- gang-scheduling checks (ISSUE 19) ---------------------------------------


def _gang_metrics(pending=0, wal_oldest=0.0, unschedulable=0,
                  members=4, frag=0.0, rollbacks=0):
    from tpu_dra.infra.metrics import Metrics

    metrics = Metrics()
    metrics.set_gauge("gang_members", members)
    metrics.set_gauge("scheduler_gang_pending", pending)
    metrics.set_gauge("scheduler_gang_wal_oldest_seconds", wal_oldest)
    metrics.set_gauge("scheduler_gang_unschedulable", unschedulable)
    metrics.set_gauge("scheduler_frag_score", frag)
    if rollbacks:
        metrics.inc("gang_partial_rollbacks_total", rollbacks)
    return metrics


def test_metrics_probe_warns_on_stuck_gang_wal(tmp_path):
    """A gang commit WAL outstanding far past one commit's duration
    means a scheduler died mid-protocol — WARN with the recovery
    remediation (members are fenced from prepare until it resolves),
    plus the gang render line. A fresh WAL (a commit in flight right
    now) stays quiet."""
    from tpu_dra.infra.metrics import MetricsServer

    metrics = _gang_metrics(pending=2, wal_oldest=300.0)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "gang commit WAL" in warns
        assert "gang.tpu.google.com/state" in warns
        assert "mid-protocol" in warns
        out = render(report)
        assert "gang: members=4 pending=2 wal_oldest=300s" in out
        # A WAL inside the commit window is the protocol working.
        metrics.set_gauge("scheduler_gang_wal_oldest_seconds", 1.5)
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        assert report["warnings"] == [], report["warnings"]
    finally:
        srv.stop()


def test_metrics_probe_warns_on_gang_unschedulable_with_high_frag(
    tmp_path,
):
    """Gangs stuck Unschedulable while the frag score says free
    capacity is stranded: a corridor-opening repack could seat them —
    WARN pointing at the repacker's corridor mode. The same stuck
    gangs over a defragmented fleet stay quiet (capacity is genuinely
    insufficient; no repack can help)."""
    from tpu_dra.infra.metrics import MetricsServer

    metrics = _gang_metrics(pending=4, unschedulable=1, frag=0.4)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        warns = "\n".join(report["warnings"])
        assert "Unschedulable" in warns
        assert "corridor mode" in warns
        assert "unschedulable=1" in render(report)
        # Defragmented fleet: the frag-driven WARN disarms (the
        # scheduler's own frag WARN would fire separately if high —
        # here it is low, so the report is clean).
        metrics.set_gauge("scheduler_frag_score", 0.0)
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        assert report["warnings"] == [], report["warnings"]
    finally:
        srv.stop()


def test_metrics_probe_gangless_endpoint_has_no_gang_section(tmp_path):
    """An endpoint exporting no gang series gets no 'gang:' section —
    the section's absence IS the 'no gangs here' signal."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.set_gauge("scheduler_frag_score", 0.1)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = collect(
            str(tmp_path / "data"), str(tmp_path / "cdi"),
            str(tmp_path / "mux"), tpulib=lib,
            metrics_endpoints=[endpoint],
        )
        assert "gang" not in report["metrics"][endpoint]
        assert "gang:" not in render(report)
    finally:
        srv.stop()


# --- apiserver flow control + retry budget (ISSUE 20) -----------------------


def _flow_probe(tmp_path, lib, endpoint, interval=None):
    return collect(
        str(tmp_path / "data"), str(tmp_path / "cdi"),
        str(tmp_path / "mux"), tpulib=lib,
        metrics_endpoints=[endpoint],
        **({"metrics_interval": interval} if interval else {}),
    )


def test_metrics_probe_quiet_when_nothing_ever_shed(tmp_path):
    """A fleet that has never shed exports no rejected series: no
    'apiflow:' section, no warnings — silence is the healthy signal."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.inc(
        "apiserver_flow_admitted_total", labels={"flow": "workload"}
    )
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = _flow_probe(tmp_path, lib, endpoint)
        assert "apiflow" not in report["metrics"][endpoint]
        assert "apiflow:" not in render(report)
        assert not any("SHEDDING" in w for w in report["warnings"])
    finally:
        srv.stop()


def test_metrics_probe_warns_on_active_flow_shedding(tmp_path):
    """A rejected counter still CLIMBING across the probe interval is a
    live brownout: doctor names the flow and says it is being shed
    RIGHT NOW."""
    import threading
    import time

    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.inc(
        "apiserver_flow_rejected_total",
        labels={"flow": "slice-publish"}, value=10,
    )
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    stop = threading.Event()

    def keep_shedding():
        while not stop.wait(0.05):
            metrics.inc(
                "apiserver_flow_rejected_total",
                labels={"flow": "slice-publish"},
            )

    t = threading.Thread(target=keep_shedding, daemon=True)
    t.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = _flow_probe(tmp_path, lib, endpoint, interval=0.4)
        assert any(
            "SHEDDING" in w and "slice-publish" in w
            for w in report["warnings"]
        ), report["warnings"]
        out = render(report)
        assert "apiflow:" in out and "slice-publish" in out
    finally:
        stop.set()
        t.join(timeout=5)
        srv.stop()


def test_metrics_probe_past_brownout_is_history_not_a_page(tmp_path):
    """A nonzero-but-static rejected counter across two samples is a
    past brownout: the totals still render (the operator can see the
    history) but no warning fires."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.inc(
        "apiserver_flow_rejected_total",
        labels={"flow": "slice-publish"}, value=44,
    )
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = _flow_probe(tmp_path, lib, endpoint, interval=0.3)
        apiflow = report["metrics"][endpoint]["apiflow"]
        assert apiflow["rejected"]["slice-publish"]["rejected"] == 44.0
        assert not any(
            "SHEDDING" in w for w in report["warnings"]
        ), report["warnings"]
    finally:
        srv.stop()


def test_metrics_probe_single_sample_shed_asks_for_reprobe(tmp_path):
    """One sample cannot tell live shedding from history: doctor flags
    the total and asks for a --metrics-interval re-probe."""
    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.inc(
        "apiserver_flow_rejected_total",
        labels={"flow": "claim-status"}, value=3,
    )
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = _flow_probe(tmp_path, lib, endpoint)
        assert any(
            "--metrics-interval" in w and "claim-status" in w
            for w in report["warnings"]
        ), report["warnings"]
    finally:
        srv.stop()


def test_metrics_probe_warns_on_burning_retry_budget(tmp_path):
    """api_retry_budget_exhausted_total climbing across the interval:
    the process is refusing its own retries — doctor says so and
    points at the apiserver-side pressure first."""
    import threading

    from tpu_dra.infra.metrics import Metrics, MetricsServer

    metrics = Metrics()
    metrics.inc("api_retry_budget_exhausted_total", value=5)
    srv = MetricsServer(metrics, port=0, address="127.0.0.1")
    srv.start()
    stop = threading.Event()

    def keep_burning():
        while not stop.wait(0.05):
            metrics.inc("api_retry_budget_exhausted_total")

    t = threading.Thread(target=keep_burning, daemon=True)
    t.start()
    try:
        _s, lib = make_state(tmp_path)
        endpoint = f"127.0.0.1:{srv.port}"
        report = _flow_probe(tmp_path, lib, endpoint, interval=0.4)
        assert any(
            "retry budget is EXHAUSTED" in w for w in report["warnings"]
        ), report["warnings"]
        assert "budget-exhausted" in render(report)
    finally:
        stop.set()
        t.join(timeout=5)
        srv.stop()
