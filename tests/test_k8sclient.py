"""Fake-cluster + informer semantics tests.

The fake is load-bearing for every controller/plugin test, so its apiserver
semantics (resourceVersion conflicts, watch ordering, finalizer-gated
deletion) are pinned here.
"""

import threading
import time

import pytest

from tpu_dra.k8sclient import (
    COMPUTE_DOMAINS,
    PODS,
    ApiConflict,
    ApiNotFound,
    FakeCluster,
    Informer,
    ResourceClient,
)


def cd_obj(name="cd1", ns="default", **spec):
    return {
        "apiVersion": "resource.tpu.google.com/v1beta1",
        "kind": "ComputeDomain",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"numNodes": 2, **spec},
    }


@pytest.fixture
def fc():
    c = FakeCluster()
    yield c
    c.clear_watches()


@pytest.fixture
def cds(fc):
    return ResourceClient(fc, COMPUTE_DOMAINS)


def test_crud_roundtrip(cds):
    created = cds.create(cd_obj())
    assert created["metadata"]["uid"]
    assert created["metadata"]["resourceVersion"] == "1"
    got = cds.get("cd1", "default")
    assert got["spec"]["numNodes"] == 2
    assert cds.try_get("nope", "default") is None
    with pytest.raises(ApiNotFound):
        cds.get("nope", "default")
    with pytest.raises(ApiConflict):
        cds.create(cd_obj())  # duplicate


def test_update_conflict_on_stale_rv(cds):
    cds.create(cd_obj())
    a = cds.get("cd1", "default")
    b = cds.get("cd1", "default")
    a["spec"]["numNodes"] = 3
    cds.update(a)
    b["spec"]["numNodes"] = 4
    with pytest.raises(ApiConflict):
        cds.update(b)  # stale resourceVersion


def test_generation_bumps_on_spec_change_only(cds):
    cds.create(cd_obj())
    obj = cds.get("cd1", "default")
    assert obj["metadata"]["generation"] == 1
    obj["spec"]["numNodes"] = 8
    obj = cds.update(obj)
    assert obj["metadata"]["generation"] == 2
    obj["status"] = {"status": "Ready"}
    obj = cds.update_status(obj)
    assert obj["metadata"]["generation"] == 2  # status change: no bump
    assert cds.get("cd1", "default")["status"]["status"] == "Ready"


def test_update_status_does_not_clobber_spec(cds):
    cds.create(cd_obj())
    obj = cds.get("cd1", "default")
    obj["spec"]["numNodes"] = 99  # local mutation must not leak via /status
    obj["status"] = {"status": "NotReady"}
    cds.update_status(obj)
    assert cds.get("cd1", "default")["spec"]["numNodes"] == 2


def test_label_selector_list(cds):
    o = cd_obj("a")
    o["metadata"]["labels"] = {"team": "x"}
    cds.create(o)
    cds.create(cd_obj("b"))
    assert [o["metadata"]["name"] for o in cds.list(label_selector={"team": "x"})] == [
        "a"
    ]
    assert len(cds.list(namespace="default")) == 2
    assert cds.list(namespace="other") == []


def test_generate_name(fc):
    pods = ResourceClient(fc, PODS)
    p = pods.create(
        {"metadata": {"generateName": "worker-", "namespace": "default"}, "spec": {}}
    )
    assert p["metadata"]["name"].startswith("worker-")
    assert len(p["metadata"]["name"]) > len("worker-")


def test_patch_merge_and_delete_key(cds):
    cds.create(cd_obj())
    cds.patch("cd1", {"metadata": {"labels": {"a": "1"}}}, "default")
    assert cds.get("cd1", "default")["metadata"]["labels"] == {"a": "1"}
    cds.patch("cd1", {"metadata": {"labels": {"a": None, "b": "2"}}}, "default")
    assert cds.get("cd1", "default")["metadata"]["labels"] == {"b": "2"}


def test_finalizer_gated_deletion(cds, fc):
    o = cd_obj()
    o["metadata"]["finalizers"] = ["tpu.google.com/cd"]
    cds.create(o)
    cds.delete("cd1", "default")
    # Parked: deletionTimestamp set, object still present.
    cur = cds.get("cd1", "default")
    assert cur["metadata"]["deletionTimestamp"]
    # Removing the finalizer completes deletion.
    cur["metadata"]["finalizers"] = []
    cds.update(cur)
    assert cds.try_get("cd1", "default") is None


def test_delete_without_finalizers_is_immediate(cds):
    cds.create(cd_obj())
    cds.delete("cd1", "default")
    assert cds.try_get("cd1", "default") is None


def test_watch_event_stream(cds, fc):
    w = fc.watch(COMPUTE_DOMAINS, namespace="default")
    cds.create(cd_obj())
    obj = cds.get("cd1", "default")
    obj["spec"]["numNodes"] = 5
    cds.update(obj)
    cds.delete("cd1", "default")
    events = []
    it = iter(w)
    for _ in range(3):
        events.append(next(it))
    assert [e[0] for e in events] == ["ADDED", "MODIFIED", "DELETED"]
    w.close()


def test_watch_label_filtering(cds, fc):
    w = fc.watch(COMPUTE_DOMAINS, label_selector={"want": "yes"})
    o = cd_obj("match")
    o["metadata"]["labels"] = {"want": "yes"}
    cds.create(cd_obj("skip"))
    cds.create(o)
    ev, obj = next(iter(w))
    assert obj["metadata"]["name"] == "match"
    w.close()


def test_informer_sync_and_events(cds, fc):
    cds.create(cd_obj("pre"))
    inf = Informer(fc, COMPUTE_DOMAINS, namespace="default")
    seen = []
    done = threading.Event()

    def handler(ev, obj):
        seen.append((ev, obj["metadata"]["name"]))
        if len(seen) >= 3:
            done.set()

    inf.add_handler(handler)
    inf.start()
    assert inf.wait_for_sync()
    assert inf.get("pre", "default") is not None
    cds.create(cd_obj("live"))
    obj = cds.get("live", "default")
    obj["spec"]["numNodes"] = 9
    cds.update(obj)
    assert done.wait(3)
    assert seen[0] == ("ADDED", "pre")
    assert ("ADDED", "live") in seen
    assert ("MODIFIED", "live") in seen
    assert {o["metadata"]["name"] for o in inf.list()} == {"pre", "live"}
    inf.stop()


def test_informer_no_gap_between_list_and_watch(cds, fc):
    """Objects created during startup are never missed."""
    inf = Informer(fc, COMPUTE_DOMAINS)
    inf.start()
    cds.create(cd_obj("during"))
    deadline = time.monotonic() + 2
    while time.monotonic() < deadline:
        if inf.get("during", "default"):
            break
        time.sleep(0.01)
    assert inf.get("during", "default") is not None
    inf.stop()


def test_informer_survives_watch_stream_end(cds, fc):
    """Watch stream death must not leave the store silently stale."""
    inf = Informer(fc, COMPUTE_DOMAINS)
    inf.resync_backoff = 0.05
    inf.start()
    assert inf.wait_for_sync()
    # Kill the underlying watch (server-side timeout analog).
    inf._watch.close()
    cds.create(cd_obj("after-drop"))
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline and not inf.get("after-drop", "default"):
        time.sleep(0.02)
    assert inf.get("after-drop", "default") is not None
    inf.stop()


def test_informer_relist_emits_deletes(cds, fc):
    inf = Informer(fc, COMPUTE_DOMAINS)
    inf.resync_backoff = 0.05
    inf.start()
    cds.create(cd_obj("doomed"))
    deadline = time.monotonic() + 2
    while time.monotonic() < deadline and not inf.get("doomed", "default"):
        time.sleep(0.02)
    deletes = []
    inf.add_handler(lambda ev, o: deletes.append(o["metadata"]["name"]) if ev == "DELETED" else None)
    # Drop the watch, delete behind its back, wait for resync.
    inf._watch.close()
    cds.delete("doomed", "default")
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline and inf.get("doomed", "default"):
        time.sleep(0.02)
    assert inf.get("doomed", "default") is None
    assert "doomed" in deletes
    inf.stop()


def test_informer_resumes_watch_from_resource_version(cds, fc):
    """After a stream drop, the informer resumes from its last observed
    resourceVersion and the server replays the missed window — no relist
    (asserted by counting backend.list calls)."""
    inf = Informer(fc, COMPUTE_DOMAINS)
    inf.resync_backoff = 0.05
    inf.start()
    assert inf.wait_for_sync()
    cds.create(cd_obj("pre-drop"))
    deadline = time.monotonic() + 2
    while time.monotonic() < deadline and not inf.get("pre-drop", "default"):
        time.sleep(0.02)

    lists = []
    orig_list = fc.list
    fc.list = lambda *a, **k: (lists.append(1), orig_list(*a, **k))[1]
    inf._watch.close()
    cds.create(cd_obj("missed-during-drop"))
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline and not inf.get(
        "missed-during-drop", "default"
    ):
        time.sleep(0.02)
    fc.list = orig_list
    assert inf.get("missed-during-drop", "default") is not None
    assert lists == [], "RV resume should have replayed without a relist"
    inf.stop()


def test_informer_error_410_event_forces_relist(fc, cds):
    """A real apiserver reports an expired watch RV as HTTP 200 + in-stream
    ERROR(code=410); the informer must drop its resume point and relist
    instead of re-resuming from the dead version forever."""
    import queue as queue_mod

    class Stream:
        def __init__(self, events):
            self._q = queue_mod.Queue()
            for e in events:
                self._q.put(e)
            self.closed = False

        def close(self):
            self.closed = True
            self._q.put(None)

        def __iter__(self):
            while True:
                item = self._q.get()
                if item is None:
                    return
                yield item

    class Backend410:
        """First resumed watch yields ERROR 410 then ends; subsequent
        watches delegate to the fake."""

        def __init__(self, fc):
            self.fc = fc
            self.resume_rvs = []

        def list(self, *a, **k):
            return self.fc.list(*a, **k)

        def watch(self, rd, namespace=None, label_selector=None,
                  resource_version=None, field_selector=None):
            if resource_version is not None:
                self.resume_rvs.append(resource_version)
                return Stream([
                    ("ERROR", {"kind": "Status", "code": 410,
                               "message": "too old resource version"}),
                ])
            return self.fc.watch(rd, namespace, label_selector)

    cds.create(cd_obj("existing"))
    backend = Backend410(fc)
    inf = Informer(backend, COMPUTE_DOMAINS)
    inf.resync_backoff = 0.05
    inf.start()
    assert inf.wait_for_sync()
    deadline = time.monotonic() + 2
    while time.monotonic() < deadline and not inf.get("existing", "default"):
        time.sleep(0.02)

    # Drop the stream: the informer resumes (gets ERROR 410), must then
    # fall back to a fresh watch + relist and keep converging.
    inf._watch.close()
    cds.create(cd_obj("post-410"))
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline and not inf.get("post-410", "default"):
        time.sleep(0.02)
    assert inf.get("post-410", "default") is not None
    assert len(backend.resume_rvs) == 1, (
        "informer must not re-resume from an RV the server declared gone"
    )
    inf.stop()


def test_finalizer_completion_delete_gets_own_resource_version(fc, cds):
    """The DELETED event emitted when the last finalizer is stripped must
    carry a NEW resourceVersion: a watch resuming from the preceding
    MODIFIED's version (strict rv > from_rv replay) would otherwise skip
    the deletion forever."""
    obj = cd_obj("fin")
    obj["metadata"]["finalizers"] = ["x"]
    created = cds.create(obj)
    cds.delete("fin", "default")
    cur = cds.get("fin", "default")
    mod_rv = int(cur["metadata"]["resourceVersion"])
    w = fc.watch(COMPUTE_DOMAINS, resource_version=str(mod_rv))
    cur["metadata"]["finalizers"] = []
    cds.update(cur)
    seen = []
    for _ in range(5):
        item = w.next_event(timeout=2)
        if not isinstance(item, tuple):
            break
        seen.append(item)
        if item[0] == "DELETED":
            break
    w.close()
    deleted = [o for ev, o in seen if ev == "DELETED"]
    assert deleted, f"no DELETED event in {[(e, None) for e, _ in seen]}"
    rvs = [int(o["metadata"]["resourceVersion"]) for _, o in seen]
    assert int(deleted[0]["metadata"]["resourceVersion"]) == max(rvs)
    assert len(set(rvs)) == len(rvs), "events must not share resourceVersions"


def test_load_dir_seeds_manifests(tmp_path):
    import json

    from tpu_dra.k8sclient import COMPUTE_DOMAINS, RESOURCE_CLAIMS, FakeCluster

    (tmp_path / "claim.json").write_text(json.dumps({
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceClaim",
        "metadata": {"name": "c1", "namespace": "ns1", "uid": "pinned-uid"},
        "status": {"allocation": {"devices": {"results": []}}},
    }))
    (tmp_path / "cds.yaml").write_text(
        "apiVersion: resource.tpu.google.com/v1beta1\n"
        "kind: ComputeDomain\n"
        "metadata: {name: cd1, namespace: ns1}\n"
        "spec: {numNodes: 2}\n"
        "---\n"
        "apiVersion: resource.tpu.google.com/v1beta1\n"
        "kind: ComputeDomain\n"
        "metadata: {name: cd2, namespace: ns1}\n"
        "spec: {numNodes: 4}\n"
    )
    fc = FakeCluster()
    assert fc.load_dir(str(tmp_path)) == 3
    claim = fc.get(RESOURCE_CLAIMS, "ns1", "c1")
    # Pinned uid and status survive seeding (the wire e2e depends on both).
    assert claim["metadata"]["uid"] == "pinned-uid"
    assert claim["status"]["allocation"] == {"devices": {"results": []}}
    assert len(fc.list(COMPUTE_DOMAINS, "ns1")) == 2


def test_load_dir_rejects_unknown_kind(tmp_path):
    import json

    import pytest as _pytest

    from tpu_dra.k8sclient import FakeCluster
    from tpu_dra.k8sclient.resources import K8sApiError

    (tmp_path / "x.json").write_text(json.dumps(
        {"apiVersion": "v1", "kind": "Martian", "metadata": {"name": "m"}}
    ))
    with _pytest.raises(K8sApiError, match="unknown resource"):
        FakeCluster().load_dir(str(tmp_path))


def test_informer_survives_raising_watch_stream():
    """A connection torn down mid-chunk RAISES out of the watch iterator
    (urllib3 ProtocolError/AttributeError) instead of ending cleanly; the
    informer thread must resync, not die — a dead thread silently freezes
    the store until process restart (seen live in the multi-slice e2e
    when the controller's clique watch broke)."""
    import time

    fc = FakeCluster()
    cds = ResourceClient(fc, COMPUTE_DOMAINS)

    class RaisingOnce:
        """First watch: yields one event, then raises mid-stream.
        Later watches delegate to the fake cluster."""

        def __init__(self, fc):
            self.fc = fc
            self.raised = False

        def list(self, *a, **k):
            return self.fc.list(*a, **k)

        def watch(self, rd, namespace=None, label_selector=None,
                  resource_version=None, field_selector=None):
            if not self.raised:
                self.raised = True
                real = self.fc.watch(rd, namespace, label_selector)

                def broken():
                    for i, item in enumerate(real):
                        yield item
                        raise AttributeError(
                            "'NoneType' object has no attribute 'readline'"
                        )

                class W:
                    def __iter__(self_w):
                        return broken()

                    def close(self_w):
                        real.close()

                return W()
            return self.fc.watch(rd, namespace, label_selector,
                                 resource_version=resource_version)

    backend = RaisingOnce(fc)
    inf = Informer(backend, COMPUTE_DOMAINS)
    inf.resync_backoff = 0.05
    inf.start()
    assert inf.wait_for_sync()

    # First event arrives, then the stream raises; the informer must
    # reconnect and keep converging on later events.
    cds.create(cd_obj("first"))
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline and not inf.get("first", "default"):
        time.sleep(0.02)
    assert inf.get("first", "default") is not None

    cds.create(cd_obj("after-crash"))
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline and not inf.get("after-crash", "default"):
        time.sleep(0.02)
    assert inf.get("after-crash", "default") is not None, (
        "informer thread died on the raising stream instead of resyncing"
    )
    inf.stop()


def test_list_page_chunks_and_expires_tokens(fc, cds, monkeypatch):
    """FakeCluster.list_page: limit/continue chunking with stable key
    order, and genuine token expiry — a continue token whose
    resourceVersion predates the retained event window raises ApiGone
    (the 410 a real apiserver answers after etcd compaction)."""
    from tpu_dra.k8sclient import COMPUTE_DOMAINS
    from tpu_dra.k8sclient.resources import ApiGone

    for i in range(7):
        cds.create(cd_obj(name=f"cd-{i}"))
    items, meta = fc.list_page(COMPUTE_DOMAINS, "default", limit=3)
    assert [o["metadata"]["name"] for o in items] == ["cd-0", "cd-1", "cd-2"]
    assert meta["continue"]
    items2, meta2 = fc.list_page(
        COMPUTE_DOMAINS, "default", limit=3, continue_token=meta["continue"]
    )
    assert [o["metadata"]["name"] for o in items2] == ["cd-3", "cd-4", "cd-5"]
    items3, meta3 = fc.list_page(
        COMPUTE_DOMAINS, "default", limit=3, continue_token=meta2["continue"]
    )
    assert [o["metadata"]["name"] for o in items3] == ["cd-6"]
    assert "continue" not in meta3

    # Age the first token out of the (shrunken) event window.
    fc._event_log = type(fc._event_log)(fc._event_log, maxlen=4)
    for i in range(7):
        cds.delete(f"cd-{i}", "default")
    with pytest.raises(ApiGone):
        fc.list_page(
            COMPUTE_DOMAINS, "default", limit=3, continue_token=meta["continue"]
        )
