"""CEL evaluator tests.

Anchored on the REAL expressions this driver ships: every DeviceClass
selector in deployments/helm/tpu-dra-driver/templates/deviceclasses.yaml,
the demo claim selectors in demo/specs/selectors/claims.yaml, and the
chart's ValidatingAdmissionPolicy expressions — plus the grammar corners
(optionals, ternary, quantities) those rely on. Reference analog: the
cel-go environments in vendor/k8s.io/dynamic-resource-allocation/cel and
the apiserver's VAP evaluator, which the reference driver inherits.
"""

import pytest

from tpu_dra.infra.cel import CelError, CelOptional, evaluate


def device_env(driver="tpu.google.com", attrs=None, capacity=None):
    return {
        "device": {
            "driver": driver,
            "attributes": {driver: attrs or {}},
            "capacity": {driver: capacity or {}},
        }
    }


# --- the chart's DeviceClass selectors, verbatim ---

TPU_CLASS = (
    "device.driver == 'tpu.google.com' && "
    "device.attributes['tpu.google.com'].type == 'tpu'"
)
SUBSLICE_CLASS = (
    "device.driver == 'tpu.google.com' && "
    "device.attributes['tpu.google.com'].type.startsWith('subslice')"
)
CHANNEL_CLASS = (
    "device.driver == 'compute-domain.tpu.google.com' && "
    "device.attributes['compute-domain.tpu.google.com'].type == 'channel'"
)


def test_tpu_deviceclass_selector():
    assert evaluate(TPU_CLASS, device_env(attrs={"type": "tpu"})) is True
    assert evaluate(TPU_CLASS, device_env(attrs={"type": "subslice-static"})) is False
    assert (
        evaluate(TPU_CLASS, device_env(driver="other.dev", attrs={"type": "tpu"}))
        is False
    )


def test_subslice_deviceclass_selector_startswith():
    for t, want in [
        ("subslice-static", True),
        ("subslice-dynamic", True),
        ("tpu", False),
    ]:
        env = device_env(attrs={"type": t})
        # attribute map is keyed by driver; selector must only see its own
        assert evaluate(SUBSLICE_CLASS, env) is want


def test_channel_deviceclass_selector():
    env = device_env(
        driver="compute-domain.tpu.google.com", attrs={"type": "channel"}
    )
    assert evaluate(CHANNEL_CLASS, env) is True


# --- demo claim selectors ---

def test_demo_generation_selector():
    expr = 'device.attributes["tpu.google.com"].generation == "v5e"'
    assert evaluate(expr, device_env(attrs={"generation": "v5e"})) is True
    assert evaluate(expr, device_env(attrs={"generation": "v5p"})) is False


def test_demo_subslice_shape_selector():
    expr = 'device.attributes["tpu.google.com"].subsliceShape == "2x1"'
    assert evaluate(expr, device_env(attrs={"subsliceShape": "2x1"})) is True


def test_missing_attribute_is_an_error_not_false():
    """k8s CEL treats a missing attribute as a runtime error (the caller
    decides match semantics), not silent false."""
    expr = 'device.attributes["tpu.google.com"].nonexistent == "x"'
    with pytest.raises(CelError):
        evaluate(expr, device_env(attrs={"type": "tpu"}))


def test_capacity_quantity_comparison():
    expr = (
        "device.capacity['tpu.google.com'].hbm.compareTo(quantity('16Gi')) >= 0"
    )
    env = device_env(capacity={"hbm": None})
    from tpu_dra.infra.cel import CelQuantity

    env["device"]["capacity"]["tpu.google.com"]["hbm"] = CelQuantity("96Gi")
    assert evaluate(expr, env) is True
    env["device"]["capacity"]["tpu.google.com"]["hbm"] = CelQuantity("8Gi")
    assert evaluate(expr, env) is False


# --- the chart's ValidatingAdmissionPolicy expressions, verbatim ---

VAP_MATCH = (
    'request.userInfo.username == '
    '"system:serviceaccount:tpu-dra-driver:tpu-dra-driver-service-account'
    '-kubeletplugin"'
)
VAP_USER_NODE = (
    "request.userInfo.extra[?'authentication.kubernetes.io/node-name'][0]"
    ".orValue('')"
)
VAP_OBJECT_NODE = (
    '(request.operation == "DELETE" ? oldObject : object)'
    '.spec.?nodeName.orValue("")'
)
VAP_MESSAGE = (
    '"the plugin on node \'"+variables.userNodeName+'
    '"\' may not modify resourceslices of other nodes"'
)


def vap_env(username, node, operation="CREATE", obj=None, old=None):
    extra = {}
    if node is not None:
        extra["authentication.kubernetes.io/node-name"] = [node]
    return {
        "request": {
            "userInfo": {"username": username, "extra": extra},
            "operation": operation,
        },
        "object": obj if obj is not None else {},
        "oldObject": old if old is not None else {},
    }


def test_vap_match_condition():
    env = vap_env(
        "system:serviceaccount:tpu-dra-driver:"
        "tpu-dra-driver-service-account-kubeletplugin",
        "node-1",
    )
    assert evaluate(VAP_MATCH, env) is True
    assert evaluate(VAP_MATCH, vap_env("system:serviceaccount:x:y", "n")) is False


def test_vap_user_node_variable_with_optional_chain():
    assert evaluate(VAP_USER_NODE, vap_env("u", "node-7")) == "node-7"
    # Missing extra key -> absent optional -> orValue default.
    assert evaluate(VAP_USER_NODE, vap_env("u", None)) == ""


def test_vap_object_node_ternary_and_optional_field():
    obj = {"spec": {"nodeName": "node-3"}}
    old = {"spec": {"nodeName": "node-9"}}
    env = vap_env("u", "n", operation="CREATE", obj=obj, old=old)
    assert evaluate(VAP_OBJECT_NODE, env) == "node-3"
    env = vap_env("u", "n", operation="DELETE", obj={}, old=old)
    assert evaluate(VAP_OBJECT_NODE, env) == "node-9"
    # spec present but nodeName absent -> optional default
    env = vap_env("u", "n", obj={"spec": {}})
    assert evaluate(VAP_OBJECT_NODE, env) == ""


def test_vap_validation_and_message_expression():
    env = vap_env("u", "n")
    env["variables"] = {"userNodeName": "node-2", "objectNodeName": "node-5"}
    assert evaluate(
        "variables.userNodeName != ''", env
    ) is True
    assert evaluate(
        "variables.userNodeName == variables.objectNodeName", env
    ) is False
    assert evaluate(VAP_MESSAGE, env) == (
        "the plugin on node 'node-2' may not modify resourceslices of "
        "other nodes"
    )


# --- grammar corners ---

def test_precedence_and_arithmetic():
    assert evaluate("1 + 2 * 3", {}) == 7
    assert evaluate("(1 + 2) * 3", {}) == 9
    assert evaluate("7 / 2", {}) == 3  # int division truncates
    assert evaluate("-7 / 2", {}) == -3  # toward zero, not floor
    assert evaluate("7 % 3", {}) == 1
    assert evaluate("true || false && false", {}) is True  # && binds tighter


def test_short_circuit():
    # RHS would error (undeclared ref); short-circuit avoids it.
    assert evaluate("false && nope.field == 1", {}) is False
    assert evaluate("true || nope.field == 1", {}) is True


def test_in_operator_and_lists():
    assert evaluate("'a' in ['a', 'b']", {}) is True
    assert evaluate("'z' in ['a', 'b']", {}) is False
    assert evaluate("'k' in {'k': 1}", {}) is True
    assert evaluate("size([1, 2, 3])", {}) == 3
    assert evaluate("[1, 2][1]", {}) == 2


def test_string_methods():
    assert evaluate("'hello'.contains('ell')", {}) is True
    assert evaluate("'hello'.endsWith('lo')", {}) is True
    assert evaluate("'hello'.matches('^h.*o$')", {}) is True
    assert evaluate("'hello'.size()", {}) == 5


def test_has_macro():
    env = {"object": {"spec": {"nodeName": "n"}}}
    assert evaluate("has(object.spec.nodeName)", env) is True
    assert evaluate("has(object.spec.other)", env) is False
    assert evaluate("has(object.missing.deeper)", env) is False


def test_comprehension_all_exists():
    """Conformance vectors shaped after cel-spec's macros suite
    (github.com/google/cel-spec tests/simple/testdata/macros.textproto:
    the all/exists/exists_one sections)."""
    assert evaluate("[1, 2, 3].all(x, x > 0)", {}) is True
    assert evaluate("[1, 2, 0].all(x, x > 0)", {}) is False
    assert evaluate("[].all(x, x > 0)", {}) is True
    assert evaluate("[1, 2, 3].exists(x, x == 2)", {}) is True
    assert evaluate("[1, 2, 3].exists(x, x > 10)", {}) is False
    assert evaluate("[].exists(x, true)", {}) is False
    assert evaluate("[1, 2, 3].exists_one(x, x == 2)", {}) is True
    assert evaluate("[1, 2, 2].exists_one(x, x == 2)", {}) is False
    assert evaluate("[1, 2, 3].exists_one(x, x > 10)", {}) is False


def test_comprehension_map_filter():
    assert evaluate("[1, 2, 3].map(x, x * 2)", {}) == [2, 4, 6]
    assert evaluate("[1, 2, 3].map(x, x > 1, x * 2)", {}) == [4, 6]
    assert evaluate("[1, 2, 3, 4].filter(x, x % 2 == 0)", {}) == [2, 4]
    assert evaluate("[].map(x, x)", {}) == []
    # Nesting with distinct variables; inner sees outer's binding.
    assert evaluate(
        "[1, 2].map(x, [10, 20].map(y, x * y))", {}
    ) == [[10, 20], [20, 40]]


def test_comprehension_over_maps_iterates_keys():
    env = {"m": {"a": 1, "b": 2}}
    assert evaluate("m.all(k, m[k] > 0)", env) is True
    assert evaluate("m.exists(k, k == 'b')", env) is True
    assert sorted(evaluate("m.map(k, m[k])", env)) == [1, 2]
    assert evaluate("m.filter(k, m[k] == 2)", env) == ["b"]


def test_comprehension_error_absorption_matches_spec():
    """cel-spec: && / || aggregation over comprehensions is commutative
    over errors — a determining element wins even when another element
    errors; with no determining element the error propagates."""
    # 'x[1] > 0' errors on element 0 ([]) but element [1] determines
    # exists -> true; all -> false via [-1].
    assert evaluate("[[], [1]].exists(x, x[0] > 0)", {}) is True
    assert evaluate("[[], [-1]].all(x, x[0] > 0)", {}) is False
    with pytest.raises(CelError):
        evaluate("[[], [1]].all(x, x[0] > 0)", {})
    with pytest.raises(CelError):
        evaluate("[[], [-1]].exists(x, x[0] > 0)", {})


def test_comprehension_variable_scoping():
    """The iteration variable is lexically scoped: it shadows an outer
    binding inside the macro and is restored after."""
    env = {"x": "outer", "xs": [1, 2]}
    assert evaluate("xs.map(x, x * 10) + [0]", env) == [10, 20, 0]
    assert evaluate("xs.all(x, x > 0) && x == 'outer'", env) is True


def test_comprehension_parse_errors():
    with pytest.raises(CelError):
        evaluate("[1].all(1 + 1, true)", {})  # var must be an identifier
    with pytest.raises(CelError):
        evaluate("[1].all(x)", {})  # missing predicate
    with pytest.raises(CelError):
        evaluate("[1].map(x, true, x, x)", {})  # too many args
    with pytest.raises(CelError):
        evaluate("'str'.all(x, true)", {})  # range must be list/map
    with pytest.raises(CelError):
        evaluate("[1].all(x, x + 1)", {})  # predicate must be bool


def test_optional_indexing_on_lists():
    assert evaluate("[1,2][?5].orValue(-1)", {}) == -1
    assert evaluate("[1,2][?1].orValue(-1)", {}) == 2


def test_type_errors_raise():
    with pytest.raises(CelError):
        evaluate("1 + 'a'", {})
    with pytest.raises(CelError):
        evaluate("!'str'", {})
    with pytest.raises(CelError):
        evaluate("1 < 'a'", {})
    with pytest.raises(CelError):
        evaluate("undeclared_var", {})


def test_raw_python_errors_surface_as_cel_errors():
    """The contract is CelError for ANY evaluation failure — a raw
    ValueError/TypeError would bypass admission failurePolicy and crash
    the scheduler's selector loop."""
    with pytest.raises(CelError):
        evaluate("int('abc')", {})
    with pytest.raises(CelError):
        evaluate("1 in 'abc'", {})
    with pytest.raises(CelError):
        evaluate("{[1]: 2}", {})


def test_quantities():
    assert evaluate("quantity('1Gi').compareTo(quantity('1024Mi'))", {}) == 0
    assert evaluate("quantity('2G').isGreaterThan(quantity('1Gi'))", {}) is True
    assert evaluate("quantity('16Gi').asInteger()", {}) == 16 * 1024**3


def test_optional_value_api():
    opt = CelOptional("x", True)
    assert opt.has_value() and opt.value() == "x"
    absent = CelOptional()
    assert not absent.has_value()
    with pytest.raises(CelError):
        absent.value()
