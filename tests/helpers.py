"""Shared test fixture builders.

Helpers used by more than one test module live here (L500's test-tree
rule: a ``test_*`` module must never import another ``test_*`` module
— that couples collection order and import side effects between
files; see docs/static-analysis.md).
"""

import uuid as uuidlib

from tpu_dra.plugin.device_state import DRIVER_NAME


def make_claim(devices=("tpu-0",), configs=None, uid=None, request="req0"):
    """A minimal allocated ResourceClaim over stub devices."""
    uid = uid or str(uuidlib.uuid4())
    results = [
        {"request": request, "driver": DRIVER_NAME, "pool": "node-0", "device": d}
        for d in devices
    ]
    return {
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceClaim",
        "metadata": {"name": f"claim-{uid[:6]}", "namespace": "default", "uid": uid},
        "status": {
            "allocation": {
                "devices": {"results": results, "config": configs or []}
            }
        },
    }
