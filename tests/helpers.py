"""Shared test fixture builders.

Helpers used by more than one test module live here (L500's test-tree
rule: a ``test_*`` module must never import another ``test_*`` module
— that couples collection order and import side effects between
files; see docs/static-analysis.md).
"""

import uuid as uuidlib

from tpu_dra.plugin.device_state import DRIVER_NAME


def make_claim(devices=("tpu-0",), configs=None, uid=None, request="req0"):
    """A minimal allocated ResourceClaim over stub devices."""
    uid = uid or str(uuidlib.uuid4())
    results = [
        {"request": request, "driver": DRIVER_NAME, "pool": "node-0", "device": d}
        for d in devices
    ]
    return {
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceClaim",
        "metadata": {"name": f"claim-{uid[:6]}", "namespace": "default", "uid": uid},
        "status": {
            "allocation": {
                "devices": {"results": results, "config": configs or []}
            }
        },
    }


# --- elastic-repacker harness (shared by test_repacker + test_trace) ---------

REPACK_NS = "default"


def make_repack_cluster(nodes=2):
    """A small published fleet (classes + per-node slices) on a fresh
    FakeCluster — the repacker drills' starting state."""
    import json

    from tpu_dra.k8sclient import (
        DEVICE_CLASSES, RESOURCE_SLICES, FakeCluster, ResourceClient,
    )
    from tpu_dra.scheduler import fleet

    cluster = FakeCluster()
    classes = ResourceClient(cluster, DEVICE_CLASSES)
    for c in fleet.CLASSES:
        classes.create(json.loads(json.dumps(c)))
    slices = ResourceClient(cluster, RESOURCE_SLICES)
    for i in range(nodes):
        slices.create(fleet.make_node_slice(i))
    return cluster


def place_claim(cluster, i, node_idx, dev, shape="1x1x1"):
    """Create claim i allocated to one named sub-slice device — precise
    placement control the scheduler's packer would refuse to produce."""
    from tpu_dra.k8sclient import RESOURCE_CLAIMS, ResourceClient
    from tpu_dra.scheduler import fleet

    claims = ResourceClient(cluster, RESOURCE_CLAIMS)
    c = fleet.make_claim(i, shape)
    c["metadata"]["namespace"] = REPACK_NS
    c["status"] = {"allocation": {"devices": {"results": [{
        "request": "tpu", "driver": fleet.DRIVER,
        "pool": fleet.node_name(node_idx), "device": dev,
    }]}}}
    claims.create(c)
    claims.update_status(c)
    return c["metadata"]["name"]


def spread_two_residents(cluster):
    """One 1x1 resident per node: 6 free chips, no 2x2 reachable —
    frag 1 - 4/6. The canonical improvable state."""
    a = place_claim(cluster, 0, 0, "ss-1x1x1-0-0-0")
    b = place_claim(cluster, 1, 1, "ss-1x1x1-0-0-0")
    return a, b


def get_claim(cluster, name):
    from tpu_dra.k8sclient import RESOURCE_CLAIMS, ResourceClient

    return ResourceClient(cluster, RESOURCE_CLAIMS).try_get(
        name, REPACK_NS
    )


class RecordingRepackAdapter:
    """ServingAdapter stand-in that records the drain/rebind protocol."""

    def __init__(self, drain_ready=True):
        self.drain_ready = drain_ready
        self.calls = []

    def begin_drain(self, key):
        self.calls.append(("begin_drain", key))

    def drain_done(self, key):
        return self.drain_ready

    def finish_drain(self, key):
        self.calls.append(("finish_drain", key))
        return 1

    def rebind(self, key, claim):
        self.calls.append(("rebind", key))

    def abort(self, key):
        self.calls.append(("abort", key))


def make_repacker(cluster, adapter=None, clock=None, metrics=None, **cfg):
    import time as _time

    from tpu_dra.infra.metrics import Metrics
    from tpu_dra.scheduler.repacker import Repacker, RepackerConfig

    defaults = dict(
        poll_period=0.0, frag_threshold=0.05,
        min_disruption_interval_seconds=0.0,
    )
    defaults.update(cfg)
    return Repacker(
        cluster, RepackerConfig(**defaults),
        serving=adapter, metrics=metrics or Metrics(),
        clock=clock or _time.monotonic,
    )
