"""tpulib tests: placement algebra (native/Python parity), stub backend
lifecycle, persistence, and linux-backend enumeration against a fabricated
sysfs tree (the fake-hardware seam the reference lacks, SURVEY.md §4.1)."""

import os

import pytest

from tpu_dra.tpulib import native, new_tpulib
from tpu_dra.tpulib.interface import TpuLibError
from tpu_dra.tpulib.linux import LinuxTpuLib
from tpu_dra.tpulib.stub import StubTpuLib
from tpu_dra.tpulib.types import (
    GENERATIONS,
    ChipHealthEvent,
    Placement,
    SubsliceShape,
    TopologyCoord,
    parse_topology,
    topology_str,
)


# --- topology primitives ----------------------------------------------------


def test_parse_topology():
    assert parse_topology("4x4") == (4, 4, 1)
    assert parse_topology("2x2x2") == (2, 2, 2)
    assert topology_str((4, 4, 1)) == "4x4"
    assert topology_str((2, 2, 2)) == "2x2x2"
    for bad in ("", "4", "0x2", "2x-1", "axb"):
        with pytest.raises(ValueError):
            parse_topology(bad)


def test_accelerator_type_counts_cores():
    assert GENERATIONS["v5p"].accelerator_type(8) == "v5p-16"  # 2 cores/chip
    assert GENERATIONS["v5e"].accelerator_type(4) == "v5e-4"  # 1 core/chip


# --- placement allocator: native + python parity ----------------------------

CASES = [
    ((2, 2, 1), (1, 1, 1)),
    ((2, 2, 1), (1, 2, 1)),
    ((2, 2, 1), (2, 2, 1)),
    ((4, 4, 4), (2, 2, 2)),
    ((4, 4, 1), (2, 1, 1)),
]


@pytest.mark.parametrize("mesh,shape", CASES)
def test_placement_enumeration_parity(mesh, shape):
    py = native._py_enumerate_placements(mesh, shape)
    assert native.enumerate_placements(mesh, shape) == py
    # aligned, in-bounds, non-overlapping tiling
    for x, y, z in py:
        assert x % shape[0] == 0 and y % shape[1] == 0 and z % shape[2] == 0
        assert x + shape[0] <= mesh[0]
    n_cover = len(py) * shape[0] * shape[1] * shape[2]
    assert n_cover <= mesh[0] * mesh[1] * mesh[2]


def test_native_lib_is_loaded():
    # The build must actually exercise the C++ path in this environment.
    assert native.native_available(), "native/build/libtputopo.so missing — run make -C native"


def test_placement_enumeration_invalid():
    with pytest.raises(ValueError):
        native.enumerate_placements((2, 2, 1), (3, 1, 1))
    with pytest.raises(ValueError):
        native.enumerate_placements((0, 2, 1), (1, 1, 1))


def test_placement_free_parity():
    mesh, shape = (2, 2, 1), (1, 2, 1)
    busy = [False, True, False, False]  # chip (1,0,0) busy
    for start in ((0, 0, 0), (1, 0, 0)):
        assert native.placement_free(mesh, shape, start, busy) == \
            native._py_placement_free(mesh, shape, start, busy)
    assert native.placement_free(mesh, shape, (0, 0, 0), busy) is True
    assert native.placement_free(mesh, shape, (1, 0, 0), busy) is False
    with pytest.raises(ValueError):
        native.placement_free(mesh, (1, 1, 1), (2, 0, 0), busy)  # oob
    with pytest.raises(ValueError):
        native.placement_free(mesh, (2, 2, 1), (1, 0, 0), busy)  # misaligned


# --- stub backend -----------------------------------------------------------


def make_stub(tmp_path=None, **cfg):
    cfg.setdefault("generation", "v5e")
    cfg.setdefault("hostname", "test-host-0")
    return StubTpuLib(
        config=cfg, state_dir=str(tmp_path / "state") if tmp_path else None
    )


def test_stub_enumeration_defaults():
    lib = make_stub()
    chips = lib.chips()
    assert len(chips) == 4
    assert chips[0].generation.name == "v5e"
    assert chips[0].hbm_bytes == 16 * 1024**3
    assert chips[0].dev_paths == ["/dev/accel0"]
    # Stable UUIDs across re-enumeration (handle-cache invariant analog).
    lib2 = make_stub()
    assert [c.uuid for c in lib2.chips()] == [c.uuid for c in chips]
    coords = {c.coord for c in chips}
    assert coords == {TopologyCoord(x, y, 0) for x in (0, 1) for y in (0, 1)}


def test_stub_slice_identity():
    lib = make_stub(
        slice={"uuid": "s" * 8, "topology": "4x4", "num_hosts": 4, "worker_id": 2}
    )
    ici = lib.ici_domain()
    assert ici.clique_id() == f"{'s'*8}.0"
    assert ici.topology == (4, 4, 1)
    assert all(c.worker_id == 2 for c in lib.chips())


def test_subslice_lifecycle(tmp_path):
    lib = make_stub(tmp_path)
    shape = SubsliceShape.parse("1x2")
    placements = lib.possible_placements(shape)
    assert len(placements) == 2
    ss = lib.create_subslice(placements[0])
    assert ss.placement.shape.chip_count == 2
    assert len(ss.parent_chip_uuids) == 2
    assert ss.runtime_env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,2,1"
    assert ss.runtime_env["TPU_VISIBLE_DEVICES"].count(",") == 1
    assert ss.hbm_bytes == 2 * 16 * 1024**3

    # Overlap rejected while live (validateNoOverlapping analog at lib level)
    with pytest.raises(TpuLibError, match="overlaps"):
        lib.create_subslice(placements[0])
    # Disjoint placement fine
    ss2 = lib.create_subslice(placements[1])
    assert {s.uuid for s in lib.list_subslices()} == {ss.uuid, ss2.uuid}

    lib.delete_subslice(ss.uuid)
    assert {s.uuid for s in lib.list_subslices()} == {ss2.uuid}
    with pytest.raises(TpuLibError, match="unknown"):
        lib.delete_subslice(ss.uuid)
    # Freed coordinates immediately reusable
    lib.create_subslice(placements[0])


def test_subslice_persistence_survives_restart(tmp_path):
    lib = make_stub(tmp_path)
    ss = lib.create_subslice(lib.possible_placements(SubsliceShape.parse("2x2"))[0])
    # New instance, same state dir: the startup-obliteration data source.
    lib2 = make_stub(tmp_path)
    live = lib2.list_subslices()
    assert [s.uuid for s in live] == [ss.uuid]
    assert live[0].runtime_env == ss.runtime_env
    lib2.delete_subslice(ss.uuid)
    lib3 = make_stub(tmp_path)
    assert lib3.list_subslices() == []


def test_unhealthy_chip_blocks_subslice():
    lib = make_stub()
    victim = lib.chips()[0]
    lib.inject_health_event(
        ChipHealthEvent(chip_uuid=victim.uuid, healthy=False, reason="ici error")
    )
    ev = lib.health_events().get_nowait()
    assert ev.chip_uuid == victim.uuid and not ev.healthy
    with pytest.raises(TpuLibError, match="unhealthy"):
        lib.create_subslice(
            Placement(TopologyCoord(0, 0, 0), SubsliceShape.parse("1x1"))
        )


def test_time_slice_knob():
    lib = make_stub()
    uuids = [c.uuid for c in lib.chips()[:2]]
    lib.set_time_slice(uuids, 2)
    assert lib.get_time_slice(uuids[0]) == 2
    assert lib.get_time_slice(lib.chips()[3].uuid) is None
    with pytest.raises(TpuLibError):
        lib.set_time_slice(["nope"], 1)
    with pytest.raises(TpuLibError):
        lib.set_time_slice(uuids, -1)


def test_fault_injection():
    lib = make_stub(fail={"create_subslice": "boom"})
    with pytest.raises(TpuLibError, match="injected fault: boom"):
        lib.create_subslice(
            Placement(TopologyCoord(0, 0, 0), SubsliceShape.parse("1x1"))
        )


def test_factory_selects_stub():
    lib = new_tpulib("stub", config={"generation": "v5p"})
    assert lib.generation().name == "v5p"
    with pytest.raises(ValueError):
        new_tpulib("banana")


# --- linux backend against fabricated sysfs ---------------------------------


def fabricate_sysfs(root, n_chips=4, device_id="0x0063", vendor="0x1ae0"):
    base = root / "sys" / "bus" / "pci" / "devices"
    for i in range(n_chips):
        addr = f"0000:0{i}:00.0"
        d = base / addr
        real = root / "sys" / "devices" / f"pci0000:0{i}" / addr
        real.mkdir(parents=True)
        (real / "vendor").write_text(vendor + "\n")
        (real / "device").write_text(device_id + "\n")
        (real / "numa_node").write_text(f"{i // 2}\n")
        base.mkdir(parents=True, exist_ok=True)
        os.symlink(real, d)
        drv = root / "sys" / "bus" / "pci" / "drivers" / "google-tpu"
        drv.mkdir(parents=True, exist_ok=True)
        os.symlink(drv, real / "driver")
        grp = root / "sys" / "kernel" / "iommu_groups" / str(10 + i)
        grp.mkdir(parents=True, exist_ok=True)
        os.symlink(grp, real / "iommu_group")
    dev = root / "dev"
    dev.mkdir(exist_ok=True)
    for i in range(n_chips):
        (dev / f"accel{i}").touch()
    return str(root / "sys"), str(dev)


def test_linux_enumeration(tmp_path):
    sysfs, dev = fabricate_sysfs(tmp_path)
    lib = LinuxTpuLib(sysfs_root=sysfs, dev_root=dev, env={})
    chips = lib.chips()
    assert len(chips) == 4
    assert lib.generation().name == "v5e"
    assert chips[0].pci_bus_id == "0000:00:00.0"
    assert chips[0].dev_paths == ["/dev/accel0"]
    assert chips[0].numa_node == 0 and chips[3].numa_node == 1
    assert chips[0].iommu_group == 10
    assert chips[0].vfio_capable
    assert chips[0].pcie_root == "pci0000:00"
    assert lib.ici_domain() is None  # no slice env -> single-host


def test_linux_slice_env(tmp_path):
    sysfs, dev = fabricate_sysfs(tmp_path, device_id="0x0062")  # v5p
    env = {
        "TPU_WORKER_ID": "1",
        "TPU_WORKER_HOSTNAMES": "h0,h1",
        "TPU_TOPOLOGY": "2x2x2",
        "TPU_ACCELERATOR_TYPE": "v5p-16",
    }
    lib = LinuxTpuLib(sysfs_root=sysfs, dev_root=dev, env=env)
    assert lib.generation().name == "v5p"
    ici = lib.ici_domain()
    assert ici is not None and ici.topology == (2, 2, 2)
    assert all(c.worker_id == 1 for c in lib.chips())
    # Identity is stable across hosts: same hostnames -> same slice uuid.
    lib2 = LinuxTpuLib(sysfs_root=sysfs, dev_root=dev, env={**env, "TPU_WORKER_ID": "0"})
    assert lib2.ici_domain().slice_uuid == ici.slice_uuid


def test_linux_no_devices_errors(tmp_path):
    (tmp_path / "sys").mkdir()
    with pytest.raises(TpuLibError, match="no Google TPU PCI functions"):
        LinuxTpuLib(sysfs_root=str(tmp_path / "sys"), dev_root="/dev", env={})


def test_linux_ignores_foreign_vendor(tmp_path):
    sysfs, dev = fabricate_sysfs(tmp_path, n_chips=2, vendor="0x10de")
    with pytest.raises(TpuLibError):
        LinuxTpuLib(sysfs_root=sysfs, dev_root=dev, env={})


def test_pci_scan_native_python_parity(tmp_path):
    sysfs, _ = fabricate_sysfs(tmp_path)
    native_result = native.pci_scan(sysfs)
    py_result = native._py_pci_scan(sysfs)
    assert native_result == py_result
    assert len(native_result) == 4
    assert native_result[0]["driver"] == "google-tpu"


def test_degenerate_shape_rejected_not_crash():
    """A zero-extent shape must raise, not SIGFPE/ZeroDivisionError."""
    mesh, busy = (2, 2, 1), [False] * 4
    with pytest.raises(ValueError):
        native.placement_free(mesh, (1, 0, 1), (0, 0, 0), busy)
    with pytest.raises(ValueError):
        native._py_placement_free(mesh, (0, 1, 1), (0, 0, 0), busy)
    lib = make_stub()
    with pytest.raises(TpuLibError):
        lib.create_subslice(Placement(TopologyCoord(0, 0, 0), SubsliceShape((1, 0, 1))))


def test_linux_health_poller_detects_and_recovers(tmp_path):
    """The sysfs poller (XID event-stream analog) emits unhealthy on accel
    node disappearance and healthy on recovery."""
    import shutil

    sysfs, dev = fabricate_sysfs(tmp_path)
    lib = LinuxTpuLib(sysfs_root=sysfs, dev_root=dev, env={})
    chip = lib.chips()[0]
    assert chip.healthy
    # Remove the chip's accel node; a probe must flag it.
    node = tmp_path / "dev" / "accel0"
    node.unlink()
    healthy, reason = lib._probe_chip(chip)
    assert not healthy and reason == "accel-node-vanished"
    from tpu_dra.tpulib.types import ChipHealthEvent

    lib.inject_health_event(
        ChipHealthEvent(chip_uuid=chip.uuid, healthy=False, reason=reason)
    )
    assert not lib.chips()[0].healthy
    ev = lib.health_events().get_nowait()
    assert ev.reason == "accel-node-vanished"
    # Node returns -> probe recovers.
    node.touch()
    healthy, reason = lib._probe_chip(chip)
    assert healthy
    # PCI function vanishing is also a fault.
    shutil.rmtree(tmp_path / "sys" / "devices" / "pci0000:00" / "0000:00:00.0")
    (tmp_path / "sys" / "bus" / "pci" / "devices" / "0000:00:00.0").unlink()
    healthy, reason = lib._probe_chip(chip)
    assert not healthy and reason == "pci-device-vanished"


def test_linux_health_poller_thread_lifecycle(tmp_path):
    import time

    sysfs, dev = fabricate_sysfs(tmp_path)
    lib = LinuxTpuLib(sysfs_root=sysfs, dev_root=dev, env={})
    lib.start_health_monitor(period=0.05)
    (tmp_path / "dev" / "accel1").unlink()
    ev = lib.health_events().get(timeout=5)
    assert ev.healthy is False
    assert ev.chip_uuid == lib.chips()[1].uuid
    # Recovery event after the node returns.
    (tmp_path / "dev" / "accel1").touch()
    ev = lib.health_events().get(timeout=5)
    assert ev.healthy is True and ev.reason == "recovered"
    lib.stop_health_monitor()


def test_linux_health_probe_vfio_and_unbound(tmp_path):
    """Passthrough chips (bound to vfio-pci) are not flagged; chips the
    accel driver never bound are unhealthy until claimed."""
    import os as _os

    sysfs, dev = fabricate_sysfs(tmp_path)
    # Chip 3 has no accel node (driver failed to bind it).
    (tmp_path / "dev" / "accel3").unlink()
    lib = LinuxTpuLib(sysfs_root=sysfs, dev_root=dev, env={})
    unbound = lib.chips()[3]
    assert unbound.dev_paths == []
    healthy, reason = lib._probe_chip(unbound)
    assert not healthy and reason == "accel-node-missing"
    # Rebind chip 3 to vfio-pci: intentionally detached -> healthy.
    real = tmp_path / "sys" / "devices" / "pci0000:03" / "0000:03:00.0"
    _os.unlink(real / "driver")
    vfio_drv = tmp_path / "sys" / "bus" / "pci" / "drivers" / "vfio-pci"
    vfio_drv.mkdir(parents=True, exist_ok=True)
    _os.symlink(vfio_drv, real / "driver")
    healthy, reason = lib._probe_chip(unbound)
    assert healthy


def test_benign_health_event_does_not_poison_chip_state():
    """Benign-reason unhealthy events (the XID skip-list analog) are
    queued for observability but never flip ChipInfo.healthy — otherwise
    a later unrelated recompute would unpublish a healthy chip."""
    lib = make_stub()
    victim = lib.chips()[0]
    lib.inject_health_event(
        ChipHealthEvent(
            chip_uuid=victim.uuid, healthy=False, reason="clock-throttle"
        )
    )
    ev = lib.health_events().get_nowait()
    assert ev.reason == "clock-throttle" and not ev.healthy
    assert lib.chips()[0].healthy is True
    # Real faults still mark.
    lib.inject_health_event(
        ChipHealthEvent(chip_uuid=victim.uuid, healthy=False, reason="hw")
    )
    assert lib.chips()[0].healthy is False


def test_stub_health_file_channel(tmp_path):
    """The stub's cross-process injection channel: a separate process
    (e2e runner, kind demo) drops JSON files under
    <state_dir>/health-events/ to break/heal fake chips."""
    import json
    import os
    import time

    lib = make_stub(tmp_path)
    lib.start_health_monitor(period=0.05)
    try:
        events_dir = tmp_path / "state" / "health-events"
        assert events_dir.is_dir()
        (events_dir / "ev1.json").write_text(
            json.dumps({"chip_index": 1, "healthy": False, "reason": "hbm"})
        )
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and lib.chips()[1].healthy:
            time.sleep(0.02)
        assert lib.chips()[1].healthy is False
        assert not (events_dir / "ev1.json").exists()  # consumed
        ev = lib.health_events().get_nowait()
        assert ev.chip_uuid == lib.chips()[1].uuid and ev.reason == "hbm"
        # Heal by uuid.
        (events_dir / "ev2.json").write_text(
            json.dumps({"chip_uuid": lib.chips()[1].uuid, "healthy": True})
        )
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not lib.chips()[1].healthy:
            time.sleep(0.02)
        assert lib.chips()[1].healthy is True
    finally:
        lib.stop_health_monitor()
