"""API-layer tests.

Reference analogs: api/nvidia.com/resource/v1beta1/sharing_test.go
(per-device limit normalization) plus decoder strict/nonstrict behavior
(api.go:46-98).
"""

import json

import pytest

from tpu_dra import api
from tpu_dra.api import (
    ComputeDomain,
    ComputeDomainChannelConfig,
    ComputeDomainDaemonConfig,
    MultiplexingConfig,
    PerProcessHbmLimit,
    Quantity,
    TpuConfig,
    TpuSubsliceConfig,
    VfioDeviceConfig,
    default_tpu_config,
)
from tpu_dra.api.serde import ApiError, DecodeError
from tpu_dra.api.sharing import InvalidDeviceSelector, time_slice_ordinal
from tpu_dra.infra import featuregates as fg

CD_UID = "8d7d6d3e-1111-4222-8333-444455556666"


def gates(**kwargs):
    g = fg.FeatureGates()
    for k, v in kwargs.items():
        g.set(k, v)
    fg.reset_for_tests(g)


# --- Quantity grammar -------------------------------------------------------


@pytest.mark.parametrize(
    "raw,expect",
    [
        ("1", 1),
        ("1Ki", 1024),
        ("4Gi", 4 * 2**30),
        ("1G", 10**9),
        ("2.5Gi", int(2.5 * 2**30)),
        ("500m", 1),  # milli rounds up for byte consumption
    ],
)
def test_quantity_parse(raw, expect):
    assert Quantity.parse(raw).to_bytes() == expect


def test_quantity_invalid():
    with pytest.raises(ValueError):
        Quantity.parse("4GiB")
    with pytest.raises(ValueError):
        Quantity.parse("banana")


def test_quantity_compare():
    assert Quantity.parse("1Gi") > Quantity.parse("1G")
    assert Quantity.parse("1024") == Quantity.parse("1Ki")


# --- decoders ---------------------------------------------------------------


def _tpu_config_json(extra=None):
    d = {
        "apiVersion": "resource.tpu.google.com/v1beta1",
        "kind": "TpuConfig",
        "sharing": {"strategy": "Multiplexing"},
    }
    if extra:
        d.update(extra)
    return json.dumps(d)


def test_strict_decode_round_trip():
    obj = api.strict_decode(_tpu_config_json())
    assert isinstance(obj, TpuConfig)
    assert obj.sharing.is_multiplexing()
    re = api.strict_decode(api.encode(obj))
    assert re == obj


def test_strict_decoder_rejects_unknown_fields():
    with pytest.raises(DecodeError, match="unknown field"):
        api.strict_decode(_tpu_config_json({"futureField": 1}))


def test_nonstrict_decoder_drops_unknown_fields():
    # Down/upgrade safety: checkpoint JSON from a newer driver decodes.
    obj = api.nonstrict_decode(_tpu_config_json({"futureField": 1}))
    assert isinstance(obj, TpuConfig)


def test_nested_unknown_fields_respect_strictness():
    d = {
        "apiVersion": "resource.tpu.google.com/v1beta1",
        "kind": "TpuConfig",
        "sharing": {"strategy": "Multiplexing", "zap": True},
    }
    with pytest.raises(DecodeError):
        api.strict_decode(json.dumps(d))
    assert api.nonstrict_decode(json.dumps(d)).sharing.is_multiplexing()


def test_decode_unknown_kind():
    with pytest.raises(DecodeError, match="no kind"):
        api.strict_decode(
            json.dumps(
                {"apiVersion": "resource.tpu.google.com/v1beta1", "kind": "Nope"}
            )
        )


def test_decode_missing_type_meta():
    with pytest.raises(DecodeError):
        api.strict_decode(json.dumps({"sharing": None}))


# --- TpuConfig normalize/validate ------------------------------------------


def test_default_config_plain_without_gates():
    cfg = default_tpu_config()
    assert cfg.sharing is None
    cfg.normalize()
    cfg.validate()
    assert cfg.sharing is None


def test_default_config_with_timeslicing_gate():
    gates(TimeSlicingSettings=True)
    cfg = default_tpu_config()
    assert cfg.sharing.is_time_slicing()
    cfg.normalize()
    cfg.validate()
    assert cfg.sharing.time_slicing_config.interval == "Default"


def test_multiplexing_requires_gate():
    cfg = api.strict_decode(_tpu_config_json())
    cfg.normalize()
    with pytest.raises(ApiError, match="MultiplexingSupport"):
        cfg.validate()
    gates(MultiplexingSupport=True)
    cfg2 = api.strict_decode(_tpu_config_json())
    cfg2.normalize()
    cfg2.validate()
    # normalize under the gate fills an empty multiplexing config
    assert cfg2.sharing.multiplexing_config is not None


def test_sharing_strategy_mutual_exclusion():
    gates(MultiplexingSupport=True, TimeSlicingSettings=True)
    d = {
        "apiVersion": "resource.tpu.google.com/v1beta1",
        "kind": "TpuConfig",
        "sharing": {
            "strategy": "Multiplexing",
            "timeSlicingConfig": {"interval": "Short"},
        },
    }
    cfg = api.strict_decode(json.dumps(d))
    with pytest.raises(ApiError, match="timeSlicingConfig invalid"):
        cfg.validate()


def test_invalid_interval_rejected():
    gates(TimeSlicingSettings=True)
    d = {
        "apiVersion": "resource.tpu.google.com/v1beta1",
        "kind": "TpuConfig",
        "sharing": {
            "strategy": "TimeSlicing",
            "timeSlicingConfig": {"interval": "Banana"},
        },
    }
    cfg = api.strict_decode(json.dumps(d))
    with pytest.raises(ApiError, match="interval"):
        cfg.validate()


def test_time_slice_ordinals():
    assert time_slice_ordinal("Default") == 0
    assert time_slice_ordinal("Short") == 1
    assert time_slice_ordinal("Medium") == 2
    assert time_slice_ordinal("Long") == 3
    assert time_slice_ordinal("X") == -1


# --- per-device HBM limit normalization (sharing_test.go analog) ------------

UUIDS = ["tpu-aaa", "tpu-bbb", "tpu-ccc"]


def test_limits_default_applied_to_all():
    mc = MultiplexingConfig(default_hbm_limit=Quantity.parse("4Gi"))
    assert mc.normalized_limits(UUIDS) == {u: "4Gi" for u in UUIDS}


def test_limits_per_device_overrides_default():
    mc = MultiplexingConfig(
        default_hbm_limit=Quantity.parse("4Gi"),
        default_per_device_hbm_limit=PerProcessHbmLimit.from_dict(
            {"1": "2Gi", "tpu-ccc": "1Gi"}
        ),
    )
    assert mc.normalized_limits(UUIDS) == {
        "tpu-aaa": "4Gi",
        "tpu-bbb": "2Gi",
        "tpu-ccc": "1Gi",
    }


def test_limits_no_default_only_selected_devices():
    mc = MultiplexingConfig(
        default_per_device_hbm_limit=PerProcessHbmLimit.from_dict({"0": "2Gi"})
    )
    assert mc.normalized_limits(UUIDS) == {"tpu-aaa": "2Gi"}


def test_limits_invalid_selector():
    mc = MultiplexingConfig(
        default_per_device_hbm_limit=PerProcessHbmLimit.from_dict({"9": "2Gi"})
    )
    with pytest.raises(InvalidDeviceSelector):
        mc.normalized_limits(UUIDS)
    mc2 = MultiplexingConfig(
        default_per_device_hbm_limit=PerProcessHbmLimit.from_dict({"tpu-zzz": "2Gi"})
    )
    with pytest.raises(InvalidDeviceSelector):
        mc2.normalized_limits(UUIDS)


def test_multiplexing_validate_bounds():
    gates(MultiplexingSupport=True)
    MultiplexingConfig(default_compute_share_percentage=50).validate()
    with pytest.raises(ApiError):
        MultiplexingConfig(default_compute_share_percentage=0).validate()
    with pytest.raises(ApiError):
        MultiplexingConfig(default_compute_share_percentage=101).validate()


# --- subslice + vfio + CD configs ------------------------------------------


def test_subslice_config_accepts_timeslicing_noop():
    cfg = TpuSubsliceConfig.from_dict(
        {"sharing": {"strategy": "TimeSlicing"}}, strict=True
    )
    cfg.normalize()
    cfg.validate()  # no-op accepted for reference parity


def test_vfio_config_roundtrip():
    obj = api.strict_decode(
        json.dumps(
            {
                "apiVersion": "resource.tpu.google.com/v1beta1",
                "kind": "VfioDeviceConfig",
            }
        )
    )
    assert isinstance(obj, VfioDeviceConfig)
    obj.normalize()
    obj.validate()


def test_channel_config_validation():
    cfg = ComputeDomainChannelConfig(domain_id=CD_UID, allocation_mode="Single")
    cfg.validate()
    with pytest.raises(ApiError, match="domainID"):
        ComputeDomainChannelConfig(domain_id="").validate()
    with pytest.raises(ApiError, match="UUID"):
        ComputeDomainChannelConfig(domain_id="not-a-uuid").validate()
    with pytest.raises(ApiError, match="allocationMode"):
        ComputeDomainChannelConfig(domain_id=CD_UID, allocation_mode="Some").validate()


def test_daemon_config_validation():
    ComputeDomainDaemonConfig(domain_id=CD_UID).validate()
    with pytest.raises(ApiError):
        ComputeDomainDaemonConfig(domain_id="").validate()


def test_channel_config_missing_required_field():
    with pytest.raises(DecodeError, match="domainID"):
        ComputeDomainChannelConfig.from_dict({}, strict=True)


# --- CRD round-trip ---------------------------------------------------------


def test_computedomain_crd_roundtrip():
    d = {
        "apiVersion": "resource.tpu.google.com/v1beta1",
        "kind": "ComputeDomain",
        "metadata": {"name": "cd1", "namespace": "default", "uid": CD_UID},
        "spec": {
            "numNodes": 4,
            "topology": "4x4",
            "acceleratorType": "v5p-16",
            "channel": {
                "resourceClaimTemplate": {"name": "cd1-channel"},
                "allocationMode": "Single",
            },
        },
        "status": {
            "status": "Ready",
            "nodes": [
                {"name": "n0", "ipAddress": "10.0.0.1", "cliqueID": "s1.0",
                 "index": 0, "status": "Ready"}
            ],
        },
    }
    cd = api.strict_decode(json.dumps(d))
    assert isinstance(cd, ComputeDomain)
    assert cd.spec.num_nodes == 4
    assert cd.spec.channel.resource_claim_template.name == "cd1-channel"
    assert cd.status.nodes[0].clique_id == "s1.0"
    cd2 = api.strict_decode(api.encode(cd))
    assert cd2 == cd


# --- review-hardening regressions ------------------------------------------


def test_negative_milli_limit_rejected():
    from tpu_dra.api.errors import ApiError as AE

    mc = MultiplexingConfig(default_hbm_limit=Quantity.parse("-500m"))
    with pytest.raises(AE):
        mc.validate()


def test_quantity_error_is_api_error():
    from tpu_dra.api.errors import ApiError as AE, QuantityError

    with pytest.raises(QuantityError):
        Quantity.parse("12XYZ")
    assert issubclass(QuantityError, AE)
    # Malformed quantity inside user claim config surfaces as ApiError.
    d = {
        "apiVersion": "resource.tpu.google.com/v1beta1",
        "kind": "TpuConfig",
        "sharing": {
            "strategy": "Multiplexing",
            "multiplexingConfig": {"defaultHbmLimit": "12XYZ"},
        },
    }
    with pytest.raises(AE):
        api.strict_decode(json.dumps(d))


def test_quantity_total_ordering():
    assert Quantity.parse("1") <= Quantity.parse("2")
    assert Quantity.parse("2Gi") >= Quantity.parse("1Gi")


def test_objectmeta_accepts_apiserver_managed_fields():
    """Objects fetched from a real cluster strict-decode (managedFields etc.)."""
    cd = ComputeDomain.from_dict(
        {
            "metadata": {
                "name": "cd",
                "managedFields": [{"manager": "kubectl"}],
                "selfLink": "/x",
                "generateName": "cd-",
                "deletionGracePeriodSeconds": 0,
            },
            "spec": {"numNodes": 1},
        },
        strict=True,
    )
    assert cd.metadata.name == "cd"
