"""SliceIndex, batched allocation, and packed-order unit tests (ISSUE 6).

The parity suite (test_alloc_parity.py) proves the indexed+packed
allocator equivalent to the exact oracle; this file pins the *point*
behaviors: index invalidation and CEL-verdict caching, staleness
accounting for unparseable slices, the batch entry point's
largest-first order, the packing heuristic's pool- and chip-level
choices, the fleet fragmentation score, and the controller's batch
reconcile over a fake cluster.
"""

import time

import pytest

from tpu_dra.infra.metrics import Metrics
from tpu_dra.k8sclient import (
    DEVICE_CLASSES,
    RESOURCE_CLAIMS,
    RESOURCE_SLICES,
    FakeCluster,
    ResourceClient,
)
from tpu_dra.scheduler.allocator import Allocator, Unschedulable
from tpu_dra.scheduler.allocbench import (
    CLASSES,
    SUBSLICE_CLASS,
    TPU_CLASS,
    make_claim,
    make_fleet,
)
from tpu_dra.scheduler import index as index_mod
from tpu_dra.scheduler.core import SchedulerCore
from tpu_dra.scheduler.index import SliceIndex


def _devices(alloc_result):
    return [
        r["device"]
        for r in alloc_result.allocation["devices"]["results"]
    ]


def _subslice_request(shape):
    return make_claim(0, shape)["spec"]["devices"]["requests"][0]


# --- index invalidation + caching ---


def test_slice_events_update_candidates():
    a, b = make_fleet(2)
    idx = SliceIndex()
    idx.on_slice_event("ADDED", a)
    alloc = Allocator(CLASSES, index=idx)
    cands = alloc._class_devices(_subslice_request("2x2x1"), [])
    assert [d.pool for d in cands] == ["node-00000"]

    idx.on_slice_event("ADDED", b)
    cands = Allocator(CLASSES, index=idx)._class_devices(
        _subslice_request("2x2x1"), []
    )
    assert [d.pool for d in cands] == ["node-00000", "node-00001"]

    # MODIFIED: drop node 0's 2x2 device -> it leaves the fingerprint.
    a2 = {**a, "spec": {**a["spec"], "devices": [
        d for d in a["spec"]["devices"] if d["name"] != "ss-2x2x1-0-0-0"
    ]}}
    idx.on_slice_event("MODIFIED", a2)
    cands = Allocator(CLASSES, index=idx)._class_devices(
        _subslice_request("2x2x1"), []
    )
    assert [d.pool for d in cands] == ["node-00001"]

    idx.on_slice_event("DELETED", b)
    cands = Allocator(CLASSES, index=idx)._class_devices(
        _subslice_request("2x2x1"), []
    )
    assert list(cands) == []


def test_unchanged_slices_run_zero_cel(monkeypatch):
    """The whole point of the index: allocating claim N+1 against an
    unchanged fleet evaluates no selector at all, and a single changed
    slice re-evaluates only that slice."""
    fleet = make_fleet(4)
    idx = SliceIndex()
    idx.resync(fleet)
    calls = []
    real = index_mod.selectors_match

    def counting(selectors, dev, reasons, who):
        calls.append(dev.pool)
        return real(selectors, dev, reasons, who)

    monkeypatch.setattr(index_mod, "selectors_match", counting)
    alloc = Allocator(CLASSES, index=idx)
    alloc._class_devices(_subslice_request("1x1x1"), [])
    first = len(calls)
    assert first > 0  # the fingerprint's initial scan

    calls.clear()
    for _ in range(5):
        Allocator(CLASSES, index=idx)._class_devices(
            _subslice_request("1x1x1"), []
        )
    assert calls == []  # steady state: zero CEL

    # Touch ONE slice: only its devices are re-judged.
    changed = {**fleet[2], "spec": {**fleet[2]["spec"], "devices": [
        d for d in fleet[2]["spec"]["devices"]
        if d["name"] != "ss-1x1x1-0-0-0"
    ]}}
    idx.on_slice_event("MODIFIED", changed)
    calls.clear()
    Allocator(CLASSES, index=idx)._class_devices(
        _subslice_request("1x1x1"), []
    )
    assert set(calls) == {"node-00002"}
    assert 0 < len(calls) < first


def test_resync_skips_unchanged_and_drops_vanished():
    fleet = make_fleet(3)
    idx = SliceIndex()
    idx.resync(fleet)
    gen = idx.generation
    idx.resync(fleet)  # identical listing: no generation churn
    assert idx.generation == gen
    idx.resync(fleet[:2])  # one slice vanished
    assert idx.generation > gen
    assert len(idx.catalog().devices) == len(
        Allocator(CLASSES, slices=fleet[:2]).catalog.devices
    )


def test_unparseable_slice_counts_seen_not_indexed():
    metrics = Metrics()
    idx = SliceIndex(metrics=metrics)
    good, bad = make_fleet(2)
    bad = {**bad, "spec": {**bad["spec"], "devices": 42}}  # not a list
    idx.on_slice_event("ADDED", good)
    idx.on_slice_event("ADDED", bad)
    assert idx.staleness() == (2, 1)
    rendered = metrics.render()
    assert "scheduler_index_slices_seen 2" in rendered
    assert "scheduler_index_slices_indexed 1" in rendered
    # The allocator simply cannot place onto the bad slice.
    assert {c.pool for c in idx.catalog().devices} == {"node-00000"}
    # Heal: a fixed republish clears the staleness.
    idx.on_slice_event("MODIFIED", make_fleet(2)[1])
    assert idx.staleness() == (2, 2)


def test_bad_slice_does_not_churn_generation_on_resync():
    """A permanently-unparseable slice must not bump the generation on
    every sweep resync — that would invalidate every merged view each
    pass, reintroducing the O(fleet) steady state the index kills."""
    idx = SliceIndex()
    fleet = make_fleet(2)
    fleet[1] = {**fleet[1], "spec": {**fleet[1]["spec"], "devices": 42}}
    idx.resync(fleet)
    gen = idx.generation
    for _ in range(3):
        idx.resync(fleet)
        idx.on_slice_event("MODIFIED", fleet[1])  # same bad content
    assert idx.generation == gen
    assert idx.staleness() == (2, 1)


def test_fingerprint_shared_across_request_names(monkeypatch):
    """Verdicts depend on the selectors, not the request name — claims
    with generated request names must share one fingerprint instead of
    thrashing the cache back to per-claim fleet scans."""
    idx = SliceIndex()
    idx.resync(make_fleet(2))
    calls = []
    real = index_mod.selectors_match

    def counting(selectors, dev, reasons, who):
        calls.append(who)
        return real(selectors, dev, reasons, who)

    monkeypatch.setattr(index_mod, "selectors_match", counting)
    alloc = Allocator(CLASSES, index=idx)
    base = _subslice_request("1x1x1")
    alloc._class_devices({**base, "name": "gen-a"}, [])
    assert calls  # first name minted + scanned the fingerprint
    calls.clear()
    cl = alloc._class_devices({**base, "name": "gen-b"}, [])
    assert calls == []  # second name: same fingerprint, zero CEL
    assert len(cl) > 0


def test_fingerprint_eviction_is_lru(monkeypatch):
    """Touching a fingerprint protects it from eviction: with the cap
    at 2, re-reading A before minting C evicts B, not A."""
    monkeypatch.setattr(index_mod, "MAX_FINGERPRINTS", 2)
    idx = SliceIndex()
    idx.resync(make_fleet(1))
    alloc = Allocator(CLASSES, index=idx)

    def request_for(shape):
        return _subslice_request(shape)

    calls = []
    real = index_mod.selectors_match

    def counting(selectors, dev, reasons, who):
        calls.append(who)
        return real(selectors, dev, reasons, who)

    monkeypatch.setattr(index_mod, "selectors_match", counting)
    alloc._class_devices(request_for("1x1x1"), [])  # A
    alloc._class_devices(request_for("2x1x1"), [])  # B
    alloc._class_devices(request_for("1x1x1"), [])  # touch A
    alloc._class_devices(request_for("2x2x1"), [])  # C evicts B
    calls.clear()
    alloc._class_devices(request_for("1x1x1"), [])  # A still cached
    assert calls == []
    alloc._class_devices(request_for("2x1x1"), [])  # B was evicted
    assert calls != []


def test_fingerprint_cache_is_bounded(monkeypatch):
    monkeypatch.setattr(index_mod, "MAX_FINGERPRINTS", 4)
    idx = SliceIndex()
    idx.resync(make_fleet(1))
    alloc = Allocator(CLASSES, index=idx)
    for i in range(10):  # unique selector per request
        req = {
            "name": f"r{i}",
            "deviceClassName": SUBSLICE_CLASS["metadata"]["name"],
            "selectors": [{"cel": {"expression":
                f"device.attributes['tpu.google.com'].subsliceShape"
                f" == '1x1x{i}'"}}],
        }
        alloc._class_devices(req, [])
        assert len(idx._fingerprints) <= 4


# --- batched allocation ---


def test_batch_order_is_largest_first_and_deterministic():
    idx = SliceIndex()
    idx.resync(make_fleet(3))
    alloc = Allocator(CLASSES, index=idx)
    claims = [
        make_claim(0, "1x1x1"),
        make_claim(1, "2x2x1"),
        make_claim(2, "2x1x1"),
        make_claim(3, "2x2x1"),
    ]
    order = alloc.batch_order(claims)
    # 2x2s (weight 4) first — name tiebreak keeps claim-1 before
    # claim-3 — then the row, then the single.
    assert order == [1, 3, 2, 0]
    assert order == Allocator(
        CLASSES, index=idx
    ).batch_order(claims)


def test_allocate_batch_results_in_input_order():
    idx = SliceIndex()
    idx.resync(make_fleet(1))  # 4 chips total
    alloc = Allocator(CLASSES, index=idx)
    claims = [
        make_claim(0, "1x1x1"),
        make_claim(1, "2x2x1"),  # would be stranded if solved last
        make_claim(2, "2x2x1"),  # loses: only one mesh exists
    ]
    results = alloc.allocate_batch(claims)
    assert len(results) == 3
    # Input order preserved: claim 0 and exactly one 2x2 fail.
    assert isinstance(results[0], Unschedulable)
    assert not isinstance(results[1], Unschedulable)
    assert isinstance(results[2], Unschedulable)


def test_batch_big_claims_win_over_claim_bursts():
    """The motivating scenario: a burst of 1x1 claims arriving with a
    2x2 must not strand it — batched largest-first places the 2x2
    before the singles splinter the grid."""
    idx = SliceIndex()
    idx.resync(make_fleet(2))  # 8 chips
    alloc = Allocator(CLASSES, index=idx)
    claims = [make_claim(i, "1x1x1") for i in range(4)]
    claims.append(make_claim(99, "2x2x1"))
    results = alloc.allocate_batch(claims)
    assert not any(isinstance(r, Unschedulable) for r in results)
    two_by_two_node = {
        r["pool"]
        for r in results[-1].allocation["devices"]["results"]
    }
    assert len(two_by_two_node) == 1
    # All four singles share the OTHER node.
    for r in results[:4]:
        assert {
            x["pool"] for x in r.allocation["devices"]["results"]
        }.isdisjoint(two_by_two_node)


# --- packed candidate order ---


def test_packed_fills_fullest_partial_pool_first():
    fleet = make_fleet(3)
    idx = SliceIndex()
    idx.resync(fleet)
    alloc = Allocator(CLASSES, index=idx, ordering="packed")
    # Seed: node-1 half full (a row), node-2 one chip used.
    r1 = alloc.allocate(make_claim(0, "2x1x1"))
    assert _devices(r1) == ["ss-2x1x1-0-0-0"]  # lands node-00000
    # Force usage onto specific nodes via selectors on pool identity:
    # simplest: allocate a row then a single; packed puts both on the
    # fullest pool (node 0), so craft the state with catalog instead.
    state = [
        {**make_claim(1, "2x1x1"),
         "status": {"allocation": r1.allocation}},
    ]
    alloc2 = Allocator(
        CLASSES, index=idx, allocated_claims=state, ordering="packed"
    )
    # node-0 is the only partial pool: the single must land there, on
    # the SAME row's remaining half (wait — the row consumed chips
    # (0,0),(1,0); the frag score prefers keeping row1 intact, so the
    # single goes to... row0 is gone; both remaining chips are row1;
    # taking either kills it; tie -> catalog order -> 0,1).
    r2 = alloc2.allocate(make_claim(2, "1x1x1"))
    assert _devices(r2) == ["ss-1x1x1-0-1-0"]
    assert r2.allocation["nodeSelector"]["nodeSelectorTerms"][0][
        "matchFields"
    ][0]["values"] == ["node-00000"]


def test_packed_single_preserves_intact_row():
    """The ParvaGPU move, chip-scale: with (0,0) already used, a new
    single goes to (1,0) — same row — keeping row (0,1)-(1,1) alive
    for a future 2x1; plain catalog order would pick (0,1) and strand
    both rows."""
    idx = SliceIndex()
    idx.resync(make_fleet(1))
    first = Allocator(CLASSES, index=idx, ordering="packed")
    r1 = first.allocate(make_claim(0, "1x1x1"))
    assert _devices(r1) == ["ss-1x1x1-0-0-0"]
    held = [{**make_claim(0, "1x1x1"),
             "status": {"allocation": r1.allocation}}]
    packed = Allocator(
        CLASSES, index=idx, allocated_claims=held, ordering="packed"
    )
    assert _devices(packed.allocate(make_claim(1, "1x1x1"))) == [
        "ss-1x1x1-1-0-0"
    ]
    catalog = Allocator(
        CLASSES, index=idx, allocated_claims=held, ordering="catalog"
    )
    assert _devices(catalog.allocate(make_claim(1, "1x1x1"))) == [
        "ss-1x1x1-0-1-0"
    ]


def test_fragmentation_score_reads_stranding():
    idx = SliceIndex()
    idx.resync(make_fleet(1))
    alloc = Allocator(CLASSES, index=idx, ordering="catalog")
    assert alloc.fragmentation()["frag_score"] == 0.0
    # Catalog order splits the rows: free chips (1,0),(1,1) can only
    # serve singles -> 2 free, best feasible 1 -> frag 0.5.
    alloc.allocate(make_claim(0, "1x1x1"))
    alloc.allocate(make_claim(1, "1x1x1"))
    frag = alloc.fragmentation()
    assert frag["free_chips"] == 2
    assert frag["achievable_chips"] == 1
    assert frag["frag_score"] == 0.5


# --- the controller's batch reconcile ---


@pytest.fixture()
def fleet_cluster():
    fc = FakeCluster()
    classes = ResourceClient(fc, DEVICE_CLASSES)
    classes.create(dict(TPU_CLASS))
    classes.create(dict(SUBSLICE_CLASS))
    return fc


def wait_for(pred, timeout=10, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {what}")


def test_core_batch_allocates_pending_set(fleet_cluster):
    claims = ResourceClient(fleet_cluster, RESOURCE_CLAIMS)
    slices = ResourceClient(fleet_cluster, RESOURCE_SLICES)
    core = SchedulerCore(fleet_cluster, retry_unschedulable_after=0.3)
    core.start()
    try:
        pend = [make_claim(i, "1x1x1") for i in range(4)]
        pend.append(make_claim(99, "2x2x1"))
        for c in pend:
            claims.create(c)
        # Capacity arrives AFTER the claims: the slice events coalesce
        # into one batch solve over the whole pending set.
        for s in make_fleet(2):
            slices.create(s)

        def all_allocated():
            got = [
                c for c in claims.list("allocbench")
                if (c.get("status") or {}).get("allocation")
            ]
            return got if len(got) == 5 else None

        wait_for(all_allocated, what="batch allocation of 5 claims")
        # The index saw the slices and the frag gauge refreshed.
        # (Whether the batch item or racing single-claim reconciles
        # performed each allocation is timing — the deterministic
        # batch-path assertions live in the next test.)
        assert core.index.staleness() == (2, 2)
        wait_for(
            lambda: (
                "scheduler_frag_score" in core.metrics.render()
            ),
            what="frag gauge",
        )
    finally:
        core.stop()


def test_reconcile_batch_solves_pending_set_in_one_pass(fleet_cluster):
    """The batch item itself, driven synchronously (no workqueue, no
    racing single-claim reconciles): one _reconcile_batch call solves
    the whole pending set against one shared snapshot, commits every
    allocation, bumps the batch metrics, and refreshes the frag
    gauge."""
    claims = ResourceClient(fleet_cluster, RESOURCE_CLAIMS)
    slices = ResourceClient(fleet_cluster, RESOURCE_SLICES)
    for s in make_fleet(2):
        slices.create(s)
    pend = [make_claim(i, "1x1x1") for i in range(4)]
    pend.append(make_claim(99, "2x2x1"))
    for c in pend:
        claims.create(c)
    core = SchedulerCore(fleet_cluster, retry_unschedulable_after=999)
    # Sync the informer stores without starting the controller loops
    # (start() would add handlers and race this test's direct call).
    for inf in (
        core.claim_informer, core.slice_informer, core.class_informer
    ):
        inf.start()
    try:
        for inf in (
            core.claim_informer, core.slice_informer,
            core.class_informer,
        ):
            assert inf.wait_for_sync()
        core.index.resync(core.slice_informer.list())
        core._reconcile_batch(None)
        allocated = [
            c for c in claims.list("allocbench")
            if (c.get("status") or {}).get("allocation")
        ]
        assert len(allocated) == 5
        assert core.metrics._counters[
            ("scheduler_batch_total", ())
        ] == 1
        assert core.metrics._counters[
            ("scheduler_allocations_total", ())
        ] == 5
        assert "scheduler_frag_score" in core.metrics.render()
        # Largest-first inside the batch: the 2x2 owns one whole node.
        big = next(
            c for c in allocated
            if c["metadata"]["name"] == "claim-00099"
        )
        assert len(
            big["status"]["allocation"]["devices"]["results"]
        ) == 1
    finally:
        for inf in (
            core.claim_informer, core.slice_informer,
            core.class_informer,
        ):
            inf.stop()


def test_claim_delete_triggers_prompt_batch_reallocation(fleet_cluster):
    """ISSUE 11: deleting an ALLOCATED claim frees capacity that may
    unblock an Unschedulable claim RIGHT NOW — the DELETED event must
    enqueue a batch solve instead of leaving the waiter to the periodic
    sweep (the serving fabric's scale-down deletes a claim exactly so a
    waiting scale-up can place; seconds of sweep latency would land in
    its reaction time)."""
    claims = ResourceClient(fleet_cluster, RESOURCE_CLAIMS)
    slices = ResourceClient(fleet_cluster, RESOURCE_SLICES)
    for s in make_fleet(1):  # one node: exactly one 2x2 placement
        slices.create(s)
    # Sweep far away: only event-driven reallocation can pass the test.
    core = SchedulerCore(fleet_cluster, retry_unschedulable_after=999)
    core.start()
    try:
        holder = make_claim(0, "2x2x1")
        claims.create(holder)
        wait_for(
            lambda: (
                claims.try_get(
                    holder["metadata"]["name"], "allocbench"
                ).get("status") or {}
            ).get("allocation"),
            what="holder allocation",
        )
        waiter = make_claim(1, "2x2x1")
        claims.create(waiter)
        # The fleet is full: the waiter must be Unschedulable.
        wait_for(
            lambda: core.metrics.get_counter(
                "scheduler_unschedulable_total"
            ) > 0,
            what="waiter marked unschedulable",
        )
        claims.delete(holder["metadata"]["name"], "allocbench")
        wait_for(
            lambda: (
                claims.try_get(
                    waiter["metadata"]["name"], "allocbench"
                ).get("status") or {}
            ).get("allocation"),
            timeout=30,
            what="waiter allocated after holder deletion (event-driven)",
        )
    finally:
        core.stop()
