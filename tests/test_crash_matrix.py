"""Crash-matrix soak: kill at EVERY registered crash point, restart, prove
recovery (``make crashmatrix``).

The WAL design in device_state.py claims that a plugin death at any
instruction leaves a state the next boot converges from. This matrix makes
that claim falsifiable: for each entry of the canonical crash-point table
(``tpu_dra.infra.crashpoint.CRASH_POINTS``) it

1. boots a driver stack over fresh node dirs,
2. arms the point and drives the lifecycle phase that reaches it
   (prepare / unprepare / checkpoint GC / CD-plugin prepare+unprepare),
3. catches the :class:`SimulatedCrash` (the in-process SIGKILL analog —
   the e2e wire drill covers the real ``os._exit`` flavor),
4. "restarts": rebuilds tpulib + checkpoint manager + driver over the
   SAME persisted dirs and runs the boot-time recovery path,
5. asserts the invariants:

   - the checkpoint is strictly loadable (no quarantine needed),
   - no leftover ``.tmp`` files anywhere in the plugin data dir,
   - no orphan sub-slices (live silicon == what completed claims vouch
     for) and no sub-slice double-materialization,
   - no overlapping prepared devices across completed claims,
   - every CDI claim spec belongs to a checkpointed claim,
   - the interrupted operation RETRIES to success (prepare is idempotent
     after recovery; unprepare/GC converge to empty).

Corrupt-checkpoint tolerance rides the same harness: a flipped byte at
boot recovers from ``.bak``; flipping BOTH copies rebuilds from the
device scan (CDI specs + live sub-slices) instead of crashing the plugin.
"""

import json
import os

import pytest

from tpu_dra.infra import crashpoint as crashpoint_mod
from tpu_dra.infra import featuregates as fg
from tpu_dra.infra.crashpoint import CRASH_POINTS, SimulatedCrash, arm
from tpu_dra.k8sclient import RESOURCE_CLAIMS, FakeCluster, ResourceClient
from tpu_dra.computedomain.cdplugin.device_state import CDDeviceState
from tpu_dra.computedomain import CD_DRIVER_NAME
from tpu_dra.plugin.checkpoint import (
    CLAIM_STATE_PREPARE_COMPLETED,
    CLAIM_STATE_PREPARE_STARTED,
    Checkpoint,
    CheckpointManager,
)
from tpu_dra.plugin.cdi import CDIHandler
from tpu_dra.plugin.device_state import DRIVER_NAME
from tpu_dra.plugin.driver import Driver, DriverConfig
from tpu_dra.tpulib.stub import StubTpuLib

SUBSLICE_DEV = "tpu-ss-1x1-0-0-0"  # covers chip (0,0,0) == tpu-0
CHIP_DEV = "tpu-3"


@pytest.fixture(autouse=True)
def _reset():
    g = fg.FeatureGates()
    g.set("DynamicSubslice", True)
    fg.reset_for_tests(g)
    crashpoint_mod.reset_for_tests()
    yield
    crashpoint_mod.reset_for_tests()
    fg.reset_for_tests(fg.FeatureGates())


def make_claim(devices, uid="claim-uid-1"):
    """One request per device: a sub-slice may never share a request."""
    results = [
        {"request": f"r{i}", "driver": DRIVER_NAME, "pool": "node-0",
         "device": d}
        for i, d in enumerate(devices)
    ]
    return {
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceClaim",
        "metadata": {
            "name": f"claim-{uid[:8]}", "namespace": "default", "uid": uid,
        },
        "status": {
            "allocation": {"devices": {"results": results, "config": []}}
        },
    }


class MatrixHarness:
    """The plugin stack over persistent node dirs; boot() is the process-
    restart analog (fresh objects, same disk)."""

    def __init__(self, tmp_path):
        self.tmp = tmp_path
        self.backend = FakeCluster()
        self.driver = None

    def boot(self) -> Driver:
        lib = StubTpuLib(
            config={"generation": "v5e", "hostname": "node-0", "chips": 4},
            state_dir=str(self.tmp / "tpustate"),
        )
        cfg = DriverConfig(
            node_name="node-0",
            cdi_root=str(self.tmp / "cdi"),
            plugin_data_dir=str(self.tmp / "plugin"),
            kubelet_registrar_dir=str(self.tmp / "registry"),
            start_grpc=False,
            cdi_hook_source="",
        )
        self.driver = Driver(lib, self.backend, cfg)
        self.driver.start()
        return self.driver

    # --- invariants -------------------------------------------------------

    def assert_invariants(self):
        d = self.driver
        plugin_dir = str(self.tmp / "plugin")
        # 1. Checkpoint strictly loadable, and no stray temp files.
        with open(os.path.join(plugin_dir, "checkpoint.json"), "rb") as f:
            cp = Checkpoint.unmarshal(f.read())
        strays = [
            n for n in os.listdir(plugin_dir) if n.endswith(".tmp")
        ]
        assert strays == [], f"leaked temp files: {strays}"
        # 2. No claim may linger in PrepareStarted after boot recovery.
        stuck = [
            uid for uid, c in cp.prepared_claims.items()
            if c.checkpoint_state == CLAIM_STATE_PREPARE_STARTED
        ]
        assert stuck == [], f"claims stuck in PrepareStarted: {stuck}"
        # 3. No orphan silicon and no double-materialization: every live
        #    sub-slice is vouched for by a checkpointed claim, exactly
        #    once. (The converse may transiently not hold: a crash inside
        #    unprepare leaves a claim vouching for already-torn-down
        #    silicon until the kubelet retries — each scenario asserts
        #    full convergence after its retry.)
        vouched = []
        for c in cp.prepared_claims.values():
            for g in c.prepared_devices:
                for pd in g.devices:
                    if pd.subslice_uuid:
                        vouched.append(pd.subslice_uuid)
        assert len(vouched) == len(set(vouched)), (
            f"sub-slice double-referenced: {vouched}"
        )
        live = sorted(ss.uuid for ss in d.tpulib.list_subslices())
        orphans = set(live) - set(vouched)
        assert not orphans, (
            f"orphan sub-slices: {orphans} (vouched: {vouched})"
        )
        # 4. No overlapping prepared devices (by chip coordinate).
        seen_coords = set()
        for c in cp.prepared_claims.values():
            for g in c.prepared_devices:
                for pd in g.devices:
                    adev = d.state.allocatable.get(pd.device.device_name)
                    if adev is None:
                        continue
                    coords = set(adev.chip_coords())
                    assert not (coords & seen_coords), (
                        f"overlapping prepared devices at {coords}"
                    )
                    seen_coords |= coords
        # 5. Every CDI claim spec belongs to a checkpointed claim.
        for uid in d.cdi.list_claim_uids():
            assert uid in cp.prepared_claims, (
                f"orphan CDI spec for claim {uid}"
            )


# --- which lifecycle phase reaches each point -------------------------------

PREPARE_POINTS = sorted(
    p for p in CRASH_POINTS
    if p.startswith(("checkpoint.write.", "plugin.prepare.",
                     "tpulib.subslice."))
)
UNPREPARE_POINTS = sorted(
    p for p in CRASH_POINTS if p.startswith("plugin.unprepare.")
)
GC_POINTS = sorted(p for p in CRASH_POINTS if p.startswith("plugin.gc."))
CD_POINTS = sorted(p for p in CRASH_POINTS if p.startswith("cdplugin."))
REPACK_POINTS = sorted(
    p for p in CRASH_POINTS if p.startswith("repack.")
)
GANG_COMMIT_POINTS = sorted(
    p for p in CRASH_POINTS if p.startswith("gang.commit.")
)
GANG_TEARDOWN_POINTS = sorted(
    p for p in CRASH_POINTS if p.startswith("gang.teardown.")
)


def test_matrix_covers_every_registered_point():
    """The acceptance bar: every registered point is reachable by exactly
    one scenario below, and the table is big enough to mean something."""
    covered = (
        PREPARE_POINTS + UNPREPARE_POINTS + GC_POINTS + CD_POINTS
        + REPACK_POINTS + GANG_COMMIT_POINTS + GANG_TEARDOWN_POINTS
    )
    assert sorted(covered) == sorted(CRASH_POINTS)
    assert len(CRASH_POINTS) >= 12


# --- the matrix -------------------------------------------------------------


@pytest.mark.parametrize("point", PREPARE_POINTS)
def test_crash_during_prepare_recovers(tmp_path, point):
    h = MatrixHarness(tmp_path)
    state = h.boot().state
    claim = make_claim([SUBSLICE_DEV, CHIP_DEV])
    with arm(point) as a:
        with pytest.raises(SimulatedCrash):
            state.prepare(claim)
    assert a.fired, f"{point} never fired during prepare"

    # Restart over the same disk; boot recovery rolls the WAL back.
    state2 = h.boot().state
    h.assert_invariants()
    assert h.driver.checkpoints.get().prepared_claims == {}

    # The kubelet retry converges, idempotently.
    devs = state2.prepare(claim)
    assert sorted(d.device_name for d in devs) == [CHIP_DEV, SUBSLICE_DEV]
    devs2 = state2.prepare(claim)
    assert sorted(d.device_name for d in devs2) == [CHIP_DEV, SUBSLICE_DEV]
    cp = h.driver.checkpoints.get()
    assert (
        cp.prepared_claims[claim["metadata"]["uid"]].checkpoint_state
        == CLAIM_STATE_PREPARE_COMPLETED
    )
    assert len(h.driver.tpulib.list_subslices()) == 1
    h.assert_invariants()

    # And unprepare returns the silicon.
    state2.unprepare(claim["metadata"]["uid"])
    assert h.driver.tpulib.list_subslices() == []
    h.assert_invariants()


@pytest.mark.parametrize("point", UNPREPARE_POINTS)
def test_crash_during_unprepare_recovers(tmp_path, point):
    h = MatrixHarness(tmp_path)
    state = h.boot().state
    claim = make_claim([SUBSLICE_DEV, CHIP_DEV])
    state.prepare(claim)
    with arm(point) as a:
        with pytest.raises(SimulatedCrash):
            state.unprepare(claim["metadata"]["uid"])
    assert a.fired, f"{point} never fired during unprepare"

    state2 = h.boot().state
    h.assert_invariants()
    # The kubelet retries Unprepare until it answers cleanly.
    state2.unprepare(claim["metadata"]["uid"])
    assert h.driver.checkpoints.get().prepared_claims == {}
    assert h.driver.tpulib.list_subslices() == []
    assert h.driver.cdi.list_claim_uids() == []
    h.assert_invariants()


@pytest.mark.parametrize("point", GC_POINTS)
def test_crash_during_gc_recovers(tmp_path, point):
    h = MatrixHarness(tmp_path)
    driver = h.boot()
    claim = make_claim([SUBSLICE_DEV, CHIP_DEV])
    driver.state.prepare(claim)
    # The claim's ResourceClaim never existed in the API server: the GC
    # judges it stale on its first pass.
    with arm(point) as a:
        with pytest.raises(SimulatedCrash):
            driver.cleanup.cleanup_once()
    assert a.fired, f"{point} never fired during GC"

    driver2 = h.boot()
    h.assert_invariants()
    driver2.cleanup.cleanup_once()  # retry pass converges
    assert h.driver.checkpoints.get().prepared_claims == {}
    assert h.driver.tpulib.list_subslices() == []
    h.assert_invariants()


def test_gc_skips_claims_the_apiserver_vouches_for(tmp_path):
    """Guard for the matrix arrangement: a live ResourceClaim keeps its
    prepared claim through a GC pass (only truly stale claims are in
    play above)."""
    h = MatrixHarness(tmp_path)
    driver = h.boot()
    claim = make_claim([CHIP_DEV])
    created = ResourceClient(h.backend, RESOURCE_CLAIMS).create(claim)
    claim["metadata"]["uid"] = created["metadata"]["uid"]
    driver.state.prepare(claim)
    assert driver.cleanup.cleanup_once() == 0
    assert (
        created["metadata"]["uid"]
        in driver.checkpoints.get().prepared_claims
    )


# --- compute-domain plugin rows ---------------------------------------------


CD_DOMAIN_UID = "bf8e1d9e-7d2b-4f80-9c8e-3a9f0a6a1c11"


def make_cd_daemon_claim(uid="cd-claim-1", domain=CD_DOMAIN_UID):
    return {
        "metadata": {"name": f"dc-{uid[:6]}", "namespace": "default",
                     "uid": uid},
        "status": {
            "allocation": {
                "devices": {
                    "results": [
                        {
                            "request": "cd-daemon",
                            "driver": CD_DRIVER_NAME,
                            "pool": "node-0-cd",
                            "device": "daemon",
                        }
                    ],
                    "config": [
                        {
                            "requests": ["cd-daemon"],
                            "opaque": {
                                "driver": CD_DRIVER_NAME,
                                "parameters": {
                                    "apiVersion": (
                                        "resource.tpu.google.com/v1beta1"
                                    ),
                                    "kind": "ComputeDomainDaemonConfig",
                                    "domainID": domain,
                                },
                            },
                        }
                    ],
                }
            }
        },
    }


class CDMatrixHarness:
    def __init__(self, tmp_path):
        self.tmp = tmp_path
        self.backend = FakeCluster()
        self.state = None

    def boot(self) -> CDDeviceState:
        self.state = CDDeviceState(
            self.backend,
            cdi=CDIHandler(cdi_root=str(self.tmp / "cd-cdi")),
            checkpoints=CheckpointManager(str(self.tmp / "cd-ckpt")),
            node_name="node-0",
            domains_dir=str(self.tmp / "domains"),
        )
        # CDDriver.start analog.
        self.state.recover_stale_prepares()
        return self.state

    def assert_invariants(self):
        ckpt_dir = str(self.tmp / "cd-ckpt")
        with open(os.path.join(ckpt_dir, "checkpoint.json"), "rb") as f:
            cp = Checkpoint.unmarshal(f.read())
        strays = [n for n in os.listdir(ckpt_dir) if n.endswith(".tmp")]
        assert strays == [], f"leaked temp files: {strays}"
        stuck = [
            uid for uid, c in cp.prepared_claims.items()
            if c.checkpoint_state == CLAIM_STATE_PREPARE_STARTED
        ]
        assert stuck == [], f"CD claims stuck in PrepareStarted: {stuck}"
        for uid in self.state.cdi.list_claim_uids():
            assert uid in cp.prepared_claims, f"orphan CD CDI spec {uid}"


@pytest.mark.parametrize("point", CD_POINTS)
def test_cd_crash_recovers(tmp_path, point):
    h = CDMatrixHarness(tmp_path)
    state = h.boot()
    claim = make_cd_daemon_claim()
    uid = claim["metadata"]["uid"]
    domain_dir = tmp_path / "domains" / CD_DOMAIN_UID
    if point.startswith("cdplugin.prepare."):
        with arm(point) as a:
            with pytest.raises(SimulatedCrash):
                state.prepare(claim)
    else:
        state.prepare(claim)
        with arm(point) as a:
            with pytest.raises(SimulatedCrash):
                state.unprepare(uid)
    assert a.fired, f"{point} never fired"

    state2 = h.boot()
    h.assert_invariants()
    # Retry to the terminal state of the interrupted operation.
    if point.startswith("cdplugin.prepare."):
        # Boot rollback removed the orphaned per-domain config dir a
        # crashed daemon prepare may have created (no other claim
        # references the domain) — even if the claim is never retried.
        assert not domain_dir.exists()
        devs = state2.prepare(claim)
        assert [d.device_name for d in devs] == ["daemon"]
        devs2 = state2.prepare(claim)
        assert [d.device_name for d in devs2] == ["daemon"]
    state2.unprepare(uid)
    assert state2.checkpoints.get().prepared_claims == {}
    assert not domain_dir.exists()
    h.assert_invariants()


# --- corrupt-checkpoint boot tolerance --------------------------------------


def _flip_byte(path, offset=20):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


def test_corrupt_checkpoint_at_boot_recovers_from_bak(tmp_path):
    h = MatrixHarness(tmp_path)
    state = h.boot().state
    claim = make_claim([SUBSLICE_DEV])
    state.prepare(claim)
    ckpt = tmp_path / "plugin" / "checkpoint.json"
    _flip_byte(str(ckpt))

    driver2 = h.boot()  # must not raise
    cp = driver2.checkpoints.get()
    assert (
        cp.prepared_claims[claim["metadata"]["uid"]].checkpoint_state
        == CLAIM_STATE_PREPARE_COMPLETED
    )
    # The sub-slice survived recovery (the claim still vouches for it).
    assert len(driver2.tpulib.list_subslices()) == 1
    quarantined = [
        n for n in os.listdir(tmp_path / "plugin") if ".corrupt-" in n
    ]
    assert len(quarantined) == 1, quarantined
    h.assert_invariants()


def test_corrupt_checkpoint_and_bak_rebuilds_from_device_scan(tmp_path):
    h = MatrixHarness(tmp_path)
    state = h.boot().state
    claim = make_claim([SUBSLICE_DEV])
    state.prepare(claim)
    _flip_byte(str(tmp_path / "plugin" / "checkpoint.json"))
    _flip_byte(str(tmp_path / "plugin" / "checkpoint.json.bak"))

    driver2 = h.boot()  # must not raise: rebuild from CDI specs + silicon
    cp = driver2.checkpoints.get()
    uid = claim["metadata"]["uid"]
    assert (
        cp.prepared_claims[uid].checkpoint_state
        == CLAIM_STATE_PREPARE_COMPLETED
    )
    assert cp.prepared_claims[uid].prepared_devices.device_names() == [
        SUBSLICE_DEV
    ]
    # Startup obliteration must NOT destroy the re-attached sub-slice.
    assert len(driver2.tpulib.list_subslices()) == 1
    # Idempotent prepare short-circuits on the rebuilt record.
    devs = driver2.state.prepare(claim)
    assert [d.device_name for d in devs] == [SUBSLICE_DEV]
    assert len(driver2.tpulib.list_subslices()) == 1
    # And unprepare still returns the silicon.
    driver2.state.unprepare(uid)
    assert driver2.tpulib.list_subslices() == []
    h.assert_invariants()


def test_rebuild_skips_torn_cdi_spec_instead_of_failing_boot(tmp_path):
    """The disk incident that ate both checkpoint copies may have torn a
    CDI spec too: the rebuild loses THAT claim (its devices swept), never
    the boot."""
    h = MatrixHarness(tmp_path)
    state = h.boot().state
    good = make_claim([CHIP_DEV], uid="good-claim-uid")
    torn = make_claim([SUBSLICE_DEV], uid="torn-claim-uid")
    state.prepare(good)
    state.prepare(torn)
    _flip_byte(str(tmp_path / "plugin" / "checkpoint.json"))
    _flip_byte(str(tmp_path / "plugin" / "checkpoint.json.bak"))
    spec_path = h.driver.cdi.spec_path("torn-claim-uid")
    with open(spec_path, "w") as f:
        f.write("{half a spe")

    driver2 = h.boot()  # must not raise
    cp = driver2.checkpoints.get()
    assert "good-claim-uid" in cp.prepared_claims
    assert "torn-claim-uid" not in cp.prepared_claims
    # The torn claim's sub-slice was swept by startup obliteration
    # (nothing vouches for it anymore).
    assert driver2.tpulib.list_subslices() == []


def test_cd_corrupt_checkpoint_rebuilds_from_cdi_scan(tmp_path):
    """CD analog of the device-scan rebuild: both copies corrupt, claims
    come back from the CD CDI specs — including the CD_UID env a daemon
    claim's unprepare needs to remove its per-domain config dir (without
    the rebuild, unprepare would no-op and leak spec + dir forever)."""
    from tpu_dra.computedomain.cdplugin.driver import CDDriver, CDDriverConfig

    backend = FakeCluster()

    def boot():
        d = CDDriver(backend, CDDriverConfig(
            node_name="node-0",
            cdi_root=str(tmp_path / "cd-cdi"),
            plugin_data_dir=str(tmp_path / "cd-plugin"),
            kubelet_registrar_dir=str(tmp_path / "cd-reg"),
            start_grpc=False,
        ))
        d.state.recover_stale_prepares()
        return d

    driver = boot()
    claim = make_cd_daemon_claim()
    uid = claim["metadata"]["uid"]
    driver.state.prepare(claim)
    domain_dir = tmp_path / "cd-plugin" / "domains" / CD_DOMAIN_UID
    assert domain_dir.is_dir()
    _flip_byte(str(tmp_path / "cd-plugin" / "checkpoint.json"))
    _flip_byte(str(tmp_path / "cd-plugin" / "checkpoint.json.bak"))

    driver2 = boot()  # must not raise; rebuild from CDI scan
    cp = driver2.checkpoints.get()
    assert (
        cp.prepared_claims[uid].checkpoint_state
        == CLAIM_STATE_PREPARE_COMPLETED
    )
    pd = cp.prepared_claims[uid].prepared_devices[0].devices[0]
    assert pd.runtime_env.get("CD_UID") == CD_DOMAIN_UID
    # Unprepare on the rebuilt record cleans up everything.
    driver2.state.unprepare(uid)
    assert driver2.cdi.list_claim_uids() == []
    assert not domain_dir.exists()
    assert driver2.checkpoints.get().prepared_claims == {}


def test_double_crash_during_heal_still_rebuilds(tmp_path):
    """Both copies corrupt AND the plugin dies mid-heal-write: the next
    boot finds no committed checkpoint at all, only the quarantine file —
    that evidence must still route to the device-scan rebuild, not to an
    empty checkpoint that would let startup obliteration destroy live
    claims' silicon."""
    h = MatrixHarness(tmp_path)
    state = h.boot().state
    claim = make_claim([SUBSLICE_DEV])
    state.prepare(claim)
    _flip_byte(str(tmp_path / "plugin" / "checkpoint.json"))
    _flip_byte(str(tmp_path / "plugin" / "checkpoint.json.bak"))

    # Crash 2: the heal write itself (quarantine already happened).
    with arm("checkpoint.write.before_replace") as a:
        with pytest.raises(SimulatedCrash):
            h.boot()
    assert a.fired
    assert not (tmp_path / "plugin" / "checkpoint.json").exists()

    driver3 = h.boot()  # crash 3 never comes; recovery must be complete
    uid = claim["metadata"]["uid"]
    cp = driver3.checkpoints.get()
    assert (
        cp.prepared_claims[uid].checkpoint_state
        == CLAIM_STATE_PREPARE_COMPLETED
    )
    assert len(driver3.tpulib.list_subslices()) == 1
    h.assert_invariants()


def test_empty_checkpoint_file_is_quarantined_not_fatal(tmp_path):
    h = MatrixHarness(tmp_path)
    h.boot()
    (tmp_path / "plugin" / "checkpoint.json").write_text("")
    h.boot()  # must not raise
    h.assert_invariants()


# --- the crash fault kind composes with the chaos schema --------------------


def test_chaos_crash_event_drives_matrix_row(tmp_path):
    """A seeded-soak-shaped drill: a schedule's crash event kills the
    plugin at a named WAL point mid-prepare; restart converges."""
    from tpu_dra.infra.chaos import CRASH, ChaosEngine, FaultSchedule

    schedule = FaultSchedule.from_dict({
        "version": 1,
        "events": [
            {"at": 0.0, "kind": "crash",
             "point": "plugin.prepare.before_wal_completed"},
        ],
    })
    h = MatrixHarness(tmp_path)
    state = h.boot().state
    claim = make_claim([SUBSLICE_DEV])

    def inject(ev):
        with arm(ev.params["point"]) as a:
            try:
                state.prepare(claim)
            except SimulatedCrash:
                pass
        assert a.fired

    engine = ChaosEngine(schedule).register(CRASH, inject)
    engine.run(time_scale=0)
    assert engine.errors == []
    assert engine.fired == {"crash": 1}

    state2 = h.boot().state
    h.assert_invariants()
    devs = state2.prepare(claim)
    assert [d.device_name for d in devs] == [SUBSLICE_DEV]
    h.assert_invariants()


# --- elastic-repacker two-phase moves (ISSUE 12) ----------------------------
#
# The repacker's WAL is an annotation ON THE CLAIM (apiserver-durable,
# survives leader failover), so its "restart" analog is a FRESH Repacker
# over the same FakeCluster running recover(). Invariants after every
# kill: each claim converges to exactly ONE valid allocation (old or new
# placement, never half), no counter overlap between claims, the WAL
# annotation fully resolved, and the serving protocol accounts for every
# drained tenant (aborted plans resume in place, committed ones rebind).


class _RepackHarness:
    def __init__(self):
        from tpu_dra.scheduler import fleet
        from tpu_dra.k8sclient import DEVICE_CLASSES, RESOURCE_SLICES

        self.fleet = fleet
        self.cluster = FakeCluster()
        for c in fleet.CLASSES:
            ResourceClient(self.cluster, DEVICE_CLASSES).create(
                json.loads(json.dumps(c))
            )
        slices = ResourceClient(self.cluster, RESOURCE_SLICES)
        for i in range(2):
            slices.create(fleet.make_node_slice(i))
        self.claims = ResourceClient(self.cluster, RESOURCE_CLAIMS)
        # One 1x1 resident per node: stranded for the 2x2, one
        # improving move exists.
        self.names = []
        for i in range(2):
            c = fleet.make_claim(i, "1x1x1")
            c["metadata"]["namespace"] = "default"
            c["status"] = {"allocation": {"devices": {"results": [{
                "request": "tpu", "driver": fleet.DRIVER,
                "pool": fleet.node_name(i), "device": "ss-1x1x1-0-0-0",
            }]}}}
            self.claims.create(c)
            self.claims.update_status(c)
            self.names.append(c["metadata"]["name"])

    def boot_repacker(self, adapter):
        from tpu_dra.infra.metrics import Metrics
        from tpu_dra.scheduler.repacker import Repacker, RepackerConfig

        return Repacker(
            self.cluster,
            RepackerConfig(
                poll_period=0.0, frag_threshold=0.05,
                min_disruption_interval_seconds=0.0,
            ),
            serving=adapter, metrics=Metrics(),
        )

    def assert_invariants(self):
        from tpu_dra.scheduler import fleet
        from tpu_dra.scheduler.allocator import Allocator
        from tpu_dra.scheduler.repacker import repack_state
        from tpu_dra.k8sclient import DEVICE_CLASSES, RESOURCE_SLICES

        claims = self.claims.list()
        alloc = Allocator(
            ResourceClient(self.cluster, DEVICE_CLASSES).list(),
            slices=ResourceClient(self.cluster, RESOURCE_SLICES).list(),
        )
        for c in claims:
            # WAL fully resolved and exactly one placement per claim.
            assert repack_state(c) is None, (
                f"unresolved repack WAL on {c['metadata']['name']}"
            )
            results = ((c.get("status") or {}).get("allocation") or {}) \
                .get("devices", {}).get("results", [])
            assert results, (
                f"claim {c['metadata']['name']} lost its allocation "
                f"(half-move)"
            )
            for r in results:
                key = (r["driver"], r["pool"], r["device"])
                dev = alloc.catalog.by_key.get(key)
                assert dev is not None, f"phantom device {key}"
                assert key not in alloc.in_use, f"double-assigned {key}"
                assert alloc.ledger.can_consume(dev), (
                    f"counter overlap at {key}"
                )
                alloc.ledger.consume(dev)
                alloc.in_use.add(key)
        del fleet


class _RepackAdapter:
    """Recording ServingAdapter: drains complete instantly; the calls
    list is the lost/duplicated-sequence accounting probe."""

    def __init__(self):
        self.calls = []

    def begin_drain(self, key):
        self.calls.append(("begin_drain", key))

    def drain_done(self, key):
        return True

    def finish_drain(self, key):
        self.calls.append(("finish_drain", key))
        return 1

    def rebind(self, key, claim):
        self.calls.append(("rebind", key))

    def abort(self, key):
        self.calls.append(("abort", key))


@pytest.mark.parametrize("point", REPACK_POINTS)
def test_repack_crash_recovers(point):
    from tpu_dra.infra.crashpoint import SimulatedCrash as SC

    h = _RepackHarness()
    adapter = _RepackAdapter()
    rp = h.boot_repacker(adapter)
    with arm(point) as a:
        with pytest.raises(SC):
            for _ in range(8):
                rp.tick()
    assert a.fired, f"{point} never fired during the migration"

    # "Restart": a fresh leader over the same cluster resolves the
    # WAL'd half-move (back or forward), then converges the fleet.
    adapter2 = _RepackAdapter()
    rp2 = h.boot_repacker(adapter2)
    rp2.recover()
    for _ in range(12):
        rp2.tick()
    h.assert_invariants()
    # Converged: the two residents are co-located (the improving move
    # happened — either the recovered one or a re-planned one).
    pools = set()
    for name in h.names:
        c = h.claims.try_get(name, "default")
        results = c["status"]["allocation"]["devices"]["results"]
        pools.add(results[0]["pool"])
    assert len(pools) == 1, f"fleet never converged: {pools}"
    # Serving accounting (conservation across both "processes"): every
    # drain was eventually handed back — resumed in place (abort) or
    # rebound at a committed placement — so no tenant is lost; and a
    # key is never rebound more often than it was drained+recovered,
    # so no tenant is duplicated.
    all_calls = adapter.calls + adapter2.calls
    for key in {k for _op, k in all_calls}:
        drains = sum(1 for op, k in all_calls
                     if op == "begin_drain" and k == key)
        rebinds_k = sum(1 for op, k in all_calls
                        if op == "rebind" and k == key)
        aborts_k = sum(1 for op, k in all_calls
                       if op == "abort" and k == key)
        assert rebinds_k + aborts_k >= drains, (
            f"{key}: drained {drains}x but handed back only "
            f"{rebinds_k + aborts_k}x — lost tenant"
        )
        assert rebinds_k <= max(drains, 1), (
            f"{key}: rebound {rebinds_k}x over {drains} drain(s) — "
            f"duplicated tenant"
        )
    assert any(op == "rebind" for op, _k in all_calls), (
        "no migration ever completed"
    )
    # Idempotent steady state: more ticks change nothing.
    before = {
        name: json.dumps(
            h.claims.try_get(name, "default")["status"], sort_keys=True
        )
        for name in h.names
    }
    for _ in range(4):
        rp2.tick()
    for name in h.names:
        assert json.dumps(
            h.claims.try_get(name, "default")["status"], sort_keys=True
        ) == before[name]


def test_repack_lease_loss_plus_crash_still_recovers():
    """The compound failure: leadership lost mid-migration (abort path
    entered) AND the process dies before the rollback write lands — the
    next leader still converges from the WAL alone."""
    h = _RepackHarness()
    rp = h.boot_repacker(_RepackAdapter())
    # Stall in draining so the WAL'd plan exists.
    rp.serving.drain_done = lambda key: False
    rp.tick()
    from tpu_dra.scheduler.repacker import repack_state

    annotated = [
        c for c in h.claims.list() if repack_state(c) is not None
    ]
    assert len(annotated) == 1
    # Process death here (no rollback ran): the fresh leader recovers.
    rp2 = h.boot_repacker(_RepackAdapter())
    rp2.recover()
    for _ in range(12):
        rp2.tick()
    h.assert_invariants()


# --- gang two-phase commit rows (ISSUE 19) ----------------------------------
#
# One row per gang.commit.* / gang.teardown.* window: kill there, then a
# fresh "scheduler" recovers from the apiserver WAL alone and the fleet
# converges — never a partial gang, never a leaked or double-assigned
# chip. The gang fuzzer (tests/test_gang_fuzz) drives the same windows
# under randomized interleavings; these rows are the deterministic
# minimal repros.


class _GangHarness:
    """3 published nodes + a 2-member full-node (2x2x1) gang, pending."""

    def __init__(self):
        from tpu_dra.scheduler import fleet
        from tpu_dra.k8sclient import DEVICE_CLASSES, RESOURCE_SLICES

        self.fleet = fleet
        self.cluster = FakeCluster()
        for c in fleet.CLASSES:
            ResourceClient(self.cluster, DEVICE_CLASSES).create(
                json.loads(json.dumps(c))
            )
        self.slices = ResourceClient(self.cluster, RESOURCE_SLICES)
        for i in range(3):
            self.slices.create(fleet.make_node_slice(i))
        self.claims = ResourceClient(self.cluster, RESOURCE_CLAIMS)
        self.members = [
            self.claims.create(c) for c in fleet.make_gang_claims(
                "mg", 0, 2, "2x2x1", namespace="default"
            )
        ]

    def refetch(self):
        return [
            self.claims.try_get(c["metadata"]["name"], "default")
            for c in self.members
        ]

    def solve(self):
        from tpu_dra.scheduler.allocator import Allocator

        members = self.refetch()
        alloc = Allocator(
            self.fleet.CLASSES, allocated_claims=self.claims.list(),
            slices=self.slices.list(),
        )
        return members, alloc.allocate_gang(members)

    def allocated(self):
        return [
            c for c in self.refetch()
            if (c.get("status") or {}).get("allocation")
        ]

    def assert_invariants(self):
        from tpu_dra.scheduler.allocbench import validate_results
        from tpu_dra.scheduler.gang import gang_state

        live = self.claims.list()
        # WAL fully resolved; all-or-nothing; exclusivity + counter
        # capacity against the published fleet.
        for c in live:
            assert gang_state(c) is None, (
                f"unresolved gang WAL on {c['metadata']['name']}"
            )
        assert len(self.allocated()) in (0, len(self.members))
        validate_results(self.slices.list(), [
            (c["metadata"]["name"], c["status"]["allocation"])
            for c in live
            if (c.get("status") or {}).get("allocation")
        ])


@pytest.mark.parametrize("point", GANG_COMMIT_POINTS)
def test_gang_commit_crash_recovers(point):
    from tpu_dra.scheduler.gang import commit_gang, recover_gangs

    h = _GangHarness()
    members, results = h.solve()
    with arm(point) as a:
        with pytest.raises(SimulatedCrash):
            commit_gang(h.claims, "mg", members, results,
                        identity="matrix")
    assert a.fired, f"{point} never fired during the commit"

    # "Restart": recovery resolves the WAL (back or forward) from the
    # apiserver alone, then the retry converges to a whole gang.
    assert recover_gangs(h.claims, identity="matrix-restart") == 1
    h.assert_invariants()
    if not h.allocated():  # rolled back: the retry re-seats it
        members, results = h.solve()
        commit_gang(h.claims, "mg", members, results, identity="retry")
    assert len(h.allocated()) == len(h.members)
    h.assert_invariants()
    # Idempotent: nothing left for a second recovery pass.
    assert recover_gangs(h.claims, identity="again") == 0


@pytest.mark.parametrize("point", GANG_TEARDOWN_POINTS)
def test_gang_teardown_crash_recovers(point):
    from tpu_dra.scheduler.gang import (
        commit_gang, recover_gangs, teardown_gang,
    )

    h = _GangHarness()
    members, results = h.solve()
    commit_gang(h.claims, "mg", members, results, identity="matrix")
    with arm(point) as a:
        with pytest.raises(SimulatedCrash):
            teardown_gang(h.claims, h.refetch(), reason="node loss",
                          identity="matrix")
    assert a.fired, f"{point} never fired during the teardown"

    # Recovery completes the journaled teardown: fully pending, and the
    # freed chips are immediately reusable (the gang re-seats whole).
    assert recover_gangs(h.claims, identity="matrix-restart") == 1
    h.assert_invariants()
    assert h.allocated() == []
    members, results = h.solve()
    commit_gang(h.claims, "mg", members, results, identity="reseat")
    assert len(h.allocated()) == len(h.members)
    h.assert_invariants()


def test_crash_points_registry_shape():
    """Names are dotted component.operation.site and the JSON round-trip
    used by schedules/tools stays stable."""
    for name in CRASH_POINTS:
        parts = name.split(".")
        assert len(parts) >= 3, name
        assert all(p and p.replace("_", "a").isalnum() for p in parts), name
    json.dumps(sorted(CRASH_POINTS))  # serializable for tooling
