"""Claim-lifecycle tracing (ISSUE 13): the span core, the flight
recorder, cross-process propagation via the ctx annotation, WAL/crash
survival of the trace context, and `doctor explain` stitching.

The tracecheck smoke (`make tracecheck`) drives the full lifecycle over
the real scheduler stack; this file pins the unit contracts — above
all that tracing OFF is a shared no-op (identity), that the repacker's
two-phase WAL and the prepare crash matrix preserve the claim's trace
id, and that the doctor's stage budget sums to the window.
"""

import json
import time
import urllib.request

import pytest

from tpu_dra.infra import crashpoint as cp
from tpu_dra.infra import trace
from tpu_dra.infra.metrics import Metrics, MetricsServer
from tpu_dra.k8sclient import RESOURCE_CLAIMS, ResourceClient
from tpu_dra.tools import doctor

from tests.helpers import (
    REPACK_NS as NS,
    RecordingRepackAdapter as RecordingAdapter,
    get_claim as claim_of,
    make_repack_cluster as make_cluster,
    make_repacker as mk_repacker,
    spread_two_residents as spread_two,
)


@pytest.fixture(autouse=True)
def _fresh_tracing():
    trace.set_enabled(True)
    trace.reset_for_tests()
    yield
    trace.reset_for_tests()
    cp.reset_for_tests()


# --- enabled/disabled contract ----------------------------------------------


def test_disabled_span_is_the_shared_noop_object():
    """The overhead gate's structural half: with tracing off, span()
    returns ONE shared object — no allocation, no recorder traffic
    (identity-pinned, per the acceptance criteria)."""
    trace.set_enabled(False)
    s1 = trace.span("scheduler.solve.batch")
    s2 = trace.span("scheduler.solve.pack", attrs={"x": 1})
    assert s1 is trace.NOOP_SPAN and s2 is trace.NOOP_SPAN
    with s1 as inner:
        assert inner is trace.NOOP_SPAN
        inner.event("anything")
        inner.set_attr("k", "v")
        assert inner.context() is None
    trace.record_span("scheduler.claim.allocated", 0.0, 1.0)
    assert trace.RECORDER.spans() == []
    assert trace.new_ctx() is None


def test_disabled_extract_returns_none():
    trace.set_enabled(False)
    obj = {"metadata": {"annotations": {trace.TRACE_ANNOTATION: "a:b"}}}
    assert trace.extract(obj) is None


# --- span mechanics ----------------------------------------------------------


def test_ambient_parenting_and_events():
    with trace.span("scheduler.solve.batch", root=True) as outer:
        with trace.span("scheduler.solve.pack") as inner:
            inner.event("mark", detail=7)
        assert inner.trace_id == outer.trace_id
        assert inner.parent_id == outer.span_id
    spans = {s["name"]: s for s in trace.RECORDER.spans()}
    assert spans["scheduler.solve.pack"]["events"][0]["name"] == "mark"
    assert spans["scheduler.solve.pack"]["events"][0]["detail"] == 7
    assert spans["scheduler.solve.batch"]["status"] == "ok"


def test_exception_marks_status_and_still_records():
    with pytest.raises(ValueError):
        with trace.span("scheduler.solve.batch", root=True):
            raise ValueError("boom")
    (s,) = trace.RECORDER.spans()
    assert s["status"] == "error: ValueError"


def test_ctx_adoption_overrides_ambient():
    ctx = trace.new_ctx()
    with trace.span("scheduler.solve.batch", root=True):
        s = trace.span("plugin.claim.prepare", ctx=ctx)
        s.end()
    assert s.trace_id == ctx.trace_id and s.parent_id == ctx.span_id


def test_context_encode_decode_roundtrip_and_malformed():
    ctx = trace.new_ctx()
    back = trace.SpanContext.decode(ctx.encode())
    assert (back.trace_id, back.span_id) == (ctx.trace_id, ctx.span_id)
    for bad in ("", "nocolon", ":", "a:", ":b", None):
        assert trace.SpanContext.decode(bad or "") is None


def test_stamp_and_extract_on_claim_dicts():
    claim = {"metadata": {"name": "c"}}
    ctx = trace.new_ctx()
    trace.stamp(claim, ctx)
    got = trace.extract(claim)
    assert got.trace_id == ctx.trace_id and got.span_id == ctx.span_id
    trace.stamp(claim, None)  # no-op, never raises
    assert trace.extract({"metadata": {}}) is None


# --- flight recorder ---------------------------------------------------------


def test_recorder_bounded_drop_oldest_and_counter():
    trace.RECORDER.capacity = 4
    metrics = Metrics()
    trace.RECORDER.bind_metrics(metrics)
    for i in range(7):
        s = trace.span("scheduler.solve.batch", root=True,
                       attrs={"i": i})
        s.end()
    spans = trace.RECORDER.spans()
    assert len(spans) == 4
    assert [s["attrs"]["i"] for s in spans] == [3, 4, 5, 6]  # oldest out
    assert trace.RECORDER.dropped == 3
    assert metrics.get_counter("trace_spans_dropped_total") == 3


def test_chrome_export_and_text_timeline(tmp_path):
    with trace.span("scheduler.claim.pending", root=True) as pend:
        pend.event("seen")
        with trace.span("scheduler.claim.allocated"):
            pass
    path = str(tmp_path / "t.json")
    n = trace.RECORDER.export_chrome(path)
    doc = json.loads(open(path).read())
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    instants = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert len(xs) == 2 and len(instants) == 1 and n == 3
    assert all(e["args"]["trace"] == pend.trace_id for e in xs)
    text = trace.RECORDER.render_text(pend.trace_id)
    assert "scheduler.claim.pending" in text
    # The child renders nested (two-space indent under its parent).
    assert "\n  " in text and "scheduler.claim.allocated" in text


# --- WAL / crash survival of the trace context (satellite 3) -----------------


def _stamp_claims(cluster, names):
    claims = ResourceClient(cluster, RESOURCE_CLAIMS)
    ctxs = {}
    for name in names:
        c = claims.try_get(name, NS)
        ctxs[name] = trace.new_ctx()
        trace.stamp(c, ctxs[name])
        claims.update(c)
    return ctxs


def test_trace_ctx_survives_full_migration():
    """The repacker's two-phase WAL rewrites the claim (annotations AND
    status) at every phase; the trace ctx annotation must ride through
    untouched, and the migration span must adopt the claim's trace id
    with the phase transitions as events."""
    cluster = make_cluster()
    a, b = spread_two(cluster)
    ctxs = _stamp_claims(cluster, (a, b))
    rp = mk_repacker(cluster, RecordingAdapter())
    for _ in range(8):
        rp.tick()
    assert rp.migrations == 1
    for name in (a, b):
        got = trace.extract(claim_of(cluster, name))
        assert got is not None, f"trace ctx annotation lost on {name}"
        assert got.trace_id == ctxs[name].trace_id
    migrate = [
        s for s in trace.RECORDER.spans()
        if s["name"] == "repacker.claim.migrate"
    ]
    assert len(migrate) == 1
    moved_name = migrate[0]["attrs"]["claim"].split("/", 1)[1]
    assert migrate[0]["trace"] == ctxs[moved_name].trace_id
    phases = [e["name"] for e in migrate[0]["events"]]
    assert phases == [
        "phase.planned", "phase.evacuated", "phase.released",
        "phase.committed",
    ]


@pytest.mark.parametrize("point", [
    "repack.migrate.after_plan_persisted",
    "repack.migrate.after_evacuate",
    "repack.migrate.between_unprepare_prepare",
    "repack.migrate.before_commit",
])
def test_trace_ctx_survives_repack_crash_and_recovery(point):
    """Kill the repacker at every WAL window, recover with a fresh
    instance: the claim's ctx annotation is intact, the recovered
    timeline still stitches into the SAME trace id (the recovery span
    adopts it), and recovery rows land as span events."""
    cluster = make_cluster()
    a, b = spread_two(cluster)
    ctxs = _stamp_claims(cluster, (a, b))
    rp = mk_repacker(cluster, RecordingAdapter())
    with cp.arm(point):
        with pytest.raises(cp.SimulatedCrash):
            for _ in range(8):
                rp.tick()
    # The dead leader's claim still carries BOTH annotations (or the
    # repack one resolved); the trace ctx always survives.
    for name in (a, b):
        got = trace.extract(claim_of(cluster, name))
        assert got is not None, (
            f"trace ctx lost at {point} on {name}"
        )
        assert got.trace_id == ctxs[name].trace_id
    # Fresh leader recovers; the recovery span must join the claim's
    # trace and carry the recovery row as an event.
    rp2 = mk_repacker(cluster, RecordingAdapter())
    rp2.recover()
    for _ in range(8):
        rp2.tick()
    for name in (a, b):
        c = claim_of(cluster, name)
        from tpu_dra.scheduler.repacker import repack_state
        assert repack_state(c) is None, "WAL annotation not resolved"
        assert trace.extract(c).trace_id == ctxs[name].trace_id
    recovery = [
        s for s in trace.RECORDER.spans()
        if s["name"] == "repacker.claim.migrate"
        and s["attrs"].get("recovery")
    ]
    assert recovery, f"no recovery span recorded after crash at {point}"
    rec = recovery[-1]
    moved_name = rec["attrs"]["claim"].split("/", 1)[1]
    assert rec["trace"] == ctxs[moved_name].trace_id, (
        "recovered timeline does not stitch into the original trace id"
    )
    assert any(e["name"] == "recovered" for e in rec["events"])


@pytest.mark.parametrize("point", [
    "plugin.prepare.after_wal_started",
    "plugin.prepare.between_devices",
    "plugin.prepare.before_wal_completed",
])
def test_prepare_crash_retry_stitches_one_trace(point, tmp_path):
    """A kill at any prepare WAL window + the kubelet's retry: both the
    crashed and the recovered prepare spans carry the claim's ONE trace
    id (no orphan spans), and the crossed crash-point windows are
    visible as events on the crashed span."""
    from tests.test_plugin_device_state import make_state
    from tests.helpers import make_claim

    state, _ = make_state(tmp_path)
    claim = make_claim(["tpu-0"])
    ctx = trace.new_ctx()
    trace.stamp(claim, ctx)
    with cp.arm(point):
        with pytest.raises(cp.SimulatedCrash):
            state.prepare(claim)
    devices = state.prepare(claim)  # the kubelet retry converges
    assert len(devices) == 1
    prepares = [
        s for s in trace.RECORDER.spans()
        if s["name"] == "plugin.claim.prepare"
    ]
    assert len(prepares) == 2
    assert {s["trace"] for s in prepares} == {ctx.trace_id}, (
        "retry prepare did not stitch into the claim's trace"
    )
    crashed = prepares[0]
    assert crashed["status"] == "error: SimulatedCrash"
    crossed = [
        e["point"] for e in crashed["events"]
        if e["name"] == "crashpoint"
    ]
    assert crossed and crossed[-1] == point, (
        f"crash-point windows not on the timeline: {crossed}"
    )


# --- scheduler stamping ------------------------------------------------------


def test_scheduler_commit_stamps_ctx_annotation():
    """_commit writes the allocation AND the ctx annotation in ONE
    update; the pending span ends with the claim's trace id matching
    the stamped annotation."""
    from tpu_dra.k8sclient import FakeCluster
    from tpu_dra.scheduler.core import SchedulerCore

    cluster = make_cluster()
    core = SchedulerCore(cluster)
    claims = ResourceClient(cluster, RESOURCE_CLAIMS)
    from tpu_dra.scheduler import fleet
    c = fleet.make_claim(0, "1x1x1")
    c["metadata"]["namespace"] = NS
    claims.create(c)
    stored = claims.try_get(c["metadata"]["name"], NS)
    core._ensure_claim_span(stored)

    class _Res:
        allocation = {"devices": {"results": [{
            "request": "tpu", "driver": fleet.DRIVER,
            "pool": fleet.node_name(0), "device": "ss-1x1x1-0-0-0",
        }]}}

    assert core._commit(stored, _Res())
    live = claims.try_get(c["metadata"]["name"], NS)
    ctx = trace.extract(live)
    assert ctx is not None
    assert (live.get("status") or {}).get("allocation")
    pend = [
        s for s in trace.RECORDER.spans()
        if s["name"] == "scheduler.claim.pending"
    ]
    assert len(pend) == 1 and pend[0]["trace"] == ctx.trace_id
    alloc_spans = [
        s for s in trace.RECORDER.spans()
        if s["name"] == "scheduler.claim.allocated"
    ]
    assert len(alloc_spans) == 1
    assert alloc_spans[0]["parent"] == ctx.span_id


# --- /debug/traces + doctor explain ------------------------------------------


def _claim_shaped_trace():
    """A synthetic claim lifecycle in the recorder; returns (trace_id,
    submit->ready window in seconds)."""
    t0 = time.monotonic()
    with trace.span("scheduler.claim.pending", root=True,
                    attrs={"claim": f"{NS}/c0"}) as pend:
        time.sleep(0.02)
        ctx = pend.context()
    trace.record_span(
        "scheduler.claim.allocated", t0 + 0.015, t0 + 0.02, ctx=ctx,
    )
    with trace.span("kubelet.claim.prepare", ctx=ctx) as prep:
        time.sleep(0.03)
    t1 = prep.t1
    return ctx.trace_id, t1 - t0


def test_debug_traces_endpoint_serves_recorder():
    metrics = Metrics()
    trace.RECORDER.bind_metrics(metrics)
    trace_id, _ = _claim_shaped_trace()
    server = MetricsServer(metrics, port=0, address="127.0.0.1")
    server.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/debug/traces", timeout=5
        ) as r:
            doc = json.loads(r.read().decode())
    finally:
        server.stop()
    names = {s["name"] for s in doc["spans"]
             if s["trace"] == trace_id}
    assert names == {
        "scheduler.claim.pending", "scheduler.claim.allocated",
        "kubelet.claim.prepare",
    }
    assert doc["dropped"] == 0


def test_doctor_explain_stage_budget_sums_to_window(capsys):
    """`doctor explain --trace-id ... --trace-endpoint ...` stitches
    the recorder dump and prints a stage budget whose rows (stages +
    unattributed) sum to the claim's submit->ready window within 5% —
    the acceptance bar's in-process half."""
    trace_id, window = _claim_shaped_trace()
    server = MetricsServer(Metrics(), port=0, address="127.0.0.1")
    server.start()
    try:
        rc = doctor.main([
            "explain", "--trace-id", trace_id,
            "--trace-endpoint", f"127.0.0.1:{server.port}",
            "--json",
        ])
    finally:
        server.stop()
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    budget = doc["budget"]
    total = sum(budget["stages"].values()) + budget["unattributed_s"]
    assert budget["window_s"] == pytest.approx(window, rel=0.05)
    assert total == pytest.approx(budget["window_s"], rel=0.05)
    # The dominant stage is the kubelet prepare (the 30ms sleep).
    top = max(budget["stages"], key=budget["stages"].get)
    assert top == "kubelet.claim.prepare"


def test_doctor_explain_fetches_claim_annotation():
    """--claim ns/name resolves the trace id through the apiserver
    annotation (the operator-facing entry point)."""
    cluster = make_cluster()
    claims = ResourceClient(cluster, RESOURCE_CLAIMS)
    from tpu_dra.scheduler import fleet
    c = fleet.make_claim(0, "1x1x1")
    c["metadata"]["namespace"] = NS
    claims.create(c)
    stored = claims.try_get(c["metadata"]["name"], NS)
    ctx = trace.new_ctx()
    trace.stamp(stored, ctx)
    claims.update(stored)
    raw = (claims.try_get(c["metadata"]["name"], NS)["metadata"]
           ["annotations"][trace.TRACE_ANNOTATION])
    got = trace.SpanContext.decode(raw)
    assert got.trace_id == ctx.trace_id


def test_doctor_warns_on_capped_series():
    warns = []
    doctor._check_cardinality(
        "ep:1",
        {'tpu_dra_metrics_series_capped_total{name="per_claim"}': 5.0},
        warns.append,
    )
    assert warns and "DROPPED" in warns[0]
    assert not doctor._check_cardinality(
        "ep:1", {"tpu_dra_prepare_total": 3.0}, warns.append,
    )
    assert len(warns) == 1


# --- review-hardening pins ----------------------------------------------------


def test_stage_budget_overlapping_siblings_sum_to_window():
    """A serving-shaped trace: first_token (submit->t_first) fully
    overlaps its prefill/dispatch siblings. Deepest-covering
    attribution keeps the rows summing to the window — per-span
    self-time would sum to ~200%."""
    t0 = time.monotonic()
    ctx = trace.new_ctx()
    trace.record_span("serving.request.queued", t0, t0 + 0.010,
                      self_ctx=ctx)
    trace.record_span("serving.request.prefill", t0 + 0.010, t0 + 0.050,
                      ctx=ctx)
    trace.record_span("serving.request.first_token", t0, t0 + 0.050,
                      ctx=ctx)
    spans = trace.RECORDER.spans()
    budget = doctor.stage_budget(spans)
    total = sum(budget["stages"].values()) + budget["unattributed_s"]
    assert total == pytest.approx(budget["window_s"], rel=1e-6)
    # The prefill window is attributed to prefill (later-started
    # sibling wins the tie), the pre-dispatch wait to first_token.
    # wall anchors are derived per record_span call, so boundaries
    # carry µs-level jitter — compare with a loose absolute tolerance.
    assert budget["stages"]["serving.request.prefill"] == (
        pytest.approx(0.040, abs=1e-3)
    )
    assert budget["stages"]["serving.request.first_token"] == (
        pytest.approx(0.010, abs=1e-3)
    )


def test_empty_batch_records_no_solve_spans():
    """A no-op reconcile (nothing pending) must not churn the ring:
    busy fleets fire batch items on every event."""
    cluster = make_cluster()
    from tpu_dra.scheduler.core import SchedulerCore

    core = SchedulerCore(cluster)
    core._reconcile_batch(None)
    assert trace.RECORDER.spans() == []


def test_claim_span_pruned_when_claim_vanishes_mid_solve():
    """A claim deleted between two batch passes (DELETE handler ran
    before the span was re-minted) must not leak its entry forever:
    the next batch prunes anything not in its pending snapshot."""
    cluster = make_cluster()
    from tpu_dra.scheduler import fleet
    from tpu_dra.scheduler.core import SchedulerCore

    core = SchedulerCore(cluster)
    claims = ResourceClient(cluster, RESOURCE_CLAIMS)
    c = fleet.make_claim(0, "1x1x1")
    c["metadata"]["namespace"] = NS
    claims.create(c)
    stored = claims.try_get(c["metadata"]["name"], NS)
    core._ensure_claim_span(stored)
    assert len(core._claim_spans) == 1
    claims.delete(c["metadata"]["name"], NS)
    core._reconcile_batch(None)
    assert core._claim_spans == {}
    gone = [
        s for s in trace.RECORDER.spans()
        if s["name"] == "scheduler.claim.pending"
    ]
    assert gone and gone[-1]["status"] == "gone"
