"""Validating admission webhook tests.

Modeled on cmd/webhook/main_test.go (reference, 520 LoC): full
admission-review round-trips through a live HTTP server, valid and invalid
opaque configs, ResourceClaim and ResourceClaimTemplate GVRs across
resource.k8s.io v1beta1/v1beta2/v1, content-type and malformed-body errors.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from tpu_dra.api.serde import encode
from tpu_dra.api.configs import (
    ComputeDomainChannelConfig,
    TpuConfig,
)
from tpu_dra.api.sharing import (
    MULTIPLEXING_STRATEGY,
    TIME_SLICING_STRATEGY,
    MultiplexingConfig,
    TimeSlicingConfig,
    TpuSharing,
)
from tpu_dra.infra import featuregates as fg
from tpu_dra.webhook.server import (
    CD_DRIVER_NAME,
    DRIVER_NAME,
    admit_resource_claim_parameters,
    handle_admission_request,
    make_server,
)

CD_UID = "8d7d6d3e-1111-4222-8333-444455556666"


def gates(**kwargs):
    g = fg.FeatureGates()
    for k, v in kwargs.items():
        g.set(k, v)
    fg.reset_for_tests(g)


# --- AdmissionReview builders (main_test.go helper analogs) -----------------


def opaque_config(obj, driver=DRIVER_NAME) -> dict:
    return {"opaque": {"driver": driver, "parameters": json.loads(encode(obj))}}


def claim_with_configs(version: str, *configs) -> tuple[dict, dict]:
    resource = {
        "group": "resource.k8s.io",
        "version": version,
        "resource": "resourceclaims",
    }
    obj = {
        "apiVersion": f"resource.k8s.io/{version}",
        "kind": "ResourceClaim",
        "spec": {"devices": {"config": list(configs)}},
    }
    return resource, obj


def template_with_configs(version: str, *configs) -> tuple[dict, dict]:
    resource = {
        "group": "resource.k8s.io",
        "version": version,
        "resource": "resourceclaimtemplates",
    }
    obj = {
        "apiVersion": f"resource.k8s.io/{version}",
        "kind": "ResourceClaimTemplate",
        "spec": {"spec": {"devices": {"config": list(configs)}}},
    }
    return resource, obj


def admission_review(resource: dict, obj: dict, uid="test-uid-123") -> dict:
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {"uid": uid, "resource": resource, "object": obj},
    }


def valid_tpu_config() -> TpuConfig:
    return TpuConfig(
        sharing=TpuSharing(
            strategy=TIME_SLICING_STRATEGY,
            time_slicing_config=TimeSlicingConfig(interval="Default"),
        )
    )


def invalid_interval_config() -> TpuConfig:
    return TpuConfig(
        sharing=TpuSharing(
            strategy=TIME_SLICING_STRATEGY,
            time_slicing_config=TimeSlicingConfig(interval="Invalid Interval"),
        )
    )


def invalid_multiplexing_config() -> TpuConfig:
    return TpuConfig(
        sharing=TpuSharing(
            strategy=MULTIPLEXING_STRATEGY,
            multiplexing_config=MultiplexingConfig(
                default_compute_share_percentage=-1
            ),
        )
    )


# --- Live-server fixture ----------------------------------------------------


@pytest.fixture()
def webhook_url():
    server = make_server(0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        yield f"http://127.0.0.1:{server.server_address[1]}"
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=5)


def post(url, body: bytes, content_type="application/json"):
    req = urllib.request.Request(
        url + "/validate-resource-claim-parameters",
        data=body,
        headers={"Content-Type": content_type},
        method="POST",
    )
    try:
        resp = urllib.request.urlopen(req)
        return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# --- HTTP-level behavior (TestReadyEndpoint + serve()) ----------------------


def test_readyz(webhook_url):
    with urllib.request.urlopen(webhook_url + "/readyz") as resp:
        assert resp.status == 200
        assert resp.read() == b"ok"


def test_tls_round_trip(tmp_path):
    """The HTTPS path the chart deploys (main.go:112-124 analog): a real
    TLS handshake against a generated serving cert, with the client
    pinning it as CA — not just the bare handler."""
    import ssl

    pytest.importorskip("cryptography")
    from tpu_dra.webhook.certs import generate_self_signed

    cert, key = generate_self_signed(
        str(tmp_path / "tls.crt"), str(tmp_path / "tls.key")
    )
    server = make_server(0, cert_file=cert, key_file=key)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        url = f"https://127.0.0.1:{server.server_address[1]}"
        ctx = ssl.create_default_context(cafile=cert)
        resource, obj = claim_with_configs(
            "v1beta1", opaque_config(valid_tpu_config())
        )
        gates(TimeSlicingSettings=True)
        body = json.dumps(admission_review(resource, obj)).encode()
        req = urllib.request.Request(
            url + "/validate-resource-claim-parameters",
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, context=ctx) as resp:
            out = json.loads(resp.read())
        assert out["response"]["allowed"] is True
        # Unpinned client must fail the handshake (proves TLS is real).
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(
                url + "/readyz", context=ssl.create_default_context()
            )
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=5)


def test_unknown_path_404(webhook_url):
    req = urllib.request.Request(
        webhook_url + "/nope", data=b"{}", method="POST",
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req)
    assert ei.value.code == 404


def test_bad_content_type(webhook_url):
    status, _ = post(webhook_url, b"{}", content_type="invalid type")
    assert status == 415


def test_invalid_admission_review(webhook_url):
    status, _ = post(webhook_url, json.dumps({}).encode())
    assert status == 400


def test_malformed_json(webhook_url):
    status, _ = post(webhook_url, b"{not json")
    assert status == 400


def test_missing_request_field():
    body = json.dumps(
        {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview"}
    ).encode()
    status, _, _, _ = handle_admission_request(body, "application/json")
    assert status == 400


def test_wrong_gvk_rejected():
    body = json.dumps(
        {"apiVersion": "admission.k8s.io/v1beta1", "kind": "AdmissionReview",
         "request": {"uid": "u"}}
    ).encode()
    status, _, _, _ = handle_admission_request(body, "application/json")
    assert status == 400


# --- Admission verdicts through the live server -----------------------------


def roundtrip(webhook_url, review: dict):
    status, body = post(webhook_url, json.dumps(review).encode())
    assert status == 200
    out = json.loads(body)
    assert out["apiVersion"] == "admission.k8s.io/v1"
    assert out["kind"] == "AdmissionReview"
    assert out["response"]["uid"] == review["request"]["uid"]
    return out["response"]


@pytest.mark.parametrize("version", ["v1beta1", "v1beta2", "v1"])
def test_valid_config_in_resource_claim(webhook_url, version):
    gates(TimeSlicingSettings=True)
    resource, obj = claim_with_configs(
        version, opaque_config(valid_tpu_config())
    )
    resp = roundtrip(webhook_url, admission_review(resource, obj))
    assert resp.get("allowed") is True


@pytest.mark.parametrize("version", ["v1beta1", "v1beta2", "v1"])
def test_valid_config_in_resource_claim_template(webhook_url, version):
    gates(TimeSlicingSettings=True)
    resource, obj = template_with_configs(
        version, opaque_config(valid_tpu_config())
    )
    resp = roundtrip(webhook_url, admission_review(resource, obj))
    assert resp.get("allowed") is True


def test_invalid_configs_in_resource_claim(webhook_url):
    gates(TimeSlicingSettings=True, MultiplexingSupport=True)
    resource, obj = claim_with_configs(
        "v1beta1",
        opaque_config(invalid_interval_config()),
        opaque_config(invalid_multiplexing_config()),
    )
    resp = roundtrip(webhook_url, admission_review(resource, obj))
    assert resp.get("allowed") is not True
    msg = resp["status"]["message"]
    assert msg.startswith("2 configs failed to validate:")
    assert "spec.devices.config[0].opaque.parameters" in msg
    assert "spec.devices.config[1].opaque.parameters" in msg


def test_invalid_configs_in_resource_claim_template(webhook_url):
    gates(TimeSlicingSettings=True, MultiplexingSupport=True)
    resource, obj = template_with_configs(
        "v1beta1",
        opaque_config(invalid_interval_config()),
        opaque_config(invalid_multiplexing_config()),
    )
    resp = roundtrip(webhook_url, admission_review(resource, obj))
    assert resp.get("allowed") is not True
    msg = resp["status"]["message"]
    # field path reflects the template's nested spec (specPath="spec.spec")
    assert "spec.spec.devices.config[0].opaque.parameters" in msg
    assert "spec.spec.devices.config[1].opaque.parameters" in msg


def test_unsupported_resource_rejected(webhook_url):
    resource = {"group": "apps", "version": "v1", "resource": "deployments"}
    resp = roundtrip(webhook_url, admission_review(resource, {"spec": {}}))
    assert resp.get("allowed") is not True
    assert resp["status"]["reason"] == "BadRequest"


# --- Unit-level admit behavior ---------------------------------------------


def test_foreign_driver_config_skipped():
    # Another driver's opaque config must not be decoded or validated.
    resource, obj = claim_with_configs(
        "v1beta1",
        {"opaque": {"driver": "gpu.example.com", "parameters": {"bogus": 1}}},
    )
    resp = admit_resource_claim_parameters(admission_review(resource, obj))
    assert resp.get("allowed") is True


def test_unknown_fields_rejected_strictly():
    resource, obj = claim_with_configs("v1beta1", opaque_config(TpuConfig()))
    obj["spec"]["devices"]["config"][0]["opaque"]["parameters"]["bogus"] = 1
    resp = admit_resource_claim_parameters(admission_review(resource, obj))
    assert resp.get("allowed") is not True
    assert "error decoding object" in resp["status"]["message"]
    assert "bogus" in resp["status"]["message"]


def test_unregistered_kind_rejected():
    resource, obj = claim_with_configs(
        "v1beta1",
        {
            "opaque": {
                "driver": DRIVER_NAME,
                "parameters": {
                    "apiVersion": "resource.tpu.google.com/v1beta1",
                    "kind": "NoSuchKind",
                },
            }
        },
    )
    resp = admit_resource_claim_parameters(admission_review(resource, obj))
    assert resp.get("allowed") is not True
    assert "error decoding object" in resp["status"]["message"]


def test_missing_parameters_rejected():
    resource, obj = claim_with_configs(
        "v1beta1", {"opaque": {"driver": DRIVER_NAME}}
    )
    resp = admit_resource_claim_parameters(admission_review(resource, obj))
    assert resp.get("allowed") is not True
    assert "missing parameters" in resp["status"]["message"]


def test_compute_domain_channel_config_validated():
    # CD configs carry the compute-domain driver name; they are validated too
    # (improvement over the reference, which filters them out).
    bad = ComputeDomainChannelConfig(domain_id="not-a-uuid")
    resource, obj = claim_with_configs(
        "v1beta1", opaque_config(bad, driver=CD_DRIVER_NAME)
    )
    resp = admit_resource_claim_parameters(admission_review(resource, obj))
    assert resp.get("allowed") is not True
    assert "domainID must be a UUID" in resp["status"]["message"]

    good = ComputeDomainChannelConfig(domain_id=CD_UID, allocation_mode="All")
    resource, obj = claim_with_configs(
        "v1beta1", opaque_config(good, driver=CD_DRIVER_NAME)
    )
    resp = admit_resource_claim_parameters(admission_review(resource, obj))
    assert resp.get("allowed") is True


def test_no_configs_allowed():
    resource, obj = claim_with_configs("v1beta1")
    resp = admit_resource_claim_parameters(admission_review(resource, obj))
    assert resp.get("allowed") is True


@pytest.mark.parametrize(
    "mutate",
    [
        lambda rev: rev["request"].__setitem__("resource", "not-a-dict"),
        lambda rev: rev["request"].__setitem__("object", {"spec": []}),
        lambda rev: rev["request"].__setitem__(
            "object", {"spec": {"devices": "nope"}}
        ),
    ],
)
def test_structurally_malformed_objects_denied_not_crashed(webhook_url, mutate):
    # Valid JSON with wrong shapes must come back as a structured deny, not a
    # dropped connection (failurePolicy=Ignore would fail open otherwise).
    resource, obj = claim_with_configs(
        "v1", {"opaque": {"driver": DRIVER_NAME, "parameters": {}}}
    )
    review = admission_review(resource, obj)
    mutate(review)
    resp = roundtrip(webhook_url, review)
    assert resp.get("allowed") is not True


def test_non_object_opaque_skipped_not_crashed(webhook_url):
    # opaque as a non-object can't name our driver; it is skipped (the
    # apiserver's own schema validation rejects it) rather than crashing.
    resource, obj = claim_with_configs("v1", {"opaque": "x"})
    resp = roundtrip(webhook_url, admission_review(resource, obj))
    assert resp.get("allowed") is True


def test_gated_strategy_denied_when_gate_off():
    # Multiplexing strategy without the MultiplexingSupport gate must fail
    # validation at admission time (sharing.go validation parity).
    gates(MultiplexingSupport=False)
    cfg = TpuConfig(
        sharing=TpuSharing(
            strategy=MULTIPLEXING_STRATEGY,
            multiplexing_config=MultiplexingConfig(),
        )
    )
    resource, obj = claim_with_configs("v1beta1", opaque_config(cfg))
    resp = admit_resource_claim_parameters(admission_review(resource, obj))
    assert resp.get("allowed") is not True


def test_admission_metrics_counters(webhook_url):
    """GET /metrics reports per-outcome admission counters (the
    reference webhook has no observability surface)."""
    import json as jsonlib

    from tpu_dra.webhook.server import METRICS

    def count(outcome):
        text = METRICS.render()
        for ln in text.splitlines():
            if "admission_requests_total" in ln and outcome in ln:
                return float(ln.rsplit(" ", 1)[1])
        return 0.0

    base_allowed = count("allowed")
    review = {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {
            "uid": "m1",
            "resource": {
                "group": "resource.k8s.io",
                "version": "v1beta1",
                "resource": "resourceclaims",
            },
            "object": {"spec": {"devices": {}}},
        },
    }
    status, _ = post(webhook_url, jsonlib.dumps(review).encode())
    assert status == 200
    assert count("allowed") == base_allowed + 1
    with urllib.request.urlopen(webhook_url + "/metrics") as resp:
        assert resp.status == 200
        assert "admission_requests_total" in resp.read().decode()
