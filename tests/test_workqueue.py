"""Work-queue tests.

Reference analog: pkg/workqueue/workqueue_test.go — retry on failure,
per-key coalescing (newer item cancels older retries), limiter behavior.
"""

import threading
import time

from tpu_dra.infra.workqueue import (
    BucketRateLimiter,
    ItemExponentialFailureRateLimiter,
    JitterRateLimiter,
    MaxOfRateLimiter,
    WorkQueue,
)


def _run(q):
    t = q.run_in_thread()
    return t


def test_success_path():
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.01))
    done = threading.Event()
    q.enqueue("obj", lambda o: done.set(), key="k")
    _run(q)
    assert done.wait(2)
    q.shutdown()


def test_retry_until_success():
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.01))
    calls = []
    done = threading.Event()

    def cb(obj):
        calls.append(obj)
        if len(calls) < 3:
            raise RuntimeError("transient")
        done.set()

    q.enqueue("x", cb, key="k")
    _run(q)
    assert done.wait(5)
    assert len(calls) == 3
    q.shutdown()


def test_per_key_coalescing_cancels_old_retries():
    """A newer enqueued item under the same key forgets the older item's
    retries (workqueue.go:171-176)."""
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.05, 0.05))
    seen = []
    new_done = threading.Event()
    old_started = threading.Event()

    def old_cb(obj):
        seen.append("old")
        old_started.set()
        raise RuntimeError("always fails")

    def new_cb(obj):
        seen.append("new")
        new_done.set()

    q.enqueue("o", old_cb, key="k")
    _run(q)
    assert old_started.wait(2)
    q.enqueue("n", new_cb, key="k")
    assert new_done.wait(2)
    time.sleep(0.3)  # old item retry window; it must not run again after drop
    q.shutdown()
    assert seen.count("new") == 1
    # old may run at most once more (a retry already scheduled before the
    # newer enqueue), but must not keep retrying forever.
    assert seen.count("old") <= 2


def test_exponential_limiter():
    rl = ItemExponentialFailureRateLimiter(0.1, 1.0)
    assert rl.when("a") == 0.1
    assert rl.when("a") == 0.2
    assert rl.when("a") == 0.4
    assert rl.when("b") == 0.1  # independent per key
    rl.forget("a")
    assert rl.when("a") == 0.1


def test_bucket_limiter_burst_then_throttle():
    rl = BucketRateLimiter(qps=10.0, burst=2)
    assert rl.when("k") == 0.0
    assert rl.when("k") == 0.0
    assert rl.when("k") > 0.0


def test_jitter_limiter_bounds():
    inner = ItemExponentialFailureRateLimiter(1.0, 1.0)
    rl = JitterRateLimiter(inner, 0.5)
    for _ in range(20):
        d = rl.when("k")
        assert 0.75 <= d <= 1.25


def test_max_of_limiter():
    a = ItemExponentialFailureRateLimiter(0.5, 10.0)
    b = ItemExponentialFailureRateLimiter(0.1, 10.0)
    rl = MaxOfRateLimiter(a, b)
    assert rl.when("k") == 0.5


def test_backoff_is_per_item_not_per_key():
    """A fresh enqueue starts at base delay even after another item failed
    repeatedly (reference rate-limits on the WorkItem pointer)."""
    from tpu_dra.infra.workqueue import WorkItem

    rl = ItemExponentialFailureRateLimiter(0.25, 3.0)
    a = WorkItem(key="", obj=None, callback=lambda o: None)
    for _ in range(5):
        rl.when(a)
    b = WorkItem(key="", obj=None, callback=lambda o: None)
    assert rl.when(b) == 0.25
