"""Work-queue tests.

Reference analog: pkg/workqueue/workqueue_test.go — retry on failure,
per-key coalescing (newer item cancels older retries), limiter behavior.
"""

import threading
import time

from tpu_dra.infra.workqueue import (
    BucketRateLimiter,
    ItemExponentialFailureRateLimiter,
    JitterRateLimiter,
    MaxOfRateLimiter,
    WorkQueue,
)


def _run(q):
    t = q.run_in_thread()
    return t


def test_success_path():
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.01))
    done = threading.Event()
    q.enqueue("obj", lambda o: done.set(), key="k")
    _run(q)
    assert done.wait(2)
    q.shutdown()


def test_retry_until_success():
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.01))
    calls = []
    done = threading.Event()

    def cb(obj):
        calls.append(obj)
        if len(calls) < 3:
            raise RuntimeError("transient")
        done.set()

    q.enqueue("x", cb, key="k")
    _run(q)
    assert done.wait(5)
    assert len(calls) == 3
    q.shutdown()


def test_per_key_coalescing_cancels_old_retries():
    """A newer enqueued item under the same key forgets the older item's
    retries (workqueue.go:171-176)."""
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.05, 0.05))
    seen = []
    new_done = threading.Event()
    old_started = threading.Event()

    def old_cb(obj):
        seen.append("old")
        old_started.set()
        raise RuntimeError("always fails")

    def new_cb(obj):
        seen.append("new")
        new_done.set()

    q.enqueue("o", old_cb, key="k")
    _run(q)
    assert old_started.wait(2)
    q.enqueue("n", new_cb, key="k")
    assert new_done.wait(2)
    time.sleep(0.3)  # old item retry window; it must not run again after drop
    q.shutdown()
    assert seen.count("new") == 1
    # old may run at most once more (a retry already scheduled before the
    # newer enqueue), but must not keep retrying forever.
    assert seen.count("old") <= 2


def test_exponential_limiter():
    rl = ItemExponentialFailureRateLimiter(0.1, 1.0)
    assert rl.when("a") == 0.1
    assert rl.when("a") == 0.2
    assert rl.when("a") == 0.4
    assert rl.when("b") == 0.1  # independent per key
    rl.forget("a")
    assert rl.when("a") == 0.1


def test_bucket_limiter_burst_then_throttle():
    rl = BucketRateLimiter(qps=10.0, burst=2)
    assert rl.when("k") == 0.0
    assert rl.when("k") == 0.0
    assert rl.when("k") > 0.0


def test_jitter_limiter_bounds():
    inner = ItemExponentialFailureRateLimiter(1.0, 1.0)
    rl = JitterRateLimiter(inner, 0.5)
    for _ in range(20):
        d = rl.when("k")
        assert 0.75 <= d <= 1.25


def test_max_of_limiter():
    a = ItemExponentialFailureRateLimiter(0.5, 10.0)
    b = ItemExponentialFailureRateLimiter(0.1, 10.0)
    rl = MaxOfRateLimiter(a, b)
    assert rl.when("k") == 0.5


def test_retry_drop_hands_slot_to_newer_item():
    """Round-3 lost-retry regression: when a failed item's retry is
    dropped because a newer item arrived mid-processing, that newer item
    MUST run — the drop hands over the slot, it doesn't orphan the key."""
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.01))
    old_running = threading.Event()
    release_old = threading.Event()
    new_ran = threading.Event()

    def old_cb(obj):
        old_running.set()
        assert release_old.wait(2)
        raise RuntimeError("fails after the newer item was enqueued")

    q.enqueue("old", old_cb, key="k")
    _run(q)
    assert old_running.wait(2)
    # Newer item lands while the old one is mid-callback.
    q.enqueue("new", lambda o: new_ran.set(), key="k")
    release_old.set()
    assert new_ran.wait(2), "newer item never ran after retry drop"
    q.shutdown()


def test_event_storm_dedups_to_single_pending():
    """Fresh enqueues for one key dedup (client-go dirty set): a burst of
    N events causes at most a couple of callback runs — with the NEWEST
    snapshot — not N rate-limited heap entries (the round-3 85s-latency
    storm)."""
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.01))
    seen = []
    gate = threading.Event()
    done = threading.Event()

    def cb(obj):
        gate.wait(2)
        seen.append(obj)
        if obj == 99:
            done.set()

    for i in range(100):
        q.enqueue(i, cb, key="k")
    _run(q)
    gate.set()
    assert done.wait(2)
    q.shutdown()
    # First pop may observe any early snapshot; everything else coalesced
    # into the newest one.
    assert len(seen) <= 3, seen
    assert seen[-1] == 99


def test_fresh_enqueue_is_not_rate_limited():
    """A token-bucket limiter must pace RETRIES only: 50 distinct keys
    enqueued at once all run promptly (client-go Add vs AddRateLimited)."""
    q = WorkQueue(BucketRateLimiter(qps=1.0, burst=2))  # 1/s: storm-hostile
    done = threading.Event()
    count = []
    lock = threading.Lock()

    def cb(obj):
        with lock:
            count.append(obj)
            if len(count) == 50:
                done.set()

    t0 = time.monotonic()
    for i in range(50):
        q.enqueue(i, cb, key=f"k{i}")
    _run(q)
    assert done.wait(5)
    assert time.monotonic() - t0 < 2.0, "fresh enqueues were rate limited"
    q.shutdown()


def test_metrics_counters_exported():
    from tpu_dra.infra.metrics import Metrics

    m = Metrics()
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.01), metrics=m)
    done = threading.Event()
    calls = []

    def cb(obj):
        calls.append(obj)
        if len(calls) < 2:
            raise RuntimeError("once")
        done.set()

    q.enqueue("x", cb, key="k")
    _run(q)
    assert done.wait(2)
    q.shutdown()
    text = m.render()
    assert "workqueue_failures_total 1.0" in text
    assert "workqueue_retries_total 1.0" in text
    assert "workqueue_depth" in text


def test_backoff_is_per_item_not_per_key():
    """A fresh enqueue starts at base delay even after another item failed
    repeatedly (reference rate-limits on the WorkItem pointer)."""
    from tpu_dra.infra.workqueue import WorkItem

    rl = ItemExponentialFailureRateLimiter(0.25, 3.0)
    a = WorkItem(key="", obj=None, callback=lambda o: None)
    for _ in range(5):
        rl.when(a)
    b = WorkItem(key="", obj=None, callback=lambda o: None)
    assert rl.when(b) == 0.25
