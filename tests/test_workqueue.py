"""Work-queue tests.

Reference analog: pkg/workqueue/workqueue_test.go — retry on failure,
per-key coalescing (newer item cancels older retries), limiter behavior.
"""

import threading
import time

from tpu_dra.infra.workqueue import (
    BucketRateLimiter,
    ItemExponentialFailureRateLimiter,
    JitterRateLimiter,
    MaxOfRateLimiter,
    WorkQueue,
)


def _run(q):
    t = q.run_in_thread()
    return t


def test_success_path():
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.01))
    done = threading.Event()
    q.enqueue("obj", lambda o: done.set(), key="k")
    _run(q)
    assert done.wait(2)
    q.shutdown()


def test_retry_until_success():
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.01))
    calls = []
    done = threading.Event()

    def cb(obj):
        calls.append(obj)
        if len(calls) < 3:
            raise RuntimeError("transient")
        done.set()

    q.enqueue("x", cb, key="k")
    _run(q)
    assert done.wait(5)
    assert len(calls) == 3
    q.shutdown()


def test_per_key_coalescing_cancels_old_retries():
    """A newer enqueued item under the same key forgets the older item's
    retries (workqueue.go:171-176)."""
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.05, 0.05))
    seen = []
    new_done = threading.Event()
    old_started = threading.Event()

    def old_cb(obj):
        seen.append("old")
        old_started.set()
        raise RuntimeError("always fails")

    def new_cb(obj):
        seen.append("new")
        new_done.set()

    q.enqueue("o", old_cb, key="k")
    _run(q)
    assert old_started.wait(2)
    q.enqueue("n", new_cb, key="k")
    assert new_done.wait(2)
    time.sleep(0.3)  # old item retry window; it must not run again after drop
    q.shutdown()
    assert seen.count("new") == 1
    # old may run at most once more (a retry already scheduled before the
    # newer enqueue), but must not keep retrying forever.
    assert seen.count("old") <= 2


def test_exponential_limiter():
    rl = ItemExponentialFailureRateLimiter(0.1, 1.0)
    assert rl.when("a") == 0.1
    assert rl.when("a") == 0.2
    assert rl.when("a") == 0.4
    assert rl.when("b") == 0.1  # independent per key
    rl.forget("a")
    assert rl.when("a") == 0.1


def test_bucket_limiter_burst_then_throttle():
    rl = BucketRateLimiter(qps=10.0, burst=2)
    assert rl.when("k") == 0.0
    assert rl.when("k") == 0.0
    assert rl.when("k") > 0.0


def test_jitter_limiter_bounds():
    inner = ItemExponentialFailureRateLimiter(1.0, 1.0)
    rl = JitterRateLimiter(inner, 0.5)
    for _ in range(20):
        d = rl.when("k")
        assert 0.75 <= d <= 1.25


def test_max_of_limiter():
    a = ItemExponentialFailureRateLimiter(0.5, 10.0)
    b = ItemExponentialFailureRateLimiter(0.1, 10.0)
    rl = MaxOfRateLimiter(a, b)
    assert rl.when("k") == 0.5


def test_retry_drop_hands_slot_to_newer_item():
    """Round-3 lost-retry regression: when a failed item's retry is
    dropped because a newer item arrived mid-processing, that newer item
    MUST run — the drop hands over the slot, it doesn't orphan the key."""
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.01))
    old_running = threading.Event()
    release_old = threading.Event()
    new_ran = threading.Event()

    def old_cb(obj):
        old_running.set()
        assert release_old.wait(2)
        raise RuntimeError("fails after the newer item was enqueued")

    q.enqueue("old", old_cb, key="k")
    _run(q)
    assert old_running.wait(2)
    # Newer item lands while the old one is mid-callback.
    q.enqueue("new", lambda o: new_ran.set(), key="k")
    release_old.set()
    assert new_ran.wait(2), "newer item never ran after retry drop"
    q.shutdown()


def test_event_storm_dedups_to_single_pending():
    """Fresh enqueues for one key dedup (client-go dirty set): a burst of
    N events causes at most a couple of callback runs — with the NEWEST
    snapshot — not N rate-limited heap entries (the round-3 85s-latency
    storm)."""
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.01))
    seen = []
    gate = threading.Event()
    done = threading.Event()

    def cb(obj):
        gate.wait(2)
        seen.append(obj)
        if obj == 99:
            done.set()

    for i in range(100):
        q.enqueue(i, cb, key="k")
    _run(q)
    gate.set()
    assert done.wait(2)
    q.shutdown()
    # First pop may observe any early snapshot; everything else coalesced
    # into the newest one.
    assert len(seen) <= 3, seen
    assert seen[-1] == 99


def test_fresh_enqueue_is_not_rate_limited():
    """A token-bucket limiter must pace RETRIES only: 50 distinct keys
    enqueued at once all run promptly (client-go Add vs AddRateLimited)."""
    q = WorkQueue(BucketRateLimiter(qps=1.0, burst=2))  # 1/s: storm-hostile
    done = threading.Event()
    count = []
    lock = threading.Lock()

    def cb(obj):
        with lock:
            count.append(obj)
            if len(count) == 50:
                done.set()

    t0 = time.monotonic()
    for i in range(50):
        q.enqueue(i, cb, key=f"k{i}")
    _run(q)
    assert done.wait(5)
    assert time.monotonic() - t0 < 2.0, "fresh enqueues were rate limited"
    q.shutdown()


def test_metrics_counters_exported():
    from tpu_dra.infra.metrics import Metrics

    m = Metrics()
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.01), metrics=m)
    done = threading.Event()
    calls = []

    def cb(obj):
        calls.append(obj)
        if len(calls) < 2:
            raise RuntimeError("once")
        done.set()

    q.enqueue("x", cb, key="k")
    _run(q)
    assert done.wait(2)
    q.shutdown()
    text = m.render()
    assert "workqueue_failures_total 1.0" in text
    assert "workqueue_retries_total 1.0" in text
    assert "workqueue_depth" in text


def test_backoff_is_per_item_not_per_key():
    """A fresh enqueue starts at base delay even after another item failed
    repeatedly (reference rate-limits on the WorkItem pointer)."""
    from tpu_dra.infra.workqueue import WorkItem

    rl = ItemExponentialFailureRateLimiter(0.25, 3.0)
    a = WorkItem(key="", obj=None, callback=lambda o: None)
    for _ in range(5):
        rl.when(a)
    b = WorkItem(key="", obj=None, callback=lambda o: None)
    assert rl.when(b) == 0.25


def test_dead_letter_after_max_retries():
    """A permanently-failing keyed item stops retrying after max_retries,
    lands in dead_letters, and bumps workqueue_dead_letter_total — instead
    of hammering the backoff cap forever."""
    from tpu_dra.infra.metrics import Metrics

    m = Metrics()
    q = WorkQueue(
        ItemExponentialFailureRateLimiter(0.001, 0.005),
        metrics=m,
        max_retries=3,
    )
    calls = []

    def cb(obj):
        calls.append(obj)
        raise RuntimeError("poison")

    q.enqueue("claim-uid", cb, key="requeue/claim")
    _run(q)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not q.dead_letters:
        time.sleep(0.01)
    assert len(q.dead_letters) == 1
    assert q.dead_letters[0].key == "requeue/claim"
    # 1 initial attempt + max_retries retries, then silence.
    assert len(calls) == 4
    time.sleep(0.1)
    assert len(calls) == 4
    q.shutdown()
    assert "workqueue_dead_letter_total 1.0" in m.render()


def test_dead_letter_unkeyed_item():
    q = WorkQueue(
        ItemExponentialFailureRateLimiter(0.001, 0.005), max_retries=1
    )
    calls = []

    def cb(obj):
        calls.append(obj)
        raise RuntimeError("poison")

    q.enqueue("x", cb)
    _run(q)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not q.dead_letters:
        time.sleep(0.01)
    assert len(calls) == 2  # initial + one retry
    q.shutdown()


def test_dead_letter_key_can_be_re_enqueued_fresh():
    """Dead-lettering drops the item AND its limiter state: a later fresh
    enqueue for the same key runs again with a clean retry budget."""
    q = WorkQueue(
        ItemExponentialFailureRateLimiter(0.001, 0.005), max_retries=1
    )
    calls = []
    done = threading.Event()

    def bad(obj):
        calls.append("bad")
        raise RuntimeError("poison")

    q.enqueue("o", bad, key="k")
    _run(q)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not q.dead_letters:
        time.sleep(0.01)
    assert q.dead_letters

    q.enqueue("n", lambda o: done.set(), key="k")
    assert done.wait(2)
    q.shutdown()


def test_no_dead_letter_by_default():
    """max_retries=None (the default) keeps today's retry-forever
    semantics — reconcilers with barrier-style RetryLater callbacks depend
    on it."""
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.002))
    calls = []
    many = threading.Event()

    def cb(obj):
        calls.append(obj)
        if len(calls) >= 10:
            many.set()
        raise RuntimeError("barrier not met")

    q.enqueue("x", cb, key="k")
    _run(q)
    assert many.wait(5)
    assert not q.dead_letters
    q.shutdown()


# --- ShardedWorkQueue (ISSUE 10) ------------------------------------------


def test_sharded_routing_is_stable_and_key_sticky():
    from tpu_dra.infra.workqueue import ShardedWorkQueue

    q = ShardedWorkQueue(shards=8)
    # crc32 routing is deterministic across instances/processes (the
    # builtin hash is salted per run — a restart must not re-shard a
    # domain mid-teardown).
    q2 = ShardedWorkQueue(shards=8)
    for key in ("uid-a", "uid-b", "ns/name", ""):
        if key:
            assert q.shard_of(key) == q2.shard_of(key)
    q.shutdown()
    q2.shutdown()


def test_sharded_hot_key_does_not_starve_other_shards():
    """Satellite: a hot domain floods its shard with slow reconciles;
    cold domains on OTHER shards complete bounded by their own shard's
    service time, not the hot backlog."""
    from tpu_dra.infra.workqueue import ShardedWorkQueue

    q = ShardedWorkQueue(shards=4)
    q.run_in_threads()
    hot_shard = q.shard_of("hot-uid")
    cold_keys = [
        f"cold-{i}" for i in range(32)
        if q.shard_of(f"cold-{i}") != hot_shard
    ][:6]
    done = {}
    lock = threading.Lock()
    t0 = time.monotonic()

    def slow(_):
        time.sleep(0.005)

    def stamp(name):
        def cb(_):
            with lock:
                done[name] = time.monotonic() - t0
        return cb

    for i in range(100):  # 0.5s of serialized hot work on one shard
        q.enqueue(None, slow, key=f"hot-{i}", shard_key="hot-uid")
    for name in cold_keys:
        q.enqueue(None, stamp(name), key=name, shard_key=name)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with lock:
            if all(n in done for n in cold_keys):
                break
        time.sleep(0.002)
    q.shutdown()
    assert all(n in done for n in cold_keys), "cold keys never ran"
    worst = max(done[n] for n in cold_keys)
    # Hot backlog is ~0.5s; cold keys on other shards must not wait it.
    assert worst < 0.2, (
        f"cold keys waited {worst:.3f}s behind the hot shard"
    )


def test_sharded_depth_gauges_are_per_shard():
    from tpu_dra.infra.metrics import Metrics
    from tpu_dra.infra.workqueue import ShardedWorkQueue

    m = Metrics()
    q = ShardedWorkQueue(shards=2, metrics=m)
    # No worker threads: enqueued items sit pending, visible per shard.
    q.enqueue(None, lambda o: None, key="a", shard_key="a")
    shard = q.shard_of("a")
    assert m.get_gauge(
        "workqueue_depth", labels={"shard": str(shard)}
    ) == 1
    other = 1 - shard
    assert m.get_gauge(
        "workqueue_depth", labels={"shard": str(other)}
    ) in (None, 0)
    q.shutdown()


def test_work_duration_seconds_observed():
    from tpu_dra.infra.metrics import Metrics

    m = Metrics()
    q = WorkQueue(ItemExponentialFailureRateLimiter(0.001, 0.01), metrics=m)
    done = threading.Event()
    q.enqueue(None, lambda o: done.set(), key="k")
    _run(q)
    assert done.wait(2)
    q.shutdown()
    assert "workqueue_work_duration_seconds_count 1" in m.render()


def test_sharded_keyless_items_round_robin():
    from tpu_dra.infra.workqueue import ShardedWorkQueue

    q = ShardedWorkQueue(shards=3)
    seen = []
    orig = [s.enqueue for s in q.shards]
    for idx, s in enumerate(q.shards):
        def spy(obj, cb, key="", _idx=idx, _orig=orig[idx]):
            seen.append(_idx)
            _orig(obj, cb, key=key)
        s.enqueue = spy
    for _ in range(6):
        q.enqueue(None, lambda o: None)
    assert seen == [0, 1, 2, 0, 1, 2]
    q.shutdown()
