"""Chaos soak: randomized fault schedules against the in-process stack.

The harness runs the real kubelet-plugin Driver (stub tpulib backend,
AutoRemediation on) with a live in-process multiplex arbiter + client, and
drives :mod:`tpu_dra.infra.chaos` schedules into every injection seam:

- chip health flaps  -> the stub's health-event queue,
- apiserver 429/5xx bursts + watch drops -> the fake apiserver's fault
  hooks (soak runs the driver over REAL HTTP through rest.KubeClient),
- kubelet-plugin crash/restart -> rebuild the Driver over the same state
  dirs (checkpoint + persisted sub-slice replay),
- multiplex client death mid-lease -> abrupt socket close.

Convergence contract (the acceptance bar): after every schedule the system
settles with zero leaked leases, zero dangling prepared claims, and
ResourceSlices matching actual chip health; a recovered chip is
re-published and re-allocatable WITHOUT a plugin restart.

The smoke test (fast, deterministic, hand-written schedule) runs in tier-1
and `make chaos`; the randomized multi-seed soak is marked slow.
"""

import os
import shutil
import tempfile
import threading
import time
import uuid as uuidlib

import pytest

from tpu_dra.infra import crashpoint as crashpoint_mod
from tpu_dra.infra import featuregates as fg
from tpu_dra.infra.chaos import (
    API_LATENCY,
    API_PARTITION,
    APISERVER_BROWNOUT,
    APISERVER_ERRORS,
    APISERVER_RESTART,
    APISERVER_THROTTLE,
    CHIP_DOWN,
    CHIP_UP,
    CLIENT_DEATH,
    CRASH,
    PLUGIN_CRASH,
    WATCH_DROP,
    ChaosEngine,
    FaultSchedule,
    validate_schedule,
)
from tpu_dra.k8sclient import (
    DEPLOYMENTS,
    RESOURCE_CLAIMS,
    RESOURCE_SLICES,
    FakeCluster,
    ResourceClient,
)
from tpu_dra.k8sclient.fakeserver import FakeApiServer
from tpu_dra.k8sclient.rest import KubeClient
from tpu_dra.plugin.checkpoint import CLAIM_STATE_PREPARE_COMPLETED
from tpu_dra.plugin.device_state import DRIVER_NAME
from tpu_dra.plugin.driver import Driver, DriverConfig
from tpu_dra.plugin.multiplexd import MultiplexDaemon
from tpu_dra.plugin.remediation import REMEDIATION_ANNOTATION
from tpu_dra.tpulib.stub import StubTpuLib
from tpu_dra.tpulib.types import ChipHealthEvent
from tpu_dra.workloads.multiplex_client import MultiplexClient

DEBOUNCE = 0.15
ALL_DEVICES = ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]


def gates(**kwargs):
    g = fg.FeatureGates()
    for k, v in kwargs.items():
        g.set(k, v)
    fg.reset_for_tests(g)


def chaos_gates():
    gates(
        DeviceHealthCheck=True,
        AutoRemediation=True,
        MultiplexingSupport=True,
    )


def wait_for(predicate, timeout=10.0, poll=0.02, msg=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(poll)
    assert predicate(), msg or "condition did not converge"


def make_claim(devices, configs=None, uid=None):
    uid = uid or str(uuidlib.uuid4())
    results = [
        {"request": "req0", "driver": DRIVER_NAME, "pool": "node-0",
         "device": d}
        for d in devices
    ]
    return {
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceClaim",
        "metadata": {
            "name": f"claim-{uid[:6]}", "namespace": "default", "uid": uid,
        },
        "status": {
            "allocation": {
                "devices": {"results": results, "config": configs or []}
            }
        },
    }


MUX_CONFIG = [{
    "opaque": {
        "driver": DRIVER_NAME,
        "parameters": {
            "apiVersion": "resource.tpu.google.com/v1beta1",
            "kind": "TpuConfig",
            "sharing": {"strategy": "Multiplexing"},
        },
    },
    "requests": [],
    "source": "FromClaim",
}]


class ChaosHarness:
    """Driver + arbiter + client + fault seams, over FakeCluster (unit
    mode) or real HTTP through the fake apiserver (soak mode)."""

    def __init__(self, tmp_path, over_http=False):
        self.tmp_path = tmp_path
        self.srv = None
        if over_http:
            self.srv = FakeApiServer(watch_heartbeat_seconds=1.0).start()
            self.cluster = self.srv.cluster
            self.backend = KubeClient(self.srv.server_url)
        else:
            self.cluster = FakeCluster()
            self.backend = self.cluster
        # AF_UNIX paths cap at ~108 chars and pytest tmp dirs are deep:
        # the socket root (root/<claim-uid>/multiplexd.sock) needs a short
        # prefix of its own.
        self.socket_root = tempfile.mkdtemp(prefix="cx-")
        self.daemons = {}       # claim uid -> in-process MultiplexDaemon
        self.clients = {}       # claim uid -> MultiplexClient (live)
        self._stop_ready = threading.Event()
        self._ready_thread = threading.Thread(
            target=self._auto_ready_loop, daemon=True,
            name="chaos-auto-ready",
        )
        self._ready_thread.start()
        self.driver = None
        self.build_driver()

    # The fake cluster has no controller manager: poll-mark every
    # multiplex-daemon Deployment ready so Prepare's assert_ready gate
    # passes. Polling (not a watch) stays oblivious to injected watch
    # drops — this loop plays "kubelet on another node", not a client
    # under test.
    def _auto_ready_loop(self):
        deployments = ResourceClient(self.cluster, DEPLOYMENTS)
        while not self._stop_ready.wait(0.05):
            try:
                for dep in deployments.list(namespace="tpu-dra-driver"):
                    if (dep.get("status") or {}).get("readyReplicas", 0) < 1:
                        dep["status"] = {"readyReplicas": 1}
                        deployments.update_status(dep)
            except Exception:
                pass

    def build_driver(self):
        self.lib = StubTpuLib(
            config={"generation": "v5e", "hostname": "node-0"},
            state_dir=str(self.tmp_path / "tpustate"),
        )
        cfg = DriverConfig(
            node_name="node-0",
            cdi_root=str(self.tmp_path / "cdi"),
            plugin_data_dir=str(self.tmp_path / "plugin"),
            kubelet_registrar_dir=str(self.tmp_path / "registry"),
            start_grpc=False,
            cdi_hook_source="",
            multiplex_socket_root=self.socket_root,
            remediation_debounce_seconds=DEBOUNCE,
        )
        self.driver = Driver(self.lib, self.backend, cfg)
        self.driver.start()

    # --- claims -----------------------------------------------------------

    def create_claim(self, devices, configs=None):
        claim = make_claim(devices, configs)
        # Setup writes go straight to the cluster (fault injection must
        # not flake the arrangement, only the system under test). Like a
        # real apiserver, create assigns the uid — the kubelet would hand
        # the plugin the server's view, so graft it into our copy.
        created = ResourceClient(self.cluster, RESOURCE_CLAIMS).create(claim)
        claim["metadata"]["uid"] = created["metadata"]["uid"]
        self.driver.state.prepare(claim)
        return claim

    def create_mux_claim(self, devices=("tpu-0", "tpu-1")):
        """A multiplexed claim + the in-process arbiter 'pod' + one live
        client holding the lease."""
        claim = self.create_claim(list(devices), configs=MUX_CONFIG)
        uid = claim["metadata"]["uid"]
        chips = [
            self.lib.chips()[int(d.split("-")[1])].uuid for d in devices
        ]
        daemon = MultiplexDaemon(
            os.path.join(self.socket_root, uid), chips, window_seconds=0.5
        ).start()
        self.daemons[uid] = daemon
        client = MultiplexClient(
            daemon.socket_dir, client_name=f"chaos-{uid[:6]}"
        )
        client.acquire()
        # Harness state is driven from the test thread only (the chaos
        # engine replays injectors synchronously).
        self.clients[uid] = client  # lint: disable=R200
        return claim

    # --- injectors --------------------------------------------------------

    def inject_chip_down(self, ev):
        chip = self.lib.chips()[int(ev.params["chip_index"])]
        self.lib.inject_health_event(ChipHealthEvent(
            chip_uuid=chip.uuid, healthy=False,
            reason=ev.params.get("reason", "injected"),
        ))

    def inject_chip_up(self, ev):
        chip = self.lib.chips()[int(ev.params["chip_index"])]
        self.lib.inject_health_event(ChipHealthEvent(
            chip_uuid=chip.uuid, healthy=True,
            reason=ev.params.get("reason", "recovered"),
        ))

    def crash_plugin(self, ev=None):
        """Process-death analog: the old driver's threads stop with NO
        graceful unprepare/teardown; a fresh driver then replays the
        persisted checkpoint + sub-slice state from the same dirs."""
        old = self.driver
        old.cleanup.stop()
        old.health_monitor.stop()
        if old.remediation is not None:
            old.remediation.stop()
        self.build_driver()

    def inject_crash(self, ev):
        """Process death pinned to a NAMED crash point: arm the point,
        drive a checkpoint touch so the write-path points actually fire
        mid-commit, then restart the driver over the persisted state. A
        point outside the write path simply doesn't fire here (the arm
        context disarms on exit) and the event degrades to plain process
        death — still a valid fault."""
        with crashpoint_mod.arm(ev.params["point"]):
            try:
                self.driver.state.checkpoints.update(lambda c: None)
            except crashpoint_mod.SimulatedCrash:
                pass
        self.crash_plugin()

    def kill_client(self, ev=None):
        """Abrupt client death mid-lease: close the socket with no
        release; the arbiter must reap the lease on its own."""
        for uid, client in sorted(self.clients.items()):
            if client._sock is not None:
                client._sock.close()
                client._sock = None
                client._file = None
                del self.clients[uid]  # lint: disable=R200 (test-thread only)
                return

    def inject_brownout(self, ev):
        """Flow-control squeeze on the LIVE apiserver: seats drop to
        params["concurrency"] for params["duration"] seconds, then the
        stock table returns. The restore rides a timer so the engine's
        replay thread is free to fire overlapping faults."""
        flow = self.srv.flow
        flow.configure(concurrency=int(ev.params.get("concurrency", 2)))
        t = threading.Timer(
            float(ev.params.get("duration", 0.5)),
            lambda: flow.configure(concurrency=64),
        )
        t.daemon = True
        t.start()

    def engine_for(self, schedule) -> ChaosEngine:
        e = ChaosEngine(schedule)
        e.register(CHIP_DOWN, self.inject_chip_down)
        e.register(CHIP_UP, self.inject_chip_up)
        e.register(PLUGIN_CRASH, self.crash_plugin)
        e.register(CRASH, self.inject_crash)
        e.register(CLIENT_DEATH, self.kill_client)
        if self.srv is not None:
            e.register(APISERVER_THROTTLE, lambda ev: self.srv.inject_faults(
                throttle=ev.params["count"],
                retry_after=ev.params.get("retry_after", 0.05),
            ))
            e.register(APISERVER_ERRORS, lambda ev: self.srv.inject_faults(
                fail=ev.params["count"],
                fail_status=ev.params.get("status", 503),
            ))
            e.register(WATCH_DROP, lambda ev: self.srv.inject_faults(
                drop_watches=True,
            ))
            e.register(API_PARTITION, lambda ev: self.srv.inject_faults(
                partition_seconds=ev.params["duration"],
            ))
            e.register(API_LATENCY, lambda ev: self.srv.inject_faults(
                latency=ev.params["delay"],
                latency_seconds=ev.params["duration"],
            ))
            e.register(APISERVER_RESTART, lambda ev: self.srv.restart(
                outage_seconds=ev.params.get("outage", 0.3),
            ))
            e.register(APISERVER_BROWNOUT, self.inject_brownout)
        return e

    # --- convergence ------------------------------------------------------

    def published_device_names(self):
        slices = ResourceClient(self.cluster, RESOURCE_SLICES).list(
            label_selector={"tpu.google.com/driver": "true"}
        )
        return sorted(d["name"] for s in slices for d in s["spec"]["devices"])

    def settle(self, timeout=15.0):
        """Wait until the remediation pipeline drained: no debounce timers,
        no queued/processing requeue work."""
        rem = self.driver.remediation

        def drained():
            return (
                rem is None
                or (
                    not rem._pending
                    and not rem.queue._pending
                    and not rem.queue._processing
                    and not rem.queue._dirty
                )
            )

        wait_for(drained, timeout, msg="remediation pipeline did not drain")

    def assert_converged(self):
        # 1. Terminal chip state is all-healthy (schedules guarantee it).
        assert all(c.healthy for c in self.lib.chips())
        # 2. ResourceSlices match chip health: every device republished.
        wait_for(
            lambda: self.published_device_names() == ALL_DEVICES,
            15,
            msg=f"slices stuck at {self.published_device_names()}",
        )
        # 3. No dangling prepared claims: every checkpoint entry maps to a
        # live API claim with the same uid and a completed WAL state.
        cp = self.driver.state.checkpoints.get()
        live = {
            c["metadata"]["uid"]
            for c in ResourceClient(self.cluster, RESOURCE_CLAIMS).list()
        }
        for uid, claim in cp.prepared_claims.items():
            assert uid in live, f"checkpoint claim {uid} dangles (no API object)"
            assert claim.checkpoint_state == CLAIM_STATE_PREPARE_COMPLETED
        # 4. No leaked leases: every arbiter's lease is either free or held
        # by a client that is still alive.
        live_names = {c.client_name for c in self.clients.values()}
        for uid, daemon in self.daemons.items():
            holder = daemon.state.status()["holder"]
            assert holder is None or holder in live_names, (
                f"leaked lease on claim {uid}: holder={holder!r}"
            )

    def assert_reallocatable(self, chip_index):
        """A recovered chip is re-allocatable WITHOUT a plugin restart."""
        claim = self.create_claim([f"tpu-{chip_index}"])
        self.driver.state.unprepare(claim["metadata"]["uid"])

    def teardown(self):
        self._stop_ready.set()
        for client in self.clients.values():
            client.close()
        for daemon in self.daemons.values():
            daemon.stop()
        self.driver.shutdown()
        if self.srv is not None:
            self.srv.stop()
        shutil.rmtree(self.socket_root, ignore_errors=True)


# --- schedule validation (the hack/lint.py gate shares this) ---------------


def test_validate_schedule_accepts_generated():
    for seed in (0, 1, 42):
        s = FaultSchedule.from_seed(seed, duration=4.0, chips=4)
        assert validate_schedule(s.to_dict()) == []


def test_validate_schedule_rejects_garbage():
    assert validate_schedule([]) != []
    assert validate_schedule({"events": []}) != []
    assert validate_schedule(
        {"events": [{"at": -1, "kind": "chip_down", "chip_index": 0}]}
    )
    assert validate_schedule({"events": [{"at": 0, "kind": "nope"}]})
    # chip_down without params
    assert validate_schedule({"events": [{"at": 0, "kind": "chip_down"}]})
    # throttle without count
    assert validate_schedule(
        {"events": [{"at": 0, "kind": "apiserver_throttle"}]}
    )


def test_validate_schedule_requires_recovery():
    errs = validate_schedule({"events": [
        {"at": 0.0, "kind": "chip_down", "chip_index": 1, "reason": "x"},
    ]})
    assert any("never recovers" in e for e in errs)
    # ... and rejects an up for a chip never taken down.
    errs = validate_schedule({"events": [
        {"at": 0.0, "kind": "chip_up", "chip_index": 1},
    ]})
    assert any("not down" in e for e in errs)
    # Pairing follows the EXECUTION timeline (sorted by 'at'), not file
    # order: an up that fires before its down leaves the chip down at the
    # end, which must be rejected.
    errs = validate_schedule({"events": [
        {"at": 2.0, "kind": "chip_down", "chip_index": 1, "reason": "x"},
        {"at": 1.0, "kind": "chip_up", "chip_index": 1},
    ]})
    assert errs


def test_validate_schedule_crash_kind():
    """crash events must name a point from the canonical crash-point
    table; a renamed/unknown point fails the schema gate, not a soak."""
    ok = {"events": [
        {"at": 0.0, "kind": "crash",
         "point": "checkpoint.write.before_replace"},
    ]}
    assert validate_schedule(ok) == []
    for bad_point in ("", "nope.not.registered", 7, None):
        errs = validate_schedule(
            {"events": [{"at": 0.0, "kind": "crash", "point": bad_point}]}
        )
        assert errs, f"accepted bad crash point {bad_point!r}"


def test_seeded_schedule_can_mix_crash_points():
    """from_seed mixes crash events in (and they carry valid points)."""
    found = []
    for seed in range(40):
        s = FaultSchedule.from_seed(seed, duration=4.0, chips=4)
        found += [e for e in s if e.kind == CRASH]
    assert found, "no crash events generated across 40 seeds"
    for e in found:
        assert e.params["point"] in crashpoint_mod.CRASH_POINTS


def test_schedule_is_deterministic_per_seed():
    a = FaultSchedule.from_seed(1234, duration=5.0, chips=4)
    b = FaultSchedule.from_seed(1234, duration=5.0, chips=4)
    assert a.to_dict() == b.to_dict()
    c = FaultSchedule.from_seed(1235, duration=5.0, chips=4)
    assert a.to_dict() != c.to_dict()


def test_schedule_json_roundtrip(tmp_path):
    s = FaultSchedule.from_seed(9, duration=4.0, chips=4)
    path = tmp_path / "drill.chaos.json"
    import json

    path.write_text(json.dumps(s.to_dict()))
    loaded = FaultSchedule.from_file(str(path))
    assert loaded.to_dict()["events"] == s.to_dict()["events"]


# --- fakeserver fault hooks -------------------------------------------------


def test_fakeserver_5xx_burst_and_recovery():
    srv = FakeApiServer().start()
    try:
        client = KubeClient(srv.server_url)
        claims = ResourceClient(client, RESOURCE_CLAIMS)
        # A burst inside the transport's retry budget is absorbed.
        srv.inject_faults(fail=2, fail_status=503)
        assert claims.list(namespace="default") == []
        with srv._fault_lock:
            assert srv._stats["failed"] == 2
    finally:
        srv.stop()


# --- the deterministic smoke drill (tier-1 + `make chaos`) ------------------


def test_chaos_smoke_remediation_cycle(tmp_path):
    """Hand-written schedule: the multiplexed claim's chip fails past the
    debounce, remediation revokes the lease + requeues the claim +
    unpublishes the chip; recovery republishes and the chip is
    re-allocatable — all without a plugin restart."""
    chaos_gates()
    h = ChaosHarness(tmp_path)
    try:
        mux = h.create_mux_claim()
        solo = h.create_claim(["tpu-3"])
        mux_uid = mux["metadata"]["uid"]
        daemon = h.daemons[mux_uid]
        assert daemon.state.status()["holder"] is not None

        schedule = FaultSchedule.from_dict({
            "version": 1,
            "description": "single sustained flap on the shared chip",
            "events": [
                {"at": 0.0, "kind": "chip_down", "chip_index": 0,
                 "reason": "ici-link-down"},
                {"at": 0.8, "kind": "chip_up", "chip_index": 0,
                 "reason": "recovered"},
            ],
        })
        engine = h.engine_for(schedule)

        # Fire the failure, then observe the down-window before recovery.
        assert engine.step().kind == CHIP_DOWN
        wait_for(
            lambda: "tpu-0" not in h.published_device_names(), 5,
            msg="unhealthy chip was not unpublished",
        )
        # Debounce elapses -> lease revoked, claim requeued + annotated.
        wait_for(
            lambda: daemon.state.status()["holder"] is None, 5,
            msg="remediation did not revoke the lease",
        )
        wait_for(
            lambda: mux_uid not in
            h.driver.state.checkpoints.get().prepared_claims, 5,
            msg="remediation did not requeue the prepared claim",
        )
        api_claim = ResourceClient(h.cluster, RESOURCE_CLAIMS).get(
            mux["metadata"]["name"], "default"
        )
        assert REMEDIATION_ANNOTATION in api_claim["metadata"]["annotations"]
        # The untouched claim survives.
        assert (
            solo["metadata"]["uid"]
            in h.driver.state.checkpoints.get().prepared_claims
        )
        # Remediation metrics moved.
        rendered = h.driver.metrics.render()
        assert "remediations_total 1.0" in rendered
        assert "remediation_claims_requeued_total 1.0" in rendered

        # Recovery: chip republished and re-allocatable, no restart.
        assert engine.step().kind == CHIP_UP
        wait_for(
            lambda: h.published_device_names() == ALL_DEVICES, 5,
            msg="recovered chip was not republished",
        )
        h.settle()
        h.assert_converged()
        h.assert_reallocatable(0)
    finally:
        h.teardown()


def test_chaos_smoke_flap_suppressed(tmp_path):
    """A flap shorter than the debounce window never remediates: the
    claim keeps its devices and the lease survives."""
    chaos_gates()
    h = ChaosHarness(tmp_path)
    try:
        mux = h.create_mux_claim()
        mux_uid = mux["metadata"]["uid"]
        h.inject_chip_down(type("E", (), {"params": {"chip_index": 0}})())
        h.inject_chip_up(type("E", (), {"params": {"chip_index": 0}})())
        # Give the (would-be) debounce window time to fire.
        time.sleep(DEBOUNCE + 0.3)
        h.settle()
        assert (
            mux_uid in h.driver.state.checkpoints.get().prepared_claims
        )
        assert h.daemons[mux_uid].state.status()["holder"] is not None
        assert (
            "remediation_flaps_suppressed_total 1.0"
            in h.driver.metrics.render()
        )
        h.assert_converged()
    finally:
        h.teardown()


# --- control-plane recovery rows (ISSUE 20): restart + brownout -------------


def test_chaos_matrix_apiserver_restart_and_brownout(tmp_path):
    """The two control-plane recovery kinds, replayed deterministically
    over REAL HTTP: a full apiserver restart (snapshot/restore, watches
    dropped, dark port) and a flow-control brownout (seats squeezed,
    low-share flows shed) land mid-chip-flap, and the driver still
    converges — checkpoint consistent, slices republished, no leaks."""
    chaos_gates()
    h = ChaosHarness(tmp_path, over_http=True)
    try:
        h.create_mux_claim()
        h.create_claim(["tpu-3"])
        schedule = FaultSchedule.from_dict({"events": [
            {"at": 0.2, "kind": CHIP_DOWN, "chip_index": 2,
             "reason": "ici-link-down"},
            {"at": 0.4, "kind": APISERVER_RESTART, "outage": 0.4},
            {"at": 1.2, "kind": CHIP_UP, "chip_index": 2},
            {"at": 1.4, "kind": APISERVER_BROWNOUT, "concurrency": 1,
             "duration": 0.6},
            {"at": 2.2, "kind": APISERVER_RESTART, "outage": 0.0},
        ]})
        assert validate_schedule(schedule.to_dict()) == []
        engine = h.engine_for(schedule)
        engine.run(time_scale=1.0)
        assert engine.errors == [], engine.errors
        assert h.srv.cluster is not None
        h.settle()
        h.assert_converged()
        # The restart counter is the observable the doctor/fleetmon
        # read; two restarts fired in this schedule.
        assert (
            "apiserver_restarts_total 2.0" in h.srv.metrics.render()
        )
    finally:
        h.teardown()


# --- the randomized soak (slow; 3 distinct seeds) ---------------------------


@pytest.mark.slow
@pytest.mark.parametrize("seed", [101, 202, 303])
def test_chaos_soak_converges(tmp_path, seed):
    chaos_gates()
    h = ChaosHarness(tmp_path, over_http=True)
    try:
        h.create_mux_claim()
        h.create_claim(["tpu-3"])
        schedule = FaultSchedule.from_seed(
            seed, duration=3.0, chips=4, events_per_second=2.5
        )
        assert validate_schedule(schedule.to_dict()) == []
        engine = h.engine_for(schedule)
        engine.run(time_scale=1.0)
        assert engine.errors == [], engine.errors
        # Clear any still-armed fault counters so convergence probes see a
        # healthy apiserver (the faults themselves already hit mid-run).
        h.srv.inject_faults(throttle=0, fail=0)
        h.settle()
        h.assert_converged()
        # A failed chip is re-allocatable unless a SURVIVING claim still
        # legitimately holds it (a flap shorter than the debounce never
        # remediates, by design).
        cp = h.driver.state.checkpoints.get()
        still_held = {
            pd.device.device_name
            for claim in cp.prepared_claims.values()
            for group in claim.prepared_devices
            for pd in group.devices
        }
        failed = sorted({
            int(e.params["chip_index"])
            for e in schedule
            if e.kind == CHIP_DOWN
        })
        free_failed = [i for i in failed if f"tpu-{i}" not in still_held]
        if free_failed:
            h.assert_reallocatable(free_failed[0])
    finally:
        h.teardown()


# --- the shipped demo drill stays replayable --------------------------------


def test_demo_schedules_validate_and_replay(tmp_path):
    """Every *.chaos.json shipped under demo/chaos/ must pass the schema
    gate AND actually replay to convergence (unit mode: apiserver faults
    are skipped by the engine, which is part of the contract)."""
    import glob

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = sorted(glob.glob(os.path.join(repo, "demo", "chaos", "*.chaos.json")))
    assert paths, "no demo chaos schedules shipped"
    chaos_gates()
    for path in paths:
        schedule = FaultSchedule.from_file(path)  # raises on schema drift
        h = ChaosHarness(tmp_path / os.path.basename(path))
        try:
            h.create_mux_claim()
            engine = h.engine_for(schedule)
            engine.run(time_scale=1.0)
            assert engine.errors == [], engine.errors
            h.settle()
            h.assert_converged()
        finally:
            h.teardown()
