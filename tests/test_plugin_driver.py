"""Driver-level tests: ResourceSlice publication, health-driven republish,
stale-claim GC, and the DRA gRPC surface over a real unix socket."""

import time
import uuid as uuidlib

import grpc
import pytest

from tpu_dra.infra import featuregates as fg
from tpu_dra.k8sclient import (
    RESOURCE_CLAIMS,
    RESOURCE_SLICES,
    FakeCluster,
    ResourceClient,
)
from tpu_dra.plugin.driver import Driver, DriverConfig
from tpu_dra.plugin.device_state import DRIVER_NAME
from tpu_dra.plugin.pb import dra_v1beta1_pb2 as drapb
from tpu_dra.plugin.pb import pluginregistration_pb2 as regpb
from tpu_dra.tpulib.stub import StubTpuLib
from tpu_dra.tpulib.types import ChipHealthEvent


def gates(**kwargs):
    g = fg.FeatureGates()
    for k, v in kwargs.items():
        g.set(k, v)
    fg.reset_for_tests(g)


def make_driver(tmp_path, backend=None, start_grpc=False, **cfg):
    lib = StubTpuLib(
        config={"generation": "v5e", "hostname": "node-0"},
        state_dir=str(tmp_path / "tpustate"),
    )
    backend = backend or FakeCluster()
    # Hooks off by default so specs keep the same shape wherever the suite
    # runs (the driver image ships /usr/local/bin/tpu-cdi-hook, dev hosts
    # don't); hook wiring is covered explicitly in test_cdi.py.
    cfg.setdefault("cdi_hook_source", "")
    config = DriverConfig(
        node_name="node-0",
        cdi_root=str(tmp_path / "cdi"),
        plugin_data_dir=str(tmp_path / "plugin"),
        kubelet_registrar_dir=str(tmp_path / "registry"),
        start_grpc=start_grpc,
        **cfg,
    )
    return Driver(lib, backend, config), backend


def test_publish_split_slices(tmp_path):
    driver, backend = make_driver(tmp_path)
    driver.publish_resources()
    slices = ResourceClient(backend, RESOURCE_SLICES).list()
    assert len(slices) == 1  # one per device type; only "tpu" without gates
    s = slices[0]
    assert s["spec"]["driver"] == DRIVER_NAME
    assert s["spec"]["nodeName"] == "node-0"
    names = [d["name"] for d in s["spec"]["devices"]]
    assert names == ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]
    d0 = s["spec"]["devices"][0]["basic"]
    assert d0["attributes"]["generation"] == {"string": "v5e"}
    assert d0["attributes"]["topologyCoord"] == {"string": "0,0,0"}
    assert d0["capacity"]["hbm"]["value"] == str(16 * 1024**3)


def test_publish_combined_partitionable_slices(tmp_path):
    gates(DynamicSubslice=True)
    driver, backend = make_driver(tmp_path, resource_api_version="v1beta2")
    driver.publish_resources()
    slices = ResourceClient(backend, RESOURCE_SLICES).list()
    assert len(slices) == 1
    s = slices[0]
    assert s["apiVersion"] == "resource.k8s.io/v1beta2"
    counters = s["spec"]["sharedCounters"][0]["counters"]
    assert set(counters) == {
        "chip-0-0-0",
        "chip-1-0-0",
        "chip-0-1-0",
        "chip-1-1-0",
    }
    by_name = {d["name"]: d for d in s["spec"]["devices"]}
    # Full host 2x2 sub-slice consumes all four counters; tpu-0 consumes one.
    ss = by_name["tpu-ss-2x2-0-0-0"]["basic"]["consumesCounters"][0]
    assert set(ss["counters"]) == set(counters)
    t0 = by_name["tpu-0"]["basic"]["consumesCounters"][0]
    assert set(t0["counters"]) == {"chip-0-0-0"}


def test_health_event_unpublishes_device(tmp_path):
    gates(DeviceHealthCheck=True)
    driver, backend = make_driver(tmp_path)
    driver.start()
    slices_client = ResourceClient(backend, RESOURCE_SLICES)
    assert len(slices_client.list()[0]["spec"]["devices"]) == 4

    victim = driver.tpulib.chips()[2]
    driver.tpulib.inject_health_event(
        ChipHealthEvent(chip_uuid=victim.uuid, healthy=False, reason="ici link down")
    )
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline:
        devs = [d["name"] for d in slices_client.list()[0]["spec"]["devices"]]
        if "tpu-2" not in devs:
            break
        time.sleep(0.02)
    assert "tpu-2" not in devs and len(devs) == 3

    # Benign reasons must not unpublish.
    driver.tpulib.inject_health_event(
        ChipHealthEvent(chip_uuid=victim.uuid, healthy=True, reason="recovered")
    )
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline:
        devs = [d["name"] for d in slices_client.list()[0]["spec"]["devices"]]
        if len(devs) == 4:
            break
        time.sleep(0.02)
    assert len(devs) == 4
    driver.shutdown()


def test_cleanup_unprepares_stale_claims(tmp_path):
    driver, backend = make_driver(tmp_path)
    claims = ResourceClient(backend, RESOURCE_CLAIMS)
    uid = str(uuidlib.uuid4())
    obj = claims.create(
        {
            "metadata": {"name": "c1", "namespace": "default"},
            "spec": {},
            "status": {
                "allocation": {
                    "devices": {
                        "results": [
                            {
                                "request": "r",
                                "driver": DRIVER_NAME,
                                "pool": "node-0",
                                "device": "tpu-0",
                            }
                        ],
                        "config": [],
                    }
                }
            },
        }
    )
    claim = claims.get("c1", "default")
    driver.state.prepare(claim)
    # Claim still exists: nothing stale.
    assert driver.cleanup.cleanup_once() == 0
    # Delete from the API server: now stale, gets unprepared.
    claims.delete("c1", "default")
    assert driver.cleanup.cleanup_once() == 1
    assert driver.state.checkpoints.get().prepared_claims == {}


def test_cleanup_detects_uid_change(tmp_path):
    driver, backend = make_driver(tmp_path)
    claims = ResourceClient(backend, RESOURCE_CLAIMS)
    claims.create({"metadata": {"name": "c1", "namespace": "default"}, "spec": {}})
    live = claims.get("c1", "default")
    live["status"] = {
        "allocation": {
            "devices": {
                "results": [
                    {
                        "request": "r",
                        "driver": DRIVER_NAME,
                        "pool": "node-0",
                        "device": "tpu-0",
                    }
                ],
                "config": [],
            }
        }
    }
    claims.update(live)
    driver.state.prepare(claims.get("c1", "default"))
    # Recreate under the same name -> new UID -> stale.
    claims.delete("c1", "default")
    claims.create({"metadata": {"name": "c1", "namespace": "default"}, "spec": {}})
    assert driver.cleanup.cleanup_once() == 1


# --- gRPC end-to-end --------------------------------------------------------


@pytest.fixture
def grpc_driver(tmp_path):
    driver, backend = make_driver(tmp_path, start_grpc=True)
    driver.start()
    yield driver, backend
    driver.shutdown()


def _dra_stub(driver):
    channel = grpc.insecure_channel(
        f"unix://{driver.config.plugin_data_dir}/dra.sock"
    )
    return channel


def test_grpc_prepare_unprepare_roundtrip(grpc_driver):
    driver, backend = grpc_driver
    claims = ResourceClient(backend, RESOURCE_CLAIMS)
    created = claims.create(
        {
            "metadata": {"name": "c1", "namespace": "default"},
            "spec": {},
            "status": {
                "allocation": {
                    "devices": {
                        "results": [
                            {
                                "request": "r",
                                "driver": DRIVER_NAME,
                                "pool": "node-0",
                                "device": "tpu-0",
                            }
                        ],
                        "config": [],
                    }
                }
            },
        }
    )
    uid = created["metadata"]["uid"]
    channel = _dra_stub(driver)
    prepare = channel.unary_unary(
        "/v1beta1.DRAPlugin/NodePrepareResources",
        request_serializer=drapb.NodePrepareResourcesRequest.SerializeToString,
        response_deserializer=drapb.NodePrepareResourcesResponse.FromString,
    )
    req = drapb.NodePrepareResourcesRequest(
        claims=[drapb.Claim(uid=uid, name="c1", namespace="default")]
    )
    resp = prepare(req, timeout=10)
    assert resp.claims[uid].error == ""
    assert resp.claims[uid].devices[0].device_name == "tpu-0"
    assert resp.claims[uid].devices[0].cdi_device_ids[0].startswith(
        "k8s.tpu.google.com/claim="
    )

    # One bad claim must not fail the batch (per-claim error isolation).
    req2 = drapb.NodePrepareResourcesRequest(
        claims=[
            drapb.Claim(uid="no-such", name="missing", namespace="default"),
        ]
    )
    resp2 = prepare(req2, timeout=10)
    assert resp2.claims["no-such"].error != ""

    unprepare = channel.unary_unary(
        "/v1beta1.DRAPlugin/NodeUnprepareResources",
        request_serializer=drapb.NodeUnprepareResourcesRequest.SerializeToString,
        response_deserializer=drapb.NodeUnprepareResourcesResponse.FromString,
    )
    uresp = unprepare(
        drapb.NodeUnprepareResourcesRequest(
            claims=[drapb.Claim(uid=uid, name="c1", namespace="default")]
        ),
        timeout=10,
    )
    assert uresp.claims[uid].error == ""
    assert driver.state.checkpoints.get().prepared_claims == {}
    channel.close()


def test_grpc_registration_service(grpc_driver):
    driver, _ = grpc_driver
    channel = grpc.insecure_channel(
        f"unix://{driver.config.kubelet_registrar_dir}/{DRIVER_NAME}-reg.sock"
    )
    get_info = channel.unary_unary(
        "/pluginregistration.Registration/GetInfo",
        request_serializer=regpb.InfoRequest.SerializeToString,
        response_deserializer=regpb.PluginInfo.FromString,
    )
    info = get_info(regpb.InfoRequest(), timeout=10)
    assert info.name == DRIVER_NAME
    assert info.type == "DRAPlugin"
    assert "v1beta1" in info.supported_versions
    notify = channel.unary_unary(
        "/pluginregistration.Registration/NotifyRegistrationStatus",
        request_serializer=regpb.RegistrationStatus.SerializeToString,
        response_deserializer=regpb.RegistrationStatusResponse.FromString,
    )
    notify(regpb.RegistrationStatus(plugin_registered=True), timeout=10)
    assert driver.registration.registered.is_set()
    channel.close()


def test_metrics_rendered(tmp_path):
    driver, _ = make_driver(tmp_path)
    driver.publish_resources()
    driver.metrics.inc("prepare_total")
    driver.metrics.observe("prepare_seconds", 0.05)
    text = driver.metrics.render()
    assert "tpu_dra_prepare_total 1.0" in text
    assert "tpu_dra_prepare_seconds_count 1" in text
    assert "tpu_dra_published_resource_slices" in text


def test_split_slices_declare_total_pool_count(tmp_path):
    gates(PassthroughSupport=True)
    driver, backend = make_driver(tmp_path)
    driver.publish_resources()
    slices = ResourceClient(backend, RESOURCE_SLICES).list()
    assert len(slices) == 2  # tpu + vfio types
    for s in slices:
        assert s["spec"]["pool"]["resourceSliceCount"] == 2


def test_partial_subslice_recovery_stays_unhealthy(tmp_path):
    """A multi-chip sub-slice recovers only when ALL covered chips do."""
    gates(DynamicSubslice=True, DeviceHealthCheck=True)
    driver, backend = make_driver(tmp_path)
    chips = driver.tpulib.chips()
    for c in chips[:2]:  # (0,0,0) and (1,0,0) — both under tpu-ss-2x2
        driver.tpulib.inject_health_event(
            ChipHealthEvent(chip_uuid=c.uuid, healthy=False, reason="ici")
        )
        driver._on_health_change(
            ChipHealthEvent(chip_uuid=c.uuid, healthy=False, reason="ici")
        )
    assert driver.state.allocatable["tpu-ss-2x2-0-0-0"].healthy is False
    # One chip recovers: still unhealthy.
    driver.tpulib.inject_health_event(
        ChipHealthEvent(chip_uuid=chips[0].uuid, healthy=True)
    )
    driver._on_health_change(ChipHealthEvent(chip_uuid=chips[0].uuid, healthy=True))
    assert driver.state.allocatable["tpu-ss-2x2-0-0-0"].healthy is False
    assert driver.state.allocatable["tpu-0"].healthy is True
    # Second chip recovers: healthy again.
    driver.tpulib.inject_health_event(
        ChipHealthEvent(chip_uuid=chips[1].uuid, healthy=True)
    )
    driver._on_health_change(ChipHealthEvent(chip_uuid=chips[1].uuid, healthy=True))
    assert driver.state.allocatable["tpu-ss-2x2-0-0-0"].healthy is True


def test_reenumeration_preserves_health_state(tmp_path):
    """vfio unprepare re-enumeration must not resurrect unhealthy chips."""
    gates(DeviceHealthCheck=True)
    driver, backend = make_driver(tmp_path)
    victim = driver.tpulib.chips()[1]
    driver.tpulib.inject_health_event(
        ChipHealthEvent(chip_uuid=victim.uuid, healthy=False, reason="hw")
    )
    driver.state.recompute_health()
    assert driver.state.allocatable["tpu-1"].healthy is False
    driver.state.allocatable = driver.state._enumerate_allocatable()
    assert driver.state.allocatable["tpu-1"].healthy is False


def test_publish_unchanged_content_is_zero_writes(tmp_path):
    """ISSUE 10: republishing an unchanged pool set touches nothing —
    no resourceVersion churn, no MODIFIED fan-out, generation parked."""
    driver, backend = make_driver(tmp_path)
    driver.publish_resources()
    slices = ResourceClient(backend, RESOURCE_SLICES)
    rv = slices.list()[0]["metadata"]["resourceVersion"]
    gen = driver._slice_generation
    for _ in range(3):
        driver.publish_resources()
    assert slices.list()[0]["metadata"]["resourceVersion"] == rv
    assert driver._slice_generation == gen
    assert driver.metrics.get_counter(
        "publish_skipped_unchanged_total"
    ) == 3


def test_publish_soon_coalesces_event_storms(tmp_path):
    """A burst of publish triggers within the coalesce window collapses
    into ONE diffed pass; window 0 restores per-event (synchronous)
    publishing."""
    driver, backend = make_driver(
        tmp_path, publish_coalesce_seconds=0.1
    )
    driver.publish_resources()
    writes_before = driver.metrics.get_counter("publish_writes_total")
    for _ in range(5):
        driver.publish_soon()
    assert driver.metrics.get_counter("publish_coalesced_total") == 4
    deadline = time.monotonic() + 5
    while (
        driver._coalesce_timer is not None
        and time.monotonic() < deadline
    ):
        time.sleep(0.01)
    # The one coalesced pass ran — and, content unchanged, wrote nothing.
    assert driver.metrics.get_counter("publish_writes_total") == writes_before
    assert driver.metrics.get_counter(
        "publish_skipped_unchanged_total"
    ) >= 1

    sync_driver, _ = make_driver(
        tmp_path / "sync", publish_coalesce_seconds=0.0
    )
    sync_driver.publish_resources()
    skipped = sync_driver.metrics.get_counter(
        "publish_skipped_unchanged_total"
    )
    sync_driver.publish_soon()  # window 0: runs inline, no timer
    assert sync_driver._coalesce_timer is None
    assert sync_driver.metrics.get_counter(
        "publish_skipped_unchanged_total"
    ) == skipped + 1


def test_health_transition_publishes_changed_content(tmp_path):
    """A real health transition DOES change content: the coalesced pass
    must commit it (the diff is against content, not against time)."""
    gates(DeviceHealthCheck=True)
    driver, backend = make_driver(
        tmp_path, publish_coalesce_seconds=0.05
    )
    driver.publish_resources()
    gen = driver._slice_generation
    chips = driver.tpulib.chips()
    driver.tpulib.inject_health_event(
        ChipHealthEvent(chip_uuid=chips[0].uuid, healthy=False, reason="ici")
    )
    driver._on_health_change(
        ChipHealthEvent(chip_uuid=chips[0].uuid, healthy=False, reason="ici")
    )
    slices = ResourceClient(backend, RESOURCE_SLICES)

    def unpublished():
        names = [
            d["name"] for s in slices.list() for d in s["spec"]["devices"]
        ]
        return "tpu-0" not in names

    deadline = time.monotonic() + 5
    while not unpublished() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert unpublished(), "unhealthy device still published after window"
    assert driver._slice_generation == gen + 1


# --- node-scoped slice informer (ISSUE 11, ROADMAP item 5 nibble) -----------


def _start_slice_informer(driver):
    assert driver.slice_informer is not None
    driver.slice_informer.start()
    assert driver.slice_informer.wait_for_sync(timeout=10)


def test_slice_informer_is_node_scoped(tmp_path):
    """The plugin's slice watcher holds THIS node's slices only — the
    PR-10 field-selector scoping wired into the real plugin: a foreign
    node's slice never enters the store."""
    driver, backend = make_driver(tmp_path)
    driver.publish_resources()
    own = len(ResourceClient(backend, RESOURCE_SLICES).list())
    assert own > 0
    _start_slice_informer(driver)
    try:
        slices = ResourceClient(backend, RESOURCE_SLICES)
        slices.create({
            "metadata": {"name": "foreign-slice"},
            "spec": {"nodeName": "some-other-node", "pool": {
                "name": "some-other-node", "generation": 1,
            }, "devices": []},
        })
        time.sleep(0.2)  # would have dispatched by now
        assert driver.slice_informer.store_size() == own
        assert driver.metrics.get_counter(
            "slice_drift_detected_total"
        ) == 0
    finally:
        driver.slice_informer.stop()


def test_slice_informer_heals_external_deletion(tmp_path):
    """An admin/GC deletion of a slice we committed is external drift:
    the informer event invalidates the publisher's diff cache and rides
    the coalesced republish — the slice is back within the window, not
    after the reverify poll."""
    driver, backend = make_driver(
        tmp_path, publish_coalesce_seconds=0.05
    )
    driver.publish_resources()
    _start_slice_informer(driver)
    try:
        slices = ResourceClient(backend, RESOURCE_SLICES)
        victim = slices.list()[0]["metadata"]["name"]
        # Our own publishes never count as drift.
        driver.publish_resources()
        assert driver.metrics.get_counter(
            "slice_drift_detected_total"
        ) == 0
        slices.delete(victim)
        deadline = time.monotonic() + 10
        while (
            slices.try_get(victim) is None
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert slices.try_get(victim) is not None, (
            "externally deleted slice was not republished"
        )
        assert driver.metrics.get_counter(
            "slice_drift_detected_total"
        ) >= 1
    finally:
        driver.slice_informer.stop()


def test_slice_informer_stomps_external_modification(tmp_path):
    """An external writer rewriting our slice's spec is drift too: the
    next coalesced pass restores the desired content (merge-PATCH
    last-writer-wins, us last)."""
    from tpu_dra.plugin.slicepub import slice_content_digest

    driver, backend = make_driver(
        tmp_path, publish_coalesce_seconds=0.05
    )
    driver.publish_resources()
    _start_slice_informer(driver)
    try:
        slices = ResourceClient(backend, RESOURCE_SLICES)
        victim = slices.list()[0]
        name = victim["metadata"]["name"]
        want = slice_content_digest(victim)
        with driver._publish_lock:
            assert driver._publisher.committed_digest(name) == want
        mangled = dict(victim["spec"])
        mangled["devices"] = []
        slices.patch(name, {"spec": mangled})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            cur = slices.try_get(name)
            if cur is not None and slice_content_digest(cur) == want:
                break
            time.sleep(0.01)
        cur = slices.try_get(name)
        assert cur is not None and slice_content_digest(cur) == want, (
            "externally modified slice was not stomped back to desired"
        )
    finally:
        driver.slice_informer.stop()


def test_watch_slices_false_keeps_poll_only_behavior(tmp_path):
    driver, _backend = make_driver(tmp_path, watch_slices=False)
    assert driver.slice_informer is None


def test_slice_drift_republish_is_rate_limited(tmp_path):
    """A PERSISTENT external writer (split-brain second plugin, an
    operator loop) must not drive a hot republish war: one drift-driven
    heal per cooldown window. The diff cache is still invalidated every
    time, so any other publish trigger re-verifies and heals."""
    driver, backend = make_driver(
        tmp_path, publish_coalesce_seconds=0.0
    )
    driver.publish_resources()
    _start_slice_informer(driver)
    try:
        slices = ResourceClient(backend, RESOURCE_SLICES)
        victim = slices.list()[0]["metadata"]["name"]
        slices.delete(victim)
        deadline = time.monotonic() + 10
        while (
            slices.try_get(victim) is None
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert slices.try_get(victim) is not None
        # Second drift inside the window: detected, but the heal is
        # deferred (no republish burst).
        slices.delete(victim)
        deadline = time.monotonic() + 1.0
        while (
            driver.metrics.get_counter("slice_drift_detected_total") < 2
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert driver.metrics.get_counter(
            "slice_drift_detected_total"
        ) >= 2
        time.sleep(0.3)
        assert slices.try_get(victim) is None, (
            "drift republish ignored the cooldown window"
        )
        # The cache WAS invalidated: the next ordinary publish heals.
        driver.publish_resources()
        assert slices.try_get(victim) is not None
    finally:
        driver.slice_informer.stop()
