"""minihelm renders the shipped chart with real helm semantics.

The renderer backs the batsless e2e runner ("helm install" against the
fake apiserver); these tests pin the semantics the chart depends on:
value overrides, feature-gate string building (scoped variable mutation
in range), capability-driven API version selection, gated documents, and
include/define plumbing.
"""

import os

import pytest
import yaml

from tpu_dra.infra.minihelm import (
    Renderer,
    TemplateError,
    Vars,
    _lex,
    _parse,
    parse_set,
    render_chart,
)

CHART = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "deployments", "helm", "tpu-dra-driver",
)


def render_text(src: str, dot=None, defines_src: str = "") -> str:
    defines = {}
    if defines_src:
        _parse(_lex(defines_src), defines)
    nodes = _parse(_lex(src), defines)
    return Renderer(defines).render_nodes(
        nodes, dot or {}, Vars(initial={"$": dot or {}})
    )


def test_chart_renders_all_expected_kinds():
    docs = render_chart(CHART)
    kinds = {d["kind"] for d in docs}
    assert {
        "CustomResourceDefinition", "DeviceClass", "DaemonSet",
        "Deployment", "ServiceAccount", "ClusterRole", "ClusterRoleBinding",
        "ValidatingAdmissionPolicy",
    } <= kinds
    # 5 DeviceClasses (the bats basics assertion).
    assert sum(1 for d in docs if d["kind"] == "DeviceClass") == 5


def test_feature_gates_string_built_via_range_mutation():
    docs = render_chart(
        CHART,
        values_overrides=[
            parse_set("featureGates.DynamicSubslice=true"),
            parse_set("featureGates.MultiplexingSupport=false"),
        ],
    )
    ds = next(d for d in docs if d["kind"] == "DaemonSet")
    envs = [
        e
        for c in ds["spec"]["template"]["spec"]["containers"]
        for e in c.get("env", [])
        if e["name"] == "FEATURE_GATES"
    ]
    assert envs and all(
        e["value"] == "DynamicSubslice=true,MultiplexingSupport=false"
        for e in envs
    )


def test_resource_api_version_follows_capabilities():
    v1 = render_chart(CHART, api_versions=["resource.k8s.io/v1"])
    dc = next(d for d in v1 if d["kind"] == "DeviceClass")
    assert dc["apiVersion"] == "resource.k8s.io/v1"
    # v1-only feature: extended-resource bridging on the tpu class.
    tpu = next(
        d for d in v1
        if d["kind"] == "DeviceClass" and d["metadata"]["name"] == "tpu.google.com"
    )
    assert tpu["spec"]["extendedResourceName"] == "google.com/tpu"

    beta = render_chart(CHART, api_versions=[])
    dc = next(d for d in beta if d["kind"] == "DeviceClass")
    assert dc["apiVersion"] == "resource.k8s.io/v1beta1"
    tpu = next(
        d for d in beta
        if d["kind"] == "DeviceClass" and d["metadata"]["name"] == "tpu.google.com"
    )
    assert "extendedResourceName" not in tpu["spec"]


def test_webhook_docs_gated():
    assert not any(
        d["kind"] == "ValidatingWebhookConfiguration"
        for d in render_chart(CHART)
    )
    docs = render_chart(CHART, values_overrides=[parse_set("webhook.enabled=true")])
    hook = next(
        d for d in docs if d["kind"] == "ValidatingWebhookConfiguration"
    )
    rules = hook["webhooks"][0]["rules"]
    assert any("resourceclaims" in r["resources"] for r in rules)


def test_chart_fail_action_raises():
    with pytest.raises(TemplateError, match="tpulibBackend"):
        render_chart(
            CHART, values_overrides=[parse_set("tpulibBackend=bogus")]
        )


def test_scoping_colon_declares_eq_assigns():
    out = render_text(
        '{{- $x := list }}'
        '{{- range $k, $v := .m }}{{- $x = append $x $k }}{{- end }}'
        '{{ join "," $x }}',
        dot={"m": {"b": 1, "a": 2}},
    )
    assert out.strip() == "a,b"  # sorted map iteration, mutation survives


def test_adjacent_field_chain_vs_argument():
    # `$x.f` chains; `contains $n .Release.Name` passes two args.
    out = render_text(
        '{{- $x := .obj }}{{ $x.f }}|{{ contains "a" .s }}',
        dot={"obj": {"f": "v"}, "s": "abc"},
    )
    assert out.strip() == "v|true"


def test_values_yaml_matches_rendered_daemonset_wiring():
    """The DaemonSet wires the stub path + backend envs the kind demo
    relies on (values.stubInventoryPath)."""
    docs = render_chart(
        CHART, values_overrides=[parse_set("stubInventoryPath=/etc/tpu/stub.yaml")]
    )
    ds = next(d for d in docs if d["kind"] == "DaemonSet")
    text = yaml.safe_dump(ds)
    assert "/etc/tpu/stub.yaml" in text
