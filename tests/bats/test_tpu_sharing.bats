# shellcheck disable=SC2148
# Chip-sharing suite (MPS-analog per-process multiplexing).

setup_file() {
  load 'helpers.sh'
  _common_setup
  local _iargs=(
    "--set" "featureGates.MultiplexingSupport=true"
    "--set" "featureGates.TimeSlicingSettings=true"
  )
  iupgrade_wait _iargs
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace tpu-test3 --ignore-not-found --timeout=120s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "sharing: two pods share one chip via multiplexing" {
  k_apply "${REPO_ROOT}/demo/specs/quickstart/tpu-test3.yaml"
  kubectl -n tpu-test3 wait --for=jsonpath='{.status.phase}'=Succeeded pod/pod0 pod/pod1 --timeout=180s
  run kubectl -n tpu-test3 logs pod0
  [[ "$output" == *MULTIPLEX* ]] || [[ "$output" == *TPU_* ]]
}

@test "sharing: two pods rotate one chip under a time-slice quantum" {
  k_apply "${REPO_ROOT}/demo/specs/quickstart/tpu-test7.yaml"
  kubectl -n tpu-test7 wait --for=jsonpath='{.status.phase}'=Succeeded pod/pod0 pod/pod1 --timeout=180s
  # Both pods must have re-acquired the lease (rotation happened): the
  # quantum measurably changed scheduling, not just env bookkeeping.
  run kubectl -n tpu-test7 logs pod0
  [[ "$output" == *"rotations:"* ]]
  [[ "$output" != *"rotations: 0"* ]]
  run kubectl -n tpu-test7 logs pod1
  [[ "$output" != *"rotations: 0"* ]]
  kubectl delete namespace tpu-test7 --ignore-not-found --timeout=120s
}

@test "sharing: invalid sharing config is rejected by admission" {
  # With the webhook (or validation at prepare), a bad interval must fail.
  run kubectl apply -n tpu-test3 -f - <<YAML
apiVersion: ${TEST_RESOURCE_API_VERSION:-resource.k8s.io/v1beta1}
kind: ResourceClaim
metadata:
  name: bad-sharing
spec:
  devices:
    requests:
    - name: tpu
      deviceClassName: tpu.google.com
    config:
    - requests: ["tpu"]
      opaque:
        driver: tpu.google.com
        parameters:
          apiVersion: resource.tpu.google.com/v1beta1
          kind: TpuConfig
          sharing:
            strategy: TimeSlicing
            timeSlicingConfig:
              interval: Bogus
YAML
  # Webhook enabled -> apply fails; webhook disabled -> claim stays unprepared.
  if kubectl get validatingwebhookconfigurations | grep -q tpu-dra; then
    [ "$status" -ne 0 ]
  fi
}

@test "sharing: device gate fences the real sandbox inodes (mode: device)" {
  # r5 (VERDICT #8): the non-surrogate enforcement record. The gated
  # paths are the SAME inodes the stub advertises and CDI injects — a
  # demoted cooperative client is blocked pre-lease and admitted under
  # its lease; a demoted adversary is EPERM-fenced for its whole window.
  local _iargs=(
    "--set" "featureGates.MultiplexingSupport=true"
    "--set" "featureGates.TimeSlicingSettings=true"
    "--set" "featureGates.MultiplexDeviceGate=true"
  )
  iupgrade_wait _iargs
  k_apply "${REPO_ROOT}/tests/bats/specs/tpu-devicegate.yaml"
  kubectl -n tpu-devgate wait --for=jsonpath='{.status.phase}'=Succeeded \
    pod/coop pod/adversary --timeout=180s
  run kubectl -n tpu-devgate logs coop
  [[ "$output" == *"OPENED_UNDER_LEASE=1"* ]]
  [[ "$output" == *"BLOCKED_PRE_LEASE=1"* ]]
  run kubectl -n tpu-devgate logs adversary
  [[ "$output" == *"(mode: device)"* ]]
  [[ "$output" == *ADVERSARY_BLOCKED* ]]
  kubectl delete namespace tpu-devgate --ignore-not-found --timeout=120s
}
