# Suite-level preflight (reference: tests/bats/setup_suite.bash): assert the
# cluster serves a DRA API group version we support and export it.

setup_suite() {
  if ! command -v kubectl >/dev/null || ! command -v helm >/dev/null; then
    echo "kubectl and helm are required" >&2
    return 1
  fi

  local versions
  versions="$(kubectl api-versions)"
  if echo "$versions" | grep -q '^resource.k8s.io/v1$'; then
    export TEST_RESOURCE_API_VERSION="resource.k8s.io/v1"
  elif echo "$versions" | grep -q '^resource.k8s.io/v1beta2$'; then
    export TEST_RESOURCE_API_VERSION="resource.k8s.io/v1beta2"
  elif echo "$versions" | grep -q '^resource.k8s.io/v1beta1$'; then
    export TEST_RESOURCE_API_VERSION="resource.k8s.io/v1beta1"
  else
    echo "cluster does not serve resource.k8s.io (enable DRA)" >&2
    return 1
  fi
  echo "using ${TEST_RESOURCE_API_VERSION}" >&3 2>/dev/null || true
}
