# shellcheck disable=SC2148
# ComputeDomain bring-up: controller stamps DS + workload RCT, daemons
# register, readiness gates workload start (reference: test_cd_mnnvl_workload).

setup_file() {
  load 'helpers.sh'
  _common_setup
  local _iargs=()
  iupgrade_wait _iargs
  k_apply "${REPO_ROOT}/demo/specs/computedomain/computedomain.yaml"
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace cd-demo --ignore-not-found --timeout=180s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "cd: controller creates workload claim template" {
  for _ in $(seq 1 30); do
    kubectl -n cd-demo get resourceclaimtemplate v5p-16-channel 2>/dev/null && return 0
    sleep 2
  done
  return 1
}

@test "cd: per-CD daemonset exists" {
  run bash -c "kubectl -n ${TEST_NAMESPACE} get daemonsets -o name | grep -c compute-domain"
  [ "$output" -ge 1 ]
}

@test "cd: workload pod is gated until domain is ready, then starts" {
  k_apply "${REPO_ROOT}/demo/specs/computedomain/llama-pjit-job.yaml"
  # The pods stay in ContainerCreating while the CD is NotReady; once every
  # host registers, status flips Ready and the job runs.
  wait_for_cd_status cd-demo v5p-16 Ready
  kubectl -n cd-demo wait --for=condition=complete job/llama-pjit --timeout=900s
}

@test "cd: deleting the domain cleans up DS, RCT, and node labels" {
  kubectl -n cd-demo delete computedomain v5p-16 --timeout=180s
  for _ in $(seq 1 45); do
    local left
    left="$(kubectl -n cd-demo get resourceclaimtemplate v5p-16-channel \
      --no-headers 2>/dev/null | wc -l)"
    [ "$left" -eq 0 ] && break
    sleep 2
  done
  [ "$left" -eq 0 ]
  run bash -c "kubectl get nodes -o json | jq -r '[.items[].metadata.labels | keys[] | select(startswith(\"resource.tpu.google.com/computeDomain\"))] | length'"
  [ "$output" == "0" ]
}
