# shellcheck disable=SC2148
# Structured timing-log assertions (reference: test_cd_logging.bats): the
# prepare path emits t_prep_* wall-time markers at high verbosity — the
# observability basis for the claim-latency metric in BASELINE.md.

setup_file() {
  load 'helpers.sh'
  _common_setup
  local _iargs=("--set" "logVerbosity=7")
  iupgrade_wait _iargs
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace tpu-test2 --ignore-not-found --timeout=180s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "logging: prepare emits t_prep_* timing markers" {
  k_apply "${REPO_ROOT}/demo/specs/quickstart/tpu-test2.yaml"
  kubectl -n tpu-test2 wait --for=jsonpath='{.status.phase}'=Succeeded \
    pod/pod --timeout=300s
  local pods logs=""
  pods="$(kubectl -n "${TEST_NAMESPACE}" get pods \
    -l tpu-dra-driver-component=kubelet-plugin -o name)"
  for p in $pods; do
    logs+="$(kubectl -n "${TEST_NAMESPACE}" logs "$p" -c tpus --tail=-1 || true)"
  done
  [[ "$logs" == *t_prep_lock_acq* ]]
  [[ "$logs" == *t_prep_total* ]]
}

@test "logging: unprepare leaves no ERROR lines for the happy path" {
  kubectl delete namespace tpu-test2 --ignore-not-found --timeout=180s
  sleep 5
  local pods
  pods="$(kubectl -n "${TEST_NAMESPACE}" get pods \
    -l tpu-dra-driver-component=kubelet-plugin -o name)"
  for p in $pods; do
    run bash -c "kubectl -n ${TEST_NAMESPACE} logs $p -c tpus --tail=200 | grep -c ' E '"
    [ "$output" == "0" ] || [ "$status" -ne 0 ]
  done
}
