# shellcheck disable=SC2148

setup_file() {
  load 'helpers.sh'
  _common_setup
  local _iargs=()
  iupgrade_wait _iargs
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace bats-tpu-basic --ignore-not-found --timeout=120s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "tpu: 2 pods get 2 distinct chips" {
  k_apply "${REPO_ROOT}/tests/bats/specs/tpu-2pods-2chips.yaml"
  kubectl -n bats-tpu-basic wait --for=condition=READY pods pod0 pod1 --timeout=120s

  run kubectl -n bats-tpu-basic logs pod0
  [[ "$output" == *TPU_VISIBLE_DEVICES* ]] || [[ "$output" == *TPU_DRA_DRIVER_VERSION* ]]

  # Exclusive allocation: the two pods must not share a device.
  local d0 d1
  d0="$(kubectl -n bats-tpu-basic get resourceclaims -o json | \
    jq -r '[.items[] | select(.status.allocation != null) | .status.allocation.devices.results[0].device] | .[0]')"
  d1="$(kubectl -n bats-tpu-basic get resourceclaims -o json | \
    jq -r '[.items[] | select(.status.allocation != null) | .status.allocation.devices.results[0].device] | .[1]')"
  [ -n "$d0" ] && [ -n "$d1" ] && [ "$d0" != "$d1" ]
}

@test "tpu: shared claim across two containers of one pod" {
  k_apply "${REPO_ROOT}/demo/specs/quickstart/tpu-test2.yaml"
  kubectl -n tpu-test2 wait --for=jsonpath='{.status.phase}'=Succeeded pod/pod --timeout=120s
  kubectl delete namespace tpu-test2 --ignore-not-found --timeout=120s
}

@test "tpu: claims release on pod deletion" {
  kubectl -n bats-tpu-basic delete pod pod0 pod1 --ignore-not-found --timeout=120s
  for _ in $(seq 1 30); do
    local n
    n="$(kubectl -n bats-tpu-basic get resourceclaims --no-headers 2>/dev/null | wc -l)"
    [ "$n" -eq 0 ] && return 0
    sleep 2
  done
  return 1
}

@test "tpu: adminAccess claims are rejected outside the driver namespace" {
  # Comprehension-bearing VAP (adminaccess-policy): the filter/all over
  # spec.devices.requests must deny at APPLY time, with the policy's
  # messageExpression surfaced to the user.
  run kubectl apply -f - <<YAML
apiVersion: ${TEST_RESOURCE_API_VERSION:-resource.k8s.io/v1beta1}
kind: ResourceClaim
metadata:
  namespace: bats-tpu-basic
  name: snooper
spec:
  devices:
    requests:
    - name: r0
      deviceClassName: tpu.google.com
      adminAccess: true
YAML
  [ "$status" -ne 0 ]
  [[ "$output" == *"only permitted in namespace"* ]]
}
