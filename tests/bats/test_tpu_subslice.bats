# shellcheck disable=SC2148
# Sub-slice allocation (DynamicMIG-analog) suite: requires the
# DynamicSubslice feature gate; asserts advertised abstract shapes carry
# shared counters so overlapping placements cannot be co-allocated.

setup_file() {
  load 'helpers.sh'
  _common_setup
  # MultiplexingSupport composes with DynamicSubslice since r5 (the
  # reference's DynamicMIG x MPSSupport gate exclusion has no TPU
  # analog) — the composition is exercised by the shared-dynamic test.
  local _iargs=(
    "--set" "featureGates.DynamicSubslice=true"
    "--set" "featureGates.MultiplexingSupport=true"
  )
  iupgrade_wait _iargs
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace tpu-test5 --ignore-not-found --timeout=120s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "subslice: abstract shapes advertised with shared counters" {
  wait_for_all_tpu_resource_slices tpu.google.com
  local combined
  combined="$(kubectl get resourceslices -o json | \
    jq -r '[.items[] | select(.spec.driver == "tpu.google.com")
            | .spec.devices[] | (.basic // .)
            | select(.consumesCounters != null)] | length')"
  [ "$combined" -gt 0 ]
}

@test "subslice: claim materializes a sub-slice" {
  k_apply "${REPO_ROOT}/demo/specs/quickstart/tpu-test5.yaml"
  kubectl -n tpu-test5 wait --for=jsonpath='{.status.phase}'=Succeeded pod/pod --timeout=180s
}

@test "subslice: attributes include shape and origin" {
  local attrs
  attrs="$(kubectl get resourceslices -o json | \
    jq -r '[.items[] | select(.spec.driver == "tpu.google.com")
            | .spec.devices[] | (.basic // .)
            | select(.attributes.type.string | startswith("subslice"))][0].attributes | keys[]')"
  echo "$attrs" | grep -q subsliceShape
  echo "$attrs" | grep -q subsliceOrigin
}

# --- dynmig-parity depth (reference test_gpu_dynmig.bats:55-90) ---

@test "subslice: shared counter sets model the chips" {
  # Every published sub-slice consumes from a per-chip counter set, so the
  # scheduler cannot co-allocate overlapping placements.
  local sets
  sets="$(kubectl get resourceslices -o json | \
    jq -r '[.items[] | select(.spec.driver == "tpu.google.com")
            | .spec.sharedCounters // [] | .[]] | length')"
  [ "$sets" -gt 0 ]
  local consumers
  consumers="$(kubectl get resourceslices -o json | \
    jq -r '[.items[] | select(.spec.driver == "tpu.google.com")
            | .spec.devices[] | (.basic // .)
            | select(.consumesCounters != null)
            | .consumesCounters[].counterSet] | unique | length')"
  [ "$consumers" -gt 0 ]
}

@test "subslice: overlapping second claim is refused while the first is held" {
  # The RCT-generated claim from tpu-test5 stays ALLOCATED after its pod
  # succeeds (released only on pod deletion); the scheduler must refuse a
  # 2x2 claim whose placement consumes the same chip counters ON THE SAME
  # HOST — pin the racing pod to the node the first sub-slice landed on.
  local node
  node="$(kubectl -n tpu-test5 get pod pod -o jsonpath='{.spec.nodeName}')"
  [ -n "$node" ]
  sed "s|OVERLAP_TARGET_NODE|$node|" \
    "${REPO_ROOT}/tests/bats/specs/tpu-subslice-overlap.yaml" | k_apply /dev/stdin
  run kubectl -n tpu-test5 wait --for=jsonpath='{.status.phase}'=Succeeded \
    pod/overlap-pod --timeout=30s
  [ "$status" -ne 0 ]
}

@test "subslice: releasing the first claim frees its counters" {
  # Deleting the first pod releases (and GCs) its RCT claim; the counters
  # it consumed return to the set, so the previously-refused overlap
  # claim must now allocate, prepare, and run to completion. This is the
  # end-to-end proof that unprepare gave the silicon back.
  kubectl -n tpu-test5 delete pod pod --ignore-not-found --timeout=120s
  for _ in $(seq 1 30); do
    local held
    held="$(kubectl -n tpu-test5 get resourceclaims -o json | \
      jq -r '[.items[] | select(.metadata.name | startswith("pod-"))] | length')"
    [ "$held" -eq 0 ] && break
    sleep 2
  done
  kubectl -n tpu-test5 wait --for=jsonpath='{.status.phase}'=Succeeded \
    pod/overlap-pod --timeout=180s
  kubectl -n tpu-test5 delete pod overlap-pod --ignore-not-found --timeout=60s
  kubectl -n tpu-test5 delete resourceclaim overlap-claim --ignore-not-found --timeout=60s
}

@test "subslice: reshape churn never disturbs a held sub-slice workload" {
  # BASELINE config 5 under load (bench twin: measure_reshape_under_load):
  # a pod HOLDS a 1x1 sub-slice while neighbors cycle allocate/prepare/
  # unprepare on the host's remaining chips. The holder must stay Running
  # on the same claim throughout.
  for _ in $(seq 1 30); do
    local held
    held="$(kubectl -n tpu-test5 get resourceclaims -o json | \
      jq -r '.items | length')"
    [ "$held" -eq 0 ] && break
    sleep 2
  done
  k_apply "${REPO_ROOT}/tests/bats/specs/tpu-subslice-churn.yaml"
  kubectl -n tpu-test5 wait --for=jsonpath='{.status.phase}'=Running \
    pod/ss-holder --timeout=180s
  local claim_uid
  claim_uid="$(kubectl -n tpu-test5 get resourceclaims -o json | \
    jq -r '[.items[] | select(.metadata.name | startswith("ss-holder-"))][0].metadata.uid // empty')"
  [ -n "$claim_uid" ]
  for i in 1 2 3; do
    sed "s/CHURN_NAME/churn-$i/" \
      "${REPO_ROOT}/tests/bats/specs/tpu-subslice-churn-pod.yaml" | k_apply /dev/stdin
    kubectl -n tpu-test5 wait --for=jsonpath='{.status.phase}'=Succeeded \
      "pod/churn-$i" --timeout=180s
    kubectl -n tpu-test5 delete pod "churn-$i" --timeout=120s
  done
  local phase uid_now
  phase="$(kubectl -n tpu-test5 get pod ss-holder -o jsonpath='{.status.phase}')"
  [ "$phase" = "Running" ]
  uid_now="$(kubectl -n tpu-test5 get resourceclaims -o json | \
    jq -r '[.items[] | select(.metadata.name | startswith("ss-holder-"))][0].metadata.uid')"
  [ "$uid_now" = "$claim_uid" ]
  kubectl -n tpu-test5 delete pod ss-holder --ignore-not-found --timeout=60s
}

@test "subslice: two pods share one DYNAMIC sub-slice via multiplexing" {
  # r5 (VERDICT #2): the arbiter owns the placement's parent chips,
  # which exist before the sub-slice is materialized — so sharing works
  # on dynamically-created partitions (the reference refuses this at
  # the gate level, featuregates.go:184-186).
  k_apply "${REPO_ROOT}/demo/specs/subslice-multiplex/dynamic-shared.yaml"
  kubectl -n tpu-ssdyn-mux wait --for=jsonpath='{.status.phase}'=Succeeded \
    pod/wl0 pod/wl1 --timeout=180s
  # Both workloads held a brokered lease (arbitrated, not exclusive)
  # over the dynamic sub-slice's TWO parent chips, and the CDI env
  # proves the 1x2 placement materialized. (The claim's allocation is
  # released the moment both pods succeed, so the proof reads from the
  # pods — not from claim status, which would race the teardown.)
  run kubectl -n tpu-ssdyn-mux logs wl0
  [[ "$output" == *holding* ]]
  [[ "$output" != *exclusive* ]]
  [[ "$output" == *"shape=1x2"* ]]
  [[ "$output" == *"', '"* ]]  # two parent-chip uuids in the lease
  run kubectl -n tpu-ssdyn-mux logs wl1
  [[ "$output" == *holding* ]]
  [[ "$output" != *exclusive* ]]
  [[ "$output" == *"shape=1x2"* ]]
  kubectl delete namespace tpu-ssdyn-mux --ignore-not-found --timeout=120s
}
