# shellcheck disable=SC2148
# Sub-slice allocation (DynamicMIG-analog) suite: requires the
# DynamicSubslice feature gate; asserts advertised abstract shapes carry
# shared counters so overlapping placements cannot be co-allocated.

setup_file() {
  load 'helpers.sh'
  _common_setup
  local _iargs=("--set" "featureGates.DynamicSubslice=true")
  iupgrade_wait _iargs
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace tpu-test5 --ignore-not-found --timeout=120s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "subslice: abstract shapes advertised with shared counters" {
  wait_for_all_tpu_resource_slices tpu.google.com
  local combined
  combined="$(kubectl get resourceslices -o json | \
    jq -r '[.items[] | select(.spec.driver == "tpu.google.com")
            | .spec.devices[] | (.basic // .)
            | select(.consumesCounters != null)] | length')"
  [ "$combined" -gt 0 ]
}

@test "subslice: claim materializes a sub-slice" {
  k_apply "${REPO_ROOT}/demo/specs/quickstart/tpu-test5.yaml"
  kubectl -n tpu-test5 wait --for=jsonpath='{.status.phase}'=Succeeded pod/pod --timeout=180s
}

@test "subslice: attributes include shape and origin" {
  local attrs
  attrs="$(kubectl get resourceslices -o json | \
    jq -r '[.items[] | select(.spec.driver == "tpu.google.com")
            | .spec.devices[] | (.basic // .)
            | select(.attributes.type.string | startswith("subslice"))][0].attributes | keys[]')"
  echo "$attrs" | grep -q subsliceShape
  echo "$attrs" | grep -q subsliceOrigin
}
