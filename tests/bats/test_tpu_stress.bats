# shellcheck disable=SC2148
# Claim churn under load (reference: test_gpu_stress.bats): many short-lived
# claims against the same chips; the checkpointed state machine must never
# double-allocate or leak prepared devices.

setup_file() {
  load 'helpers.sh'
  _common_setup
  local _iargs=()
  iupgrade_wait _iargs
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace bats-stress --ignore-not-found --timeout=300s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "stress: 20 sequential claim cycles leave no leaked state" {
  kubectl create namespace bats-stress --dry-run=client -o yaml | kubectl apply -f -
  for i in $(seq 1 20); do
    cat <<EOF | sed "s|resource.k8s.io/v1beta1|${TEST_RESOURCE_API_VERSION:-resource.k8s.io/v1beta1}|" | kubectl apply -f -
apiVersion: resource.k8s.io/v1beta1
kind: ResourceClaim
metadata:
  namespace: bats-stress
  name: churn-$i
spec:
  devices:
    requests:
    - name: tpu
      deviceClassName: tpu.google.com
EOF
  done
  # Pods cycling through the claims in waves of 4 (the stub host has 4 chips).
  for wave in 0 1 2 3 4; do
    for j in 1 2 3 4; do
      local i=$((wave * 4 + j))
      [ "$i" -le 20 ] || continue
      cat <<EOF | kubectl apply -f -
apiVersion: v1
kind: Pod
metadata:
  namespace: bats-stress
  name: churn-pod-$i
spec:
  restartPolicy: Never
  containers:
  - name: ctr
    image: ${TEST_IMAGE_REPO}:${TEST_IMAGE_TAG}
    command: ["python", "-c", "print('ok')"]
    resources:
      claims:
      - name: tpu
  resourceClaims:
  - name: tpu
    resourceClaimName: churn-$i
  tolerations:
  - key: google.com/tpu
    operator: Exists
    effect: NoSchedule
EOF
    done
    for j in 1 2 3 4; do
      local i=$((wave * 4 + j))
      [ "$i" -le 20 ] || continue
      kubectl -n bats-stress wait --for=jsonpath='{.status.phase}'=Succeeded \
        "pod/churn-pod-$i" --timeout=300s
      kubectl -n bats-stress delete pod "churn-pod-$i" --timeout=120s
    done
  done
  # After the churn every claim must be deallocated (no pod references it).
  run bash -c "kubectl -n bats-stress get resourceclaims -o json | \
    jq '[.items[] | select(.status.allocation != null and .status.reservedFor != null and (.status.reservedFor | length) > 0)] | length'"
  [ "$output" == "0" ]
}

@test "stress: overcommit claim stays pending, then schedules after release" {
  # 4-chip stub host: a 5th concurrent single-chip pod cannot schedule.
  for i in 1 2 3 4 5; do
    cat <<EOF | sed "s|resource.k8s.io/v1beta1|${TEST_RESOURCE_API_VERSION:-resource.k8s.io/v1beta1}|" | kubectl apply -f -
apiVersion: resource.k8s.io/v1beta1
kind: ResourceClaim
metadata:
  namespace: bats-stress
  name: over-$i
spec:
  devices:
    requests:
    - name: tpu
      deviceClassName: tpu.google.com
EOF
    cat <<EOF | kubectl apply -f -
apiVersion: v1
kind: Pod
metadata:
  namespace: bats-stress
  name: over-pod-$i
spec:
  restartPolicy: Never
  containers:
  - name: ctr
    image: ${TEST_IMAGE_REPO}:${TEST_IMAGE_TAG}
    command: ["python", "-c", "import time; time.sleep(30)"]
    resources:
      claims:
      - name: tpu
  resourceClaims:
  - name: tpu
    resourceClaimName: over-$i
  tolerations:
  - key: google.com/tpu
    operator: Exists
    effect: NoSchedule
EOF
  done
  # All five eventually run (the fifth after one of the first four exits).
  for i in 1 2 3 4 5; do
    kubectl -n bats-stress wait --for=jsonpath='{.status.phase}'=Succeeded \
      "pod/over-pod-$i" --timeout=600s
  done
}
