# shellcheck disable=SC2148
# Extended-resource -> DRA bridging (reference: test_gpu_extres.bats): a pod
# asking for the classic `google.com/tpu` extended resource is satisfied by
# DRA allocation via the DeviceClass's extendedResourceName (only served on
# resource.k8s.io/v1 clusters).

setup_file() {
  load 'helpers.sh'
  _common_setup
  if [[ "${TEST_RESOURCE_API_VERSION:-}" != "resource.k8s.io/v1" ]]; then
    skip "extendedResourceName needs resource.k8s.io/v1 (have ${TEST_RESOURCE_API_VERSION:-unset})"
  fi
  local _iargs=()
  iupgrade_wait _iargs
}

setup() {
  load 'helpers.sh'
  _common_setup
  if [[ "${TEST_RESOURCE_API_VERSION:-}" != "resource.k8s.io/v1" ]]; then
    skip "extendedResourceName needs resource.k8s.io/v1"
  fi
}

teardown_file() {
  kubectl delete namespace bats-extres --ignore-not-found --timeout=180s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "extres: DeviceClass advertises the extended-resource bridge" {
  run kubectl get deviceclass tpu.google.com \
    -o jsonpath='{.spec.extendedResourceName}'
  [ "$output" == "google.com/tpu" ]
}

@test "extres: classic resources.limits pod gets a DRA-allocated chip" {
  kubectl create namespace bats-extres --dry-run=client -o yaml | kubectl apply -f -
  cat <<EOF | kubectl apply -f -
apiVersion: v1
kind: Pod
metadata:
  namespace: bats-extres
  name: classic
spec:
  restartPolicy: Never
  containers:
  - name: ctr
    image: ${TEST_IMAGE_REPO}:${TEST_IMAGE_TAG}
    command: ["python", "-c"]
    args: ["import os; print('TPU_VISIBLE_DEVICES=' + os.environ.get('TPU_VISIBLE_DEVICES', 'MISSING'))"]
    resources:
      limits:
        google.com/tpu: 1
  tolerations:
  - key: google.com/tpu
    operator: Exists
    effect: NoSchedule
EOF
  kubectl -n bats-extres wait --for=jsonpath='{.status.phase}'=Succeeded \
    pod/classic --timeout=300s
  run kubectl -n bats-extres logs classic
  [[ "$output" == *TPU_VISIBLE_DEVICES=* ]]
  [[ "$output" != *MISSING* ]]
}
