# shellcheck disable=SC2148
# Channel-injection modes (reference: test_cd_imex_chan_inject.bats): the
# slice-membership "channel" surface a workload pod sees — bootstrap env +
# the per-CD config-dir mount — under default and allocationMode=All claims.

setup_file() {
  load 'helpers.sh'
  _common_setup
  local _iargs=()
  iupgrade_wait _iargs
  kubectl create namespace cd-demo --dry-run=client -o yaml | kubectl apply -f -
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace cd-demo --ignore-not-found --timeout=180s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "chan-inject: allocationMode All injects the slice bootstrap surface" {
  k_apply "${REPO_ROOT}/demo/specs/computedomain/channel-injection-all.yaml"
  kubectl -n cd-demo wait --for=jsonpath='{.status.phase}'=Succeeded \
    pod/channel-inspect --timeout=600s
  run kubectl -n cd-demo logs channel-inspect
  # The injected env must carry the multi-host bootstrap identity.
  [[ "$output" == *TPU_WORKER_ID* ]]
  [[ "$output" == *TPU_WORKER_HOSTNAMES* ]]
}

@test "chan-inject: channel claim forged in another namespace never prepares" {
  # The CD lives in cd-demo. Forge an RCT in another namespace embedding the
  # CD's real domainID: prepare must fail the namespace assertion
  # (AssertComputeDomainNamespace analog) and hold the pod forever.
  kubectl create namespace cd-demo-other --dry-run=client -o yaml | kubectl apply -f -
  local uid
  uid="$(kubectl -n cd-demo get computedomain all-channels -o jsonpath='{.metadata.uid}')"
  [ -n "$uid" ]
  cat <<EOF | sed "s|resource.k8s.io/v1beta1|${TEST_RESOURCE_API_VERSION:-resource.k8s.io/v1beta1}|" | kubectl apply -f -
apiVersion: resource.k8s.io/v1beta1
kind: ResourceClaimTemplate
metadata:
  namespace: cd-demo-other
  name: forged-channel
spec:
  spec:
    devices:
      requests:
      - name: cd-channel
        deviceClassName: compute-domain-default-channel.tpu.google.com
      config:
      - requests: ["cd-channel"]
        opaque:
          driver: compute-domain.tpu.google.com
          parameters:
            apiVersion: resource.tpu.google.com/v1beta1
            kind: ComputeDomainChannelConfig
            domainID: "$uid"
EOF
  cat <<EOF | kubectl apply -f -
apiVersion: v1
kind: Pod
metadata:
  namespace: cd-demo-other
  name: forged
spec:
  restartPolicy: Never
  containers:
  - name: ctr
    image: ${TEST_IMAGE_REPO}:${TEST_IMAGE_TAG}
    command: ["python", "-c", "print('should never run')"]
    resources:
      claims:
      - name: ch
  resourceClaims:
  - name: ch
    resourceClaimTemplateName: forged-channel
EOF
  # The pod must stay un-started: prepare keeps failing the namespace
  # assertion, kubelet retries, phase never leaves Pending.
  sleep 45
  run kubectl -n cd-demo-other get pod forged -o jsonpath='{.status.phase}'
  [ "$output" == "Pending" ]
  kubectl delete namespace cd-demo-other --ignore-not-found --timeout=120s
}
