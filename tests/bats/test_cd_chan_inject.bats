# shellcheck disable=SC2148
# Channel-injection modes (reference: test_cd_imex_chan_inject.bats): the
# slice-membership "channel" surface a workload pod sees — bootstrap env +
# the per-CD config-dir mount — under default and allocationMode=All claims.

setup_file() {
  load 'helpers.sh'
  _common_setup
  local _iargs=()
  iupgrade_wait _iargs
  kubectl create namespace cd-demo --dry-run=client -o yaml | kubectl apply -f -
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace cd-demo --ignore-not-found --timeout=180s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "chan-inject: allocationMode All injects the slice bootstrap surface" {
  k_apply "${REPO_ROOT}/demo/specs/computedomain/channel-injection-all.yaml"
  kubectl -n cd-demo wait --for=jsonpath='{.status.phase}'=Succeeded \
    pod/channel-inspect --timeout=600s
  run kubectl -n cd-demo logs channel-inspect
  # The injected env must carry the multi-host bootstrap identity.
  [[ "$output" == *TPU_WORKER_ID* ]]
  [[ "$output" == *TPU_WORKER_HOSTNAMES* ]]
}

@test "chan-inject: channel claim in the wrong namespace is rejected" {
  # The CD lives in cd-demo; a claim referencing its template from another
  # namespace must never prepare (AssertComputeDomainNamespace analog).
  kubectl create namespace cd-demo-other --dry-run=client -o yaml | kubectl apply -f -
  run kubectl -n cd-demo-other get resourceclaimtemplate all-channels-rct
  [ "$status" -ne 0 ]
  kubectl delete namespace cd-demo-other --ignore-not-found --timeout=120s
}
