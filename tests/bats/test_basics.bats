# shellcheck disable=SC2148

setup_file() {
  load 'helpers.sh'
  _common_setup
  # Make the suite rerunnable on a long-lived kind cluster: start from a
  # clean slate so the clean-cluster assertion below is meaningful.
  uninstall_driver
}

setup() {
  load 'helpers.sh'
  _common_setup
}

bats::on_failure() {
  log_objects
}

@test "basics: clean cluster has no leftover driver state" {
  run kubectl get resourceslices -o json
  [ "$status" -eq 0 ]
  run bash -c "kubectl get resourceslices -o json | jq -r '[.items[] | select(.spec.driver | test(\"tpu.google.com\"))] | length'"
  [ "$output" == "0" ]
}

@test "basics: chart installs and plugins roll out" {
  local _iargs=("--set" "logVerbosity=6")
  iupgrade_wait _iargs
  run kubectl -n "${TEST_NAMESPACE}" get pods
  [ "$status" -eq 0 ]
}

@test "basics: CRDs are served" {
  run kubectl get crd computedomains.resource.tpu.google.com
  [ "$status" -eq 0 ]
  run kubectl get crd computedomaincliques.resource.tpu.google.com
  [ "$status" -eq 0 ]
}

@test "basics: DeviceClasses exist" {
  for dc in tpu.google.com tpu-subslice.google.com vfio-tpu.google.com \
            compute-domain-daemon.tpu.google.com \
            compute-domain-default-channel.tpu.google.com; do
    run kubectl get deviceclass "$dc"
    [ "$status" -eq 0 ]
  done
}

@test "basics: every TPU node publishes resource slices" {
  wait_for_all_tpu_resource_slices tpu.google.com
  wait_for_all_tpu_resource_slices compute-domain.tpu.google.com
}

@test "basics: device attributes are sane" {
  local attrs
  attrs="$(get_device_attrs_from_any_tpu_slice tpu.google.com)"
  assert_attr_equal "$attrs" type tpu
  # Generation comes from the stub inventory on the kind path
  # (demo/clusters/kind/stub-config.yaml: v5e; the minicluster runner
  # provisions a 2-host v5p slice and exports the expectation).
  [[ "${TEST_STUB_BACKEND}" != "1" ]] || \
    assert_attr_equal "$attrs" generation "${TEST_EXPECT_GENERATION:-v5e}"
  echo "$attrs" | grep -q '^uuid '
  echo "$attrs" | grep -q '^topologyCoord '
}
