# shellcheck disable=SC2148
# Misc ComputeDomain invariants (reference: test_cd_misc.bats).

setup_file() {
  load 'helpers.sh'
  _common_setup
  local _iargs=()
  iupgrade_wait _iargs
  k_apply "${REPO_ROOT}/demo/specs/computedomain/computedomain.yaml"
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace cd-demo --ignore-not-found --timeout=180s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "misc: controller stamps daemon + workload claim templates" {
  # Workload RCT in the CD's namespace; daemon RCT uid-named in the
  # DRIVER namespace (daemon pods are its only consumers and an RCT
  # reference cannot cross namespaces — resourceclaimtemplate.go:295).
  local found=1
  for _ in $(seq 1 30); do
    kubectl -n cd-demo get resourceclaimtemplate v5p-16-channel \
      >/dev/null 2>&1 && { found=0; break; }
    sleep 2
  done
  [ "$found" -eq 0 ]
  local uid
  uid="$(kubectl -n cd-demo get computedomain v5p-16 -o jsonpath='{.metadata.uid}')"
  [ -n "$uid" ]
  found=1
  for _ in $(seq 1 30); do
    kubectl -n "${TEST_NAMESPACE}" get resourceclaimtemplate \
      "computedomain-daemon-$uid" >/dev/null 2>&1 && { found=0; break; }
    sleep 2
  done
  [ "$found" -eq 0 ]
}

@test "misc: workload RCT embeds opaque channel config with the CD's UID" {
  local uid cfg_uid
  uid="$(kubectl -n cd-demo get computedomain v5p-16 -o jsonpath='{.metadata.uid}')"
  cfg_uid="$(kubectl -n cd-demo get resourceclaimtemplate v5p-16-channel -o json | \
    jq -r '.. | .domainID? // empty' | head -1)"
  [ -n "$uid" ]
  [ "$cfg_uid" == "$uid" ]
}

@test "misc: CD carries our finalizer while alive" {
  run kubectl -n cd-demo get computedomain v5p-16 \
    -o jsonpath='{.metadata.finalizers[0]}'
  [[ "$output" == *computedomain-finalizer* ]] || [[ "$output" == *tpu.google.com* ]]
}

@test "misc: duplicate ComputeDomain names in different namespaces coexist" {
  kubectl create namespace cd-demo2 --dry-run=client -o yaml | kubectl apply -f -
  sed 's/namespace: cd-demo/namespace: cd-demo2/' \
    "${REPO_ROOT}/demo/specs/computedomain/computedomain.yaml" | kubectl apply -f -
  local found=1
  for _ in $(seq 1 30); do
    kubectl -n cd-demo2 get resourceclaimtemplate v5p-16-channel >/dev/null 2>&1 \
      && { found=0; break; }
    sleep 2
  done
  [ "$found" -eq 0 ]
  kubectl -n cd-demo2 delete computedomain v5p-16 --timeout=180s
  kubectl delete namespace cd-demo2 --ignore-not-found --timeout=180s
}

@test "misc: deleting a CD with no workload cleans up promptly" {
  kubectl -n cd-demo delete computedomain v5p-16 --timeout=180s
  local left=1
  for _ in $(seq 1 45); do
    left="$(kubectl -n cd-demo get resourceclaimtemplates --no-headers \
      2>/dev/null | wc -l)"
    [ "$left" -eq 0 ] && break
    sleep 2
  done
  [ "$left" -eq 0 ]
}
