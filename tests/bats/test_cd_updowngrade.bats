# shellcheck disable=SC2148
# ComputeDomain up/downgrade (reference: test_cd_updowngrade.bats): a live
# domain with a running workload must survive a chart upgrade — the CD
# plugin's checkpoint and the controller's informer state both rebuild from
# the API server on restart.

setup_file() {
  load 'helpers.sh'
  _common_setup
  local _iargs=()
  iupgrade_wait _iargs
  k_apply "${REPO_ROOT}/demo/specs/computedomain/computedomain.yaml"
  # "CD follows workload": the job's channel claims label nodes, which
  # schedules the per-CD daemons and drives the domain to Ready.
  k_apply "${REPO_ROOT}/demo/specs/computedomain/llama-pjit-job.yaml"
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace cd-demo --ignore-not-found --timeout=180s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "cd-updowngrade: domain reaches Ready before the upgrade" {
  wait_for_cd_status cd-demo v5p-16 Ready
}

@test "cd-updowngrade: domain stays functional across a chart upgrade" {
  local _iargs=("--set" "logVerbosity=7")
  iupgrade_wait _iargs
  kubectl -n "${TEST_NAMESPACE}" rollout status \
    deploy/tpu-dra-driver-controller --timeout=300s
  wait_for_cd_status cd-demo v5p-16 Ready
}

@test "cd-updowngrade: workload completes after the upgrade" {
  kubectl -n cd-demo wait --for=condition=complete job/llama-pjit \
    --timeout=900s
}

@test "cd-updowngrade: deletion cleans up after the upgrade" {
  kubectl -n cd-demo delete computedomain v5p-16 --timeout=180s
  local left=1
  for _ in $(seq 1 45); do
    left="$(kubectl -n cd-demo get resourceclaimtemplate v5p-16-channel \
      --no-headers 2>/dev/null | wc -l)"
    [ "$left" -eq 0 ] && break
    sleep 2
  done
  [ "$left" -eq 0 ]
}
