# shellcheck disable=SC2148
# Chart upgrade/downgrade with live state (reference: test_gpu_updowngrade.bats):
# a claim prepared by one driver rollout must survive the next — the
# checkpoint carries both V1 and V2 schema renderings so either version can
# read it (tpu_dra/plugin/checkpoint.py marshal).

setup_file() {
  load 'helpers.sh'
  _common_setup
  local _iargs=()
  iupgrade_wait _iargs
  k_apply "${REPO_ROOT}/tests/bats/specs/tpu-sleeper.yaml"
  kubectl -n bats-updowngrade wait --for=jsonpath='{.status.phase}'=Running \
    pod/sleeper --timeout=300s
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace bats-updowngrade --ignore-not-found --timeout=180s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "updowngrade: prepared claim survives a chart upgrade rollout" {
  local _iargs=("--set" "logVerbosity=7")
  iupgrade_wait _iargs
  # The plugin restarted; the sleeper pod (and its prepared claim) must not.
  run kubectl -n bats-updowngrade get pod sleeper \
    -o jsonpath='{.status.phase} {.status.containerStatuses[0].restartCount}'
  [ "$output" == "Running 0" ]
}

@test "updowngrade: node checkpoint carries both V1 and V2 renderings" {
  # kind nodes are docker containers; read the checkpoint where the plugin
  # wrote it on the node the sleeper landed on.
  local node
  node="$(kubectl -n bats-updowngrade get pod sleeper \
    -o jsonpath='{.spec.nodeName}')"
  run bash -c "docker exec '$node' \
    cat /var/lib/kubelet/plugins/tpu.google.com/checkpoint.json | \
    jq -r 'has(\"v1\") and has(\"v2\")'"
  [ "$output" == "true" ]
}

@test "updowngrade: plugin re-registers after kubelet restart" {
  local node
  node="$(kubectl -n bats-updowngrade get pod sleeper \
    -o jsonpath='{.spec.nodeName}')"
  restart_kubelet_on_node "$node"
  wait_for_all_tpu_resource_slices tpu.google.com
}

@test "updowngrade: controller survives rollout with new pod" {
  local before after
  before="$(get_current_controller_pod_name)"
  local _iargs=("--set" "logVerbosity=6")
  iupgrade_wait _iargs
  kubectl -n "${TEST_NAMESPACE}" rollout status \
    deploy/tpu-dra-driver-controller --timeout=300s
  after="$(get_current_controller_pod_name)"
  [ -n "$after" ]
  [ "$before" != "$after" ]
}

@test "updowngrade: claim unprepare still works after the upgrades" {
  k_delete "${REPO_ROOT}/tests/bats/specs/tpu-sleeper.yaml"
  # Unprepare runs when the pod goes away; the claim must be released and
  # deleted (it was created from a template, so it is owned by the pod).
  for _ in $(seq 1 45); do
    local left
    left="$(kubectl -n bats-updowngrade get resourceclaims --no-headers \
      2>/dev/null | wc -l)"
    [ "$left" -eq 0 ] && return 0
    sleep 2
  done
  return 1
}
