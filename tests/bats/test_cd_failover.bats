# shellcheck disable=SC2148
# Fault injection (reference: test_cd_failover.bats + test_cd_nvb_failover.sh):
# kill slice daemons / workers mid-run, assert the domain and job recover.

setup_file() {
  load 'helpers.sh'
  _common_setup
  local _iargs=()
  iupgrade_wait _iargs
  k_apply "${REPO_ROOT}/demo/specs/computedomain/computedomain.yaml"
  # "CD follows workload": daemons only schedule onto nodes labeled by a
  # workload channel-claim Prepare, so the domain cannot reach Ready until a
  # workload lands (controller/daemonset.py nodeSelector on CD_LABEL_KEY).
  k_apply "${REPO_ROOT}/demo/specs/computedomain/llama-pjit-job.yaml"
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace cd-demo --ignore-not-found --timeout=180s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "failover: force-delete one slice daemon pod, domain recovers" {
  wait_for_cd_status cd-demo v5p-16 Ready
  local daemon
  daemon="$(kubectl -n "${TEST_NAMESPACE}" get pods -o name | grep compute-domain-daemon | head -1)"
  [ -n "$daemon" ]
  kubectl -n "${TEST_NAMESPACE}" delete "$daemon" --force --grace-period=0
  # DS recreates the daemon; it re-registers with its stable index and the
  # domain converges back to Ready.
  wait_for_cd_status cd-demo v5p-16 Ready
}

@test "failover: delete all slice daemons at once, domain recovers" {
  local n
  n="$(kubectl -n "${TEST_NAMESPACE}" get pods \
    -l app.kubernetes.io/name=compute-domain-daemon --no-headers | wc -l)"
  [ "$n" -ge 1 ]
  kubectl -n "${TEST_NAMESPACE}" delete pods \
    -l app.kubernetes.io/name=compute-domain-daemon --force --grace-period=0
  wait_for_cd_status cd-demo v5p-16 Ready
}

@test "failover: workload job survives worker pod deletion" {
  # Re-create the job so the deletion hits a live run (the setup_file job may
  # already be complete by now).
  kubectl -n cd-demo delete job llama-pjit --ignore-not-found --timeout=120s
  # Job deletion cascades its pods ASYNCHRONOUSLY; wait them out so the
  # worker we kill below provably belongs to the NEW run (polling with
  # old pods still dying raced into deleting a ghost / not-found).
  local leftover
  for _ in $(seq 1 60); do
    leftover="$(kubectl -n cd-demo get pods -l job-name=llama-pjit \
      --no-headers 2>/dev/null | wc -l)"
    [ "$leftover" -eq 0 ] && break
    sleep 2
  done
  [ "$leftover" -eq 0 ]
  k_apply "${REPO_ROOT}/demo/specs/computedomain/llama-pjit-job.yaml"
  # Poll for the first worker (a fixed sleep raced the job controller on
  # slow boxes and found zero pods to kill).
  local worker=""
  for _ in $(seq 1 30); do
    worker="$(kubectl -n cd-demo get pods -l job-name=llama-pjit -o name | head -1)"
    [ -n "$worker" ] && break
    sleep 2
  done
  [ -n "$worker" ]
  kubectl -n cd-demo delete "$worker" --force --grace-period=0
  kubectl -n cd-demo wait --for=condition=complete job/llama-pjit --timeout=900s
}

@test "failover: ICI bandwidth exerciser passes after daemon churn" {
  # The nvbandwidth analog (reference test_cd_failover.bats:32-46 payload):
  # after the daemon-churn tests above, the fabric must still move bytes —
  # the exerciser measures psum/all-gather/reduce-scatter/ppermute bus
  # bandwidth across the domain and fails below its threshold.
  # The finished llama job's pods still hold their four-chip claims
  # (template claims release on pod deletion); clean it up first or the
  # exerciser can never allocate the chips.
  kubectl -n cd-demo delete job llama-pjit --ignore-not-found --timeout=120s
  k_apply "${REPO_ROOT}/demo/specs/computedomain/ici-bandwidth-job.yaml"
  kubectl -n cd-demo wait --for=condition=complete job/ici-bandwidth --timeout=600s
  # --tail generous: the jax runtime prints coordination-teardown noise
  # AFTER the result line when the workers exit.
  run kubectl -n cd-demo logs -l job-name=ici-bandwidth --tail=20
  [[ "$output" == *busbw_gbps* ]]
  kubectl -n cd-demo delete job ici-bandwidth --ignore-not-found --timeout=120s
}
