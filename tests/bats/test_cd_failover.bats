# shellcheck disable=SC2148
# Fault injection (reference: test_cd_failover.bats + test_cd_nvb_failover.sh):
# kill slice daemons / workers mid-run, assert the domain and job recover.

setup_file() {
  load 'helpers.sh'
  _common_setup
  local _iargs=()
  iupgrade_wait _iargs
  kubectl apply -f "${REPO_ROOT}/demo/specs/computedomain/computedomain.yaml"
}

setup() {
  load 'helpers.sh'
  _common_setup
}

teardown_file() {
  kubectl delete namespace cd-demo --ignore-not-found --timeout=180s
}

bats::on_failure() {
  log_objects
  show_kubelet_plugin_log_tails
}

@test "failover: force-delete one slice daemon pod, domain recovers" {
  wait_for_cd_status cd-demo v5p-16 Ready
  local daemon
  daemon="$(kubectl -n "${TEST_NAMESPACE}" get pods -o name | grep compute-domain-daemon | head -1)"
  [ -n "$daemon" ]
  kubectl -n "${TEST_NAMESPACE}" delete "$daemon" --force --grace-period=0
  # DS recreates the daemon; it re-registers with its stable index and the
  # domain converges back to Ready.
  wait_for_cd_status cd-demo v5p-16 Ready
}

@test "failover: delete all slice daemons at once, domain recovers" {
  kubectl -n "${TEST_NAMESPACE}" delete pods -l tpu-dra-driver-component=cd-daemon \
    --force --grace-period=0 || true
  wait_for_cd_status cd-demo v5p-16 Ready
}

@test "failover: workload job survives worker pod deletion" {
  kubectl apply -f "${REPO_ROOT}/demo/specs/computedomain/llama-pjit-job.yaml"
  sleep 5
  local worker
  worker="$(kubectl -n cd-demo get pods -l job-name=llama-pjit -o name | head -1)"
  [ -n "$worker" ] && kubectl -n cd-demo delete "$worker" --force --grace-period=0
  kubectl -n cd-demo wait --for=condition=complete job/llama-pjit --timeout=900s
}
