# Shared plumbing for the bats e2e suites (reference: tests/bats/helpers.sh).
# shellcheck shell=bash

: "${TEST_CHART_PATH:=deployments/helm/tpu-dra-driver}"
: "${TEST_NAMESPACE:=tpu-dra-driver}"
: "${TEST_IMAGE_REPO:=registry.local/tpu-dra-driver}"
: "${TEST_IMAGE_TAG:=v0.1.0}"
: "${TEST_STUB_BACKEND:=1}"
: "${TEST_RELEASE:=tpu-dra-driver}"

_common_setup() {
  load "$(dirname "$BATS_TEST_FILENAME")/../bats-helpers/bats-support/load" 2>/dev/null || true
  load "$(dirname "$BATS_TEST_FILENAME")/../bats-helpers/bats-assert/load" 2>/dev/null || true
  REPO_ROOT="$(cd "$(dirname "$BATS_TEST_FILENAME")/../.." && pwd)"
  export REPO_ROOT
}

log() {
  printf '[%s] %s\n' "$(date -u +%H:%M:%S)" "$*" >&3 2>/dev/null || \
    printf '[%s] %s\n' "$(date -u +%H:%M:%S)" "$*"
}

# Install or upgrade the chart and wait for the kubelet-plugin rollout.
# Extra --set pairs come as the name of an array variable (nameref).
iupgrade_wait() {
  local -n _extra_args=${1:-_empty}
  local _empty=()
  local args=(
    upgrade --install "${TEST_RELEASE}" "${REPO_ROOT}/${TEST_CHART_PATH}"
    --create-namespace --namespace "${TEST_NAMESPACE}"
    --set "image.repository=${TEST_IMAGE_REPO}"
    --set "image.tag=${TEST_IMAGE_TAG}"
  )
  if [[ "${TEST_STUB_BACKEND}" == "1" ]]; then
    args+=(
      --set tpulibBackend=stub
      --set stubInventoryPath=/etc/tpu-dra/stub-config.yaml
      --set kubeletPlugin.affinity=null
    )
  fi
  args+=("${_extra_args[@]}")
  helm "${args[@]}"
  # The DaemonSet name derives from the chart name, not the release.
  kubectl -n "${TEST_NAMESPACE}" rollout status \
    ds/tpu-dra-driver-kubelet-plugin --timeout=300s
}

# Apply a spec file, rewriting the resource.k8s.io apiVersion that specs pin
# (v1beta1) to the version the cluster actually serves, as detected by
# setup_suite.bash (reference: setup_suite.bash v1beta1-vs-v1 spec shims).
k_apply() {
  sed "s|resource.k8s.io/v1beta1|${TEST_RESOURCE_API_VERSION:-resource.k8s.io/v1beta1}|g" \
    "$1" | kubectl apply -f -
}

k_delete() {
  sed "s|resource.k8s.io/v1beta1|${TEST_RESOURCE_API_VERSION:-resource.k8s.io/v1beta1}|g" \
    "$1" | kubectl delete --ignore-not-found -f -
}

uninstall_driver() {
  helm uninstall "${TEST_RELEASE}" --namespace "${TEST_NAMESPACE}" || true
  kubectl delete namespace "${TEST_NAMESPACE}" --ignore-not-found --timeout=120s
}

log_objects() {
  log "--- resourceslices ---"
  kubectl get resourceslices -o wide || true
  log "--- resourceclaims (all ns) ---"
  kubectl get resourceclaims -A || true
  log "--- computedomains (all ns) ---"
  kubectl get computedomains -A || true
  log "--- driver pods ---"
  kubectl -n "${TEST_NAMESPACE}" get pods -o wide || true
}

get_node_count() {
  kubectl get nodes --no-headers -l google.com/tpu.present=true | wc -l
}

# Wait until every TPU node has published at least one ResourceSlice for the
# given driver (default tpu.google.com).
wait_for_all_tpu_resource_slices() {
  local driver="${1:-tpu.google.com}"
  local want
  want="$(get_node_count)"
  local have=0
  for _ in $(seq 1 60); do
    have="$(kubectl get resourceslices -o json | \
      jq -r --arg d "$driver" \
        '[.items[] | select(.spec.driver == $d) | .spec.nodeName] | unique | length')"
    [[ "$have" -ge "$want" ]] && return 0
    sleep 2
  done
  log "resource slices: have nodes=$have want=$want"
  return 1
}

# Print "<name> <value>" attribute pairs of the first device in any slice of
# the given driver.
get_device_attrs_from_any_tpu_slice() {
  local driver="${1:-tpu.google.com}"
  # `.basic // .`: v1beta1 wraps device fields in `.basic`; v1beta2/v1 hoist
  # them to the device object itself.
  kubectl get resourceslices -o json | \
    jq -r --arg d "$driver" \
      '([.items[] | select(.spec.driver == $d)][0].spec.devices[0] | .basic // .).attributes
       | to_entries[] | "\(.key) \(.value | to_entries[0].value)"'
}

assert_attr_equal() {
  local attrs="$1" name="$2" want="$3"
  local got
  got="$(echo "$attrs" | awk -v n="$name" '$1 == n {print $2}')"
  [[ "$got" == "$want" ]] || {
    log "attribute $name: got '$got', want '$want'"
    return 1
  }
}

show_kubelet_plugin_log_tails() {
  local pods
  pods="$(kubectl -n "${TEST_NAMESPACE}" get pods \
    -l tpu-dra-driver-component=kubelet-plugin -o name)"
  for p in $pods; do
    for c in tpus compute-domains; do
      log "--- ${p}/${c} (last 30 lines) ---"
      kubectl -n "${TEST_NAMESPACE}" logs "$p" -c "$c" --tail=30 || true
    done
  done
}

get_current_controller_pod_name() {
  kubectl -n "${TEST_NAMESPACE}" get pods \
    -l tpu-dra-driver-component=controller \
    -o jsonpath='{.items[0].metadata.name}'
}

wait_for_cd_status() {
  local ns="$1" name="$2" want="$3"
  for _ in $(seq 1 90); do
    local got
    got="$(kubectl -n "$ns" get computedomain "$name" \
      -o jsonpath='{.status.status}' 2>/dev/null)"
    [[ "$got" == "$want" ]] && return 0
    sleep 2
  done
  return 1
}

restart_kubelet_on_node() {
  # kind nodes are docker containers; real nodes need node-shell/ssh.
  local node="$1"
  docker exec "$node" systemctl restart kubelet 2>/dev/null || \
    log "cannot restart kubelet on $node (not a kind node?)"
}
