"""Paged-KV layer tests (ISSUE 7): the ref-counted page allocator, the
per-page zero-tail invariant, the page-boundary edge cases the satellite
names, and the block-table attention ops' parity contracts —
bit-identity with the contiguous chunked path at matching block size
(what the engine's paged-vs-unpaged oracle relies on) and closeness to
the naive fp32 oracles."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dra.workloads import paged_kv
from tpu_dra.workloads.models.llama import TINY_LLAMA
from tpu_dra.workloads.ops import attention as A
from tpu_dra.workloads.paged_kv import (
    PageAllocator,
    PageExhaustedError,
    SCRATCH_PAGE,
    init_paged_cache,
)
from tpu_dra.workloads.quantize import dequantize_kv, quantize_kv


CFG = dataclasses.replace(
    TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
)


# --- allocator ---------------------------------------------------------------


def test_allocator_basics_and_scratch_reservation():
    a = PageAllocator(6)
    assert a.free_pages == 5  # page 0 is reserved scratch
    pages = [a.alloc() for _ in range(5)]
    assert SCRATCH_PAGE not in pages
    assert sorted(pages) == [1, 2, 3, 4, 5]
    with pytest.raises(PageExhaustedError):
        a.alloc()
    assert a.exhausted == 1
    with pytest.raises(ValueError):
        a.decref(SCRATCH_PAGE)


def test_allocator_refcounted_reuse_after_evict():
    """Satellite: ref-counted page reuse after evict — a page freed by
    one sequence's eviction is handed to the next allocation, and a
    shared (incref'd) page survives one owner's release."""
    a = PageAllocator(4)
    p1, p2 = a.alloc(), a.alloc()
    a.incref(p1)  # a second table now references p1 (prefix sharing)
    assert not a.decref(p1)  # first owner evicts: page must survive
    assert a.refcount(p1) == 1
    assert a.decref(p2)  # sole owner evicts: page freed
    assert a.alloc() == p2  # LIFO: the freed page is reused first
    assert a.decref(p1)  # last reference gone -> freed for real
    assert a.alloc() == p1


def test_allocator_reservation_gates_admission():
    a = PageAllocator(5)  # 4 usable
    assert a.reserve(3)
    assert not a.reserve(2)  # only 1 unreserved page left
    assert a.reserve(1)
    a.unreserve(1)
    a.alloc()
    a.unreserve(1)
    assert a.reserved_pages == 2
    with pytest.raises(ValueError):
        a.unreserve(3)


# --- cache invariants --------------------------------------------------------


def _fill_pages(cache, pages, length, seed=0):
    """Write `length` positions of random K/V (and scales) into the
    given page list, per layer — the engine's write pattern."""
    rng = np.random.default_rng(seed)
    page = cache.page_size
    out = cache
    for pos in range(length):
        pid, off = pages[pos // page], pos % page
        for name, pool in out._pools():
            newpool = []
            for layer in pool:
                val = rng.normal(size=layer.shape[2:]).astype(
                    np.float32
                ) + 1.0  # nonzero
                newpool.append(layer.at[pid, off].set(val.astype(
                    layer.dtype
                )))
            out = dataclasses.replace(out, **{name: tuple(newpool)})
    return out


@pytest.mark.parametrize("kv", ["none", "int8"])
def test_tail_is_zero_per_page(kv):
    """Satellite: zero-tail/tail_is_zero per page — a sequence ending
    exactly at a page boundary has fully-clean later pages; a mid-page
    ending leaves the partial page's tail zero; any poison breaks it."""
    cache = init_paged_cache(CFG, num_pages=5, page_size=4, kv_quant=kv)
    pages = [1, 2, 3]
    # Exactly at a page boundary (length == 2 pages exactly).
    filled = _fill_pages(cache, pages, length=8)
    assert paged_kv.tail_is_zero(filled, pages, 8)
    assert paged_kv.pages_are_zero(filled, [3, 4])
    # Mid-page ending: positions 9..11 of page 3 must be zero.
    filled = _fill_pages(cache, pages, length=9)
    assert paged_kv.tail_is_zero(filled, pages, 9)
    assert not paged_kv.tail_is_zero(filled, pages, 8)  # pos 8 is live
    # Poison the tail -> the check must catch it.
    k0 = filled.k[0].at[3, 2].set(
        jnp.ones_like(filled.k[0][3, 2])
    )
    poisoned = dataclasses.replace(
        filled, k=(k0,) + tuple(filled.k[1:])
    )
    assert not paged_kv.tail_is_zero(poisoned, pages, 9)


def test_zero_pages_restores_invariant():
    """Eviction mid-page: zero_pages over the freed list clears values
    AND scales, so the next owner starts from clean pages."""
    cache = init_paged_cache(
        CFG, num_pages=4, page_size=4, kv_quant="int8"
    )
    filled = _fill_pages(cache, [1, 2], length=6)  # ends mid-page 2
    assert not paged_kv.pages_are_zero(filled, [1, 2])
    wiped = paged_kv.zero_pages(filled, [1, 2])
    assert paged_kv.pages_are_zero(wiped, [1, 2])
    assert paged_kv.tail_is_zero(wiped, [1, 2], 0)


# --- block-table attention ops ----------------------------------------------


def _random_paged(seed, b, num_pages, page, kvh, hd, quant=False):
    """Random pools + disjoint random tables + mixed lengths (one
    exactly at a page boundary — the satellite edge)."""
    key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 4)
    kp = jax.random.normal(ks[0], (num_pages, page, kvh, hd), jnp.float32)
    vp = jax.random.normal(ks[1], (num_pages, page, kvh, hd), jnp.float32)
    max_pages = (num_pages - 1) // b
    perm = np.random.default_rng(seed).permutation(
        np.arange(1, num_pages)
    )
    tables = np.zeros((b, max_pages), np.int32)
    for i in range(b):
        tables[i] = perm[i * max_pages:(i + 1) * max_pages]
    lengths = np.zeros((b,), np.int32)
    caps = max_pages * page
    rng = np.random.default_rng(seed + 1)
    for i in range(b):
        lengths[i] = rng.integers(1, caps + 1)
    lengths[0] = page * max(1, max_pages // 2)  # exact page boundary
    if b > 1:
        lengths[1] = 1
    q = jax.random.normal(ks[2], (b, 2 * kvh, hd), jnp.float32)
    if quant:
        kq, ksc = quantize_kv(kp)
        vq, vsc = quantize_kv(vp)
        return q, kq, vq, ksc, vsc, jnp.asarray(tables), jnp.asarray(lengths)
    return q, kp, vp, None, None, jnp.asarray(tables), jnp.asarray(lengths)


@pytest.mark.parametrize("quant", [False, True])
def test_paged_decode_attention_matches_reference(quant):
    q, kp, vp, ksc, vsc, tables, lengths = _random_paged(
        0, b=3, num_pages=10, page=4, kvh=2, hd=64, quant=quant
    )
    ref = A.reference_paged_decode_attention(
        q, kp, vp, tables, lengths, k_scale=ksc, v_scale=vsc
    )
    got = A.paged_decode_attention(
        q, kp, vp, tables, lengths, k_scale=ksc, v_scale=vsc
    )
    assert float(jnp.max(jnp.abs(got - ref))) < 1e-4


def test_paged_decode_attention_bit_identical_to_contiguous():
    """The engine parity keystone: walking a block table over scattered
    pages must produce BIT-IDENTICAL output to the contiguous chunked
    decode op at block_k == page_size, per sequence."""
    q, kp, vp, _, _, tables, lengths = _random_paged(
        1, b=3, num_pages=13, page=4, kvh=2, hd=64
    )
    got = A.paged_decode_attention(q, kp, vp, tables, lengths)
    for i in range(q.shape[0]):
        # Materialize sequence i's cache contiguously.
        k_seq = kp[tables[i]].reshape(-1, 2, 64)[None]
        v_seq = vp[tables[i]].reshape(-1, 2, 64)[None]
        want = A.decode_attention(
            q[i:i + 1], k_seq, v_seq, lengths[i], impl="xla", block_k=4
        )
        assert jnp.array_equal(got[i], want[0]), f"sequence {i} drifted"


def test_paged_decode_attention_dead_slot_is_zero():
    q, kp, vp, _, _, tables, lengths = _random_paged(
        2, b=3, num_pages=10, page=4, kvh=2, hd=64
    )
    lengths = lengths.at[2].set(0)
    out = A.paged_decode_attention(q, kp, vp, tables, lengths)
    assert float(jnp.max(jnp.abs(out[2]))) == 0.0


@pytest.mark.parametrize("quant", [False, True])
def test_paged_prefill_attention_matches_causal_reference(quant):
    """Chunk queries [pos, pos+s) over the block table == causal
    attention of the q-suffix against the contiguous prefix."""
    page, kvh, hd, pos, s = 4, 2, 64, 6, 5
    key = jax.random.PRNGKey(3)
    ks = jax.random.split(key, 3)
    total = pos + s
    k_all = jax.random.normal(ks[0], (1, total, kvh, hd), jnp.float32)
    v_all = jax.random.normal(ks[1], (1, total, kvh, hd), jnp.float32)
    q = jax.random.normal(ks[2], (s, 2 * kvh, hd), jnp.float32)
    num_pages = -(-total // page) + 2
    table = np.array([2, 1, 3], np.int32)  # scattered on purpose
    kp = jnp.zeros((num_pages, page, kvh, hd), jnp.float32)
    vp = jnp.zeros((num_pages, page, kvh, hd), jnp.float32)
    ksc = vsc = None
    if quant:
        k8, k8s = quantize_kv(k_all)
        v8, v8s = quantize_kv(v_all)
        k_all = dequantize_kv(k8, k8s)
        v_all = dequantize_kv(v8, v8s)
        kp8 = jnp.zeros((num_pages, page, kvh, hd), jnp.int8)
        vp8 = jnp.zeros((num_pages, page, kvh, hd), jnp.int8)
        kscp = jnp.zeros((num_pages, page, kvh), jnp.float32)
        vscp = jnp.zeros((num_pages, page, kvh), jnp.float32)
        for p in range(total):
            pid, off = table[p // page], p % page
            kp8 = kp8.at[pid, off].set(k8[0, p])
            vp8 = vp8.at[pid, off].set(v8[0, p])
            kscp = kscp.at[pid, off].set(k8s[0, p])
            vscp = vscp.at[pid, off].set(v8s[0, p])
        kp, vp, ksc, vsc = kp8, vp8, kscp, vscp
    else:
        for p in range(total):
            pid, off = table[p // page], p % page
            kp = kp.at[pid, off].set(k_all[0, p])
            vp = vp.at[pid, off].set(v_all[0, p])
    got = A.paged_prefill_attention(
        q, kp, vp, jnp.asarray(table), jnp.int32(pos),
        k_scale=ksc, v_scale=vsc,
    )
    want = A.reference_attention(q[None], k_all, v_all, causal=True)[0]
    assert float(jnp.max(jnp.abs(got - want))) < 1e-4


def test_paged_decode_attention_validates_shapes():
    q = jnp.zeros((2, 4, 64))
    kp = jnp.zeros((5, 4, 2, 64))
    tables = jnp.zeros((2, 2), jnp.int32)
    lengths = jnp.zeros((2,), jnp.int32)
    with pytest.raises(ValueError, match="k_scale and v_scale"):
        A.paged_decode_attention(
            q, kp, kp, tables, lengths, k_scale=jnp.zeros((5, 4, 2))
        )
    with pytest.raises(ValueError, match="do not match batch"):
        A.paged_decode_attention(
            q, kp, kp, tables[:1], lengths
        )
    with pytest.raises(ValueError, match="multiple of kv heads"):
        A.paged_decode_attention(
            jnp.zeros((2, 3, 64)), kp, kp, tables, lengths
        )
    with pytest.raises(ValueError, match="unknown paged"):
        A.paged_decode_attention(
            q, kp, kp, tables, lengths, impl="bogus"
        )


# --- pallas paged-decode kernel (scalar-prefetched block table) --------------
#
# The ISSUE 8 kernel runs only on TPU in production; attention._INTERPRET
# executes the same pallas program on CPU, so its parity contract — the
# SAME online-softmax block update as the XLA gather path, hence
# BIT-IDENTICAL output — is pinned in CI without hardware.


@pytest.fixture()
def interpret_mode(monkeypatch):
    monkeypatch.setattr(A, "_INTERPRET", True)
    yield


@pytest.mark.parametrize("quant", [False, True])
def test_pallas_paged_decode_matches_reference(interpret_mode, quant):
    q, kp, vp, ksc, vsc, tables, lengths = _random_paged(
        4, b=3, num_pages=10, page=4, kvh=2, hd=64, quant=quant
    )
    ref = A.reference_paged_decode_attention(
        q, kp, vp, tables, lengths, k_scale=ksc, v_scale=vsc
    )
    got = A.paged_decode_attention(
        q, kp, vp, tables, lengths, k_scale=ksc, v_scale=vsc,
        impl="pallas",
    )
    assert A._LAST_PAGED_IMPL == "pallas"
    assert float(jnp.max(jnp.abs(got - ref))) < 1e-4


# Kernel-vs-gather-path tolerance: the kernel runs the SAME online-
# softmax block update as _xla_paged_decode_attention, but interpret
# mode and the fori_loop path compile to different XLA graphs, and the
# backend's fusion choices (FMA contraction, vectorized-exp remainder
# lanes) produce data-dependent 1-ulp differences. The parity pinned
# here is ulp-level; the BIT-level oracle chain stays
# xla-gather == contiguous decode_attention at block_k == page_size
# (test_paged_decode_attention_bit_identical_to_contiguous above),
# which is what the engine's token-parity contract rests on.
_KERNEL_ULP_TOL = 2e-6


@pytest.mark.parametrize("quant", [False, True])
def test_pallas_paged_decode_matches_xla_gather_path(interpret_mode, quant):
    """Same block update as the parity oracle — ulp-level agreement
    including the int8 in-flight dequant, page-boundary lengths and a
    1-length slot (_random_paged pins both)."""
    q, kp, vp, ksc, vsc, tables, lengths = _random_paged(
        5, b=3, num_pages=13, page=4, kvh=2, hd=64, quant=quant
    )
    xla = A.paged_decode_attention(
        q, kp, vp, tables, lengths, k_scale=ksc, v_scale=vsc, impl="xla"
    )
    pallas = A.paged_decode_attention(
        q, kp, vp, tables, lengths, k_scale=ksc, v_scale=vsc,
        impl="pallas",
    )
    assert float(jnp.max(jnp.abs(xla - pallas))) <= _KERNEL_ULP_TOL


def test_pallas_paged_decode_dead_slot_and_zero_length(interpret_mode):
    """length 0 contributes exactly zero (the all-masked m = NEG_INF
    corner the naive path gets wrong), and the kernel's clamped index
    map tolerates a table whose dead entries point anywhere."""
    q, kp, vp, _, _, tables, lengths = _random_paged(
        6, b=3, num_pages=10, page=4, kvh=2, hd=64
    )
    lengths = lengths.at[2].set(0)
    out = A.paged_decode_attention(q, kp, vp, tables, lengths, impl="pallas")
    assert float(jnp.max(jnp.abs(out[2]))) == 0.0
    want = A.paged_decode_attention(q, kp, vp, tables, lengths, impl="xla")
    assert float(jnp.max(jnp.abs(out - want))) <= _KERNEL_ULP_TOL


def test_pallas_paged_decode_page_boundary_lengths(interpret_mode):
    """Lengths exactly at page boundaries (the off-by-one corner of the
    num_visible bound) — including the full-capacity table — agree with
    the oracle. (_random_paged at num_pages=10/b=3 gives 3-entry tables:
    capacity 12.)"""
    q, kp, vp, _, _, tables, lengths = _random_paged(
        7, b=3, num_pages=10, page=4, kvh=2, hd=64
    )
    for boundary in (4, 8, 12):
        ln = jnp.asarray([boundary, boundary, boundary], jnp.int32)
        pallas = A.paged_decode_attention(q, kp, vp, tables, ln, impl="pallas")
        xla = A.paged_decode_attention(q, kp, vp, tables, ln, impl="xla")
        assert float(jnp.max(jnp.abs(pallas - xla))) <= _KERNEL_ULP_TOL, (
            f"boundary {boundary}"
        )


def test_paged_dispatch_auto_prefers_pallas_on_platform(
    interpret_mode, monkeypatch
):
    """auto -> pallas wherever the platform allows (interpret mode
    stands in for TPU), auto -> xla otherwise; the probe records the
    decision at trace time."""
    q, kp, vp, _, _, tables, lengths = _random_paged(
        8, b=2, num_pages=8, page=4, kvh=2, hd=64
    )
    A._LAST_PAGED_IMPL = None
    A.paged_decode_attention(q, kp, vp, tables, lengths)
    assert A._LAST_PAGED_IMPL == "pallas"
    monkeypatch.setattr(A, "_INTERPRET", False)
    A._LAST_PAGED_IMPL = None
    A.paged_decode_attention(q, kp, vp, tables, lengths)
    assert A._LAST_PAGED_IMPL == "xla"


# --- COW page forks + rewind primitives (ISSUE 15) ---------------------------


@pytest.mark.parametrize("kv", ["none", "int8"])
def test_copy_page_forks_all_pools_including_scales(kv):
    """Satellite: int8 scale pools are forked WITH their pages — a COW
    copy carries values and scales for every layer, so a forked int8
    sequence dequantizes identically to its parent."""
    cache = init_paged_cache(CFG, num_pages=5, page_size=4, kv_quant=kv)
    filled = _fill_pages(cache, [1], length=3)
    forked = paged_kv.copy_page(filled, src=1, dst=2)
    for _, pool in forked._pools():
        for layer in pool:
            assert jnp.array_equal(layer[2], layer[1])
    # The source is untouched and other pages stay zero.
    assert paged_kv.pages_are_zero(forked, [3, 4])


def test_copy_page_prefix_freezes_zero_tail():
    """The frozen-boundary fork: only [0, upto) copies; the tail of the
    destination is ZERO even when the source page carries the
    registrant's own tokens past the prefix (the zero-tail invariant
    every sharer forks from)."""
    cache = init_paged_cache(
        CFG, num_pages=5, page_size=4, kv_quant="int8"
    )
    filled = _fill_pages(cache, [1], length=4)  # source fully written
    frozen = paged_kv.copy_page_prefix(filled, src=1, dst=2, upto=2)
    for _, pool in frozen._pools():
        for layer in pool:
            assert jnp.array_equal(layer[2][:2], layer[1][:2])
            assert float(
                jnp.sum(jnp.abs(layer[2][2:].astype(jnp.float32)))
            ) == 0.0
    assert paged_kv.tail_is_zero(frozen, [2], 2)


def test_zero_page_tail_rewinds_in_place():
    """Speculative rewind: positions >= start of one page are wiped in
    every pool; earlier positions survive byte-for-byte."""
    cache = init_paged_cache(
        CFG, num_pages=4, page_size=4, kv_quant="int8"
    )
    filled = _fill_pages(cache, [1], length=4)
    wiped = paged_kv.zero_page_tail(filled, 1, start=1)
    for (name, pool), (_, opool) in zip(wiped._pools(), filled._pools()):
        for layer, orig in zip(pool, opool):
            assert jnp.array_equal(layer[1][:1], orig[1][:1]), name
            assert float(
                jnp.sum(jnp.abs(layer[1][1:].astype(jnp.float32)))
            ) == 0.0, name
    assert paged_kv.tail_is_zero(wiped, [1], 1)


def test_allocator_shared_extra_and_min_free():
    a = PageAllocator(6)
    assert a.shared_extra() == 0
    p1, p2 = a.alloc(), a.alloc()
    a.incref(p1)
    a.incref(p1)
    assert a.shared_extra() == 2  # one page standing in for 3 copies
    assert a.min_free == 3
    a.decref(p1)
    assert a.shared_extra() == 1
    a.decref(p2)
    a.alloc()
    assert a.min_free == 3  # low-water survives the free


def test_allocator_shared_extra_discounts_registry_pins():
    """A reference held by a cache/registry stands in for no
    allocation: a registered-but-never-shared page reports 0 saved;
    savings count only the effective (sequence-held) refcount."""
    a = PageAllocator(6)
    p1, p2 = a.alloc(), a.alloc()
    a.incref(p1)  # registry pin: registrant 1 + registry 1
    assert a.shared_extra() == 1
    assert a.shared_extra(discount={p1: 1}) == 0
    a.incref(p1)  # one real sharer
    assert a.shared_extra(discount={p1: 1}) == 1
    # p2 registry-only (frozen boundary page, no sequence holder yet).
    assert a.shared_extra(discount={p1: 1, p2: 1}) == 1
    a.incref(p2)
    a.incref(p2)  # two sharers fork off the frozen page
    assert a.shared_extra(discount={p1: 1, p2: 1}) == 2


# --- multiquery (verify / batched-prefill) op (ISSUE 15) ---------------------


@pytest.mark.parametrize("quant", [False, True])
def test_paged_multiquery_matches_reference(quant):
    q1, kp, vp, ksc, vsc, tables, lengths = _random_paged(
        10, b=3, num_pages=13, page=4, kvh=2, hd=64, quant=quant
    )
    s = 3
    key = jax.random.PRNGKey(21)
    q = jax.random.normal(key, (3, s, 4, 64), jnp.float32)
    # Chunk starts: the queries sit at [pos, pos+s) — keep them inside
    # each sequence's table capacity.
    pos = jnp.asarray([0, 2, 5], jnp.int32)
    ref = A.reference_paged_multiquery_attention(
        q, kp, vp, tables, pos, k_scale=ksc, v_scale=vsc
    )
    got = A.paged_multiquery_attention(
        q, kp, vp, tables, pos, k_scale=ksc, v_scale=vsc
    )
    assert float(jnp.max(jnp.abs(got - ref))) < 1e-4


def test_paged_multiquery_row_matches_single_sequence_prefill_op():
    """A batch row of the multiquery op runs the SAME online-softmax
    block walk as the single-sequence chunked-prefill op — appended
    fully-masked blocks (another row's longer frontier) contribute
    exactly zero, so rows are independent of their batchmates. This is
    the op-level half of the batched-prefill token-parity contract."""
    _, kp, vp, _, _, tables, _ = _random_paged(
        11, b=3, num_pages=13, page=4, kvh=2, hd=64
    )
    s = 3
    q = jax.random.normal(jax.random.PRNGKey(5), (3, s, 4, 64), jnp.float32)
    pos = jnp.asarray([1, 4, 7], jnp.int32)
    batched = A.paged_multiquery_attention(q, kp, vp, tables, pos)
    for i in range(3):
        single = A.paged_prefill_attention(
            q[i], kp, vp, tables[i], pos[i]
        )
        assert float(
            jnp.max(jnp.abs(batched[i] - single))
        ) <= 2e-6, f"row {i} diverged from the single-sequence op"


def test_paged_multiquery_validates_shapes():
    q = jnp.zeros((2, 3, 4, 64))
    kp = jnp.zeros((5, 4, 2, 64))
    tables = jnp.zeros((2, 2), jnp.int32)
    pos = jnp.zeros((2,), jnp.int32)
    with pytest.raises(ValueError, match="k_scale and v_scale"):
        A.paged_multiquery_attention(
            q, kp, kp, tables, pos, k_scale=jnp.zeros((5, 4, 2))
        )
    with pytest.raises(ValueError, match="do not match batch"):
        A.paged_multiquery_attention(q, kp, kp, tables[:1], pos)
    with pytest.raises(ValueError, match="unknown paged multiquery"):
        A.paged_multiquery_attention(q, kp, kp, tables, pos, impl="bogus")


def test_paged_dispatch_auto_falls_back_on_bad_head_dim(interpret_mode):
    """hd not a lane multiple -> the kernel is ineligible and auto
    quietly takes the gather path instead of tripping mosaic."""
    b, num_pages, page, kvh, hd = 2, 6, 4, 2, 48
    key = jax.random.PRNGKey(9)
    ks = jax.random.split(key, 3)
    kp = jax.random.normal(ks[0], (num_pages, page, kvh, hd), jnp.float32)
    vp = jax.random.normal(ks[1], (num_pages, page, kvh, hd), jnp.float32)
    q = jax.random.normal(ks[2], (b, 2 * kvh, hd), jnp.float32)
    tables = jnp.tile(jnp.arange(2, dtype=jnp.int32)[None], (b, 1))
    lengths = jnp.asarray([3, 7], jnp.int32)
    A._LAST_PAGED_IMPL = None
    out = A.paged_decode_attention(q, kp, vp, tables, lengths)
    assert A._LAST_PAGED_IMPL == "xla"
    ref = A.reference_paged_decode_attention(q, kp, vp, tables, lengths)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-4


# --- KV extents: serialize -> graft round-trip (ISSUE 17) --------------------


def _pages_equal(src_cache, src_pages, dst_cache, dst_pages):
    """Bit-equality of the listed pages across every pool and layer,
    src_pages[i] compared against dst_pages[i]."""
    sids = jnp.asarray(list(src_pages), jnp.int32)
    dids = jnp.asarray(list(dst_pages), jnp.int32)
    for (_, spool), (_, dpool) in zip(
        src_cache._pools(), dst_cache._pools()
    ):
        for sl, dl in zip(spool, dpool):
            if not np.array_equal(np.asarray(sl[sids]), np.asarray(dl[dids])):
                return False
    return True


@pytest.mark.parametrize(
    "kv,dtype",
    [
        ("none", jnp.float32),
        ("none", jnp.bfloat16),
        ("int8", jnp.float32),
    ],
)
def test_kv_extent_roundtrip_property(kv, dtype):
    """Satellite: randomized serialize->graft round-trips — random page
    counts and lengths (including exactly-at-page-boundary), bf16 and
    int8 scale pools. The grafted copy is bit-identical, keeps the
    zero-tail invariant, and both allocators' ledgers balance: the
    destination debits exactly n pages, the source releases exactly
    once (a second release is a ledger violation the allocator rejects)."""
    rng = np.random.default_rng(17)
    cfg = dataclasses.replace(TINY_LLAMA, dtype=dtype, param_dtype=jnp.float32)
    page = 4
    for trial in range(6):
        n_pages = int(rng.integers(2, 5))
        length = int(
            rng.integers((n_pages - 1) * page + 1, n_pages * page + 1)
        )
        if trial == 0:
            length = n_pages * page  # ends exactly at a page boundary
        src = init_paged_cache(cfg, num_pages=8, page_size=page, kv_quant=kv)
        sa = PageAllocator(8)
        pages = [sa.alloc() for _ in range(n_pages)]
        src = _fill_pages(src, pages, length, seed=trial)
        ext = paged_kv.serialize_extent(src, pages, length)
        assert ext.n_payload_pages == n_pages and ext.n_shared_pages == 0
        assert ext.length == length and ext.nbytes > 0

        dst = init_paged_cache(cfg, num_pages=8, page_size=page, kv_quant=kv)
        da = PageAllocator(8)
        free_before = da.free_pages
        dst, dpages = paged_kv.graft_extent(dst, da, ext)
        assert len(dpages) == n_pages
        assert da.free_pages == free_before - n_pages
        assert all(da.refcount(p) == 1 for p in dpages)
        assert _pages_equal(src, pages, dst, dpages)
        assert paged_kv.tail_is_zero(dst, dpages, length)

        for p in pages:
            assert sa.decref(p)  # freed on the first release...
        assert sa.free_pages == 8 - 1  # ...and the ledger is whole
        with pytest.raises(ValueError):
            sa.decref(pages[0])  # a double release never passes silently


@pytest.mark.parametrize("kv", ["none", "int8"])
def test_kv_extent_shared_prefix_carried_by_id(kv):
    """A refcount>1 shared-prefix page travels by id: the graft increfs
    it instead of copying, so only the non-shared tail costs a page."""
    cache = init_paged_cache(CFG, num_pages=6, page_size=4, kv_quant=kv)
    a = PageAllocator(6)
    prefix, tail = a.alloc(), a.alloc()
    a.incref(prefix)  # a registered shared prefix: refcount 2
    cache = _fill_pages(cache, [prefix, tail], length=6)
    ext = paged_kv.serialize_extent(cache, [prefix, tail], 6, by_id=[prefix])
    assert ext.n_shared_pages == 1 and ext.n_payload_pages == 1
    rc, free_before = a.refcount(prefix), a.free_pages
    cache2, pages = paged_kv.graft_extent(cache, a, ext)
    assert pages[0] == prefix  # carried by reference, never copied
    assert a.refcount(prefix) == rc + 1
    assert a.free_pages == free_before - 1  # only the tail page allocs
    assert _pages_equal(cache, [tail], cache2, [pages[1]])
    assert paged_kv.tail_is_zero(cache2, pages, 6)


@pytest.mark.parametrize("kv", ["none", "int8"])
def test_kv_extent_attach_increfs_destination_page(kv):
    """``attach`` maps a slot to a destination page the importer already
    holds equivalent content for (a registered prefix): that slot increfs
    the local page and skips both the alloc and the scatter."""
    src = init_paged_cache(CFG, num_pages=6, page_size=4, kv_quant=kv)
    sa = PageAllocator(6)
    spages = [sa.alloc(), sa.alloc()]
    src = _fill_pages(src, spages, length=8, seed=3)
    ext = paged_kv.serialize_extent(src, spages, 8)

    dst = init_paged_cache(CFG, num_pages=6, page_size=4, kv_quant=kv)
    da = PageAllocator(6)
    held = da.alloc()
    # Same rng draw order as the first page of the source fill -> the
    # held page's content is identical to slot 0's payload.
    dst = _fill_pages(dst, [held], length=4, seed=3)
    free_before = da.free_pages
    dst2, pages = paged_kv.graft_extent(dst, da, ext, attach={0: held})
    assert pages[0] == held and da.refcount(held) == 2
    assert da.free_pages == free_before - 1  # only slot 1 allocated
    assert _pages_equal(src, spages, dst2, pages)
    assert paged_kv.tail_is_zero(dst2, pages, 8)


@pytest.mark.parametrize("kv", ["none", "int8"])
def test_kv_extent_graft_failure_releases_everything(kv):
    """Exhaustion mid-graft (shared page increfed, first payload page
    allocated, second alloc raises) rolls everything back: no page stays
    allocated, no refcount stays raised."""
    cache = init_paged_cache(CFG, num_pages=6, page_size=4, kv_quant=kv)
    a = PageAllocator(6)
    spages = [a.alloc() for _ in range(3)]
    a.alloc()  # pin one page: exactly one free page remains
    cache = _fill_pages(cache, spages, length=12, seed=5)
    ext = paged_kv.serialize_extent(cache, spages, 12, by_id=[spages[0]])
    assert ext.n_payload_pages == 2
    rc, free_before = a.refcount(spages[0]), a.free_pages
    assert free_before == 1
    with pytest.raises(PageExhaustedError):
        paged_kv.graft_extent(cache, a, ext)
    assert a.refcount(spages[0]) == rc
    assert a.free_pages == free_before


def test_kv_extent_validates_shape_and_mode():
    """serialize refuses a length the page list can't cover; graft
    refuses page-size and kv-quantization mismatches."""
    cache = init_paged_cache(CFG, num_pages=4, page_size=4, kv_quant="none")
    with pytest.raises(ValueError, match="exceeds"):
        paged_kv.serialize_extent(cache, [1], 5)
    a = PageAllocator(4)
    p = a.alloc()
    filled = _fill_pages(cache, [p], 3)
    ext = paged_kv.serialize_extent(filled, [p], 3)
    qcache = init_paged_cache(CFG, num_pages=4, page_size=4, kv_quant="int8")
    with pytest.raises(ValueError, match="quantization"):
        paged_kv.graft_extent(qcache, PageAllocator(4), ext)
    wide = init_paged_cache(CFG, num_pages=4, page_size=8, kv_quant="none")
    with pytest.raises(ValueError, match="page_size"):
        paged_kv.graft_extent(wide, PageAllocator(4), ext)
