"""Control-plane weather tests (ISSUE 5): deadline budgets, the per-verb
circuit breaker, degraded-mode operation, and the apiserver-partition
soak.

Layers under test, bottom up:

- :mod:`tpu_dra.infra.deadline` — the Go-context-style ``Budget``
  (deadline + stop event, thread-local activation, budget-capped
  sleeps);
- :mod:`tpu_dra.k8sclient.circuit` — the closed/open/half-open state
  machine, probed with a fake clock;
- :mod:`tpu_dra.k8sclient.rest` — the transport integration: failures
  trip the breaker, waits consume the caller's budget, reads can serve
  from an informer cache while the circuit is open;
- the plugin: budget expiry mid-Prepare is retriable and converges via
  the PR-4 WAL; the driver pauses GC/publish while degraded, keeps
  serving prepare/unprepare from checkpoint state, and runs the fenced
  resync on heal;
- the acceptance soak (`make apisoak`): under an ``api_partition``
  window no kubelet RPC blocks past its budget, and after the heal the
  stack reconverges (circuit closed, checkpoint == apiserver) within
  the recovery bound. The smoke runs in tier-1; the seeded weather
  matrix is ``slow``-marked.
"""

import tempfile
import threading
import time

import pytest

from tests.helpers import make_claim
from tpu_dra.infra import deadline
from tpu_dra.infra import featuregates as fg
from tpu_dra.infra.chaos import (
    API_LATENCY,
    API_PARTITION,
    APISERVER_ERRORS,
    APISERVER_THROTTLE,
    WATCH_DROP,
    ChaosEngine,
    FaultSchedule,
)
from tpu_dra.infra.deadline import Budget, BudgetCancelled, BudgetExceeded
from tpu_dra.infra.flock import Flock
from tpu_dra.infra.metrics import Metrics
from tpu_dra.k8sclient import (
    DEPLOYMENTS,
    RESOURCE_CLAIMS,
    FakeCluster,
    Informer,
    ResourceClient,
    install_read_fallback,
)
from tpu_dra.k8sclient.circuit import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    CircuitOpenError,
)
from tpu_dra.k8sclient.degraded import DegradedModeController
from tpu_dra.k8sclient.fakeserver import FakeApiServer
from tpu_dra.k8sclient.resources import COMPUTE_DOMAINS
from tpu_dra.k8sclient.rest import KubeClient
from tpu_dra.plugin.checkpoint import (
    CLAIM_STATE_PREPARE_COMPLETED,
    CLAIM_STATE_PREPARE_STARTED,
)
from tpu_dra.plugin.device_state import DRIVER_NAME
from tpu_dra.plugin.driver import Driver, DriverConfig
from tpu_dra.plugin.pb import dra_v1beta1_pb2 as drapb
from tpu_dra.tpulib.stub import StubTpuLib


def counter(metrics, name, **labels):
    return metrics._counters.get(metrics._key(name, labels or None), 0.0)


def gauge(metrics, name, **labels):
    return metrics._gauges.get(metrics._key(name, labels or None))


def gates(**kwargs):
    g = fg.FeatureGates()
    for k, v in kwargs.items():
        g.set(k, v)
    fg.reset_for_tests(g)


def wait_for(predicate, timeout=10.0, poll=0.02, msg=""):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if predicate():
            return
        time.sleep(poll)
    assert predicate(), msg or "condition did not converge"


# --- Budget ------------------------------------------------------------------


def test_budget_remaining_expiry_and_check():
    b = Budget(0.05, name="rpc")
    assert 0 < b.remaining() <= 0.05
    assert not b.expired()
    b.check("fetching claim")  # inside budget: no raise
    time.sleep(0.06)
    assert b.expired() and b.remaining() == 0.0
    with pytest.raises(BudgetExceeded) as ei:
        b.check("fetching claim")
    assert "rpc fetching claim" in str(ei.value)
    # Typed retriable: a TimeoutError, NOT wrapped as permanent.
    assert isinstance(ei.value, TimeoutError)
    assert ei.value.retriable is True


def test_budget_unbounded_only_ends_on_stop():
    b = Budget()
    assert b.remaining() is None and not b.expired()
    b.check()
    b.stop.set()
    with pytest.raises(BudgetCancelled):
        b.check()
    # BudgetCancelled IS a BudgetExceeded: one except path for callers.
    assert issubclass(BudgetCancelled, BudgetExceeded)


def test_budget_sleep_refuses_uncoverable_wait():
    b = Budget(0.05)
    t0 = time.monotonic()
    with pytest.raises(BudgetExceeded):
        b.sleep(10.0, "retrying apiserver get")
    # The refusal is immediate — it must NOT sleep out the budget tail.
    assert time.monotonic() - t0 < 0.05


def test_budget_sleep_cancelled_by_stop_event():
    b = Budget(5.0)
    threading.Timer(0.05, b.stop.set).start()
    t0 = time.monotonic()
    with pytest.raises(BudgetCancelled):
        b.sleep(2.0)
    assert time.monotonic() - t0 < 1.0


def test_budget_pause_clamps_and_never_raises():
    b = Budget(0.05)
    t0 = time.monotonic()
    b.pause(5.0)  # clamped to the remaining budget
    assert time.monotonic() - t0 < 1.0
    b.pause(0.01)  # expired: returns immediately, still no raise


def test_budget_child_takes_min_deadline_and_shares_stop():
    parent = Budget(0.05)
    child = parent.child(timeout=10.0)
    assert child.deadline() == parent.deadline()  # cannot extend
    tighter = parent.child(timeout=0.01)
    assert tighter.deadline() < parent.deadline()  # may tighten
    assert child.stop is parent.stop
    unbounded_child = Budget(0.05).child()
    assert unbounded_child.deadline() is not None  # inherits, not None


def test_budget_active_is_thread_local():
    b = Budget(5.0, name="mine")
    seen = {}
    with b.active():
        assert deadline.current() is b

        def other():
            seen["other"] = deadline.current()

        t = threading.Thread(target=other)
        t.start()
        t.join()
    assert seen["other"] is deadline.UNLIMITED
    assert deadline.current() is deadline.UNLIMITED  # restored on exit


def test_flock_acquire_consumes_ambient_budget(tmp_path):
    lock = Flock(str(tmp_path / "pu.lock"))
    release = lock.acquire(timeout=5)
    try:
        with Budget(0.1).active():
            t0 = time.monotonic()
            with pytest.raises(BudgetExceeded):
                lock.acquire(timeout=60, poll_period=0.01)
            assert time.monotonic() - t0 < 2.0
    finally:
        release()
    # Uncontended acquire under a live budget still works.
    with Budget(5.0).active():
        lock.acquire(timeout=5)()


# --- circuit breaker state machine -------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def make_breaker(**kw):
    clock = FakeClock()
    kw.setdefault("failure_threshold", 3)
    kw.setdefault("cooldown_seconds", 5.0)
    return CircuitBreaker(clock=clock, **kw), clock


def test_circuit_trips_after_consecutive_failures():
    cb, _ = make_breaker()
    for _ in range(2):
        cb.record_failure("get")
    assert cb.state("get") == CLOSED  # below threshold
    cb.record_success("get")  # success resets the streak
    for _ in range(2):
        cb.record_failure("get")
    assert cb.state("get") == CLOSED
    cb.record_failure("get")
    assert cb.state("get") == OPEN
    # Verbs are independent: "create" never failed.
    assert cb.state("create") == CLOSED
    cb.check("create")


def test_open_circuit_refuses_with_retry_after():
    cb, clock = make_breaker()
    for _ in range(3):
        cb.record_failure("get")
    clock.t = 2.0
    with pytest.raises(CircuitOpenError) as ei:
        cb.check("get")
    assert ei.value.verb == "get"
    assert ei.value.retriable is True
    assert 2.9 < ei.value.retry_after <= 3.0  # 5s cooldown - 2s elapsed
    assert ei.value.status == 503


def test_half_open_admits_exactly_one_probe():
    cb, clock = make_breaker()
    for _ in range(3):
        cb.record_failure("get")
    clock.t = 6.0  # past the cooldown
    cb.check("get")  # the probe is admitted
    assert cb.state("get") == HALF_OPEN
    with pytest.raises(CircuitOpenError):
        cb.check("get")  # concurrent caller refused until the probe lands
    cb.record_success("get")
    assert cb.state("get") == CLOSED
    cb.check("get")  # closed again: flows freely


def test_half_open_probe_failure_reopens():
    cb, clock = make_breaker()
    for _ in range(3):
        cb.record_failure("get")
    clock.t = 6.0
    cb.check("get")
    cb.record_failure("get")  # the probe itself failed
    assert cb.state("get") == OPEN
    with pytest.raises(CircuitOpenError):
        cb.check("get")  # a fresh cooldown started at t=6
    clock.t = 12.0
    cb.check("get")
    cb.record_success("get")
    assert cb.state("get") == CLOSED


def test_circuit_listener_fires_on_transitions():
    cb, clock = make_breaker()
    edges = []
    cb.add_listener(lambda verb, old, new: edges.append((verb, old, new)))
    for _ in range(3):
        cb.record_failure("get")
    clock.t = 6.0
    cb.check("get")
    cb.record_success("get")
    assert edges == [
        ("get", CLOSED, OPEN),
        ("get", OPEN, HALF_OPEN),
        ("get", HALF_OPEN, CLOSED),
    ]


def test_circuit_metrics_gauge_and_transition_counters():
    metrics = Metrics()
    clock = FakeClock()
    cb = CircuitBreaker(
        failure_threshold=2, cooldown_seconds=5.0, metrics=metrics,
        clock=clock,
    )
    # Construction exports a closed gauge for every known verb.
    assert gauge(metrics, "api_circuit_state", verb="get") == 0
    cb.record_failure("get")
    cb.record_failure("get")
    assert gauge(metrics, "api_circuit_state", verb="get") == 2
    assert counter(
        metrics, "api_circuit_transitions_total", verb="get", to=OPEN
    ) == 1
    clock.t = 6.0
    cb.check("get")
    assert gauge(metrics, "api_circuit_state", verb="get") == 1
    cb.record_success("get")
    assert gauge(metrics, "api_circuit_state", verb="get") == 0
    assert counter(
        metrics, "api_circuit_transitions_total", verb="get", to=CLOSED
    ) == 1


def test_any_open_and_reset():
    cb, clock = make_breaker(failure_threshold=1)
    assert not cb.any_open()
    cb.record_failure("list")
    assert cb.any_open()
    # Half-open still counts: not known-good until the probe lands.
    clock.t = 6.0
    cb.check("list")
    assert cb.state("list") == HALF_OPEN and cb.any_open()
    cb.reset()
    assert not cb.any_open() and cb.state("list") == CLOSED
    assert cb.states()["list"] == CLOSED


# --- transport integration (rest.KubeClient vs the fake apiserver) -----------


def make_client(srv, metrics=None, threshold=2, cooldown=0.25, timeout=0.3):
    return KubeClient(
        srv.server_url,
        qps=10_000, burst=10_000,
        metrics=metrics,
        circuit=CircuitBreaker(
            failure_threshold=threshold, cooldown_seconds=cooldown,
            metrics=metrics,
        ),
        request_timeouts={v: timeout for v in (
            "get", "list", "create", "update", "patch", "delete", "watch",
        )},
    )


@pytest.fixture
def srv():
    server = FakeApiServer().start()
    yield server
    server.stop()


def seed_cd(cluster, name="cd-0"):
    return ResourceClient(cluster, COMPUTE_DOMAINS).create({
        "apiVersion": "resource.tpu.google.com/v1beta1",
        "kind": "ComputeDomain",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"numNodes": 1},
    })


def test_rest_5xx_trip_circuit_then_fail_fast(srv):
    metrics = Metrics()
    kc = make_client(srv, metrics=metrics)
    obj = seed_cd(srv.cluster)
    cds = ResourceClient(kc, COMPUTE_DOMAINS)
    assert cds.get("cd-0", "default")["metadata"]["uid"] == (
        obj["metadata"]["uid"]
    )
    # A long 5xx burst exhausts the transport's own retries AND trips
    # the breaker (threshold 2) along the way.
    srv.inject_faults(fail=8, fail_status=503)
    with pytest.raises(Exception):
        cds.get("cd-0", "default")
    assert kc.circuit.state("get") == OPEN
    # While open: refused locally, fast, with the circuit_open metric.
    t0 = time.monotonic()
    with pytest.raises(CircuitOpenError):
        cds.get("cd-0", "default")
    assert time.monotonic() - t0 < 0.1
    assert counter(
        metrics, "api_requests_total", verb="get", code="circuit_open"
    ) >= 1
    assert counter(metrics, "api_requests_total", verb="get", code="503") >= 1
    # After the cooldown the half-open probe goes through and closes it
    # (the burst count is long gone by now... drain whatever remains).
    wait_for(
        lambda: _probe_until_closed(cds, kc), timeout=10,
        msg="circuit never closed after the burst drained",
    )
    assert kc.circuit.state("get") == CLOSED


def _probe_until_closed(cds, kc):
    try:
        cds.get("cd-0", "default")
    except Exception:
        return False
    return kc.circuit.state("get") == CLOSED


def test_slow_answering_apiserver_cannot_outlive_budget(srv):
    """The answered-slowly regime (api_latency weather under the wire
    timeout) never fires a retry sleep, so budget.sleep alone cannot
    bound it: each attempt's wire timeout must be clamped to the
    remaining budget and every new attempt gated on it, or a sequence
    of ~0.4s answers rides a 55s RPC straight past its deadline."""
    kc = KubeClient(srv.server_url, qps=10_000, burst=10_000)
    seed_cd(srv.cluster)
    cds = ResourceClient(kc, COMPUTE_DOMAINS)
    cds.get("cd-0", "default")  # warm the connection, fast-weather
    srv.inject_faults(latency=0.4)
    t0 = time.monotonic()
    with Budget(1.0).active():
        with pytest.raises(BudgetExceeded):
            for _ in range(10):  # unclamped: ~4s of answered GETs
                cds.get("cd-0", "default")
    elapsed = time.monotonic() - t0
    assert elapsed < 2.5, f"budget did not bound slow answers ({elapsed:.1f}s)"
    srv.inject_faults(latency=0.0)


def test_rest_read_fallback_serves_cache_while_open(srv):
    """Satellite: reads may serve from an informer cache while the
    circuit is open — the degraded read path, through the production
    ``install_read_fallback`` wiring (the ComputeDomain controller
    installs exactly this over its informers)."""
    metrics = Metrics()
    kc = make_client(srv, metrics=metrics)
    seed_cd(srv.cluster)
    # A second, breaker-free client feeds the informer (its transport
    # weather is not under test here).
    feeder = KubeClient(srv.server_url, qps=10_000, burst=10_000)
    informer = Informer(feeder, COMPUTE_DOMAINS)
    informer.start()
    assert informer.wait_for_sync(timeout=10)
    try:
        install_read_fallback(kc, [informer])
        cds = ResourceClient(kc, COMPUTE_DOMAINS)
        for verb in ("get", "list", "create"):
            kc.circuit.record_failure(verb)
            kc.circuit.record_failure(verb)
        assert kc.circuit.state("get") == OPEN
        # Stale-but-available beats unavailable: both reads serve.
        assert cds.get("cd-0", "default")["metadata"]["name"] == "cd-0"
        assert [o["metadata"]["name"] for o in cds.list()] == ["cd-0"]
        assert counter(
            metrics, "api_reads_served_from_cache_total", verb="get"
        ) == 1
        assert counter(
            metrics, "api_reads_served_from_cache_total", verb="list"
        ) == 1
        # A resource NO installed informer watches falls through to the
        # circuit error (never a fabricated empty answer), as does a
        # stale-store get miss (unavailability, not ApiNotFound).
        with pytest.raises(CircuitOpenError):
            ResourceClient(kc, RESOURCE_CLAIMS).list()
        with pytest.raises(CircuitOpenError):
            cds.get("cd-never-seen", "default")
        # Writes have no cache to serve from: still refused.
        with pytest.raises(CircuitOpenError):
            cds.create({
                "apiVersion": "resource.tpu.google.com/v1beta1",
                "kind": "ComputeDomain",
                "metadata": {"name": "cd-1", "namespace": "default"},
                "spec": {"numNodes": 1},
            })
    finally:
        informer.stop()


def test_informer_relist_bypasses_read_fallback(srv):
    """An informer's own resync list must observe the REAL apiserver.
    With the fallback installed on the same backend the informer reads
    through, an open list circuit would otherwise route the relist to
    an informer cache — typically its own store, whose scope guards
    pass by construction — faking a successful resync that emits no
    DELETEDs and resets the reconnect backoff."""
    kc = make_client(srv, cooldown=30)
    seed_cd(srv.cluster)
    informer = Informer(kc, COMPUTE_DOMAINS)
    informer.start()
    assert informer.wait_for_sync(timeout=10)
    try:
        install_read_fallback(kc, [informer])
        kc.circuit.record_failure("list")
        kc.circuit.record_failure("list")
        assert kc.circuit.state("list") == OPEN
        # Ordinary reads: stale-but-available beats unavailable.
        assert ResourceClient(kc, COMPUTE_DOMAINS).list()
        # The informer's own resync: fails (and keeps backing off)
        # instead of serving itself a fake relist.
        with pytest.raises(CircuitOpenError):
            informer._relist()
    finally:
        informer.stop()


def test_degraded_heal_request_during_running_fence_not_dropped():
    """A heal that loses the fence trylock while a previous fence is
    mid-replay must still run: the earlier fence already drained the
    parked-publish flag, so dropping the request would strand a publish
    parked during the replay until the next unrelated outage."""
    replay_started = threading.Event()
    release_replay = threading.Event()
    replays = []

    def replay():
        replays.append(1)
        replay_started.set()
        release_replay.wait(5)

    ctl = DegradedModeController(
        circuit=CircuitBreaker(failure_threshold=1, cooldown_seconds=0.05),
        metrics=Metrics(),
        stop=threading.Event(),
        probe=lambda: None,
        resync=lambda: None,
        replay=replay,
    )
    with ctl._lock:
        ctl._publish_pending_heal = True
    t1 = threading.Thread(target=ctl._resync_after_heal)
    t1.start()
    assert replay_started.wait(5), "fence #1 never reached its replay"
    # While fence #1 is mid-replay: a new publish parks, and a second
    # heal request loses the trylock.
    with ctl._lock:
        ctl._publish_pending_heal = True
    ctl._resync_after_heal()  # must record the request, not drop it
    release_replay.set()
    t1.join(5)
    wait_for(
        lambda: len(replays) == 2 and not ctl.publish_pending_heal,
        timeout=5,
        msg="second heal request dropped; parked publish stranded",
    )


def test_informer_serve_read_scope_guards():
    """serve_read answers only what the store can faithfully answer:
    nothing before sync, nothing outside the informer's namespace
    scope, nothing for a selector it did not watch with."""
    cluster = FakeCluster()
    ResourceClient(cluster, COMPUTE_DOMAINS).create({
        "apiVersion": "resource.tpu.google.com/v1beta1",
        "kind": "ComputeDomain",
        "metadata": {
            "name": "cd-a", "namespace": "default",
            "labels": {"tier": "prod"},
        },
        "spec": {"numNodes": 1},
    })
    inf = Informer(cluster, COMPUTE_DOMAINS)
    assert inf.serve_read("default", "cd-a", None) is None  # pre-sync
    inf.start()
    assert inf.wait_for_sync(timeout=10)
    try:
        assert inf.serve_read("default", "cd-a", None)["metadata"][
            "name"] == "cd-a"
        # List filters: namespace and (informer-side unselected) labels.
        assert [o["metadata"]["name"]
                for o in inf.serve_read(None, None, None)] == ["cd-a"]
        assert inf.serve_read("other-ns", None, None) == []
        assert [o["metadata"]["name"]
                for o in inf.serve_read(None, None, {"tier": "prod"})
                ] == ["cd-a"]
        assert inf.serve_read(None, None, {"tier": "dev"}) == []
    finally:
        inf.stop()

    # A namespace- or selector-scoped informer refuses queries outside
    # its scope instead of answering from a partial store.
    scoped = Informer(
        cluster, COMPUTE_DOMAINS, namespace="default",
        label_selector={"tier": "prod"},
    )
    scoped.start()
    assert scoped.wait_for_sync(timeout=10)
    try:
        assert scoped.serve_read(None, None, None) is None
        assert scoped.serve_read("default", None, {"tier": "dev"}) is None
        assert [o["metadata"]["name"]
                for o in scoped.serve_read(
                    "default", None, {"tier": "prod"})] == ["cd-a"]
    finally:
        scoped.stop()


def test_rest_retry_waits_consume_budget(srv):
    """429 Retry-After waits come out of the caller's budget: when the
    budget cannot cover the directed wait, the call fails retriable NOW
    instead of sleeping through its deadline."""
    kc = make_client(srv)
    seed_cd(srv.cluster)
    cds = ResourceClient(kc, COMPUTE_DOMAINS)
    srv.inject_faults(throttle=3, retry_after=30.0)
    with Budget(0.4).active():
        t0 = time.monotonic()
        with pytest.raises(BudgetExceeded):
            cds.get("cd-0", "default")
        assert time.monotonic() - t0 < 1.0
    srv.inject_faults(throttle=0)
    kc.circuit.reset()
    assert cds.get("cd-0", "default")["metadata"]["name"] == "cd-0"


def test_rest_partition_is_budget_bounded(srv):
    """An api_partition blackhole cannot hold a budgeted caller past
    its deadline: the per-verb read timeout fires, retries consume the
    budget, and the typed retriable error surfaces."""
    kc = make_client(srv, timeout=0.2)
    seed_cd(srv.cluster)
    cds = ResourceClient(kc, COMPUTE_DOMAINS)
    srv.inject_faults(partition_seconds=2.0)
    with Budget(0.8).active():
        t0 = time.monotonic()
        with pytest.raises((BudgetExceeded, Exception)) as ei:
            cds.get("cd-0", "default")
        elapsed = time.monotonic() - t0
    # Bound: the budget plus at most one in-flight read timeout.
    assert elapsed < 0.8 + 0.2 + 0.3, (
        f"partitioned get took {elapsed:.2f}s ({ei.value!r})"
    )
    wait_for(
        lambda: _probe_until_closed(cds, kc), timeout=10,
        msg="circuit never closed after the partition healed",
    )


def test_rest_per_verb_timeouts_configurable(srv):
    kc = KubeClient(
        srv.server_url, request_timeouts={"list": 7.5, "watch": 3.0}
    )
    assert kc._timeout("list") == 7.5
    assert kc._timeout("watch") == 3.0
    assert kc._timeout("get") == 30.0  # untouched verbs keep the default
    assert kc._timeout("brand-new-verb") == 30.0


# --- plugin: budget expiry mid-prepare converges via the WAL -----------------


MUX_CONFIG = [{
    "opaque": {
        "driver": DRIVER_NAME,
        "parameters": {
            "apiVersion": "resource.tpu.google.com/v1beta1",
            # The claim below allocates a *sub-slice* device, so the
            # sharing config must be the subslice kind — a TpuConfig
            # only matches full chips and would silently fall through
            # to the daemon-free default subslice config.
            "kind": "TpuSubsliceConfig",
            "sharing": {"strategy": "Multiplexing"},
        },
    },
    "requests": [],
    "source": "FromClaim",
}]


def make_driver(tmp_path, backend=None, **cfg):
    lib = StubTpuLib(
        config={"generation": "v5e", "hostname": "node-0"},
        state_dir=str(tmp_path / "tpustate"),
    )
    backend = backend or FakeCluster()
    cfg.setdefault("cdi_hook_source", "")
    # AF_UNIX socket paths cap at ~108 chars; tmp_path is deep.
    cfg.setdefault("multiplex_socket_root", tempfile.mkdtemp(prefix="aw-"))
    config = DriverConfig(
        node_name="node-0",
        cdi_root=str(tmp_path / "cdi"),
        plugin_data_dir=str(tmp_path / "plugin"),
        kubelet_registrar_dir=str(tmp_path / "registry"),
        start_grpc=False,
        **cfg,
    )
    return Driver(lib, backend, config), backend


def prepare_rpc(driver, claim):
    md = claim["metadata"]
    req = drapb.NodePrepareResourcesRequest(claims=[drapb.Claim(
        uid=md["uid"], name=md["name"], namespace=md["namespace"],
    )])
    t0 = time.monotonic()
    resp = driver.dra_service.node_prepare_resources(req, None)
    return resp.claims[md["uid"]], time.monotonic() - t0


def unprepare_rpc(driver, claim):
    md = claim["metadata"]
    req = drapb.NodeUnprepareResourcesRequest(claims=[drapb.Claim(
        uid=md["uid"], name=md["name"], namespace=md["namespace"],
    )])
    t0 = time.monotonic()
    resp = driver.dra_service.node_unprepare_resources(req, None)
    return resp.claims[md["uid"]], time.monotonic() - t0


def mark_daemons_ready(cluster):
    deployments = ResourceClient(cluster, DEPLOYMENTS)
    for dep in deployments.list(namespace="tpu-dra-driver"):
        if (dep.get("status") or {}).get("readyReplicas", 0) < 1:
            dep["status"] = {"readyReplicas": 1}
            deployments.update_status(dep)


def test_budget_expiry_mid_prepare_converges_via_wal(tmp_path):
    """The satellite scenario end to end: Prepare runs out of budget
    AFTER the WAL's PrepareStarted record (stalled on the multiplex
    daemon readiness gate), the kubelet sees a typed retriable error
    inside the deadline, and the retry with a fresh budget rolls the
    partial prepare back and converges — no orphan sub-slices."""
    gates(MultiplexingSupport=True, DynamicSubslice=True)
    driver, backend = make_driver(tmp_path)
    claims = ResourceClient(backend, RESOURCE_CLAIMS)
    claim = make_claim(devices=("tpu-ss-2x2-0-0-0",), configs=MUX_CONFIG)
    claim["metadata"]["uid"] = claims.create(claim)["metadata"]["uid"]
    uid = claim["metadata"]["uid"]
    driver.dra_service.rpc_budget_seconds = 0.4

    # Nothing marks the control daemon's Deployment ready: the
    # readiness gate consumes the whole RPC budget.
    result, took = prepare_rpc(driver, claim)
    assert result.error.startswith("deadline:"), result.error
    assert took < 2.0  # the RPC surfaced the expiry, it did not hang
    cp = driver.state.checkpoints.get()
    assert cp.prepared_claims[uid].checkpoint_state == (
        CLAIM_STATE_PREPARE_STARTED
    )  # the WAL intent record is exactly what makes the retry safe
    assert counter(driver.metrics, "prepare_budget_exceeded_total") == 1

    # The kubelet retries once the daemon is ready (fresh budget).
    mark_daemons_ready(backend)
    driver.dra_service.rpc_budget_seconds = 30.0
    result2, _ = prepare_rpc(driver, claim)
    assert result2.error == "", result2.error
    assert [d.device_name for d in result2.devices] == ["tpu-ss-2x2-0-0-0"]
    cp = driver.state.checkpoints.get()
    assert cp.prepared_claims[uid].checkpoint_state == (
        CLAIM_STATE_PREPARE_COMPLETED
    )
    # No orphan sub-slices: exactly the claim's one, nothing leaked by
    # the rolled-back first attempt.
    assert len(driver.tpulib.list_subslices()) == 1

    # Idempotent re-Prepare (kubelet redelivery) keeps the same answer.
    result3, _ = prepare_rpc(driver, claim)
    assert result3.error == ""
    assert len(driver.tpulib.list_subslices()) == 1
    driver.shutdown()


def test_unprepare_budget_expiry_is_retriable(tmp_path):
    """Unprepare stuck behind the node flock runs out of budget with a
    typed error; the retry (lock free again) converges."""
    driver, backend = make_driver(tmp_path)
    claims = ResourceClient(backend, RESOURCE_CLAIMS)
    claim = make_claim(devices=("tpu-0",))
    claim["metadata"]["uid"] = claims.create(claim)["metadata"]["uid"]
    result, _ = prepare_rpc(driver, claim)
    assert result.error == ""

    driver.dra_service.rpc_budget_seconds = 0.3
    release = driver.pu_flock.acquire(timeout=5)
    try:
        result, took = unprepare_rpc(driver, claim)
        assert result.error.startswith("deadline:"), result.error
        assert took < 2.0
        assert counter(driver.metrics, "unprepare_budget_exceeded_total") == 1
    finally:
        release()
    result2, _ = unprepare_rpc(driver, claim)
    assert result2.error == "", result2.error
    assert driver.state.checkpoints.get().prepared_claims == {}
    driver.shutdown()


# --- driver degraded mode ----------------------------------------------------


class WeatherHarness:
    """Driver over REAL HTTP through the circuit-broken KubeClient, with
    the fake apiserver's partition/latency seams and a kubelet-style
    timed RPC surface."""

    RPC_BUDGET = 1.5
    # A returned RPC may overshoot its budget by at most one in-flight
    # per-verb read timeout plus scheduling slack.
    RPC_SLACK = 1.0

    def __init__(self, tmp_path):
        self.srv = FakeApiServer(watch_heartbeat_seconds=1.0).start()
        self.cluster = self.srv.cluster
        self.metrics = Metrics()
        self.kc = make_client(
            self.srv, metrics=self.metrics, threshold=2, cooldown=0.25,
            timeout=0.25,
        )
        self.driver, _ = make_driver(tmp_path, backend=self.kc)
        self.driver.dra_service.rpc_budget_seconds = self.RPC_BUDGET
        self.driver.start()
        self.rpc_durations = []

    def create_claim(self, devices=("tpu-0",)):
        # Arrangement writes bypass HTTP: fault injection must never
        # flake the setup, only the system under test.
        claim = make_claim(devices=devices)
        created = ResourceClient(self.cluster, RESOURCE_CLAIMS).create(claim)
        claim["metadata"]["uid"] = created["metadata"]["uid"]
        return claim

    def timed_prepare(self, claim):
        result, took = prepare_rpc(self.driver, claim)
        self.rpc_durations.append(("prepare", took))
        return result

    def timed_unprepare(self, claim):
        result, took = unprepare_rpc(self.driver, claim)
        self.rpc_durations.append(("unprepare", took))
        return result

    def assert_rpcs_inside_budget(self):
        bound = self.RPC_BUDGET + self.RPC_SLACK
        over = [(op, t) for op, t in self.rpc_durations if t > bound]
        assert not over, (
            f"kubelet RPCs blocked past their budget (bound {bound}s): "
            f"{over}"
        )

    def prepare_until_converged(self, claim, timeout=15.0):
        """The kubelet's retry loop: re-Prepare with a fresh budget until
        success, each attempt individually bounded."""
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            result = self.timed_prepare(claim)
            if not result.error:
                return result
            time.sleep(0.1)
        raise AssertionError(
            f"prepare of {claim['metadata']['uid']} did not converge "
            f"within {timeout}s (last error: {result.error})"
        )

    def assert_converged(self, recovery_bound=15.0):
        """Post-heal contract: circuit closed, degraded mode exited, and
        checkpoint == apiserver claim state."""
        wait_for(
            lambda: not self.kc.circuit.any_open(), recovery_bound,
            msg=f"circuit still open: {self.kc.circuit.states()}",
        )
        wait_for(
            lambda: gauge(self.driver.metrics, "api_degraded") == 0,
            recovery_bound, msg="driver stuck in degraded mode",
        )

        def checkpoint_matches_api():
            cp = self.driver.state.checkpoints.get()
            live = {
                c["metadata"]["uid"]
                for c in ResourceClient(self.cluster, RESOURCE_CLAIMS).list()
            }
            return all(
                uid in live
                and c.checkpoint_state == CLAIM_STATE_PREPARE_COMPLETED
                for uid, c in cp.prepared_claims.items()
            )

        wait_for(
            checkpoint_matches_api, recovery_bound,
            msg="checkpoint and apiserver state did not reconverge",
        )

    def teardown(self):
        self.driver.shutdown()
        self.srv.stop()


def trip_circuit(h):
    """Force the breaker open deterministically: answer the next
    requests 503 and burn them with cheap gets."""
    h.srv.inject_faults(fail=50, fail_status=503)
    cds = ResourceClient(h.kc, COMPUTE_DOMAINS)
    wait_for(
        lambda: _absorb_failure(cds) and h.kc.circuit.any_open(),
        timeout=10, msg="circuit did not trip",
    )
    h.srv.inject_faults(fail=0)


def _absorb_failure(cds):
    try:
        cds.get("nope", "default")
    except Exception:
        pass
    return True


def test_degraded_mode_pauses_gc_defers_publish_and_heals(tmp_path):
    h = WeatherHarness(tmp_path)
    try:
        claim = h.create_claim(devices=("tpu-0",))
        assert h.timed_prepare(claim).error == ""

        trip_circuit(h)
        assert gauge(h.driver.metrics, "api_degraded") == 1

        # GC pauses while degraded (the running thread's 600s interval
        # never ticks in this test — the thread-level gate is covered by
        # test_cleanup_manager_skips_passes_while_degraded below).
        before = counter(
            h.driver.metrics, "cleanup_passes_skipped_degraded_total"
        )
        assert h.driver.circuit.any_open()
        # A health republish while degraded parks itself for the heal.
        h.driver.publish_with_retry()
        assert counter(
            h.driver.metrics, "publish_deferred_degraded_total"
        ) >= 1
        assert h.driver._publish_pending_heal is True

        # Prepare of the ALREADY-COMPLETED claim keeps serving from
        # checkpoint state — a restarting pod must not wedge.
        result = h.timed_prepare(claim)
        assert result.error == "", result.error
        assert counter(h.driver.metrics, "prepare_served_degraded_total") >= 1

        # Unprepare is local: it keeps working while the apiserver is
        # dark.
        claim2 = h.create_claim(devices=("tpu-1",))
        # (prepare of a NEW claim needs the apiserver — retriable error)
        r2 = h.timed_prepare(claim2)
        assert r2.error != ""

        # Heal: the kubelet's retry loop drives the half-open probe
        # through, the circuit closes, the fenced resync runs, and the
        # parked publish replays.
        h.prepare_until_converged(claim2)
        h.assert_converged()
        wait_for(
            lambda: counter(h.driver.metrics, "degraded_resyncs_total") >= 1,
            10, msg="heal resync never ran",
        )
        wait_for(
            lambda: h.driver._publish_pending_heal is False, 10,
            msg="parked publish never replayed",
        )
        h.assert_rpcs_inside_budget()
        assert before == 0  # the long-interval GC thread never ticked
    finally:
        h.teardown()


def test_cleanup_manager_skips_passes_while_degraded(tmp_path):
    """The GC loop's degraded gate, driven through the real thread."""
    h = WeatherHarness(tmp_path)
    try:
        h.driver.cleanup.stop()
        h.driver.cleanup.interval = 0.02
        h.driver.cleanup._stop = threading.Event()
        h.driver.cleanup.start()
        trip_circuit(h)
        wait_for(
            lambda: counter(
                h.driver.metrics, "cleanup_passes_skipped_degraded_total"
            ) >= 2,
            10, msg="degraded GC passes did not skip",
        )
    finally:
        h.teardown()


# --- the apiserver-partition soak (acceptance) -------------------------------


def run_partition_soak(tmp_path, schedule=None):
    """Drive weather over the harness while a kubelet loop keeps
    issuing prepare/unprepare RPCs. Asserts the acceptance bar: every
    RPC inside its budget, reconvergence after the heal."""
    h = WeatherHarness(tmp_path)
    try:
        # Steady state: two claims prepared over healthy HTTP.
        stay = h.create_claim(devices=("tpu-0",))
        doomed = h.create_claim(devices=("tpu-1",))
        assert h.timed_prepare(stay).error == ""
        assert h.timed_prepare(doomed).error == ""

        stop = threading.Event()
        # Longer than one RPC budget: at least one kubelet attempt is
        # guaranteed to run out of budget inside the blackhole, and the
        # failed requests trip the circuit so the heal path (half-open
        # probe, fenced resync) deterministically runs. Seeded storms
        # layer on top — their events can be individually too short to
        # trip anything, which must not let the doomed-claim GC
        # assertion below silently wait on a resync that never fires.
        h.srv.inject_faults(partition_seconds=2.5)
        if schedule is not None:
            engine = ChaosEngine(schedule)
            for kind, inject in _weather_injectors(h).items():
                engine.register(kind, inject)
            t = threading.Thread(
                target=engine.run, kwargs={"time_scale": 1.0, "stop": stop},
                daemon=True,
            )
            t.start()

        # The apiserver object for `doomed` vanishes while the plugin
        # cannot see the control plane: the fenced heal resync must GC
        # it from the checkpoint afterwards.
        ResourceClient(h.cluster, RESOURCE_CLAIMS).delete(
            doomed["metadata"]["name"], doomed["metadata"]["namespace"]
        )

        # Kubelet keeps trying a NEW claim through the weather; every
        # attempt must return inside its budget (typed error, not a
        # stall).
        fresh = h.create_claim(devices=("tpu-2",))
        saw_retriable_error = False
        end = time.monotonic() + 6.0
        while time.monotonic() < end:
            result = h.timed_prepare(fresh)
            if result.error:
                saw_retriable_error = True
                assert "PermanentError" not in result.error
                time.sleep(0.05)
                continue
            break
        h.assert_rpcs_inside_budget()

        # Heal + recovery bound: the new claim converges, the circuit
        # closes, the fenced resync reconciles the deleted claim away.
        h.prepare_until_converged(fresh)
        h.assert_converged(recovery_bound=15.0)
        wait_for(
            lambda: doomed["metadata"]["uid"] not in (
                h.driver.state.checkpoints.get().prepared_claims
            ),
            15, msg="fenced resync never GC'd the claim deleted "
                    "during the partition",
        )
        # The surviving claim is untouched, and re-Prepare stays
        # idempotent after the weather.
        cp = h.driver.state.checkpoints.get()
        assert cp.prepared_claims[stay["metadata"]["uid"]].checkpoint_state \
            == CLAIM_STATE_PREPARE_COMPLETED
        assert h.timed_prepare(stay).error == ""
        h.assert_rpcs_inside_budget()
        stop.set()
        return saw_retriable_error
    finally:
        h.teardown()


def _weather_injectors(h):
    return {
        API_PARTITION: lambda ev: h.srv.inject_faults(
            partition_seconds=ev.params["duration"],
        ),
        API_LATENCY: lambda ev: h.srv.inject_faults(
            latency=ev.params["delay"],
            latency_seconds=ev.params["duration"],
        ),
        APISERVER_THROTTLE: lambda ev: h.srv.inject_faults(
            throttle=ev.params["count"],
            retry_after=ev.params.get("retry_after", 0.05),
        ),
        APISERVER_ERRORS: lambda ev: h.srv.inject_faults(
            fail=ev.params["count"],
            fail_status=ev.params.get("status", 503),
        ),
        WATCH_DROP: lambda ev: h.srv.inject_faults(drop_watches=True),
    }


def test_api_partition_soak_smoke(tmp_path):
    """Tier-1 acceptance: one partition window. The kubelet sees typed
    retriable errors inside the budget while the apiserver is dark, and
    the stack reconverges after the heal."""
    saw_error = run_partition_soak(tmp_path)
    assert saw_error, (
        "the partition window produced no retriable prepare error — "
        "the fault never landed and the soak proved nothing"
    )


WEATHER_KINDS = [
    API_PARTITION, API_LATENCY, APISERVER_THROTTLE, APISERVER_ERRORS,
    WATCH_DROP,
]


@pytest.mark.slow
@pytest.mark.parametrize("seed", [11, 23, 47])
def test_api_weather_soak_matrix(tmp_path, seed):
    """Seeded storms mixing partitions, latency, throttles, 5xx bursts
    and watch drops — same acceptance bar as the smoke."""
    schedule = FaultSchedule.from_seed(
        seed, duration=3.0, events_per_second=2.0, kinds=WEATHER_KINDS,
    )
    run_partition_soak(tmp_path, schedule=schedule)


# --- review regressions: probe leaks, listener deadlock, park races --------


def test_release_probe_returns_half_open_slot():
    """A probe abandoned with no outcome (budget expiry before the
    request left the client) must not wedge the verb half-open."""
    cb, clock = make_breaker(failure_threshold=1)
    cb.record_failure("get")
    assert cb.state("get") == OPEN
    clock.t += 5.1
    cb.check("get")  # grants the half-open probe
    with pytest.raises(CircuitOpenError):
        cb.check("get")  # concurrent caller refused while probing
    cb.release_probe("get")
    cb.check("get")  # the NEXT caller may probe instead of being wedged
    cb.record_success("get")
    assert cb.state("get") == CLOSED


def test_rest_abandoned_probe_does_not_wedge_half_open(srv):
    """Transport-level version: the granted probe dies inside the QPS
    throttle wait (BudgetExceeded) before any outcome reaches the
    breaker; a later caller must still be able to probe and close."""
    kc = KubeClient(
        srv.server_url, qps=1, burst=1,
        circuit=CircuitBreaker(failure_threshold=2, cooldown_seconds=0.2),
        request_timeouts={"get": 0.5},
    )
    seed_cd(srv.cluster)
    cds = ResourceClient(kc, COMPUTE_DOMAINS)
    cds.get("cd-0", "default")  # drains the single-token bucket
    kc.circuit.record_failure("get")
    kc.circuit.record_failure("get")
    assert kc.circuit.state("get") == OPEN
    time.sleep(0.25)  # cooldown elapses; next check grants the probe
    # A budget below MIN_ATTEMPT_SECONDS fails BEFORE the breaker is
    # consulted: no probe slot is granted, the circuit stays untouched.
    with Budget(0.01).active():
        with pytest.raises(BudgetExceeded):
            cds.get("cd-0", "default")
    assert kc.circuit.state("get") == OPEN
    # A budget that passes the pre-attempt gate but cannot cover the
    # ~1s throttle wait IS granted the probe and abandons it there.
    with Budget(0.1).active():
        with pytest.raises(BudgetExceeded):
            cds.get("cd-0", "default")  # ~1s throttle wait, ~100ms budget
    assert kc.circuit.state("get") == HALF_OPEN
    # The abandoned slot was returned: an unbudgeted caller probes
    # through and closes the circuit.
    wait_for(
        lambda: _probe_until_closed(cds, kc), timeout=10,
        msg="half-open probe slot leaked; circuit can never close",
    )


def test_publish_circuit_trip_on_publish_thread_does_not_deadlock(tmp_path):
    """publish_resources holds _publish_lock across its apiserver calls;
    when those calls trip the breaker, _on_circuit fires synchronously
    ON THE PUBLISHING THREAD. It must not re-acquire _publish_lock."""
    h = WeatherHarness(tmp_path)
    try:
        assert gauge(h.driver.metrics, "api_degraded") == 0
        # Threshold is 2: one publish's list retries record enough 503
        # failures to trip the breaker mid-call. The content-diffed
        # publisher would make a repeat publish a zero-write no-op
        # (never reaching the apiserver); drop its cache so this pass
        # must relist + write — the regime the deadlock guard protects.
        h.driver._publisher.invalidate()
        h.srv.inject_faults(fail=50, fail_status=503)
        done = threading.Event()
        err = []

        def _publish():
            try:
                h.driver.publish_resources()
            except Exception as e:
                err.append(e)
            finally:
                done.set()

        t = threading.Thread(target=_publish, daemon=True)
        t.start()
        assert done.wait(timeout=20), (
            "publish_resources deadlocked against the circuit listener"
        )
        assert err, "publish should have failed under the 503 burst"
        assert gauge(h.driver.metrics, "api_degraded") == 1
        h.srv.inject_faults(fail=0)
    finally:
        h.teardown()


def test_defer_publish_unparks_when_circuit_closes_mid_park(tmp_path):
    """The heal resync may drain _publish_pending_heal between the
    degraded gate and the park; with the circuit already closed again,
    no future heal will replay the parked publish — the defer must
    detect the close, take the park back, and let the caller publish."""
    h = WeatherHarness(tmp_path)
    try:
        answers = iter([True, False])  # gate sees the outage; park recheck
        h.driver.circuit.any_open = lambda: next(answers)  # sees the heal
        assert h.driver._defer_publish_while_degraded() is False
        assert h.driver._publish_pending_heal is False
    finally:
        del h.driver.circuit.any_open
        h.teardown()


def test_informer_resync_backoff_exponent_capped():
    """A multi-hour outage pushes the consecutive-failure count past
    2**1024's float range; the delay must stay capped, not overflow."""
    inf = Informer(FakeCluster(), COMPUTE_DOMAINS)
    inf._resync_failures = 5000
    delay = inf._next_resync_delay()  # must not raise OverflowError
    # The cap is the documented worst case: jitter spreads below it,
    # never past it.
    assert delay <= inf.resync_backoff_max


def test_cd_driver_degraded_gauge_and_heal_resync(srv, tmp_path):
    """CDDriver has the same degraded-mode contract as Driver: the
    api_degraded gauge tracks the breaker and a fenced resync (claim GC
    + slice republish) runs on heal."""
    from tpu_dra.computedomain.cdplugin.driver import CDDriver, CDDriverConfig
    from tpu_dra.k8sclient import RESOURCE_SLICES

    kc = make_client(srv)
    driver = CDDriver(
        kc,
        CDDriverConfig(
            node_name="cd-node-0",
            cdi_root=f"{tmp_path}/cdi",
            plugin_data_dir=f"{tmp_path}/plugin",
            start_grpc=False,
        ),
        clique_id="s.0",
    )
    assert gauge(driver.metrics, "api_degraded") == 0
    kc.circuit.record_failure("get")
    kc.circuit.record_failure("get")
    assert gauge(driver.metrics, "api_degraded") == 1
    # Heal: the listener leaves degraded mode through the fenced resync,
    # which republishes this node's CD slices.
    kc.circuit.record_success("get")
    assert gauge(driver.metrics, "api_degraded") == 0
    wait_for(
        lambda: counter(driver.metrics, "degraded_resyncs_total") >= 1,
        10, msg="CD heal resync never ran",
    )
    wait_for(
        lambda: len(ResourceClient(kc, RESOURCE_SLICES).list()) > 0,
        10, msg="CD heal resync never republished the slices",
    )
