"""Tests for the hack/lints static-analysis suite (ISSUE 3).

Every code the suite can emit — old and new — gets at least one
positive fixture (the code fires), one negative fixture (a nearby
correct idiom stays clean), and, where the disable marker applies, a
``# lint: disable=`` case. Baseline semantics (shrink-only: stale
entries fail, growth vs the committed copy fails) are covered against
throwaway git repos, plus a guard that the checked-in baseline never
grows relative to HEAD.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO / "hack") not in sys.path:
    sys.path.insert(0, str(REPO / "hack"))

from lints import baseline as baseline_mod  # noqa: E402
from lints.base import FileContext, Finding, disabled_codes  # noqa: E402
from lints.asyncblock import AsyncBlockingPass  # noqa: E402
from lints.benchkeys import BenchSchemaPass  # noqa: E402
from lints.chaosjson import ChaosSchedulePass  # noqa: E402
from lints.cli import main as lint_main  # noqa: E402
from lints.crashpoints import CrashPointPass  # noqa: E402
from lints.spannames import SpanNamePass  # noqa: E402
from lints.gates import GateDominancePass  # noqa: E402
from lints.layering import LayeringPass, validate_dag  # noqa: E402
from lints.legacy import CorePass  # noqa: E402
from lints.names import UndefinedNamePass  # noqa: E402
from lints.races import RaceLintPass  # noqa: E402
from lints.sleeps import DriverSleepPass  # noqa: E402
from lints.tracer import TracerSafetyPass  # noqa: E402


def write(tmp_path: Path, rel: str, source: str) -> Path:
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source), encoding="utf-8")
    return p


def codes(tmp_path, rel, source, pass_cls):
    ctx = FileContext(write(tmp_path, rel, source), REPO)
    return [f.code for f in pass_cls().run(ctx)]


# --- marker parsing ---------------------------------------------------------


def test_disable_marker_plain_and_with_justification():
    assert disabled_codes("x = 1  # lint: disable=R200") == {"R200"}
    assert disabled_codes(
        "x = 1  # lint: disable=R200,J300 (thread-confined; see _run)"
    ) == {"R200", "J300"}
    assert disabled_codes("x = 1  # no marker") == set()


# --- core (legacy) codes ----------------------------------------------------


def test_f401_unused_import(tmp_path):
    assert codes(tmp_path, "a.py", "import os\n", CorePass) == ["F401"]


def test_f401_negative_used_and_noqa(tmp_path):
    assert codes(tmp_path, "a.py", "import os\nprint(os.sep)\n", CorePass) == []
    assert codes(tmp_path, "a.py", "import os  # noqa\n", CorePass) == []


def test_f811_redefinition(tmp_path):
    src = "def f():\n    pass\n\n\ndef f():\n    pass\n"
    assert codes(tmp_path, "a.py", src, CorePass) == ["F811"]


def test_f811_negative_methods(tmp_path):
    src = "class A:\n    def f(self):\n        pass\n\n\nclass B:\n    def f(self):\n        pass\n"
    assert codes(tmp_path, "a.py", src, CorePass) == []


def test_e722_bare_except(tmp_path):
    src = "try:\n    pass\nexcept:\n    pass\n"
    assert codes(tmp_path, "a.py", src, CorePass) == ["E722"]


def test_e722_negative_typed(tmp_path):
    src = "try:\n    pass\nexcept ValueError:\n    pass\n"
    assert codes(tmp_path, "a.py", src, CorePass) == []


def test_b006_mutable_default(tmp_path):
    assert codes(tmp_path, "a.py", "def f(x=[]):\n    return x\n", CorePass) == ["B006"]


def test_b006_negative_none_default(tmp_path):
    assert codes(tmp_path, "a.py", "def f(x=None):\n    return x\n", CorePass) == []


def test_f541_placeholderless_fstring(tmp_path):
    assert codes(tmp_path, "a.py", "x = f'nope'\n", CorePass) == ["F541"]


def test_f541_negative_format_spec(tmp_path):
    # {v:.1f} carries a nested placeholder-less JoinedStr; not an f-string.
    assert codes(tmp_path, "a.py", "v = 1.0\nx = f'{v:.1f}'\n", CorePass) == []


def test_w605_invalid_escape_flagged(tmp_path):
    ctx = FileContext(
        write(tmp_path, "a.py", "import re\nre.compile('\\d+')\n"), REPO
    )
    out = CorePass().run(ctx)
    # Byte-identical to the pre-package linter: on this Python, compile
    # under warnings-as-errors surfaces the invalid escape as a
    # SyntaxError (E999); older/newer interpreters may surface the
    # Warning object itself (W605). Either way the gate fails with the
    # escape named.
    assert [f.code for f in out] in (["E999"], ["W605"])
    assert "invalid escape sequence" in out[0].message


def test_w605_negative_raw_string(tmp_path):
    assert codes(tmp_path, "a.py", "import re\nre.compile(r'\\d+')\n", CorePass) == []


def test_e999_syntax_error_short_circuits(tmp_path):
    assert codes(tmp_path, "a.py", "def f(:\n", CorePass) == ["E999"]


def test_core_disable_marker(tmp_path):
    src = "try:\n    pass\nexcept:  # lint: disable=E722\n    pass\n"
    assert codes(tmp_path, "a.py", src, CorePass) == []


# --- F821 scoped undefined names --------------------------------------------


def test_f821_typo_fires(tmp_path):
    src = "def f():\n    return undefined_nam\n"
    assert codes(tmp_path, "a.py", src, UndefinedNamePass) == ["F821"]


def test_f821_negative_scoping_rules(tmp_path):
    # Closures, class-body comprehension first-iterable, global/nonlocal,
    # walrus hoisting, builtins, lambda params, match captures.
    src = '''
        import os

        TOP = 1


        def outer():
            local = 2

            def inner():
                return local + TOP + len(os.sep)

            return inner


        class C:
            xs = [1, 2]
            ys = [x for x in xs]

            def m(self):
                return super().__init__()


        def walrus(rows):
            if (n := len(rows)) > 0:
                return n
            return 0


        def declares_global():
            global _late
            _late = 3


        def uses_global():
            return _late


        def matcher(obj):
            match obj:
                case {"k": v, **rest}:
                    return v, rest
                case [first, *others]:
                    return first, others
                case _:
                    return None
    '''
    assert codes(tmp_path, "a.py", src, UndefinedNamePass) == []


def test_f821_class_scope_invisible_to_methods(tmp_path):
    src = '''
        class C:
            attr = 1

            def m(self):
                return attr
    '''
    assert codes(tmp_path, "a.py", src, UndefinedNamePass) == ["F821"]


def test_f821_star_import_suppresses(tmp_path):
    src = "from os.path import *\n\n\ndef f():\n    return join('a', 'b')\n"
    assert codes(tmp_path, "a.py", src, UndefinedNamePass) == []


def test_f821_disable_marker(tmp_path):
    src = "def f():\n    return mystery  # lint: disable=F821\n"
    assert codes(tmp_path, "a.py", src, UndefinedNamePass) == []


# --- R200 lock-discipline race lint -----------------------------------------

R200_POSITIVE = '''
    import threading


    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._state = {}

        def start(self):
            threading.Thread(target=self._run).start()
            self._state["a"] = 1

        def _run(self):
            self._state["b"] = 2
'''


def test_r200_unlocked_shared_write_fires(tmp_path):
    assert codes(tmp_path, "a.py", R200_POSITIVE, RaceLintPass) == [
        "R200", "R200"
    ]


def test_r200_negative_writes_under_lock(tmp_path):
    src = R200_POSITIVE.replace(
        'self._state["a"] = 1',
        'with self._lock:\n                self._state["a"] = 1',
    ).replace(
        'self._state["b"] = 2',
        'with self._lock:\n                self._state["b"] = 2',
    )
    assert codes(tmp_path, "a.py", src, RaceLintPass) == []


def test_r200_negative_not_concurrent(tmp_path):
    src = '''
        class Plain:
            def a(self):
                self.x = 1

            def b(self):
                self.x = 2
    '''
    assert codes(tmp_path, "a.py", src, RaceLintPass) == []


def test_r200_negative_single_writer_method(tmp_path):
    src = '''
        import threading


        class OneWriter:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                print("no shared writes here")
    '''
    assert codes(tmp_path, "a.py", src, RaceLintPass) == []


def test_r200_annotated_lock_assignment_discovered(tmp_path):
    """Review regression: `self._lock: threading.Lock =
    threading.Lock()` must register as a lock."""
    src = '''
        import threading


        class Annotated:
            def __init__(self):
                self._lock: threading.Lock = threading.Lock()
                self._state = {}

            def start(self):
                threading.Thread(target=self._run).start()
                with self._lock:
                    self._state["a"] = 1

            def _run(self):
                with self._lock:
                    self._state["b"] = 2
    '''
    assert codes(tmp_path, "a.py", src, RaceLintPass) == []


def test_r200_locked_suffix_convention(tmp_path):
    src = '''
        import threading


        class Queue:
            def __init__(self):
                self._cond = threading.Condition()
                self._items = []

            def run_in_thread(self):
                threading.Thread(target=self.run).start()

            def run(self):
                with self._cond:
                    self._push_locked(1)

            def _push_locked(self, item):
                self._items.append(item)

            def add(self, item):
                with self._cond:
                    self._items.append(item)
    '''
    assert codes(tmp_path, "a.py", src, RaceLintPass) == []


def test_r200_negative_plain_attr_to_constructor_not_concurrent(tmp_path):
    """Review regression: passing a plain self ATTRIBUTE (not a bound
    method) to a capitalized callable — ValueError(self.root),
    Path(self.base) — must not mark the class concurrent."""
    src = '''
        class SingleThreaded:
            def __init__(self, root):
                self.root = root
                self.state = {}

            def a(self):
                self.state["a"] = 1
                raise ValueError(self.root)

            def b(self):
                self.state["b"] = 2
    '''
    assert codes(tmp_path, "a.py", src, RaceLintPass) == []


def test_r200_bound_method_to_constructor_is_concurrent(tmp_path):
    src = '''
        class HandsOutCallback:
            def __init__(self):
                self.state = {}
                self.mon = Monitor(self._on_event)

            def _on_event(self, ev):
                self.state["e"] = ev

            def poke(self):
                self.state["p"] = 1
    '''
    assert codes(tmp_path, "a.py", src, RaceLintPass) == ["R200", "R200"]


def test_r200_disable_marker(tmp_path):
    src = R200_POSITIVE.replace(
        'self._state["b"] = 2',
        'self._state["b"] = 2  # lint: disable=R200 (why: test)',
    ).replace(
        'self._state["a"] = 1',
        'self._state["a"] = 1  # lint: disable=R200',
    )
    assert codes(tmp_path, "a.py", src, RaceLintPass) == []


# --- J300 tracer safety ------------------------------------------------------

WL = "tpu_dra/workloads/snippet.py"


def test_j300_host_sync_in_jit(tmp_path):
    src = '''
        import jax
        import jax.numpy as jnp


        @jax.jit
        def f(x):
            return float(jnp.sum(x))
    '''
    assert codes(tmp_path, WL, src, TracerSafetyPass) == ["J300"]


def test_j300_item_in_scan_body(tmp_path):
    src = '''
        from jax import lax


        def body(carry, x):
            v = carry.item()
            return carry, v


        def outer(xs):
            return lax.scan(body, 0.0, xs)
    '''
    assert codes(tmp_path, WL, src, TracerSafetyPass) == ["J300"]


def test_j300_traced_branch(tmp_path):
    src = '''
        import jax
        import jax.numpy as jnp


        @jax.jit
        def f(x):
            if jnp.any(x > 0):
                x = x + 1
            return x
    '''
    assert codes(tmp_path, WL, src, TracerSafetyPass) == ["J300"]


def test_j300_import_time_jnp(tmp_path):
    src = "import jax.numpy as jnp\n\nX = jnp.ones((4,))\n"
    assert codes(tmp_path, WL, src, TracerSafetyPass) == ["J300"]


def test_j300_static_mention_does_not_mask_traced_use(tmp_path):
    """Review regression: a shape read inside the expression must not
    exempt a traced reduction next to it."""
    src = '''
        import jax
        import jax.numpy as jnp


        @jax.jit
        def f(x):
            m = float(jnp.sum(x) / x.shape[0])
            if jnp.sum(x) > x.shape[0]:
                m = m + 1
            return m
    '''
    assert codes(tmp_path, WL, src, TracerSafetyPass) == ["J300", "J300"]


def test_j300_bare_param_and_method_reduction_casts(tmp_path):
    """Review regression: `float(x)` over a traced parameter and
    `float(x.sum())` (zero-arg method on a traced receiver) are the
    canonical per-step host syncs and must fire."""
    src = '''
        import jax


        @jax.jit
        def f(x):
            a = float(x.sum())
            b = float(x)
            return a + b
    '''
    assert codes(tmp_path, WL, src, TracerSafetyPass) == ["J300", "J300"]


def test_j300_negative_cast_of_local_python_scalar(tmp_path):
    # A non-parameter local fed by static values stays unflagged.
    src = '''
        import jax


        @jax.jit
        def f(x, scale=2.0):
            k = len(x.shape)
            n = float(k)
            return x, n
    '''
    assert codes(tmp_path, WL, src, TracerSafetyPass) == []


def test_j300_negative_fully_static_jnp_over_shapes(tmp_path):
    src = '''
        import jax
        import jax.numpy as jnp


        @jax.jit
        def f(x):
            if jnp.prod(jnp.asarray(x.shape)) > 16:
                x = x[:2]
            n = float(x.shape[0])
            return x, n
    '''
    assert codes(tmp_path, WL, src, TracerSafetyPass) == []


def test_j300_negative_clean_patterns(tmp_path):
    # Static branches, shape reads, lax.cond, host sync OUTSIDE jit,
    # module-level attribute access (dtype), main-guard jnp calls.
    src = '''
        import jax
        import jax.numpy as jnp
        from jax import lax

        DTYPE = jnp.float32


        @jax.jit
        def f(x, flag: bool = True):
            if flag:
                x = x + 1
            if x.shape[0] > 4:
                x = x[:4]
            return lax.cond(x[0] > 0, lambda v: v, lambda v: -v, x)


        def host_side(x):
            y = f(x)
            return float(jnp.sum(y))


        if __name__ == "__main__":
            print(float(jnp.sum(jnp.ones((2,)))))
    '''
    assert codes(tmp_path, WL, src, TracerSafetyPass) == []


def test_j300_scoped_to_workloads_only(tmp_path):
    src = '''
        import jax
        import jax.numpy as jnp


        @jax.jit
        def f(x):
            return float(jnp.sum(x))
    '''
    assert codes(tmp_path, "tpu_dra/plugin/snippet.py", src, TracerSafetyPass) == []


def test_j300_disable_marker(tmp_path):
    src = '''
        import jax
        import jax.numpy as jnp


        @jax.jit
        def f(x):
            return float(jnp.sum(x))  # lint: disable=J300
    '''
    assert codes(tmp_path, WL, src, TracerSafetyPass) == []


# --- G400 gate dominance -----------------------------------------------------

GATED_MODULE = '''
    __feature_gate__ = "AutoRemediation"


    class RemediationController:
        pass
'''


def g400(tmp_path, caller_src):
    gated = FileContext(
        write(tmp_path, "tpu_dra/plugin/remediation.py", GATED_MODULE), tmp_path
    )
    caller = FileContext(
        write(tmp_path, "tpu_dra/plugin/driver.py", caller_src), tmp_path
    )
    return [f.code for f in GateDominancePass().run_project([gated, caller])]


def test_g400_undominated_call_fires(tmp_path):
    src = '''
        from tpu_dra.plugin.remediation import RemediationController


        def build():
            return RemediationController()
    '''
    assert g400(tmp_path, src) == ["G400"]


def test_g400_negative_dominated(tmp_path):
    src = '''
        from tpu_dra.infra import featuregates as fg
        from tpu_dra.plugin.remediation import RemediationController


        def build():
            ctl = None
            if fg.enabled(fg.AUTO_REMEDIATION):
                ctl = RemediationController()
            return ctl


        def build_guarded():
            if not fg.enabled(fg.AUTO_REMEDIATION):
                return None
            return RemediationController()
    '''
    assert g400(tmp_path, src) == []


def test_g400_negative_string_gate_and_else_branch(tmp_path):
    src = '''
        from tpu_dra.infra.featuregates import enabled
        from tpu_dra.plugin.remediation import RemediationController


        def build():
            if enabled("AutoRemediation"):
                return RemediationController()
            return None
    '''
    assert g400(tmp_path, src) == []


def test_g400_negated_guard_without_return_does_not_establish(tmp_path):
    """Review regression: `if not enabled(G):` must not establish G
    inside its own (gate-OFF) branch — only a terminating guard
    establishes it below, and only the ELSE branch runs gate-ON."""
    src = '''
        from tpu_dra.infra import featuregates as fg
        from tpu_dra.plugin.remediation import RemediationController


        def build():
            ctl = None
            if not fg.enabled(fg.AUTO_REMEDIATION):
                ctl = RemediationController()
            return ctl
    '''
    assert g400(tmp_path, src) == ["G400"]
    src_else = '''
        from tpu_dra.infra import featuregates as fg
        from tpu_dra.plugin.remediation import RemediationController


        def build():
            if not fg.enabled(fg.AUTO_REMEDIATION):
                return None
            else:
                return RemediationController()
    '''
    assert g400(tmp_path, src_else) == []


def test_g400_else_branch_not_dominated(tmp_path):
    src = '''
        from tpu_dra.infra import featuregates as fg
        from tpu_dra.plugin.remediation import RemediationController


        def build():
            if fg.enabled(fg.AUTO_REMEDIATION):
                return None
            else:
                return RemediationController()
    '''
    assert g400(tmp_path, src) == ["G400"]


def test_g400_module_object_import_forms(tmp_path):
    # `from pkg import gated_module` and dotted `import` both route
    # through the gate check.
    src = '''
        from tpu_dra.plugin import remediation


        def build():
            return remediation.RemediationController()
    '''
    assert g400(tmp_path, src) == ["G400"]
    src2 = '''
        import tpu_dra.plugin.remediation as rem
        from tpu_dra.infra import featuregates as fg


        def build():
            if fg.enabled(fg.AUTO_REMEDIATION):
                return rem.RemediationController()
            return None
    '''
    assert g400(tmp_path, src2) == []


def test_g400_or_alternative_does_not_establish(tmp_path):
    """Review regression: `if enabled(G) or force:` — the or-branch is
    reachable with the gate off, so the call is NOT dominated; `and`
    still dominates."""
    src = '''
        from tpu_dra.infra import featuregates as fg
        from tpu_dra.plugin.remediation import RemediationController


        def build(force):
            if fg.enabled(fg.AUTO_REMEDIATION) or force:
                return RemediationController()
            return None
    '''
    assert g400(tmp_path, src) == ["G400"]
    src_and = '''
        from tpu_dra.infra import featuregates as fg
        from tpu_dra.plugin.remediation import RemediationController


        def build(ready):
            if fg.enabled(fg.AUTO_REMEDIATION) and ready:
                return RemediationController()
            return None
    '''
    assert g400(tmp_path, src_and) == []


def test_g400_tests_exempt(tmp_path):
    gated = FileContext(
        write(tmp_path, "tpu_dra/plugin/remediation.py", GATED_MODULE), tmp_path
    )
    test_src = '''
        from tpu_dra.plugin.remediation import RemediationController


        def test_it():
            return RemediationController()
    '''
    caller = FileContext(
        write(tmp_path, "tests/test_thing.py", test_src), tmp_path
    )
    assert [f.code for f in GateDominancePass().run_project([gated, caller])] == []


def test_g400_disable_marker(tmp_path):
    src = '''
        from tpu_dra.plugin.remediation import RemediationController


        def build():
            # Caller establishes the gate (see Driver.start).
            return RemediationController()  # lint: disable=G400
    '''
    assert g400(tmp_path, src) == []


def test_g400_real_remediation_module_declares_gate():
    from tpu_dra.plugin import remediation

    assert remediation.__feature_gate__ == "AutoRemediation"


# --- L500 layering ------------------------------------------------------------


def test_l500_dag_is_valid():
    assert validate_dag() == []


def test_l500_upward_import_fires(tmp_path):
    src = "from tpu_dra.plugin.driver import Driver\n"
    assert codes(tmp_path, "tpu_dra/tpulib/snippet.py", src, LayeringPass) == ["L500"]


def test_l500_workloads_never_imported_by_driver_layer(tmp_path):
    src = "from tpu_dra.workloads import generate\n"
    assert codes(tmp_path, "tpu_dra/plugin/snippet.py", src, LayeringPass) == ["L500"]


def test_l500_negative_downward_and_lazy(tmp_path):
    src = '''
        from tpu_dra.tpulib.types import ChipInfo


        def late():
            # Function-local imports are the sanctioned escape.
            from tpu_dra.minicluster.cluster import MiniCluster

            return MiniCluster, ChipInfo
    '''
    assert codes(tmp_path, "tpu_dra/plugin/snippet.py", src, LayeringPass) == []


def test_l500_cross_test_import_fires(tmp_path):
    src = "from tests.test_other import helper\n"
    assert codes(tmp_path, "tests/test_snippet.py", src, LayeringPass) == ["L500"]


def test_l500_relative_import_cannot_dodge_dag(tmp_path):
    """Review regression: `from ..workloads import x` is the same edge
    as `from tpu_dra.workloads import x`."""
    src = "from ..workloads import generate\n"
    assert codes(tmp_path, "tpu_dra/plugin/snippet.py", src, LayeringPass) == ["L500"]
    ok = "from ..tpulib import types\nfrom . import cdi\n"
    assert codes(tmp_path, "tpu_dra/plugin/snippet.py", ok, LayeringPass) == []


def test_l500_from_tests_import_test_module_fires(tmp_path):
    """Review regression: `from tests import test_x` and
    `from . import test_x` are cross-test imports too."""
    src = "from tests import test_other\n"
    assert codes(tmp_path, "tests/test_snippet.py", src, LayeringPass) == ["L500"]
    src2 = "from . import test_other\n"
    assert codes(tmp_path, "tests/test_snippet.py", src2, LayeringPass) == ["L500"]
    ok = "from fixtures import test_data_value\n"
    assert codes(tmp_path, "tests/test_snippet.py", ok, LayeringPass) == []


def test_l500_negative_helpers_import(tmp_path):
    src = "from tests.helpers import make_claim\nprint(make_claim)\n"
    assert codes(tmp_path, "tests/test_snippet.py", src, LayeringPass) == []


def test_l500_disable_marker(tmp_path):
    src = "from tests.test_other import helper  # lint: disable=L500\n"
    assert codes(tmp_path, "tests/test_snippet.py", src, LayeringPass) == []


# --- A600 blocking-in-async ---------------------------------------------------


def test_a600_blocking_calls_fire(tmp_path):
    src = '''
        import subprocess
        import time


        async def handler():
            time.sleep(1)
            subprocess.run(["true"])
    '''
    assert codes(tmp_path, "a.py", src, AsyncBlockingPass) == ["A600", "A600"]


def test_a600_negative_async_and_executor(tmp_path):
    src = '''
        import asyncio
        import time


        async def handler():
            await asyncio.sleep(1)
            loop = asyncio.get_running_loop()

            def sync_work():
                time.sleep(1)  # runs on the executor, not the loop

            await loop.run_in_executor(None, sync_work)


        def plain():
            time.sleep(1)
    '''
    assert codes(tmp_path, "a.py", src, AsyncBlockingPass) == []


def test_a600_disable_marker(tmp_path):
    src = '''
        import time


        async def handler():
            time.sleep(0)  # lint: disable=A600
    '''
    assert codes(tmp_path, "a.py", src, AsyncBlockingPass) == []


# --- C900/C901 chaos schedules ------------------------------------------------


def test_c900_invalid_json(tmp_path):
    p = write(tmp_path, "bad.chaos.json", "{nope")
    out = ChaosSchedulePass().run_schedule(p, REPO)
    assert [f.code for f in out] == ["C900"]


def test_c901_schema_violation_and_negative(tmp_path):
    bad = write(tmp_path, "bad2.chaos.json", json.dumps({
        "seed": 1, "events": [{"at": 0.0, "kind": "not-a-fault"}]
    }))
    assert "C901" in [f.code for f in ChaosSchedulePass().run_schedule(bad, REPO)]
    good = sorted(REPO.rglob("*.chaos.json"))
    assert good, "repo should carry at least one chaos schedule"
    assert ChaosSchedulePass().run_schedule(good[0], REPO) == []


# --- C700/C701/C702 crash-point registry discipline ---------------------------


# The synthetic tree's canonical table: the pass AST-parses this file
# from the linted tree (never imports the real module).
C700_REGISTRY_SRC = '''
CRASH_POINTS = {
    "plugin.prepare.after_wal_started": "doc",
    "plugin.unprepare.after_teardown": "doc",
}
'''


def c700(tmp_path, rel, source):
    write(tmp_path, "tpu_dra/infra/crashpoint.py", C700_REGISTRY_SRC)
    ctx = FileContext(write(tmp_path, rel, source), tmp_path)
    return CrashPointPass().run_project([ctx], extra_paths=[ctx.path])


def test_c700_non_literal_name(tmp_path):
    src = '''
        from tpu_dra.infra.crashpoint import crashpoint


        def f(name):
            crashpoint(name)
    '''
    out = c700(tmp_path, "tpu_dra/plugin/scratch.py", src)
    assert [f.code for f in out] == ["C700"]


def test_c700_not_dotted_namespaced(tmp_path):
    # The name must read component.operation.site; a flat name gives the
    # matrix no way to group points by lifecycle phase.
    src = '''
        from tpu_dra.infra.crashpoint import crashpoint


        def f():
            crashpoint("justonename")
    '''
    out = c700(tmp_path, "tpu_dra/plugin/scratch.py", src)
    assert [f.code for f in out] == ["C700"]


def test_c700_unregistered_name(tmp_path):
    src = '''
        from tpu_dra.infra.crashpoint import crashpoint


        def f():
            crashpoint("plugin.prepare.never_registered_anywhere")
    '''
    out = c700(tmp_path, "tpu_dra/plugin/scratch.py", src)
    assert [f.code for f in out] == ["C700"]


def test_c701_duplicate_call_sites(tmp_path):
    # Registered name (real registry), threaded twice.
    src = '''
        from tpu_dra.infra.crashpoint import crashpoint


        def f():
            crashpoint("plugin.prepare.after_wal_started")


        def g():
            crashpoint("plugin.prepare.after_wal_started")
    '''
    out = c700(tmp_path, "tpu_dra/plugin/scratch.py", src)
    assert [f.code for f in out] == ["C701", "C701"]


def test_c700_negative_unique_registered_names(tmp_path):
    src = '''
        from tpu_dra.infra import crashpoint as cpt


        def f():
            cpt.crashpoint("plugin.prepare.after_wal_started")
            cpt.crashpoint("plugin.unprepare.after_teardown")
    '''
    assert c700(tmp_path, "tpu_dra/plugin/scratch.py", src) == []


def test_c700_tests_and_hack_trees_exempt(tmp_path):
    # Arming helpers in tests may spell crashpoint() freely; only
    # tpu_dra/ threads count as call sites.
    src = '''
        def crashpoint(name):
            return name


        crashpoint("whatever")
    '''
    assert c700(tmp_path, "tests/scratch.py", src) == []


def test_c702_registered_point_with_no_call_site(tmp_path):
    # Table registers two points, the tree threads one: the other is an
    # untested matrix row, filed against the (linted) registry module.
    registry = write(
        tmp_path, "tpu_dra/infra/crashpoint.py", C700_REGISTRY_SRC
    )
    caller = write(tmp_path, "tpu_dra/plugin/scratch.py", (
        "from tpu_dra.infra.crashpoint import crashpoint\n"
        "\n"
        "\n"
        "def f():\n"
        "    crashpoint('plugin.prepare.after_wal_started')\n"
    ))
    ctxs = [FileContext(caller, tmp_path), FileContext(registry, tmp_path)]
    out = CrashPointPass().run_project(ctxs, extra_paths=[caller, registry])
    c702 = [f for f in out if f.code == "C702"]
    assert len(c702) == 1, out
    assert "plugin.unprepare.after_teardown" in c702[0].message
    assert c702[0].path == registry


def test_c700_registry_parsed_from_linted_tree_not_import(tmp_path):
    """The table comes from the TREE under lint (AST), never from the
    importable tpu_dra: a name only the synthetic tree registers passes,
    and a name only the REAL module registers fails."""
    write(tmp_path, "tpu_dra/infra/crashpoint.py", (
        'CRASH_POINTS = {"synthetic.only.point": "doc"}\n'
    ))
    ok = FileContext(write(tmp_path, "tpu_dra/plugin/a.py", (
        "from tpu_dra.infra.crashpoint import crashpoint\n"
        "\n"
        "\n"
        "def f():\n"
        "    crashpoint('synthetic.only.point')\n"
    )), tmp_path)
    assert CrashPointPass().run_project([ok], extra_paths=[ok.path]) == []
    real_only = FileContext(write(tmp_path, "tpu_dra/plugin/b.py", (
        "from tpu_dra.infra.crashpoint import crashpoint\n"
        "\n"
        "\n"
        "def g():\n"
        "    crashpoint('checkpoint.write.before_tmp')\n"
    )), tmp_path)
    out = CrashPointPass().run_project(
        [real_only], extra_paths=[real_only.path]
    )
    assert [f.code for f in out] == ["C700"]


def test_c700_tree_without_registry_marks_all_unregistered(tmp_path):
    src = '''
        from tpu_dra.infra.crashpoint import crashpoint


        def f():
            crashpoint("plugin.prepare.after_wal_started")
    '''
    ctx = FileContext(
        write(tmp_path, "tpu_dra/plugin/scratch.py", src), tmp_path
    )
    out = CrashPointPass().run_project([ctx], extra_paths=[ctx.path])
    assert [f.code for f in out] == ["C700"]


def test_c700_disable_marker(tmp_path):
    src = '''
        from tpu_dra.infra.crashpoint import crashpoint


        def f(name):
            crashpoint(name)  # lint: disable=C700 (driven by the matrix)
    '''
    assert c700(tmp_path, "tpu_dra/plugin/scratch.py", src) == []


def test_c700_changed_only_keeps_cross_file_uniqueness(tmp_path):
    """A changed-only run linting one file must still see a duplicate
    call site living in an UNCHANGED file (via extra_paths), and report
    only on the linted file."""
    write(tmp_path, "tpu_dra/infra/crashpoint.py", C700_REGISTRY_SRC)
    linted = FileContext(write(tmp_path, "tpu_dra/plugin/a.py", (
        "from tpu_dra.infra.crashpoint import crashpoint\n"
        "\n"
        "\n"
        "def f():\n"
        "    crashpoint('plugin.prepare.after_wal_started')\n"
    )), tmp_path)
    unchanged = write(tmp_path, "tpu_dra/plugin/b.py", (
        "from tpu_dra.infra.crashpoint import crashpoint\n"
        "\n"
        "\n"
        "def g():\n"
        "    crashpoint('plugin.prepare.after_wal_started')\n"
    ))
    out = CrashPointPass().run_project(
        [linted], extra_paths=[linted.path, unchanged]
    )
    assert [f.code for f in out] == ["C701"]
    assert out[0].path == linted.path


# --- S800 bare time.sleep in driver layers ------------------------------------


def s800(tmp_path, rel, source):
    ctx = FileContext(write(tmp_path, rel, source), tmp_path)
    return [f.code for f in DriverSleepPass().run_project([ctx])]


def test_s800_bare_sleep_in_driver_layer_fires(tmp_path):
    src = '''
        import time


        def retry():
            time.sleep(0.5)
    '''
    assert s800(tmp_path, "tpu_dra/plugin/driver.py", src) == ["S800"]
    assert s800(tmp_path, "tpu_dra/k8sclient/rest.py", src) == ["S800"]
    assert s800(tmp_path, "tpu_dra/infra/flock.py", src) == ["S800"]
    assert s800(
        tmp_path, "tpu_dra/computedomain/cdplugin/driver.py", src
    ) == ["S800"]


def test_s800_from_import_alias_fires(tmp_path):
    src = '''
        from time import sleep as snooze


        def retry():
            snooze(1.0)
    '''
    assert s800(tmp_path, "tpu_dra/plugin/cleanup.py", src) == ["S800"]


def test_s800_module_import_alias_fires(tmp_path):
    src = '''
        import time as t


        def retry():
            t.sleep(0.5)
    '''
    assert s800(tmp_path, "tpu_dra/plugin/cleanup.py", src) == ["S800"]


def test_s800_negative_stop_aware_and_budgeted_waits(tmp_path):
    src = '''
        import threading

        from tpu_dra.infra import deadline


        def retry(stop: threading.Event):
            stop.wait(0.5)
            deadline.current().sleep(0.5, "retrying")
            deadline.current().pause(0.1)
    '''
    assert s800(tmp_path, "tpu_dra/plugin/driver.py", src) == []


def test_s800_exempt_layers_and_trees(tmp_path):
    src = '''
        import time


        def wait():
            time.sleep(1.0)
    '''
    # JAX payloads, the device stub, the minicluster, and CLI tools
    # sleep on purpose; tests/demo/hack are not driver code at all.
    assert s800(tmp_path, "tpu_dra/workloads/decode.py", src) == []
    assert s800(tmp_path, "tpu_dra/tpulib/stub.py", src) == []
    assert s800(tmp_path, "tpu_dra/minicluster/kubelet.py", src) == []
    assert s800(tmp_path, "tpu_dra/tools/doctor.py", src) == []
    assert s800(tmp_path, "tests/test_something.py", src) == []
    assert s800(tmp_path, "hack/tool.py", src) == []


def test_s800_disable_marker(tmp_path):
    src = '''
        import time


        def hold():
            time.sleep(0.05)  # lint: disable=S800 (injected fault hold)
    '''
    assert s800(tmp_path, "tpu_dra/k8sclient/fakeserver.py", src) == []


def test_s800_real_driver_layers_are_clean():
    """The live tree holds the invariant the pass enforces: no
    unannotated bare sleep anywhere in the driver spine."""
    ctxs = [
        FileContext(p, REPO)
        for layer in ("plugin", "computedomain", "k8sclient", "infra")
        for p in sorted((REPO / "tpu_dra" / layer).rglob("*.py"))
        if "/pb/" not in str(p)
    ]
    assert DriverSleepPass().run_project(ctxs) == []


# --- B100 bench schema --------------------------------------------------------


def _alloc_keys_literal():
    """The ISSUE-6 forward-required allocator keys, as dict-literal
    source the B100 fixtures splice in so they exercise exactly the
    rule under test."""
    from lints.benchkeys import REQUIRED_STATIC

    return ", ".join(f"'{k}': 0" for k in REQUIRED_STATIC)


def test_b100_dropped_key_fires_and_superset_passes(tmp_path):
    write(tmp_path, "BENCH_r01.json", json.dumps(
        {"parsed": {"keep": 1, "dropped": 2}}
    ))
    bench = write(tmp_path, "bench.py", (
        "import json\n"
        f"print(json.dumps({{'keep': 1, {_alloc_keys_literal()}}}))\n"
    ))
    out = BenchSchemaPass().run(FileContext(bench, tmp_path))
    assert [f.code for f in out] == ["B100"]
    assert "'dropped'" in out[0].message
    bench.write_text(
        "import json\nprint(json.dumps({'keep': 1, 'dropped': 2, "
        f"'new': 3, {_alloc_keys_literal()}}}))\n"
    )
    assert BenchSchemaPass().run(FileContext(bench, tmp_path)) == []


def test_b100_allocator_keys_required_even_without_artifact(tmp_path):
    """ISSUE 6/7: the allocator and serving-engine legs' headline keys
    are required in bench.py's final dict BEFORE any artifact records
    them — the superset rule alone would let a new leg be dropped
    unnoticed until the next recorded round."""
    from lints.benchkeys import REQUIRED_STATIC

    bench = write(tmp_path, "bench.py", (
        "import json\n"
        "print(json.dumps({'metric': 'x', 'alloc_p50_ms': 1.0}))\n"
    ))
    out = BenchSchemaPass().run(FileContext(bench, tmp_path))
    assert sorted(f.code for f in out) == (
        ["B100"] * (len(REQUIRED_STATIC) - 1)
    )
    missing = "".join(f.message for f in out)
    for key in ("alloc_p99_ms", "alloc_claims_per_s", "frag_score",
                "serve_tok_s", "serve_p50_ms", "serve_p99_ms"):
        assert f"'{key}'" in missing
    # With every required key present (and still no artifact): clean.
    bench.write_text(
        f"import json\nprint(json.dumps({{{_alloc_keys_literal()}}}))\n"
    )
    assert BenchSchemaPass().run(FileContext(bench, tmp_path)) == []


# --- baseline semantics -------------------------------------------------------


def _findings(path, n, code="R200"):
    return [Finding(path, i + 1, code, "x") for i in range(n)]


def test_baseline_suppresses_up_to_quota(tmp_path):
    bpath = write(tmp_path, "lint-baseline.json", json.dumps({
        "version": 1, "suppressions": {"pkg/a.py": {"R200": 2}}
    }))
    supp, probs = baseline_mod.load(bpath)
    assert probs == []
    target = tmp_path / "pkg" / "a.py"
    reported, suppressed = baseline_mod.apply(
        _findings(target, 2), supp, tmp_path, bpath
    )
    assert suppressed == 2 and reported == []


def test_baseline_overflow_reports_extra(tmp_path):
    bpath = write(tmp_path, "lint-baseline.json", json.dumps({
        "version": 1, "suppressions": {"pkg/a.py": {"R200": 1}}
    }))
    supp, _ = baseline_mod.load(bpath)
    target = tmp_path / "pkg" / "a.py"
    reported, suppressed = baseline_mod.apply(
        _findings(target, 3), supp, tmp_path, bpath
    )
    assert suppressed == 1 and len(reported) == 2


def test_baseline_partial_run_does_not_condemn_unlinted_entries(tmp_path):
    """A --changed-only / --select / single-file run must judge
    staleness only for entries it could have refilled."""
    bpath = write(tmp_path, "lint-baseline.json", json.dumps({
        "version": 1,
        "suppressions": {
            "pkg/linted.py": {"R200": 1},
            "pkg/untouched.py": {"R200": 1, "J300": 2},
        },
    }))
    write(tmp_path, "pkg/linted.py", "x = 1\n")
    write(tmp_path, "pkg/untouched.py", "x = 1\n")
    supp, _ = baseline_mod.load(bpath)
    # Only pkg/linted.py was linted this run; its quota went unspent.
    reported, _ = baseline_mod.apply(
        [], supp, tmp_path, bpath, linted_paths={"pkg/linted.py"}
    )
    assert [f.code for f in reported] == ["B901"]
    assert "pkg/linted.py:R200" in reported[0].message
    # Same run restricted to J300 only: the R200 quota is out of scope.
    reported, _ = baseline_mod.apply(
        [], supp, tmp_path, bpath,
        linted_paths={"pkg/linted.py", "pkg/untouched.py"},
        selected_codes={"J300"},
    )
    assert [f.code for f in reported] == ["B901"]
    assert "pkg/untouched.py:J300" in reported[0].message


def test_g400_nested_def_checked_once_with_def_site_gates(tmp_path):
    """Review regression: a callback defined under a gate check must
    inherit the def-site gates (no false positive), and an ungated
    nested call must be reported exactly once."""
    gated_ok = '''
        from tpu_dra.infra import featuregates as fg
        from tpu_dra.plugin.remediation import RemediationController


        def outer(informer):
            if fg.enabled(fg.AUTO_REMEDIATION):
                def cb():
                    return RemediationController()

                informer.add_handler(cb)
    '''
    assert g400(tmp_path, gated_ok) == []
    ungated_nested = '''
        from tpu_dra.plugin.remediation import RemediationController


        def outer(informer):
            def cb():
                return RemediationController()

            informer.add_handler(cb)
    '''
    assert g400(tmp_path, ungated_nested) == ["G400"]


def test_g400_discovers_gated_module_outside_linted_set(tmp_path):
    """Review regression: a changed-only run that lints a caller but
    not the gated module must still see the module's gate marker (via
    run_project's extra_paths)."""
    gated_path = write(tmp_path, "tpu_dra/plugin/remediation.py",
                       GATED_MODULE)
    caller_src = '''
        from tpu_dra.plugin.remediation import RemediationController


        def build():
            return RemediationController()
    '''
    caller = FileContext(
        write(tmp_path, "tpu_dra/plugin/driver.py", caller_src), tmp_path
    )
    # Only the caller is in the linted set; the gated module arrives
    # through extra_paths (the full discovery list).
    out = GateDominancePass().run_project(
        [caller], extra_paths=[gated_path]
    )
    assert [f.code for f in out] == ["G400"]


def test_baseline_entry_for_deleted_file_is_stale_even_on_partial_run(
    tmp_path,
):
    """Review regression: a quota for a file that no longer exists can
    never be refilled — B901 on every run, partial or not."""
    bpath = write(tmp_path, "lint-baseline.json", json.dumps({
        "version": 1, "suppressions": {"pkg/deleted.py": {"R200": 3}}
    }))
    supp, _ = baseline_mod.load(bpath)
    reported, _ = baseline_mod.apply(
        [], supp, tmp_path, bpath, linted_paths={"pkg/other.py"}
    )
    assert [f.code for f in reported] == ["B901"]
    assert "no longer exists" in reported[0].message
    # But an entry for an existing, merely-unlinted file stays quiet.
    write(tmp_path, "pkg/alive.py", "x = 1\n")
    bpath.write_text(json.dumps({
        "version": 1, "suppressions": {"pkg/alive.py": {"R200": 1}}
    }))
    supp, _ = baseline_mod.load(bpath)
    reported, _ = baseline_mod.apply(
        [], supp, tmp_path, bpath, linted_paths={"pkg/other.py"}
    )
    assert reported == []


def test_a600_nested_async_def_reported_once(tmp_path):
    src = '''
        import time


        async def outer():
            async def inner():
                time.sleep(1)

            return inner
    '''
    assert codes(tmp_path, "a.py", src, AsyncBlockingPass) == ["A600"]


def test_baseline_stale_entry_fails_b901(tmp_path):
    bpath = write(tmp_path, "lint-baseline.json", json.dumps({
        "version": 1, "suppressions": {"pkg/a.py": {"R200": 2}}
    }))
    supp, _ = baseline_mod.load(bpath)
    reported, suppressed = baseline_mod.apply([], supp, tmp_path, bpath)
    assert [f.code for f in reported] == ["B901"]


def test_baseline_unbaselinable_codes_rejected_b900(tmp_path):
    bpath = write(tmp_path, "lint-baseline.json", json.dumps({
        "version": 1, "suppressions": {"pkg/a.py": {"E999": 1}}
    }))
    supp, probs = baseline_mod.load(bpath)
    assert supp == {} and [f.code for f in probs] == ["B900"]


def test_baseline_malformed_b900(tmp_path):
    bpath = write(tmp_path, "lint-baseline.json", "{nope")
    supp, probs = baseline_mod.load(bpath)
    assert supp == {} and [f.code for f in probs] == ["B900"]


def _git(repo, *args):
    return subprocess.run(
        ["git", "-C", str(repo), *args], capture_output=True, text=True
    )


@pytest.fixture
def git_repo(tmp_path):
    if _git(tmp_path, "init").returncode != 0:
        pytest.skip("git unavailable")
    _git(tmp_path, "config", "user.email", "t@t")
    _git(tmp_path, "config", "user.name", "t")
    return tmp_path


def test_baseline_growth_vs_head_fails_b902(git_repo):
    bpath = write(git_repo, "hack/lint-baseline.json", json.dumps({
        "version": 1, "suppressions": {"pkg/a.py": {"R200": 1}}
    }))
    _git(git_repo, "add", "-A")
    assert _git(git_repo, "commit", "-m", "seed").returncode == 0
    # Same counts: clean.
    supp, _ = baseline_mod.load(bpath)
    assert baseline_mod.check_growth_vs_head(supp, git_repo, bpath) == []
    # Grown count and a brand-new entry: both fail.
    bpath.write_text(json.dumps({
        "version": 1,
        "suppressions": {"pkg/a.py": {"R200": 2}, "pkg/b.py": {"J300": 1}},
    }))
    supp, _ = baseline_mod.load(bpath)
    out = baseline_mod.check_growth_vs_head(supp, git_repo, bpath)
    assert [f.code for f in out] == ["B902", "B902"]
    # Shrunk: clean.
    bpath.write_text(json.dumps({"version": 1, "suppressions": {}}))
    supp, _ = baseline_mod.load(bpath)
    assert baseline_mod.check_growth_vs_head(supp, git_repo, bpath) == []


def test_committed_baseline_only_shrinks_vs_head():
    """The checked-in baseline must never grow relative to HEAD — the
    linter enforces it at runtime (B902); this pins it in CI too."""
    bpath = REPO / "hack" / "lint-baseline.json"
    assert bpath.exists(), "hack/lint-baseline.json must be checked in"
    supp, probs = baseline_mod.load(bpath)
    assert probs == []
    blob = _git(REPO, "show", "HEAD:hack/lint-baseline.json")
    if blob.returncode != 0:
        return  # first landing: nothing to compare against
    head = json.loads(blob.stdout).get("suppressions") or {}
    for fk, codes_ in supp.items():
        for code, count in codes_.items():
            assert count <= head.get(fk, {}).get(code, 0), (
                f"baseline grew for {fk}:{code} — the baseline only shrinks"
            )


# --- CLI integration ----------------------------------------------------------


def test_cli_reports_findings_and_exits_1(tmp_path, capsys):
    p = write(tmp_path, "scratch.py", "import os\n")
    rc = lint_main([str(p), "--no-baseline"])
    out = capsys.readouterr()
    assert rc == 1
    assert f"{p}:1: F401 'os' imported but unused" in out.out
    assert "lint: pass core" in out.err
    assert "finding(s)" in out.err


def test_cli_clean_file_exits_0(tmp_path, capsys):
    p = write(tmp_path, "scratch.py", "import os\nprint(os.sep)\n")
    rc = lint_main([str(p), "--no-baseline"])
    capsys.readouterr()
    assert rc == 0


def test_cli_select_runs_only_named_passes(tmp_path, capsys):
    p = write(tmp_path, "scratch.py", "import os\n")
    rc = lint_main([str(p), "--no-baseline", "--select", "R200"])
    out = capsys.readouterr()
    assert rc == 0  # F401 pass not selected
    assert "pass core" not in out.err and "pass R200" in out.err


def test_cli_baseline_suppresses_then_b901_when_stale(tmp_path, capsys):
    p = write(tmp_path, "scratch.py", "import os\n")
    rel = p.resolve().relative_to(REPO).as_posix() if str(p).startswith(
        str(REPO)
    ) else p.as_posix()
    bpath = write(tmp_path, "baseline.json", json.dumps({
        "version": 1, "suppressions": {rel: {"F401": 1}}
    }))
    rc = lint_main([str(p), "--baseline", str(bpath)])
    out = capsys.readouterr()
    assert rc == 0 and "baselined" in out.err
    # Fix the finding but keep the entry: stale -> B901, exit 1.
    p.write_text("import os\nprint(os.sep)\n")
    rc = lint_main([str(p), "--baseline", str(bpath)])
    out = capsys.readouterr()
    assert rc == 1 and "B901" in out.out


def test_cli_synthetic_violations_of_every_new_code(tmp_path, capsys):
    """Acceptance criterion: seeding a synthetic violation of each new
    code makes `lint` exit 1 with path:line: CODE message."""
    seeds = {
        "F821": ("scratch_f821.py", "def f():\n    return typo_name\n"),
        "R200": ("scratch_r200.py", textwrap.dedent(R200_POSITIVE)),
        "J300": (
            "tpu_dra/workloads/scratch_j300.py",
            "import jax\nimport jax.numpy as jnp\n\n\n@jax.jit\n"
            "def f(x):\n    return float(jnp.sum(x))\n",
        ),
        "L500": (
            "tpu_dra/tpulib/scratch_l500.py",
            "from tpu_dra.plugin.driver import Driver\nprint(Driver)\n",
        ),
        "A600": (
            "scratch_a600.py",
            "import time\n\n\nasync def f():\n    time.sleep(1)\n",
        ),
    }
    for code, (rel, src) in seeds.items():
        p = write(tmp_path, rel, src)
        rc = lint_main([str(p), "--no-baseline", "--select", code])
        out = capsys.readouterr()
        assert rc == 1, f"{code} did not fail the run"
        lines = [l for l in out.out.splitlines() if f": {code} " in l]
        assert lines and lines[0].startswith(f"{p}:"), (code, out.out)
        lineno_part = lines[0].split(f": {code} ")[0][len(str(p)) + 1:]
        assert lineno_part.isdigit(), lines[0]


def test_cli_g400_synthetic_violation_against_real_tree(tmp_path, capsys):
    """G400 is project-scoped (needs the gated module in the same run):
    lint the real remediation module plus a synthetic undominated
    caller placed under tpu_dra/."""
    caller = write(tmp_path, "scratch_g400.py", (
        "from tpu_dra.plugin.remediation import RemediationController\n"
        "\n"
        "\n"
        "def build(state, backend):\n"
        "    return RemediationController(state, backend)\n"
    ))
    rc = lint_main([
        str(REPO / "tpu_dra" / "plugin" / "remediation.py"),
        str(caller), "--no-baseline", "--select", "G400",
    ])
    out = capsys.readouterr()
    assert rc == 1
    assert any(
        l.startswith(f"{caller}:5: G400 ") for l in out.out.splitlines()
    ), out.out


# --- T900/T901/T902 span-name registry discipline -----------------------------


# The synthetic tree's canonical table (the pass AST-parses the trace
# module out of the linted tree, never imports the real one).
T900_REGISTRY_SRC = """
SPAN_NAMES = {
    "scheduler.claim.pending": ("scheduler", "", "doc"),
    "plugin.claim.prepare": ("plugin", "scheduler.claim.pending", "doc"),
}
"""


def t900(tmp_path, rel, source):
    write(tmp_path, "tpu_dra/infra/trace.py", T900_REGISTRY_SRC)
    ctx = FileContext(write(tmp_path, rel, source), tmp_path)
    return SpanNamePass().run_project([ctx], extra_paths=[ctx.path])


def test_t900_non_literal_name(tmp_path):
    src = """
        from tpu_dra.infra import trace


        def f(name):
            with trace.span(name):
                pass
    """
    out = t900(tmp_path, "tpu_dra/plugin/scratch.py", src)
    assert [f.code for f in out] == ["T900"]


def test_t900_not_dotted_namespaced(tmp_path):
    src = """
        from tpu_dra.infra import trace


        def f():
            trace.record_span("flatname", 0.0, 1.0)
    """
    out = t900(tmp_path, "tpu_dra/plugin/scratch.py", src)
    assert [f.code for f in out] == ["T900"]


def test_t900_unregistered_name(tmp_path):
    src = """
        from tpu_dra.infra import trace


        def f():
            with trace.span("plugin.claim.never_registered"):
                pass
    """
    out = t900(tmp_path, "tpu_dra/plugin/scratch.py", src)
    assert [f.code for f in out] == ["T900"]


def test_t901_duplicate_call_sites(tmp_path):
    src = """
        from tpu_dra.infra import trace


        def f():
            with trace.span("plugin.claim.prepare"):
                pass


        def g():
            trace.record_span("plugin.claim.prepare", 0.0, 1.0)
    """
    out = t900(tmp_path, "tpu_dra/plugin/scratch.py", src)
    assert [f.code for f in out] == ["T901", "T901"]


def test_t900_negative_unique_registered_names(tmp_path):
    src = """
        from tpu_dra.infra import trace


        def f():
            with trace.span("scheduler.claim.pending", root=True):
                trace.record_span("plugin.claim.prepare", 0.0, 1.0)
    """
    assert t900(tmp_path, "tpu_dra/plugin/scratch.py", src) == []


def test_t900_tests_tree_exempt(tmp_path):
    src = """
        from tpu_dra.infra import trace


        def drive():
            with trace.span("whatever"):
                pass
    """
    assert t900(tmp_path, "tests/scratch.py", src) == []


def test_t902_registered_span_with_no_call_site(tmp_path):
    registry = write(tmp_path, "tpu_dra/infra/trace.py", T900_REGISTRY_SRC)
    caller = write(tmp_path, "tpu_dra/plugin/scratch.py", (
        "from tpu_dra.infra import trace\n"
        "def f():\n"
        "    with trace.span('scheduler.claim.pending'):\n"
        "        pass\n"
    ))
    ctxs = [FileContext(registry, tmp_path), FileContext(caller, tmp_path)]
    out = SpanNamePass().run_project(ctxs, extra_paths=[c.path for c in ctxs])
    assert [f.code for f in out] == ["T902"]
    assert "plugin.claim.prepare" in out[0].message


def test_t900_real_tree_is_clean_and_bijective():
    """The live tree: every SPAN_NAMES entry threaded exactly once,
    every call site literal+registered (the taxonomy table in
    docs/observability.md mirrors SPAN_NAMES)."""
    files = sorted((REPO / "tpu_dra").rglob("*.py"))
    ctxs = [FileContext(p, REPO) for p in files]
    assert SpanNamePass().run_project(ctxs, extra_paths=[]) == []


# --- D800-D803 lockdep (lock order + thread ownership) ----------------------

from lints.lockdep import LockdepPass  # noqa: E402


def d80x(tmp_path, sources):
    """Run the project-scope lockdep pass over {relpath: source}
    fixtures rooted at tmp_path (so `tpu_dra/...` paths get product
    module names)."""
    ctxs = [
        FileContext(write(tmp_path, rel, src), tmp_path)
        for rel, src in sources.items()
    ]
    return LockdepPass().run_project(ctxs, extra_paths=[c.path for c in ctxs])


def d80x_codes(tmp_path, src, rel="tpu_dra/serving/fix.py"):
    return [f.code for f in d80x(tmp_path, {rel: src})]


D800_CYCLE_SRC = """
    import threading


    class A:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._b:
                with self._a:
                    pass
"""


def test_d800_lock_order_cycle_fires(tmp_path):
    out = d80x(tmp_path, {"tpu_dra/serving/fix.py": D800_CYCLE_SRC})
    assert [f.code for f in out] == ["D800"]
    # The finding names BOTH locks and a witness site per direction.
    assert "A._a" in out[0].message and "A._b" in out[0].message


def test_d800_negative_consistent_order(tmp_path):
    src = """
        import threading


        class A:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
    """
    assert d80x_codes(tmp_path, src) == []


def test_d800_interprocedural_cycle_through_helper(tmp_path):
    """one() holds _a and calls helper() which takes _b; two() nests
    the other way around — the edge comes from following the call."""
    src = """
        import threading


        class A:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def helper(self):
                with self._b:
                    pass

            def one(self):
                with self._a:
                    self.helper()

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """
    assert d80x_codes(tmp_path, src) == ["D800"]


def test_d800_trylock_takes_no_edge(tmp_path):
    """A non-blocking acquire cannot deadlock-wait: it must not
    contribute an ordering edge (but a consistent-order nesting on the
    other side stays clean too)."""
    src = """
        import threading


        class A:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    if self._b.acquire(blocking=False):
                        self._b.release()

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """
    assert d80x_codes(tmp_path, src) == []


def test_d801_blocking_call_under_lock_fires(tmp_path):
    src = """
        import threading
        import time


        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                with self._lock:
                    time.sleep(1.0)
    """
    assert d80x_codes(tmp_path, src) == ["D801"]


def test_d801_negative_sleep_outside_lock(tmp_path):
    src = """
        import threading
        import time


        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                with self._lock:
                    x = 1
                time.sleep(1.0)
                return x
    """
    assert d80x_codes(tmp_path, src) == []


def test_d801_interprocedural_blocking_reported_at_call_site(tmp_path):
    """The lock is held in f(); the sleep lives in helper(). The report
    lands where the lock first becomes held, with the via chain."""
    src = """
        import threading
        import time


        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def helper(self):
                time.sleep(0.5)

            def f(self):
                with self._lock:
                    self.helper()
    """
    out = d80x(tmp_path, {"tpu_dra/serving/fix.py": D800_CYCLE_SRC and src})
    assert [f.code for f in out] == ["D801"]
    assert "helper" in out[0].message


def test_d801_condition_wait_on_held_condition_exempt(tmp_path):
    """cond.wait() RELEASES the lock it waits on — the canonical
    pattern must not be flagged."""
    src = """
        import threading


        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)

            def f(self):
                with self._cond:
                    self._cond.wait(timeout=1.0)
    """
    assert d80x_codes(tmp_path, src) == []


def test_d801_condition_wait_still_fires_for_other_held_lock(tmp_path):
    """wait() releases ITS lock, not every lock the thread holds."""
    src = """
        import threading


        class A:
            def __init__(self):
                self._other = threading.Lock()
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)

            def f(self):
                with self._other:
                    with self._cond:
                        self._cond.wait(timeout=1.0)
    """
    assert d80x_codes(tmp_path, src) == ["D801"]


def test_d801_origin_disable_silences_lifted_reports(tmp_path):
    """A disable on the deliberately-blocking primitive line silences
    every interprocedurally-lifted report of it (the flock poll idiom)."""
    src = """
        import threading
        import time


        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def helper(self):
                time.sleep(0.5)  # lint: disable=D801 (bounded poll)

            def f(self):
                with self._lock:
                    self.helper()
    """
    assert d80x_codes(tmp_path, src) == []


D802_SRC = """
    import threading


    class A:
        def __init__(self):
            self.state = 0  # thread: control
            self._spawned = threading.Thread(target=self.worker)

        def poll(self):  # thread: control
            self.state += 1

        def worker(self):  # thread: worker
            self.state = 2
"""


def test_d802_wrong_thread_attr_touch_fires(tmp_path):
    out = d80x(tmp_path, {"tpu_dra/serving/fix.py": D802_SRC})
    assert [f.code for f in out] == ["D802"]
    assert "control" in out[0].message and "worker" in out[0].message


def test_d802_negative_same_domain(tmp_path):
    src = """
        class A:
            def __init__(self):
                self.state = 0  # thread: control

            def poll(self):  # thread: control
                self.state += 1

            def tick(self):  # thread: control
                self.state = 0
    """
    assert d80x_codes(tmp_path, src) == []


def test_d802_unannotated_caller_of_domain_method_fires(tmp_path):
    """Enforcement is opt-in per class, but once on, completeness is
    forced: an unannotated method calling a domain-only one is flagged."""
    src = """
        class A:
            def poll(self):  # thread: control
                pass

            def entry(self):
                self.poll()
    """
    out = d80x(tmp_path, {"tpu_dra/serving/fix.py": src})
    assert [f.code for f in out] == ["D802"]
    assert "entry" in out[0].message


def test_d802_any_method_touching_owned_state_fires(tmp_path):
    """`any` is a claim of thread-safety: touching single-domain state
    from it is exactly the violation the annotation would hide."""
    src = """
        class A:
            def __init__(self):
                self.state = 0  # thread: control

            def poll(self):  # thread: control
                self.state += 1

            def status(self):  # thread: any (lock-free read... not!)
                self.state = -1
    """
    assert d80x_codes(tmp_path, src) == ["D802"]


def test_d802_private_methods_inherit_caller_domain(tmp_path):
    src = """
        class A:
            def __init__(self):
                self.state = 0  # thread: control

            def poll(self):  # thread: control
                self._step()

            def _step(self):
                self.state += 1
    """
    assert d80x_codes(tmp_path, src) == []


def test_d803_stale_attr_annotation_fires(tmp_path):
    src = """
        class A:
            def __init__(self):
                self.state = 0  # thread: control

            def poll(self):  # thread: control
                pass
    """
    out = d80x(tmp_path, {"tpu_dra/serving/fix.py": src})
    assert [f.code for f in out] == ["D803"]
    assert "state" in out[0].message


def test_d803_malformed_marker_fires(tmp_path):
    src = """
        class A:
            def poll(self):  # thread: !!!
                pass
    """
    assert d80x_codes(tmp_path, src) == ["D803"]


def test_d803_misplaced_marker_fires(tmp_path):
    src = """
        class A:
            def poll(self):
                x = 1  # thread: control
                return x
    """
    assert d80x_codes(tmp_path, src) == ["D803"]


def test_d803_negative_prose_mention_in_docstring(tmp_path):
    src = '''
        class A:
            """Annotate methods with ``# thread: control`` to pin them."""

            def poll(self):
                pass
    '''
    assert d80x_codes(tmp_path, src) == []


def test_d80x_real_tree_is_clean():
    """The live tree carries no lock-order cycles, no blocking calls
    under locks, and no ownership violations — with an EMPTY baseline."""
    files = sorted((REPO / "tpu_dra").rglob("*.py"))
    files = [f for f in files if "/pb/" not in str(f)]
    ctxs = [FileContext(p, REPO) for p in files]
    assert LockdepPass().run_project(ctxs, extra_paths=files) == []


def test_d80x_dot_graph_emits_nodes_and_edges():
    files = sorted((REPO / "tpu_dra").rglob("*.py"))
    files = [f for f in files if "/pb/" not in str(f)]
    ctxs = [FileContext(p, REPO) for p in files]
    p = LockdepPass()
    list(p.run_project(ctxs, extra_paths=files))
    dot = p.dot()
    assert "digraph lock_order {" in dot
    assert "Metrics._lock" in dot
    # The well-known Router._lock -> Metrics._lock edge (closed static
    # blind spot: found by runtime divergence, see hack/lockdep_diff.py)
    assert '"serving.router.Router._lock" -> "infra.metrics.Metrics._lock"' \
        in dot


# --- R200 extension: explicit acquire/release + D802 deference --------------


def test_r200_explicit_acquire_release_region_is_locked(tmp_path):
    """The acquire(); try: ... finally: release() idiom counts as a
    locked region — previously only `with` did."""
    src = """
        import threading


        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.shared = 0
                threading.Thread(target=self.b).start()

            def a(self):
                self._lock.acquire()
                try:
                    self.shared = 1
                finally:
                    self._lock.release()

            def b(self):
                with self._lock:
                    self.shared = 2
    """
    assert codes(tmp_path, "c.py", src, RaceLintPass) == []


def test_r200_write_after_release_still_fires(tmp_path):
    src = """
        import threading


        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.shared = 0
                threading.Thread(target=self.b).start()

            def a(self):
                self._lock.acquire()
                self._lock.release()
                self.shared = 1

            def b(self):
                with self._lock:
                    self.shared = 2
    """
    assert codes(tmp_path, "c.py", src, RaceLintPass) == ["R200"]


def test_r200_defers_to_d802_domain_annotated_methods(tmp_path):
    """Attrs written only from methods pinned to ONE thread domain are
    single-writer by enforced (D802) contract: no lock demanded, no
    double-report."""
    src = """
        import threading


        class C:
            def __init__(self):
                self.shared = 0
                threading.Thread(target=self.b).start()

            def a(self):  # thread: control
                self.shared = 1

            def b(self):  # thread: control
                self.shared = 2
    """
    assert codes(tmp_path, "c.py", src, RaceLintPass) == []


def test_r200_mixed_domain_writers_still_fire(tmp_path):
    src = """
        import threading


        class C:
            def __init__(self):
                self.shared = 0
                threading.Thread(target=self.b).start()

            def a(self):  # thread: control
                self.shared = 1

            def b(self):
                self.shared = 2
    """
    assert codes(tmp_path, "c.py", src, RaceLintPass) == ["R200", "R200"]


def test_r200_defers_to_d802_domain_annotated_attr(tmp_path):
    src = """
        import threading


        class C:
            def __init__(self):
                self.shared = 0  # thread: control
                threading.Thread(target=self.b).start()

            def a(self):
                self.shared = 1

            def b(self):
                self.shared = 2
    """
    assert codes(tmp_path, "c.py", src, RaceLintPass) == []
