"""Metrics registry: prometheus text rendering + summary quantiles."""

from tpu_dra.infra.metrics import TIMING_WINDOW, Metrics


def test_counters_gauges_render():
    m = Metrics()
    m.inc("prepare_total")
    m.inc("prepare_total")
    m.set_gauge("allocatable_devices", 4, labels={"node": "n0"})
    text = m.render()
    assert "tpu_dra_prepare_total 2.0" in text
    assert 'tpu_dra_allocatable_devices{node="n0"} 4' in text


def test_summary_quantiles_rendered():
    m = Metrics()
    for i in range(100):
        m.observe("prepare_seconds", (i + 1) / 1000.0)
    assert abs(m.quantile("prepare_seconds", 0.5) - 0.050) < 0.002
    assert abs(m.quantile("prepare_seconds", 0.99) - 0.099) < 0.002
    text = m.render()
    assert 'tpu_dra_prepare_seconds{quantile="0.5"}' in text
    assert 'tpu_dra_prepare_seconds{quantile="0.9"}' in text
    assert 'tpu_dra_prepare_seconds{quantile="0.99"}' in text
    assert "tpu_dra_prepare_seconds_count 100" in text


def test_timing_window_bounded():
    m = Metrics()
    for i in range(TIMING_WINDOW + 500):
        m.observe("t", float(i))
    assert len(m._timing_recent[("t", ())]) == TIMING_WINDOW
    # Quantiles reflect the recent window (old observations dropped).
    assert m.quantile("t", 0.0) == 500.0
    # Cumulative sum/count keep the full history.
    assert m._timing_count[("t", ())] == TIMING_WINDOW + 500


def test_quantile_empty_series():
    assert Metrics().quantile("nope", 0.5) is None


def test_scrape_time_collector_refreshes_gauges():
    m = Metrics()
    state = {"n": 0}

    def collect():
        state["n"] += 1
        m.set_gauge("collected", state["n"])

    m.register_collector(collect)
    assert "tpu_dra_collected 1" in m.render()
    assert "tpu_dra_collected 2" in m.render()  # re-collected per scrape


def test_failing_collector_does_not_break_scrape():
    m = Metrics()
    m.inc("ok_counter")
    m.register_collector(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    assert "ok_counter 1" in m.render()


def test_remove_gauges_drops_label_superset_series():
    """remove_gauges(name, match) drops every series whose labels
    CONTAIN the match — the cleanup for per-entity histogram-bucket
    families whose extra `le` label the caller cannot enumerate
    (exact-key remove_gauge leaks them forever under entity churn)."""
    from tpu_dra.infra.metrics import Metrics

    m = Metrics()
    for le in ("0.1", "1", "+Inf"):
        m.set_gauge(
            "lease_wait_bucket", 1.0, {"claim": "dead", "le": le}
        )
        m.set_gauge(
            "lease_wait_bucket", 2.0, {"claim": "live", "le": le}
        )
    m.remove_gauges("lease_wait_bucket", {"claim": "dead"})
    out = m.render()
    assert 'claim="dead"' not in out
    assert out.count('claim="live"') == 3
