"""Metrics registry: prometheus text rendering + summary quantiles."""

from tpu_dra.infra.metrics import TIMING_WINDOW, Metrics


def test_counters_gauges_render():
    m = Metrics()
    m.inc("prepare_total")
    m.inc("prepare_total")
    m.set_gauge("allocatable_devices", 4, labels={"node": "n0"})
    text = m.render()
    assert "tpu_dra_prepare_total 2.0" in text
    assert 'tpu_dra_allocatable_devices{node="n0"} 4' in text


def test_summary_quantiles_rendered():
    m = Metrics()
    for i in range(100):
        m.observe("prepare_seconds", (i + 1) / 1000.0)
    assert abs(m.quantile("prepare_seconds", 0.5) - 0.050) < 0.002
    assert abs(m.quantile("prepare_seconds", 0.99) - 0.099) < 0.002
    text = m.render()
    assert 'tpu_dra_prepare_seconds{quantile="0.5"}' in text
    assert 'tpu_dra_prepare_seconds{quantile="0.9"}' in text
    assert 'tpu_dra_prepare_seconds{quantile="0.99"}' in text
    assert "tpu_dra_prepare_seconds_count 100" in text


def test_timing_window_bounded():
    m = Metrics()
    for i in range(TIMING_WINDOW + 500):
        m.observe("t", float(i))
    assert len(m._timing_recent[("t", ())]) == TIMING_WINDOW
    # Quantiles reflect the recent window (old observations dropped).
    assert m.quantile("t", 0.0) == 500.0
    # Cumulative sum/count keep the full history.
    assert m._timing_count[("t", ())] == TIMING_WINDOW + 500


def test_quantile_empty_series():
    assert Metrics().quantile("nope", 0.5) is None


def test_scrape_time_collector_refreshes_gauges():
    m = Metrics()
    state = {"n": 0}

    def collect():
        state["n"] += 1
        m.set_gauge("collected", state["n"])

    m.register_collector(collect)
    assert "tpu_dra_collected 1" in m.render()
    assert "tpu_dra_collected 2" in m.render()  # re-collected per scrape


def test_failing_collector_does_not_break_scrape():
    m = Metrics()
    m.inc("ok_counter")
    m.register_collector(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    assert "ok_counter 1" in m.render()


def test_remove_gauges_drops_label_superset_series():
    """remove_gauges(name, match) drops every series whose labels
    CONTAIN the match — the cleanup for per-entity histogram-bucket
    families whose extra `le` label the caller cannot enumerate
    (exact-key remove_gauge leaks them forever under entity churn)."""
    from tpu_dra.infra.metrics import Metrics

    m = Metrics()
    for le in ("0.1", "1", "+Inf"):
        m.set_gauge(
            "lease_wait_bucket", 1.0, {"claim": "dead", "le": le}
        )
        m.set_gauge(
            "lease_wait_bucket", 2.0, {"claim": "live", "le": le}
        )
    m.remove_gauges("lease_wait_bucket", {"claim": "dead"})
    out = m.render()
    assert 'claim="dead"' not in out
    assert out.count('claim="live"') == 3


# --- label-value escaping (ISSUE 13 satellite) ------------------------------


def test_render_escapes_hostile_label_values():
    """Claim names carrying quotes/backslashes/newlines must emit VALID
    exposition lines — one hostile label used to poison the whole
    scrape. Round-trip: parse the rendered line back and recover the
    original value."""
    m = Metrics()
    hostile = 'claim-"quoted"\\back\nslash'
    m.set_gauge("per_claim", 1.0, labels={"claim": hostile})
    line = next(
        ln for ln in m.render().splitlines()
        if ln.startswith("tpu_dra_per_claim{")
    )
    # A valid exposition line is one physical line: name{k="v"} value.
    assert "\n" not in line
    body = line.split("{", 1)[1].rsplit("}", 1)[0]
    assert body.startswith('claim="') and body.endswith('"')
    escaped = body[len('claim="'):-1]
    # Unescape per the Prometheus text-format rules and recover the
    # original hostile value exactly.
    out, i = [], 0
    while i < len(escaped):
        ch = escaped[i]
        if ch == "\\" and i + 1 < len(escaped):
            nxt = escaped[i + 1]
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, nxt))
            i += 2
        else:
            assert ch != '"', "unescaped quote inside a label value"
            out.append(ch)
            i += 1
    assert "".join(out) == hostile


# --- cardinality guard (ISSUE 13 satellite) ---------------------------------


def test_series_cap_refuses_unbounded_growth():
    m = Metrics(series_cap=3)
    for i in range(10):
        m.set_gauge("per_claim", 1.0, labels={"claim": f"c{i}"})
    text = m.render()
    # Exactly the cap's worth of series exist; the overflow landed in
    # the guard counter keyed by the offending NAME.
    assert text.count("tpu_dra_per_claim{") == 3
    assert (
        m.get_counter("metrics_series_capped_total",
                      labels={"name": "per_claim"}) == 7
    )
    # Existing series still update past the cap.
    m.set_gauge("per_claim", 9.0, labels={"claim": "c0"})
    assert m.get_gauge("per_claim", labels={"claim": "c0"}) == 9.0


def test_series_cap_applies_to_counters_and_timings():
    m = Metrics(series_cap=2)
    for i in range(4):
        m.inc("per_req", labels={"rid": f"r{i}"})
        m.observe("per_req_seconds", 0.01, labels={"rid": f"r{i}"})
    assert (
        m.get_counter("metrics_series_capped_total",
                      labels={"name": "per_req"}) == 2
    )
    assert (
        m.get_counter("metrics_series_capped_total",
                      labels={"name": "per_req_seconds"}) == 2
    )


def test_series_cap_frees_slots_on_gauge_removal():
    """remove_gauge/remove_gauges give their slots back: per-entity
    cleanup (the PR-12 dead-claim series removal) keeps a churning
    fleet under the cap forever."""
    m = Metrics(series_cap=2)
    m.set_gauge("per_claim", 1.0, labels={"claim": "a"})
    m.set_gauge("per_claim", 1.0, labels={"claim": "b"})
    m.remove_gauge("per_claim", labels={"claim": "a"})
    m.set_gauge("per_claim", 1.0, labels={"claim": "c"})
    assert m.get_gauge("per_claim", labels={"claim": "c"}) == 1.0
    assert (
        m.get_counter("metrics_series_capped_total",
                      labels={"name": "per_claim"}) == 0
    )
    m.remove_gauges("per_claim", {"claim": "b"})
    m.set_gauge("per_claim", 1.0, labels={"claim": "d"})
    assert m.get_gauge("per_claim", labels={"claim": "d"}) == 1.0


# --- exposition TYPE lines (ISSUE 14 satellite) ------------------------------


def test_type_lines_emitted_once_per_family():
    """One `# TYPE` line per metric NAME, not per labeled series (the
    exposition format forbids repeats, and the fleetmon parser
    classifies series from these lines)."""
    m = Metrics()
    m.inc("writes_total", labels={"node": "a"})
    m.inc("writes_total", labels={"node": "b"})
    m.set_gauge("depth", 1.0, labels={"shard": "0"})
    m.set_gauge("depth", 2.0, labels={"shard": "1"})
    m.observe("lat_seconds", 0.1, labels={"shard": "0"})
    m.observe("lat_seconds", 0.2, labels={"shard": "1"})
    text = m.render()
    assert text.count("# TYPE tpu_dra_writes_total counter") == 1
    assert text.count("# TYPE tpu_dra_depth gauge") == 1
    assert text.count("# TYPE tpu_dra_lat_seconds summary") == 1
    # Each family's TYPE line precedes its first series line.
    lines = text.splitlines()
    for family in ("writes_total", "depth", "lat_seconds"):
        first_series = next(
            i for i, ln in enumerate(lines)
            if ln.startswith(f"tpu_dra_{family}")
        )
        assert lines[first_series - 1].startswith(
            f"# TYPE tpu_dra_{family} "
        )
