"""``python -m tpu_dra.minicluster`` — bring up the kind-analog cluster.

Prints one ready line with the base dir and apiserver URL, then serves
until SIGTERM/SIGINT. hack/run-bats.sh uses this to execute the bats
suites; ``--nodes`` controls the simulated TPU host count (default 2 =
one 2x2x2 v5p slice, 4 chips per host).
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from tpu_dra.minicluster.cluster import MiniCluster


def main(argv=None) -> int:
    p = argparse.ArgumentParser("tpu-dra-minicluster")
    p.add_argument("--base-dir", required=True)
    p.add_argument("--nodes", type=int, default=2)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO
    )
    mc = MiniCluster(
        args.base_dir, num_nodes=args.nodes, port=args.port
    ).start()
    print(
        f"minicluster ready base={mc.base} server={mc.srv.server_url} "
        f"kubeconfig={mc.kubeconfig}",
        flush=True,
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    mc.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
