"""jq-subset interpreter for the bats e2e suites.

This image ships no ``jq`` binary, and the bats suites (tests/bats/,
mirroring /root/reference/tests/bats helpers.sh jq pipelines) lean on it
for every JSON assertion. Rather than rewriting the suites, this module
evaluates the jq dialect they actually use, so the suites execute
verbatim through the ``jq`` shim in hack/bats-shims/.

Supported (the suites' working set — see tests/test_jqmini.py):
  pipes ``a | b``; identity ``.``; field access ``.a.b``, optional
  ``.a?``; iteration ``.[]`` and ``.items[]``; indexing ``.[0]``;
  slices of nothing else; array construction ``[ ... ]``; parens;
  recursive descent ``..``; alternative ``//``; ``and`` / ``or``;
  comparisons ``==`` ``!=`` ``>`` ``<`` ``>=`` ``<=``; literals
  (numbers, strings, null, true, false, ``[]``); string interpolation
  ``"\\(expr)"``; variables ``$name`` (from ``--arg``); functions:
  ``select/1 length unique keys to_entries empty has/1 startswith/1
  endswith/1 test/1 not``; comma sequences inside ``[...]`` are not
  needed and unsupported.

Anything outside the subset raises :class:`JqError` — a loud failure,
never a silently-wrong answer.
"""

from __future__ import annotations

import json
import re
from typing import Any, Iterator, List, Optional, Tuple


class JqError(ValueError):
    pass


# --- tokenizer ---

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<number>-?\d+(\.\d+)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<var>\$[A-Za-z_][A-Za-z0-9_]*)
  | (?P<dotdot>\.\.)
  | (?P<field>\.[A-Za-z_][A-Za-z0-9_]*)
  | (?P<dot>\.)
  | (?P<op>==|!=|>=|<=|//|[|()\[\],?><])
    """,
    re.VERBOSE,
)


def _tokenize(src: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    i = 0
    while i < len(src):
        if src[i] == '"':
            j, parts = _scan_string(src, i)
            out.append(("string", src[i:j]))
            i = j
            continue
        m = _TOKEN_RE.match(src, i)
        if not m:
            raise JqError(f"jq: cannot tokenize at {src[i:i+20]!r}")
        i = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append((kind, m.group()))
    return out


def _scan_string(src: str, start: int) -> Tuple[int, None]:
    """Find the end of a double-quoted string starting at `start`,
    honoring backslash escapes and \\( ... ) interpolations."""
    i = start + 1
    while i < len(src):
        c = src[i]
        if c == "\\":
            if src[i + 1] == "(":
                depth = 1
                i += 2
                while i < len(src) and depth:
                    if src[i] == "(":
                        depth += 1
                    elif src[i] == ")":
                        depth -= 1
                    i += 1
                continue
            i += 2
            continue
        if c == '"':
            return i + 1, None
        i += 1
    raise JqError("jq: unterminated string")


# --- parser: produces a small AST of tuples ---
# ("pipe", left, right)  ("field", name, optional)  ("iterate",)
# ("index", n)  ("identity",)  ("recurse",)  ("collect", expr)
# ("alt", a, b)  ("and", a, b)  ("or", a, b)  ("cmp", op, a, b)
# ("lit", value)  ("str", [parts])  ("var", name)
# ("call", name, [args])  ("chain", head, [postfix...])


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> Optional[Tuple[str, str]]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> Tuple[str, str]:
        t = self.peek()
        if t is None:
            raise JqError("jq: unexpected end of expression")
        self.i += 1
        return t

    def expect(self, text: str) -> None:
        t = self.next()
        if t[1] != text:
            raise JqError(f"jq: expected {text!r}, got {t[1]!r}")

    def parse(self):
        e = self.parse_pipe()
        if self.peek() is not None:
            raise JqError(f"jq: trailing tokens at {self.peek()[1]!r}")
        return e

    def parse_pipe(self):
        left = self.parse_alt()
        while self.peek() and self.peek()[1] == "|":
            self.next()
            right = self.parse_alt()
            left = ("pipe", left, right)
        return left

    def parse_alt(self):
        left = self.parse_logic()
        while self.peek() and self.peek()[1] == "//":
            self.next()
            right = self.parse_logic()
            left = ("alt", left, right)
        return left

    def parse_logic(self):
        left = self.parse_cmp()
        while self.peek() and self.peek()[0] == "ident" and self.peek()[1] in (
            "and", "or"
        ):
            op = self.next()[1]
            right = self.parse_cmp()
            left = (op, left, right)
        return left

    def parse_cmp(self):
        left = self.parse_postfix()
        if self.peek() and self.peek()[1] in ("==", "!=", ">", "<", ">=", "<="):
            op = self.next()[1]
            right = self.parse_postfix()
            return ("cmp", op, left, right)
        return left

    def parse_postfix(self):
        head = self.parse_primary()
        parts = []
        while True:
            t = self.peek()
            if t is None:
                break
            if t[0] == "field":
                self.next()
                optional = False
                if self.peek() and self.peek()[1] == "?":
                    self.next()
                    optional = True
                parts.append(("field", t[1][1:], optional))
            elif t[1] == "[":
                # .[] or .[N] postfix on the current value
                self.next()
                nxt = self.peek()
                if nxt and nxt[1] == "]":
                    self.next()
                    parts.append(("iterate",))
                elif nxt and nxt[0] == "number":
                    n = self.next()[1]
                    self.expect("]")
                    parts.append(("index", int(n)))
                else:
                    raise JqError("jq: unsupported bracket postfix")
            else:
                break
        if not parts:
            return head
        return ("chain", head, parts)

    def parse_primary(self):
        t = self.peek()
        if t is None:
            raise JqError("jq: unexpected end")
        kind, text = t
        if text == "(":
            self.next()
            e = self.parse_pipe()
            self.expect(")")
            return e
        if text == "[":
            self.next()
            if self.peek() and self.peek()[1] == "]":
                self.next()
                return ("lit", [])
            e = self.parse_pipe()
            self.expect("]")
            return ("collect", e)
        if kind == "dotdot":
            self.next()
            return ("recurse",)
        if kind == "field":
            self.next()
            optional = False
            if self.peek() and self.peek()[1] == "?":
                self.next()
                optional = True
            return ("chain", ("identity",), [("field", text[1:], optional)])
        if kind == "dot":
            self.next()
            return ("identity",)
        if kind == "number":
            self.next()
            v = float(text)
            return ("lit", int(v) if v == int(v) else v)
        if kind == "string":
            self.next()
            return _parse_string_literal(text)
        if kind == "var":
            self.next()
            return ("var", text[1:])
        if kind == "ident":
            self.next()
            if text == "null":
                return ("lit", None)
            if text == "true":
                return ("lit", True)
            if text == "false":
                return ("lit", False)
            if text == "empty":
                return ("call", "empty", [])
            args = []
            if self.peek() and self.peek()[1] == "(":
                self.next()
                args.append(self.parse_pipe())
                while self.peek() and self.peek()[1] == ",":
                    self.next()
                    args.append(self.parse_pipe())
                self.expect(")")
            return ("call", text, args)
        raise JqError(f"jq: unsupported token {text!r}")


def _parse_string_literal(raw: str):
    """Parse '"...\\(expr)..."' into ("str", [literal-or-AST parts])."""
    body = raw[1:-1]
    parts: List[Any] = []
    buf = ""
    i = 0
    while i < len(body):
        c = body[i]
        if c == "\\":
            nxt = body[i + 1]
            if nxt == "(":
                depth = 1
                j = i + 2
                while j < len(body) and depth:
                    if body[j] == "(":
                        depth += 1
                    elif body[j] == ")":
                        depth -= 1
                    j += 1
                if buf:
                    parts.append(buf)
                    buf = ""
                inner = body[i + 2:j - 1]
                parts.append(_Parser(_tokenize(inner)).parse())
                i = j
                continue
            buf += json.loads(f'"\\{nxt}"')
            i += 2
            continue
        buf += c
        i += 1
    if buf or not parts:
        parts.append(buf)
    if len(parts) == 1 and isinstance(parts[0], str):
        return ("lit", parts[0])
    return ("str", parts)


# --- evaluator: every node yields a stream of values ---


def _recurse(v) -> Iterator[Any]:
    yield v
    if isinstance(v, dict):
        for x in v.values():
            yield from _recurse(x)
    elif isinstance(v, list):
        for x in v:
            yield from _recurse(x)


def _truthy(v) -> bool:
    return v is not None and v is not False


class _Env:
    def __init__(self, variables):
        self.vars = variables or {}


def _eval(node, v, env: _Env) -> Iterator[Any]:
    kind = node[0]
    if kind == "identity":
        yield v
    elif kind == "lit":
        yield node[1]
    elif kind == "var":
        if node[1] not in env.vars:
            raise JqError(f"jq: undefined variable ${node[1]}")
        yield env.vars[node[1]]
    elif kind == "pipe":
        for mid in _eval(node[1], v, env):
            yield from _eval(node[2], mid, env)
    elif kind == "chain":
        streams = _eval(node[1], v, env)
        for base in streams:
            yield from _eval_postfix(node[2], 0, base, env)
    elif kind == "collect":
        yield list(_eval(node[1], v, env))
    elif kind == "recurse":
        yield from _recurse(v)
    elif kind == "alt":
        got = []
        try:
            got = [x for x in _eval(node[1], v, env) if _truthy(x)]
        except JqError:
            raise
        except Exception:  # noqa: BLE001 — jq // swallows errors
            got = []
        if got:
            yield from got
        else:
            yield from _eval(node[2], v, env)
    elif kind in ("and", "or"):
        for a in _eval(node[1], v, env):
            for b in _eval(node[2], v, env):
                yield (_truthy(a) and _truthy(b)) if kind == "and" else (
                    _truthy(a) or _truthy(b)
                )
    elif kind == "cmp":
        op = node[1]
        for a in _eval(node[2], v, env):
            for b in _eval(node[3], v, env):
                yield _compare(op, a, b)
    elif kind == "str":
        out = ""
        for part in node[1]:
            if isinstance(part, str):
                out += part
            else:
                vals = list(_eval(part, v, env))
                if len(vals) != 1:
                    raise JqError("jq: interpolation must yield one value")
                x = vals[0]
                out += x if isinstance(x, str) else json.dumps(x)
        yield out
    elif kind == "call":
        yield from _call(node[1], node[2], v, env)
    else:
        raise JqError(f"jq: unhandled node {kind}")


def _eval_postfix(parts, i, v, env) -> Iterator[Any]:
    if i == len(parts):
        yield v
        return
    p = parts[i]
    if p[0] == "field":
        _, name, optional = p
        if v is None:
            yield from _eval_postfix(parts, i + 1, None, env)
            return
        if not isinstance(v, dict):
            if optional:
                return
            raise JqError(
                f"jq: cannot index {type(v).__name__} with .{name}"
            )
        yield from _eval_postfix(parts, i + 1, v.get(name), env)
    elif p[0] == "iterate":
        if v is None:
            return
        if isinstance(v, dict):
            items = list(v.values())
        elif isinstance(v, list):
            items = v
        else:
            raise JqError(f"jq: cannot iterate {type(v).__name__}")
        for x in items:
            yield from _eval_postfix(parts, i + 1, x, env)
    elif p[0] == "index":
        if v is None:
            yield from _eval_postfix(parts, i + 1, None, env)
            return
        if not isinstance(v, list):
            raise JqError(f"jq: cannot index {type(v).__name__}")
        n = p[1]
        x = v[n] if -len(v) <= n < len(v) else None
        yield from _eval_postfix(parts, i + 1, x, env)
    else:
        raise JqError(f"jq: unhandled postfix {p[0]}")


def _compare(op, a, b):
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    try:
        if op == ">":
            return a > b
        if op == "<":
            return a < b
        if op == ">=":
            return a >= b
        if op == "<=":
            return a <= b
    except TypeError:
        raise JqError(f"jq: cannot compare {a!r} {op} {b!r}")
    raise JqError(f"jq: unknown comparison {op}")


def _call(name, args, v, env) -> Iterator[Any]:
    if name == "empty":
        return
    if name == "select":
        for cond in _eval(args[0], v, env):
            if _truthy(cond):
                yield v
        return
    if name == "length":
        if v is None:
            yield 0
        elif isinstance(v, (list, dict, str)):
            yield len(v)
        else:
            raise JqError(f"jq: {type(v).__name__} has no length")
        return
    if name == "unique":
        if not isinstance(v, list):
            raise JqError("jq: unique input must be an array")
        seen = []
        for x in sorted(v, key=lambda x: json.dumps(x, sort_keys=True)):
            if not seen or seen[-1] != x:
                seen.append(x)
        yield seen
        return
    if name == "keys":
        if not isinstance(v, dict):
            raise JqError("jq: keys input must be an object")
        yield sorted(v.keys())
        return
    if name == "to_entries":
        if not isinstance(v, dict):
            raise JqError("jq: to_entries input must be an object")
        yield [{"key": k, "value": val} for k, val in v.items()]
        return
    if name == "not":
        yield not _truthy(v)
        return
    if name == "has":
        key = _one(args[0], v, env)
        if isinstance(v, dict):
            yield key in v
        elif isinstance(v, list):
            yield isinstance(key, int) and 0 <= key < len(v)
        else:
            raise JqError(f"jq: has() on {type(v).__name__}")
        return
    if name in ("startswith", "endswith", "test"):
        arg = _one(args[0], v, env)
        if not isinstance(v, str) or not isinstance(arg, str):
            raise JqError(f"jq: {name}() needs strings")
        if name == "startswith":
            yield v.startswith(arg)
        elif name == "endswith":
            yield v.endswith(arg)
        else:
            yield re.search(arg, v) is not None
        return
    raise JqError(f"jq: unsupported function {name}/{len(args)}")


def _one(node, v, env):
    vals = list(_eval(node, v, env))
    if len(vals) != 1:
        raise JqError("jq: argument must yield exactly one value")
    return vals[0]


def evaluate(expr: str, value: Any, variables=None) -> List[Any]:
    """Evaluate `expr` against `value`; returns the output stream."""
    ast = _Parser(_tokenize(expr)).parse()
    return list(_eval(ast, value, _Env(variables)))


def main(argv=None) -> int:
    """CLI compatible with the suites' usage: ``jq [-r] [--arg k v] EXPR``
    reading one JSON document from stdin."""
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    raw_output = False
    variables = {}
    expr = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "-r":
            raw_output = True
        elif a == "--arg":
            variables[argv[i + 1]] = argv[i + 2]
            i += 2
        elif a in ("-c", "--compact-output"):
            pass
        elif expr is None:
            expr = a
        else:
            print(f"jq shim: unexpected argument {a!r}", file=sys.stderr)
            return 2
        i += 1
    if expr is None:
        print("jq shim: missing expression", file=sys.stderr)
        return 2
    try:
        doc = json.load(sys.stdin)
    except json.JSONDecodeError as e:
        print(f"jq shim: invalid JSON input: {e}", file=sys.stderr)
        return 2
    try:
        results = evaluate(expr, doc, variables)
    except JqError as e:
        print(str(e), file=sys.stderr)
        return 3
    for r in results:
        if raw_output and isinstance(r, str):
            print(r)
        else:
            print(json.dumps(r))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
