"""Pod -> OS process translation for the minicluster.

The minicluster (kind analog for this clusterless environment) runs every
pod container as a real OS process on this machine. The translation is
generic over the pod spec — command, args, env (incl. fieldRef downward
API), hostPath/emptyDir volumes, http/exec probes — with per-image
*runtime profiles* standing in for container images (the same role kind's
image side-loading plays):

- the driver image runs repo entrypoints from the repo root (with image
  filesystem paths like /usr/local/share/tpu-dra/ mapped to hack/, and
  the ``tpu-multiplex-daemon`` binary to native/build/);
- the workload image (jax + libtpu in production) runs on this machine's
  CPU jax with big-model presets substituted for their tiny twins —
  declared, visible knobs, not silent edits (see PROFILES).

hostPath volumes resolve into the pod's node sandbox
(``<node_dir>/rootfs/<path>``) unless the path is already inside the
minicluster base dir (e.g. a Deployment rendered by the plugin whose env
was itself already sandbox-absolute). Env values under a volumeMount's
mountPath are rewritten to the resolved host dir, so a process reads and
writes exactly where a container would have.
"""

from __future__ import annotations

import os
import signal
import socket as socketlib
import subprocess
import sys
import tempfile
import time
import urllib.request
from pathlib import Path
from typing import Dict, List, Optional

REPO_ROOT = Path(__file__).resolve().parents[2]


class Profile:
    def __init__(self, env=None, arg_subst=None, path_map=None,
                 cmd_map=None, arg_pairs=None):
        self.env = env or {}
        self.arg_subst = arg_subst or {}
        self.path_map = path_map or {}
        # Image binary name -> host argv prefix (a container image's
        # PATH entrypoints don't exist on the host).
        self.cmd_map = cmd_map or {}
        # (flag, value) -> replacement value: flag-anchored so a bare
        # numeric can't be rewritten wherever it appears.
        self.arg_pairs = arg_pairs or {}


PROFILES = {
    # Driver image: repo entrypoints.
    "registry.local/tpu-dra-driver": Profile(
        path_map={
            "/usr/local/share/tpu-dra/": str(REPO_ROOT / "hack") + "/",
        },
        cmd_map={
            "tpu-multiplex-daemon": [str(
                REPO_ROOT / "native" / "build" / "tpu-multiplex-daemon"
            )],
            "tpu-compute-domain-daemon": [
                sys.executable, "-m", "tpu_dra.computedomain.daemon.main",
            ],
        },
    ),
    # Workload image: CPU jax, tiny-model stand-ins for the big presets
    # (this machine has no multi-host TPU slice; the code path — DRA
    # claims, CD bootstrap, jax.distributed, the training loop — is the
    # real one).
    "registry.local/tpu-workload": Profile(
        # JAX_PLATFORMS alone loses on hosts whose interpreter startup
        # already imported jax against a tunneled accelerator; the
        # workload mains honor TPU_DRA_FORCE_PLATFORM via
        # apply_forced_platform().
        env={"JAX_PLATFORMS": "cpu", "TPU_DRA_FORCE_PLATFORM": "cpu:1"},
        arg_subst={
            "llama3-8b": "tiny",
            "mixtral-8x7b": "tiny-moe",
        },
        arg_pairs={
            # CPU wall-time / fabric calibration: steps trimmed; the
            # bandwidth threshold is ICI-calibrated, the CPU Gloo
            # fabric measures the same collectives orders of magnitude
            # slower.
            ("--steps", "30"): "2",
            ("--min-gbps", "1"): "0.01",
        },
    ),
}


def profile_for(image: str) -> Profile:
    name = image.split(":")[0]
    return PROFILES.get(name, Profile())


def resolve_field_ref(path: str, pod: dict) -> str:
    md = pod.get("metadata", {})
    if path == "metadata.name":
        return md.get("name", "")
    if path == "metadata.namespace":
        return md.get("namespace", "")
    if path == "metadata.uid":
        return md.get("uid", "")
    if path == "spec.nodeName":
        return pod.get("spec", {}).get("nodeName", "")
    if path == "status.podIP":
        return pod.get("status", {}).get("podIP", "127.0.0.1")
    return ""


class ContainerProc:
    """One running container: process + log capture + probe state."""

    def __init__(self, name: str, proc: subprocess.Popen, log_path: Path,
                 ready_check=None):
        self.name = name
        self.proc = proc
        self.log_path = log_path
        self.ready_check = ready_check  # None = ready when started
        self.started = time.monotonic()

    def alive(self) -> bool:
        return self.proc.poll() is None

    def ready(self) -> bool:
        if not self.alive():
            return False
        if self.ready_check is None:
            return True
        return self.ready_check()


class PodSandbox:
    """All processes of one pod."""

    def __init__(self, pod: dict):
        self.uid = pod["metadata"]["uid"]
        self.namespace = pod["metadata"].get("namespace", "default")
        self.name = pod["metadata"]["name"]
        self.containers: List[ContainerProc] = []
        self.init_failed: Optional[str] = None
        self.tmp_dirs: List[str] = []

    def all_ready(self) -> bool:
        return bool(self.containers) and all(
            c.ready() for c in self.containers
        )

    def phase(self, restart_policy: str) -> str:
        """Terminal phase for restartPolicy=Never pods, else Running."""
        if not self.containers:
            return "Pending"
        if any(c.alive() for c in self.containers):
            return "Running"
        rcs = [c.proc.returncode for c in self.containers]
        return "Succeeded" if all(rc == 0 for rc in rcs) else "Failed"

    def kill(self):
        # Containers start with start_new_session=True; signal the whole
        # process GROUP so helpers a workload forked die with it — a
        # surviving child would keep rendezvous/device state alive past
        # the pod object's deletion.
        def _signal(c, sig):
            try:
                os.killpg(c.proc.pid, sig)
            except (OSError, ProcessLookupError):
                try:
                    getattr(
                        c.proc,
                        "terminate" if sig == signal.SIGTERM else "kill",
                    )()
                except OSError:
                    pass

        for c in self.containers:
            if c.alive():
                _signal(c, signal.SIGTERM)
        deadline = time.monotonic() + 5
        for c in self.containers:
            while c.alive() and time.monotonic() < deadline:
                time.sleep(0.05)
            if c.alive():
                _signal(c, signal.SIGKILL)
            try:
                c.proc.wait(timeout=5)
            except Exception:  # noqa: BLE001
                pass


def _free_port() -> int:
    s = socketlib.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class PodRunner:
    def __init__(self, base_dir: Path, node_dirs: Dict[str, Path],
                 kubeconfig: str):
        self.base = Path(base_dir)
        self.node_dirs = node_dirs
        self.kubeconfig = kubeconfig
        self.logs_dir = self.base / "logs"
        self.logs_dir.mkdir(parents=True, exist_ok=True)

    # --- path plumbing ---

    def node_rootfs(self, node: str) -> Path:
        return self.node_dirs[node] / "rootfs"

    def resolve_host_path(self, node: str, path: str) -> Path:
        p = Path(path)
        if str(p).startswith(str(self.base)):
            return p  # already sandbox-absolute (plugin-rendered spec)
        return self.node_rootfs(node) / str(p).lstrip("/")

    def _mounts(self, pod: dict, container: dict, sandbox: PodSandbox):
        """[(mountPath, resolved_host_dir)] sorted longest-first."""
        node = pod["spec"].get("nodeName", "")
        vols = {
            v["name"]: v for v in pod["spec"].get("volumes", []) or []
        }
        out = []
        for vm in container.get("volumeMounts", []) or []:
            vol = vols.get(vm["name"])
            if vol is None:
                continue
            if "hostPath" in vol:
                host = self.resolve_host_path(node, vol["hostPath"]["path"])
                hp_type = vol["hostPath"].get("type", "")
                if hp_type == "File" or host.is_file():
                    # Device-node mounts (the arbiter's gate paths) are
                    # FILES the node sandbox already created; mkdir on
                    # them would throw and directory-ing them would hide
                    # the inode the gate chowns.
                    host.parent.mkdir(parents=True, exist_ok=True)
                else:
                    host.mkdir(parents=True, exist_ok=True)
            elif "emptyDir" in vol:
                d = tempfile.mkdtemp(prefix=f"empty-{vm['name']}-")
                sandbox.tmp_dirs.append(d)
                host = Path(d)
            else:
                continue
            out.append((vm["mountPath"].rstrip("/"), host))
        out.sort(key=lambda t: -len(t[0]))
        return out

    def _rewrite(self, value: str, mounts) -> str:
        for mount_path, host in mounts:
            if value == mount_path:
                return str(host)
            if value.startswith(mount_path + "/"):
                return str(host) + value[len(mount_path):]
        return value

    # --- env/argv assembly ---

    def _container_env(self, pod: dict, container: dict, mounts,
                       profile: Profile, extra_env: Dict[str, str]):
        env = dict(os.environ)
        env.pop("PYTEST_CURRENT_TEST", None)
        env["KUBECONFIG"] = self.kubeconfig
        env["PYTHONPATH"] = (
            str(REPO_ROOT) + os.pathsep + env.get("PYTHONPATH", "")
        )
        env["PYTHONUNBUFFERED"] = "1"
        for e in container.get("env", []) or []:
            name = e.get("name")
            if "value" in e:
                env[name] = self._rewrite(str(e["value"]), mounts)
            elif "valueFrom" in e and "fieldRef" in e["valueFrom"]:
                env[name] = resolve_field_ref(
                    e["valueFrom"]["fieldRef"].get("fieldPath", ""), pod
                )
        env.update(profile.env)
        env.update(extra_env)
        return env

    def _argv(self, container: dict, profile: Profile, mounts) -> List[str]:
        argv = list(container.get("command", []) or []) + list(
            container.get("args", []) or []
        )
        if argv and argv[0] in profile.cmd_map:
            argv = list(profile.cmd_map[argv[0]]) + argv[1:]
        out = []
        prev = None
        for tok in argv:
            pair = profile.arg_pairs.get((prev, tok))
            prev = tok
            if pair is not None:
                out.append(pair)
                continue
            tok = profile.arg_subst.get(tok, tok)
            for prefix, repl in profile.path_map.items():
                if tok == prefix:
                    tok = repl
                elif tok.startswith(prefix):
                    tok = repl + tok[len(prefix):]
            out.append(self._rewrite(tok, mounts))
        if out and out[0] == "python":
            out[0] = sys.executable
        return out

    # --- probes ---

    def _probe(self, container: dict, env, mounts, port_remap):
        """Build a ready_check callable from startup/readiness probes."""
        probe = (
            container.get("startupProbe")
            or container.get("readinessProbe")
            or container.get("livenessProbe")
        )
        if not probe:
            return None
        if "httpGet" in probe:
            port = int(probe["httpGet"].get("port", 80))
            port = port_remap.get(port, port)
            path = probe["httpGet"].get("path", "/")
            url = f"http://127.0.0.1:{port}{path}"

            def check_http():
                try:
                    with urllib.request.urlopen(url, timeout=2) as r:
                        return 200 <= r.status < 400
                except OSError:
                    return False

            return check_http
        if "exec" in probe:
            argv = probe["exec"].get("command", [])
            profile = Profile()  # exec probes re-resolve through profiles
            for p in PROFILES.values():
                profile.path_map.update(p.path_map)
                profile.cmd_map.update(p.cmd_map)
            if argv and argv[0] in profile.cmd_map:
                argv = list(profile.cmd_map[argv[0]]) + argv[1:]
            resolved = []
            for tok in argv:
                for prefix, repl in profile.path_map.items():
                    if tok == prefix:
                        tok = repl
                    elif tok.startswith(prefix):
                        tok = repl + tok[len(prefix):]
                resolved.append(self._rewrite(tok, mounts))
            if resolved and resolved[0] == "python":
                resolved[0] = sys.executable

            def check_exec():
                try:
                    return subprocess.run(
                        resolved, env=env, capture_output=True, timeout=10
                    ).returncode == 0
                except (OSError, subprocess.TimeoutExpired):
                    return False

            return check_exec
        return None

    # --- launch ---

    def launch(self, pod: dict, extra_env: Optional[Dict[str, str]] = None,
               extra_env_by_container=None) -> PodSandbox:
        """Run initContainers to completion, then start every container.
        `extra_env` merges into every container (CDI-injected claim env);
        `extra_env_by_container` maps container name -> env overrides."""
        sandbox = PodSandbox(pod)
        extra_env = dict(extra_env or {})
        by_ctr = extra_env_by_container or {}
        pod_log_dir = (
            self.logs_dir / pod["metadata"].get("namespace", "default")
            / pod["metadata"]["name"]
        )
        pod_log_dir.mkdir(parents=True, exist_ok=True)

        # Per-pod HTTP-probe port remapping: two nodes' plugin pods would
        # otherwise race on one configured healthcheck port. Any env var
        # carrying the original port number follows the remap.
        port_remap: Dict[int, int] = {}
        for c in (pod["spec"].get("containers", []) or []):
            for probe_kind in (
                "startupProbe", "readinessProbe", "livenessProbe"
            ):
                probe = c.get(probe_kind) or {}
                if "httpGet" in probe:
                    orig = int(probe["httpGet"].get("port", 0))
                    if orig > 0 and orig not in port_remap:
                        port_remap[orig] = _free_port()

        def remap_env(env):
            for k, v in list(env.items()):
                if v.isdigit() and int(v) in port_remap:
                    env[k] = str(port_remap[int(v)])
            return env

        for init in pod["spec"].get("initContainers", []) or []:
            profile = profile_for(init.get("image", ""))
            mounts = self._mounts(pod, init, sandbox)
            env = remap_env(self._container_env(
                pod, init, mounts, profile, extra_env
            ))
            argv = self._argv(init, profile, mounts)
            log_path = pod_log_dir / f"{init['name']}.log"
            with open(log_path, "ab") as lf:
                rc = subprocess.run(
                    argv, env=env, stdout=lf, stderr=subprocess.STDOUT,
                    cwd=str(REPO_ROOT), timeout=120,
                ).returncode
            if rc != 0:
                sandbox.init_failed = (
                    f"init container {init['name']} exited {rc}"
                )
                return sandbox

        for c in pod["spec"].get("containers", []) or []:
            profile = profile_for(c.get("image", ""))
            mounts = self._mounts(pod, c, sandbox)
            env = remap_env(self._container_env(
                pod, c, mounts, profile,
                {**extra_env, **by_ctr.get(c["name"], {})},
            ))
            argv = self._argv(c, profile, mounts)
            log_path = pod_log_dir / f"{c['name']}.log"
            lf = open(log_path, "ab")
            try:
                proc = subprocess.Popen(
                    argv, env=env, stdout=lf, stderr=subprocess.STDOUT,
                    cwd=str(REPO_ROOT), start_new_session=True,
                )
            except OSError as e:
                # ErrImagePull analog: record the failure, reap what
                # already started, and let the kubelet retry/backoff.
                lf.write(f"spawn failed: {argv[0]}: {e}\n".encode())
                lf.close()
                sandbox.init_failed = f"container {c['name']}: {e}"
                sandbox.kill()
                sandbox.containers.clear()
                return sandbox
            lf.close()
            ready = self._probe(c, env, mounts, port_remap)
            sandbox.containers.append(
                ContainerProc(c["name"], proc, log_path, ready)
            )
        return sandbox


def container_log_path(base_dir: Path, namespace: str, pod: str,
                      container: str) -> Path:
    return Path(base_dir) / "logs" / namespace / pod / f"{container}.log"
