"""helm shim: upgrade --install / uninstall over the fakeserver.

Renders with tpu_dra.infra.minihelm (no helm binary in this image) and
applies the manifests through the production REST transport. Release
state (the rendered object list) is recorded in a ConfigMap in the
release namespace — the role helm's release Secrets play — so uninstall
deletes exactly what the release installed and an upgrade prunes objects
that fell out of the render.
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional

from tpu_dra.infra.minihelm import parse_set, render_chart
from tpu_dra.k8sclient.resources import (
    CONFIG_MAPS,
    ApiNotFound,
    K8sApiError,
    iter_descriptors,
)
from tpu_dra.k8sclient.rest import KubeClient


def _release_cm(release: str) -> str:
    return f"helm-release-{release}"


def _apply(kc, rd, doc) -> None:
    md = doc.setdefault("metadata", {})
    try:
        kc.create(rd, doc)
        return
    except K8sApiError as e:
        if getattr(e, "status", None) != 409:
            raise
    # Update with CAS retry: controllers (status writers) race the
    # upgrade, bumping resourceVersion between our GET and PUT.
    for attempt in range(8):
        existing = kc.get(
            rd, md.get("namespace") if rd.namespaced else None, md["name"]
        )
        doc["metadata"]["resourceVersion"] = existing["metadata"][
            "resourceVersion"
        ]
        try:
            kc.update(rd, doc)
            return
        except K8sApiError as e:
            if getattr(e, "status", None) != 409 or attempt == 7:
                raise


def upgrade(release: str, chart: str, namespace: str,
            sets: List[str]) -> int:
    kc = KubeClient.from_config(qps=1000, burst=1000)
    docs = render_chart(
        chart,
        values_overrides=[parse_set(s) for s in sets],
        release_name=release,
        namespace=namespace,
        # Capabilities from the live registry (helm asks the apiserver the
        # same question), so the chart's resourceApiVersion auto-detect
        # picks the newest DRA version this cluster serves.
        api_versions=sorted({d.api_version for d in iter_descriptors()}),
    )
    by_gvk = {(d.api_version, d.kind): d for d in iter_descriptors()}
    # Namespace first (helm --create-namespace).
    from tpu_dra.k8sclient.resources import NAMESPACES

    try:
        kc.create(NAMESPACES, {
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": namespace},
        })
    except K8sApiError:
        pass
    applied = []
    skipped = []
    for doc in docs:
        rd = by_gvk.get((doc.get("apiVersion", ""), doc.get("kind", "")))
        if rd is None:
            skipped.append(
                f"{doc.get('apiVersion')}/{doc.get('kind')}"
            )
            continue
        if rd.namespaced:
            doc.setdefault("metadata", {}).setdefault(
                "namespace", namespace
            )
        _apply(kc, rd, doc)
        applied.append([
            rd.group, rd.version, rd.plural,
            doc["metadata"].get("namespace"), doc["metadata"]["name"],
        ])
    # Prune objects from the previous revision that this render dropped.
    # Keys omit the VERSION: storage is per group/plural, so the same
    # object re-applied at a newer DRA version must not be pruned via
    # its old version's entry.
    prev = _load_manifest(kc, namespace, release)

    def prune_key(e):
        return (e[0], e[2], e[3], e[4])

    cur_keys = {prune_key(a) for a in applied}
    for entry in prev:
        if prune_key(entry) in cur_keys:
            continue
        rd = next(
            (d for d in iter_descriptors()
             if [d.group, d.version, d.plural] == entry[:3]),
            None,
        )
        if rd is not None:
            try:
                kc.delete(rd, entry[3] if rd.namespaced else None, entry[4])
            except K8sApiError:
                pass
    cm = {
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": _release_cm(release), "namespace": namespace},
        "data": {"manifest": json.dumps(applied)},
    }
    _apply(kc, CONFIG_MAPS, cm)
    if skipped:
        print(
            f"note: kinds not served by this cluster: {sorted(set(skipped))}",
            file=sys.stderr,
        )
    print(f'Release "{release}" has been upgraded. ({len(applied)} objects)')
    return 0


def _load_manifest(kc, namespace: str, release: str) -> List[list]:
    try:
        cm = kc.get(CONFIG_MAPS, namespace, _release_cm(release))
        return json.loads(cm.get("data", {}).get("manifest", "[]"))
    except (ApiNotFound, ValueError):
        return []


def uninstall(release: str, namespace: str) -> int:
    kc = KubeClient.from_config(qps=1000, burst=1000)
    entries = _load_manifest(kc, namespace, release)
    if not entries:
        print(f'Error: uninstall: Release not loaded: {release}',
              file=sys.stderr)
        return 1
    for entry in reversed(entries):
        rd = next(
            (d for d in iter_descriptors()
             if [d.group, d.version, d.plural] == entry[:3]),
            None,
        )
        if rd is None:
            continue
        try:
            kc.delete(rd, entry[3] if rd.namespaced else None, entry[4])
        except K8sApiError:
            pass
    try:
        kc.delete(CONFIG_MAPS, namespace, _release_cm(release))
    except K8sApiError:
        pass
    print(f'release "{release}" uninstalled')
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print("helm shim: missing command", file=sys.stderr)
        return 1
    verb = argv[0]
    positionals = []
    namespace = "default"
    sets: List[str] = []
    i = 1
    while i < len(argv):
        a = argv[i]
        if a in ("--namespace", "-n"):
            namespace = argv[i + 1]
            i += 1
        elif a == "--set":
            sets.append(argv[i + 1])
            i += 1
        elif a.startswith("--set="):
            sets.append(a.split("=", 1)[1])
        elif a in ("--install", "--create-namespace", "--wait"):
            pass
        else:
            positionals.append(a)
        i += 1
    if verb == "upgrade":
        if len(positionals) < 2:
            print("helm shim: upgrade RELEASE CHART", file=sys.stderr)
            return 1
        return upgrade(positionals[0], positionals[1], namespace, sets)
    if verb == "uninstall":
        return uninstall(positionals[0], namespace)
    print(f"helm shim: unsupported command {verb}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
