"""Minimal bats-compatible runner: executes .bats files with bash.

No bats binary ships in this image, so this runner gives the bats suites
(tests/bats/) the harness surface they use — ``@test`` blocks, ``run``
(populating ``$status``/``$output``/``$lines``), ``skip``, ``load``,
``setup_suite``/``setup_file``/``setup``/``teardown_file``, the fd-3 log
stream, and the repo's ``bats::on_failure`` diagnostic hook — and runs
each file as one bash process emitting TAP.

Semantics per test (bats-core behavior): setup + body run in a subshell
with ``set -e``; nonzero exit fails the test, exit 200 (the ``skip``
sentinel) skips it. setup_file/teardown_file run once in the file's main
shell so their exports reach every test. Per-test output is captured to
a log and dumped (indented, TAP-comment style) on failure.
"""

from __future__ import annotations

import argparse
import re
import subprocess
import time
from pathlib import Path
from typing import List, Optional, Tuple

TEST_RE = re.compile(r'^@test\s+"(.+)"\s*\{\s*$')

PRELUDE = r"""
exec 3>>"$__BATS_FILE_LOG"
run() {
  local _ec=0
  output="$("$@" 2>&1)" || _ec=$?
  status=$_ec
  mapfile -t lines <<<"$output"
  return 0
}
load() {
  local f="$(dirname "$BATS_TEST_FILENAME")/$1"
  [[ -f "$f" ]] || f="$f.bash"
  source "$f"
}
skip() { echo "__BATS_SKIP__:${1:-skipped}"; exit 200; }
"""


def transform(path: Path) -> Tuple[str, List[str]]:
    """Rewrite @test blocks to numbered functions; returns (bash, names)."""
    names: List[str] = []
    out: List[str] = []
    for line in path.read_text().splitlines():
        m = TEST_RE.match(line)
        if m:
            names.append(m.group(1))
            out.append(f"bats_test_{len(names) - 1}() {{")
        else:
            out.append(line)
    return "\n".join(out) + "\n", names


def build_script(path: Path, log_dir: Path) -> Tuple[str, List[str]]:
    body, names = transform(path)
    file_log = log_dir / f"{path.stem}.file.log"
    suite = path.parent / "setup_suite.bash"
    lines = [
        "#!/bin/bash",
        f'BATS_TEST_FILENAME="{path.resolve()}"',
        f'__BATS_FILE_LOG="{file_log}"',
        "export BATS_TEST_FILENAME",
        PRELUDE,
        body,
    ]
    if suite.exists():
        lines += [
            f'source "{suite}"',
            'if ! setup_suite >>"$__BATS_FILE_LOG" 2>&1; then',
            '  echo "__BATS_SUITE_FAIL__"; exit 70; fi',
        ]
    lines += [
        "_FILE_SKIP=''",
        "if declare -F setup_file >/dev/null; then",
        "  skip() { _FILE_SKIP=\"${1:-skipped}\"; }",
        '  setup_file >>"$__BATS_FILE_LOG" 2>&1 || '
        'echo "__BATS_SETUP_FILE_FAIL__"',
        "  skip() { echo \"__BATS_SKIP__:${1:-skipped}\"; exit 200; }",
        "fi",
    ]
    for i, name in enumerate(names):
        tlog = log_dir / f"{path.stem}.{i}.log"
        esc = name.replace('"', '\\"')
        lines += [
            'if [[ -n "$_FILE_SKIP" ]]; then',
            f'  echo "__BATS_RESULT__:{i}:skip:$_FILE_SKIP"',
            "else",
            f'  ( exec >"{tlog}" 2>&1 3>&1; set -e; '
            f"declare -F setup >/dev/null && setup; bats_test_{i} )",
            "  _rc=$?",
            f'  if [[ $_rc -eq 0 ]]; then echo "__BATS_RESULT__:{i}:ok:"',
            f'  elif [[ $_rc -eq 200 ]]; then '
            f'echo "__BATS_RESULT__:{i}:skip:$(grep -o '
            f"'__BATS_SKIP__:.*' \"{tlog}\" | head -1 | cut -d: -f2-)\"",
            "  else",
            f'    echo "__BATS_RESULT__:{i}:fail:rc=$_rc"',
            "    if declare -F bats::on_failure >/dev/null; then",
            f'      ( exec >>"{tlog}" 2>&1 3>&1; bats::on_failure ) || true',
            "    fi",
            "  fi",
            "fi",
        ]
    lines += [
        "if declare -F teardown_file >/dev/null; then",
        '  teardown_file >>"$__BATS_FILE_LOG" 2>&1 || true',
        "fi",
    ]
    return "\n".join(lines) + "\n", names


def run_file(path: Path, log_dir: Path, out, timeout: float) -> dict:
    script, names = build_script(path, log_dir)
    script_path = log_dir / f"{path.stem}.generated.sh"
    script_path.write_text(script)
    counts = {"ok": 0, "fail": 0, "skip": 0, "names": names}
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            ["bash", str(script_path)], capture_output=True, text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        out(f"# {path.name}: TIMED OUT after {timeout:.0f}s")
        counts["fail"] = len(names)
        for i, name in enumerate(names):
            out(f"not ok - {path.stem}: {name} (file timeout)")
        return counts
    results = {}
    for line in proc.stdout.splitlines():
        if line.startswith("__BATS_RESULT__:"):
            _, idx, verdict, detail = line.split(":", 3)
            results[int(idx)] = (verdict, detail)
        elif line.startswith("__BATS_SUITE_FAIL__"):
            out(f"# {path.name}: setup_suite failed")
        elif line.startswith("__BATS_SETUP_FILE_FAIL__"):
            out(f"# {path.name}: setup_file failed "
                f"(see {log_dir / (path.stem + '.file.log')})")
    for i, name in enumerate(names):
        verdict, detail = results.get(i, ("fail", "no result (file died)"))
        label = f"{path.stem}: {name}"
        if verdict == "ok":
            counts["ok"] += 1
            out(f"ok - {label}")
        elif verdict == "skip":
            counts["skip"] += 1
            out(f"ok - {label} # SKIP {detail}")
        else:
            counts["fail"] += 1
            out(f"not ok - {label} ({detail})")
            tlog = log_dir / f"{path.stem}.{i}.log"
            if tlog.exists():
                for ln in tlog.read_text(errors="replace").splitlines()[-40:]:
                    out(f"#   {ln}")
    out(
        f"# {path.name}: {counts['ok']} ok, {counts['fail']} failed, "
        f"{counts['skip']} skipped in {time.monotonic() - t0:.1f}s"
    )
    return counts


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser("tpu-dra-batsrun")
    p.add_argument("paths", nargs="+")
    p.add_argument("--log", default="")
    p.add_argument("--workdir", default="")
    p.add_argument("--file-timeout", type=float, default=1800.0)
    args = p.parse_args(argv)
    files: List[Path] = []
    for raw in args.paths:
        path = Path(raw)
        if path.is_dir():
            files.extend(sorted(path.glob("*.bats")))
        else:
            files.append(path)
    log_dir = Path(args.workdir or ".batsrun")
    log_dir.mkdir(parents=True, exist_ok=True)
    log_f = open(args.log, "w") if args.log else None

    def out(line: str) -> None:
        print(line, flush=True)
        if log_f:
            log_f.write(line + "\n")
            log_f.flush()

    out("TAP version 13")
    total = {"ok": 0, "fail": 0, "skip": 0}
    for f in files:
        c = run_file(f, log_dir, out, args.file_timeout)
        for k in total:
            total[k] += c[k]
    out(
        f"# TOTAL: {total['ok']} ok, {total['fail']} failed, "
        f"{total['skip']} skipped across {len(files)} files"
    )
    if log_f:
        log_f.close()
    return 1 if total["fail"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
