from tpu_dra.minicluster.main import main

raise SystemExit(main())
