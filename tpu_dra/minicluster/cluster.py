"""MiniCluster: the kind analog for this clusterless environment.

`kind` gives the reference's bats suites a real control plane + kubelets
in docker containers. This image has no docker/kind/kubectl, so the
minicluster supplies the same roles around the repo's own fake apiserver,
letting the bats suites (tests/bats/) EXECUTE verbatim:

- **apiserver**: FakeApiServer over HTTP (admission always on; the
  production REST transport speaks to it unmodified);
- **nodes**: N simulated TPU hosts, each a sandbox directory
  (``<base>/nodes/<n>/rootfs``) with a per-host stub-tpulib inventory —
  one 2x2x2 v5p slice split across the hosts, 4 chips each;
- **kubelet**: pods run as real OS processes (podrun.py); DRA claims are
  resolved from templates, allocated (structured-parameters allocator,
  node-constrained the way kube-scheduler's DynamicResources plugin
  allocates), prepared over the node plugin's real gRPC socket, and the
  CDI env is injected into the right containers;
- **controller-manager**: DaemonSet/Deployment/Job reconcilers (template
  hash rollouts, job completion/retry), ownerReference GC, namespace
  cascade deletion, reservedFor bookkeeping and claim release.

Everything the driver does — registering plugins, publishing slices,
stamping CD daemonsets, arbitrating shared chips — is the production
code running as chart-installed pods.

Crash drills: pod processes inherit the runner's environment (podrun
``_container_env`` starts from ``os.environ``), so exporting
``TPU_DRA_CRASH_POINT=<name>`` + ``TPU_DRA_CRASH_STATE_DIR=<dir>``
before bringing the cluster up makes the named component die with a real
``os._exit(137)`` at that WAL instruction; the kubelet's restart-with-
backoff then replays the boot recovery path, and the state-dir marker
keeps the re-spawned process from crash-looping (crash once, recover —
see docs/operations.md "Crash recovery & restart drills").
"""

from __future__ import annotations

import copy
import hashlib
import json
import logging
import socket as socketlib
import threading
import time
import uuid as uuidlib
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

import yaml

from tpu_dra.k8sclient.fake import FakeCluster
from tpu_dra.k8sclient.fakeserver import FakeApiServer
from tpu_dra.k8sclient.resources import (
    DAEMON_SETS,
    DEPLOYMENTS,
    DEVICE_CLASSES,
    JOBS,
    NAMESPACES,
    NODES,
    PODS,
    RESOURCE_CLAIM_TEMPLATES,
    RESOURCE_CLAIMS,
    RESOURCE_SLICES,
    K8sApiError,
    iter_descriptors,
)
from tpu_dra.minicluster.podrun import PodRunner, PodSandbox
from tpu_dra.scheduler.allocator import Allocator, Unschedulable

log = logging.getLogger(__name__)

TICK_SECONDS = 0.15
PREPARE_BACKOFF_SECONDS = 2.0


def _template_hash(template: dict) -> str:
    return hashlib.sha256(
        json.dumps(template, sort_keys=True).encode()
    ).hexdigest()[:10]


def _owner_ref(obj: dict, controller_kind: str) -> dict:
    return {
        "apiVersion": obj.get("apiVersion", ""),
        "kind": controller_kind,
        "name": obj["metadata"]["name"],
        "uid": obj["metadata"]["uid"],
        "controller": True,
    }


def _int_quantity(amount) -> int:
    """Integer value of a k8s quantity ("2", 2, "1k"); 0 for anything
    unparsable — a malformed third-party limit must not crash the
    binder for every pod on the cluster."""
    from tpu_dra.api.quantity import Quantity

    try:
        return int(Quantity.parse(str(amount)).value)
    except Exception:  # noqa: BLE001 — tolerant by design
        return 0


def _match_node_selector(selector: Optional[dict], labels: dict) -> bool:
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


def _socket_connectable(path: Path) -> bool:
    if not path.exists():
        return False
    s = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
    try:
        s.settimeout(1.0)
        s.connect(str(path))
        return True
    except OSError:
        return False
    finally:
        s.close()


class MiniCluster:
    def __init__(self, base_dir: str, num_nodes: int = 2,
                 port: int = 0):
        self.base = Path(base_dir)
        self.base.mkdir(parents=True, exist_ok=True)
        self.num_nodes = num_nodes
        self.srv = FakeApiServer(port=port, watch_heartbeat_seconds=5.0)
        self.fc: FakeCluster = self.srv.cluster
        self.kubeconfig = str(self.base / "kubeconfig.yaml")
        self.node_names = [f"node-{i}" for i in range(num_nodes)]
        self.node_dirs = {
            n: self.base / "nodes" / n for n in self.node_names
        }
        self.runner = PodRunner(self.base, self.node_dirs, self.kubeconfig)
        self.sandboxes: Dict[str, PodSandbox] = {}  # pod uid -> sandbox
        # pod uid -> {claim uid: (namespace, name, driver, node)}
        self.prepared: Dict[str, Dict[str, Tuple[str, str, str, str]]] = {}
        self.released: Set[str] = set()  # pod uids already released
        self.restarts: Dict[str, int] = {}  # pod uid -> container restarts
        self._reg_misses: Dict[Tuple[str, str], int] = {}
        self.next_attempt: Dict[str, float] = {}  # pod uid -> backoff
        self._job_failures: Dict[str, int] = {}  # job uid -> replaced fails
        # Pod admission (allocation + gRPC prepare + launch) runs on a
        # worker pool: prepares block (up to the 30s RPC timeout), and a
        # single-threaded loop would stall teardown/status for EVERY pod
        # behind one slow prepare — observed as force-deleted pods
        # running to completion before their kill arrived.
        from concurrent.futures import ThreadPoolExecutor

        self._admit_pool = ThreadPoolExecutor(
            max_workers=6, thread_name_prefix="mc-admit"
        )
        self._admitting: Set[str] = set()
        # Allocation is a read-modify-write over shared cluster capacity:
        # concurrent admits must serialize it (kube-scheduler binds one
        # pod at a time for the same reason). Prepare/launch parallelize.
        # Reentrant, and it also guards the pod bookkeeping maps
        # (next_attempt/_admitting/sandboxes/prepared/released/restarts)
        # shared by the reconcile thread, the admit pool, and the pod
        # reaper (R200).
        self._alloc_lock = threading.RLock()
        self.ns_seen: Set[str] = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._kill_thread: Optional[threading.Thread] = None
        self._reaper_watch = None
        self._rd_by_gvk = {
            (d.api_version, d.kind): d for d in iter_descriptors()
        }

    # --- lifecycle ---

    # The deepest per-node socket path the driver binds; AF_UNIX caps
    # sun_path around 107 chars, and gRPC just says "failed to bind".
    _DEEPEST_SOCKET_SUFFIX = (
        "/nodes/{node}/rootfs/var/lib/kubelet/plugins_registry/"
        "compute-domain.tpu.google.com-reg.sock"
    )

    def start(self) -> "MiniCluster":
        longest_node = max(self.node_names, key=len)
        deepest = str(self.base) + self._DEEPEST_SOCKET_SUFFIX.format(
            node=longest_node
        )
        # Linux sun_path is 108 bytes incl. NUL and gRPC's unix:// bind
        # fails at 107 measured chars; 105 is the longest observed to
        # work — keep a safety char.
        if len(deepest) > 105:
            raise ValueError(
                f"--base-dir too long: the node registration socket "
                f"path would be {len(deepest)} chars, over the AF_UNIX "
                f"sun_path limit; use a shorter base (e.g. /tmp/mcXXXXXX)"
            )
        self.srv.start()
        self.srv.write_kubeconfig(self.kubeconfig)
        self._make_nodes()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="minicluster"
        )
        self._thread.start()
        # Event-driven pod teardown: the sweep in _reconcile_pods also
        # reaps ghosts, but a full tick can take tens of seconds on a
        # loaded single-core box — long enough for a force-deleted
        # worker's PROCESS to keep running, complete a rendezvous with
        # its partner, and poison a failover drill (a real kubelet kills
        # the container the moment the pod object dies). Watch DELETED
        # events and kill immediately.
        self._kill_thread = threading.Thread(
            target=self._watch_pod_deletes, daemon=True,
            name="minicluster-pod-reaper",
        )
        self._kill_thread.start()
        log.info(
            "minicluster up: %s (%d nodes) base=%s",
            self.srv.server_url, self.num_nodes, self.base,
        )
        return self

    def _watch_pod_deletes(self) -> None:
        while not self._stop.is_set():
            try:
                # Close any previous stream FIRST: an abandoned _Watch
                # stays registered and accumulates a copy of every
                # subsequent pod event into a queue nobody drains.
                if self._reaper_watch is not None:
                    try:
                        self._reaper_watch.close()
                    except Exception:  # noqa: BLE001
                        pass
                self._reaper_watch = self.fc.watch(PODS)
                for ev, obj in self._reaper_watch:
                    if self._stop.is_set():
                        return
                    if ev != "DELETED":
                        continue
                    uid = (obj.get("metadata") or {}).get("uid")
                    if uid and uid in self.sandboxes:
                        log.info(
                            "pod %s/%s deleted: killing its sandbox now",
                            obj["metadata"].get("namespace"),
                            obj["metadata"].get("name"),
                        )
                        try:
                            self._teardown_pod(uid)
                        except Exception:  # noqa: BLE001
                            log.exception("event-driven teardown failed")
            except Exception:  # noqa: BLE001 — reconnect on any stream
                # failure; the sweep remains the backstop meanwhile.
                if not self._stop.wait(1.0):
                    continue
                return

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        # Unblock + join the reaper: its watch otherwise sits in q.get()
        # forever, leaking the thread and a registered _Watch per
        # cluster.
        if self._reaper_watch is not None:
            try:
                self._reaper_watch.close()
            except Exception:  # noqa: BLE001
                pass
        if self._kill_thread is not None:
            self._kill_thread.join(timeout=5)
        # Drain in-flight admissions BEFORE killing sandboxes: a worker
        # finishing a blocked prepare after the kill loop would launch an
        # orphan pod process that outlives the cluster.
        self._admit_pool.shutdown(wait=True, cancel_futures=True)
        # Snapshot: the reaper may still pop entries concurrently.
        for sandbox in list(self.sandboxes.values()):
            sandbox.kill()
        self.srv.stop()

    def _make_nodes(self) -> None:
        for i, name in enumerate(self.node_names):
            rootfs = self.node_dirs[name] / "rootfs"
            rootfs.mkdir(parents=True, exist_ok=True)
            state_dir = rootfs / "var/lib/tpu-dra/stub-state"
            state_dir.mkdir(parents=True, exist_ok=True)
            hosts = rootfs / "etc/hosts"
            hosts.parent.mkdir(parents=True, exist_ok=True)
            if not hosts.exists():
                hosts.write_text("127.0.0.1 localhost\n")
            # Sandbox device inodes: the stub advertises these paths, so
            # CDI/device-gate/workloads all see the SAME real inodes (the
            # device-mode enforcement drill chowns them — bench.py's
            # `enforcement_mode: "device"` record, r5 VERDICT #8).
            dev_dir = rootfs / "dev"
            dev_dir.mkdir(parents=True, exist_ok=True)
            for c in range(8):
                node_file = dev_dir / f"accel{c}"
                if not node_file.exists():
                    node_file.touch()
                    node_file.chmod(0o666)
            stub = rootfs / "etc/tpu-dra/stub-config.yaml"
            stub.parent.mkdir(parents=True, exist_ok=True)
            stub.write_text(yaml.safe_dump({
                "generation": "v5p",
                "hostname": name,
                "state_dir": str(state_dir),
                "dev_root": str(dev_dir),
                "slice": {
                    "uuid": "feedfeed",
                    "topology": "2x2x2",
                    "num_hosts": self.num_nodes,
                    "worker_id": i,
                },
            }))
            self.fc.create(NODES, {
                "apiVersion": "v1", "kind": "Node",
                "metadata": {
                    "name": name,
                    "labels": {
                        "kubernetes.io/hostname": name,
                        "google.com/tpu.present": "true",
                    },
                },
                "status": {"conditions": [
                    {"type": "Ready", "status": "True"}
                ]},
            })

    # --- store helpers (direct FakeCluster access: the control loops are
    # part of the cluster, like kube-controller-manager sharing etcd) ---

    def _list(self, rd, namespace=None, label_selector=None):
        return self.fc.list(rd, namespace, label_selector=label_selector)

    def _try_get(self, rd, namespace, name):
        try:
            return self.fc.get(rd, namespace, name)
        except K8sApiError:
            return None

    def _delete_quiet(self, rd, namespace, name):
        try:
            self.fc.delete(rd, namespace, name)
        except K8sApiError:
            pass

    def _update_status_quiet(self, rd, obj):
        try:
            obj["metadata"]["resourceVersion"] = None
            self.fc.update_status(rd, obj)
        except K8sApiError as e:
            log.debug("status update failed: %s", e)

    # --- main loop ---

    def _run(self) -> None:
        while not self._stop.wait(TICK_SECONDS):
            try:
                self._gc_namespaces()
                self._gc_owners()
                self._gc_resource_slices()
                self._reconcile_daemonsets()
                self._reconcile_deployments()
                self._reconcile_jobs()
                self._reconcile_pods()
                self._reconcile_claims()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("minicluster reconcile tick failed")

    # --- namespace cascade ---

    def _gc_namespaces(self) -> None:
        current = {
            o["metadata"]["name"] for o in self._list(NAMESPACES)
        }
        gone = self.ns_seen - current
        self.ns_seen |= current
        for ns in gone:
            for rd in iter_descriptors():
                if not rd.namespaced:
                    continue
                for obj in self._list(rd, ns):
                    self._delete_quiet(rd, ns, obj["metadata"]["name"])
            self.ns_seen.discard(ns)

    def _gc_resource_slices(self) -> None:
        """The real kubelet deletes a driver's ResourceSlices when the
        plugin deregisters (DRA manager wipe-on-deregistration). Analog:
        a slice whose driver's registration socket on its node stops
        ACCEPTING (a dead socket file still exists after SIGKILL) for a
        few consecutive ticks is stale — e.g. after `helm uninstall`
        killed the plugin pods. A restarting plugin republishes on
        startup, so a wipe during its down-window self-heals."""
        slices = self._list(RESOURCE_SLICES)
        keys = set()
        for s in slices:
            spec = s.get("spec", {})
            node, driver = spec.get("nodeName"), spec.get("driver", "")
            if node in self.node_dirs and driver:
                keys.add((node, driver))
        dead = set()
        for key in keys:
            node, driver = key
            reg = (
                self.runner.node_rootfs(node)
                / "var/lib/kubelet/plugins_registry"
                / f"{driver}-reg.sock"
            )
            if _socket_connectable(reg):
                self._reg_misses.pop(key, None)
                continue
            self._reg_misses[key] = self._reg_misses.get(key, 0) + 1
            if self._reg_misses[key] >= 5:
                dead.add(key)
        for s in slices:
            spec = s.get("spec", {})
            if (spec.get("nodeName"), spec.get("driver", "")) in dead:
                self._delete_quiet(
                    RESOURCE_SLICES, None, s["metadata"]["name"]
                )

    # --- ownerReference GC ---

    def _gc_owners(self) -> None:
        live_uids: Set[str] = set()
        for rd in iter_descriptors():
            for obj in self._list(rd):
                uid = obj.get("metadata", {}).get("uid")
                if uid:
                    live_uids.add(uid)
        for rd in (PODS, RESOURCE_CLAIMS, RESOURCE_CLAIM_TEMPLATES):
            for obj in self._list(rd):
                refs = obj["metadata"].get("ownerReferences") or []
                if refs and all(
                    r.get("uid") not in live_uids for r in refs
                ):
                    self._delete_quiet(
                        rd, obj["metadata"].get("namespace"),
                        obj["metadata"]["name"],
                    )

    # --- workload controllers ---

    def _pods_of(self, owner_uid: str) -> List[dict]:
        return [
            p for p in self._list(PODS)
            if any(
                r.get("uid") == owner_uid
                for r in p["metadata"].get("ownerReferences") or []
            )
        ]

    def _make_pod(self, namespace: str, name: str, template: dict,
                  owner: dict, owner_kind: str, node: Optional[str],
                  extra_labels=None, extra_annotations=None) -> None:
        spec = copy.deepcopy(template.get("spec", {}))
        if node:
            spec["nodeName"] = node
        md = copy.deepcopy(template.get("metadata", {}))
        labels = md.get("labels", {}) or {}
        labels.update(extra_labels or {})
        annotations = md.get("annotations", {}) or {}
        annotations.update(extra_annotations or {})
        try:
            self.fc.create(PODS, {
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {
                    "name": name, "namespace": namespace,
                    "labels": labels, "annotations": annotations,
                    "ownerReferences": [_owner_ref(owner, owner_kind)],
                },
                "spec": spec,
            })
        except K8sApiError:
            pass  # already exists (or racing delete); reconverge next tick

    def _reconcile_daemonsets(self) -> None:
        nodes = self._list(NODES)
        for ds in self._list(DAEMON_SETS):
            template = ds["spec"].get("template", {})
            thash = _template_hash(template)
            selector = (
                template.get("spec", {}).get("nodeSelector")
            )
            eligible = [
                n["metadata"]["name"] for n in nodes
                if _match_node_selector(
                    selector, n["metadata"].get("labels", {}) or {}
                )
            ]
            ns = ds["metadata"]["namespace"]
            existing = {
                p["spec"].get("nodeName"): p for p in self._pods_of(
                    ds["metadata"]["uid"]
                )
            }
            ready = 0
            for node in eligible:
                pod = existing.get(node)
                if pod is not None and (
                    pod["metadata"].get("labels", {}).get(
                        "minicluster/template-hash"
                    ) != thash
                ):
                    self._delete_quiet(
                        PODS, ns, pod["metadata"]["name"]
                    )
                    pod = None
                if pod is None:
                    self._make_pod(
                        ns, f"{ds['metadata']['name']}-{node}",
                        template, ds, "DaemonSet", node,
                        extra_labels={"minicluster/template-hash": thash},
                    )
                elif self._pod_ready(pod):
                    ready += 1
            for node, pod in existing.items():
                if node not in eligible:
                    self._delete_quiet(PODS, ns, pod["metadata"]["name"])
            ds["status"] = {
                "desiredNumberScheduled": len(eligible),
                "currentNumberScheduled": len(eligible),
                "numberReady": ready,
                "updatedNumberScheduled": ready,
                "observedGeneration": ds["metadata"].get("generation", 1),
            }
            self._update_status_quiet(DAEMON_SETS, ds)

    def _reconcile_deployments(self) -> None:
        for deploy in self._list(DEPLOYMENTS):
            template = deploy["spec"].get("template", {})
            thash = _template_hash(template)
            replicas = int(deploy["spec"].get("replicas", 1) or 1)
            ns = deploy["metadata"]["namespace"]
            pods = self._pods_of(deploy["metadata"]["uid"])
            current = [
                p for p in pods
                if p["metadata"].get("labels", {}).get(
                    "minicluster/template-hash"
                ) == thash
            ]
            stale = [p for p in pods if p not in current]
            for p in stale:
                self._delete_quiet(PODS, ns, p["metadata"]["name"])
            node = template.get("spec", {}).get("nodeName") or (
                self.node_names[0]
            )
            while len(current) < replicas:
                name = (
                    f"{deploy['metadata']['name']}-{thash[:6]}-"
                    f"{uuidlib.uuid4().hex[:5]}"
                )
                self._make_pod(
                    ns, name, template, deploy, "Deployment", node,
                    extra_labels={"minicluster/template-hash": thash},
                )
                current.append({"metadata": {"name": name}})
            ready = sum(
                1 for p in current
                if "uid" in p.get("metadata", {}) and self._pod_ready(p)
            )
            deploy["status"] = {
                "observedGeneration": deploy["metadata"].get(
                    "generation", 1
                ),
                "replicas": len(current),
                "updatedReplicas": len(current),
                "readyReplicas": ready,
                "availableReplicas": ready,
            }
            self._update_status_quiet(DEPLOYMENTS, deploy)

    def _reconcile_jobs(self) -> None:
        for job in self._list(JOBS):
            spec = job.get("spec", {})
            template = spec.get("template", {})
            parallelism = int(spec.get("parallelism", 1) or 1)
            completions = int(spec.get("completions", parallelism) or 1)
            backoff_limit = int(spec.get("backoffLimit", 6) or 6)
            ns = job["metadata"]["namespace"]
            jname = job["metadata"]["name"]
            pods = self._pods_of(job["metadata"]["uid"])
            by_index: Dict[int, List[dict]] = {}
            # Failures accumulate across replaced-and-deleted pods.
            failed = self._job_failures.get(job["metadata"]["uid"], 0)
            for p in pods:
                idx = int(p["metadata"].get("annotations", {}).get(
                    "batch.kubernetes.io/job-completion-index", 0
                ))
                by_index.setdefault(idx, []).append(p)
                if (p.get("status") or {}).get("phase") == "Failed":
                    failed += 1
            succeeded = sum(
                1 for idx, ps in by_index.items()
                if any(
                    (p.get("status") or {}).get("phase") == "Succeeded"
                    for p in ps
                )
            )
            conditions = (job.get("status") or {}).get("conditions", [])
            complete = any(
                c.get("type") == "Complete" and c.get("status") == "True"
                for c in conditions
            )
            if succeeded >= completions:
                if not complete:
                    job["status"] = {
                        "succeeded": succeeded, "failed": failed,
                        "conditions": [{
                            "type": "Complete", "status": "True",
                        }],
                    }
                    self._update_status_quiet(JOBS, job)
                continue
            if failed > backoff_limit:
                job["status"] = {
                    "succeeded": succeeded, "failed": failed,
                    "conditions": [{"type": "Failed", "status": "True"}],
                }
                self._update_status_quiet(JOBS, job)
                continue
            for idx in range(parallelism):
                ps = by_index.get(idx, [])
                if any(
                    (p.get("status") or {}).get("phase") == "Succeeded"
                    for p in ps
                ):
                    continue
                live = [
                    p for p in ps
                    if (p.get("status") or {}).get("phase")
                    not in ("Failed",)
                ]
                if live:
                    continue
                # Replace-and-delete (podReplacementPolicy analog): a
                # Failed worker still OWNS its template-generated claims
                # (released on pod deletion), so leaving it would starve
                # its own replacement of the very devices it needs.
                for p in ps:
                    self._job_failures[job["metadata"]["uid"]] = (
                        self._job_failures.get(
                            job["metadata"]["uid"], 0
                        ) + 1
                    )
                    self._delete_quiet(
                        PODS, ns, p["metadata"]["name"]
                    )
                self._make_pod(
                    ns,
                    f"{jname}-{idx}-{uuidlib.uuid4().hex[:5]}",
                    template, job, "Job", None,
                    extra_labels={"job-name": jname},
                    extra_annotations={
                        "batch.kubernetes.io/job-completion-index": str(idx),
                    },
                )
            job["status"] = {
                "succeeded": succeeded, "failed": failed,
                "active": max(0, len(pods) - succeeded - failed),
                "conditions": conditions,
            }
            self._update_status_quiet(JOBS, job)

    # --- kubelet + binder ---

    def _pod_ready(self, pod: dict) -> bool:
        sandbox = self.sandboxes.get(pod["metadata"].get("uid", ""))
        return sandbox is not None and sandbox.all_ready()

    def _reconcile_pods(self) -> None:
        pods = self._list(PODS)
        seen_uids = set()
        for pod in pods:
            uid = pod["metadata"]["uid"]
            seen_uids.add(uid)
            sandbox = self.sandboxes.get(uid)
            try:
                if sandbox is None:
                    phase = (pod.get("status") or {}).get("phase")
                    if phase in ("Succeeded", "Failed"):
                        continue  # terminal before restart? leave it
                    if not phase:
                        # Admission stamps Pending immediately (real
                        # apiserver/kubelet behavior): a pod held back
                        # by failing prepares must READ as Pending.
                        pod.setdefault("status", {})["phase"] = "Pending"
                        self._update_status_quiet(PODS, pod)
                    with self._alloc_lock:
                        # Test-and-set under the lock: the pool thread
                        # discards the uid in _admit_async's finally —
                        # unlocked, a pod finishing admission right here
                        # could be submitted twice.
                        if uid not in self._admitting:
                            self._admitting.add(uid)
                            self._admit_pool.submit(self._admit_async, pod)
                else:
                    self._sync_pod_status(pod, sandbox)
            except Exception:  # noqa: BLE001 — one broken pod must not
                # starve every pod after it in the list (a kubelet
                # isolates pod sync failures the same way).
                log.exception(
                    "pod %s/%s reconcile failed; backing off",
                    pod["metadata"].get("namespace"),
                    pod["metadata"]["name"],
                )
                with self._alloc_lock:
                    self.next_attempt[uid] = (
                        time.monotonic() + PREPARE_BACKOFF_SECONDS
                    )
        # Pods whose objects are gone: tear down.
        for uid in list(self.sandboxes):
            if uid not in seen_uids:
                self._teardown_pod(uid)

    def _claims_of(self, pod: dict) -> Optional[List[dict]]:
        """Resolve (creating from templates as needed) every claim the
        pod references; None while templates are still missing."""
        ns = pod["metadata"].get("namespace", "default")
        statuses = {
            s["name"]: s.get("resourceClaimName")
            for s in (pod.get("status") or {}).get(
                "resourceClaimStatuses", []
            ) or []
        }
        claims = []
        dirty = False
        for ref in pod["spec"].get("resourceClaims", []) or []:
            refname = ref["name"]
            template_name = (
                ref.get("resourceClaimTemplateName")
                or (ref.get("source") or {}).get(
                    "resourceClaimTemplateName"
                )
            )
            claim_name = ref.get("resourceClaimName") or (
                ref.get("source") or {}
            ).get("resourceClaimName")
            if claim_name:
                claim = self._try_get(RESOURCE_CLAIMS, ns, claim_name)
                if claim is None:
                    return None
                claims.append(claim)
                continue
            if not template_name:
                continue
            existing_name = statuses.get(refname)
            if existing_name:
                claim = self._try_get(RESOURCE_CLAIMS, ns, existing_name)
                if claim is not None:
                    claims.append(claim)
                    continue
            template = self._try_get(
                RESOURCE_CLAIM_TEMPLATES, ns, template_name
            )
            if template is None:
                return None  # e.g. CD channel RCT not stamped yet
            claim = self.fc.create(RESOURCE_CLAIMS, {
                "apiVersion": RESOURCE_CLAIMS.api_version,
                "kind": "ResourceClaim",
                "metadata": {
                    "generateName": (
                        f"{pod['metadata']['name']}-{refname}-"
                    ),
                    "namespace": ns,
                    "ownerReferences": [_owner_ref(pod, "Pod")],
                    "annotations": {
                        "resource.kubernetes.io/pod-claim-name": refname,
                    },
                },
                "spec": copy.deepcopy(
                    template.get("spec", {}).get("spec", {})
                ),
            })
            statuses[refname] = claim["metadata"]["name"]
            claims.append(claim)
            dirty = True
        dirty |= self._bridge_extended_resources(pod, ns, statuses, claims)
        if dirty:
            pod.setdefault("status", {})["resourceClaimStatuses"] = [
                {"name": k, "resourceClaimName": v}
                for k, v in statuses.items()
            ]
            self._update_status_quiet(PODS, pod)
        return claims

    def _bridge_extended_resources(
        self, pod: dict, ns: str, statuses: Dict[str, str],
        claims: List[dict],
    ) -> bool:
        """Extended-resource → DRA bridging (reference: DeviceClass
        ``spec.extendedResourceName`` on resource.k8s.io/v1,
        deployments/helm/.../deviceclass-gpu.yaml:13, exercised by
        tests/bats/test_gpu_extres.bats): a classic ``resources.limits:
        {google.com/tpu: N}`` pod gets a scheduler-synthesized
        ResourceClaim against the bridging DeviceClass — one request per
        consuming container, GA `exactly` schema — and is then bound,
        allocated, and prepared exactly like an explicit DRA pod.
        Returns True when pod.status.resourceClaimStatuses changed."""
        wanted: Dict[str, int] = {}  # extended resource name -> total
        per_container: List[Tuple[str, str, int]] = []
        for c in pod["spec"].get("containers", []) or []:
            limits = ((c.get("resources") or {}).get("limits") or {})
            for rname, amount in limits.items():
                # Extended resources are domain-qualified ("vendor/res");
                # native resources (cpu, memory, hugepages-*) never are.
                if "/" not in rname:
                    continue
                n = _int_quantity(amount)
                if n > 0:
                    wanted[rname] = wanted.get(rname, 0) + n
                    per_container.append((c["name"], rname, n))
        if not wanted:
            return False
        bridges = {}
        for dc in self._list(DEVICE_CLASSES):
            ern = (dc.get("spec") or {}).get("extendedResourceName")
            if ern in wanted:
                bridges[ern] = dc["metadata"]["name"]
        dirty = False
        for rname, total in wanted.items():
            class_name = bridges.get(rname)
            if class_name is None:
                continue  # not bridged: classic device-plugin territory
            refname = f"extres:{rname}"
            existing = statuses.get(refname)
            if existing:
                claim = self._try_get(RESOURCE_CLAIMS, ns, existing)
                if claim is not None:
                    claims.append(claim)
                    continue
            requests = [
                {
                    "name": f"container-{i}",
                    "exactly": {
                        "deviceClassName": class_name,
                        "allocationMode": "ExactCount",
                        "count": n,
                    },
                }
                for i, (_, rn, n) in enumerate(per_container)
                if rn == rname
            ]
            claim = self.fc.create(RESOURCE_CLAIMS, {
                "apiVersion": RESOURCE_CLAIMS.api_version,
                "kind": "ResourceClaim",
                "metadata": {
                    "generateName": f"{pod['metadata']['name']}-extres-",
                    "namespace": ns,
                    "ownerReferences": [_owner_ref(pod, "Pod")],
                    "annotations": {
                        "resource.kubernetes.io/extended-resource-name":
                            rname,
                    },
                },
                "spec": {"devices": {"requests": requests}},
            })
            statuses[refname] = claim["metadata"]["name"]
            claims.append(claim)
            dirty = True
        return dirty

    def _allocate_for_node(self, node: str, pending: List[dict],
                           classes, slices, allocated) -> Optional[List[dict]]:
        """Try to allocate all `pending` claims on `node`; returns the
        allocation dicts (same order) or None."""
        node_slices = [
            s for s in slices
            if s.get("spec", {}).get("nodeName") in (node, None)
        ]
        hypothetical = list(allocated)
        out = []
        for claim in pending:
            alloc = Allocator(classes, node_slices, hypothetical)
            try:
                result = alloc.allocate(claim)
            except Unschedulable:
                return None
            except Exception as e:  # noqa: BLE001 — allocator bug, not
                # a full node: surface it instead of retrying forever.
                log.warning("allocator error for %s: %s",
                            claim["metadata"]["name"], e)
                return None
            out.append(result.allocation)
            ghost = copy.deepcopy(claim)
            ghost.setdefault("status", {})["allocation"] = (
                result.allocation
            )
            hypothetical.append(ghost)
        return out

    def _admit_async(self, pod: dict) -> None:
        uid = pod["metadata"]["uid"]
        try:
            self._admit_pod(pod)
        except Exception:  # noqa: BLE001
            log.exception(
                "pod %s/%s admission failed; backing off",
                pod["metadata"].get("namespace"), pod["metadata"]["name"],
            )
            with self._alloc_lock:
                self.next_attempt[uid] = (
                    time.monotonic() + PREPARE_BACKOFF_SECONDS
                )
        finally:
            with self._alloc_lock:
                self._admitting.discard(uid)

    def _admit_pod(self, pod: dict) -> None:
        uid = pod["metadata"]["uid"]
        now = time.monotonic()
        if self.next_attempt.get(uid, 0) > now:
            return
        with self._alloc_lock:
            node = self._bind_pod_locked(pod, uid, now)
        if node is None:
            return
        self._prepare_and_launch(pod, node)

    def _bind_pod_locked(
        self, pod: dict, uid: str, now: float
    ) -> Optional[str]:
        """Claims + allocation + reservation + node binding; the caller
        holds the binder lock (`_locked` suffix — R200 convention).
        Returns the bound node or None to retry later."""
        ns = pod["metadata"].get("namespace", "default")
        claims = self._claims_of(pod)
        if claims is None:
            self.next_attempt[uid] = now + 1.0
            return None
        pending = [
            c for c in claims
            if not (c.get("status") or {}).get("allocation")
        ]
        node = pod["spec"].get("nodeName")
        if pending:
            classes = self._list(DEVICE_CLASSES)
            slices = self._list(RESOURCE_SLICES)
            allocated = [
                c for c in self._list(RESOURCE_CLAIMS)
                if (c.get("status") or {}).get("allocation")
            ]
            if node:
                candidates = [node]
            else:
                # Scheduler filter phase: the pod's nodeSelector prunes
                # candidates before the allocator scores them.
                selector = pod["spec"].get("nodeSelector")
                candidates = [
                    n["metadata"]["name"] for n in self._list(NODES)
                    if _match_node_selector(
                        selector, n["metadata"].get("labels", {}) or {}
                    )
                ]
            chosen = None
            for cand in candidates:
                allocs = self._allocate_for_node(
                    cand, pending, classes, slices, allocated
                )
                if allocs is not None:
                    chosen = (cand, allocs)
                    break
            if chosen is None:
                self.next_attempt[uid] = now + 1.0
                return None
            node, allocs = chosen
            for claim, alloc in zip(pending, allocs):
                claim.setdefault("status", {})["allocation"] = alloc
                self._update_status_quiet(RESOURCE_CLAIMS, claim)
        if node is None:
            # No (pending) claims: place on any node passing the selector.
            selector = pod["spec"].get("nodeSelector")
            matching = [
                n["metadata"]["name"] for n in self._list(NODES)
                if _match_node_selector(
                    selector, n["metadata"].get("labels", {}) or {}
                )
            ]
            if not matching:
                self.next_attempt[uid] = now + 1.0
                return None
            node = matching[0]
        if pod["spec"].get("nodeName") != node:
            pod["spec"]["nodeName"] = node
            pod["metadata"]["resourceVersion"] = None
            try:
                self.fc.update(PODS, pod)
            except K8sApiError:
                return None
        # Reserve every claim for this pod.
        for claim in claims:
            live = self._try_get(
                RESOURCE_CLAIMS, ns, claim["metadata"]["name"]
            )
            if live is None:
                return None
            reserved = live.setdefault("status", {}).setdefault(
                "reservedFor", []
            )
            if not any(r.get("uid") == uid for r in reserved):
                reserved.append({
                    "resource": "pods",
                    "name": pod["metadata"]["name"],
                    "uid": uid,
                })
                self._update_status_quiet(RESOURCE_CLAIMS, live)
        return node

    def _prepare_and_launch(self, pod: dict, node: str) -> None:
        uid = pod["metadata"]["uid"]
        ns = pod["metadata"].get("namespace", "default")
        claims = self._claims_of(pod) or []
        rootfs = self.runner.node_rootfs(node)
        prepared_here: Dict[str, Tuple[str, str, str, str]] = {}
        cdi_env_by_claim_ref: Dict[str, Dict[str, str]] = {}
        ref_by_claim_name = {}
        for ref in pod["spec"].get("resourceClaims", []) or []:
            refname = ref["name"]
            claim_name = ref.get("resourceClaimName") or (
                ref.get("source") or {}
            ).get("resourceClaimName")
            if claim_name:
                ref_by_claim_name[claim_name] = refname
        statuses = {
            s.get("resourceClaimName"): s["name"]
            for s in (pod.get("status") or {}).get(
                "resourceClaimStatuses", []
            ) or []
        }
        ref_by_claim_name.update(statuses)
        try:
            for claim in claims:
                alloc = (claim.get("status") or {}).get("allocation") or {}
                results = (alloc.get("devices") or {}).get("results", [])
                drivers = sorted({
                    r.get("driver", "") for r in results if r.get("driver")
                })
                env: Dict[str, str] = {}
                for driver in drivers:
                    sock = (
                        rootfs / "var/lib/kubelet/plugins" / driver
                        / "dra.sock"
                    )
                    if not _socket_connectable(sock):
                        raise RuntimeError(
                            f"plugin socket for {driver} not up on {node}"
                        )
                    self._grpc_prepare(sock, claim)
                    prepared_here[claim["metadata"]["uid"]] = (
                        ns, claim["metadata"]["name"], driver, node,
                    )
                env.update(self._cdi_env(
                    rootfs, claim["metadata"]["uid"]
                ))
                refname = ref_by_claim_name.get(
                    claim["metadata"]["name"], claim["metadata"]["name"]
                )
                cdi_env_by_claim_ref[refname] = env
        except Exception as e:  # noqa: BLE001 — prepare failures retry
            log.info(
                "pod %s/%s prepare: %s (will retry)",
                ns, pod["metadata"]["name"], e,
            )
            # Claims prepared before the failure stay prepared (prepare
            # is idempotent); the retry reuses them.
            with self._alloc_lock:
                self.prepared.setdefault(uid, {}).update(prepared_here)
                self.next_attempt[uid] = (
                    time.monotonic() + PREPARE_BACKOFF_SECONDS
                )
            return
        with self._alloc_lock:
            self.prepared.setdefault(uid, {}).update(prepared_here)

        # Per-container env: only the claims the container asks for —
        # explicit resources.claims refs, plus bridged extended-resource
        # claims for containers with a matching resources.limits entry.
        by_container: Dict[str, Dict[str, str]] = {}
        for c in pod["spec"].get("containers", []) or []:
            env: Dict[str, str] = {}
            for cl in (c.get("resources") or {}).get("claims", []) or []:
                env.update(cdi_env_by_claim_ref.get(cl.get("name"), {}))
            limits = ((c.get("resources") or {}).get("limits") or {})
            for refname, claim_env in cdi_env_by_claim_ref.items():
                if not refname.startswith("extres:"):
                    continue
                rname = refname[len("extres:"):]
                # Amount-aware: a container with an explicit 0 limit
                # opted out and must not receive the device env.
                if _int_quantity(limits.get(rname, 0)) > 0:
                    env.update(claim_env)
            by_container[c["name"]] = env
        extra = {
            "TPU_DRA_MULTIPLEX_SOCKET_ROOT": str(
                rootfs / "run/tpu-multiplex"
            ),
            # A containerized CD daemon rewrites its own /etc/hosts; a
            # host process must NEVER touch the real one.
            "CD_HOSTS_PATH": str(rootfs / "etc/hosts"),
        }
        idx = (pod["metadata"].get("annotations") or {}).get(
            "batch.kubernetes.io/job-completion-index"
        )
        if idx is not None:
            extra["JOB_COMPLETION_INDEX"] = str(idx)
        pod["status"] = {
            **(pod.get("status") or {}),
            "phase": "Pending", "podIP": "127.0.0.1",
        }
        self._update_status_quiet(PODS, pod)
        sandbox = self.runner.launch(
            pod, extra_env=extra, extra_env_by_container=by_container
        )
        if sandbox.init_failed:
            log.warning(
                "pod %s/%s init: %s", ns, pod["metadata"]["name"],
                sandbox.init_failed,
            )
            with self._alloc_lock:
                self.next_attempt[uid] = (
                    time.monotonic() + PREPARE_BACKOFF_SECONDS
                )
            return
        with self._alloc_lock:
            self.sandboxes[uid] = sandbox
            self.next_attempt.pop(uid, None)

    def _grpc_prepare(self, sock: Path, claim: dict) -> None:
        import grpc

        from tpu_dra.plugin.dra_service import DRA_SERVICE_NAME
        from tpu_dra.plugin.pb import dra_v1beta1_pb2 as drapb

        req = drapb.NodePrepareResourcesRequest()
        req.claims.append(drapb.Claim(
            uid=claim["metadata"]["uid"],
            name=claim["metadata"]["name"],
            namespace=claim["metadata"]["namespace"],
        ))
        with grpc.insecure_channel(f"unix://{sock}") as ch:
            resp = ch.unary_unary(
                f"/{DRA_SERVICE_NAME}/NodePrepareResources",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=(
                    drapb.NodePrepareResourcesResponse.FromString
                ),
            )(req, timeout=30)
        result = resp.claims[claim["metadata"]["uid"]]
        if result.error:
            raise RuntimeError(result.error)

    def _grpc_unprepare(self, sock: Path, cns: str, cname: str,
                        cuid: str) -> None:
        import grpc

        from tpu_dra.plugin.dra_service import DRA_SERVICE_NAME
        from tpu_dra.plugin.pb import dra_v1beta1_pb2 as drapb

        req = drapb.NodeUnprepareResourcesRequest()
        req.claims.append(drapb.Claim(
            uid=cuid, name=cname, namespace=cns,
        ))
        with grpc.insecure_channel(f"unix://{sock}") as ch:
            resp = ch.unary_unary(
                f"/{DRA_SERVICE_NAME}/NodeUnprepareResources",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=(
                    drapb.NodeUnprepareResourcesResponse.FromString
                ),
            )(req, timeout=30)
        result = resp.claims[cuid]
        if result.error:
            raise RuntimeError(result.error)

    @staticmethod
    def _cdi_env(rootfs: Path, claim_uid: str) -> Dict[str, str]:
        """Env from the claim's CDI spec containerEdits. Containers get
        CDI mounts for free; host processes can't mount, so env values
        that point INTO a CDI mount's containerPath are rewritten to the
        mount's hostPath (which the plugin already wrote node-sandbox-
        absolute)."""
        env: Dict[str, str] = {}
        mounts: Dict[str, str] = {}  # containerPath -> hostPath
        dev_nodes: List[str] = []
        cdi_dir = rootfs / "var/run/cdi"
        if not cdi_dir.is_dir():
            return env
        for f in cdi_dir.glob("*.json"):
            if claim_uid not in f.name:
                continue
            spec = json.loads(f.read_text())
            for d in spec.get("devices", []):
                edits = d.get("containerEdits") or {}
                for m in edits.get("mounts", []) or []:
                    cp = (m.get("containerPath") or "").rstrip("/")
                    if cp and m.get("hostPath"):
                        mounts[cp] = m["hostPath"]
                for dn in edits.get("deviceNodes", []) or []:
                    if dn.get("path"):
                        dev_nodes.append(dn["path"])
                for kv in edits.get("env", []):
                    k, _, v = kv.partition("=")
                    env[k] = v
        if dev_nodes:
            # Containers get these injected as real /dev nodes by the CDI
            # runtime; host-process pods get the inode PATHS instead (the
            # stub advertises node-sandbox-absolute paths), so a workload
            # can open — and a device-gate drill can probe — its chips.
            env["TPU_DRA_DEVICE_NODES"] = ",".join(sorted(set(dev_nodes)))
        for k, v in env.items():
            for cp in sorted(mounts, key=len, reverse=True):
                if v == cp or v.startswith(cp + "/"):
                    env[k] = mounts[cp] + v[len(cp):]
                    break
        return env

    def _sync_pod_status(self, pod: dict, sandbox: PodSandbox) -> None:
        restart_policy = pod["spec"].get("restartPolicy", "Always")
        phase = sandbox.phase(restart_policy)
        prev = (pod.get("status") or {}).get("phase")
        uid = pod["metadata"]["uid"]
        if phase in ("Succeeded", "Failed") and (
            restart_policy == "Always"
            or (restart_policy == "OnFailure" and phase == "Failed")
        ):
            # Service pods (DS/Deployment) restart in place, like a
            # kubelet restarting a crashed container: same pod object,
            # bumped restartCount, exponential-ish backoff. Claims stay
            # prepared — re-admission re-prepares idempotently.
            sandbox.kill()
            with self._alloc_lock:
                self.sandboxes.pop(uid, None)
                n = self.restarts.get(uid, 0) + 1
                self.restarts[uid] = n
                self.next_attempt[uid] = (
                    time.monotonic() + min(5.0, 0.5 * n)
                )
            status = pod.setdefault("status", {})
            status["phase"] = "Running"
            status["conditions"] = [
                {"type": "Ready", "status": "False"},
                {"type": "ContainersReady", "status": "False"},
            ]
            self._update_status_quiet(PODS, pod)
            return
        ready = sandbox.all_ready()
        status = pod.setdefault("status", {})
        status["phase"] = phase
        status["podIP"] = "127.0.0.1"
        status["conditions"] = [
            {"type": "Ready", "status": "True" if ready else "False"},
            {"type": "ContainersReady",
             "status": "True" if ready else "False"},
        ]
        status["containerStatuses"] = [
            {
                "name": c.name,
                "ready": c.ready(),
                "restartCount": self.restarts.get(uid, 0),
                "state": (
                    {"running": {}} if c.alive() else {
                        "terminated": {"exitCode": c.proc.returncode}
                    }
                ),
            }
            for c in sandbox.containers
        ]
        self._update_status_quiet(PODS, pod)
        if phase in ("Succeeded", "Failed") and prev not in (
            "Succeeded", "Failed"
        ):
            self._release_pod_claims(pod["metadata"]["uid"], delete=False)

    def _teardown_pod(self, uid: str) -> None:
        # Claim-the-sandbox under the lock: the reaper thread and the
        # reconcile sweep both tear down; whoever pops kills. The kill
        # itself runs unlocked (it waits on processes).
        with self._alloc_lock:
            sandbox = self.sandboxes.pop(uid, None)
        if sandbox is not None:
            sandbox.kill()
        self._release_pod_claims(uid, delete=True)
        with self._alloc_lock:
            self.next_attempt.pop(uid, None)
            self.released.discard(uid)

    def _release_pod_claims(self, uid: str, delete: bool) -> None:
        """Pod done (terminal or deleted): unprepare what this pod held
        (when no other live pod still reserves it), drop the reservedFor
        entry, and deallocate standalone claims left unreserved. Claims
        created from templates are ownerRef'd to the pod — the owner GC
        deletes them on pod deletion, releasing their devices."""
        with self._alloc_lock:
            if not delete and uid in self.released:
                return
            self.released.add(uid)
            held = self.prepared.pop(uid, {})
        for cuid, (cns, cname, driver, node) in held.items():
            claim = self._try_get(RESOURCE_CLAIMS, cns, cname)
            if claim is not None:
                reserved = (claim.get("status") or {}).get(
                    "reservedFor", []
                ) or []
                reserved = [r for r in reserved if r.get("uid") != uid]
                others_live = any(
                    r.get("uid") in self.sandboxes
                    and r.get("uid") not in self.released
                    for r in reserved
                )
                claim.setdefault("status", {})["reservedFor"] = reserved
                owned_by_pod = any(
                    (ref.get("kind") == "Pod")
                    for ref in claim["metadata"].get(
                        "ownerReferences"
                    ) or []
                )
                if not reserved and not owned_by_pod:
                    # Standalone claim, no consumers left: deallocate
                    # (frees devices/counters for the next pod).
                    claim["status"].pop("allocation", None)
                self._update_status_quiet(RESOURCE_CLAIMS, claim)
                if others_live:
                    continue  # shared claim still in use: stay prepared
            sock = (
                self.runner.node_rootfs(node)
                / "var/lib/kubelet/plugins" / driver / "dra.sock"
            )
            try:
                self._grpc_unprepare(sock, cns, cname, cuid)
            except Exception as e:  # noqa: BLE001
                log.info("unprepare %s/%s: %s", cns, cname, e)

    def _reconcile_claims(self) -> None:
        """reservedFor hygiene: drop entries for pods that no longer
        exist (force-deleted mid-flight), deallocating standalone claims
        that end up unreserved."""
        pod_uids = {
            p["metadata"]["uid"] for p in self._list(PODS)
        }
        for claim in self._list(RESOURCE_CLAIMS):
            status = claim.get("status") or {}
            reserved = status.get("reservedFor") or []
            if not reserved:
                continue
            keep = [r for r in reserved if r.get("uid") in pod_uids]
            if len(keep) == len(reserved):
                continue
            claim["status"]["reservedFor"] = keep
            owned_by_pod = any(
                ref.get("kind") == "Pod"
                for ref in claim["metadata"].get("ownerReferences") or []
            )
            if not keep and not owned_by_pod and status.get("allocation"):
                claim["status"].pop("allocation", None)
            self._update_status_quiet(RESOURCE_CLAIMS, claim)
