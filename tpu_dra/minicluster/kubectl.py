"""kubectl shim: the verb/flag subset the bats e2e suites use, speaking
the fakeserver's REST API through the production transport
(rest.KubeClient + KUBECONFIG), so every suite assertion exercises the
same wire path a real kubectl would.

Supported: apply/delete -f (file or '-'); create namespace
[--dry-run=client -o yaml]; get (json/yaml/name/wide/jsonpath/
no-headers, -A, -l, -n); delete <kind> <names...>/-l; wait
--for=condition=X|jsonpath={p}=v; rollout status ds|deploy/NAME; logs
(-c, -l, --tail); api-versions. Pod logs are read from the
minicluster's log directory (MINICLUSTER_DIR), the kubectl analog of
the kubelet's log endpoint.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from typing import Dict, List, Optional

import yaml

from tpu_dra.k8sclient.resources import (
    ApiNotFound,
    K8sApiError,
    ResourceDescriptor,
    iter_descriptors,
)
from tpu_dra.k8sclient.rest import KubeClient


def _registry():
    by_alias: Dict[str, ResourceDescriptor] = {}
    for d in iter_descriptors():
        by_alias[d.plural] = d
        by_alias[d.kind.lower()] = d
        singular = d.plural[:-1] if d.plural.endswith("s") else d.plural
        by_alias.setdefault(singular, d)
    # kubectl-isms
    by_alias["crd"] = by_alias["customresourcedefinitions"]
    by_alias["crds"] = by_alias["customresourcedefinitions"]
    by_alias["ds"] = by_alias["daemonsets"]
    by_alias["deploy"] = by_alias["deployments"]
    by_alias["ns"] = by_alias["namespaces"]
    by_alias["po"] = by_alias["pods"]
    return by_alias


REGISTRY = _registry()


class Args:
    """Loose kubectl-style argv: flags anywhere, positionals in order."""

    def __init__(self, argv: List[str]):
        self.namespace: Optional[str] = None
        self.all_namespaces = False
        self.output: Optional[str] = None
        self.selector: Optional[str] = None
        self.filename: Optional[str] = None
        self.ignore_not_found = False
        self.timeout: Optional[float] = None
        self.wait_for: Optional[str] = None
        self.container: Optional[str] = None
        self.tail: Optional[int] = None
        self.no_headers = False
        self.dry_run: Optional[str] = None
        self.positionals: List[str] = []
        i = 0
        while i < len(argv):
            a = argv[i]
            if a in ("-n", "--namespace"):
                self.namespace = argv[i + 1]
                i += 1
            elif a == "-A" or a == "--all-namespaces":
                self.all_namespaces = True
            elif a == "-o" or a == "--output":
                self.output = argv[i + 1]
                i += 1
            elif a.startswith("-o"):
                self.output = a[2:]
            elif a.startswith("--output="):
                self.output = a.split("=", 1)[1]
            elif a == "-l" or a == "--selector":
                self.selector = argv[i + 1]
                i += 1
            elif a == "-f" or a == "--filename":
                self.filename = argv[i + 1]
                i += 1
            elif a == "--ignore-not-found":
                self.ignore_not_found = True
            elif a.startswith("--timeout"):
                raw = (
                    a.split("=", 1)[1] if "=" in a else argv[(i := i + 1)]
                )
                self.timeout = _parse_duration(raw)
            elif a.startswith("--for="):
                self.wait_for = a.split("=", 1)[1]
            elif a == "--for":
                self.wait_for = argv[i + 1]
                i += 1
            elif a == "-c" or a == "--container":
                self.container = argv[i + 1]
                i += 1
            elif a.startswith("--tail="):
                self.tail = int(a.split("=", 1)[1])
            elif a == "--tail":
                self.tail = int(argv[i + 1])
                i += 1
            elif a == "--no-headers":
                self.no_headers = True
            elif a.startswith("--dry-run"):
                self.dry_run = a.split("=", 1)[1] if "=" in a else "client"
            elif a in ("--force", "--create-namespace", "--wait"):
                pass
            elif a.startswith("--grace-period"):
                if "=" not in a:
                    i += 1
            else:
                self.positionals.append(a)
            i += 1

    def label_selector(self) -> Optional[Dict[str, str]]:
        if not self.selector:
            return None
        out = {}
        for part in self.selector.split(","):
            k, _, v = part.partition("=")
            out[k.strip()] = v.strip()
        return out


def _parse_duration(raw: str) -> float:
    m = re.fullmatch(r"(\d+)(s|m|h)?", raw)
    if not m:
        return 30.0
    mult = {"s": 1, "m": 60, "h": 3600, None: 1}[m.group(2)]
    return int(m.group(1)) * mult


def jsonpath(expr: str, obj) -> str:
    """The `{.a.b[0].c}` subset kubectl's suites use. Multiple `{...}`
    groups are space-joined (kubectl behavior)."""
    out_parts = []
    for group in re.findall(r"\{([^}]*)\}", expr):
        cur = obj
        for tok in re.findall(r"\.([A-Za-z0-9_-]+)|\[(\d+)\]", group):
            field, index = tok
            if cur is None:
                break
            if field:
                if not isinstance(cur, dict):
                    cur = None
                    break
                cur = cur.get(field)
            else:
                idx = int(index)
                if not isinstance(cur, list) or idx >= len(cur):
                    cur = None
                    break
                cur = cur[idx]
        if cur is None:
            out_parts.append("")
        elif isinstance(cur, (dict, list)):
            out_parts.append(json.dumps(cur))
        else:
            out_parts.append(str(cur))
    return " ".join(out_parts).rstrip()


def _client() -> KubeClient:
    return KubeClient.from_config(qps=1000, burst=1000)


def _resolve_kind(token: str) -> Optional[ResourceDescriptor]:
    return REGISTRY.get(token.lower())


def _split_slash(token: str):
    """'pod/name' -> (rd, name); plain token -> (None, token)."""
    if "/" in token:
        kind, _, name = token.partition("/")
        return _resolve_kind(kind), name
    return None, token


def _slash_targets(pos: List[str]):
    """Parse EVERY positional as TYPE/name (kubectl slash-form
    semantics — honoring only pos[0] silently dropped the rest, found
    by the 16-node scale drill where each churn round's
    `delete pod/a pod/b pod/c pod/d` leaked three Succeeded pods whose
    claims eventually held all 64 chips). Returns (targets, error):
    targets is [(rd, name)]; error is a printable message
    distinguishing a bare token from a typo'd kind."""
    out = []
    for p in pos:
        if "/" not in p:
            return None, f"expected TYPE/name, got {p!r}"
        kind, _, name = p.partition("/")
        rd = _resolve_kind(kind)
        if rd is None:
            return None, f"unknown kind {kind!r} in {p!r}"
        out.append((rd, name))
    return out, None


def _load_docs(filename: str) -> List[dict]:
    text = (
        sys.stdin.read() if filename == "-" else open(filename).read()
    )
    docs = []
    for doc in yaml.safe_load_all(text):
        if not doc:
            continue
        if doc.get("kind", "").endswith("List"):
            docs.extend(doc.get("items") or [])
        else:
            docs.append(doc)
    return docs


def _rd_for_doc(doc: dict) -> Optional[ResourceDescriptor]:
    for d in iter_descriptors():
        if (
            d.api_version == doc.get("apiVersion")
            and d.kind == doc.get("kind")
        ):
            return d
    return None


def cmd_apply(kc: KubeClient, args: Args) -> int:
    rc = 0
    for doc in _load_docs(args.filename):
        rd = _rd_for_doc(doc)
        if rd is None:
            print(
                f"error: unsupported {doc.get('apiVersion')}/"
                f"{doc.get('kind')}", file=sys.stderr,
            )
            rc = 1
            continue
        md = doc.setdefault("metadata", {})
        if rd.namespaced and args.namespace and not md.get("namespace"):
            md["namespace"] = args.namespace
        name = md.get("name", md.get("generateName", "?"))
        try:
            try:
                kc.create(rd, doc)
                print(f"{rd.plural}/{name} created")
            except K8sApiError as e:
                if getattr(e, "status", None) != 409:
                    raise
                kc.patch(
                    rd, md.get("namespace"), md["name"],
                    {k: v for k, v in doc.items() if k != "metadata"},
                )
                print(f"{rd.plural}/{name} configured")
        except K8sApiError as e:
            print(f"error: {rd.plural}/{name}: {e}", file=sys.stderr)
            rc = 1
    return rc


def cmd_delete(kc: KubeClient, args: Args) -> int:
    targets: List[tuple] = []  # (rd, namespace, name)
    if args.filename:
        for doc in _load_docs(args.filename):
            rd = _rd_for_doc(doc)
            if rd is None:
                continue
            ns = doc.get("metadata", {}).get("namespace") or args.namespace
            targets.append((rd, ns, doc["metadata"]["name"]))
    else:
        pos = list(args.positionals)
        rd, name = _split_slash(pos[0])
        if rd is not None:
            slash, err = _slash_targets(pos)
            if err:
                print(f"error: {err}", file=sys.stderr)
                return 1
            targets.extend(
                (prd, args.namespace, pname) for prd, pname in slash
            )
        else:
            rd = _resolve_kind(pos[0])
            if rd is None:
                print(f"error: unknown kind {pos[0]}", file=sys.stderr)
                return 1
            names = pos[1:]
            if not names and args.selector:
                for o in kc.list(
                    rd,
                    None if args.all_namespaces else args.namespace,
                    label_selector=args.label_selector(),
                ):
                    targets.append((
                        rd, o["metadata"].get("namespace"),
                        o["metadata"]["name"],
                    ))
            for n in names:
                targets.append((rd, args.namespace, n))
    rc = 0
    for rd, ns, name in targets:
        try:
            kc.delete(rd, ns if rd.namespaced else None, name)
            print(f"{rd.plural}/{name} deleted")
        except ApiNotFound:
            if not args.ignore_not_found:
                print(
                    f"error: {rd.plural}/{name} not found",
                    file=sys.stderr,
                )
                rc = 1
        except K8sApiError as e:
            print(f"error deleting {rd.plural}/{name}: {e}",
                  file=sys.stderr)
            rc = 1
    # Namespace deletion cascades asynchronously; block (like kubectl)
    # until the contents are gone so follow-on asserts see a clean slate.
    ns_targets = [t for t in targets if t[0].plural == "namespaces"]
    if ns_targets:
        deadline = time.monotonic() + (args.timeout or 60)
        from tpu_dra.k8sclient.resources import PODS, RESOURCE_CLAIMS

        while time.monotonic() < deadline:
            left = 0
            for _, _, name in ns_targets:
                for rd2 in (PODS, RESOURCE_CLAIMS):
                    left += len(kc.list(rd2, name))
            if left == 0:
                break
            time.sleep(0.3)
    return rc


def cmd_create(kc: KubeClient, args: Args) -> int:
    if args.positionals[:1] != ["namespace"]:
        print("create: only 'create namespace' is supported",
              file=sys.stderr)
        return 1
    name = args.positionals[1]
    doc = {
        "apiVersion": "v1", "kind": "Namespace",
        "metadata": {"name": name},
    }
    if args.dry_run:
        print(yaml.safe_dump(doc), end="")
        return 0
    from tpu_dra.k8sclient.resources import NAMESPACES

    try:
        kc.create(NAMESPACES, doc)
        print(f"namespace/{name} created")
    except K8sApiError as e:
        if getattr(e, "status", None) == 409:
            print(f"namespace/{name} unchanged")
            return 0
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


_WIDE_COLS = {
    "pods": lambda o: (
        o["metadata"]["name"],
        (o.get("status") or {}).get("phase", "Pending"),
        (o.get("spec") or {}).get("nodeName", ""),
    ),
}


def cmd_get(kc: KubeClient, args: Args) -> int:
    pos = list(args.positionals)
    if not pos:
        print("get: missing resource", file=sys.stderr)
        return 1
    rd, name = _split_slash(pos[0])
    names: List[str] = []
    if rd is not None:
        # Same multi-target slash-form semantics as delete; this shim
        # requires one resource kind per get.
        slash, err = _slash_targets(pos)
        if err:
            print(f"error: {err}", file=sys.stderr)
            return 1
        kinds = {prd.plural for prd, _ in slash}
        if len(kinds) > 1:
            print(
                f"error: mixed resource kinds in one get not supported "
                f"({sorted(kinds)})", file=sys.stderr,
            )
            return 1
        names = [pname for _, pname in slash]
    else:
        rd = _resolve_kind(pos[0])
        if rd is None:
            print(f"error: unknown kind {pos[0]}", file=sys.stderr)
            return 1
        names = pos[1:]
    ns = None if args.all_namespaces else (
        args.namespace if rd.namespaced else None
    )
    if rd.namespaced and not args.all_namespaces and ns is None:
        ns = "default"
    objs: List[dict] = []
    if names:
        for n in names:
            try:
                objs.append(kc.get(rd, ns, n))
            except ApiNotFound:
                if not args.ignore_not_found:
                    print(
                        f'Error from server (NotFound): {rd.plural} '
                        f'"{n}" not found', file=sys.stderr,
                    )
                    return 1
    else:
        objs = kc.list(rd, ns, label_selector=args.label_selector())
    return _print_objs(rd, objs, args, single=bool(names) and len(names) == 1)


def _print_objs(rd, objs, args: Args, single: bool) -> int:
    out = args.output
    if out == "json":
        if single:
            print(json.dumps(objs[0], indent=2))
        else:
            print(json.dumps({
                "kind": f"{rd.kind}List", "apiVersion": rd.api_version,
                "items": objs,
            }, indent=2))
        return 0
    if out == "yaml":
        print(yaml.safe_dump(objs[0] if single else {
            "kind": f"{rd.kind}List", "items": objs,
        }), end="")
        return 0
    if out == "name":
        for o in objs:
            print(f"{rd.plural[:-1] if rd.plural.endswith('s') else rd.plural}/{o['metadata']['name']}")
        return 0
    if out and out.startswith("jsonpath="):
        expr = out[len("jsonpath="):].strip("'")
        target = objs[0] if single else {"items": objs}
        print(jsonpath(expr, target))
        return 0
    rows = []
    for o in objs:
        fn = _WIDE_COLS.get(rd.plural)
        if fn:
            rows.append("   ".join(str(x) for x in fn(o)))
        else:
            rows.append(o["metadata"]["name"])
    if not args.no_headers and rows:
        print("NAME")
    for r in rows:
        print(r)
    return 0


def cmd_wait(kc: KubeClient, args: Args) -> int:
    pos = list(args.positionals)
    rd = None
    names = []
    for tok in pos:
        trd, name = _split_slash(tok)
        if trd is not None:
            rd = trd
            names.append(name)
        elif _resolve_kind(tok) is not None and rd is None:
            rd = _resolve_kind(tok)
        else:
            names.append(tok)
    if rd is None or not args.wait_for:
        print("wait: need <kind>/<name> and --for", file=sys.stderr)
        return 1
    ns = args.namespace or ("default" if rd.namespaced else None)
    cond = args.wait_for
    deadline = time.monotonic() + (args.timeout or 30)

    def satisfied(obj) -> bool:
        if cond.startswith("condition="):
            want = cond.split("=", 1)[1]
            want_status = "True"
            if "=" in want:
                want, want_status = want.split("=", 1)
            for c in (obj.get("status") or {}).get("conditions", []) or []:
                if c.get("type", "").lower() == want.lower():
                    return c.get("status") == want_status
            return False
        if cond.startswith("jsonpath="):
            rest = cond[len("jsonpath="):]
            expr, _, want = rest.rpartition("=")
            if not expr:
                return False
            return jsonpath(expr.strip("'"), obj) == want
        if cond == "delete":
            return False  # handled below
        return False

    while True:
        done = True
        for n in names:
            try:
                obj = kc.get(rd, ns if rd.namespaced else None, n)
            except ApiNotFound:
                if cond == "delete":
                    continue
                done = False
                break
            if cond == "delete" or not satisfied(obj):
                done = False
                break
        if done:
            for n in names:
                print(f"{rd.plural}/{n} condition met")
            return 0
        if time.monotonic() > deadline:
            print(
                f"error: timed out waiting for {cond} on "
                f"{rd.plural}/{','.join(names)}", file=sys.stderr,
            )
            return 1
        time.sleep(0.3)


def cmd_rollout(kc: KubeClient, args: Args) -> int:
    if args.positionals[:1] != ["status"]:
        print("rollout: only 'rollout status' supported", file=sys.stderr)
        return 1
    rd, name = _split_slash(args.positionals[1])
    if rd is None:
        print("rollout status: need ds/NAME or deploy/NAME",
              file=sys.stderr)
        return 1
    ns = args.namespace or "default"
    deadline = time.monotonic() + (args.timeout or 300)
    while True:
        try:
            obj = kc.get(rd, ns, name)
            st = obj.get("status") or {}
            gen_ok = st.get("observedGeneration", 0) >= obj[
                "metadata"
            ].get("generation", 1)
            if rd.plural == "daemonsets":
                want = st.get("desiredNumberScheduled", -1)
                ok = (
                    gen_ok and want >= 0
                    and st.get("numberReady", 0) >= want
                )
            else:
                want = (obj.get("spec") or {}).get("replicas", 1) or 1
                ok = gen_ok and st.get("readyReplicas", 0) >= want
            if ok:
                print(f'{rd.plural} "{name}" successfully rolled out')
                return 0
        except ApiNotFound:
            pass
        if time.monotonic() > deadline:
            print(f"error: rollout of {name} timed out", file=sys.stderr)
            return 1
        time.sleep(0.5)


def cmd_logs(kc: KubeClient, args: Args) -> int:
    base = os.environ.get("MINICLUSTER_DIR")
    if not base:
        print("logs: MINICLUSTER_DIR not set", file=sys.stderr)
        return 1
    ns = args.namespace or "default"
    from tpu_dra.k8sclient.resources import PODS

    pods: List[str] = []
    if args.selector:
        pods = [
            o["metadata"]["name"]
            for o in kc.list(
                PODS, ns, label_selector=args.label_selector()
            )
        ]
        if not pods:
            print("No resources found", file=sys.stderr)
            return 1
    else:
        tok = args.positionals[0]
        _, name = _split_slash(tok)
        pods = [name]
    rc = 0
    for pod in pods:
        log_dir = os.path.join(base, "logs", ns, pod)
        if not os.path.isdir(log_dir):
            print(f"error: no logs for pod {ns}/{pod}", file=sys.stderr)
            rc = 1
            continue
        files = sorted(os.listdir(log_dir))
        if args.container:
            files = [f"{args.container}.log"]
        for f in files:
            path = os.path.join(log_dir, f)
            if not os.path.exists(path):
                print(
                    f"error: container {f[:-4]} log missing",
                    file=sys.stderr,
                )
                rc = 1
                continue
            with open(path, errors="replace") as fh:
                lines = fh.read().splitlines()
            if args.tail is not None and args.tail >= 0:
                lines = lines[-args.tail:] if args.tail else []
            for line in lines:
                print(line)
    return rc


def cmd_api_versions(_kc, _args) -> int:
    seen = set()
    for d in iter_descriptors():
        seen.add(d.api_version if d.group else d.version)
    for v in sorted(seen):
        print(v)
    return 0


def main(argv=None) -> int:
    import signal

    # The suites pipe kubectl into head/grep -q; dying readers must make
    # us exit quietly (SIGPIPE default), not traceback with rc 1.
    try:
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    except (ValueError, OSError):
        pass
    argv = list(sys.argv[1:] if argv is None else argv)
    args = Args(argv)
    if not args.positionals:
        print("kubectl shim: missing command", file=sys.stderr)
        return 1
    # kubectl accepts global flags before the verb (`kubectl -n ns get`).
    verb = args.positionals.pop(0)
    kc = _client()
    try:
        if verb == "apply":
            return cmd_apply(kc, args)
        if verb == "delete":
            return cmd_delete(kc, args)
        if verb == "create":
            return cmd_create(kc, args)
        if verb == "get":
            return cmd_get(kc, args)
        if verb == "wait":
            return cmd_wait(kc, args)
        if verb == "rollout":
            return cmd_rollout(kc, args)
        if verb == "logs":
            return cmd_logs(kc, args)
        if verb == "api-versions":
            return cmd_api_versions(kc, args)
        if verb == "exec":
            print("kubectl shim: exec unsupported", file=sys.stderr)
            return 1
    except K8sApiError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"kubectl shim: unsupported verb {verb}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
