"""tpu-dra doctor: one-shot node-state inspection for operators.

The reference has no equivalent — debugging a node means exec'ing into
the plugin pod and reading logs. This reads the same stores the plugins
own and cross-checks them:

- **tpulib**: backend, generation, chips (uuid, coordinate, health),
  ICI domain identity, live sub-slices;
- **checkpoint**: every prepared claim and its WAL state — a claim stuck
  in ``PrepareStarted`` means a crash mid-prepare (the plugin will roll
  it back on next touch, the cleanup manager will GC it if its
  ResourceClaim is gone);
- **CDI**: transient claim specs on disk, cross-checked against the
  checkpoint (an orphan spec means an unprepare crashed before spec
  removal);
- **arbiters**: every per-claim sharing daemon socket, probed live
  (holder, queue depth, revocations);
- **component metrics** (``--metrics-endpoint host:port``, repeatable):
  scrapes a component's ``/metrics`` and WARNs on the failure-class
  counters of the round-3 incident — informer sync/watch failures,
  handler errors, workqueue failures and retry drops. With
  ``--metrics-interval S`` it samples twice and warns only on counters
  that CLIMBED in the window (a healthy component can carry old
  nonzero counts from a survived blip).

Exit 0 when healthy; 1 when any WARN was printed (probe-friendly).

Run it where the plugin runs (same data dirs), e.g.::

    kubectl exec -it <plugin-pod> -- python -m tpu_dra.tools.doctor
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time
from typing import Dict, List, Optional

from tpu_dra.plugin.checkpoint import (
    CLAIM_STATE_PREPARE_COMPLETED,
    CLAIM_STATE_PREPARE_STARTED,
    ChecksumError,
    inspect_file,
)
from tpu_dra.plugin.cdi import CDI_VENDOR
from tpu_dra.plugin.multiplexd import SOCKET_NAME
# ONE staleness constant: the doctor's live-probe verdict and
# fleetmon's own snapshot `stale` flag must agree on what "stale"
# means, or `doctor --metrics-endpoint` and `doctor slo --snapshot`
# would disagree about the same target.
from tpu_dra.tools.fleetmon import (
    STALE_AFTER_INTERVALS as FLEETMON_STALE_INTERVALS,
)
from tpu_dra.tpulib import new_tpulib


# Failure-class counters (metric name prefixes, label sets vary) that a
# healthy steady-state component should not be accumulating. These are
# exactly the signals of the round-3 multi-slice incident: the informer
# silently failing to sync/watch, handlers throwing, and the workqueue
# shedding retries.
FAILURE_COUNTER_PREFIXES = (
    "tpu_dra_informer_sync_failures_total",
    "tpu_dra_informer_watch_failures_total",
    "tpu_dra_informer_handler_errors_total",
    "tpu_dra_workqueue_failures_total",
    "tpu_dra_workqueue_retry_drops_total",
    # Dead-lettered work is work the system gave up on — always worth a
    # human look (the item itself is in the component's logs).
    "tpu_dra_workqueue_dead_letter_total",
)

# Control-plane weather gauges (ISSUE 5): api_degraded says the driver
# is in degraded mode RIGHT NOW (claim GC and slice publication paused,
# prepare/unprepare serving from gRPC+checkpoint state);
# api_circuit_state{verb} says which verb's breaker tripped
# (0 closed / 1 half-open / 2 open).
# Matched by SUFFIX: the TPU plugin exports tpu_dra_api_degraded, the
# CD plugin tpu_dra_cd_api_degraded (its Metrics prefix differs) — an
# exact-name match would silently skip the CD plugin's degraded state.
DEGRADED_GAUGE = "api_degraded"
CIRCUIT_GAUGE = "api_circuit_state"
CIRCUIT_STATE_NAMES = {0: "closed", 1: "half-open", 2: "open"}

# Scheduler fleet-health gauges (ISSUE 6), suffix-matched like the
# weather gauges: frag_score says how much of the grid's free capacity
# is stranded (free chips no advertised placement can reach — the
# ParvaGPU stranding metric over our chip meshes); the index pair says
# whether every published ResourceSlice actually made it into the
# scheduler's candidate index (seen > indexed means a slice failed to
# parse and is INVISIBLE to allocation).
FRAG_GAUGE = "scheduler_frag_score"
INDEX_SEEN_GAUGE = "scheduler_index_slices_seen"
INDEX_INDEXED_GAUGE = "scheduler_index_slices_indexed"
# Above this, a meaningful share of free capacity is unreachable —
# the bench's loaded traces stay at 0.0 under packed allocation, so a
# sustained high score means pathological churn or a placement bug.
FRAG_WARN_THRESHOLD = 0.25

# Serving-engine gauges (ISSUE 7), suffix-matched like the others:
# engine_admission_stalled is the SECONDS the engine's current
# backpressure stall has lasted (a co-tenant holds the chip lease, or
# ours was revoked — the engine drained and is waiting to re-acquire);
# engine_pages_free / engine_page_exhausted_total say whether the paged
# KV allocator's free list can still admit work.
ENGINE_STALL_GAUGE = "engine_admission_stalled"
ENGINE_PAGES_FREE_GAUGE = "engine_pages_free"
ENGINE_EXHAUSTED_COUNTER = "engine_page_exhausted_total"
# Speculative decoding (ISSUE 15): proposed/accepted draft-token
# counters and the live prefix-sharing gauge. A live acceptance rate
# under the floor while speculation is enabled means every verify pass
# is paying K wasted positions of compute and a rewind — pure overhead
# vs plain decoding; the floor only arms once enough proposals exist
# for the ratio to mean something.
ENGINE_SPEC_PROPOSED_COUNTER = "engine_spec_proposed_total"
ENGINE_SPEC_ACCEPTED_COUNTER = "engine_spec_accepted_total"
ENGINE_PREFIX_SHARED_GAUGE = "engine_prefix_shared_pages"
ENGINE_SPEC_ACCEPT_WARN_RATE = 0.1
ENGINE_SPEC_MIN_PROPOSED = 64
# Momentary stalls are the multiplexing quantum working as intended; a
# stall older than this means the lease is not coming back (daemon
# wedged, cooldown storm, starved FIFO) and requests are aging in the
# queue.
ENGINE_STALL_WARN_SECONDS = 1.0

# Workqueue pressure (ISSUE 10), suffix-matched like the other gauges
# (per-shard series carry a {shard="i"} label): depth is the number of
# pending+parked reconciles. A deep queue that is still GROWING across
# the probe interval means the reconciler is falling behind its event
# rate — arrivals outpacing service — and every domain behind that
# queue is aging. Matched with the workqueue_work_duration summary next
# to it, the remediation differs: long durations mean one slow
# callback; short durations with growth mean an event storm (or too
# few shards).
WORKQUEUE_DEPTH_GAUGE = "workqueue_depth"
WORKQUEUE_DEPTH_WARN = 100

# Serving-fabric gauges (ISSUE 11), suffix-matched like the others.
# fabric_tenant_vtime_lag{tenant=} is the router's WFQ starvation
# signal: how far (in weighted tokens) the fabric's virtual clock has
# run past a backlogged tenant's head turn. Healthy WFQ keeps it within
# ~one request cost; a large AND growing lag means that tenant is owed
# service others are receiving — a mis-weighted config, a quiesced
# affinity home, or a router bug. fabric_autoscaler_flaps_total counts
# scale-direction REVERSALS desired inside one cooldown window (the
# autoscaler suppresses the action and bumps this instead).
FABRIC_LAG_GAUGE = "fabric_tenant_vtime_lag"
FABRIC_LAG_WARN_TOKENS = 1024.0
FABRIC_FLAP_COUNTER = "fabric_autoscaler_flaps_total"
FABRIC_REPLICAS_GAUGE = "fabric_replicas"
# Crash-tolerance signals (ISSUE 16). fabric_replica_deaths_total{
# reason=crash|stall|claim-vanished} counts control-loop death
# classifications; fabric_circuit_open is the number of QUARANTINED
# claims (the breaker stopped routing to a crash-looper and the
# autoscaler owes a replacement); fabric_in_system_sequences against
# fabric_replicas == 0 is the live-capacity-vs-admitted-load check —
# admitted user state with nothing left to run it on is an outage, not
# a warning.
FABRIC_DEATHS_COUNTER = "fabric_replica_deaths_total"
FABRIC_CIRCUIT_GAUGE = "fabric_circuit_open"
FABRIC_DEGRADED_GAUGE = "fabric_degraded"
FABRIC_INSYSTEM_GAUGE = "fabric_in_system_sequences"

# Disaggregated-serving signals (ISSUE 17). fabric_migration_backlog is
# the router's migration WAITING ROOM — exported extents whose pages
# are in hand but which no decode replica has headroom to graft; a
# backlog GROWING across the probe interval means the decode pool is
# undersized (or dead) while prefill keeps exporting.
# fabric_queued_prefill_tokens / fabric_queued_decode_tokens split the
# queued-token backlog by phase, and fabric_phase_replicas{phase=}
# counts the live pools — together they expose the imbalance shape:
# one phase's per-replica backlog far above the other's while the
# other pool sits idle.
DISAGG_BACKLOG_GAUGE = "fabric_migration_backlog"
DISAGG_PREFILL_GAUGE = "fabric_queued_prefill_tokens"
DISAGG_DECODE_GAUGE = "fabric_queued_decode_tokens"
DISAGG_PHASE_GAUGE = "fabric_phase_replicas"
DISAGG_MIGRATIONS_COUNTER = "fabric_kv_migrations_total"
# Imbalance warns only past BOTH bars: the loaded phase carries at
# least IMBALANCE_X times the idle phase's per-replica backlog AND at
# least FLOOR tokens absolute (sub-floor backlogs are noise on any
# machine).
DISAGG_IMBALANCE_X = 8.0
DISAGG_IMBALANCE_FLOOR_TOKENS = 512.0

# Elastic-repacker gauges (ISSUE 12), suffix-matched like the others.
# repacker_frag_score is the fleet fragmentation the repacker itself
# last observed; repacker_leader says whether this instance holds the
# Lease; repacker_active_migrations / repacker_oldest_migration_seconds
# describe in-flight moves; repacker_migrations_total counts completed
# ones. The two failure shapes the doctor catches: fragmentation HIGH
# while the repacker sits idle (not leading, or mis-thresholded — free
# capacity stays stranded and large claims go Unschedulable), and a
# migration stuck past its budget window (a wedged drain or an
# unschedulable re-allocation holding a tenant in limbo).
REPACKER_FRAG_GAUGE = "repacker_frag_score"
REPACKER_LEADER_GAUGE = "repacker_leader"
REPACKER_ACTIVE_GAUGE = "repacker_active_migrations"
REPACKER_OLDEST_GAUGE = "repacker_oldest_migration_seconds"
REPACKER_MIGRATIONS_COUNTER = "repacker_migrations_total"
REPACKER_STUCK_WARN_SECONDS = 60.0

# Gang-scheduling gauges (ISSUE 19), suffix-matched like the others.
# gang_members counts claims currently seated (or being committed) as
# part of an all-or-nothing gang; scheduler_gang_pending is gang-labeled
# claims awaiting a gang solve; scheduler_gang_wal_oldest_seconds is the
# age of the OLDEST gang.tpu.google.com/state WAL annotation — the
# commit protocol holds it only for the duration of one atomic commit,
# so an old WAL means a scheduler died mid-commit and nothing has run
# recovery since; scheduler_gang_unschedulable counts gangs the last
# reconcile pass could not seat. The two failure shapes the doctor
# catches: a WAL stuck pre-commit past the threshold (members are
# half-committed and fenced from kubelet prepare until recovery
# resolves them), and gangs Unschedulable while the fleet's frag score
# says a corridor-opening repack could seat them.
GANG_MEMBERS_GAUGE = "gang_members"
GANG_PENDING_GAUGE = "scheduler_gang_pending"
GANG_WAL_OLDEST_GAUGE = "scheduler_gang_wal_oldest_seconds"
GANG_UNSCHED_GAUGE = "scheduler_gang_unschedulable"
GANG_ROLLBACKS_COUNTER = "gang_partial_rollbacks_total"
GANG_WAL_STUCK_WARN_SECONDS = 90.0

# Metrics cardinality guard (ISSUE 13), suffix-matched like the others:
# metrics_series_capped_total{name=} counts writes the registry REFUSED
# because one metric name hit its per-name label-set cap. Any nonzero
# value means some label carries an unbounded value (a claim name under
# churn, a request id) and series are being silently dropped from the
# scrape — the PR-12 remove_gauges lesson showing up as a visible
# counter instead of unbounded memory.
SERIES_CAPPED_COUNTER = "metrics_series_capped_total"

# fleetmon scrape health (ISSUE 14), suffix-matched like the others:
# fleetmon_target_up{target=} says whether the fleet monitor's LAST
# scrape of that component succeeded; fleetmon_scrape_age_seconds is
# how long ago the last SUCCESSFUL scrape was; the interval gauge lets
# the staleness verdict be stated in intervals. A down or stale target
# means the fleet's SLO verdicts are being computed over a PARTIAL
# view — every burn rate involving that component's series is stale.
FLEETMON_UP_GAUGE = "fleetmon_target_up"
FLEETMON_AGE_GAUGE = "fleetmon_scrape_age_seconds"
FLEETMON_INTERVAL_GAUGE = "fleetmon_scrape_interval_seconds"

# Apiserver flow-control health (ISSUE 20), suffix-matched like the
# others: apiserver_flow_rejected_total{flow=} counts requests the
# priority-and-fairness gate SHED with 429 + Retry-After, per flow;
# api_retry_budget_exhausted_total{verb=} counts retries a component
# wanted but could not afford from its process-wide retry-token bucket
# (it failed the request through instead of joining the storm). A
# CLIMBING rejected counter means the apiserver is actively shedding
# that flow right now — flow-ordered, so the flow name says who is over
# their share; an exhausted retry budget means the component's retry
# pressure has outrun its refill and errors are surfacing to callers.
APIFLOW_REJECTED_COUNTER = "apiserver_flow_rejected_total"
APIFLOW_BUDGET_EXHAUSTED_COUNTER = "api_retry_budget_exhausted_total"

# Decode-roofline trend gate (ISSUE 8): the key bench.py records as the
# gap between the measured decode step and the bf16 HBM floor. Matched
# by SUFFIX inside the artifact (like the scheduler/engine gauges): the
# key lives at the top level today and inside decode_roofline as
# x_above_bf16_floor — a rename/move between rounds must not silently
# disarm the gate. A >10% climb between the two newest BENCH_r*.json
# artifacts means the serving perf work regressed and nothing else
# caught it.
BENCH_TREND_KEY = "x_above_bf16_floor"
BENCH_TREND_REGRESSION = 0.10


def _endpoint_url(endpoint: str, path: str) -> str:
    """host:port / URL -> a full http URL ending in ``path``. One rule
    shared by the /metrics scrape, explain's /debug/traces scrape AND
    fleetmon's scraper (the canonical implementation lives there) so
    the normalization cannot diverge."""
    from tpu_dra.tools.fleetmon import endpoint_url

    return endpoint_url(endpoint, path)


def _scrape(endpoint: str, timeout: float = 2.0) -> Dict[str, float]:
    """Fetch and parse a Prometheus text endpoint into
    ``{"name{labels}": value}`` for counters/gauges (summaries included,
    harmless)."""
    import urllib.request

    url = _endpoint_url(endpoint, "/metrics")
    out: Dict[str, float] = {}
    with urllib.request.urlopen(url, timeout=timeout) as r:
        for line in r.read().decode().splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            series, _, value = line.rpartition(" ")
            try:
                out[series] = float(value)
            except ValueError:
                continue
    return out


def probe_metrics(
    endpoints: List[str], interval: float = 0.0, warn=None
) -> Dict[str, dict]:
    """Scrape each component endpoint; with ``interval`` > 0 sample twice
    around ONE shared sleep (N endpoints cost ~interval, not N*interval,
    and the climb deltas cover comparable windows). Calls ``warn(msg)``
    for every failure-class series that is nonzero (single sample) or
    climbing (two samples). A scrape failure — connection, malformed
    HTTP, non-HTTP protocol on the port — warns and moves on: the doctor
    must deliver its other sections on exactly the broken nodes it
    exists for."""
    import http.client
    import time as _time

    scrape_errors = (OSError, ValueError, http.client.HTTPException)
    warn = warn or (lambda _m: None)
    report: Dict[str, dict] = {}
    firsts: Dict[str, Dict[str, float]] = {}
    for ep in endpoints:
        try:
            firsts[ep] = _scrape(ep)
        except scrape_errors as e:
            report[ep] = {"error": str(e)}
            warn(f"metrics endpoint {ep} did not answer: {e}")
    if interval > 0 and firsts:
        _time.sleep(interval)
    for ep, first in firsts.items():
        second = None
        if interval > 0:
            try:
                second = _scrape(ep)
            except scrape_errors as e:
                report[ep] = {"error": f"second sample failed: {e}"}
                warn(f"metrics endpoint {ep} died mid-probe: {e}")
                continue
        failures = {}
        for series, value in sorted((second or first).items()):
            if not series.startswith(FAILURE_COUNTER_PREFIXES):
                continue
            if second is not None:
                delta = value - first.get(series, 0.0)
                failures[series] = {"value": value, "climbed": delta}
                if delta > 0:
                    warn(
                        f"{ep}: {series} CLIMBED by {delta:g} in "
                        f"{interval:g}s (now {value:g}) — the component "
                        f"is failing right now; check its logs and the "
                        f"apiserver connection"
                    )
            elif value > 0:
                failures[series] = {"value": value}
                warn(
                    f"{ep}: {series} = {value:g} — the component has "
                    f"been failing to sync/dispatch; re-run with "
                    f"--metrics-interval to see whether it is still "
                    f"climbing"
                )
        report[ep] = {"failure_counters": failures}
        report[ep]["degraded"] = _check_degraded(
            ep, second or first, warn
        )
        scheduler = _check_scheduler(ep, second or first, warn)
        if scheduler:
            report[ep]["scheduler"] = scheduler
        engine = _check_engine(ep, second or first, warn)
        if engine:
            report[ep]["engine"] = engine
        wq = _check_workqueue(ep, first, second, warn)
        if wq:
            report[ep]["workqueue"] = wq
        fabric = _check_fabric(ep, first, second, warn)
        if fabric:
            report[ep]["fabric"] = fabric
        disagg = _check_disagg(ep, first, second, warn)
        if disagg:
            report[ep]["disagg"] = disagg
        repacker = _check_repacker(ep, first, second, warn)
        if repacker:
            report[ep]["repacker"] = repacker
        gangd = _check_gang(ep, second or first, warn)
        if gangd:
            report[ep]["gang"] = gangd
        capped = _check_cardinality(ep, second or first, warn)
        if capped:
            report[ep]["series_capped"] = capped
        fleetmon = _check_fleetmon(ep, second or first, warn)
        if fleetmon:
            report[ep]["fleetmon"] = fleetmon
        apiflow = _check_apiflow(ep, first, second, warn)
        if apiflow:
            report[ep]["apiflow"] = apiflow
    return report


def _label_of(series: str, key: str) -> str:
    """Extract one label's value from a rendered series key (the
    scrape dict's ``name{k="v",...}`` form) — escape-aware via
    fleetmon's parser, so a target name carrying ``,`` or an escaped
    quote never splits into a phantom target."""
    from tpu_dra.tools.fleetmon import parse_series_labels

    return parse_series_labels(series).get(key, "?")


def _check_fleetmon(
    ep: str, sample: Dict[str, float], warn
) -> Dict[str, object]:
    """Surface the fleet monitor's own scrape health (ISSUE 14): a
    target whose last scrape failed (``fleetmon_target_up == 0``) or
    whose last SUCCESS is older than 3 scrape intervals means the SLO
    engine is evaluating burn rates over a partial or stale view —
    the monitoring, not the fleet, is what needs fixing first. Empty
    dict when the endpoint exports no fleetmon series."""
    out: Dict[str, object] = {}
    targets: Dict[str, dict] = {}
    interval = None
    for series, value in sorted(sample.items()):
        name = series.split("{", 1)[0]
        if name.endswith(FLEETMON_INTERVAL_GAUGE):
            interval = value
        elif name.endswith(FLEETMON_UP_GAUGE):
            targets.setdefault(
                _label_of(series, "target"), {}
            )["up"] = bool(value)
        elif name.endswith(FLEETMON_AGE_GAUGE):
            targets.setdefault(
                _label_of(series, "target"), {}
            )["age_s"] = value
    if not targets and interval is None:
        return out
    if interval is not None:
        out["interval_s"] = interval
    out["targets"] = targets
    for tname, t in sorted(targets.items()):
        if t.get("up") is False:
            warn(
                f"{ep}: fleetmon target {tname!r} is DOWN (last scrape "
                f"failed) — the fleet's SLOs are being evaluated over "
                f"a PARTIAL view and every burn rate that reads this "
                f"component's series is blind. Check the component's "
                f"MetricsServer port and the fleetmon --target "
                f"spelling (docs/observability.md, 'Fleet SLOs & "
                f"burn-rate alerting')"
            )
            continue
        age = t.get("age_s")
        if (
            interval and age is not None
            and age > FLEETMON_STALE_INTERVALS * interval
        ):
            warn(
                f"{ep}: fleetmon scrape of {tname!r} is STALE — last "
                f"success {age:g}s ago (> {FLEETMON_STALE_INTERVALS:g} "
                f"x the {interval:g}s interval); burn rates are "
                f"running on old samples. The target answers up=1 but "
                f"new scrapes are not landing: check whether the "
                f"fleetmon scrape loop is wedged or the target slowed "
                f"past the scrape timeout"
            )
    return out


def _check_apiflow(
    ep: str, first: Dict[str, float], second: Optional[Dict[str, float]],
    warn,
) -> Dict[str, object]:
    """Surface apiserver flow-control shedding and client retry-budget
    exhaustion (ISSUE 20). With two samples, only a counter that is
    still CLIMBING warns — a nonzero total from a past brownout is
    history, not a page; a single sample can only flag the total and
    ask for a re-probe. Empty dict (and silence) on fleets that export
    neither series or have never shed."""
    out: Dict[str, object] = {}
    sample = second if second is not None else first
    rejected: Dict[str, Dict[str, float]] = {}
    exhausted_total = 0.0
    exhausted_climbed = 0.0
    for series, value in sorted(sample.items()):
        name = series.split("{", 1)[0]
        if name.endswith(APIFLOW_REJECTED_COUNTER):
            if value <= 0:
                continue
            flow = _label_of(series, "flow")
            entry: Dict[str, float] = {"rejected": value}
            if second is not None:
                entry["climbed"] = value - first.get(series, 0.0)
            rejected[flow] = entry
        elif name.endswith(APIFLOW_BUDGET_EXHAUSTED_COUNTER):
            if value <= 0:
                continue
            exhausted_total += value
            if second is not None:
                exhausted_climbed += value - first.get(series, 0.0)
    if not rejected and exhausted_total <= 0:
        return out
    if rejected:
        out["rejected"] = rejected
    if exhausted_total > 0:
        out["retry_budget_exhausted"] = exhausted_total
        if second is not None:
            out["retry_budget_climbed"] = exhausted_climbed
    for flow, entry in sorted(rejected.items()):
        if second is not None:
            if entry.get("climbed", 0.0) > 0:
                warn(
                    f"{ep}: apiserver is SHEDDING the {flow!r} flow "
                    f"right now — apiserver_flow_rejected_total"
                    f"{{flow={flow!r}}} climbed by "
                    f"{entry['climbed']:g} over the probe interval "
                    f"(total {entry['rejected']:g}). The gate sheds "
                    f"flow-ordered, so this flow is over its share: "
                    f"either widen its share (FlowControl.configure) "
                    f"or slow the producer — for slice-publish that "
                    f"means publisher storm weather outrunning "
                    f"coalescing (docs/operations.md, 'Apiserver flow "
                    f"control & restart semantics')"
                )
        else:
            warn(
                f"{ep}: apiserver_flow_rejected_total"
                f"{{flow={flow!r}}} = {entry['rejected']:g} — this "
                f"flow has been shed; re-run with --metrics-interval "
                f"to see whether it is still being shed or the "
                f"brownout has passed"
            )
    if second is not None and exhausted_climbed > 0:
        warn(
            f"{ep}: the process retry budget is EXHAUSTED and still "
            f"burning — api_retry_budget_exhausted_total climbed by "
            f"{exhausted_climbed:g} over the probe interval (total "
            f"{exhausted_total:g}); retries this component wanted are "
            f"being refused and errors are failing through to "
            f"callers. The apiserver is either shedding or flapping "
            f"faster than the budget refills: fix the apiserver-side "
            f"pressure first (see the apiflow shed warnings), then "
            f"widen TPU_DRA_RETRY_BUDGET_CAPACITY/REFILL only if the "
            f"weather is expected"
        )
    elif second is None and exhausted_total > 0:
        warn(
            f"{ep}: api_retry_budget_exhausted_total = "
            f"{exhausted_total:g} — this process has refused retries "
            f"for want of budget; re-run with --metrics-interval to "
            f"see whether the budget is still exhausted"
        )
    return out


def _check_cardinality(
    ep: str, sample: Dict[str, float], warn
) -> Dict[str, float]:
    """WARN on any nonzero metrics_series_capped_total{name=} series:
    the registry is refusing new label sets for that metric name, so
    some entity's series are missing from this very scrape."""
    out: Dict[str, float] = {}
    for series, value in sample.items():
        base = series.split("{", 1)[0]
        if not base.endswith(SERIES_CAPPED_COUNTER) or value <= 0:
            continue
        out[series] = value
        warn(
            f"{ep}: {series} = {value:g} — a metric name hit its "
            f"per-name series cap and new label sets are being DROPPED "
            f"from the scrape. Some label carries an unbounded value "
            f"(claim/request ids under churn): fix the label choice or "
            f"add the per-entity cleanup (Metrics.remove_gauges) the "
            f"exporter is missing; raising Metrics(series_cap=) only "
            f"defers the explosion"
        )
    return out


def _check_repacker(
    ep: str, first: Dict[str, float], second: Optional[Dict[str, float]],
    warn,
) -> Dict[str, object]:
    """Surface the elastic repacker's health (ISSUE 12). Two WARN
    shapes: (a) fragmentation high while the repacker is IDLE — not
    holding the Lease, or configured so it never acts (with two samples
    an idle verdict also requires migrations_total NOT climbing, so a
    repacker mid-burst stays quiet); (b) a migration stuck past the
    budget window — the WAL'd move is holding a tenant in limbo. Empty
    dict when the endpoint exports no repacker series."""
    out: Dict[str, object] = {}
    sample = second if second is not None else first
    migrations_series = None
    for series, value in sorted(sample.items()):
        name = series.split("{", 1)[0]
        if name.endswith(REPACKER_FRAG_GAUGE):
            out["frag_score"] = value
        elif name.endswith(REPACKER_LEADER_GAUGE):
            out["leader"] = bool(value)
        elif name.endswith(REPACKER_ACTIVE_GAUGE):
            out["active"] = int(value)
        elif name.endswith(REPACKER_OLDEST_GAUGE):
            out["oldest_migration_s"] = value
        elif name.endswith(REPACKER_MIGRATIONS_COUNTER):
            out["migrations"] = int(value)
            migrations_series = series
    if not out:
        return out
    frag = out.get("frag_score", 0.0)
    if frag > FRAG_WARN_THRESHOLD:
        if not out.get("leader", False):
            warn(
                f"{ep}: fleet fragmentation is {frag:g} and this "
                f"repacker is NOT LEADING — if no other instance holds "
                f"the Lease, stranded capacity stays stranded and large "
                f"claims go Unschedulable. Check the repacker Lease "
                f"(holder, renewTime) and that leader election is "
                f"enabled/healthy (docs/scheduling.md, 'Autonomous "
                f"repacking')"
            )
        elif out.get("active", 0) == 0:
            climbed = None
            if second is not None and migrations_series is not None:
                climbed = sample.get(migrations_series, 0.0) - first.get(
                    migrations_series, 0.0
                )
            if climbed is None or climbed <= 0:
                warn(
                    f"{ep}: fleet fragmentation is {frag:g} but the "
                    f"repacker is IDLE (leading, no active migrations"
                    + (
                        ", migrations_total flat over the probe interval"
                        if second is not None else ""
                    )
                    + ") — likely misconfigured: frag_threshold above "
                    "the live score, every candidate deferred by the "
                    "disruption budget, or no move improves the score "
                    "(check repacker_disruption_budget_deferred_total "
                    "and the planner log; docs/scheduling.md, "
                    "'Autonomous repacking')"
                )
    oldest = out.get("oldest_migration_s", 0.0)
    if oldest > REPACKER_STUCK_WARN_SECONDS:
        warn(
            f"{ep}: a repack migration has been in flight for "
            f"{oldest:g}s — past the disruption-budget window; its "
            f"tenant may be drained and waiting. Check whether the "
            f"victim engine's drain is wedged (engine_admission_stalled "
            f"on the serving endpoint), whether the re-allocation is "
            f"Unschedulable (scheduler events for the claim), and the "
            f"claim's repack.tpu.google.com/state annotation phase — "
            f"recovery rolls a stale plan back/forward on the next "
            f"leader (docs/scheduling.md, 'Autonomous repacking')"
        )
    return out


def _check_gang(
    ep: str, sample: Dict[str, float], warn
) -> Dict[str, object]:
    """Surface gang-scheduling health (ISSUE 19). Two WARN shapes:
    (a) a gang WAL stuck pre-commit past the threshold — the atomic
    commit holds the ``gang.tpu.google.com/state`` annotation only for
    one commit's duration, so an old WAL means a scheduler died
    mid-protocol and no recovery has resolved the half-committed
    members (the plugin fences them from prepare until it does);
    (b) gangs Unschedulable while the fleet's fragmentation score is
    high — whole-node corridors are exactly what the repacker's
    corridor mode manufactures, so a stuck gang plus a fragmented
    fleet means the repacker is absent or idle. Empty dict when the
    endpoint exports no gang series."""
    out: Dict[str, object] = {}
    for series, value in sorted(sample.items()):
        name = series.split("{", 1)[0]
        if name.endswith(GANG_PENDING_GAUGE):
            out["pending"] = int(value)
        elif name.endswith(GANG_WAL_OLDEST_GAUGE):
            out["wal_oldest_s"] = value
        elif name.endswith(GANG_UNSCHED_GAUGE):
            out["unschedulable"] = int(value)
        elif name.endswith(GANG_ROLLBACKS_COUNTER):
            out["partial_rollbacks"] = int(value)
        elif name.endswith(GANG_MEMBERS_GAUGE):
            out["members"] = int(value)
        elif name.endswith(FRAG_GAUGE):
            out["_frag"] = value
    frag = out.pop("_frag", 0.0)
    if not out:
        return out
    wal_oldest = out.get("wal_oldest_s", 0.0)
    if wal_oldest > GANG_WAL_STUCK_WARN_SECONDS:
        warn(
            f"{ep}: a gang commit WAL has been outstanding for "
            f"{wal_oldest:g}s — far past one commit's duration, so a "
            f"scheduler died mid-protocol and its members are "
            f"half-committed (the plugin refuses to prepare them until "
            f"the protocol resolves). Recovery is automatic on the "
            f"next scheduler start or reconcile pass (rolling_back "
            f"anywhere -> teardown; all-committed -> roll forward; "
            f"anything else -> roll back): check that a scheduler is "
            f"actually running and leading, then the members' "
            f"gang.tpu.google.com/state annotation phases "
            f"(docs/scheduling.md, 'Gang scheduling & heterogeneous "
            f"fleets')"
        )
    if out.get("unschedulable", 0) > 0 and frag > FRAG_WARN_THRESHOLD:
        warn(
            f"{ep}: {out['unschedulable']} gang(s) are Unschedulable "
            f"while the fleet fragmentation score is {frag:g} — free "
            f"capacity exists but no whole-node corridor does, which "
            f"is the exact state the repacker's corridor mode "
            f"defragments (it migrates residents off nearly-free pools "
            f"while gang members sit pending). Check that a repacker "
            f"is running and leading (repacker_leader), and that the "
            f"disruption budget is not deferring every candidate "
            f"(repacker_disruption_budget_deferred_total; "
            f"docs/scheduling.md, 'Gang scheduling & heterogeneous "
            f"fleets')"
        )
    return out


def _check_workqueue(
    ep: str, first: Dict[str, float], second: Optional[Dict[str, float]],
    warn,
) -> Dict[str, object]:
    """Surface workqueue pressure (ISSUE 10): per-queue (and per-shard)
    depth, WARNing on sustained growth past the threshold. With two
    samples, a deep-but-draining queue stays quiet — only deep AND
    still growing is the falling-behind signal; a single sample can
    only flag depth and ask for a re-probe."""
    out: Dict[str, object] = {}
    sample = second if second is not None else first
    for series, value in sorted(sample.items()):
        name = series.split("{", 1)[0]
        if not name.endswith(WORKQUEUE_DEPTH_GAUGE):
            continue
        entry: Dict[str, float] = {"depth": value}
        if second is not None:
            entry["grew"] = value - first.get(series, 0.0)
        out[series] = entry
        if value <= WORKQUEUE_DEPTH_WARN:
            continue
        if second is not None:
            if entry["grew"] > 0:
                warn(
                    f"{ep}: {series} = {value:g} and still GROWING "
                    f"(+{entry['grew']:g} over the probe interval) — the "
                    f"reconciler is falling behind its event rate and "
                    f"work is aging. Check the component's "
                    f"workqueue_work_duration_seconds next to it: long "
                    f"durations mean one slow callback (fix the "
                    f"reconcile, or move its slow I/O off the queue); "
                    f"short durations mean an event storm — coalesce "
                    f"the producer or raise the queue's shard count"
                )
        else:
            warn(
                f"{ep}: {series} = {value:g} — deep reconcile backlog; "
                f"re-run with --metrics-interval to see whether it is "
                f"draining or still growing"
            )
    return out


def _check_fabric(
    ep: str, first: Dict[str, float], second: Optional[Dict[str, float]],
    warn,
) -> Dict[str, object]:
    """Surface the serving fabric's health (ISSUE 11): sustained
    per-tenant WFQ starvation and autoscaler flapping. Like the
    workqueue check, starvation needs TWO samples to warn decisively —
    a large lag that is DRAINING is a recovering fabric, not a sick
    one; a single sample past the threshold asks for a re-probe.
    ISSUE 16 adds the crash-tolerance checks: replica deaths (growth
    over the interval means replicas are dying right now), quarantined
    claims (the breaker opened — the autoscaler owes a replacement),
    and the live-capacity-vs-admitted-load outage check."""
    out: Dict[str, object] = {}
    sample = second if second is not None else first
    lags: Dict[str, Dict[str, float]] = {}
    deaths = 0.0
    deaths_grew = 0.0
    in_system = 0.0
    for series, value in sorted(sample.items()):
        name = series.split("{", 1)[0]
        if name.endswith(FABRIC_DEATHS_COUNTER):
            # Labeled by reason= — sum the series for the headline,
            # keep the per-reason split for the render line.
            deaths += value
            if second is not None:
                deaths_grew += value - first.get(series, 0.0)
            by = out.setdefault("deaths_by_reason", {})
            by[_label_of(series, "reason")] = int(value)
        elif name.endswith(FABRIC_CIRCUIT_GAUGE):
            out["circuit_open"] = int(value)
        elif name.endswith(FABRIC_DEGRADED_GAUGE):
            out["degraded"] = value
        elif name.endswith(FABRIC_INSYSTEM_GAUGE):
            in_system = value
            out["in_system"] = int(value)
        elif name.endswith(FABRIC_REPLICAS_GAUGE):
            out["replicas"] = int(value)
        elif name.endswith(FABRIC_FLAP_COUNTER):
            out["flaps"] = int(value)
        elif name.endswith(FABRIC_LAG_GAUGE):
            entry: Dict[str, float] = {"lag": value}
            if second is not None:
                entry["grew"] = value - first.get(series, 0.0)
            lags[series] = entry
            if value <= FABRIC_LAG_WARN_TOKENS:
                continue
            if second is not None:
                if entry["grew"] > 0:
                    warn(
                        f"{ep}: {series} = {value:g} weighted tokens "
                        f"and still GROWING (+{entry['grew']:g} over "
                        f"the probe interval) — this tenant is being "
                        f"STARVED: service others received was owed to "
                        f"its queue head. Check the tenant's weight vs "
                        f"its SLO class, whether its affinity home "
                        f"replica is quiesced/draining, and the "
                        f"router's per-replica inflight cap "
                        f"(docs/serving.md, 'Serving fabric')"
                    )
            else:
                warn(
                    f"{ep}: {series} = {value:g} weighted tokens of "
                    f"WFQ lag — re-run with --metrics-interval to see "
                    f"whether the tenant is draining or being starved"
                )
    if lags:
        out["tenant_lags"] = lags
    flaps = out.get("flaps", 0)
    if flaps:
        climbed = None
        if second is not None:
            for series, value in second.items():
                if series.split("{", 1)[0].endswith(FABRIC_FLAP_COUNTER):
                    climbed = value - first.get(series, 0.0)
        if climbed is None or climbed > 0 or second is None:
            warn(
                f"{ep}: autoscaler FLAPPING — {flaps} scale-direction "
                f"reversal(s) desired inside one cooldown window "
                f"(suppressed, but the signal means the hysteresis "
                f"band is too tight for this load's variance). Widen "
                f"the up_factor/down_factor gap or raise "
                f"cooldown_seconds (docs/operations.md, 'Serving "
                f"fabric autoscaler')"
            )
    # Crash-tolerance checks (ISSUE 16).
    if deaths:
        out["deaths"] = int(deaths)
        if deaths_grew > 0:
            warn(
                f"{ep}: fabric replicas DYING — "
                f"{FABRIC_DEATHS_COUNTER} climbed by {deaths_grew:g} "
                f"over the probe interval (total {deaths:g}, by reason "
                f"{out.get('deaths_by_reason')}). The journal re-queues "
                f"their in-flight sequences, but sustained deaths mean "
                f"a sick node, a poisoned model rev, or a watchdog "
                f"deadline tighter than the engine's real step time "
                f"(docs/serving.md, 'Failure semantics')"
            )
    circuit = int(out.get("circuit_open", 0) or 0)
    if circuit:
        warn(
            f"{ep}: {circuit} claim(s) QUARANTINED — the circuit "
            f"breaker saw repeated deaths inside one window and "
            f"stopped routing to them. The autoscaler deletes the "
            f"claim and requests a packer-placed replacement; if the "
            f"replacement loops too, the fault travels with the "
            f"workload or the node pool, not the claim — check the "
            f"node's chip health and the replica's last death reasons "
            f"(docs/serving.md, 'Failure semantics')"
        )
    if out.get("replicas") == 0 and in_system > 0:
        warn(
            f"{ep}: ERROR — live capacity below admitted load: 0 live "
            f"replicas with {in_system:g} admitted sequence(s) in the "
            f"system. Nothing can serve the journaled backlog until a "
            f"replacement claim binds; check the autoscaler's pending "
            f"claim, the scheduler's placement feasibility, and the "
            f"quarantine list (docs/serving.md, 'Failure semantics')"
        )
    return out


def _check_disagg(
    ep: str, first: Dict[str, float], second: Optional[Dict[str, float]],
    warn,
) -> Dict[str, object]:
    """Surface disaggregated-serving health (ISSUE 17): a migration
    waiting room GROWING across the probe interval (exported page
    extents piling up faster than the decode pool grafts them), and a
    phase-pool imbalance (one phase's per-replica backlog far above
    the other's while the other pool idles). Empty dict when the
    endpoint runs no phase-role replicas — colocated fleets get no
    disagg section."""
    out: Dict[str, object] = {}
    sample = second if second is not None else first
    backlog = None
    backlog_first = None
    prefill_tokens = decode_tokens = 0.0
    pools: Dict[str, int] = {}
    migrations: Dict[str, int] = {}
    for series, value in sorted(sample.items()):
        name = series.split("{", 1)[0]
        if name.endswith(DISAGG_BACKLOG_GAUGE):
            backlog = value
            if second is not None:
                backlog_first = first.get(series)
        elif name.endswith(DISAGG_PREFILL_GAUGE):
            prefill_tokens = value
        elif name.endswith(DISAGG_DECODE_GAUGE):
            decode_tokens = value
        elif name.endswith(DISAGG_PHASE_GAUGE):
            pools[_label_of(series, "phase")] = int(value)
        elif name.endswith(DISAGG_MIGRATIONS_COUNTER):
            migrations[_label_of(series, "outcome")] = int(value)
    n_p, n_d = pools.get("prefill", 0), pools.get("decode", 0)
    if n_p == 0 and n_d == 0 and not (backlog or 0):
        return out  # colocated fleet (or no fabric at all)
    out["pools"] = pools
    out["queued_prefill_tokens"] = prefill_tokens
    out["queued_decode_tokens"] = decode_tokens
    if backlog is not None:
        out["migration_backlog"] = int(backlog)
    if migrations:
        out["migrations"] = migrations
    if backlog:
        if second is not None and backlog_first is not None:
            grew = backlog - backlog_first
            out["backlog_grew"] = grew
            if grew > 0:
                warn(
                    f"{ep}: KV-migration backlog GROWING — "
                    f"{DISAGG_BACKLOG_GAUGE} climbed by {grew:g} over "
                    f"the probe interval (now {backlog:g} extents "
                    f"waiting, pages already exported off the prefill "
                    f"pool). The decode pool ({n_d} replica(s)) is not "
                    f"grafting as fast as prefill exports: scale the "
                    f"decode pool up, check for dead/quiesced decode "
                    f"replicas, or lower the prefill pool's share "
                    f"(docs/serving.md, 'Disaggregated serving')"
                )
        elif second is None:
            warn(
                f"{ep}: {DISAGG_BACKLOG_GAUGE} = {backlog:g} extents "
                f"in the migration waiting room — re-run with "
                f"--metrics-interval to see whether the decode pool is "
                f"draining it or falling behind"
            )
    # Phase imbalance: per-replica backlog of one phase dwarfing the
    # other's while that other pool idles. Warn only when BOTH pools
    # exist (a missing pool is the outage check's job, not a tuning
    # hint) and the loaded side clears the absolute floor.
    if n_p > 0 and n_d > 0:
        load_p = prefill_tokens / n_p
        load_d = decode_tokens / n_d
        if (
            load_p > DISAGG_IMBALANCE_FLOOR_TOKENS
            and load_p > DISAGG_IMBALANCE_X * max(load_d, 1.0)
        ):
            warn(
                f"{ep}: phase-pool IMBALANCE — prefill backlog "
                f"{prefill_tokens:g} tokens over {n_p} replica(s) "
                f"({load_p:.0f}/replica) while the decode pool idles "
                f"({load_d:.0f}/replica over {n_d}). TTFT is queueing "
                f"on prompts the decode pool cannot help with: move "
                f"replicas prefill-ward or let the disaggregated "
                f"autoscaler resize the pools "
                f"(docs/serving.md, 'Disaggregated serving')"
            )
        elif (
            load_d > DISAGG_IMBALANCE_FLOOR_TOKENS
            and load_d > DISAGG_IMBALANCE_X * max(load_p, 1.0)
        ):
            warn(
                f"{ep}: phase-pool IMBALANCE — decode backlog "
                f"{decode_tokens:g} tokens over {n_d} replica(s) "
                f"({load_d:.0f}/replica) while the prefill pool idles "
                f"({load_p:.0f}/replica over {n_p}). ITL is queueing "
                f"on migrated sequences the prefill pool cannot help "
                f"with: move replicas decode-ward or let the "
                f"disaggregated autoscaler resize the pools "
                f"(docs/serving.md, 'Disaggregated serving')"
            )
    return out


def _check_degraded(
    ep: str, sample: Dict[str, float], warn
) -> Dict[str, object]:
    """Surface the control-plane-weather gauges: degraded mode and any
    non-closed per-verb circuit. These are gauges, not counters — the
    current value IS the state, no climb delta needed."""
    out: Dict[str, object] = {}
    circuits: Dict[str, str] = {}
    for series, value in sorted(sample.items()):
        name = series.split("{", 1)[0]
        if name.endswith(DEGRADED_GAUGE):
            out["api_degraded"] = bool(value)
            if value:
                warn(
                    f"{ep}: driver is in DEGRADED mode (apiserver "
                    f"circuit open) — claim GC and slice publication "
                    f"are paused; prepare/unprepare still serve from "
                    f"gRPC+checkpoint state; a fenced resync runs "
                    f"automatically when the circuit closes"
                )
        elif name.endswith(CIRCUIT_GAUGE):
            verb = "?"
            if "{" in series:
                labels = series.split("{", 1)[1].rstrip("}")
                for part in labels.split(","):
                    k, _, v = part.partition("=")
                    if k == "verb":
                        verb = v.strip('"')
            state = CIRCUIT_STATE_NAMES.get(int(value), str(value))
            circuits[verb] = state
            if state != "closed":
                warn(
                    f"{ep}: apiserver circuit for {verb!r} is {state} — "
                    f"the control plane is (or was very recently) "
                    f"unreachable from this component; check apiserver "
                    f"health and network path"
                )
    if circuits:
        out["circuits"] = circuits
    return out


def _check_scheduler(
    ep: str, sample: Dict[str, float], warn
) -> Dict[str, object]:
    """Surface the scheduler's fleet-health gauges (ISSUE 6): the grid
    fragmentation score and index staleness. Empty dict when the
    component exports neither (plugin endpoints, older schedulers)."""
    out: Dict[str, object] = {}
    for series, value in sorted(sample.items()):
        name = series.split("{", 1)[0]
        if name.endswith(FRAG_GAUGE):
            out["frag_score"] = value
        elif name.endswith(INDEX_SEEN_GAUGE):
            out["slices_seen"] = int(value)
        elif name.endswith(INDEX_INDEXED_GAUGE):
            out["slices_indexed"] = int(value)
    if out.get("frag_score", 0.0) > FRAG_WARN_THRESHOLD:
        warn(
            f"{ep}: fleet fragmentation score is "
            f"{out['frag_score']:g} — a meaningful share of free chip "
            f"capacity is stranded (no advertised placement can reach "
            f"it); large claims will go Unschedulable despite free "
            f"capacity. Check for reshape churn leaving odd-shaped "
            f"holes, and whether the allocator is running with the "
            f"packed ordering (docs/scheduling.md)"
        )
    seen = out.get("slices_seen")
    indexed = out.get("slices_indexed")
    if seen is not None and indexed is not None and seen > indexed:
        warn(
            f"{ep}: scheduler index is STALE — {seen} ResourceSlice(s) "
            f"seen but only {indexed} indexed; the difference failed "
            f"to parse and is invisible to allocation (claims needing "
            f"those devices go Unschedulable). Find the malformed "
            f"slice in the scheduler log ('failed to index') and fix "
            f"its publisher"
        )
    return out


def _check_engine(
    ep: str, sample: Dict[str, float], warn
) -> Dict[str, object]:
    """Surface the serving engine's health gauges (ISSUE 7): a
    backpressure stall held past the threshold, and page-allocator
    free-list exhaustion. Empty dict when the component exports neither
    (non-serving endpoints)."""
    out: Dict[str, object] = {}
    for series, value in sorted(sample.items()):
        name = series.split("{", 1)[0]
        if name.endswith(ENGINE_STALL_GAUGE):
            out["admission_stalled_s"] = value
        elif name.endswith(ENGINE_PAGES_FREE_GAUGE):
            out["pages_free"] = int(value)
        elif name.endswith(ENGINE_EXHAUSTED_COUNTER):
            out["page_exhausted"] = int(value)
        elif name.endswith(ENGINE_SPEC_PROPOSED_COUNTER):
            out["spec_proposed"] = int(value)
        elif name.endswith(ENGINE_SPEC_ACCEPTED_COUNTER):
            out["spec_accepted"] = int(value)
        elif name.endswith(ENGINE_PREFIX_SHARED_GAUGE):
            out["prefix_shared_pages"] = int(value)
    stalled = out.get("admission_stalled_s", 0.0)
    if stalled > ENGINE_STALL_WARN_SECONDS:
        warn(
            f"{ep}: serving-engine admissions have been STALLED for "
            f"{stalled:g}s — the chip lease is held elsewhere (or was "
            f"revoked) and is not coming back; in-flight sequences are "
            f"checkpointed and waiting. Check the claim's arbiter "
            f"(doctor's arbiters section: holder/overdue/cooldown) and "
            f"the co-tenant's behavior; requests are aging in the queue"
        )
    if out.get("page_exhausted", 0) > 0:
        warn(
            f"{ep}: serving-engine page allocator hit free-list "
            f"exhaustion {out['page_exhausted']} time(s) "
            f"({out.get('pages_free', '?')} pages free now) — admission "
            f"is blocking on KV memory. Lower max concurrent sequences "
            f"or per-request max_new_tokens, raise the page pool "
            f"(num_pages), or enable int8 KV (kv_quant) to halve page "
            f"bytes (docs/serving.md)"
        )
    proposed = out.get("spec_proposed", 0)
    if proposed >= ENGINE_SPEC_MIN_PROPOSED:
        rate = out.get("spec_accepted", 0) / proposed
        out["spec_accept_rate"] = round(rate, 4)
        if rate < ENGINE_SPEC_ACCEPT_WARN_RATE:
            warn(
                f"{ep}: speculative-decoding acceptance rate is "
                f"{rate:.3f} over {proposed} proposed draft tokens "
                f"(floor {ENGINE_SPEC_ACCEPT_WARN_RATE}) — at this "
                f"rate every verify pass pays K wasted positions and "
                f"a rewind: speculation is PURE OVERHEAD vs plain "
                f"decoding. Disable it (spec_k=0) or raise the lookup "
                f"order (spec_lookup_order) so the proposer only "
                f"fires on real structure (docs/serving.md, "
                f"'Speculative decoding & prefix sharing')"
            )
    return out


def _bench_floor_x(path: str) -> Optional[float]:
    """decode_x_above_bf16_floor from one BENCH_r*.json, suffix-matched
    over the (possibly "parsed"-wrapped) top level; None when the
    artifact predates the key or doesn't parse (older rounds are not
    evidence of anything)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict):
        # Valid JSON but not an object (truncated/mis-redirected bench
        # output): skip it like any other unparseable artifact.
        return None
    if isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    # Top level first, then one nested level: older artifacts (BENCH_r05
    # and earlier) carry the ratio only inside the decode_roofline dict
    # — an exact-location match would silently disarm the gate for the
    # first real comparison.
    for sample in [data] + [
        v for _, v in sorted(data.items()) if isinstance(v, dict)
    ]:
        for key in sorted(sample):
            if key.endswith(BENCH_TREND_KEY):
                value = sample[key]
                if isinstance(value, (int, float)):
                    return float(value)
    return None


def check_bench_trend(bench_dir: str, warn) -> Dict[str, object]:
    """Compare decode_x_above_bf16_floor across the two newest
    BENCH_r*.json artifacts (the ISSUE 8 trend gate: the roofline goal
    is a TREND in BENCH_r*, not a one-off number) and WARN on a >10%
    regression. Silent when fewer than two artifacts carry the key."""
    import glob as _glob
    import re as _re

    def _round_of(path: str) -> int:
        m = _re.search(r"BENCH_r(\d+)", os.path.basename(path))
        return int(m.group(1)) if m else -1

    # Numeric round order, not lexicographic: BENCH_r100.json must sort
    # AFTER BENCH_r99.json or the gate is permanently stuck comparing
    # two stale artifacts once rounds gain a digit.
    paths = sorted(
        _glob.glob(os.path.join(bench_dir, "BENCH_r*.json")),
        key=_round_of,
    )
    carrying = [
        (p, x) for p in paths
        if (x := _bench_floor_x(p)) is not None
    ]
    out: Dict[str, object] = {"artifacts": len(paths)}
    if len(carrying) < 2:
        return out
    (prev_path, prev), (last_path, last) = carrying[-2], carrying[-1]
    out.update({
        "previous": {"path": os.path.basename(prev_path), "x": prev},
        "latest": {"path": os.path.basename(last_path), "x": last},
    })
    if prev > 0 and last > prev * (1.0 + BENCH_TREND_REGRESSION):
        warn(
            f"decode roofline REGRESSED: {os.path.basename(last_path)} "
            f"has decode_x_above_bf16_floor = {last:g} vs {prev:g} in "
            f"{os.path.basename(prev_path)} (> {BENCH_TREND_REGRESSION:.0%} "
            f"climb) — the decode step moved AWAY from the bf16 HBM "
            f"floor. Check decode_step_breakdown in the artifact for the "
            f"component that grew (attention vs mlp vs logits vs "
            f"sampling), whether the fused decode attention/MLP paths "
            f"still dispatch (make decodebench asserts both), and the "
            f"sharded-decode mesh shape (docs/serving.md 'Decode "
            f"roofline')"
        )
    return out


def collect(
    plugin_data_dir: str,
    cdi_root: str,
    multiplex_socket_root: str,
    tpulib=None,
    metrics_endpoints: Optional[List[str]] = None,
    metrics_interval: float = 0.0,
    bench_dir: Optional[str] = None,
) -> dict:
    """Gather every section; pure data (rendering and exit codes are the
    caller's problem, so tests and future UIs can reuse this)."""
    report: dict = {"warnings": []}

    def warn(msg: str) -> None:
        report["warnings"].append(msg)

    # --- tpulib ---
    # A fresh lib reflects what the NODE says right now (kernel surfaces
    # on the linux backend); tests pass the plugin's live instance in.
    lib = tpulib or new_tpulib()
    gen = lib.generation()
    ici = lib.ici_domain()
    chips = lib.chips()
    report["tpulib"] = {
        "backend": type(lib).__name__,
        "generation": gen.name,
        "ici_domain": ici.clique_id() if ici else None,
        "chips": [
            {
                "uuid": c.uuid,
                "index": c.index,
                "coord": str(c.coord),
                "healthy": c.healthy,
            }
            for c in chips
        ],
        "subslices": [
            {
                "uuid": ss.uuid,
                "shape": str(ss.placement.shape),
                "origin": str(ss.placement.start),
                "parent_chips": ss.parent_chip_uuids,
            }
            for ss in lib.list_subslices()
        ],
    }
    for c in chips:
        if not c.healthy:
            warn(f"chip {c.uuid} ({c.coord}) is UNHEALTHY — it is "
                 f"unpublished from ResourceSlices until it recovers")

    # --- checkpoint (WAL) ---
    # Strictly read-only (inspect_file): the manager's tolerant load path
    # quarantines/heals as a side effect, and a diagnostic must not
    # mutate the node.
    claims: Dict[str, dict] = {}
    ckpt_path = os.path.join(plugin_data_dir, "checkpoint.json")
    ckpt_exists = os.path.exists(ckpt_path)
    ckpt_corrupt = None
    if ckpt_exists:
        try:
            cp = inspect_file(ckpt_path)
        except (ChecksumError, OSError) as e:
            ckpt_corrupt = str(e)
            cp = None
            bak = ckpt_path + ".bak"
            try:
                inspect_file(bak)
                bak_verdict = (
                    f"the backup {bak} is readable: the plugin will "
                    f"quarantine the corrupt file and recover from it at "
                    f"next boot"
                )
            except FileNotFoundError:
                bak_verdict = (
                    f"no backup at {bak}: the plugin will rebuild from "
                    f"the device scan (CDI specs + live sub-slices) at "
                    f"next boot"
                )
            except (ChecksumError, OSError) as be:
                bak_verdict = (
                    f"the backup {bak} is ALSO unreadable ({be}): the "
                    f"plugin will rebuild from the device scan (CDI "
                    f"specs + live sub-slices) at next boot"
                )
            warn(
                f"checkpoint {ckpt_path} is CORRUPT ({e}); {bak_verdict}"
            )
        if cp is not None:
            for uid, claim in sorted(cp.prepared_claims.items()):
                devices = claim.prepared_devices.device_names()
                claims[uid] = {
                    "state": claim.checkpoint_state,
                    "name": claim.name,
                    "namespace": claim.namespace,
                    "devices": devices,
                }
                if claim.checkpoint_state == CLAIM_STATE_PREPARE_STARTED:
                    warn(
                        f"claim {uid} ({claim.namespace}/{claim.name}) is "
                        f"in PrepareStarted: a prepare crashed mid-flight; "
                        f"the plugin rolls it back at next boot (or on the "
                        f"next kubelet retry) and the cleanup manager GCs "
                        f"it if the ResourceClaim is gone"
                    )
    else:
        report.setdefault("notes", []).append(
            f"no checkpoint at {ckpt_path} (plugin never ran here?)"
        )
    # Crash residue around the checkpoint file: a .tmp means a write was
    # interrupted; .corrupt-* quarantine files mean a past recovery ran.
    residue = {"tmp": [], "quarantined": []}
    try:
        for name in sorted(os.listdir(plugin_data_dir)):
            if name.startswith("checkpoint.json") and name.endswith(".tmp"):
                residue["tmp"].append(name)
            elif ".corrupt-" in name:
                residue["quarantined"].append(name)
    except FileNotFoundError:
        pass
    for name in residue["tmp"]:
        warn(
            f"leftover checkpoint temp file {name} — a checkpoint write "
            f"was interrupted (crash between the temp write and the "
            f"atomic replace); the plugin sweeps it at next boot, or "
            f"delete it by hand — NEVER rename it over checkpoint.json"
        )
    for name in residue["quarantined"]:
        warn(
            f"quarantined corrupt checkpoint {name} — a past boot "
            f"recovered from .bak or the device scan; inspect it for "
            f"forensics, then delete it to clear this warning"
        )
    report["checkpoint"] = {
        "path": ckpt_path,
        "claims": claims,
        "corrupt": ckpt_corrupt,
        "residue": residue,
    }

    # --- CDI specs vs checkpoint ---
    # Read the directory directly: constructing CDIHandler would CREATE
    # a mistyped --cdi-root as a side effect (and crash unprivileged
    # runs) — a diagnostic must not mutate the node.
    prefix = f"{CDI_VENDOR}-claim_"
    try:
        spec_uids = sorted(
            name[len(prefix):-len(".json")]
            for name in os.listdir(cdi_root)
            if name.startswith(prefix) and name.endswith(".json")
        )
    except FileNotFoundError:
        spec_uids = []
        report.setdefault("notes", []).append(
            f"CDI root {cdi_root} does not exist (plugin never ran here, "
            f"or --cdi-root is mistyped)"
        )
    report["cdi"] = {"root": cdi_root, "claim_specs": spec_uids}
    completed = {
        uid for uid, c in claims.items()
        if c["state"] == CLAIM_STATE_PREPARE_COMPLETED
    }
    for uid in spec_uids:
        # Keyed on checkpoint-FILE existence, not the claim map's
        # truthiness: an empty checkpoint with a leftover spec is exactly
        # the crashed-unprepare scenario this check exists for. A corrupt
        # checkpoint says nothing about claims — skip rather than accuse
        # every spec of being orphaned.
        if ckpt_exists and ckpt_corrupt is None and uid not in claims:
            warn(
                f"CDI spec for claim {uid} has no checkpoint entry — an "
                f"unprepare likely crashed after checkpoint removal; the "
                f"spec is inert but should be cleaned up"
            )
    for uid in completed:
        if uid not in spec_uids:
            warn(
                f"claim {uid} is PrepareCompleted but its CDI spec is "
                f"missing — containers for it cannot start; re-Prepare "
                f"will regenerate it"
            )

    # --- sharing arbiters ---
    arbiters: Dict[str, dict] = {}
    if os.path.isdir(multiplex_socket_root):
        for claim_uid in sorted(os.listdir(multiplex_socket_root)):
            path = os.path.join(
                multiplex_socket_root, claim_uid, SOCKET_NAME
            )
            if not os.path.exists(path):
                continue
            try:
                with socket.socket(
                    socket.AF_UNIX, socket.SOCK_STREAM
                ) as s:
                    s.settimeout(1.0)
                    s.connect(path)
                    s.sendall(b'{"op": "status"}\n')
                    st = json.loads(s.makefile().readline())
                arbiters[claim_uid] = {
                    k: st.get(k)
                    for k in ("holder", "waiting", "heldSeconds",
                              "maxHoldSeconds", "overdue", "revocations",
                              "preemption")
                }
                if st.get("overdue"):
                    warn(
                        f"arbiter for claim {claim_uid}: holder "
                        f"{st.get('holder')!r} is OVERDUE with "
                        f"{st.get('waiting')} waiter(s)"
                        + ("" if st.get("preemption")
                           else " and preemption is OFF — it can starve "
                                "its neighbors indefinitely")
                    )
            except (OSError, ValueError) as e:
                arbiters[claim_uid] = {"error": str(e)}
                warn(f"arbiter socket for claim {claim_uid} did not "
                     f"answer: {e}")
    report["arbiters"] = arbiters

    # --- component metrics ---
    if metrics_endpoints:
        report["metrics"] = probe_metrics(
            metrics_endpoints, interval=metrics_interval, warn=warn
        )

    # --- bench artifact trend (decode roofline) ---
    if bench_dir:
        report["bench_trend"] = check_bench_trend(bench_dir, warn)
    return report


def render(report: dict) -> str:
    t = report["tpulib"]
    lines = [
        f"tpulib     : {t['backend']} generation={t['generation']} "
        f"ici={t['ici_domain']}",
    ]
    for c in t["chips"]:
        mark = "ok " if c["healthy"] else "BAD"
        lines.append(
            f"  chip {c['index']} [{mark}] {c['uuid']} @ {c['coord']}"
        )
    for ss in t["subslices"]:
        lines.append(
            f"  subslice {ss['uuid']} {ss['shape']} @ {ss['origin']}"
        )
    ck = report["checkpoint"]
    status = " CORRUPT" if ck.get("corrupt") else ""
    lines.append(
        f"checkpoint : {ck['path']} ({len(ck['claims'])} claims){status}"
    )
    for uid, c in ck["claims"].items():
        lines.append(
            f"  {uid} {c['state']} {c['namespace']}/{c['name']} "
            f"devices={c['devices']}"
        )
    residue = ck.get("residue") or {}
    for name in residue.get("tmp", []):
        lines.append(f"  residue: {name} (interrupted write)")
    for name in residue.get("quarantined", []):
        lines.append(f"  residue: {name} (quarantined)")
    lines.append(
        f"cdi        : {report['cdi']['root']} "
        f"({len(report['cdi']['claim_specs'])} claim specs)"
    )
    lines.append(f"arbiters   : {len(report['arbiters'])} live")
    for uid, st in report["arbiters"].items():
        lines.append(f"  {uid}: {st}")
    for ep, m in report.get("metrics", {}).items():
        if "error" in m:
            lines.append(f"metrics    : {ep} UNREACHABLE ({m['error']})")
            continue
        n = len(m.get("failure_counters", {}))
        lines.append(
            f"metrics    : {ep} ({n} failure-class series present)"
        )
        for series, st in m.get("failure_counters", {}).items():
            climbed = (
                f" (climbed {st['climbed']:g})" if "climbed" in st else ""
            )
            lines.append(f"  {series} = {st['value']:g}{climbed}")
        deg = m.get("degraded") or {}
        if deg.get("api_degraded"):
            lines.append("  DEGRADED mode (apiserver circuit open)")
        for verb, state in (deg.get("circuits") or {}).items():
            if state != "closed":
                lines.append(f"  circuit[{verb}] = {state}")
        sched = m.get("scheduler") or {}
        if sched:
            frag = sched.get("frag_score")
            seen = sched.get("slices_seen")
            indexed = sched.get("slices_indexed")
            parts = []
            if frag is not None:
                parts.append(f"frag_score={frag:g}")
            if seen is not None or indexed is not None:
                parts.append(f"index={indexed}/{seen} slices")
            lines.append(f"  scheduler: {' '.join(parts)}")
        eng = m.get("engine") or {}
        if eng:
            parts = []
            if "admission_stalled_s" in eng:
                parts.append(
                    f"stalled={eng['admission_stalled_s']:g}s"
                )
            if "pages_free" in eng:
                parts.append(f"pages_free={eng['pages_free']}")
            if "page_exhausted" in eng:
                parts.append(f"exhausted={eng['page_exhausted']}")
            if "spec_accept_rate" in eng:
                parts.append(
                    f"spec_accept={eng['spec_accept_rate']:g} "
                    f"({eng.get('spec_accepted', 0)}/"
                    f"{eng.get('spec_proposed', 0)})"
                )
            if "prefix_shared_pages" in eng:
                parts.append(
                    f"shared_pages={eng['prefix_shared_pages']}"
                )
            lines.append(f"  engine: {' '.join(parts)}")
        fabric = m.get("fabric") or {}
        if fabric:
            parts = []
            if "replicas" in fabric:
                parts.append(f"replicas={fabric['replicas']}")
            if "deaths" in fabric:
                by = fabric.get("deaths_by_reason") or {}
                split = ",".join(
                    f"{k}:{v}" for k, v in sorted(by.items())
                )
                parts.append(
                    f"deaths={fabric['deaths']}"
                    + (f"({split})" if split else "")
                )
            if fabric.get("circuit_open"):
                parts.append(f"circuit_open={fabric['circuit_open']}")
            if fabric.get("degraded"):
                parts.append(f"degraded={fabric['degraded']:g}")
            if "flaps" in fabric:
                parts.append(f"flaps={fabric['flaps']}")
            for series, st in sorted(
                (fabric.get("tenant_lags") or {}).items()
            ):
                label = series.split("{", 1)
                tenant = ""
                if len(label) > 1 and "tenant=" in label[1]:
                    tenant = "[" + label[1].rstrip("}").split(
                        "tenant=", 1
                    )[1].strip('"') + "]"
                grew = (
                    f"+{st['grew']:g}" if st.get("grew", 0) > 0 else ""
                )
                parts.append(f"lag{tenant}={st['lag']:g}{grew}")
            lines.append(f"  fabric: {' '.join(parts)}")
        disagg = m.get("disagg") or {}
        if disagg:
            parts = []
            pools = disagg.get("pools") or {}
            if pools:
                parts.append(
                    "pools="
                    + ",".join(
                        f"{k}:{v}" for k, v in sorted(pools.items())
                    )
                )
            parts.append(
                f"queued=p:{disagg.get('queued_prefill_tokens', 0):g}"
                f"/d:{disagg.get('queued_decode_tokens', 0):g}"
            )
            if "migration_backlog" in disagg:
                grew = (
                    f"+{disagg['backlog_grew']:g}"
                    if disagg.get("backlog_grew", 0) > 0 else ""
                )
                parts.append(
                    f"backlog={disagg['migration_backlog']}{grew}"
                )
            mig = disagg.get("migrations") or {}
            if mig:
                parts.append(
                    "migrations="
                    + ",".join(
                        f"{k}:{v}" for k, v in sorted(mig.items())
                    )
                )
            lines.append(f"  disagg: {' '.join(parts)}")
        rep = m.get("repacker") or {}
        if rep:
            parts = []
            if "leader" in rep:
                parts.append(f"leader={1 if rep['leader'] else 0}")
            if "active" in rep:
                parts.append(f"active={rep['active']}")
            if "migrations" in rep:
                parts.append(f"migrations={rep['migrations']}")
            if "frag_score" in rep:
                parts.append(f"frag={rep['frag_score']:g}")
            if rep.get("oldest_migration_s", 0.0) > 0:
                parts.append(f"oldest={rep['oldest_migration_s']:g}s")
            lines.append(f"  repacker: {' '.join(parts)}")
        gng = m.get("gang") or {}
        if gng:
            parts = []
            if "members" in gng:
                parts.append(f"members={gng['members']}")
            if "pending" in gng:
                parts.append(f"pending={gng['pending']}")
            if gng.get("unschedulable"):
                parts.append(f"unschedulable={gng['unschedulable']}")
            if gng.get("wal_oldest_s", 0.0) > 0:
                parts.append(f"wal_oldest={gng['wal_oldest_s']:g}s")
            if gng.get("partial_rollbacks"):
                parts.append(
                    f"partial_rollbacks={gng['partial_rollbacks']}"
                )
            lines.append(f"  gang: {' '.join(parts)}")
        for series, v in sorted((m.get("series_capped") or {}).items()):
            lines.append(f"  series-capped: {series} = {v:g}")
        wq = m.get("workqueue") or {}
        if wq:
            parts = []
            for series, st in sorted(wq.items()):
                label = series.split("{", 1)
                shard = ""
                if len(label) > 1 and "shard=" in label[1]:
                    shard = "[" + label[1].rstrip("}").split(
                        "shard=", 1
                    )[1].strip('"') + "]"
                grew = (
                    f"+{st['grew']:g}" if st.get("grew", 0) > 0 else ""
                )
                parts.append(f"depth{shard}={st['depth']:g}{grew}")
            lines.append(f"  workqueue: {' '.join(parts)}")
        fmon = m.get("fleetmon") or {}
        if fmon.get("targets"):
            tgts = fmon["targets"]
            up = sum(1 for t in tgts.values() if t.get("up"))
            parts = [f"up={up}/{len(tgts)}"]
            if "interval_s" in fmon:
                parts.append(f"interval={fmon['interval_s']:g}s")
            interval = fmon.get("interval_s") or 0
            for tname, t in sorted(tgts.items()):
                if t.get("up") is False:
                    parts.append(f"down[{tname}]")
                elif (
                    interval and t.get("age_s") is not None
                    and t["age_s"] > FLEETMON_STALE_INTERVALS * interval
                ):
                    parts.append(f"stale[{tname}]={t['age_s']:g}s")
            lines.append(f"  fleetmon: {' '.join(parts)}")
        aflow = m.get("apiflow") or {}
        if aflow:
            parts = []
            for flow, entry in sorted(
                (aflow.get("rejected") or {}).items()
            ):
                climbed = (
                    f"+{entry['climbed']:g}"
                    if entry.get("climbed", 0) > 0 else ""
                )
                parts.append(
                    f"rejected[{flow}]={entry['rejected']:g}{climbed}"
                )
            if aflow.get("retry_budget_exhausted"):
                climbed = (
                    f"+{aflow['retry_budget_climbed']:g}"
                    if aflow.get("retry_budget_climbed", 0) > 0 else ""
                )
                parts.append(
                    f"budget-exhausted="
                    f"{aflow['retry_budget_exhausted']:g}{climbed}"
                )
            lines.append(f"  apiflow: {' '.join(parts)}")
    for note in report.get("notes", []):
        lines.append(f"note: {note}")
    trend = report.get("bench_trend")
    if trend is not None:
        if "latest" in trend:
            lines.append(
                f"bench      : decode_x_above_bf16_floor "
                f"{trend['latest']['x']:g} ({trend['latest']['path']}) "
                f"vs {trend['previous']['x']:g} "
                f"({trend['previous']['path']})"
            )
        else:
            lines.append(
                f"bench      : {trend['artifacts']} artifact(s), no "
                f"roofline trend yet"
            )
    for w in report["warnings"]:
        lines.append(f"WARN: {w}")
    if not report["warnings"]:
        lines.append("healthy: no warnings")
    return "\n".join(lines)


# --- `doctor explain` — claim-lifecycle timeline stitching (ISSUE 13) --


def _scrape_traces(endpoint: str, timeout: float = 2.0) -> List[dict]:
    """Fetch one process's /debug/traces flight-recorder dump."""
    import urllib.request

    url = _endpoint_url(endpoint, "/debug/traces")
    with urllib.request.urlopen(url, timeout=timeout) as r:
        doc = json.loads(r.read().decode())
    return doc.get("spans", []) if isinstance(doc, dict) else []


def stitch(all_spans: List[dict], trace_id: str) -> List[dict]:
    """Merge every process's spans for one trace id, deduped by span id
    (a span can appear in two dumps when recorders are scraped through
    a shared endpoint), time-ordered."""
    seen = {}
    for s in all_spans:
        if s.get("trace") == trace_id:
            seen[s["span"]] = s
    return sorted(seen.values(), key=lambda s: s["wall0"])


def stage_budget(spans: List[dict]) -> dict:
    """The stage breakdown: every instant of the trace window is
    attributed to exactly ONE span — the DEEPEST (most-nested; ties to
    the latest-started) span covering it — or to `(unattributed)` when
    nothing covers it, so the rows SUM to the window by construction.
    This is the tool that turns 'p99 is 12.7s' into '11.9s was kubelet
    prepare serialization' without hiding time nothing instruments —
    and it stays honest for traces with OVERLAPPING siblings (the
    serving request's first_token measurement span covers the same
    wall time its prefill/dispatch siblings do; per-span self-time
    would sum to >100% of the window)."""
    if not spans:
        return {"window_s": 0.0, "stages": {}, "unattributed_s": 0.0}
    t0 = min(s["wall0"] for s in spans)
    t1 = max(s["wall0"] + max(s["dur_s"], 0.0) for s in spans)
    by_id = {s["span"]: s for s in spans}

    def depth(s: dict) -> int:
        d, cur, hops = 0, s, 0
        while cur["parent"] in by_id and hops < len(spans):
            cur = by_id[cur["parent"]]
            d += 1
            hops += 1
        return d

    depths = {s["span"]: depth(s) for s in spans}
    ivals = [
        (s["wall0"], s["wall0"] + max(s["dur_s"], 0.0), s)
        for s in spans
    ]
    # Sweep over elementary segments between interval boundaries; the
    # span count per trace is small, so O(segments x spans) is fine.
    cuts = sorted({a for a, _b, _s in ivals} | {b for _a, b, _s in ivals})
    stages: Dict[str, float] = {}
    unattributed = 0.0
    for seg_a, seg_b in zip(cuts, cuts[1:]):
        if seg_b <= seg_a:
            continue
        covering = [
            s for a, b, s in ivals if a <= seg_a and b >= seg_b
        ]
        if not covering:
            unattributed += seg_b - seg_a
            continue
        winner = max(
            covering, key=lambda s: (depths[s["span"]], s["wall0"])
        )
        stages[winner["name"]] = (
            stages.get(winner["name"], 0.0) + (seg_b - seg_a)
        )
    # Zero-length rows for every span name so the render still lists
    # instantaneous stages (a 0.0 ms device prepare is information).
    for s in spans:
        stages.setdefault(s["name"], 0.0)
    return {
        "window_s": t1 - t0,
        "stages": stages,
        "unattributed_s": unattributed,
    }


def render_explain(
    claim_key: str, trace_id: str, spans: List[dict], budget: dict
) -> str:
    from tpu_dra.infra import trace as trace_mod

    lines = [
        f"claim      : {claim_key}",
        f"trace      : {trace_id} ({len(spans)} spans)",
        "",
        trace_mod.render_timeline(spans),
        "",
        f"stage budget (window {budget['window_s'] * 1000:.1f} ms):",
    ]
    window = budget["window_s"] or 1.0
    rows = sorted(
        budget["stages"].items(), key=lambda kv: kv[1], reverse=True
    )
    for name, self_t in rows:
        lines.append(
            f"  {name:<32} {self_t * 1000:9.1f} ms "
            f"({self_t / window * 100:5.1f}%)"
        )
    lines.append(
        f"  {'(unattributed)':<32} "
        f"{budget['unattributed_s'] * 1000:9.1f} ms "
        f"({budget['unattributed_s'] / window * 100:5.1f}%)"
    )
    return "\n".join(lines)


def explain_main(argv) -> int:
    """`doctor explain --claim ns/name`: fetch the claim's ctx
    annotation, scrape the involved processes' flight recorders, stitch
    ONE timeline by trace id, and print the stage budget breakdown."""
    from tpu_dra.infra import flags
    from tpu_dra.infra import trace as trace_mod

    p = argparse.ArgumentParser(
        "tpu-dra-doctor explain", description=explain_main.__doc__
    )
    flags.KubeClientConfig.add_flags(p)
    p.add_argument(
        "--claim", default="",
        metavar="NS/NAME",
        help="ResourceClaim whose lifecycle to explain (its "
        "trace.tpu.google.com/ctx annotation names the trace)",
    )
    p.add_argument(
        "--trace-id", default="",
        help="Explain this trace id directly (skips the claim fetch — "
        "for request traces or already-deleted claims)",
    )
    p.add_argument(
        "--trace-endpoint", action="append", default=[],
        dest="trace_endpoints", metavar="HOST:PORT",
        help="Component /debug/traces endpoint to scrape (repeatable: "
        "scheduler + the claim's node plugin + the serving router)",
    )
    p.add_argument(
        "--chrome-out", default="",
        help="Also write the stitched trace as Chrome/Perfetto "
        "trace_event JSON to this path",
    )
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)

    trace_id = args.trace_id
    claim_key = args.claim or "(direct trace id)"
    if not trace_id:
        if not args.claim or "/" not in args.claim:
            print(
                "doctor explain: need --claim NS/NAME or --trace-id",
                file=sys.stderr,
            )
            return 2
        ns, _, name = args.claim.partition("/")
        from tpu_dra.k8sclient import (
            ApiNotFound, RESOURCE_CLAIMS, ResourceClient,
        )

        backend = flags.KubeClientConfig.from_args(args).new_client()
        try:
            claim = ResourceClient(backend, RESOURCE_CLAIMS).get(name, ns)
        except ApiNotFound:
            print(
                f"doctor explain: claim {args.claim} not found",
                file=sys.stderr,
            )
            return 1
        raw = (claim["metadata"].get("annotations") or {}).get(
            trace_mod.TRACE_ANNOTATION, ""
        )
        ctx = trace_mod.SpanContext.decode(raw)
        if ctx is None:
            print(
                f"claim {args.claim} carries no "
                f"{trace_mod.TRACE_ANNOTATION} annotation (allocated "
                f"before tracing was enabled, or tracing is off)",
                file=sys.stderr,
            )
            return 1
        trace_id = ctx.trace_id
    all_spans: List[dict] = []
    for ep in args.trace_endpoints:
        # ValueError covers a 200 with a non-JSON body (a proxy error
        # page, some other service on the port): skip-and-continue so
        # the remaining recorders still stitch.
        try:
            all_spans.extend(_scrape_traces(ep))
        except (OSError, ValueError) as e:
            print(
                f"doctor explain: {ep} did not answer: {e}",
                file=sys.stderr,
            )
    spans = stitch(all_spans, trace_id)
    if not spans:
        print(
            f"no spans for trace {trace_id} in "
            f"{len(args.trace_endpoints)} recorder(s) — the window may "
            f"have rotated out of the ring (flight recorders are "
            f"bounded; docs/observability.md 'Flight recorder sizing')",
            file=sys.stderr,
        )
        return 1
    budget = stage_budget(spans)
    if args.chrome_out:
        with open(args.chrome_out, "w") as f:
            json.dump(
                {
                    "traceEvents": trace_mod.chrome_events(spans),
                    "displayTimeUnit": "ms",
                },
                f,
            )
    if args.as_json:
        print(json.dumps({
            "claim": claim_key,
            "trace": trace_id,
            "spans": spans,
            "budget": budget,
        }, indent=2))
    else:
        print(render_explain(claim_key, trace_id, spans, budget))
    return 0


# --- `doctor slo` — SLO snapshot triage (ISSUE 14) ---------------------------


def render_slo(snapshot: dict, warn) -> str:
    """Render a fleetmon snapshot (``fleetmon --once --json-out``) as
    per-SLO triage: burn rate, remaining budget, alert state, and the
    catalog's remediation for everything burning. Counter resets are
    FLAGGED, not folded into the burn — a restarted exporter re-counts
    from zero and the reset-safe increase already absorbed it; the
    operator should know a restart happened, not chase a bogus burn."""
    targets = snapshot.get("targets", {})
    up = sum(1 for t in targets.values() if t.get("up"))
    age_s = max(0.0, time.time() - snapshot.get("ts", time.time()))
    lines = [
        f"slo        : {len(snapshot.get('slos', []))} SLOs, "
        f"{up}/{len(targets)} targets up "
        f"(snapshot age {age_s:.0f}s)",
    ]
    for tname, t in sorted(targets.items()):
        if not t.get("up"):
            warn(
                f"fleetmon target {tname!r} was DOWN at snapshot time "
                f"({t.get('last_error') or 'scrape failed'}) — verdicts "
                f"below cover a partial fleet"
            )
        elif t.get("stale"):
            warn(
                f"fleetmon scrape of {tname!r} was STALE at snapshot "
                f"time (age {t.get('age_s')}s) — burn rates ran on old "
                f"samples"
            )
    from tpu_dra.tools.fleetmon import slo_state

    for s in snapshot.get("slos", []):
        state = slo_state(s)
        burn = s.get("burn_rate")
        left = s.get("budget_remaining")
        windows = " ".join(
            f"{w}={b:g}" for w, b in (s.get("burn") or {}).items()
        )
        lines.append(
            f"  {s['name']:<20} {state:<9} "
            f"burn={'-' if burn is None else f'{burn:g}'} "
            f"budget-left={'-' if left is None else f'{left:.0%}'} "
            f"[{windows or 'no windows'}] "
            f"objective {s.get('objective', '?')}"
        )
        if s.get("resets"):
            lines.append(
                f"    note: {s['resets']} counter reset(s) in the "
                f"window — an exporting process RESTARTED and "
                f"re-counted from zero; the burn above is reset-safe "
                f"(increase sums positive deltas), so do not read the "
                f"raw counter drop as budget coming back"
            )
        if s.get("alert"):
            sev = s["alert"]
            warn(
                f"SLO {s['name']!r} is {'PAGING' if sev == 'page' else 'TICKETING'}: "
                f"burn rate {burn:g}x budget over the "
                f"{'fast' if sev == 'page' else 'slow'} window pair "
                f"({windows}). {s.get('remediation') or ''}".rstrip()
            )
        elif s.get("ok") is False:
            warn(
                f"SLO {s['name']!r} is out of objective right now "
                f"(current {s.get('current')}, {s.get('objective')}) "
                f"but not yet burning past an alert window. "
                f"{s.get('remediation') or ''}".rstrip()
            )
    return "\n".join(lines)


def slo_main(argv) -> int:
    """`doctor slo --snapshot PATH`: read a fleetmon snapshot and
    print per-SLO burn rate, remaining budget, and remediation.
    Exit 0 healthy, 1 when any SLO alerts / violates / a target was
    down (probe-friendly, like the main doctor)."""
    p = argparse.ArgumentParser(
        "tpu-dra-doctor slo", description=slo_main.__doc__
    )
    p.add_argument(
        "--snapshot", default="",
        help="fleetmon snapshot JSON (`fleetmon --once --json-out P`); "
        "'-' reads stdin",
    )
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)
    if not args.snapshot:
        print("doctor slo: need --snapshot PATH (or '-')", file=sys.stderr)
        return 2
    try:
        if args.snapshot == "-":
            snapshot = json.load(sys.stdin)
        else:
            with open(args.snapshot) as f:
                snapshot = json.load(f)
    except (OSError, ValueError) as e:
        print(f"doctor slo: cannot read snapshot: {e}", file=sys.stderr)
        return 2
    warnings: List[str] = []
    body = render_slo(snapshot, warnings.append)
    if args.as_json:
        print(json.dumps(
            {"snapshot": snapshot, "warnings": warnings}, indent=2
        ))
    else:
        print(body)
        for w in warnings:
            print(f"WARN: {w}")
        if not warnings:
            print("healthy: every SLO inside budget")
    return 1 if warnings else 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if argv and argv[0] == "explain":
        return explain_main(argv[1:])
    if argv and argv[0] == "slo":
        return slo_main(argv[1:])
    p = argparse.ArgumentParser("tpu-dra-doctor", description=__doc__)
    p.add_argument(
        "--plugin-data-dir",
        default=os.environ.get(
            "PLUGIN_DATA_DIR", "/var/lib/kubelet/plugins/tpu.google.com"
        ),
    )
    p.add_argument(
        "--cdi-root", default=os.environ.get("CDI_ROOT", "/var/run/cdi")
    )
    p.add_argument(
        "--multiplex-socket-root",
        default=os.environ.get(
            "TPU_MULTIPLEX_SOCKET_ROOT", "/run/tpu-multiplex"
        ),
    )
    p.add_argument(
        "--metrics-endpoint", action="append", default=[],
        dest="metrics_endpoints", metavar="HOST:PORT",
        help="Component /metrics endpoint to scrape for failure-class "
        "counters (repeatable)",
    )
    p.add_argument(
        "--metrics-interval", type=float, default=0.0,
        help="Sample each metrics endpoint twice, this many seconds "
        "apart, and warn only on counters that climbed in the window",
    )
    p.add_argument(
        "--bench-dir",
        default=os.environ.get("TPU_DRA_BENCH_DIR", ""),
        help="Directory holding BENCH_r*.json artifacts; when given "
        "(or TPU_DRA_BENCH_DIR is set) the doctor WARNs when "
        "decode_x_above_bf16_floor regressed >10%% between the two "
        "newest. OPT-IN: a bench perf trend is not node health, so a "
        "plain doctor run never couples its exit code to it",
    )
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)
    report = collect(
        args.plugin_data_dir, args.cdi_root, args.multiplex_socket_root,
        metrics_endpoints=args.metrics_endpoints,
        metrics_interval=args.metrics_interval,
        bench_dir=args.bench_dir or None,
    )
    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        print(render(report))
    return 1 if report["warnings"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
