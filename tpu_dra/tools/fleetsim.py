"""Control-plane fleet simulator (ISSUE 10): 5k nodes, claim storms,
relist avalanches, and the claim-ready SLO.

PR 5 proved the control plane survives a *sick* apiserver and PR 6 made
allocation fast against a *synthetic* fleet — this harness proves the
whole control plane survives a *big* cluster, end to end: thousands of
synthetic nodes publishing ResourceSlices through the driver's real
publisher (:class:`tpu_dra.plugin.slicepub.SlicePublisher`), the real
:class:`tpu_dra.scheduler.core.SchedulerCore` (informers, SliceIndex,
batched allocation), and a kubelet analog that "prepares" each
allocated claim on its owning node and renders the claim's CDI env.
The fleet is the IDENTICAL synthetic fleet the allocator microbench
measures (:mod:`tpu_dra.scheduler.fleet`).

Headline SLO: **claim-submitted → pod-env-injected** p50/p99 over a
seeded open-loop (Poisson) claim trace with create/delete churn —
recorded by ``bench.py --leg-fleet`` as ``fleet_claim_ready_p50_ms`` /
``fleet_claim_ready_p99_ms`` so regressions land in BENCH_r*.json.

Two modes, same workload, measured against each other:

- **optimized** (the shipped path): content-diffed + coalesced slice
  publishes (a health-flap burst that settles back to the same content
  costs ZERO apiserver writes) and the kubelet's prepare queue SHARDED
  by node (``infra.workqueue.ShardedWorkQueue``);
- **baseline** (the pre-ISSUE-10 behavior, kept callable): one full
  slice rewrite per event — every flap is a GET+PUT that bumps the
  resourceVersion, fans out MODIFIED to every slice watcher, and makes
  the scheduler's index re-parse the slice — and one serial unsharded
  prepare queue.

``fleet_p99_speedup`` = baseline p99 / optimized p99; the smoke gates
it hard at small scale (``FLEETSIM_ALLOW_GAP=1`` to bypass on hostile
CI), the full leg records it at fleet scale.

Relist-storm drill (optimized stack, post-trace): overflow the server's
watch-event window, drop every watch, and measure each informer's
resync-to-converged time (``fleet_relist_storm_p99_ms``) — asserting,
not eyeballing, that informer store sizes, cache bytes, and live
watch-slot counts return exactly to baseline (no leaked watchers, no
unbounded relist loops), and that field-selector-scoped node-local
informers stay O(node) while the fleet informer holds O(fleet).

Entry points::

    python -m tpu_dra.tools.fleetsim            # full (5k nodes)
    python -m tpu_dra.tools.fleetsim --smoke    # CI: small fleet +
                                                # hard asserts

Knobs (env): FLEETSIM_NODES, FLEETSIM_CLAIMS, FLEETSIM_RATE,
FLEETSIM_SEED, FLEETSIM_STORM_TICK, FLEETSIM_STORM_FRAC,
FLEETSIM_PREPARE_MS, FLEETSIM_ALLOW_GAP.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import statistics
import sys
import threading
import time
from typing import Dict, List, Optional

from tpu_dra.infra import trace
from tpu_dra.infra.metrics import Metrics
from tpu_dra.infra.workqueue import (
    ShardedWorkQueue,
    WorkQueue,
    default_controller_rate_limiter,
)
from tpu_dra.k8sclient import (
    CONFIG_MAPS,
    DEVICE_CLASSES,
    RESOURCE_CLAIMS,
    RESOURCE_SLICES,
    Informer,
    ResourceClient,
)
from tpu_dra.k8sclient.fake import EVENT_LOG_WINDOW_ENV, FakeCluster
from tpu_dra.plugin.slicepub import SlicePublisher
from tpu_dra.scheduler import fleet
from tpu_dra.scheduler.core import SchedulerCore

NS = "fleetsim"
# Event window for the harness's FakeCluster: small enough that the
# relist drill can overflow it quickly (forcing ApiGone -> full relist
# on every informer), large enough that nothing trips it mid-trace
# (informers only consult the window on reconnect).
EVENT_WINDOW = 256


def _note(msg: str) -> None:
    print(f"fleetsim: {msg}", file=sys.stderr)


def _pct(sorted_ms: List[float], q: float) -> float:
    if not sorted_ms:
        return 0.0
    return sorted_ms[int(q * (len(sorted_ms) - 1))]


class NodeAgent:
    """One synthetic node's publisher — the driver's publish path
    without the silicon underneath it."""

    def __init__(
        self,
        index: int,
        slices: ResourceClient,
        metrics: Metrics,
        reverify_seconds: float = 0.0,
    ):
        self.index = index
        self.node = fleet.node_name(index)
        self.slices = slices
        self.metrics = metrics
        self.publisher = SlicePublisher(
            slices, node_name=self.node, metrics=metrics,
            presume_empty=True,
            # Default: no trust-but-verify relists — the in-process
            # harness owns the cluster (no external drift), and N agents
            # re-listing an N-node fleet on the reverify beat would be
            # O(N^2). The wire-mode storm workers (stormsim) OVERRIDE
            # this: there the apiserver restarts underneath the
            # publisher mid-run, and the reverify pass is exactly the
            # heal path the drill asserts.
            reverify_seconds=reverify_seconds,
        )
        self.naive_gen = 0
        self.naive_writes = 0

    def _slice(self, generation: int, degraded: bool) -> dict:
        s = fleet.make_node_slice(self.index, generation=generation)
        if degraded:
            # A health flap's content change: chip (0,0,0) reports
            # degraded (the real driver would unpublish it; an
            # attribute flip keeps the fleet's capacity stable so both
            # modes schedule the identical claims).
            s["spec"]["devices"][0]["basic"]["attributes"]["health"] = {
                "string": "degraded"
            }
        return s

    def publish(self, degraded: bool = False) -> int:
        """The shipped path: one content-diffed pass (zero writes when
        the state matches the last committed publish)."""
        return self.publisher.publish(
            lambda generation: [self._slice(generation, degraded)]
        )

    def naive_publish(self, degraded: bool = False) -> None:
        """The pre-ISSUE-10 driver behavior: every trigger re-reads and
        rewrites the full slice at a fresh generation, changed or not —
        resourceVersion churn and a MODIFIED fan-out per event."""
        self.naive_gen += 1
        s = self._slice(self.naive_gen, degraded)
        cur = self.slices.try_get(s["metadata"]["name"])
        if cur is None:
            self.slices.create(s)
        else:
            s["metadata"]["resourceVersion"] = cur["metadata"][
                "resourceVersion"
            ]
            self.slices.update(s)
        self.naive_writes += 1
        # The write happened on the apiserver either way — export it on
        # the SAME counter the diffed publisher uses, so the fleetmon
        # write-budget SLO sees a naive-publish regression over the
        # wire instead of only in the harness's private tally.
        self.metrics.inc("publish_writes_total")


def spin_fleet(cluster, nodes: int, metrics: Metrics) -> List[NodeAgent]:
    """Publish the shared synthetic fleet into ``cluster`` through the
    driver's REAL publisher and register the device classes — the
    composition point the serving fabric reuses (ISSUE 11): fabricbench
    stands its engine replicas on the IDENTICAL fleet the allocator
    microbench and this control-plane harness measure."""
    slices = ResourceClient(cluster, RESOURCE_SLICES)
    for cls in fleet.CLASSES:
        ResourceClient(cluster, DEVICE_CLASSES).create(
            json.loads(json.dumps(cls))
        )
    agents = [NodeAgent(i, slices, metrics) for i in range(nodes)]
    for a in agents:
        a.publish()
    return agents


class KubeletSim:
    """The fleet's kubelet+plugin analog: watches claims; when an
    allocation lands, 'prepares' the claim on its owning node (a fixed
    per-claim cost standing in for the NodePrepareResources RPC) and
    renders the CDI env — the t_ready stamp of the claim-submitted →
    pod-env-injected SLO. Prepares are serialized per node; across
    nodes they ride either the sharded queue (shipped) or one global
    serial queue (baseline)."""

    def __init__(
        self,
        backend,
        metrics: Metrics,
        sharded: bool,
        shards: int = 16,
        prepare_ms: float = 1.0,
        submit_time_of=None,
        on_ready=None,
    ):
        self.metrics = metrics
        self.sharded = sharded
        self.prepare_ms = prepare_ms
        # Optional (name, claim, env) callback fired exactly once per
        # claim after the ready stamp: the wire-mode kubelet worker
        # (stormsim) uses it to PATCH a ready annotation back onto the
        # claim so the parent process can observe pod-env-injected over
        # the apiserver instead of a shared-memory dict.
        self.on_ready = on_ready
        # Optional claim-name -> submit monotonic-time lookup: with it,
        # the kubelet EXPORTS the claim-submitted -> pod-env-injected
        # latency as the `claim_ready_seconds` summary — the series the
        # fleetmon SLO catalog evaluates claim-ready-p99 against over
        # the wire (ISSUE 14), instead of the SLO living only in the
        # harness's private latency list.
        self.submit_time_of = submit_time_of
        self.informer = Informer(backend, RESOURCE_CLAIMS, metrics=metrics)
        if sharded:
            self.queue: object = ShardedWorkQueue(
                shards=shards, metrics=metrics,
            )
        else:
            self.queue = WorkQueue(
                default_controller_rate_limiter(), metrics=metrics
            )
        self.ready: Dict[str, tuple] = {}  # name -> (t_ready, env)
        self._lock = threading.Lock()
        self._threads: List[threading.Thread] = []

    def start(self) -> None:
        self.informer.add_handler(self._on_claim)
        self.informer.start()
        if self.sharded:
            self._threads.extend(self.queue.run_in_threads())
        else:
            self._threads.append(self.queue.run_in_thread())

    def stop(self) -> None:
        self.queue.shutdown()
        self.informer.stop()

    def _on_claim(self, event: str, claim: dict) -> None:
        if event == "DELETED":
            return
        alloc = (claim.get("status") or {}).get("allocation")
        if not alloc:
            return
        name = claim["metadata"]["name"]
        with self._lock:
            if name in self.ready:
                return
        results = alloc["devices"]["results"]
        node = results[0]["pool"] if results else ""
        if self.sharded:
            self.queue.enqueue(
                claim, self._prepare, key=name, shard_key=node
            )
        else:
            self.queue.enqueue(claim, self._prepare, key=name)

    def _prepare(self, claim: dict) -> None:
        name = claim["metadata"]["name"]
        with self._lock:
            if name in self.ready:
                return
        # Adopt the claim's ctx annotation (stamped by the scheduler's
        # allocation commit): the harness's prepare stand-in stitches
        # into the claim's trace exactly like the real plugin's
        # plugin.claim.prepare does — `make tracecheck` asserts it.
        with trace.span(
            "kubelet.claim.prepare",
            ctx=trace.extract(claim),
            attrs={"claim": name},
        ):
            results = claim["status"]["allocation"]["devices"]["results"]
            env = {
                "TPU_DRA_CLAIM": claim["metadata"].get("uid", name),
            }
            for i, r in enumerate(results):
                env[f"TPU_DRA_DEVICE_{i}"] = f"{r['pool']}/{r['device']}"
            if self.prepare_ms > 0:
                # The kubelet RPC + CDI spec write stand-in; serialized
                # per node like the real plugin's prepare path.
                time.sleep(self.prepare_ms / 1000.0)
            t_ready = time.monotonic()
            stamped = False
            with self._lock:
                if name not in self.ready:
                    self.ready[name] = (t_ready, env)
                    stamped = True
            if stamped and self.submit_time_of is not None:
                t_submit = self.submit_time_of(name)
                if t_submit is not None:
                    self.metrics.observe(
                        "claim_ready_seconds", t_ready - t_submit
                    )
            if stamped and self.on_ready is not None:
                self.on_ready(name, claim, env)

    def ready_count(self) -> int:
        with self._lock:
            return len(self.ready)


class _ModeRun:
    """One full mode execution over a fresh cluster."""

    def __init__(
        self,
        nodes: int,
        claims: int,
        rate: float,
        seed: int,
        optimized: bool,
        storm_tick: float,
        storm_frac: float,
        prepare_ms: float,
        churn: float,
        sample_scoped: int,
    ):
        self.nodes = nodes
        self.n_claims = claims
        self.rate = rate
        self.seed = seed
        self.optimized = optimized
        self.storm_tick = storm_tick
        self.storm_frac = storm_frac
        self.churn = churn
        self.sample_scoped = min(sample_scoped, nodes)

        os.environ[EVENT_LOG_WINDOW_ENV] = str(EVENT_WINDOW)
        self.cluster = FakeCluster()
        self.metrics = Metrics()
        self.slices = ResourceClient(self.cluster, RESOURCE_SLICES)
        self.claims = ResourceClient(self.cluster, RESOURCE_CLAIMS)
        for cls in fleet.CLASSES:
            ResourceClient(self.cluster, DEVICE_CLASSES).create(
                json.loads(json.dumps(cls))
            )
        self.agents = [
            NodeAgent(i, self.slices, self.metrics) for i in range(nodes)
        ]
        self.core = SchedulerCore(
            self.cluster, retry_unschedulable_after=0.5
        )
        self.submit_times: Dict[str, float] = {}
        self._submit_lock = threading.Lock()
        self.kubelet = KubeletSim(
            self.cluster, self.metrics, sharded=optimized,
            prepare_ms=prepare_ms,
            submit_time_of=self.submit_times.get,
        )
        # Node-local scoped observers: the field-selector scoping the
        # harness measures (each holds ONE node's slice, not the fleet).
        self.scoped = [
            Informer(
                self.cluster, RESOURCE_SLICES,
                field_selector={"spec.nodeName": fleet.node_name(j)},
                metrics=Metrics(),
            )
            for j in range(self.sample_scoped)
        ]
        self._informers: List[Informer] = []
        self._stop_storm = threading.Event()
        self._threads: List[threading.Thread] = []
        self.deleted: set = set()

    # --- lifecycle ---

    def start(self) -> None:
        t0 = time.perf_counter()
        for a in self.agents:
            if self.optimized:
                a.publish()
            else:
                a.naive_publish()
        self.initial_publish_s = time.perf_counter() - t0
        self._informers = [
            self.core.claim_informer, self.core.slice_informer,
            self.core.class_informer, self.kubelet.informer,
            *self.scoped,
        ]
        for inf in self._informers:
            inf.resync_backoff = 0.05
            inf.resync_backoff_max = 0.5
        self.core.start()
        self.kubelet.start()
        for inf in self.scoped:
            inf.start()
        t1 = time.perf_counter()
        deadline = time.monotonic() + 120
        for inf in self._informers:
            if not inf.wait_for_sync(timeout=deadline - time.monotonic()):
                raise RuntimeError("informer sync timed out at startup")
        _note(
            f"{'optimized' if self.optimized else 'baseline'}: initial "
            f"publish {self.initial_publish_s:.1f}s, informer sync "
            f"{time.perf_counter() - t1:.1f}s"
        )

    def stop(self) -> None:
        self._stop_storm.set()
        for t in self._threads:
            t.join(timeout=10)
        self.kubelet.stop()
        self.core.stop()
        for inf in self.scoped:
            inf.stop()

    # --- load ---

    def _storm(self) -> None:
        """Publish weather: every tick a seeded sample of nodes takes a
        4-event health flap that settles back to healthy. Shipped path:
        the driver's coalescing collapses the burst into one diffed
        pass over the FINAL (unchanged) state — zero writes. Baseline:
        one full rewrite per event."""
        rng = random.Random(self.seed ^ 0xF1EE7)
        n_flap = max(1, int(self.nodes * self.storm_frac))
        first = True
        # First tick fires immediately: a fast machine draining the
        # whole trace inside one tick period must still see weather
        # (the publish-batching contrast is part of the contract).
        while first or not self._stop_storm.wait(self.storm_tick):
            first = False
            for i in rng.sample(range(self.nodes), n_flap):
                agent = self.agents[i]
                if self.optimized:
                    agent.publish(degraded=False)
                else:
                    for k in range(4):
                        agent.naive_publish(degraded=(k % 2 == 0))

    def _submit(self) -> None:
        rng = random.Random(self.seed ^ 0x5AB417)
        trace = fleet.make_trace(self.n_claims, self.seed)
        t_next = time.monotonic()
        for claim in trace:
            t_next += rng.expovariate(self.rate)
            now = time.monotonic()
            if t_next > now:
                time.sleep(t_next - now)
            c = json.loads(json.dumps(claim))
            c["metadata"]["namespace"] = NS
            c["metadata"].pop("uid", None)
            with self._submit_lock:
                self.submit_times[c["metadata"]["name"]] = time.monotonic()
            self.claims.create(c)

    def _churn(self) -> None:
        """Delete a seeded, name-keyed fraction of claims once they are
        ready (the create/delete storm half of the trace; name-keyed so
        both modes churn the identical claim set)."""
        import zlib

        while not self._stop_storm.wait(0.2):
            with self.kubelet._lock:
                ready_names = list(self.kubelet.ready)
            for name in ready_names:
                if name in self.deleted:
                    continue
                if (zlib.crc32(name.encode()) % 100) < self.churn * 100:
                    try:
                        self.claims.delete(name, NS)
                    except Exception:  # noqa: BLE001 — already gone
                        pass
                    self.deleted.add(name)

    def run_trace(self) -> dict:
        for target, name in (
            (self._storm, "fleet-storm"),
            (self._churn, "fleet-churn"),
        ):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        submit = threading.Thread(
            target=self._submit, daemon=True, name="fleet-submit"
        )
        t0 = time.monotonic()
        submit.start()
        self._threads.append(submit)
        # Generous drain bound: open-loop arrival (~claims/rate) plus
        # allocation + prepare backlog (the baseline mode's per-event
        # storms make it MUCH slower than the shipped path, by design).
        deadline = t0 + self.n_claims / self.rate + 600
        while time.monotonic() < deadline:
            if (
                not submit.is_alive()
                and self.kubelet.ready_count() >= self.n_claims
            ):
                break
            time.sleep(0.05)
        self._stop_storm.set()
        unready = self.n_claims - self.kubelet.ready_count()
        with self.kubelet._lock:
            ready = dict(self.kubelet.ready)
        lat_ms = sorted(
            (t_ready - self.submit_times[name]) * 1000.0
            for name, (t_ready, _env) in ready.items()
            if name in self.submit_times
        )
        writes = (
            self.metrics.get_counter("publish_writes_total")
            if self.optimized
            else float(sum(a.naive_writes for a in self.agents))
        )
        return {
            "claims": self.n_claims,
            "unready": unready,
            "claim_ready_p50_ms": round(_pct(lat_ms, 0.5), 2),
            "claim_ready_p99_ms": round(_pct(lat_ms, 0.99), 2),
            "claim_ready_mean_ms": round(
                statistics.mean(lat_ms), 2
            ) if lat_ms else 0.0,
            "publish_writes": int(writes),
            "publish_skipped_unchanged": int(self.metrics.get_counter(
                "publish_skipped_unchanged_total"
            )),
            "deleted": len(self.deleted),
            "wall_s": round(time.monotonic() - t0, 2),
        }

    # --- relist storm drill (optimized stack, post-trace) ---

    def _informer_cache_bytes(self) -> int:
        obs = [self.core.slice_informer, *self.scoped]
        return sum(
            len(json.dumps(o, sort_keys=True))
            for inf in obs
            for o in inf.list_refs()
        )

    def relist_storm(self) -> dict:
        """Overflow the event window, drop every watch, and measure the
        heal: per-informer resync latency, plus the flatness asserts
        (store sizes, cache bytes, live watch slots back to baseline)."""
        # Quiesce: storms/submits are stopped, but late churn DELETEDs
        # may still be dispatching on informer threads — baselines
        # captured mid-drain would never be matched again. Wait for
        # every store to hold still.
        stable_since = time.monotonic()
        last = {inf: inf.store_size() for inf in self._informers}
        deadline = time.monotonic() + 60
        while time.monotonic() - stable_since < 1.0:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "informer stores never quiesced before the drill"
                )
            time.sleep(0.05)
            cur = {inf: inf.store_size() for inf in self._informers}
            if cur != last:
                last = cur
                stable_since = time.monotonic()
        base_counts = {inf: inf.store_size() for inf in self._informers}
        base_watches = self.cluster.live_watch_count()
        base_bytes = self._informer_cache_bytes()
        relists_before = {
            inf: inf.metrics.get_counter(
                "informer_relists_total",
                labels={"informer": inf.rd.plural},
            ) if inf.metrics is not None else 0.0
            for inf in self._informers
        }
        # Push every informer's resume point out of the retained event
        # window so reconnect => ApiGone => full relist (the partition-
        # heal avalanche), then drop every stream at once.
        cms = ResourceClient(self.cluster, CONFIG_MAPS)
        for i in range(EVENT_WINDOW // 2 + 8):
            cms.create({"metadata": {"name": f"filler-{i}", "namespace": NS}})
            cms.delete(f"filler-{i}", NS)
        t_drop = time.monotonic()
        self.cluster.clear_watches()
        durations_ms = {}
        deadline = t_drop + 300
        pending = set(self._informers)
        while pending and time.monotonic() < deadline:
            for inf in list(pending):
                if inf.metrics is None:
                    pending.discard(inf)
                    continue
                relists = inf.metrics.get_counter(
                    "informer_relists_total",
                    labels={"informer": inf.rd.plural},
                )
                if (
                    relists > relists_before[inf]
                    and inf.store_size() == base_counts[inf]
                ):
                    durations_ms[inf] = (time.monotonic() - t_drop) * 1000
                    pending.discard(inf)
            time.sleep(0.005)
        if pending:
            detail = [
                f"{inf.rd.plural}"
                f"{'(scoped)' if inf.field_selector else ''}: "
                f"store {inf.store_size()} (base {base_counts[inf]}), "
                f"relists +{(inf.metrics.get_counter('informer_relists_total', labels={'informer': inf.rd.plural}) - relists_before[inf]) if inf.metrics else 0:g}"
                for inf in pending
            ]
            raise RuntimeError(
                f"{len(pending)} informer(s) never relisted after the "
                f"storm (unbounded relist loop or dead watch): {detail}"
            )
        # Settle: every informer must be back on a LIVE watch.
        t_end = time.monotonic() + 30
        while (
            self.cluster.live_watch_count() < base_watches
            and time.monotonic() < t_end
        ):
            time.sleep(0.01)
        after_counts = {inf: inf.store_size() for inf in self._informers}
        after_watches = self.cluster.live_watch_count()
        after_bytes = self._informer_cache_bytes()
        sorted_ms = sorted(durations_ms.values())
        scoped_max = max(
            (inf.store_size() for inf in self.scoped), default=0
        )
        out = {
            "relist_p50_ms": round(_pct(sorted_ms, 0.5), 2),
            "relist_p99_ms": round(_pct(sorted_ms, 0.99), 2),
            "informers": len(self._informers),
            "watch_slots_before": base_watches,
            "watch_slots_after": after_watches,
            "cache_bytes_before": base_bytes,
            "cache_bytes_after": after_bytes,
            "stores_flat": after_counts == base_counts,
            "scoped_informer_max_objects": scoped_max,
            "unscoped_informer_objects":
                self.core.slice_informer.store_size(),
        }
        # Harness asserts, not eyeballs (acceptance criteria).
        assert out["stores_flat"], (
            f"informer store sizes moved across the relist storm: "
            f"{[(i.rd.plural, base_counts[i], after_counts[i]) for i in self._informers if base_counts[i] != after_counts[i]]}"
        )
        assert after_watches == base_watches, (
            f"watch slots leaked across the storm: "
            f"{base_watches} -> {after_watches}"
        )
        assert after_bytes == base_bytes, (
            f"informer cache bytes moved across the storm: "
            f"{base_bytes} -> {after_bytes}"
        )
        assert scoped_max <= 1, (
            f"a node-scoped informer holds {scoped_max} objects — "
            f"field-selector scoping is not engaged"
        )
        return out


def _assert_shard_fairness(prepare_ms: float = 2.0) -> dict:
    """Hot-shard isolation drill: one hot node floods its shard with
    slow work while cold nodes trickle; cold completion latency must
    stay bounded by their own shard's service time, NOT the hot
    backlog's. (The unsharded queue serializes cold behind hot —
    measured below as the contrast.)"""
    results: Dict[str, float] = {}
    lock = threading.Lock()

    def drive(queue, enqueue, cold_nodes):
        t0 = time.monotonic()

        def slow(_):
            time.sleep(prepare_ms / 1000.0)

        def stamp(name):
            def cb(_):
                with lock:
                    results[name] = time.monotonic() - t0
            return cb

        for i in range(200):
            enqueue(queue, None, slow, f"hot-{i}", "hot-node")
        for node in cold_nodes:
            enqueue(queue, None, stamp(node), f"cold-{node}", node)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with lock:
                if all(n in results for n in cold_nodes):
                    break
            time.sleep(0.002)
        with lock:
            return max(results[n] for n in cold_nodes)

    sharded = ShardedWorkQueue(shards=8)
    sharded.run_in_threads()
    # Cold keys are picked OFF the hot shard: hashing may legitimately
    # co-locate a cold key with the hot one (that key shares its fate —
    # the point of sharding is bounding the blast radius, not
    # eliminating it), so the fairness claim is about the OTHER shards.
    hot_shard = sharded.shard_of("hot-node")
    cold_nodes = [
        f"node-{i}" for i in range(64)
        if sharded.shard_of(f"node-{i}") != hot_shard
    ][:8]
    sharded_cold = drive(
        sharded,
        lambda q, obj, cb, key, sk: q.enqueue(obj, cb, key=key, shard_key=sk),
        cold_nodes,
    )
    sharded.shutdown()
    results.clear()
    serial = WorkQueue(default_controller_rate_limiter())
    serial.run_in_thread()
    serial_cold = drive(
        serial, lambda q, obj, cb, key, sk: q.enqueue(obj, cb, key=key),
        cold_nodes,
    )
    serial.shutdown()
    hot_backlog_s = 200 * prepare_ms / 1000.0
    assert sharded_cold < hot_backlog_s / 4, (
        f"cold keys waited {sharded_cold:.3f}s behind a hot shard — "
        f"sharding is not isolating (hot backlog {hot_backlog_s:.3f}s)"
    )
    return {
        "sharded_cold_p100_ms": round(sharded_cold * 1000, 2),
        "serial_cold_p100_ms": round(serial_cold * 1000, 2),
    }


# --- SLO-evaluated wire mode (ISSUE 14) --------------------------------------


def run_slo_leg(
    nodes: int = 16,
    claims: int = 20,
    rate: float = 60.0,
    seed: int = 20260804,
    prepare_ms: float = 1.0,
    window_scale: float = 1.0 / 600.0,
    regress_s: float = 30.0,
    smoke: bool = False,
) -> dict:
    """The fleet's gates as **runtime SLO verdicts, over the wire**:
    fakeserver HTTP (reduced node count — transport is part of the
    measurement), the real publisher/scheduler/kubelet-analog exporting
    on ONE MetricsServer, and fleetmon scraping that endpoint while the
    run is live, evaluating the built-in catalog with scaled SRE burn
    windows.

    Asserted phases (the `make slocheck` contract, also run by
    ``bench.py --leg-fleet``):

    1. **steady state**: the content-diffed publisher stays INSIDE the
       apiserver write budget (ROADMAP item 5: slice writes per node
       per hour — health flaps settling back to identical content cost
       zero writes), claim-ready-p99 and frag verdicts carry data, and
       a deliberately-dead scrape target reports ``fleetmon_target_up
       == 0`` (the doctor's WARN signal);
    2. **injected regression**: the agents flip to the pre-ISSUE-10
       naive per-event republish — the write-budget burn rate blows
       through the page thresholds on BOTH fast windows and the
       multi-window alert FIRES. The zero-write steady state is a
       monitored objective now, not a one-shot bench assert;
    3. **brownout + restart** (ISSUE 20): seats squeezed under a
       saturating storm — the flow-rejection-rate SLO must page with
       the sheds landing on the slice-publish flow; then a mid-watch
       apiserver restart followed by a fresh claim wave — the
       claim-ready-recovery-p99 SLO must carry data and hold.
    """
    from tpu_dra.infra.metrics import MetricsServer
    from tpu_dra.k8sclient.fakeserver import FakeApiServer
    from tpu_dra.k8sclient.rest import KubeClient
    from tpu_dra.tools import fleetmon as fleetmon_mod

    interval_s = 0.2
    page = None  # resolved below from the scaled policy
    srv = FakeApiServer(port=0).start()
    metrics = Metrics()
    stop = threading.Event()
    threads: List[threading.Thread] = []
    fm = msrv = core = kubelet = None
    try:
        def client() -> KubeClient:
            return KubeClient(
                server=srv.server_url, qps=5000, burst=5000
            )

        agents = spin_fleet(client(), nodes, metrics)
        submit_times: Dict[str, float] = {}
        core = SchedulerCore(
            client(), retry_unschedulable_after=0.5, metrics=metrics
        )
        # Phase 3's restart drill: claims submitted after the restart
        # instant additionally export claim_ready_recovery_seconds —
        # the series the claim-ready-recovery-p99 SLO evaluates.
        restart_t: List[Optional[float]] = [None]

        def observe_recovery(name: str, claim: dict, env: dict) -> None:
            t0 = restart_t[0]
            t_submit = submit_times.get(name)
            if t0 is not None and t_submit is not None and t_submit >= t0:
                metrics.observe(
                    "claim_ready_recovery_seconds",
                    time.monotonic() - t_submit,
                )

        kubelet = KubeletSim(
            client(), metrics, sharded=True, prepare_ms=prepare_ms,
            submit_time_of=submit_times.get,
            on_ready=observe_recovery,
        )
        core.start()
        kubelet.start()
        deadline = time.monotonic() + 60
        for inf in (
            core.claim_informer, core.slice_informer,
            core.class_informer, kubelet.informer,
        ):
            if not inf.wait_for_sync(timeout=deadline - time.monotonic()):
                raise RuntimeError("slo leg: informer sync timed out")
        msrv = MetricsServer(metrics, port=0, address="127.0.0.1")
        msrv.start()
        # Claim-ready target: wire-mode p99 at this scale measures a
        # few seconds (transport + batch cadence); 10s keeps the
        # verdict meaningful without CI-machine flake.
        catalog = fleetmon_mod.builtin_catalog(
            nodes=nodes, window_scale=window_scale,
            claim_ready_target_s=10.0,
        )
        page = catalog[0].policy[0]
        fm = fleetmon_mod.FleetMon(
            [
                fleetmon_mod.Target("fleet", f"127.0.0.1:{msrv.port}"),
                # The apiserver exports its own registry at GET
                # /metrics (flow-control + restart counters); the
                # flow-rejection-rate SLO reads this target. The
                # endpoint bypasses the flow gate, so scrapes survive
                # the brownout they are measuring.
                fleetmon_mod.Target("apiserver", f"127.0.0.1:{srv.port}"),
                # The deliberately-broken target: nothing listens on
                # port 1 — fleetmon_target_up must report it down
                # (what the doctor's fleetmon section WARNs on).
                fleetmon_mod.Target("ghost", "127.0.0.1:1"),
            ],
            catalog=catalog, interval_s=interval_s, metrics=metrics,
        )
        fm.start()

        rng = random.Random(seed ^ 0x510)
        flap = max(1, nodes // 8)

        def storm() -> None:
            # Settling health flaps: the diffed publisher's zero-write
            # steady state, exercised continuously while monitored.
            while not stop.wait(interval_s):
                for i in rng.sample(range(nodes), flap):
                    try:
                        agents[i].publish(degraded=False)
                    except Exception:  # noqa: BLE001
                        # Phase 3's restart/brownout sever pooled
                        # connections mid-PUT; the publisher's reverify
                        # heals, the flap loop must survive to see it.
                        pass

        t = threading.Thread(target=storm, daemon=True, name="slo-storm")
        t.start()
        threads.append(t)

        claims_client = ResourceClient(client(), RESOURCE_CLAIMS)
        trace_claims = fleet.make_trace(claims, seed)
        arr = random.Random(seed ^ 0x51)
        t_next = time.monotonic()
        for c in trace_claims:
            t_next += arr.expovariate(rate)
            now = time.monotonic()
            if t_next > now:
                time.sleep(t_next - now)
            c = json.loads(json.dumps(c))
            c["metadata"]["namespace"] = NS
            c["metadata"].pop("uid", None)
            submit_times[c["metadata"]["name"]] = time.monotonic()
            claims_client.create(c)
        drain_deadline = time.monotonic() + 120
        while kubelet.ready_count() < claims:
            if time.monotonic() > drain_deadline:
                raise RuntimeError(
                    f"slo leg wedged: {claims - kubelet.ready_count()} "
                    f"claim(s) never became ready"
                )
            time.sleep(0.02)
        # Let the scrape cover the page pair's LONG window with steady
        # post-drain data before judging the steady-state verdicts.
        time.sleep(page.long_s + 3 * interval_s)

        steady = {st.name: st for st in fm.evaluate()}
        wb, ready = steady["write-budget"], steady["claim-ready-p99"]
        assert wb.data and wb.burn_rate is not None, (
            "write-budget SLO has no data — publish_writes_total not "
            "scraped"
        )
        assert wb.ok and wb.alert is None, (
            f"steady state blew the write budget: "
            f"{wb.current} writes/node/h (burn {wb.burn_rate}) — the "
            f"content-diffed publisher should be at ~zero writes"
        )
        assert ready.data and ready.burn_rate is not None, (
            "claim-ready SLO has no data — claim_ready_seconds not "
            "scraped"
        )
        tgts = fm.target_report()
        assert tgts["fleet"]["up"] and not tgts["ghost"]["up"], (
            f"target health wrong: {tgts}"
        )
        assert metrics.get_gauge(
            "fleetmon_target_up", {"target": "ghost"}
        ) == 0.0, "dead target not exported as fleetmon_target_up 0"

        # Phase 2: the injected regression — naive per-event republish,
        # held LIVE until the alert is observed. Probing after the
        # regression stopped would (correctly!) find the fast windows
        # healed — the multi-window alert requires the burn to be
        # sustained AND still happening, which is the design, so the
        # drill keeps burning while it probes. Two threads over
        # disjoint agent halves: each write is a synchronous HTTP
        # GET+PUT, so one thread's achievable write rate is transport-
        # bound and machine-dependent.
        regress_stop = threading.Event()

        def regress_loop(part: List[NodeAgent]) -> None:
            while not regress_stop.is_set():
                for a in part:
                    if regress_stop.is_set():
                        break
                    a.naive_publish()

        regressors = [
            threading.Thread(
                target=regress_loop, args=(agents[j::2],),
                daemon=True, name=f"slo-regress-{j}",
            )
            for j in range(2)
        ]
        for t in regressors:
            t.start()
        alerted = None
        try:
            probe_deadline = time.monotonic() + max(regress_s, 30.0)
            while alerted is None and time.monotonic() < probe_deadline:
                st = fm.status_of("write-budget")
                if st is not None and st.alert == "page":
                    alerted = st
                else:
                    time.sleep(interval_s)
        finally:
            regress_stop.set()
            for t in regressors:
                t.join(timeout=10)
        assert alerted is not None, (
            f"naive-publish regression did NOT trip the write-budget "
            f"page alert: {fm.status_of('write-budget')}"
        )

        # Phase 3a: injected BROWNOUT — the apiserver's seats squeezed
        # to 2 with loaded-handler latency, under a saturating naive
        # publish storm. The flow gate must shed the low-priority
        # slice-publish flow (429 + Retry-After) and the
        # flow-rejection-rate SLO must PAGE — shedding is a monitored
        # objective, not just a unit-tested mechanism.
        # Latency is spent while HOLDING a seat: 8 writers over 2
        # seats at 100ms each queue ~0.4s — past the 0.2s bound, so
        # the gate sheds flow-ordered.
        srv.flow.configure(concurrency=2, max_queue_seconds=0.2)
        srv.inject_faults(latency=0.1, latency_seconds=120.0)
        brown_stop = threading.Event()

        def brown_loop(part: List[NodeAgent]) -> None:
            while not brown_stop.is_set():
                for a in part:
                    if brown_stop.is_set():
                        break
                    try:
                        a.naive_publish()
                    except Exception:  # noqa: BLE001
                        # Shed-after-retries IS the drill; the counter
                        # the SLO reads already recorded it.
                        pass

        browners = [
            threading.Thread(
                target=brown_loop, args=(agents[j::4],),
                daemon=True, name=f"slo-brownout-{j}",
            )
            for j in range(4)
        ]
        for t in browners:
            t.start()
        flow_alerted = None
        try:
            probe_deadline = time.monotonic() + max(regress_s, 30.0)
            while (
                flow_alerted is None
                and time.monotonic() < probe_deadline
            ):
                st = fm.status_of("flow-rejection-rate")
                if st is not None and st.alert == "page":
                    flow_alerted = st
                else:
                    time.sleep(interval_s)
        finally:
            brown_stop.set()
            for t in browners:
                t.join(timeout=10)
            # Lift the brownout: stock seats back, latency cleared.
            srv.flow.configure(concurrency=64, max_queue_seconds=15.0)
            srv.inject_faults(latency=0.0, latency_seconds=0.0)
        assert flow_alerted is not None, (
            f"apiserver brownout did NOT trip the flow-rejection-rate "
            f"page alert: {fm.status_of('flow-rejection-rate')}"
        )
        flow_rejected = {
            f: s["rejected"] for f, s in srv.flow.stats().items()
        }
        assert flow_rejected.get("slice-publish", 0) > 0, (
            f"brownout sheds did not land on the slice-publish flow: "
            f"{flow_rejected}"
        )

        # Phase 3b: apiserver RESTART mid-watch, then a fresh claim
        # wave. Informers relist off 410 Gone, the transport rides the
        # refused-connect window, and the recovery wave's
        # submitted -> ready latency exports as
        # claim_ready_recovery_seconds — the claim-ready-recovery-p99
        # SLO must carry data and hold.
        # The phase-1 workloads are done: release their claims so the
        # recovery wave contends for transport + scheduling latency,
        # not for devices (a fleet sized for one wave cannot hold two —
        # leftover allocations would read as "recovery wedged" when the
        # truth is "unschedulable forever"). ready_count keeps the old
        # names: the 2×claims drain below still counts both waves.
        for c in claims_client.list(NS):
            claims_client.delete(c["metadata"]["name"], NS)
        restart_t[0] = time.monotonic()
        srv.restart(outage_seconds=0.3)
        rec_trace = fleet.make_trace(claims, seed ^ 0x77)
        arr_rec = random.Random(seed ^ 0x77)
        t_next = time.monotonic()
        for c in rec_trace:
            t_next += arr_rec.expovariate(rate)
            now = time.monotonic()
            if t_next > now:
                time.sleep(t_next - now)
            c = json.loads(json.dumps(c))
            c["metadata"]["name"] = "rec-" + c["metadata"]["name"]
            c["metadata"]["namespace"] = NS
            c["metadata"].pop("uid", None)
            submit_times[c["metadata"]["name"]] = time.monotonic()
            claims_client.create(c)
        rec_deadline = time.monotonic() + 120
        while kubelet.ready_count() < 2 * claims:
            if time.monotonic() > rec_deadline:
                raise RuntimeError(
                    f"post-restart recovery wedged: "
                    f"{2 * claims - kubelet.ready_count()} claim(s) "
                    f"never became ready after the apiserver restart"
                )
            time.sleep(0.02)
        time.sleep(page.long_s + 3 * interval_s)
        rec = fm.status_of("claim-ready-recovery-p99")
        assert rec is not None and rec.data, (
            "claim-ready-recovery-p99 SLO has no data — "
            "claim_ready_recovery_seconds not scraped after the "
            "restart drill"
        )
        assert rec.ok, (
            f"post-restart claim-ready p99 {rec.current}s blew the "
            f"recovery objective"
        )

        snapshot = fm.snapshot()
        report = {
            "slo_nodes": nodes,
            "slo_claims": claims,
            "slo_write_budget_ok": bool(wb.ok),
            "slo_write_budget_burn_rate": round(wb.burn_rate, 4),
            "slo_writes_per_node_per_hour": round(wb.current or 0.0, 2),
            "slo_claim_ready_burn_rate": round(ready.burn_rate, 4),
            "slo_claim_ready_p99_s": round(ready.current or 0.0, 4),
            "slo_claim_ready_ok": bool(ready.ok),
            "slo_regression_alert": alerted.alert,
            "slo_regression_burn_rate": round(
                alerted.burn_rate or 0.0, 2
            ),
            "slo_flow_rejection_alert": flow_alerted.alert,
            "slo_flow_rejected": flow_rejected,
            "slo_recovery_p99_s": round(rec.current or 0.0, 4),
            "slo_recovery_ok": bool(rec.ok),
            "slo_targets_up": sum(
                1 for t in snapshot["targets"].values() if t["up"]
            ),
            "slo_targets_total": len(snapshot["targets"]),
            "slo_catalog": {
                st.name: {
                    "data": st.data,
                    "ok": st.ok,
                    "burn_rate": st.burn_rate,
                    "alert": st.alert,
                }
                for st in steady.values()
            },
        }
        frag = steady.get("frag-ceiling")
        if frag is not None and frag.data:
            report["slo_frag_ok"] = bool(frag.ok)
        if smoke:
            _note(
                "slocheck contract: steady write budget "
                f"{report['slo_writes_per_node_per_hour']}/node/h "
                f"(burn {report['slo_write_budget_burn_rate']}), "
                f"claim-ready burn "
                f"{report['slo_claim_ready_burn_rate']}, regression "
                f"alert={report['slo_regression_alert']} (burn "
                f"{report['slo_regression_burn_rate']}), brownout "
                f"alert={report['slo_flow_rejection_alert']} with "
                f"sheds on "
                f"{[f for f, n in flow_rejected.items() if n]}, "
                f"post-restart recovery p99 "
                f"{report['slo_recovery_p99_s']}s, dead target "
                "reported down — all hold"
            )
        return report
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if fm is not None:
            fm.stop()
        if msrv is not None:
            msrv.stop()
        if kubelet is not None:
            kubelet.stop()
        if core is not None:
            core.stop()
        srv.stop()


def run(
    nodes: int,
    claims: int,
    rate: float,
    seed: int,
    storm_tick: float,
    storm_frac: float,
    prepare_ms: float,
    churn: float,
    smoke: bool = False,
) -> dict:
    # Trace determinism: the seeded claim trace is the contract both
    # modes (and future rounds) replay; pin it before spending minutes.
    t1 = fleet.make_trace(claims, seed)
    t2 = fleet.make_trace(claims, seed)
    assert json.dumps(t1, sort_keys=True) == json.dumps(t2, sort_keys=True), (
        "claim trace is not deterministic for a fixed seed"
    )

    report: dict = {
        "fleet_nodes": nodes,
        "fleet_chips": nodes * len(fleet.MESH_COORDS),
        "seed": seed,
        "rate_claims_per_s": rate,
    }
    modes = {}
    for optimized in (True, False):
        label = "optimized" if optimized else "baseline"
        _note(
            f"{label}: {nodes} nodes, {claims} claims at {rate}/s, "
            f"storm {storm_frac:.0%}/{storm_tick}s, prepare "
            f"{prepare_ms}ms, churn {churn:.0%}"
        )
        mode = _ModeRun(
            nodes, claims, rate, seed, optimized, storm_tick,
            storm_frac, prepare_ms, churn, sample_scoped=8,
        )
        mode.start()
        try:
            res = mode.run_trace()
            if res["unready"]:
                raise RuntimeError(
                    f"{label}: {res['unready']} claim(s) never became "
                    f"ready — control plane wedged or fleet overfull"
                )
            if optimized:
                res["relist_storm"] = mode.relist_storm()
        finally:
            mode.stop()
        modes[label] = res
        _note(
            f"{label}: claim-ready p50 {res['claim_ready_p50_ms']} ms "
            f"p99 {res['claim_ready_p99_ms']} ms, publish writes "
            f"{res['publish_writes']}, wall {res['wall_s']}s"
        )

    opt, base = modes["optimized"], modes["baseline"]
    speedup = (
        base["claim_ready_p99_ms"] / opt["claim_ready_p99_ms"]
        if opt["claim_ready_p99_ms"] > 0 else 0.0
    )

    # Tracing-overhead leg (ISSUE 13): the IDENTICAL seeded trace over
    # the optimized stack with TPU_DRA_TRACE=0 semantics — the traced
    # mode above vs this one is the `fleet_trace_overhead_pct` the
    # overhead gate rides (tracing must be near-free when on, a shared
    # no-op when off).
    _note(
        "untraced: rerunning the optimized leg with tracing disabled "
        "(overhead measurement)"
    )
    prev_traced = trace.set_enabled(False)
    try:
        untraced_mode = _ModeRun(
            nodes, claims, rate, seed, True, storm_tick,
            storm_frac, prepare_ms, churn, sample_scoped=8,
        )
        untraced_mode.start()
        try:
            untraced = untraced_mode.run_trace()
            if untraced["unready"]:
                raise RuntimeError(
                    f"untraced: {untraced['unready']} claim(s) never "
                    f"became ready"
                )
        finally:
            untraced_mode.stop()
    finally:
        trace.set_enabled(prev_traced)
    overhead_pct = (
        (opt["claim_ready_p99_ms"] / untraced["claim_ready_p99_ms"] - 1.0)
        * 100.0
        if untraced["claim_ready_p99_ms"] > 0 else 0.0
    )
    modes["untraced"] = untraced
    _note(
        f"trace overhead: traced p99 {opt['claim_ready_p99_ms']} ms vs "
        f"untraced {untraced['claim_ready_p99_ms']} ms -> "
        f"{overhead_pct:+.1f}%"
    )

    fairness = _assert_shard_fairness()
    report.update({
        "fleet_claims": claims,
        "fleet_claim_ready_p50_ms": opt["claim_ready_p50_ms"],
        "fleet_claim_ready_p99_ms": opt["claim_ready_p99_ms"],
        "fleet_relist_storm_p99_ms":
            opt["relist_storm"]["relist_p99_ms"],
        "fleet_p99_speedup": round(speedup, 3),
        "fleet_publish_writes": opt["publish_writes"],
        "fleet_baseline_publish_writes": base["publish_writes"],
        "fleet_baseline_claim_ready_p50_ms": base["claim_ready_p50_ms"],
        "fleet_baseline_claim_ready_p99_ms": base["claim_ready_p99_ms"],
        "fleet_trace_overhead_pct": round(overhead_pct, 2),
        "fleet_untraced_claim_ready_p99_ms":
            untraced["claim_ready_p99_ms"],
        "fleet_scoped_informer_max_objects":
            opt["relist_storm"]["scoped_informer_max_objects"],
        "fleet_unscoped_informer_objects":
            opt["relist_storm"]["unscoped_informer_objects"],
        "fleet_watch_slots": opt["relist_storm"]["watch_slots_after"],
        "fleet_cache_bytes": opt["relist_storm"]["cache_bytes_after"],
        "shard_fairness": fairness,
        "modes": modes,
    })

    if not smoke:
        # SLO-evaluated wire mode (ISSUE 14): reduced node count over
        # fakeserver HTTP, fleetmon scraping the live run — the write
        # budget + claim-ready gates as catalog verdicts (the smoke
        # equivalent is its own `make slocheck` target).
        _note(
            "slo: SLO-evaluated wire leg (fakeserver HTTP, fleetmon "
            "scraping the live run)"
        )
        report.update(run_slo_leg(seed=seed))

    allow_gap = os.environ.get("FLEETSIM_ALLOW_GAP") == "1"
    # Tracing-overhead gate, smoke AND full leg. The acceptance bound
    # is <5% at the full-leg scale (where p99 is seconds and stable);
    # the smoke's p99 is tens of milliseconds on a shared CI machine,
    # so the smoke bound is loosened to absorb scheduler-tick noise
    # while still catching a structural regression (a lock, a sync
    # write, an O(n) pass on the hot path shows up as x2, not +25%).
    bound = 25.0 if smoke else 5.0
    if not allow_gap:
        assert overhead_pct < bound, (
            f"trace overhead gate: traced claim-ready p99 "
            f"{opt['claim_ready_p99_ms']} ms is {overhead_pct:+.1f}% "
            f"over the untraced {untraced['claim_ready_p99_ms']} ms "
            f"(bound {bound}%; FLEETSIM_ALLOW_GAP=1 to bypass on a "
            f"hostile machine)"
        )
    if smoke:
        # The SLO keys the bench leg records must be present and sane.
        for key in (
            "fleet_claim_ready_p50_ms", "fleet_claim_ready_p99_ms",
            "fleet_relist_storm_p99_ms",
        ):
            assert report[key] > 0, f"smoke: {key} missing/zero"
        # The hard gate: sharded + batched beats unsharded + per-event
        # on p99 claim-ready, by a margin (acceptance criteria).
        if not allow_gap:
            assert speedup >= 1.1, (
                f"smoke gate: optimized p99 {opt['claim_ready_p99_ms']} "
                f"ms vs baseline {base['claim_ready_p99_ms']} ms — "
                f"speedup {speedup:.3f} < 1.1 (FLEETSIM_ALLOW_GAP=1 to "
                f"bypass on a hostile machine)"
            )
        # Publish batching engaged: the same storm cost the optimized
        # path strictly fewer apiserver writes than per-event baseline.
        assert opt["publish_writes"] < base["publish_writes"], (
            f"smoke: diffed publishes ({opt['publish_writes']}) not "
            f"fewer than per-event baseline ({base['publish_writes']})"
        )
        _note(
            "smoke contract: SLO keys present, p99 gate "
            f"({speedup:.2f}x), publish batching, trace overhead "
            f"({overhead_pct:+.1f}%), relist flatness, shard fairness "
            "— all hold"
        )
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser("fleetsim", description=__doc__)
    p.add_argument(
        "--smoke", action="store_true",
        help="small fleet + hard contract asserts (the CI leg)",
    )
    p.add_argument(
        "--slocheck", action="store_true",
        help="SLO-evaluated wire smoke only (`make slocheck`): mini "
        "fleet over fakeserver HTTP, fleetmon scrapes it live, catalog "
        "verdicts + the naive-publish regression tripping the "
        "write-budget burn alert are hard-asserted",
    )
    args = p.parse_args(argv)
    env = os.environ.get
    if args.slocheck:
        report = run_slo_leg(
            nodes=int(env("FLEETSIM_SLO_NODES", "16")),
            claims=int(env("FLEETSIM_SLO_CLAIMS", "20")),
            seed=int(env("FLEETSIM_SEED", "20260804")),
            smoke=True,
        )
        print(json.dumps(report))
        return 0
    if args.smoke:
        # Arrival rate is held ABOVE the baseline's serial prepare
        # service rate (400/s vs 1000ms/5ms = 200/s): the unsharded
        # queue's backlog is structural, so the p99 gate separates by
        # design, not by CI-machine luck. Claim count stays within the
        # fleet's chip capacity (120 x ~2.35 chips < 96 x 4) so every
        # claim schedules without waiting on churn.
        nodes = int(env("FLEETSIM_NODES", "96"))
        claims = int(env("FLEETSIM_CLAIMS", "120"))
        rate = float(env("FLEETSIM_RATE", "400"))
        prepare_ms = float(env("FLEETSIM_PREPARE_MS", "5.0"))
    else:
        nodes = int(env("FLEETSIM_NODES", "5000"))
        claims = int(env("FLEETSIM_CLAIMS", "1500"))
        rate = float(env("FLEETSIM_RATE", "250"))
        prepare_ms = float(env("FLEETSIM_PREPARE_MS", "1.0"))
    seed = int(env("FLEETSIM_SEED", "20260804"))
    # Storm intensity scales DOWN with fleet size: 2% of 96 nodes per
    # tick is a handful of flaps; 2% of 5000 is 400 slice events per
    # 250ms, which buries the BASELINE mode's slice informer + index
    # in per-event reparses so deep the leg never drains (measured —
    # that cliff is exactly why per-event republish had to go, but a
    # recorded ratio needs a baseline that finishes). Full scale
    # defaults to ~0.1% per 500ms: every node flapping about once per
    # 8 minutes, heavy-but-survivable real weather.
    if args.smoke:
        storm_tick = float(env("FLEETSIM_STORM_TICK", "0.25"))
        storm_frac = float(env("FLEETSIM_STORM_FRAC", "0.02"))
    else:
        storm_tick = float(env("FLEETSIM_STORM_TICK", "0.5"))
        storm_frac = float(env("FLEETSIM_STORM_FRAC", "0.001"))
    churn = float(env("FLEETSIM_CHURN", "0.3"))
    report = run(
        nodes, claims, rate, seed, storm_tick, storm_frac, prepare_ms,
        churn, smoke=args.smoke,
    )
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
