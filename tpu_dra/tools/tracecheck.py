"""Trace-lifecycle smoke (`make tracecheck`, ISSUE 13).

Proves the claim/request tracing contract end to end on a tiny run, in
seconds, with hard asserts — the T900 lint keeps the span-name table
honest statically; this keeps it honest dynamically:

1. **claim path**: a 4-node synthetic fleet through the REAL publisher
   + SchedulerCore + the fleetsim kubelet analog; every lifecycle span
   (claim.pending → solve.batch/snapshot/pack → claim.allocated →
   slice.publish → kubelet prepare) must land in the flight recorder,
   and at least one claim's kubelet prepare must stitch into its
   scheduler trace VIA THE ctx ANNOTATION (same trace id, parented);
2. **plugin path**: a stub-silicon DeviceState prepare of a claim
   carrying a ctx annotation — plugin.claim.prepare adopts it and the
   per-device child parents under it, WAL events present;
3. **request path**: a stub-engine serving fabric round trip —
   queued/dispatch/prefill/first_token spans share the request's trace;
4. **export**: the recorder's Chrome/Perfetto export is schema-valid
   ``trace_event`` JSON (the format Perfetto loads), and the text
   timeline renders.

Every registered lifecycle span must be present AND (where the
taxonomy declares a parent) correctly parented; a span that stops
firing — or stops stitching — fails CI here, not in an operator's
3am `doctor explain`.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import uuid

import numpy as np

from tpu_dra.infra import trace
from tpu_dra.infra.metrics import Metrics
from tpu_dra.k8sclient import (
    RESOURCE_CLAIMS,
    FakeCluster,
    ResourceClient,
)
from tpu_dra.scheduler import fleet
from tpu_dra.scheduler.core import SchedulerCore
from tpu_dra.tools.fleetsim import KubeletSim, spin_fleet

NS = "tracecheck"


def _note(msg: str) -> None:
    print(f"tracecheck: {msg}", file=sys.stderr)


# --- stage 1: the claim path over the real scheduler stack -------------


def drive_claim_path(n_nodes: int = 4, n_claims: int = 4):
    cluster = FakeCluster()
    metrics = Metrics()
    spin_fleet(cluster, n_nodes, metrics)
    core = SchedulerCore(cluster, retry_unschedulable_after=0.2)
    kubelet = KubeletSim(cluster, metrics, sharded=True, prepare_ms=1.0)
    core.start()
    kubelet.start()
    claims = ResourceClient(cluster, RESOURCE_CLAIMS)
    try:
        for c in fleet.make_trace(n_claims, seed=7)[:n_claims]:
            c = json.loads(json.dumps(c))
            c["metadata"]["namespace"] = NS
            c["metadata"].pop("uid", None)
            claims.create(c)
        deadline = time.monotonic() + 30
        while kubelet.ready_count() < n_claims:
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"claim path never drained: "
                    f"{kubelet.ready_count()}/{n_claims} ready"
                )
            time.sleep(0.01)
        # Every allocated claim must carry the ctx annotation the
        # commit write stamped.
        allocated = []
        for c in claims.list():
            if (c.get("status") or {}).get("allocation"):
                assert trace.extract(c) is not None, (
                    f"allocated claim {c['metadata']['name']} carries no "
                    f"{trace.TRACE_ANNOTATION} annotation"
                )
                allocated.append(c)
        assert allocated, "no claim reached allocation"
        return allocated[0]
    finally:
        kubelet.stop()
        core.stop()


# --- stage 2: the plugin prepare path over stub silicon ----------------


def drive_plugin_path(tmp: str, ctx) -> None:
    from tpu_dra.plugin.cdi import CDIHandler
    from tpu_dra.plugin.checkpoint import CheckpointManager
    from tpu_dra.plugin.device_state import DRIVER_NAME, DeviceState
    from tpu_dra.tpulib.stub import StubTpuLib

    lib = StubTpuLib(
        config={"generation": "v5e", "hostname": "node-0"},
        state_dir=os.path.join(tmp, "tpustate"),
    )
    state = DeviceState(
        tpulib=lib,
        cdi=CDIHandler(cdi_root=os.path.join(tmp, "cdi")),
        checkpoints=CheckpointManager(os.path.join(tmp, "ckpt")),
        node_name="node-0",
    )
    uid = str(uuid.uuid4())
    claim = {
        "apiVersion": "resource.k8s.io/v1beta1",
        "kind": "ResourceClaim",
        "metadata": {"name": "tc-claim", "namespace": NS, "uid": uid},
        "status": {"allocation": {"devices": {"results": [{
            "request": "req0", "driver": DRIVER_NAME,
            "pool": "node-0", "device": "tpu-0",
        }], "config": []}}},
    }
    # The scheduler-side stamp, as the plugin would receive it — the
    # REAL ctx minted by stage 1's allocation commit, so the plugin
    # prepare parents under an actual scheduler.claim.pending span.
    trace.stamp(claim, ctx)
    devices = state.prepare(claim)
    assert devices, "stub prepare returned no devices"
    prepared = [
        s for s in trace.RECORDER.spans()
        if s["name"] == "plugin.claim.prepare"
    ]
    assert prepared, "plugin.claim.prepare span not recorded"
    p = prepared[-1]
    assert p["trace"] == ctx.trace_id and p["parent"] == ctx.span_id, (
        "plugin.claim.prepare did not adopt the claim's ctx annotation"
    )
    ev_names = {e["name"] for e in p["events"]}
    assert {"wal.prepare_started", "wal.prepare_completed"} <= ev_names, (
        f"WAL phase events missing from the prepare span: {ev_names}"
    )
    assert any(
        e["name"] == "crashpoint" for e in p["events"]
    ), "crash-point windows did not land as span events"


# --- stage 3: the request path over a stub-engine fabric ---------------


def drive_request_path(n_requests: int = 3) -> None:
    # Function-local imports: tools may not depend on the serving/
    # workloads layers at module level (L500) — this drill is the one
    # spot the smoke needs them.
    from tpu_dra.serving.router import Replica, Router, TenantSpec
    from tpu_dra.workloads.engine import Completion, Evacuated, Request

    class _StubEngine:
        """One completion per step, arrival order — no JAX."""

        def __init__(self):
            self.queue = []
            self.completed = {}

        def add_request(self, req):
            self.queue.append(req)

        @property
        def busy(self):
            return bool(self.queue)

        def step(self):
            if self.queue:
                r = self.queue.pop(0)
                now = time.monotonic()
                self.completed[r.rid] = Completion(
                    rid=r.rid,
                    tokens=np.arange(r.max_new_tokens, dtype=np.int32),
                    t_submit=now, t_arrival=now,
                    t_first_token=now, t_done=now,
                )
            return self.busy

        def evacuate(self):
            out = [
                Evacuated(req=r, emitted=np.zeros(0, np.int32),
                          t_submit=0.0, t_first=None)
                for r in self.queue
            ]
            self.queue = []
            return out

        def close(self):
            pass

    rep = Replica("r0", _StubEngine())
    router = Router([TenantSpec(name="t0")], replicas=[rep])
    for i in range(n_requests):
        ok = router.submit("t0", Request(
            rid=f"tc-{i}",
            prompt=np.arange(4, dtype=np.int32),
            max_new_tokens=4,
        ))
        assert ok, "stub fabric rejected a request"
    for _ in range(200):
        router.poll()
        if rep.engine.busy:
            rep.engine.step()
        rep._drain_outbox()
        if not router.busy:
            break
    assert len(router.completions) == n_requests, (
        f"stub fabric completed {len(router.completions)}/{n_requests}"
    )


# --- assertions over the recorder --------------------------------------


def assert_lifecycle(spans) -> dict:
    by_name: dict = {}
    by_id = {s["span"]: s for s in spans}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    missing = [n for n in trace.LIFECYCLE_SPANS if n not in by_name]
    assert not missing, f"lifecycle spans never fired: {missing}"
    # Parenting: where the taxonomy declares a parent, at least one
    # instance must actually be parented under a span of that name in
    # the SAME trace (ring rotation can orphan older instances; one
    # correctly-stitched instance proves the mechanism).
    bad = []
    for name in trace.LIFECYCLE_SPANS:
        declared = trace.SPAN_NAMES[name][1]
        if not declared:
            continue
        ok = False
        for s in by_name[name]:
            parent = by_id.get(s["parent"])
            if (
                parent is not None
                and parent["name"] == declared
                and parent["trace"] == s["trace"]
            ):
                ok = True
                break
        if not ok:
            bad.append(f"{name} (declared parent {declared})")
    assert not bad, f"lifecycle spans never parented as declared: {bad}"
    # Cross-process-shaped stitch: a kubelet prepare sharing a trace id
    # with a scheduler pending span, via the annotation.
    stitched = {
        s["trace"] for s in by_name["kubelet.claim.prepare"]
    } & {
        s["trace"] for s in by_name["scheduler.claim.pending"]
    }
    assert stitched, (
        "no kubelet prepare stitched into a scheduler claim trace — "
        "ctx annotation propagation is broken"
    )
    return {n: len(v) for n, v in by_name.items()}


def assert_chrome_schema(path: str) -> int:
    with open(path) as f:
        doc = json.load(f)
    assert isinstance(doc, dict) and isinstance(
        doc.get("traceEvents"), list
    ), "chrome export: top level must be {'traceEvents': [...]}"
    assert doc["traceEvents"], "chrome export: no events"
    for ev in doc["traceEvents"]:
        assert isinstance(ev.get("name"), str) and ev["name"], (
            f"chrome event without a name: {ev}"
        )
        assert ev.get("ph") in ("X", "i"), f"unexpected phase: {ev}"
        assert isinstance(ev.get("ts"), (int, float)), f"bad ts: {ev}"
        assert isinstance(ev.get("pid"), int), f"bad pid: {ev}"
        if ev["ph"] == "X":
            assert isinstance(ev.get("dur"), (int, float)) and (
                ev["dur"] >= 0
            ), f"X event needs a non-negative dur: {ev}"
        else:
            assert ev.get("s") in ("t", "p", "g"), (
                f"instant event needs a scope: {ev}"
            )
        assert isinstance(ev.get("args"), dict), f"bad args: {ev}"
    return len(doc["traceEvents"])


def main(argv=None) -> int:
    prev = trace.set_enabled(True)
    trace.RECORDER.clear()
    try:
        stamped = drive_claim_path()
        with tempfile.TemporaryDirectory() as tmp:
            drive_plugin_path(tmp, trace.extract(stamped))
            drive_request_path()
            spans = trace.RECORDER.spans()
            counts = assert_lifecycle(spans)
            chrome = os.path.join(tmp, "trace.json")
            n = trace.RECORDER.export_chrome(chrome)
            n_events = assert_chrome_schema(chrome)
            assert n == n_events
            # The text timeline renders for a stitched claim trace.
            claim_trace = next(
                s["trace"] for s in spans
                if s["name"] == "kubelet.claim.prepare"
            )
            text = trace.RECORDER.render_text(claim_trace)
            assert "kubelet.claim.prepare" in text
        _note(
            "lifecycle spans fired+parented, claim stitched across "
            "components, chrome export schema-valid "
            f"({n_events} events), text timeline renders"
        )
        print(json.dumps({
            "lifecycle_spans": counts,
            "chrome_events": n_events,
            "dropped": trace.RECORDER.dropped,
        }))
        return 0
    finally:
        trace.set_enabled(prev)


if __name__ == "__main__":
    raise SystemExit(main())
