"""Operator tooling (no reference analog — the reference leaves node
debugging to kubectl exec + log spelunking)."""
