"""fleetmon: fleet-wide metrics aggregation + the SLO engine's scraper
(ISSUE 14).

Every component already exports Prometheus text on its MetricsServer
(plugin, scheduler + repacker leader, CD controller, multiplexd driver,
serving router, fleetsim's kubelet analog). fleetmon is the tier above:
it scrapes every configured ``/metrics`` endpoint on one cadence,
parses the exposition **round-trip against the registry's label
escaping** (a claim name carrying ``"`` or ``\\`` must survive
scrape -> store -> dashboard exactly), classifies series from the
``# TYPE`` lines (no name-suffix heuristics), feeds a
:class:`tpu_dra.infra.slo.SampleStore`, and evaluates the built-in SLO
catalog with multi-window burn-rate alerting.

Per-target health is itself exported (and doctor-checked):
``fleetmon_target_up{target=}``, ``fleetmon_scrape_age_seconds{target=}``
(refreshed at scrape time via a collector), and
``fleetmon_scrape_interval_seconds`` — a target whose age exceeds 3
intervals is STALE and the doctor says so.

CLI::

    python -m tpu_dra.tools.fleetmon \
        --target scheduler=127.0.0.1:9093 --target plugin=:9092 \
        --once --json-out /tmp/slo.json      # one snapshot (2 scrapes)
    python -m tpu_dra.tools.fleetmon --target ... --watch   # dashboard

``doctor slo --snapshot /tmp/slo.json`` renders the snapshot with
per-SLO burn rate, remaining budget, and remediation
(docs/observability.md, "Fleet SLOs & burn-rate alerting").
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from tpu_dra.infra import slo
from tpu_dra.infra.metrics import Metrics

# Default scrape cadence; the staleness verdict is stated in intervals
# so it survives retuning.
DEFAULT_INTERVAL_S = 15.0
STALE_AFTER_INTERVALS = 3.0

_UNESCAPE = {"n": "\n", '"': '"', "\\": "\\"}


def endpoint_url(endpoint: str, path: str) -> str:
    """host:port / URL -> a full http URL ending in ``path`` (the one
    normalization shared by fleetmon's scrape, doctor's /metrics probe
    and explain's /debug/traces scrape, so the rules cannot diverge)."""
    url = endpoint
    if not url.startswith(("http://", "https://")):
        url = f"http://{url}"
    if not url.endswith(path):
        url = url.rstrip("/") + path
    return url


# --- exposition parsing ------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Sample:
    """One parsed series sample. ``type`` comes from the family's
    ``# TYPE`` line ("counter"/"gauge"/"summary"; summaries cover their
    ``_sum``/``_count`` children), or "untyped" when absent."""

    name: str
    labels: Tuple[Tuple[str, str], ...]
    value: float
    type: str = "untyped"

    def label_dict(self) -> Dict[str, str]:
        return dict(self.labels)


def _parse_labels(body: str) -> Dict[str, str]:
    """The inside of ``{...}``, escape-aware: label VALUES may contain
    ``,``/``=``/escaped quotes — the naive split-on-comma parser is
    exactly what the registry's escaping exists to defeat."""
    out: Dict[str, str] = {}
    i = 0
    while i < len(body):
        eq = body.index("=", i)
        key = body[i:eq].strip()
        if eq + 1 >= len(body) or body[eq + 1] != '"':
            raise ValueError(f"unquoted label value for {key!r}")
        i = eq + 2
        buf: List[str] = []
        while True:
            if i >= len(body):
                raise ValueError(f"unterminated label value for {key!r}")
            ch = body[i]
            if ch == "\\" and i + 1 < len(body):
                buf.append(_UNESCAPE.get(body[i + 1], body[i + 1]))
                i += 2
            elif ch == '"':
                i += 1
                break
            else:
                buf.append(ch)
                i += 1
        out[key] = "".join(buf)
        if i < len(body) and body[i] == ",":
            i += 1
    return out


def _find_label_end(line: str, start: int) -> int:
    """Index of the closing ``}`` of a label block opened at ``start``,
    skipping escaped characters and quoted sections."""
    i = start + 1
    in_quotes = False
    while i < len(line):
        ch = line[i]
        if in_quotes:
            if ch == "\\":
                i += 1
            elif ch == '"':
                in_quotes = False
        elif ch == '"':
            in_quotes = True
        elif ch == "}":
            return i
        i += 1
    raise ValueError("unterminated label block")


def parse_series_labels(series: str) -> Dict[str, str]:
    """Labels of a rendered series key (``name{k="v",...}``),
    escape-aware — the doctor's label extraction delegates here so a
    label value carrying ``,``/``=``/escaped quotes never mis-parses
    (empty dict for an unlabeled or malformed key)."""
    brace = series.find("{")
    if brace == -1:
        return {}
    try:
        end = _find_label_end(series, brace)
        return _parse_labels(series[brace + 1:end])
    except (ValueError, IndexError):
        return {}


def parse_exposition(text: str) -> List[Sample]:
    """Parse a Prometheus text-format page into typed samples. Lines
    that do not parse are skipped (one hostile series must not poison
    the whole scrape), but label escaping is honored exactly — the
    golden round-trip tests pin parse(render()) == registry state."""
    types: Dict[str, str] = {}
    out: List[Sample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip()
            continue
        try:
            brace = line.find("{")
            space = line.find(" ")
            if brace != -1 and (space == -1 or brace < space):
                name = line[:brace]
                end = _find_label_end(line, brace)
                labels = _parse_labels(line[brace + 1:end])
                rest = line[end + 1:]
            else:
                name, _, rest = line.partition(" ")
                labels = {}
            # `<value> [timestamp]`: the format allows an optional
            # trailing millisecond timestamp — float() over the whole
            # remainder would reject every line a standard exporter
            # stamps, silently emptying the store.
            value = float(rest.split()[0])
        except (ValueError, IndexError):
            continue
        mtype = types.get(name, "untyped")
        if mtype == "untyped":
            for suffix in ("_sum", "_count"):
                base = name[: -len(suffix)] if name.endswith(suffix) else None
                if base and types.get(base) == "summary":
                    mtype = "summary"
                    break
        out.append(Sample(
            name=name, labels=tuple(sorted(labels.items())),
            value=value, type=mtype,
        ))
    return out


# --- the scraper -------------------------------------------------------------


@dataclasses.dataclass
class Target:
    """One component /metrics endpoint. ``fetch`` overrides the HTTP
    GET for in-process composition (harness legs scrape their own
    registry without a port when they want to)."""

    name: str
    endpoint: str = ""
    fetch: Optional[Callable[[], str]] = None

    def scrape(self, timeout: float = 2.0) -> str:
        if self.fetch is not None:
            return self.fetch()
        import urllib.request

        url = endpoint_url(self.endpoint, "/metrics")
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()


class FleetMon:
    """Scrape loop + store + catalog evaluation, one object.

    Threading: ``scrape_once`` may run on a background thread while
    ``evaluate``/``snapshot`` run on the caller's — per-target state is
    guarded by one lock; the SampleStore locks itself.
    """

    def __init__(
        self,
        targets: List[Target],
        catalog: Optional[List[slo.SLOSpec]] = None,
        interval_s: float = DEFAULT_INTERVAL_S,
        store: Optional[slo.SampleStore] = None,
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.targets = list(targets)
        self.catalog = list(catalog) if catalog is not None else []
        self.interval_s = interval_s
        self.store = store or slo.SampleStore()
        self.metrics = metrics
        self.clock = clock
        self._lock = threading.Lock()
        self._up: Dict[str, bool] = {}
        self._last_ok: Dict[str, float] = {}
        self._errors: Dict[str, int] = {}
        self._scrapes: Dict[str, int] = {}
        self._last_error: Dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.metrics is not None:
            self.metrics.set_gauge(
                "fleetmon_scrape_interval_seconds", self.interval_s
            )
            # Ages refresh at scrape time (the doctor's staleness
            # verdict must see the CURRENT age, not the age at the
            # last successful pass).
            self.metrics.register_collector(self._export_ages)

    # -- scraping --

    def scrape_once(self, now: Optional[float] = None) -> Dict[str, bool]:
        """One pass over every target; per-target failures are recorded
        (``fleetmon_target_up`` 0, error counter), never raised — the
        fleet view must survive one sick component."""
        now = self.clock() if now is None else now
        verdicts: Dict[str, bool] = {}
        import http.client

        scrape_errors = (OSError, ValueError, http.client.HTTPException)
        for t in self.targets:
            try:
                samples = [
                    # Per-target `instance` label before ingest: two
                    # components legitimately export the SAME series
                    # (every KubeClient has api_circuit_state{verb=},
                    # every node plugin has publish_writes_total) and
                    # merging them into one ring would read target A's
                    # 1000 -> target B's 10 as a counter reset every
                    # cycle — phantom resets, garbage burns, a false
                    # page on a healthy fleet. Rate SLOs still SUM
                    # across the per-instance series (one fleet, one
                    # budget); threshold SLOs keep worst-series
                    # semantics per component.
                    dataclasses.replace(
                        s, labels=s.labels + (("instance", t.name),)
                    )
                    for s in parse_exposition(t.scrape())
                ]
            except scrape_errors as e:
                with self._lock:
                    self._up[t.name] = False
                    self._errors[t.name] = self._errors.get(t.name, 0) + 1
                    self._scrapes[t.name] = self._scrapes.get(t.name, 0) + 1
                    self._last_error[t.name] = str(e)
                if self.metrics is not None:
                    self.metrics.set_gauge(
                        "fleetmon_target_up", 0.0,
                        labels={"target": t.name},
                    )
                    self.metrics.inc(
                        "fleetmon_scrape_errors_total",
                        labels={"target": t.name},
                    )
                verdicts[t.name] = False
                continue
            self.store.ingest(samples, now)
            with self._lock:
                self._up[t.name] = True
                self._last_ok[t.name] = now
                self._scrapes[t.name] = self._scrapes.get(t.name, 0) + 1
                self._last_error.pop(t.name, None)
            if self.metrics is not None:
                self.metrics.set_gauge(
                    "fleetmon_target_up", 1.0, labels={"target": t.name}
                )
                self.metrics.inc("fleetmon_scrapes_total")
            verdicts[t.name] = True
        return verdicts

    def _export_ages(self) -> None:
        now = self.clock()
        with self._lock:
            ages = {
                t.name: now - self._last_ok[t.name]
                for t in self.targets if t.name in self._last_ok
            }
        for name, age in ages.items():
            self.metrics.set_gauge(
                "fleetmon_scrape_age_seconds", age,
                labels={"target": name},
            )

    def start(self) -> None:
        """Background scrape loop at ``interval_s`` (idempotent: a
        second start() while running is a no-op — an orphan second
        loop would halve the apparent scrape interval and double-count
        every scrape). The check and the thread assignment stay under
        ONE lock hold, or two concurrent start()s both pass the check
        and both spawn loops; the new thread's first scrape simply
        waits out the remainder of this critical section."""

        def loop():
            self.scrape_once()
            while not self._stop.wait(self.interval_s):
                self.scrape_once()

        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            if self.metrics is not None:
                # Symmetric with stop()'s cleanup: a restarted monitor
                # re-hooks its age collector (unregister first so a
                # start/start never double-registers).
                self.metrics.unregister_collector(self._export_ages)
                self.metrics.register_collector(self._export_ages)
                self.metrics.set_gauge(
                    "fleetmon_scrape_interval_seconds", self.interval_s
                )
            self._thread = threading.Thread(
                target=loop, daemon=True, name="fleetmon-scrape"
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            # Join OUTSIDE the lock: the loop thread takes it per
            # scrape and must be able to finish its last pass.
            t.join(timeout=10)
        if self.metrics is not None:
            # The registry may outlive this monitor (harness legs share
            # the fleet registry): unhook the age collector and drop
            # the health gauges, or a deliberately-stopped monitor
            # keeps exporting ever-growing ages the doctor would flag
            # as STALE targets (and pins this object alive).
            self.metrics.unregister_collector(self._export_ages)
            for name in (
                "fleetmon_target_up", "fleetmon_scrape_age_seconds",
            ):
                self.metrics.remove_gauges(name, {})
            self.metrics.remove_gauge("fleetmon_scrape_interval_seconds")

    # -- evaluation --

    def evaluate(self, now: Optional[float] = None) -> List[slo.SLOStatus]:
        now = self.clock() if now is None else now
        return slo.evaluate_catalog(self.store, self.catalog, now)

    def status_of(self, name: str, now: Optional[float] = None
                  ) -> Optional[slo.SLOStatus]:
        # One spec, one evaluation: hot probe loops poll this per tick
        # and must not pay the whole catalog's store scans each time.
        now = self.clock() if now is None else now
        for spec in self.catalog:
            if spec.name == name:
                return slo.evaluate(self.store, spec, now)
        return None

    def target_report(self, now: Optional[float] = None) -> Dict[str, dict]:
        now = self.clock() if now is None else now
        with self._lock:
            out = {}
            for t in self.targets:
                age = (
                    now - self._last_ok[t.name]
                    if t.name in self._last_ok else None
                )
                out[t.name] = {
                    "endpoint": t.endpoint,
                    "up": self._up.get(t.name, False),
                    "age_s": None if age is None else round(age, 3),
                    "stale": bool(
                        age is not None
                        and age > STALE_AFTER_INTERVALS * self.interval_s
                    ),
                    "scrapes": self._scrapes.get(t.name, 0),
                    "errors": self._errors.get(t.name, 0),
                    "last_error": self._last_error.get(t.name),
                }
            return out

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The JSON document ``--once``/``--json-out`` writes and
        ``doctor slo`` reads: wall timestamp, per-target health, and
        every catalog verdict."""
        now = self.clock() if now is None else now
        return {
            "ts": time.time(),
            "interval_s": self.interval_s,
            "targets": self.target_report(now),
            "slos": [st.to_json() for st in self.evaluate(now)],
        }


# --- the built-in catalog ----------------------------------------------------

# Per-class TTFT objectives mirror the router's SLOClass constants
# (serving/router.py); stated here rather than imported because the
# layer DAG points serving -> tools, not the reverse.
DEFAULT_TTFT_TARGETS_S = {
    "interactive": 0.25,
    "standard": 1.0,
    "batch": 30.0,
}

# ROADMAP item 5's apiserver write budget: slice writes per node per
# hour. The content-diffed publisher's steady state is ZERO writes, so
# a budget of one write per node per minute is generous headroom for
# real weather while a naive per-event republisher blows through it in
# seconds.
DEFAULT_WRITE_BUDGET_PER_NODE_PER_HOUR = 60.0


def builtin_catalog(
    nodes: Optional[int] = None,
    window_scale: float = 1.0,
    claim_ready_target_s: float = 30.0,
    ttft_targets_s: Optional[Dict[str, float]] = None,
    write_budget_per_node_per_hour: float =
        DEFAULT_WRITE_BUDGET_PER_NODE_PER_HOUR,
    frag_ceiling: float = 0.25,
) -> List[slo.SLOSpec]:
    """The SLO catalog every harness and the CLI share. Specs whose
    series a fleet does not export simply evaluate to no-data — the
    catalog is a superset, discovery is what the scrape finds."""
    policy = slo.scaled_policy(window_scale)
    window_s = slo.DEFAULT_SLO_WINDOW_S * window_scale
    ttft = dict(DEFAULT_TTFT_TARGETS_S)
    ttft.update(ttft_targets_s or {})
    catalog = [
        slo.SLOSpec(
            name="claim-ready-p99",
            description="claim-submitted -> pod-env-injected p99",
            kind="threshold",
            series="claim_ready_seconds",
            labels=(("quantile", "0.99"),),
            threshold=claim_ready_target_s, op="le", budget=0.05,
            window_s=window_s, policy=policy,
            remediation=(
                "claim-ready latency is over target: check the "
                "scheduler's workqueue depth + batch solve latency "
                "(doctor's workqueue/scheduler sections) and the "
                "kubelet prepare path (docs/operations.md, 'Fleet "
                "scale & claim-ready SLO')"
            ),
        ),
        slo.SLOSpec(
            name="write-budget",
            description="apiserver slice writes per node per hour",
            kind="rate",
            series="publish_writes_total",
            budget=write_budget_per_node_per_hour,
            per_seconds=3600.0,
            divisor=float(nodes) if nodes else 1.0,
            window_s=window_s, policy=policy,
            remediation=(
                "slice publishes are outrunning the apiserver write "
                "budget: the content-diffed publisher's steady state "
                "is ZERO writes, so a sustained burn means something "
                "republishes unchanged content per event (check "
                "publish_skipped_unchanged_total is climbing next to "
                "it — flat means the diff cache is being invalidated), "
                "an external writer is fighting the publisher "
                "(slice_drift_detected_total), or real weather is "
                "flapping health faster than coalescing absorbs "
                "(docs/operations.md, 'The apiserver write budget')"
            ),
        ),
        slo.SLOSpec(
            name="frag-ceiling",
            description="fleet fragmentation score ceiling",
            kind="threshold",
            series="scheduler_frag_score",
            threshold=frag_ceiling, op="le", budget=0.10,
            window_s=window_s, policy=policy,
            remediation=(
                "free capacity is stranded past the ceiling: check "
                "the repacker is leading and migrating (doctor's "
                "repacker section) and that allocation runs the "
                "packed ordering (docs/scheduling.md)"
            ),
        ),
        slo.SLOSpec(
            name="circuit-open",
            description="apiserver circuit-open minutes",
            kind="threshold",
            series="api_circuit_state",
            threshold=0.0, op="le", budget=0.01,
            window_s=window_s, policy=policy,
            remediation=(
                "a component's apiserver circuit keeps opening: the "
                "control plane is flapping from that component's view "
                "— check apiserver health, the network path, and the "
                "component's degraded-mode counters "
                "(docs/operations.md, 'Control-plane outages')"
            ),
        ),
        slo.SLOSpec(
            name="fabric-degraded",
            description="serving fabric capacity-loss minutes",
            kind="threshold",
            series="fabric_degraded",
            threshold=0.0, op="le", budget=0.01,
            window_s=window_s, policy=policy,
            remediation=(
                "the serving fabric is running below its owed replica "
                "count — replicas died faster than replacements bound, "
                "and BATCH-class admissions are being shed at the "
                "door. Check fabric_replica_deaths_total by reason "
                "(doctor's fabric section), fabric_circuit_open for "
                "quarantined claims awaiting packer-placed "
                "replacements, and the autoscaler's pending claim "
                "(docs/serving.md, 'Failure semantics')"
            ),
        ),
        slo.SLOSpec(
            name="flow-rejection-rate",
            description="apiserver priority-and-fairness 429 sheds",
            kind="rate",
            series="apiserver_flow_rejected_total",
            budget=60.0,  # sheds/hour fleet-wide; brownouts blow through
            per_seconds=3600.0,
            window_s=window_s, policy=policy,
            remediation=(
                "the apiserver is shedding requests by flow: per-flow "
                "rejected counters (doctor's apiflow line, or "
                "apiserver_flow_rejected_total{flow=...}) name WHICH "
                "flow is over its share — slice-publish sheds mean "
                "publisher storm weather (widen coalescing or the "
                "flow's share), claim-status or system-leader sheds "
                "mean the control plane itself is starving "
                "(docs/operations.md, 'Apiserver flow control & "
                "restart semantics')"
            ),
        ),
        slo.SLOSpec(
            name="claim-ready-recovery-p99",
            description="post-restart claim-submitted -> ready p99",
            kind="threshold",
            series="claim_ready_recovery_seconds",
            labels=(("quantile", "0.99"),),
            threshold=claim_ready_target_s * 2.0, op="le", budget=0.05,
            window_s=window_s, policy=policy,
            remediation=(
                "claims submitted after an apiserver restart are not "
                "reconverging inside the recovery objective: informers "
                "should relist on 410 Gone, the leader should re-renew "
                "inside one lease duration, and publishers should "
                "reverify-and-heal — `make stormbench` reproduces the "
                "drill; see docs/operations.md, 'Apiserver flow "
                "control & restart semantics'"
            ),
        ),
    ]
    for cls, target_s in sorted(ttft.items()):
        catalog.append(slo.SLOSpec(
            name=f"ttft-p99-{cls}",
            description=f"{cls}-class submitted -> first-token p99",
            kind="threshold",
            series="fabric_ttft_seconds",
            labels=(("cls", cls), ("quantile", "0.99")),
            threshold=target_s, op="le", budget=0.05,
            window_s=window_s, policy=policy,
            remediation=(
                f"the {cls} tier's TTFT p99 is over its objective: "
                f"check per-tenant WFQ lag (doctor's fabric section), "
                f"the autoscaler's replica count vs queued tokens, "
                f"and whether a scale-up is stuck waiting on "
                f"allocation (docs/serving.md, 'Serving fabric')"
            ),
        ))
    return catalog


# --- rendering ---------------------------------------------------------------


def slo_state(status: dict) -> str:
    """The one-word triage state of a snapshot SLO entry — shared by
    the watch dashboard and `doctor slo` so the two renderers can
    never disagree on what counts as PAGE vs VIOLATING vs no-data."""
    if not status.get("data"):
        return "no-data"
    if status.get("alert"):
        return status["alert"].upper()
    if status.get("ok") is False:
        return "VIOLATING"
    return "ok"


def render_dashboard(snapshot: dict) -> str:
    """The watch-mode text dashboard (also what tests golden)."""
    targets = snapshot.get("targets", {})
    up = sum(1 for t in targets.values() if t.get("up"))
    lines = [
        f"fleetmon   : {up}/{len(targets)} targets up, interval "
        f"{snapshot.get('interval_s', 0):g}s",
    ]
    for name, t in sorted(targets.items()):
        mark = "UP " if t.get("up") else "DOWN"
        age = t.get("age_s")
        stale = " STALE" if t.get("stale") else ""
        lines.append(
            f"  target {name:<12} [{mark}] "
            f"age={'-' if age is None else f'{age:g}s'}{stale} "
            f"scrapes={t.get('scrapes', 0)} errors={t.get('errors', 0)}"
        )
    lines.append(
        f"{'SLO':<22} {'state':<8} {'current':>12} "
        f"{'burn':>8} {'left':>6}  windows"
    )
    for s in snapshot.get("slos", []):
        state = slo_state(s)
        burn = s.get("burn_rate")
        left = s.get("budget_remaining")
        cur = s.get("current")
        windows = " ".join(
            f"{w}={b:g}" for w, b in (s.get("burn") or {}).items()
        )
        reset = " RESET" if s.get("resets") else ""
        lines.append(
            f"  {s['name']:<20} {state:<8} "
            f"{'-' if cur is None else f'{cur:g}':>12} "
            f"{'-' if burn is None else f'{burn:g}':>8} "
            f"{'-' if left is None else f'{left:.0%}':>6}  "
            f"{windows}{reset}"
        )
    return "\n".join(lines)


# --- CLI ---------------------------------------------------------------------


def _parse_target(arg: str) -> Target:
    name, sep, ep = arg.partition("=")
    if not sep:
        # Bare endpoint: name it by its address.
        return Target(name=arg, endpoint=arg)
    return Target(name=name, endpoint=ep)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("fleetmon", description=__doc__)
    p.add_argument(
        "--target", action="append", default=[], dest="targets",
        metavar="NAME=HOST:PORT",
        help="component /metrics endpoint to scrape (repeatable)",
    )
    p.add_argument("--interval", type=float, default=DEFAULT_INTERVAL_S)
    p.add_argument(
        "--once", action="store_true",
        help="scrape twice (rates need two samples), print one JSON "
        "snapshot, exit 0/1 by alert state",
    )
    p.add_argument(
        "--watch", action="store_true",
        help="scrape on the interval and redraw the text dashboard",
    )
    p.add_argument(
        "--window-scale", type=float, default=1.0,
        help="shrink the SRE alert windows uniformly (harness runs)",
    )
    p.add_argument(
        "--nodes", type=int, default=0,
        help="fleet node count for the per-node write budget",
    )
    p.add_argument(
        "--claim-ready-target", type=float, default=30.0,
        help="claim-ready p99 objective, seconds",
    )
    p.add_argument(
        "--write-budget", type=float,
        default=DEFAULT_WRITE_BUDGET_PER_NODE_PER_HOUR,
        help="allowed slice writes per node per hour",
    )
    p.add_argument("--json-out", default="", help="write the snapshot here")
    p.add_argument(
        "--metrics-port", type=int, default=0,
        help="serve fleetmon's OWN /metrics here (fleetmon_target_up, "
        "scrape ages — what `doctor --metrics-endpoint` probes); "
        "0 = off",
    )
    args = p.parse_args(argv)
    if not args.targets:
        print("fleetmon: need at least one --target", file=sys.stderr)
        return 2
    own = Metrics()
    fm = FleetMon(
        [_parse_target(t) for t in args.targets],
        catalog=builtin_catalog(
            nodes=args.nodes or None,
            window_scale=args.window_scale,
            claim_ready_target_s=args.claim_ready_target,
            write_budget_per_node_per_hour=args.write_budget,
        ),
        interval_s=args.interval,
        metrics=own,
    )
    mon_srv = None
    if args.metrics_port:
        from tpu_dra.infra.metrics import start_health_server

        mon_srv = start_health_server(own, args.metrics_port)
        if mon_srv is not None:
            print(
                f"fleetmon: serving /metrics on :{mon_srv.port}",
                file=sys.stderr,
            )
    if args.watch:
        try:
            while True:
                fm.scrape_once()
                snap = fm.snapshot()
                if args.json_out:
                    # Continuously refreshed snapshot: the documented
                    # `doctor slo --snapshot` pairing works against a
                    # live watcher, not only a --once run (atomic
                    # replace so a reader never sees a torn file).
                    tmp = args.json_out + ".tmp"
                    with open(tmp, "w") as f:
                        f.write(json.dumps(snap, indent=2) + "\n")
                    os.replace(tmp, args.json_out)
                print("\n" + render_dashboard(snap), flush=True)
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
        finally:
            if mon_srv is not None:
                mon_srv.stop()
    # --once (default): two spaced scrapes so rate()/increase() have a
    # window to work with.
    try:
        fm.scrape_once()
        time.sleep(min(args.interval, 2.0))
        fm.scrape_once()
        snap = fm.snapshot()
        doc = json.dumps(snap, indent=2)
        if args.json_out:
            with open(args.json_out, "w") as f:
                f.write(doc + "\n")
        print(doc)
        paging = [
            s["name"] for s in snap["slos"] if s.get("alert") == "page"
        ]
        down = [
            n for n, t in snap["targets"].items() if not t.get("up")
        ]
        return 1 if paging or down else 0
    finally:
        if mon_srv is not None:
            mon_srv.stop()


if __name__ == "__main__":
    raise SystemExit(main())
