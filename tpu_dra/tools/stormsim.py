"""Wire-honest fleet storm bench (ISSUE 20): the fleet as REAL OS
processes over REAL HTTP, apiserver priority-and-fairness under its
own weight, and the mid-storm apiserver restart convergence drill.

The in-process fleetsim (ISSUE 10) proved the control plane's
*algorithms* scale to 5k nodes; every component there shared one
interpreter and one FakeCluster, so the apiserver's transport — accept
backlog, per-connection handler threads, flow-control queuing, 429
shedding, connection-refused windows — was never load-bearing. This
harness removes that flattery:

- **NodeAgents are sharded across worker subprocesses** (``--publish-
  worker``): each worker owns a contiguous index range of the synthetic
  fleet and drives the driver's REAL publisher
  (:class:`tpu_dra.plugin.slicepub.SlicePublisher`, reverify enabled —
  the heal path the restart drill asserts) over fakeserver HTTP.
- **The scheduler is the shipped binary** (``python -m
  tpu_dra.scheduler.main``): leader-elected against a Lease, elastic
  repacker riding its leadership, talking to the same endpoint.
- **The kubelet analog is its own process** (``--kubelet-worker``):
  :class:`tpu_dra.tools.fleetsim.KubeletSim` preparing allocated
  claims, then PATCHing a ready annotation back onto each claim so the
  parent observes claim-submitted -> pod-env-injected through the
  apiserver, not through shared memory.

Headline: ``fleet_wire_claim_ready_p50/p99_ms`` at fleet scale plus
``fleet_wire_vs_inproc_p99_pct`` — the honest price of the wire,
measured against the identical in-process trace
(:class:`tpu_dra.tools.fleetsim._ModeRun`).

**Restart drill** (the robustness tentpole): halfway through the claim
storm the apiserver process-restarts (state snapshot/restore, watches
dropped, resourceVersions jumped past the retained window, listen
socket dark for the outage) with the scheduler, publishers, repacker
and gang WALs all live. Afterwards the drill asserts CONVERGENCE, not
vibes: every claim holds exactly one allocation, allocated devices are
fleet-wide disjoint, zero gang/repack WAL annotations survive, the
scheduler's Lease was re-acquired/renewed past the outage, and
``storm_recovery_p99_ms`` records claim-ready p99 for claims submitted
into the recovery window.

**Cliff ladder**: node count is pushed rung by rung until the endpoint
breaks — sustained flow-control shedding, refused connections, or
publish throughput collapse — and the breaking rung's bottleneck is
NAMED from the server's per-flow APF counters and the workers' client
tallies (``fleet_wire_cliff_nodes`` / ``fleet_wire_cliff_bottleneck``).

Entry points::

    python -m tpu_dra.tools.stormsim            # full (5k nodes, wire)
    python -m tpu_dra.tools.stormsim --smoke    # `make stormbench` leg

Knobs (env): STORMSIM_NODES, STORMSIM_CLAIMS, STORMSIM_RATE,
STORMSIM_WORKERS, STORMSIM_SEED, STORMSIM_OUTAGE, STORMSIM_PREPARE_MS,
STORMSIM_CLIFF_RUNGS, STORMSIM_CLIFF_WINDOW, STORMSIM_CLIFF_SEATS.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from tpu_dra.infra.metrics import Metrics
from tpu_dra.k8sclient import (
    DEVICE_CLASSES,
    LEASES,
    RESOURCE_CLAIMS,
    RESOURCE_SLICES,
    ApiConflict,
    ApiNotFound,
    Informer,
    ResourceClient,
)
from tpu_dra.k8sclient.rest import KubeClient
from tpu_dra.scheduler import fleet
from tpu_dra.scheduler.gang import GANG_ANNOTATION
from tpu_dra.scheduler.repacker import REPACK_ANNOTATION
from tpu_dra.tools.fleetsim import KubeletSim, NodeAgent, _pct

NS = "stormsim"
READY_ANNOTATION = "storm.tpu.google.com/ready"
LEASE_NAME = "tpu-dra-scheduler"
LEASE_NS = "default"
# Worker stdout protocol: exactly these two prefixed JSON lines; logs
# go to stderr so the protocol stream stays parseable.
READY_PREFIX = "#stormsim-ready "
STATS_PREFIX = "#stormsim-stats "

_VERBS = ("get", "list", "create", "update", "patch", "delete", "watch")


def _note(msg: str) -> None:
    print(f"stormsim: {msg}", file=sys.stderr)


def _client(server: str, metrics: Optional[Metrics] = None) -> KubeClient:
    return KubeClient(server=server, qps=5000, burst=5000, metrics=metrics)


def _sum_code(metrics: Metrics, code: str) -> int:
    return int(sum(
        metrics.get_counter(
            "api_requests_total", labels={"verb": v, "code": code}
        )
        for v in _VERBS
    ))


def _client_tally(metrics: Metrics) -> Dict[str, int]:
    """The transport-level weather one process absorbed: answered
    sheds, connection-level failures, and retries refused by the
    process-wide retry budget."""
    return {
        "sheds_429": _sum_code(metrics, "429"),
        "conn_errors": _sum_code(metrics, "conn_error"),
        "retry_budget_exhausted": int(sum(
            metrics.get_counter(
                "api_retry_budget_exhausted_total", labels={"verb": v}
            )
            for v in _VERBS
        )),
    }


# --- worker subprocess mains -------------------------------------------------


def _publish_worker_main(args) -> int:
    """One shard of the fleet's publishers: agents [start, start+count)
    publishing over HTTP, then seeded settling health flaps until the
    parent closes stdin. Every flap toggles real content, so every
    publish is a real apiserver write on the slice-publish flow —
    exactly the low-priority pressure the APF analog exists to shed
    before it starves lease renewals."""
    metrics = Metrics()
    kc = _client(args.server, metrics)
    slices = ResourceClient(kc, RESOURCE_SLICES)
    agents = [
        NodeAgent(i, slices, metrics, reverify_seconds=args.reverify)
        for i in range(args.start, args.start + args.count)
    ]
    retried = 0
    t0 = time.monotonic()
    for a in agents:
        for attempt in range(6):
            try:
                a.publish()
                break
            except Exception:  # noqa: BLE001 — weather; retry the agent
                retried += 1
                time.sleep(0.2 * (attempt + 1))
    print(READY_PREFIX + json.dumps({
        "start": args.start, "count": args.count,
        "publish_wall_s": round(time.monotonic() - t0, 3),
        "publish_retries": retried,
    }), flush=True)

    stop = threading.Event()
    failed = [0] * max(1, args.flap_threads)

    def flaps(tid: int, part: List[NodeAgent]) -> None:
        # One flap thread per partition: the threads publish
        # CONCURRENTLY, so a worker's offered load is flap_threads
        # outstanding requests, not one — the concurrency the cliff
        # ladder needs to actually overrun the server's seats. A
        # publish that fails THROUGH (the client exhausted its own
        # retries/budget) is counted as a failure; transient weather
        # the transport absorbed never reaches here.
        rng = random.Random(args.seed ^ args.start ^ (tid * 0x9E37))
        degraded: Dict[int, bool] = {}
        n_flap = max(1, int(len(part) * args.flap_frac))
        while not stop.wait(args.flap_tick):
            for k in rng.sample(range(len(part)), min(n_flap, len(part))):
                if stop.is_set():
                    break
                degraded[k] = not degraded.get(k, False)
                try:
                    part[k].publish(degraded=degraded[k])
                except Exception:  # noqa: BLE001
                    failed[tid] += 1

    n_threads = max(1, args.flap_threads)
    threads = [
        threading.Thread(
            target=flaps, args=(tid, agents[tid::n_threads]),
            daemon=True, name=f"storm-flaps-{tid}",
        )
        for tid in range(n_threads)
        if agents[tid::n_threads]
    ]
    for t in threads:
        t.start()
    sys.stdin.read()  # parent closes our stdin to stop us
    stop.set()
    for t in threads:
        t.join(timeout=15)
    publish_failures = sum(failed)
    tally = _client_tally(metrics)
    tally.update({
        "writes": int(metrics.get_counter("publish_writes_total")),
        "skipped_unchanged": int(
            metrics.get_counter("publish_skipped_unchanged_total")
        ),
        "publish_failures": publish_failures,
        "publish_retries": retried,
    })
    print(STATS_PREFIX + json.dumps(tally), flush=True)
    return 0


def _kubelet_worker_main(args) -> int:
    """The fleet's kubelet analog as its own process: prepares
    allocated claims (sharded by node) and PATCHes the ready annotation
    back through the apiserver — the parent's only view of
    pod-env-injected, as in a real cluster."""
    metrics = Metrics()
    claims = ResourceClient(_client(args.server, metrics), RESOURCE_CLAIMS)
    patch_errors = [0]

    def on_ready(name: str, claim: dict, env: dict) -> None:
        ns = claim["metadata"].get("namespace")
        for attempt in range(10):
            try:
                claims.patch(name, {
                    "metadata": {"annotations": {READY_ANNOTATION: "1"}},
                }, ns)
                return
            except ApiNotFound:
                return  # churned away; nothing to stamp
            except Exception:  # noqa: BLE001 — outage window; retry
                patch_errors[0] += 1
                time.sleep(0.2 * (attempt + 1))

    kubelet = KubeletSim(
        _client(args.server, metrics), metrics, sharded=True,
        prepare_ms=args.prepare_ms, on_ready=on_ready,
    )
    kubelet.start()
    if not kubelet.informer.wait_for_sync(timeout=120):
        print(READY_PREFIX + json.dumps({"error": "sync timeout"}),
              flush=True)
        return 1
    print(READY_PREFIX + json.dumps({"synced": True}), flush=True)
    sys.stdin.read()
    kubelet.stop()
    tally = _client_tally(metrics)
    tally.update({
        "prepared": kubelet.ready_count(),
        "patch_errors": patch_errors[0],
    })
    print(STATS_PREFIX + json.dumps(tally), flush=True)
    return 0


# --- parent-side worker handle -----------------------------------------------


class _Worker:
    """A protocol-speaking subprocess: argv in, #stormsim-ready /
    #stormsim-stats JSON lines out, stopped by closing its stdin."""

    def __init__(self, argv: List[str], name: str):
        self.name = name
        self.proc = subprocess.Popen(
            argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, bufsize=1,
        )
        self.ready: Optional[dict] = None
        self.stats: Optional[dict] = None
        self._ready_evt = threading.Event()
        self._stats_evt = threading.Event()
        self._reader = threading.Thread(
            target=self._read, daemon=True, name=f"{name}-reader"
        )
        self._reader.start()

    def _read(self) -> None:
        for line in self.proc.stdout:
            if line.startswith(READY_PREFIX):
                self.ready = json.loads(line[len(READY_PREFIX):])
                self._ready_evt.set()
            elif line.startswith(STATS_PREFIX):
                self.stats = json.loads(line[len(STATS_PREFIX):])
                self._stats_evt.set()
        # EOF: a worker that died unready must not wedge the parent.
        self._ready_evt.set()
        self._stats_evt.set()

    def wait_ready(self, timeout: float) -> dict:
        if not self._ready_evt.wait(timeout) or self.ready is None:
            raise RuntimeError(
                f"storm worker {self.name} never reported ready "
                f"(rc={self.proc.poll()})"
            )
        return self.ready

    def stop(self, timeout: float = 30.0) -> Optional[dict]:
        try:
            self.proc.stdin.close()
        except OSError:
            pass
        self._stats_evt.wait(timeout)
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=10)
        return self.stats

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)


def _spawn_publishers(
    server: str, nodes: int, workers: int, seed: int,
    flap_tick: float, flap_frac: float, reverify: float,
    flap_threads: int = 2,
) -> List[_Worker]:
    out = []
    per = (nodes + workers - 1) // workers
    start = 0
    while start < nodes:
        count = min(per, nodes - start)
        out.append(_Worker([
            sys.executable, "-m", "tpu_dra.tools.stormsim",
            "--publish-worker", "--server", server,
            "--start", str(start), "--count", str(count),
            "--seed", str(seed), "--flap-tick", str(flap_tick),
            "--flap-frac", str(flap_frac), "--reverify", str(reverify),
            "--flap-threads", str(flap_threads),
        ], name=f"publish-{start}"))
        start += count
    return out


def _merge_tallies(tallies: List[Optional[dict]]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for t in tallies:
        for k, v in (t or {}).items():
            if isinstance(v, (int, float)):
                out[k] = out.get(k, 0) + int(v)
    return out


def _apf_stats(server: str) -> dict:
    """The server's own view over the wire: GET /_stats (flow-control
    admission/rejection per flow, restart count)."""
    import urllib.request

    with urllib.request.urlopen(f"{server}/_stats", timeout=10) as r:
        return json.loads(r.read())


# --- the storm leg -----------------------------------------------------------


def run_storm_leg(
    nodes: int,
    claims: int,
    rate: float,
    seed: int = 20260807,
    workers: int = 4,
    prepare_ms: float = 2.0,
    outage_s: float = 0.75,
    gangs: int = 2,
    gang_size: int = 3,
    flap_tick: float = 0.25,
    flap_frac: float = 0.02,
    drain_timeout_s: float = 300.0,
    smoke: bool = False,
) -> dict:
    """The wire fleet + the mid-storm apiserver restart drill. Returns
    the ``fleet_wire_*`` / ``storm_*`` report; raises on any
    convergence violation."""
    from tpu_dra.k8sclient.fakeserver import FakeApiServer

    srv = FakeApiServer(port=0).start()
    server = srv.server_url
    parent_metrics = Metrics()
    kc = _client(server, parent_metrics)
    for cls in fleet.CLASSES:
        ResourceClient(kc, DEVICE_CLASSES).create(
            json.loads(json.dumps(cls))
        )

    pubs: List[_Worker] = []
    kubelet: Optional[_Worker] = None
    sched: Optional[subprocess.Popen] = None
    claim_inf: Optional[Informer] = None
    kc_dir = None
    try:
        t0 = time.monotonic()
        pubs = _spawn_publishers(
            server, nodes, workers, seed, flap_tick, flap_frac,
            reverify=2.0,
        )
        for w in pubs:
            w.wait_ready(timeout=600)
        publish_wall = time.monotonic() - t0
        n_slices = len(ResourceClient(kc, RESOURCE_SLICES).list())
        if n_slices < nodes:
            raise RuntimeError(
                f"initial publish incomplete: {n_slices}/{nodes} slices"
            )
        _note(
            f"{nodes} nodes published over the wire by {len(pubs)} "
            f"worker processes in {publish_wall:.1f}s"
        )

        kubelet = _Worker([
            sys.executable, "-m", "tpu_dra.tools.stormsim",
            "--kubelet-worker", "--server", server,
            "--prepare-ms", str(prepare_ms),
        ], name="kubelet")

        import tempfile

        kc_dir = tempfile.mkdtemp(prefix="stormsim-")
        kubeconfig = srv.write_kubeconfig(
            os.path.join(kc_dir, "kubeconfig")
        )
        sched = subprocess.Popen([
            sys.executable, "-m", "tpu_dra.scheduler.main",
            "--kubeconfig", kubeconfig,
            "--kube-api-qps", "5000", "--kube-api-burst", "5000",
            "--leader-election",
            "--leader-election-namespace", LEASE_NS,
            "--leader-election-lease-name", LEASE_NAME,
            "--leader-election-lease-duration", "4",
            "--retry-unschedulable-after", "0.5",
            "--repack", "--repack-poll-period", "1.0",
        ])
        leases = ResourceClient(kc, LEASES)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            lease = leases.try_get(LEASE_NAME, LEASE_NS)
            if lease and (lease.get("spec") or {}).get("holderIdentity"):
                break
            if sched.poll() is not None:
                raise RuntimeError(
                    f"scheduler exited rc={sched.returncode} before "
                    f"acquiring leadership"
                )
            time.sleep(0.1)
        else:
            raise RuntimeError("scheduler never acquired the Lease")
        kubelet.wait_ready(timeout=120)

        # Parent-side observation: submit times stamped at create, ready
        # times stamped when the kubelet's annotation arrives on the
        # claim WATCH — both ends observed through the apiserver.
        submit_times: Dict[str, float] = {}
        ready_times: Dict[str, float] = {}
        obs_lock = threading.Lock()

        def on_claim(event: str, claim: dict) -> None:
            if event == "DELETED":
                return
            if READY_ANNOTATION not in (
                claim["metadata"].get("annotations") or {}
            ):
                return
            name = claim["metadata"]["name"]
            with obs_lock:
                if name in submit_times and name not in ready_times:
                    ready_times[name] = time.monotonic()

        claim_inf = Informer(_client(server), RESOURCE_CLAIMS, namespace=NS)
        claim_inf.add_handler(on_claim)
        claim_inf.start()
        if not claim_inf.wait_for_sync(timeout=60):
            raise RuntimeError("parent claim informer never synced")

        trace_claims = fleet.make_trace(claims, seed)
        # Gang claims ride the same storm so live gang WALs cross the
        # restart: members submitted back-to-back at seeded offsets.
        gang_members: List[List[dict]] = [
            fleet.make_gang_claims(
                f"storm-gang-{g}", claims + g * gang_size, gang_size,
                "1x1x1", namespace=NS,
            )
            for g in range(gangs)
        ]
        gang_at = {
            max(1, (g + 1) * claims // (gangs + 1)): g
            for g in range(gangs)
        }

        restart_done = threading.Event()
        lease_before = leases.get(LEASE_NAME, LEASE_NS)
        restart_info: Dict[str, float] = {}

        def fire_restart() -> None:
            restart_info["t_start"] = time.monotonic()
            srv.restart(outage_seconds=outage_s)
            restart_info["t_up"] = time.monotonic()
            restart_done.set()

        claims_rc = ResourceClient(_client(server), RESOURCE_CLAIMS)

        def submit_one(c: dict) -> None:
            c = json.loads(json.dumps(c))
            c["metadata"]["namespace"] = NS
            c["metadata"].pop("uid", None)
            with obs_lock:
                submit_times[c["metadata"]["name"]] = time.monotonic()
            # A create racing the restart can see its connection die
            # AFTER the write was acknowledged server-side: the
            # transport (correctly) refuses to auto-retry a
            # non-idempotent verb on that ambiguity, so the submitter
            # owns it — replay until stored, and 409 means the first
            # attempt landed.
            for attempt in range(12):
                try:
                    claims_rc.create(c)
                    return
                except ApiConflict:
                    return
                except Exception:  # noqa: BLE001 — outage window
                    if attempt == 11:
                        raise
                    time.sleep(0.25 * (attempt + 1))

        arr = random.Random(seed ^ 0x570)
        t_next = time.monotonic()
        restart_thread = None
        for i, c in enumerate(trace_claims):
            t_next += arr.expovariate(rate)
            now = time.monotonic()
            if t_next > now:
                time.sleep(t_next - now)
            if i == claims // 2 and outage_s >= 0:
                # Mid-storm: the apiserver goes dark UNDER the open
                # submission loop; creates during the window ride the
                # transport's refused-connect retries.
                restart_thread = threading.Thread(
                    target=fire_restart, daemon=True, name="storm-restart"
                )
                restart_thread.start()
            if i in gang_at:
                for m in gang_members[gang_at[i]]:
                    submit_one(m)
            submit_one(c)
        if restart_thread is not None:
            restart_thread.join(timeout=outage_s + 120)
            assert restart_done.is_set(), "apiserver restart never completed"

        total = claims + gangs * gang_size
        # The wire pace is claims-proportional (every allocation is its
        # own GET+PUT round trips): scale the convergence deadline with
        # the trace instead of wedging full-scale runs on a smoke bound.
        drain_deadline = time.monotonic() + max(
            drain_timeout_s, 120.0 + 0.6 * total
        )
        while True:
            with obs_lock:
                n_ready = len(ready_times)
            if n_ready >= total:
                break
            if time.monotonic() > drain_deadline:
                with obs_lock:
                    missing = sorted(set(submit_times) - set(ready_times))
                raise RuntimeError(
                    f"storm never converged: {total - n_ready}/{total} "
                    f"claim(s) still unready at the drain deadline "
                    f"(first missing: {missing[:5]})"
                )
            if sched.poll() is not None:
                raise RuntimeError(
                    f"scheduler died mid-storm rc={sched.returncode}"
                )
            time.sleep(0.05)

        # --- convergence: asserted, not eyeballed ---
        # Readiness is NOT quiescence: gang/repack WAL finalize (drop
        # the commit annotation) trails the allocation landing, and a
        # post-restart gang recovery may roll a partially-allocated
        # gang back (teardown) and re-place it AFTER the kubelet first
        # reported the members ready.  Poll until the cluster is truly
        # settled — full count, every claim allocated, zero WAL
        # residue — then run the hard asserts on that settled state.
        def _settle_scan():
            stored = claims_rc.list(NS)
            unalloc, residue = [], []
            for c in stored:
                name = c["metadata"]["name"]
                alloc = (c.get("status") or {}).get("allocation")
                results = (
                    ((alloc or {}).get("devices") or {}).get("results")
                    or []
                )
                if not results:
                    unalloc.append(name)
                anns = c["metadata"].get("annotations") or {}
                if GANG_ANNOTATION in anns or REPACK_ANNOTATION in anns:
                    residue.append(name)
            return stored, unalloc, residue

        settle_deadline = time.monotonic() + max(90.0, 0.1 * total)
        while True:
            stored, unalloc, wal_residue = _settle_scan()
            if len(stored) == total and not unalloc and not wal_residue:
                break
            if time.monotonic() > settle_deadline:
                break  # fall through to the asserts for a precise error
            if sched.poll() is not None:
                raise RuntimeError(
                    f"scheduler died while settling rc={sched.returncode}"
                )
            time.sleep(0.25)
        assert len(stored) == total, (
            f"claim count diverged: {len(stored)} stored vs {total} "
            f"submitted"
        )
        assert not unalloc, (
            f"claim(s) converged without an allocation: {unalloc[:5]}"
        )
        assert not wal_residue, (
            f"WAL residue survived convergence on: {wal_residue}"
        )
        seen_devices: Dict[tuple, str] = {}
        for c in stored:
            name = c["metadata"]["name"]
            alloc = (c.get("status") or {}).get("allocation")
            for r in (alloc.get("devices") or {}).get("results") or []:
                pair = (r["pool"], r["device"])
                assert pair not in seen_devices, (
                    f"device {pair} allocated to BOTH "
                    f"{seen_devices[pair]} and {name} — the restart "
                    f"double-allocated"
                )
                seen_devices[pair] = name
        lease_after = leases.get(LEASE_NAME, LEASE_NS)
        spec_after = lease_after.get("spec") or {}
        assert spec_after.get("holderIdentity"), (
            "no leader after the restart"
        )
        assert (
            spec_after.get("renewTime", "")
            > (lease_before.get("spec") or {}).get("renewTime", "")
        ), "the Lease was never renewed after the apiserver restart"

        with obs_lock:
            lat_ms = sorted(
                (ready_times[n] - submit_times[n]) * 1000.0
                for n in ready_times
            )
            recovery_ms = sorted(
                (ready_times[n] - submit_times[n]) * 1000.0
                for n in ready_times
                if submit_times[n] >= restart_info.get("t_start", 0.0)
            )
        apf = _apf_stats(server)
        flow_rejected = {
            f: s["rejected"] for f, s in (apf.get("apf") or {}).items()
        }
        report = {
            "fleet_wire_nodes": nodes,
            "fleet_wire_claims": total,
            "fleet_wire_workers": len(pubs) + 2,  # + kubelet + scheduler
            "fleet_wire_publish_wall_s": round(publish_wall, 2),
            "fleet_wire_claim_ready_p50_ms": round(_pct(lat_ms, 0.5), 2),
            "fleet_wire_claim_ready_p99_ms": round(_pct(lat_ms, 0.99), 2),
            "storm_recovery_p99_ms": round(_pct(recovery_ms, 0.99), 2),
            "storm_recovery_claims": len(recovery_ms),
            "storm_outage_s": outage_s,
            "storm_restarts": int(apf.get("restarts", 0)),
            "storm_flow_rejected": flow_rejected,
            "storm_gangs": gangs,
        }
        return report
    finally:
        if claim_inf is not None:
            claim_inf.stop()
        if sched is not None and sched.poll() is None:
            sched.terminate()
            try:
                sched.wait(timeout=15)
            except subprocess.TimeoutExpired:
                sched.kill()
                sched.wait(timeout=10)
        tallies = []
        if kubelet is not None:
            tallies.append(("kubelet", kubelet.stop()))
        for w in pubs:
            tallies.append((w.name, w.stop()))
        srv.stop()
        if kc_dir:
            import shutil

            shutil.rmtree(kc_dir, ignore_errors=True)
        # Stash for the caller even on the failure path (diagnosis).
        merged = _merge_tallies([t for _n, t in tallies])
        _note(f"client weather (all processes): {merged}")
        run_storm_leg.last_tallies = merged  # type: ignore[attr-defined]


# --- the cliff ladder --------------------------------------------------------


def _name_bottleneck(flow_stats: Dict[str, dict], tally: Dict[str, int],
                     seats: int) -> str:
    rejected = {f: s.get("rejected", 0) for f, s in flow_stats.items()}
    total_rej = sum(rejected.values())
    if total_rej:
        top = max(rejected, key=rejected.get)
        return (
            f"apf fair-queue shed at {seats} seats: flow '{top}' "
            f"rejected {rejected[top]}/{total_rej} rejections "
            f"(flow-ordered: low-share publish traffic sheds first)"
        )
    if tally.get("conn_errors", 0):
        return (
            f"transport: {tally['conn_errors']} connection-level "
            f"failures (accept backlog / handler thread exhaustion)"
        )
    return (
        "handler saturation: publish throughput collapsed with zero "
        "shed — the single-process apiserver's CPU (GIL) is the wall"
    )


def probe_cliff(
    rungs: List[int],
    workers: int,
    seed: int,
    window_s: float = 5.0,
    seats: Optional[int] = None,
    shed_bound: float = 0.02,
) -> dict:
    """Push node count rung by rung until the endpoint breaks. A rung
    FAILS when the shed rate (429s per request) crosses ``shed_bound``,
    connections start failing, or a worker dies; the failing rung's
    bottleneck is named from the server's per-flow counters. ``seats``
    pins the APF concurrency (smoke squeezes it so the cliff is
    reachable at CI scale; the full leg runs the shipped default)."""
    from tpu_dra.k8sclient.fakeserver import FakeApiServer

    ladder = []
    cliff_nodes = 0
    bottleneck = ""
    for nodes in rungs:
        srv = FakeApiServer(port=0).start()
        if seats is not None:
            srv.flow.configure(concurrency=seats, max_queue_seconds=0.5)
        kc = _client(srv.server_url)
        for cls in fleet.CLASSES:
            ResourceClient(kc, DEVICE_CLASSES).create(
                json.loads(json.dumps(cls))
            )
        pubs = []
        wedged = ""
        try:
            t0 = time.monotonic()
            pubs = _spawn_publishers(
                srv.server_url, nodes, workers, seed,
                flap_tick=0.05, flap_frac=0.5, reverify=0.0,
                flap_threads=8,
            )
            for w in pubs:
                w.wait_ready(timeout=600)
            publish_wall = time.monotonic() - t0
            if seats is not None:
                # Constrained mode (smoke): seats alone cannot overrun
                # when handlers answer in a millisecond — add the
                # handler latency a loaded apiserver actually has, so
                # queue waits cross max_queue_seconds and the shed
                # machinery engages at CI scale. 16 concurrent writers
                # over 2 seats at 100ms/handler queue ~0.7s — past the
                # 0.5s bound, so the gate sheds flow-ordered.
                srv.inject_faults(
                    latency=0.1, latency_seconds=window_s + 30.0,
                )
            time.sleep(window_s)  # the saturation window
        except RuntimeError as e:
            # A rung the fleet cannot even STAND UP on is the cliff,
            # not a harness bug: record it, don't crash the ladder.
            wedged = str(e)
            publish_wall = time.monotonic() - t0
        finally:
            tallies = [w.stop() for w in pubs]
            flow_stats = srv.flow.stats()
            srv.stop()
        tally = _merge_tallies(tallies)
        requests_total = (
            tally.get("writes", 0) + tally.get("sheds_429", 0)
            + tally.get("conn_errors", 0)
        )
        shed_rate = (
            (tally.get("sheds_429", 0) + tally.get("conn_errors", 0))
            / requests_total if requests_total else 0.0
        )
        broke = (
            bool(wedged)
            or shed_rate > shed_bound
            or tally.get("publish_failures", 0) > 0
            or tally.get("retry_budget_exhausted", 0) > 0
        )
        rung = {
            "nodes": nodes,
            "publish_wall_s": round(publish_wall, 2),
            "writes": tally.get("writes", 0),
            "sheds_429": tally.get("sheds_429", 0),
            "conn_errors": tally.get("conn_errors", 0),
            "shed_rate": round(shed_rate, 4),
            "broke": broke,
        }
        ladder.append(rung)
        _note(f"cliff rung: {rung}")
        if broke:
            cliff_nodes = nodes
            if wedged:
                bottleneck = f"initial publish wedged: {wedged}"
            else:
                bottleneck = _name_bottleneck(
                    flow_stats, tally,
                    seats if seats is not None else 64,
                )
            break
    if not cliff_nodes and ladder:
        # The ladder never broke: record the last rung as the measured
        # frontier, named honestly as such — a silent cap would read as
        # "covered everything".
        cliff_nodes = ladder[-1]["nodes"]
        bottleneck = (
            f"no break up to {cliff_nodes} nodes at this window — "
            f"frontier, not cliff (raise STORMSIM_CLIFF_RUNGS)"
        )
    return {
        "fleet_wire_cliff_nodes": cliff_nodes,
        "fleet_wire_cliff_bottleneck": bottleneck,
        "fleet_wire_cliff_ladder": ladder,
    }


# --- in-process reference (the wire delta's denominator) ---------------------


def run_inproc_reference(
    nodes: int, claims: int, rate: float, seed: int, prepare_ms: float,
    flap_tick: float, flap_frac: float,
) -> dict:
    """The IDENTICAL trace through the in-process fleetsim stack (one
    interpreter, no HTTP): the denominator of
    ``fleet_wire_vs_inproc_p99_pct``."""
    from tpu_dra.tools.fleetsim import _ModeRun

    mode = _ModeRun(
        nodes, claims, rate, seed, optimized=True,
        storm_tick=flap_tick, storm_frac=flap_frac,
        prepare_ms=prepare_ms, churn=0.0, sample_scoped=0,
    )
    mode.start()
    try:
        res = mode.run_trace()
    finally:
        mode.stop()
    if res["unready"]:
        raise RuntimeError(
            f"in-process reference wedged: {res['unready']} unready"
        )
    return res


# --- entrypoint --------------------------------------------------------------


def run(
    nodes: int, claims: int, rate: float, seed: int, workers: int,
    prepare_ms: float, outage_s: float, cliff_rungs: List[int],
    cliff_window_s: float, cliff_seats: Optional[int],
    smoke: bool = False,
) -> dict:
    flap_tick, flap_frac = 0.25, 0.02
    wire = run_storm_leg(
        nodes, claims, rate, seed=seed, workers=workers,
        prepare_ms=prepare_ms, outage_s=outage_s,
        flap_tick=flap_tick, flap_frac=flap_frac, smoke=smoke,
    )
    tallies = getattr(run_storm_leg, "last_tallies", {})
    _note(
        f"wire: claim-ready p50 {wire['fleet_wire_claim_ready_p50_ms']} "
        f"ms p99 {wire['fleet_wire_claim_ready_p99_ms']} ms; restart "
        f"recovery p99 {wire['storm_recovery_p99_ms']} ms over "
        f"{wire['storm_recovery_claims']} claims"
    )
    inproc = run_inproc_reference(
        nodes, claims, rate, seed, prepare_ms, flap_tick, flap_frac,
    )
    delta_pct = (
        (wire["fleet_wire_claim_ready_p99_ms"]
         / inproc["claim_ready_p99_ms"] - 1.0) * 100.0
        if inproc["claim_ready_p99_ms"] > 0 else 0.0
    )
    _note(
        f"in-process reference p99 {inproc['claim_ready_p99_ms']} ms -> "
        f"wire delta {delta_pct:+.1f}%"
    )
    cliff = probe_cliff(
        cliff_rungs, workers, seed, window_s=cliff_window_s,
        seats=cliff_seats,
    )
    _note(
        f"cliff: {cliff['fleet_wire_cliff_nodes']} nodes — "
        f"{cliff['fleet_wire_cliff_bottleneck']}"
    )
    report = dict(wire)
    report.update(cliff)
    report.update({
        "fleet_wire_inproc_p99_ms": inproc["claim_ready_p99_ms"],
        "fleet_wire_vs_inproc_p99_pct": round(delta_pct, 1),
        "storm_client_weather": tallies,
    })
    if smoke:
        # The stormbench contract, hard-asserted at CI scale.
        assert report["storm_restarts"] >= 1, "the restart never fired"
        assert report["fleet_wire_claim_ready_p99_ms"] > 0
        assert report["storm_recovery_p99_ms"] > 0, (
            "no claim latencies recorded in the recovery window"
        )
        assert report["fleet_wire_cliff_nodes"] > 0
        assert report["fleet_wire_cliff_bottleneck"]
        _note(
            "stormbench contract: wire fleet converged through the "
            "mid-storm apiserver restart (one allocation per claim, "
            "disjoint devices, zero WAL residue, leader renewed), "
            "recovery + cliff recorded — all hold"
        )
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser("stormsim", description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="CI scale + hard contract asserts "
                   "(`make stormbench`)")
    p.add_argument("--publish-worker", action="store_true",
                   help="internal: publisher shard subprocess")
    p.add_argument("--kubelet-worker", action="store_true",
                   help="internal: kubelet analog subprocess")
    p.add_argument("--server", default="")
    p.add_argument("--start", type=int, default=0)
    p.add_argument("--count", type=int, default=0)
    p.add_argument("--seed", type=int, default=20260807)
    p.add_argument("--flap-tick", type=float, default=0.25)
    p.add_argument("--flap-frac", type=float, default=0.02)
    p.add_argument("--flap-threads", type=int, default=2)
    p.add_argument("--reverify", type=float, default=0.0)
    p.add_argument("--prepare-ms", type=float, default=2.0)
    args = p.parse_args(argv)
    if args.publish_worker:
        return _publish_worker_main(args)
    if args.kubelet_worker:
        return _kubelet_worker_main(args)
    env = os.environ.get
    if args.smoke:
        nodes = int(env("STORMSIM_NODES", "64"))
        claims = int(env("STORMSIM_CLAIMS", "72"))
        rate = float(env("STORMSIM_RATE", "150"))
        workers = int(env("STORMSIM_WORKERS", "4"))
        # Smoke cliff: APF seats squeezed so the shed cliff is
        # reachable at CI scale — the point is exercising the
        # detection + naming machinery, not sizing a laptop.
        default_rungs, default_window, default_seats = "48,96,192", 2.0, 2
    else:
        nodes = int(env("STORMSIM_NODES", "5000"))
        claims = int(env("STORMSIM_CLAIMS", "1500"))
        rate = float(env("STORMSIM_RATE", "250"))
        workers = int(env("STORMSIM_WORKERS", "8"))
        default_rungs, default_window, default_seats = (
            "5000,7500,10000,15000", 10.0, None,
        )
    rungs = [
        int(x) for x in env("STORMSIM_CLIFF_RUNGS", default_rungs).split(",")
        if x.strip()
    ]
    seats_env = env("STORMSIM_CLIFF_SEATS", "")
    seats = int(seats_env) if seats_env else default_seats
    report = run(
        nodes, claims, rate,
        seed=int(env("STORMSIM_SEED", "20260807")),
        workers=workers,
        prepare_ms=float(env("STORMSIM_PREPARE_MS", "2.0")),
        outage_s=float(env("STORMSIM_OUTAGE", "0.75")),
        cliff_rungs=rungs,
        cliff_window_s=float(env("STORMSIM_CLIFF_WINDOW",
                                 str(default_window))),
        cliff_seats=seats,
        smoke=args.smoke,
    )
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
