"""ICI collective-bandwidth exerciser — the nvbandwidth analog.

The reference ships nvbandwidth as its ComputeDomain smoke/failover
payload (demo/specs/imex/nvbandwidth-test-job.yaml,
tests/bats/test_cd_failover.bats:32-46): a pass/fail probe that the
fabric actually moves bytes. The TPU-native equivalent measures the XLA
collectives a training step lives on — psum (all-reduce), all-gather,
reduce-scatter, and ppermute (the ring-attention primitive) — over the
device mesh, and fails when achieved bus bandwidth drops below a
threshold.

Bus-bandwidth normalization (the NCCL-tests algebra nvbandwidth users
expect): every leg divides the wire bytes its algorithm moves per device
by the elapsed time, so on a balanced fabric with per-link bandwidth B
each leg reports ~B and a single ``--min-gbps`` threshold gates them all
equally — all-reduce ``2(n-1)/n * S``, all-gather ``(n-1)S``,
reduce-scatter ``(n-1)/n * S``, one-hop ppermute ``S`` (S = the per-rank
shard).

On a single-device allocation (no fabric) it degrades to an HBM
copy-bandwidth probe, so the same job spec stays meaningful on one chip.

CLI (the Job payload):
    python -m tpu_dra.workloads.icibandwidth \
        --size-mb 64 --reps 10 --min-gbps 0

Prints one JSON line per run; exit 1 when any collective misses
``--min-gbps`` (0 disables the gate: smoke mode, like nvbandwidth's
pass/fail-only use).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, Optional


def fetch(y) -> float:
    """Force completion with a host read of a FULL reduction: on deferring
    backends (the axon tunnel) ``block_until_ready`` can return before
    execution, and fetching one element lets the compiler dead-code the
    rest of the probe — a sum keeps every element live. The differential
    timing cancels the reduction's own cost."""
    import jax.numpy as jnp
    import numpy as np

    return float(np.asarray(jnp.sum(y)))


def _timed_pair(run1, run_n, x, reps: int, outer: int = 3) -> float:
    """Per-op seconds by DIFFERENTIAL timing: a 1-iteration loop vs an
    N-iteration loop (both fetched), cancelling dispatch + transfer
    overhead that would otherwise swamp a single op."""
    fetch(run1(x))
    fetch(run_n(x))

    def best(run):
        b = float("inf")
        for _ in range(outer):
            t0 = time.perf_counter()
            fetch(run(x))
            b = min(b, time.perf_counter() - t0)
        return b

    t1, tn = best(run1), best(run_n)
    per_op = (tn - t1) / (reps - 1) if reps > 1 else tn
    # Noise floor: differential timing can go epsilon-negative.
    return max(per_op, 1e-9)


def measure_collectives(
    size_mb: float = 64.0, reps: int = 10, axis: str = "x", devices=None
) -> Dict[str, dict]:
    """Bandwidth per collective over the given (default: all) devices —
    one mesh axis; the exerciser probes the fabric, not a parallelism
    layout."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    size_bytes = int(size_mb * 1024 * 1024)
    out: Dict[str, dict] = {
        "devices": n,
        "payload_mb": size_mb,
        "reps": reps,
    }

    def loop(body, iters, vary=False):
        def step(i, t):
            r = body(t)
            if vary:
                # Under shard_map the carry must keep its device-varying
                # type; a psum output is axis-invariant and would change
                # the fori_loop carry type.
                from tpu_dra.workloads.jaxcompat import pcast

                r = pcast(r, axis, to="varying")
            # Materialize every iteration: without the barrier XLA fuses
            # the whole loop into one kernel and the probe measures
            # registers, not HBM/ICI.
            return lax.optimization_barrier(r)

        return lambda s: lax.fori_loop(0, iters, step, s)

    if n == 1:
        # No fabric: HBM copy probe (read + write size_bytes each rep).
        x = jax.device_put(
            jnp.zeros(size_bytes // 4, jnp.float32), devices[0]
        )
        body = lambda v: v * 1.000001 + 1e-9  # noqa: E731
        dt = _timed_pair(
            jax.jit(loop(body, 1)), jax.jit(loop(body, reps)), x, reps
        )
        out["hbm_copy"] = {
            "seconds": dt,
            "gbps": 2 * size_bytes / dt / 1e9,
        }
        return out

    mesh = Mesh(np.array(devices), (axis,))
    spec = NamedSharding(mesh, P(axis))
    # Per-device shard of size_bytes: the collectives move the whole
    # payload across the fabric each application.
    x = jax.device_put(
        jnp.zeros(n * (size_bytes // 4), jnp.float32), spec
    )

    def timed(body, vary=False):
        def sharded(iters):
            return jax.jit(shard_map(
                loop(body, iters, vary=vary),
                mesh=mesh, in_specs=P(axis), out_specs=P(axis),
            ))

        return _timed_pair(sharded(1), sharded(reps), x, reps)

    results = {}

    # Bus-bandwidth normalization: on a balanced fabric with per-link
    # bandwidth B every leg below reports ~B, so one --min-gbps threshold
    # gates them all equally. Per-rank shard = size_bytes throughout.

    # all-reduce over per-rank buffer S: wire bytes 2(n-1)S/n per device.
    dt = timed(lambda s: jax.lax.psum(s, axis) * (1.0 / n), vary=True)
    results["psum_allreduce"] = {
        "seconds": dt,
        "busbw_gbps": 2 * (n - 1) / n * size_bytes / dt / 1e9,
    }

    # all-gather then re-slice back to the shard (keeps shapes stable for
    # repeated application); busbw factor (n-1)/n of gathered bytes.
    def ag(s):
        g = jax.lax.all_gather(s, axis, tiled=True)
        i = jax.lax.axis_index(axis)
        return jax.lax.dynamic_slice_in_dim(g, i * s.shape[0], s.shape[0])

    # gathered output = n*S; each device receives (n-1)S.
    dt = timed(ag)
    results["all_gather"] = {
        "seconds": dt,
        "busbw_gbps": (n - 1) * size_bytes / dt / 1e9,
    }

    # reduce-scatter via psum_scatter; same busbw factor as all-gather.
    def rs(s):
        r = jax.lax.psum_scatter(s, axis, tiled=True)
        return jnp.tile(r, n)

    # scatters the per-rank S into n chunks; each device sends (n-1)S/n.
    dt = timed(rs)
    results["reduce_scatter"] = {
        "seconds": dt,
        "busbw_gbps": (n - 1) / n * size_bytes / dt / 1e9,
    }

    # ring ppermute: each device forwards its shard one hop (the ring
    # attention / pipeline primitive); moves the full payload once.
    def pp(s):
        return jax.lax.ppermute(
            s, axis, [(i, (i + 1) % n) for i in range(n)]
        )

    # one hop: each device sends its whole shard S over one link.
    dt = timed(pp)
    results["ppermute_ring"] = {
        "seconds": dt,
        "busbw_gbps": size_bytes / dt / 1e9,
    }

    out.update(results)
    return out


def main(argv=None) -> int:
    from tpu_dra.workloads import apply_forced_platform

    apply_forced_platform()

    p = argparse.ArgumentParser("tpu-ici-bandwidth")
    p.add_argument("--size-mb", type=float, default=64.0)
    p.add_argument("--reps", type=int, default=10)
    p.add_argument(
        "--min-gbps", type=float, default=0.0,
        help="Fail when any collective's bus bandwidth is below this "
        "(0 = smoke mode: measure and pass)",
    )
    p.add_argument(
        "--distributed", action="store_true",
        help="Initialize jax.distributed from the CD-injected bootstrap "
        "env first (multi-host domains)",
    )
    p.add_argument(
        "--cpu-devices", type=int, default=0,
        help="Force N virtual CPU devices (fabric-free smoke/e2e; env "
        "vars alone lose to interpreters that import jax at startup)",
    )
    args = p.parse_args(argv)

    if args.cpu_devices:
        from tpu_dra.workloads import force_cpu_devices

        force_cpu_devices(args.cpu_devices)
    if args.distributed:
        from tpu_dra.workloads.bootstrap import initialize_from_env

        initialize_from_env()
    from tpu_dra.workloads.multiplex_client import auto_lease

    with auto_lease():
        results = measure_collectives(args.size_mb, args.reps)
    print(json.dumps(results))

    failed: Optional[str] = None
    if args.min_gbps > 0:
        for name, leg in results.items():
            if not isinstance(leg, dict):
                continue
            bw = leg.get("busbw_gbps", leg.get("gbps"))
            if bw is not None and bw < args.min_gbps:
                failed = f"{name}: {bw:.2f} GB/s < {args.min_gbps}"
                print(f"FAIL {failed}", file=sys.stderr)
    if failed:
        return 1
    print("PASS", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
