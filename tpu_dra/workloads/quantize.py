"""Weight-only int8 quantization for the serving path.

TPU-native rationale: single-chip decode is HBM-bandwidth-bound and the
bench model's weights are the largest per-step read (2.4 GB bf16 for the
1B model vs 1.6 GB of KV cache at batch 128). Symmetric per-output-channel
int8 halves that traffic; the int8->bf16 convert fuses into the MXU feed
on TPU, so there is no separate dequantized copy in HBM. Integers up to
|127| are exact in bf16 (8 significand bits), so dequantization error is
bounded by the quantization rounding alone.

Quantizes every 2D ``kernel`` in the Llama param tree (attention
projections, MLP, lm_head) into ``{"kernel_q": int8 [in, out],
"scale": f32 [1, out]}``; everything else (embeddings, norms — tiny,
accuracy-critical) stays as-is. generate.py's ``_mm`` consumes either
form, so quantized and full-precision trees run the same decode code.

No reference counterpart (the reference is a DRA driver); this is the
workload-payload serving layer, proven by
tests/test_workloads.py::test_int8_weight_only_decode (both param
layouts) and the bench's ``decode_int8`` leg.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def quantize_weight(kernel: jnp.ndarray) -> dict:
    """Symmetric per-output-channel int8: kernel [in, out] ->
    {"kernel_q" int8, "scale" f32 [1, out]} with
    dequant(kernel_q) = kernel_q * scale ~= kernel."""
    if kernel.ndim != 2:
        raise ValueError(f"expected 2D kernel, got shape {kernel.shape}")
    absmax = jnp.max(jnp.abs(kernel.astype(jnp.float32)), axis=0,
                     keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(kernel.astype(jnp.float32) / scale), -127, 127
    ).astype(jnp.int8)
    return {"kernel_q": q, "scale": scale}


def quantize_params(params: dict) -> dict:
    """Quantize every 2D ``{"kernel": ...}`` subtree (any nesting/layout:
    unrolled, scan-stacked 2D slices stay 2D only when unrolled — the
    stacked [L, in, out] layout is quantized per (layer, out) channel)."""

    def walk(node, path):
        if isinstance(node, dict):
            if "kernel" in node:
                k = node["kernel"]
                if set(node) == {"kernel"} and k.ndim == 2:
                    return quantize_weight(k)
                if set(node) == {"kernel"} and k.ndim == 3:
                    # scan-stacked [L, in, out]: vmap gives scale
                    # [L, 1, out]; keep that shape — _mm broadcasts it
                    # against [L, ..., out] per layer.
                    return jax.vmap(quantize_weight)(k)
                # A kernel we don't understand (extra sibling keys such
                # as a bias, or an unexpected rank) must be LOUD: a
                # silent skip here means the serving path quietly runs
                # that projection in bf16 and the int8 leg's claimed
                # weight-traffic cut is no longer true.
                raise ValueError(
                    f"unquantizable kernel node at {'/'.join(path)}: "
                    f"keys={sorted(node)}, ndim={k.ndim} "
                    "(expected a bare 2D/3D {'kernel': ...} leaf)"
                )
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        return node

    return walk(params, ())


def dequantize_weight(q: dict) -> jnp.ndarray:
    """Exact inverse view (f32) — for tests and fallbacks."""
    return q["kernel_q"].astype(jnp.float32) * q["scale"]


# --- KV-cache quantization (serving path) -----------------------------------
#
# Decode is KV-bandwidth-bound once weights are int8 (BENCH_r05: 1.611 GB
# of bf16 KV at batch 128 vs 1.04 GB of int8 weights). Symmetric int8
# with a PER-TOKEN PER-HEAD scale (one f32 per [batch, position, kv_head]
# row) keeps the rounding error of each head's hd-vector bounded by its
# own absmax/254 while cutting KV bytes ~2x (hd=64: 64+4 bytes vs 128).
# Dequantization happens on the fly inside the attention contraction
# (ops/attention.py decode path; generate.py prefill) — the int8->compute
# convert fuses into the dot feed, so no dequantized KV copy ever lands
# in HBM.


def quantize_kv(x: jnp.ndarray) -> tuple:
    """x [..., heads, head_dim] -> (int8 same shape, f32 scale [..., heads]).

    Symmetric per-(token, head) scale over the head_dim axis. An all-zero
    row quantizes to zeros with scale 0 — NOT 1 — so freshly-zeroed cache
    tails keep the zero-tail invariant checkable on the scale arrays too.
    """
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = absmax / 127.0
    q = jnp.clip(
        jnp.round(xf / jnp.where(scale > 0, scale, 1.0)[..., None]),
        -127, 127,
    ).astype(jnp.int8)
    return q, scale


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Exact inverse view (f32) — for tests and the reference decode
    attention path."""
    return q.astype(jnp.float32) * scale[..., None]
