"""Version-compat shims for JAX APIs the workloads lean on.

The driver control plane is stdlib-only, but the workload payloads track
moving JAX APIs; these shims keep them importable (and the test suite
collectable) across the JAX versions the fleet actually runs:

- ``shard_map`` moved from ``jax.experimental.shard_map`` to top-level
  ``jax.shard_map``;
- ``lax.pcast`` (marking values device-varying for shard_map's
  representation checking) does not exist on older JAX — where the
  varying/invariant type system also doesn't exist, so identity is the
  faithful fallback.
"""

from __future__ import annotations

import inspect

import jax

try:
    from jax import shard_map as _shard_map  # newer JAX: top-level
except ImportError:  # older JAX
    from jax.experimental.shard_map import shard_map as _shard_map

_SHARD_MAP_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(*args, **kwargs):
    """``jax.shard_map`` with the representation-check kwarg translated
    across its rename (``check_rep`` -> ``check_vma``)."""
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(*args, **kwargs)


def pcast(x, axes, to="varying"):
    """``jax.lax.pcast`` where it exists; identity on older JAX (which has
    no varying-type checking for the cast to satisfy)."""
    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axes, to=to)
