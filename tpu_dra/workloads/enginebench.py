"""Serving-engine bench + CPU smoke — ``make enginebench`` (wired into
``ci``), and the measurement core behind ``bench.py --leg-serve``.

The smoke is a hardware-free gate on the ISSUE 7 serving engine:

1. **paged-vs-unpaged / fused-vs-unfused exact parity**: the paged +
   continuous-batched engine must be TOKEN-IDENTICAL to the oracle
   configuration (contiguous page ranges + one jitted step per token)
   over a mixed-length trace — same completions, same tokens;
2. **admission/eviction accounting**: every submitted request completes
   exactly once with exactly ``max_new_tokens`` tokens, and the page
   allocator ends the run leak-free (all pages back on the free list,
   refcounts zero, freed pages re-zeroed — the per-page zero-tail
   invariant);
3. **backpressure drill**: a lease revocation mid-trace drains the
   engine (admissions stop, in-flight state checkpointed, pages freed),
   and after the lease returns every sequence resumes and completes
   with its pre-drain token prefix intact — no lost or duplicated
   sequences;
4. **honest padding accounting**: the fixed-batch baseline's
   ``decode_padding_waste`` must equal the value computed directly from
   the trace's length mix (the satellite fix: tok/s over PADDED tokens
   is not a serving number).

Prints one JSON line; exits nonzero on any violation — the same
contract as bench.py legs, so CI sees a regression before a TPU run
does. The full (timed) configuration runs as bench.py's ``--leg-serve``
through the DRA claim env and records ``serve_tok_s`` /
``serve_p50_ms`` / ``serve_p99_ms`` against the fixed-batch baseline
at equal batch memory (docs/serving.md has the methodology).
"""

from __future__ import annotations

import json
import statistics
import sys
import time

import numpy as np


# --- seeded Poisson arrival trace -------------------------------------------


def make_trace(
    seed: int,
    n_requests: int,
    rate_rps: float,
    prompt_lens,
    output_lens,
    vocab: int,
):
    """Seeded trace: exponential inter-arrivals (a Poisson process at
    ``rate_rps``), prompt/output lengths drawn uniformly from the given
    mixes, prompt tokens uniform over [1, vocab). Returns a list of
    engine Requests (arrival_s is the offset from trace start)."""
    from tpu_dra.workloads.engine import Request

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, n_requests))
    reqs = []
    for i in range(n_requests):
        plen = int(rng.choice(prompt_lens))
        olen = int(rng.choice(output_lens))
        reqs.append(
            Request(
                rid=f"r{i:04d}",
                prompt=rng.integers(1, vocab, plen).astype(np.int32),
                max_new_tokens=olen,
                arrival_s=float(arrivals[i]),
            )
        )
    return reqs


def make_lookup_trace(
    seed: int,
    n_requests: int,
    rate_rps: float,
    prompt_lens,
    output_lens,
    vocab: int,
):
    """Lookup-friendly twin of :func:`make_trace` (ISSUE 15): each
    prompt is a short random motif TILED to the drawn length, so the
    n-gram/prompt-lookup draft source has real structure to hit — the
    templated/extractive regime where speculative decoding earns its
    keep. Same arrival process and length mixes as make_trace; the
    spec-vs-nonspec serving gate runs both engines over THIS trace so
    the comparison is apples-to-apples."""
    from tpu_dra.workloads.engine import Request

    rng = np.random.default_rng(seed + 777)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, n_requests))
    reqs = []
    for i in range(n_requests):
        plen = int(rng.choice(prompt_lens))
        olen = int(rng.choice(output_lens))
        motif = rng.integers(
            1, vocab, max(2, plen // 4)
        ).astype(np.int32)
        prompt = np.tile(motif, -(-plen // len(motif)))[:plen]
        reqs.append(
            Request(
                rid=f"lk{i:04d}",
                prompt=prompt,
                max_new_tokens=olen,
                arrival_s=float(arrivals[i]),
            )
        )
    return reqs


def trace_stats(trace) -> dict:
    return {
        "requests": len(trace),
        "prompt_tokens": int(sum(len(r.prompt) for r in trace)),
        "output_tokens": int(sum(r.max_new_tokens for r in trace)),
        "max_prompt": max(len(r.prompt) for r in trace),
        "max_output": max(r.max_new_tokens for r in trace),
    }


# --- fixed-batch baseline (the system the engine replaces) -------------------


def fixed_batch_padding_waste(trace, batch: int) -> dict:
    """Pure accounting for the fixed-batch system: requests grouped in
    arrival order into batches of ``batch``, every prompt padded to the
    GLOBAL max prompt and every output to the GLOBAL max output (one
    compiled executable — the fixed-batch deployment model). Decode
    waste is the fraction of decoded token-steps that served padding
    instead of a real request token."""
    stats = trace_stats(trace)
    n_batches = -(-len(trace) // batch)
    padded_decode = n_batches * batch * stats["max_output"]
    useful_decode = stats["output_tokens"]
    return {
        "n_batches": n_batches,
        "padded_decode_tokens": padded_decode,
        "useful_decode_tokens": useful_decode,
        "decode_padding_waste": round(1.0 - useful_decode / padded_decode, 4),
    }


def run_fixed_batch_baseline(
    config, params, trace, batch: int, kv_quant: str = "none"
) -> dict:
    """Measure the fixed-batch system on the trace: batches of
    ``batch`` in arrival order, prompts padded to the global max prompt,
    decoding the global max output — one compiled shape, warmed once.
    Reports BOTH the padded-token rate (the dishonest number the old
    accounting produced) and useful-token throughput, plus per-request
    completion latency quantiles (a request completes when its whole
    batch does)."""
    import jax
    import jax.numpy as jnp

    from tpu_dra.workloads.generate import greedy_generate
    from tpu_dra.workloads.icibandwidth import fetch

    acc = fixed_batch_padding_waste(trace, batch)
    stats = trace_stats(trace)
    P, O = stats["max_prompt"], stats["max_output"]

    fn = jax.jit(
        lambda p, t: greedy_generate(
            config, p, t, max_new_tokens=O, kv_quant=kv_quant
        )
    )
    pad_prompt = jnp.ones((batch, P), jnp.int32)
    fetch(fn(params, pad_prompt))  # compile outside the timing

    lat = []
    t0 = time.monotonic()
    for b0 in range(0, len(trace), batch):
        group = trace[b0:b0 + batch]
        # A fixed-batch server cannot launch a batch before its LAST
        # member arrives (batches form in arrival order) — the wait is
        # part of the system being measured, and it keeps latencies
        # honestly non-negative.
        gate = max(r.arrival_s for r in group)
        wait = gate - (time.monotonic() - t0)
        if wait > 0:
            time.sleep(wait)
        toks = np.ones((batch, P), np.int32)
        for i, r in enumerate(group):
            # Right-align so every prompt's last token sits at the decode
            # boundary (left padding, the fixed-batch convention).
            toks[i, P - len(r.prompt):] = r.prompt
        out = fn(params, jnp.asarray(toks))
        fetch(out)
        done = time.monotonic() - t0
        lat.extend(done - r.arrival_s for r in group)
    wall = time.monotonic() - t0
    lat_ms = sorted(x * 1000 for x in lat)
    return {
        **acc,
        "wall_seconds": round(wall, 3),
        "padded_tok_s": round(acc["padded_decode_tokens"] / wall, 1),
        "useful_tok_s": round(acc["useful_decode_tokens"] / wall, 1),
        # Unrounded, for the strict beat-the-baseline gate: a marginal
        # true win must not round down to exactly 1.0 and fail the leg.
        "useful_tok_s_raw": acc["useful_decode_tokens"] / wall,
        "p50_ms": round(statistics.median(lat_ms), 1),
        "p99_ms": round(lat_ms[int(0.99 * (len(lat_ms) - 1))], 1),
        "batch": batch,
        "max_seq": P + O,
    }


# --- engine replay -----------------------------------------------------------


def equal_memory_engine_config(
    trace,
    batch: int,
    page_size: int = 16,
    scan_chunk: int = 8,
    prefill_chunk: int = 64,
    slots_factor: int = 2,
    kv_quant: str = "none",
    weight_quant: str = "none",
):
    """EngineConfig whose page pool holds the SAME number of KV
    positions as the fixed-batch baseline's ``batch x (max_prompt +
    max_output)`` allocation — the equal-batch-memory comparison the
    acceptance bar names. The engine may hold more CONCURRENT sequences
    (``slots_factor * batch``) because short sequences release their
    pages instead of squatting on a max_seq row."""
    from tpu_dra.workloads.engine import EngineConfig

    stats = trace_stats(trace)
    max_seq = stats["max_prompt"] + stats["max_output"]
    mpp = -(-(max_seq + scan_chunk) // page_size)
    budget_pages = batch * (-(-max_seq // page_size))
    return EngineConfig(
        page_size=page_size,
        max_slots=slots_factor * batch,
        max_pages_per_seq=mpp,
        num_pages=1 + budget_pages,
        scan_chunk=scan_chunk,
        prefill_chunk=prefill_chunk,
        kv_quant=kv_quant,
        weight_quant=weight_quant,
    )


def run_engine_trace(
    config, params, ec, trace, gate=None, metrics=None, warmup=True
) -> dict:
    """Replay the trace through a fresh Engine (arrivals honored on the
    wall clock) and report sustained useful tok/s + per-request latency
    quantiles. ``warmup`` runs a two-request mini-trace through the same
    engine first so jit compiles land outside the timing."""
    from tpu_dra.workloads.engine import Engine, Request

    engine = Engine(
        config, params, ec, gate=gate, metrics=metrics
    )
    if warmup:
        # Compile outside the timing: one warmup request per prefill
        # bucket (chunks are padded to power-of-two buckets, so this
        # covers every prefill trace) plus the decode chunk itself.
        cap = ec.max_pages_per_seq * ec.page_size - (
            2 * ec.scan_chunk + 1
        )
        buckets = set()
        b = 1
        while b < ec.prefill_chunk:
            buckets.add(b)
            b *= 2
        buckets.add(ec.prefill_chunk)
        lens = sorted(x for x in buckets if 1 <= x <= cap)
        w = [
            Request(
                rid=f"warm{i}",
                prompt=np.ones(bl, np.int32),
                max_new_tokens=ec.scan_chunk + 1,
            )
            for i, bl in enumerate(lens)
        ]
        engine.run(w)
        engine.completed.clear()
        # The all-ones warmup prompts feed the draft source junk with
        # near-zero acceptance — the recorded spec_accept_rate must
        # cover only the measured trace.
        engine.spec_proposed = 0
        engine.spec_accepted = 0
    t0 = time.monotonic()
    completions = engine.run(trace)
    wall = time.monotonic() - t0
    useful = int(sum(len(c.tokens) for c in completions.values()))
    lat_ms = sorted(c.latency_s * 1000 for c in completions.values())
    ttft_ms = sorted(c.ttft_s * 1000 for c in completions.values())
    return {
        "completions": completions,
        "wall_seconds": round(wall, 3),
        "useful_decode_tokens": useful,
        "tok_s": round(useful / wall, 1),
        "tok_s_raw": useful / wall,
        "p50_ms": round(statistics.median(lat_ms), 1),
        "p99_ms": round(lat_ms[int(0.99 * (len(lat_ms) - 1))], 1),
        "ttft_p50_ms": round(statistics.median(ttft_ms), 1),
        "engine": engine,
    }


def run_prefix_fleet(
    config, params, fleet_n: int, prompt_len: int, max_new: int,
    page_size: int, vocab: int, seed: int = 0,
) -> dict:
    """COW prefix-sharing accounting (ISSUE 15): a fleet of ``fleet_n``
    sequences carrying ONE shared system prompt, run twice — private
    (no prefix_id) vs shared (prefix_id + page-aligned prefix_len) —
    and compared on PEAK simultaneously-allocated pages (the
    allocator's free-list low-water mark: the honest memory number).
    Token identity between the two runs and a leak-free/zeroed pool
    after each are asserted here, not just measured."""
    import dataclasses as _dc

    from tpu_dra.workloads import paged_kv
    from tpu_dra.workloads.engine import Engine, EngineConfig, Request

    rng = np.random.default_rng(seed + 99)
    prompt = rng.integers(1, vocab, prompt_len).astype(np.int32)
    # Page-aligned share point: the clean ~1/N number (a mid-page
    # prefix additionally pays one frozen page + one COW fork per
    # sharer — correctness covered by tests, accounting kept simple
    # here).
    prefix_len = (prompt_len - 1) // page_size * page_size

    def fleet(share: bool):
        return [
            Request(
                rid=f"pf{i}", prompt=prompt, max_new_tokens=max_new,
                prefix_id="bench-sys" if share else None,
                prefix_len=prefix_len if share else 0,
            )
            for i in range(fleet_n)
        ]

    mpp = -(-(prompt_len + max_new + 8) // page_size)
    ec = EngineConfig(
        page_size=page_size, max_slots=fleet_n, max_pages_per_seq=mpp,
        num_pages=1 + fleet_n * mpp, scan_chunk=4, prefill_chunk=32,
    )
    out = {}
    tokens = {}
    for label, share in (("private", False), ("shared", True)):
        eng = Engine(config, params, _dc.replace(ec))
        done = eng.run(fleet(share))
        alloc = eng.allocator
        peak = alloc.num_pages - 1 - alloc.min_free
        assert alloc.free_pages == alloc.num_pages - 1, (
            f"{label} fleet leaked pages"
        )
        assert paged_kv.pages_are_zero(
            eng.cache, list(range(1, alloc.num_pages))
        ), f"{label} fleet left unzeroed pages"
        out[f"{label}_peak_pages"] = peak
        tokens[label] = {rid: c.tokens for rid, c in done.items()}
        if share:
            out["prefix_attached"] = eng.prefix_attached
            out["prefix_saved_hw"] = eng.prefix_saved_hw
    mismatch = [
        rid for rid in tokens["private"]
        if not np.array_equal(
            tokens["private"][rid], tokens["shared"][rid]
        )
    ]
    assert not mismatch, (
        f"prefix sharing changed tokens on {mismatch} — COW must be "
        f"invisible to the math"
    )
    out["prefix_pages_saved"] = (
        out["private_peak_pages"] - out["shared_peak_pages"]
    )
    out["fleet_n"] = fleet_n
    return out


def run_prefill_ttft_pair(config, params, ec=None, burst_n: int = 8,
                          prompt_len: int = 24, vocab: int = 0,
                          seed: int = 0, page_size: int = 16,
                          prefill_chunk: int = 64) -> dict:
    """Batched-vs-serial chunked prefill (ISSUE 15): the SAME
    admission burst (all arrivals at t=0) through the engine with the
    bucket packing on (prefill_batch=0) vs the old one-sequence-per-
    iteration schedule (prefill_batch=1); first-token p50 is the
    serialization the tentpole removes. ``ec`` defaults to a
    generously-pooled config with ``burst_n`` slots — the phase
    measures prefill SCHEDULING, so admission must not block on pages
    (a tight pool throttles both schedules identically and hides the
    contrast)."""
    import dataclasses as _dc

    from tpu_dra.workloads.engine import EngineConfig

    if vocab < 2:
        vocab = config.vocab_size
    if ec is None:
        mpp = -(-(prompt_len + 8 + 8) // page_size)
        ec = EngineConfig(
            page_size=page_size, max_slots=burst_n,
            max_pages_per_seq=mpp, num_pages=1 + burst_n * mpp,
            scan_chunk=8, prefill_chunk=prefill_chunk,
        )
    rng = np.random.default_rng(seed + 55)
    # Distinct prompts (same length): identical content would let
    # prefix sharing skip work and muddy the comparison. The SAME burst
    # replays through both schedules.
    burst = [
        _mk_burst_req(rng, i, prompt_len, vocab) for i in range(burst_n)
    ]
    out = {}
    for label, pb in (("batched", 0), ("serial", 1)):
        res = run_engine_trace(
            config, params, _dc.replace(ec, prefill_batch=pb), burst
        )
        out[f"{label}_ttft_p50_ms"] = res["ttft_p50_ms"]
        out[f"{label}_tok_s"] = res["tok_s"]
    return out


def _mk_burst_req(rng, i, prompt_len, vocab):
    from tpu_dra.workloads.engine import Request

    return Request(
        rid=f"b{i}",
        prompt=rng.integers(1, vocab, prompt_len).astype(np.int32),
        max_new_tokens=8,
    )


def run_serve_bench(config, params, env) -> dict:
    """The --leg-serve measurement (bench.py calls this in the leg
    subprocess): seeded Poisson trace, fixed-batch baseline at the
    decode leg's batch size, then the engine at equal batch memory —
    bf16 and the int8 weight-only knob (the ROADMAP item 4 satellite).
    Returns the leg's result dict (serve_* keys)."""
    seed = int(env.get("BENCH_SERVE_SEED", "0"))
    n = int(env.get("BENCH_SERVE_REQUESTS", "64"))
    # Default rate saturates the chip (arrivals far faster than service)
    # so sustained tok/s measures CAPACITY, not the arrival process; the
    # p99 then reflects queueing under burst. Lower it to probe the
    # latency-vs-load curve.
    rate = float(env.get("BENCH_SERVE_RATE_RPS", "1000"))
    batch = int(env.get("BENCH_SERVE_BATCH", "16"))
    kv_quant = env.get("BENCH_SERVE_KV_QUANT", "none")
    prompt_lens = [
        int(x) for x in env.get(
            "BENCH_SERVE_PROMPTS", "16,64,128,256"
        ).split(",")
    ]
    output_lens = [
        int(x) for x in env.get(
            "BENCH_SERVE_OUTPUTS", "8,32,96,192"
        ).split(",")
    ]
    trace = make_trace(
        seed, n, rate, prompt_lens, output_lens, config.vocab_size
    )
    baseline = run_fixed_batch_baseline(
        config, params, trace, batch, kv_quant=kv_quant
    )
    ec = equal_memory_engine_config(
        trace, batch,
        page_size=int(env.get("BENCH_SERVE_PAGE", "16")),
        scan_chunk=int(env.get("BENCH_SERVE_CHUNK", "8")),
        kv_quant=kv_quant,
    )
    engine = run_engine_trace(config, params, ec, trace)
    ec_w8 = equal_memory_engine_config(
        trace, batch,
        page_size=ec.page_size, scan_chunk=ec.scan_chunk,
        kv_quant=kv_quant, weight_quant="int8",
    )
    engine_w8 = run_engine_trace(config, params, ec_w8, trace)
    # Sampled serving (ISSUE 8 satellite: PR-2's sample_token/topk_exact
    # wired into the engine scan): same trace, temperature/top-k drawn
    # inside the fused chunk — the step-breakdown's sampling_ms says
    # where any gap vs the greedy engine comes from.
    import dataclasses as _dc

    ec_sampled = _dc.replace(
        ec,
        temperature=float(env.get("BENCH_SERVE_TEMP", "0.8")),
        top_k=int(env.get("BENCH_SERVE_TOPK", "40")),
    )
    engine_sampled = run_engine_trace(config, params, ec_sampled, trace)
    # Speculative decoding (ISSUE 15): spec-vs-nonspec on the SAME
    # lookup-friendly trace (repetitive prompts — the regime where the
    # prompt-lookup draft source has real structure to hit), so the
    # gate compares apples to apples.
    spec_k = int(env.get("BENCH_SPEC_K", "6"))
    lookup = make_lookup_trace(
        seed, n, rate, prompt_lens, output_lens, config.vocab_size
    )
    ec_lookup = equal_memory_engine_config(
        lookup, batch,
        page_size=ec.page_size, scan_chunk=ec.scan_chunk,
        kv_quant=kv_quant,
    )
    lookup_base = run_engine_trace(config, params, ec_lookup, lookup)
    spec_run = run_engine_trace(
        config, params, _dc.replace(ec_lookup, spec_k=spec_k), lookup
    )
    spec_engine = spec_run["engine"]
    accept = spec_engine.spec_accepted / max(spec_engine.spec_proposed, 1)
    # Copy-on-write prefix sharing: peak pages for an N-strong
    # same-system-prompt fleet, shared vs private.
    fleet_n = int(env.get("BENCH_PREFIX_FLEET", "8"))
    prefix = run_prefix_fleet(
        config, params, fleet_n,
        prompt_len=max(prompt_lens), max_new=min(output_lens),
        page_size=ec.page_size, vocab=config.vocab_size, seed=seed,
    )
    # Batched chunked prefill: TTFT under an admission burst, bucket
    # packing vs the serialized one-sequence-per-iteration schedule
    # (own generously-pooled config: the phase measures scheduling,
    # not page pressure).
    ttft_pair = run_prefill_ttft_pair(
        config, params,
        burst_n=min(2 * batch, 16),
        prompt_len=max(prompt_lens), vocab=config.vocab_size, seed=seed,
        page_size=ec.page_size,
    )
    result = {
        "serve_tok_s": engine["tok_s"],
        "serve_sampled_tok_s": engine_sampled["tok_s"],
        "serve_p50_ms": engine["p50_ms"],
        "serve_p99_ms": engine["p99_ms"],
        "serve_ttft_p50_ms": engine["ttft_p50_ms"],
        "serve_w8_tok_s": engine_w8["tok_s"],
        "serve_baseline_tok_s": baseline["useful_tok_s"],
        "serve_baseline_padded_tok_s": baseline["padded_tok_s"],
        "serve_baseline_p50_ms": baseline["p50_ms"],
        "serve_baseline_p99_ms": baseline["p99_ms"],
        "decode_padding_waste": baseline["decode_padding_waste"],
        # Rounded for the artifact; the leg's strict > 1.0 gate uses the
        # _raw twin so a marginal true win cannot round to exactly 1.0.
        "serve_vs_fixed_batch": round(
            engine["tok_s_raw"] / max(baseline["useful_tok_s_raw"], 1e-9),
            3,
        ),
        "serve_vs_fixed_batch_raw": engine["tok_s_raw"] / max(
            baseline["useful_tok_s_raw"], 1e-9
        ),
        "serve_requests": n,
        "serve_batch": batch,
        "serve_kv_quant": kv_quant,
        "trace": trace_stats(trace),
        # Speculative decoding (ISSUE 15): spec engine vs the nonspec
        # engine over the IDENTICAL lookup-friendly trace; _raw twin
        # carries the strict > 1.0 gate (rounding must not flip it).
        "serve_spec_tok_s": spec_run["tok_s"],
        "serve_spec_baseline_tok_s": lookup_base["tok_s"],
        "serve_spec_vs_nonspec": round(
            spec_run["tok_s_raw"] / max(lookup_base["tok_s_raw"], 1e-9),
            3,
        ),
        "serve_spec_vs_nonspec_raw": spec_run["tok_s_raw"] / max(
            lookup_base["tok_s_raw"], 1e-9
        ),
        "spec_accept_rate": round(accept, 4),
        "spec_k": spec_k,
        "spec_proposed": spec_engine.spec_proposed,
        "spec_accepted": spec_engine.spec_accepted,
        # Copy-on-write prefix sharing: fleet-of-N peak page savings.
        "prefix_pages_saved": prefix["prefix_pages_saved"],
        "prefix_fleet_n": prefix["fleet_n"],
        "prefix_private_peak_pages": prefix["private_peak_pages"],
        "prefix_shared_peak_pages": prefix["shared_peak_pages"],
        # Batched chunked prefill: first-token latency under a burst.
        "prefill_batched_ttft_p50_ms": ttft_pair["batched_ttft_p50_ms"],
        "prefill_serial_ttft_p50_ms": ttft_pair["serial_ttft_p50_ms"],
    }
    return result


# --- CPU smoke ---------------------------------------------------------------


def _smoke_config():
    import dataclasses

    import jax
    import jax.numpy as jnp

    from tpu_dra.workloads.models.llama import TINY_LLAMA, Llama

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, param_dtype=jnp.float32
    )
    model = Llama(cfg)
    params = model.init_params(jax.random.PRNGKey(7), batch=2, seq=8)
    return cfg, params


def _smoke_trace(cfg, n=8, seed=3):
    return make_trace(
        seed, n, rate_rps=1e9,  # all arrive immediately: saturating
        prompt_lens=[3, 7, 11, 16], output_lens=[2, 5, 9, 13],
        vocab=cfg.vocab_size,
    )


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    report = {"ok": False}

    from tpu_dra.infra.metrics import Metrics
    from tpu_dra.workloads.engine import EngineConfig, EventGate
    from tpu_dra.workloads.ops import attention as A
    from tpu_dra.workloads import paged_kv

    cfg, params = _smoke_config()
    trace = _smoke_trace(cfg)

    def ec(**kw):
        base = dict(
            page_size=4, max_slots=3, max_pages_per_seq=10,
            scan_chunk=3, prefill_chunk=5,
        )
        base.update(kw)
        return EngineConfig(**base)

    # (1) exact parity: paged+fused vs the contiguous+unfused oracle.
    A._LAST_PAGED_IMPL = None
    paged = run_engine_trace(
        cfg, params, ec(), trace, warmup=False
    )
    assert A._LAST_PAGED_IMPL is not None, (
        "the engine never dispatched the block-table attention op"
    )
    oracle = run_engine_trace(
        cfg, params, ec(fused=False, contiguous=True), trace,
        warmup=False,
    )
    assert set(paged["completions"]) == set(oracle["completions"])
    mismatches = [
        rid for rid in paged["completions"]
        if not np.array_equal(
            paged["completions"][rid].tokens,
            oracle["completions"][rid].tokens,
        )
    ]
    assert not mismatches, (
        f"paged/fused engine diverged from the unpaged/unfused oracle "
        f"on {mismatches}"
    )
    report["parity_requests"] = len(paged["completions"])

    # (2) admission/eviction accounting + allocator leak/zero checks.
    eng = paged["engine"]
    for r in trace:
        c = paged["completions"][r.rid]
        assert len(c.tokens) == r.max_new_tokens, (
            f"{r.rid}: {len(c.tokens)} tokens != {r.max_new_tokens}"
        )
    alloc = eng.allocator
    assert alloc.free_pages == alloc.num_pages - 1, "page leak"
    assert alloc.reserved_pages == 0, "reservation leak"
    live = [p for p in range(1, alloc.num_pages)]
    assert paged_kv.pages_are_zero(eng.cache, live), (
        "freed pages were not re-zeroed (per-page zero-tail invariant)"
    )
    report["pages"] = alloc.num_pages

    # (3) backpressure drill: revoke mid-trace, drain, resume.
    gate = EventGate()
    metrics = Metrics()
    from tpu_dra.workloads.engine import Engine

    drill = Engine(cfg, params, ec(), gate=gate, metrics=metrics)
    for r in _smoke_trace(cfg):
        drill.add_request(r)
    for _ in range(6):
        drill.step()
    pre = {
        s.req.rid: list(s.out)
        for s in drill._live()
    }
    in_flight = [s.req.rid for s in drill._slots if s is not None]
    assert in_flight, "drill revoked before anything was in flight"
    gate.revoke()
    for _ in range(3):
        drill.step()  # enters the stall: drains + sets the gauge
    assert all(s is None for s in drill._slots), "drain left slots live"
    assert drill.allocator.free_pages == drill.allocator.num_pages - 1
    g = metrics.render()
    assert "engine_admission_stalled" in g
    assert "engine_backpressure_drains_total 1" in g.replace(".0", "")
    stalled_completed = len(drill.completed)
    for _ in range(3):
        drill.step()
    assert len(drill.completed) == stalled_completed, (
        "engine made progress while the lease was revoked"
    )
    gate.restore()
    completions = drill.run([])
    assert set(completions) == {r.rid for r in _smoke_trace(cfg)}, (
        "a sequence was lost (or invented) across the drain"
    )
    for rid, c in completions.items():
        if rid in pre and pre[rid]:
            assert list(c.tokens[: len(pre[rid])]) == pre[rid], (
                f"{rid}: pre-drain tokens were re-emitted or changed"
            )
    lens = {rid: len(c.tokens) for rid, c in completions.items()}
    want = {r.rid: r.max_new_tokens for r in _smoke_trace(cfg)}
    assert lens == want, f"post-drain token counts drifted: {lens}"
    report["drill_drains"] = 1
    report["drill_resumed"] = len(completions)

    # (4) honest padding accounting (the satellite fix).
    acc = fixed_batch_padding_waste(trace, batch=3)
    useful = sum(r.max_new_tokens for r in trace)
    batches = -(-len(trace) // 3)
    expect = 1.0 - useful / (batches * 3 * max(
        r.max_new_tokens for r in trace
    ))
    assert abs(acc["decode_padding_waste"] - expect) < 5e-5  # 4-dp round
    assert acc["useful_decode_tokens"] == useful
    report["decode_padding_waste"] = acc["decode_padding_waste"]

    # (6) sampling inside the engine scan (ISSUE 8 satellite): the
    # fused sampled engine must be TOKEN-IDENTICAL to the per-token
    # unfused oracle with the same (seed, serial, position) key
    # schedule — the same parity bar the greedy oracles set.
    samp_kw = dict(temperature=0.8, top_k=8, sample_seed=11)
    sampled = run_engine_trace(
        cfg, params, ec(**samp_kw), trace, warmup=False
    )
    sampled_oracle = run_engine_trace(
        cfg, params, ec(fused=False, contiguous=True, **samp_kw),
        trace, warmup=False,
    )
    assert set(sampled["completions"]) == set(sampled_oracle["completions"])
    samp_mismatch = [
        rid for rid in sampled["completions"]
        if not np.array_equal(
            sampled["completions"][rid].tokens,
            sampled_oracle["completions"][rid].tokens,
        )
    ]
    assert not samp_mismatch, (
        f"sampled fused engine diverged from the unfused oracle on "
        f"{samp_mismatch}"
    )
    # Sampling must actually sample: a trace-wide argmax match would
    # mean the sampler silently degenerated to greedy.
    assert any(
        not np.array_equal(
            sampled["completions"][rid].tokens,
            paged["completions"][rid].tokens,
        )
        for rid in sampled["completions"]
    ), "sampled engine emitted the greedy trajectory on every request"
    report["sampled_parity_requests"] = len(sampled["completions"])

    # (5) int8 KV + int8 weight-only engine knobs complete and agree
    # with the f32 engine on almost every token (quantization noise
    # only — same bar family as make decodebench).
    for name, kw in (
        ("int8kv", {"kv_quant": "int8"}),
        ("w8", {"weight_quant": "int8"}),
    ):
        q = run_engine_trace(
            cfg, params, ec(**kw), trace, warmup=False
        )
        total = agree = 0
        for rid, c in q["completions"].items():
            ref = paged["completions"][rid].tokens
            total += len(ref)
            agree += int(np.sum(np.asarray(c.tokens) == np.asarray(ref)))
        ratio = agree / total
        assert ratio >= 0.9, (
            f"{name} engine agreement {ratio:.3f} vs f32 (bar 0.9)"
        )
        report[f"{name}_token_agreement"] = round(ratio, 3)

    report["ok"] = True
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
