"""Multi-chip smoke tests (BASELINE config 2: JAX pmap psum on a 4-chip
v5e ResourceClaim — the quickstart workload analog of the reference's
nvbandwidth/nbody pass-fail loads)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pmap_psum_smoke(n_devices: int = 0) -> dict:
    """All-reduce across every visible chip; returns a pass/fail report."""
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = devices[:n]

    import functools

    @functools.partial(jax.pmap, axis_name="i", devices=devices)
    def allreduce(x):
        return jax.lax.psum(x, axis_name="i")

    x = jnp.arange(n, dtype=jnp.float32)
    out = allreduce(x.reshape(n, 1))
    expected = float(x.sum())
    ok = bool(jnp.all(out == expected))
    return {
        "ok": ok,
        "devices": n,
        "platform": devices[0].platform,
        "expected": expected,
        "got": float(out[0, 0]),
    }


def matmul_smoke(size: int = 1024) -> dict:
    """One MXU-sized matmul sanity check on the first chip."""
    x = jnp.ones((size, size), dtype=jnp.bfloat16)
    y = (x @ x).block_until_ready()
    ok = bool(jnp.allclose(y[0, 0], size, rtol=1e-2))
    return {"ok": ok, "size": size, "value": float(y[0, 0])}


def decode_smoke(
    batch: int = 2, prompt_len: int = 8, max_new_tokens: int = 16
) -> dict:
    """The serving path on whatever chip the claim granted: jitted
    prefill + KV-cache greedy decode (workloads/generate.py) on a tiny
    model. Pass = right shape, prompt preserved, finite ids."""
    import time

    from tpu_dra.workloads.generate import greedy_generate
    from tpu_dra.workloads.models.llama import TINY_LLAMA, Llama

    model = Llama(TINY_LLAMA)
    params = model.init_params(
        jax.random.PRNGKey(0), batch=batch, seq=prompt_len
    )
    prompt = jnp.tile(
        jnp.arange(prompt_len, dtype=jnp.int32)[None], (batch, 1)
    )
    gen = jax.jit(
        lambda p, t: greedy_generate(
            TINY_LLAMA, p, t, max_new_tokens=max_new_tokens
        )
    )
    out = gen(params, prompt)
    out.block_until_ready()
    t0 = time.monotonic()
    out = gen(params, prompt)
    last = int(out[0, -1])  # host fetch closes the timing
    dt = time.monotonic() - t0
    ok = (
        out.shape == (batch, prompt_len + max_new_tokens)
        and bool(jnp.all(out[:, :prompt_len] == prompt))
        and 0 <= last < TINY_LLAMA.vocab_size
    )
    return {
        "ok": ok,
        "platform": jax.devices()[0].platform,
        "decode_tok_s": round(batch * max_new_tokens / dt, 1),
        "shape": list(out.shape),
    }


if __name__ == "__main__":
    print(pmap_psum_smoke())
    print(matmul_smoke())
    print(decode_smoke())
