"""Multi-chip smoke tests (BASELINE config 2: JAX pmap psum on a 4-chip
v5e ResourceClaim — the quickstart workload analog of the reference's
nvbandwidth/nbody pass-fail loads)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pmap_psum_smoke(n_devices: int = 0) -> dict:
    """All-reduce across every visible chip; returns a pass/fail report."""
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = devices[:n]

    import functools

    @functools.partial(jax.pmap, axis_name="i", devices=devices)
    def allreduce(x):
        return jax.lax.psum(x, axis_name="i")

    x = jnp.arange(n, dtype=jnp.float32)
    out = allreduce(x.reshape(n, 1))
    expected = float(x.sum())
    ok = bool(jnp.all(out == expected))
    return {
        "ok": ok,
        "devices": n,
        "platform": devices[0].platform,
        "expected": expected,
        "got": float(out[0, 0]),
    }


def matmul_smoke(size: int = 1024) -> dict:
    """One MXU-sized matmul sanity check on the first chip."""
    x = jnp.ones((size, size), dtype=jnp.bfloat16)
    y = (x @ x).block_until_ready()
    ok = bool(jnp.allclose(y[0, 0], size, rtol=1e-2))
    return {"ok": ok, "size": size, "value": float(y[0, 0])}


if __name__ == "__main__":
    print(pmap_psum_smoke())
    print(matmul_smoke())
